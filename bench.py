"""Benchmark: Llama-3-8B-shaped pretraining step on one chip.

Prints ONE JSON line: {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}.
The driver-designated metric (BASELINE.json) is Llama-3-8B pretrain MFU with a
north star of >= 45% MFU; vs_baseline is measured_mfu / 45%.

On TPU the model is Llama-3-8B per-layer shapes (hidden 4096 / ffn 14336 /
32 heads / 8 KV heads / vocab 128256 / seq 8192) with the layer count scaled to
fit one chip — MFU is per-layer-shape-bound, so this measures the same thing the
full 32-layer multi-chip run would.  On CPU it shrinks to a smoke config.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.optim.adamw import (
    AdamWConfig,
    init_opt_state,
    opt_state_specs,
)
from neuronx_distributed_training_tpu.optim.lr import constant_lr
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.trainer.step import jit_train_step, make_train_step
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy
from neuronx_distributed_training_tpu.utils import perf


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def has_flash() -> bool:
    try:
        from neuronx_distributed_training_tpu.ops import flash_attention  # noqa: F401

        return True
    except ImportError:
        return False


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--mbs", type=int, default=1)
    ap.add_argument("--attn", choices=["auto", "core", "flash"], default="auto")
    args = ap.parse_args()

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if args.attn == "auto":
        attn_impl = "flash" if (on_tpu and has_flash()) else "core"
    else:
        attn_impl = args.attn

    if on_tpu:
        # Flash attention handles seq 8192; naive core attention's O(s^2)
        # transients need the shorter default on small-HBM chips.
        seq = args.seq or (8192 if attn_impl == "flash" else 4096)
        h, ffn, nh, nkv, vocab = 4096, 14336, 32, 8, 128256
        if args.layers:
            layers = args.layers
        else:
            # Auto-size the layer count to HBM: pure-bf16 regime costs
            # ~6 bytes/param (param + m + v) plus transient bf16 grads (2).
            try:
                hbm = dev.memory_stats()["bytes_limit"]
            except Exception:
                hbm = 16 << 30
            per_layer = h * (nh + 2 * nkv) * (h // nh) + nh * (h // nh) * h + 3 * h * ffn
            vocab_params = 2 * vocab * h
            budget_params = hbm * 0.60 / 8.0
            layers = max(1, min(32, int((budget_params - vocab_params) // per_layer)))
        cfg = llama.LlamaConfig(
            vocab_size=vocab,
            hidden_size=h,
            intermediate_size=ffn,
            num_layers=layers,
            num_attention_heads=nh,
            num_kv_heads=nkv,
            max_position_embeddings=seq,
            rope_theta=500000.0,
            fuse_qkv=True,
            attention_impl=attn_impl,
            activations_checkpoint_granularity="selective",
        )
    else:
        seq = args.seq or 512
        cfg = llama.LlamaConfig(
            vocab_size=1024,
            hidden_size=256,
            intermediate_size=704,
            num_layers=args.layers or 2,
            num_attention_heads=8,
            num_kv_heads=4,
            max_position_embeddings=seq,
            attention_impl="core" if attn_impl == "auto" else attn_impl,
        )
        args.steps = min(args.steps, 4)
        args.warmup = min(args.warmup, 1)

    # Pure-bf16 regime on TPU (the reference's bf16+SR regime,
    # training_orchestrator.py precision matrix) — 6 bytes/param keeps the
    # Llama3-8B layer shapes + full vocab resident on a small-HBM chip.
    policy = (
        DtypePolicy.from_precision_config(
            {"type": "bf16SR", "optimizer_dtype": "bf16", "grad_accum_dtype": "bf16"}
        )
        if on_tpu
        else DtypePolicy.from_precision_config("mixed_precision")
    )
    mesh = build_mesh(MeshConfig(), devices=[dev])
    log(f"bench: device={dev.device_kind} layers={cfg.num_layers} seq={seq} "
        f"mbs={args.mbs} attn={cfg.attention_impl}")

    pspecs = llama.param_specs(cfg)
    with mesh, shd.use_mesh(mesh):
        params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)
        ns = functools.partial(NamedSharding, mesh)
        put = lambda tree, specs: jax.device_put(
            tree, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        params = put(params, pspecs)
        opt_state = init_opt_state(params, policy)
        ospecs = opt_state_specs(params, pspecs, mesh, zero1=True, policy=policy)
        opt_state = put(opt_state, ospecs)

        def loss_fn(p, batch, step_key):
            return llama.forward(p, batch, cfg, policy)

        step = make_train_step(loss_fn, AdamWConfig(), constant_lr(1e-4), policy)
        jstep = jit_train_step(step, mesh, pspecs, ospecs)

        ids = jax.random.randint(
            jax.random.PRNGKey(1), (args.mbs, seq), 0, cfg.vocab_size, dtype=jnp.int32
        )
        batch = {"input_ids": ids, "labels": ids}
        batch = jax.device_put(batch, ns(P(("data", "expert"))))
        key = jax.random.PRNGKey(2)

        t_compile = time.perf_counter()
        for _ in range(args.warmup):
            params, opt_state, metrics = jstep(params, opt_state, batch, key)
        # A host scalar fetch is the only reliable execution fence on remote
        # (tunnelled) TPU backends — block_until_ready alone doesn't flush.
        log(f"bench: warmup done in {time.perf_counter() - t_compile:.1f}s "
            f"loss={float(metrics['loss']):.4f}")

        # Measure fetch round-trip on settled buffers: min of several samples so
        # a one-off connection-setup stall can't dominate the correction.
        rtts = []
        # only never-fetched buffers: a fetched jax.Array caches its host value,
        # so re-fetching "loss" (read at the warmup log) measures ~0
        for m in ("grad_norm", "lr"):
            t_rtt = time.perf_counter()
            _ = float(metrics[m])
            rtts.append(time.perf_counter() - t_rtt)
        rtt = min(rtts)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            params, opt_state, metrics = jstep(params, opt_state, batch, key)
        _ = float(metrics["loss"])  # fence: forces the whole dependent chain
        elapsed = time.perf_counter() - t0
        # the rtt correction must stay a correction — never let it swallow the
        # measurement and report a fantasy number
        rtt = min(rtt, 0.1 * elapsed)
        dt = (elapsed - rtt) / args.steps
        log(f"bench: fetch rtt {rtt * 1e3:.0f} ms")

    tokens_per_step = args.mbs * seq
    tokens_per_sec = tokens_per_step / dt
    fwd_ft = perf.flops_for_config(cfg, seq)
    step_ft = perf.train_step_flops_per_token(fwd_ft)
    peak = perf.detect_peak_tflops(dev)
    mfu = perf.mfu(tokens_per_sec, step_ft, peak)
    log(f"bench: {dt * 1e3:.1f} ms/step, {tokens_per_sec:,.0f} tok/s/chip, "
        f"MFU {100 * mfu:.1f}% (peak {peak} TF)")

    print(json.dumps({
        "metric": "llama3_8B_pretrain_mfu",
        "value": round(100 * mfu, 2),
        "unit": "percent_mfu",
        "vs_baseline": round(mfu / 0.45, 4),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "ms_per_step": round(dt * 1e3, 2),
        "device": dev.device_kind,
        "attn_impl": cfg.attention_impl,
        "num_layers": cfg.num_layers,
        "seq_len": seq,
    }))


if __name__ == "__main__":
    main()
