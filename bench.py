"""Benchmark: Llama-3-8B-shaped pretraining step on one chip.

Prints ONE JSON line: {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}.
The driver-designated metric (BASELINE.json) is Llama-3-8B pretrain MFU with a
north star of >= 45% MFU; vs_baseline is measured_mfu / 45%.

Regimes: the baseline config (reference ``hf_llama3_8B_config.yaml:45-107``)
specifies ``mixed_precision`` (bf16 compute, fp32 master weights + optimizer
state).  That is the headline number when it fits on the chip; the pure-bf16
regime (the reference's bf16+SR) is measured alongside and reported in the same
JSON.  On TPU the model is Llama-3-8B per-layer shapes (hidden 4096 / ffn 14336
/ 32 heads / 8 KV heads / vocab 128256 / seq 8192) with the layer count scaled
to fit one chip — MFU is per-layer-shape-bound, so this measures the same thing
the full 32-layer multi-chip run would.  On CPU it shrinks to a smoke config.

Failure behavior: every error path still emits the JSON line (value 0.0 +
"error" field) so the driver records a diagnostic instead of a traceback.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time
import traceback


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def emit(payload: dict) -> None:
    """The single JSON-line emitter.  A headline (metric-shaped) line REFUSES
    to go out without a perf-contract verdict field: every BENCH_*.json line
    must say whether the measurement was checked against the committed
    baseline — ``{"verdict": "no_baseline"}`` is an acceptable answer,
    silence is not (analysis.perf_contract, docs/observability.md)."""
    if "metric" in payload and "perf_contract" not in payload:
        raise RuntimeError(
            "bench: refusing to emit a headline JSON line without a "
            "perf_contract verdict field (populate it via "
            "analysis.perf_contract.bench_verdict — 'no_baseline' counts)"
        )
    print(json.dumps(payload), flush=True)


# Most recent successful on-hardware measurement (round-2 fallback; freshly
# measured runs overwrite bench_results/last_measured.json, which takes
# precedence): carried in the diagnostic JSON so a transient tunnel/backend
# outage at bench time doesn't erase the evidence of what the code measured.
LAST_MEASURED = {
    "date": "2026-07-30",
    "device": "TPU v5 lite",
    "mfu_mixed_precision": 66.59,
    "mfu_bf16": 71.38,
    "tokens_per_sec_per_chip_bf16": 30161.3,
    "seq_len": 8192,
    "note": "flash tile kv=2048 defaults; see bench_results/ for full lines",
}

_LAST_MEASURED_PATH = "bench_results/last_measured.json"
_MEASURED_LOG = "bench_results/r5_measured.jsonl"
# the last completed preemption drill (tools/elastic_drill.py writes it);
# when present its restart cost + goodput ride the bench JSON line so fleet
# survivability is visible in the bench trajectory (docs/elasticity.md)
_LAST_DRILL_PATH = "bench_results/last_drill.json"


def _read_repo_json(rel_path: str, default):
    """One loader for the bench_results/*.json snapshots (repo-relative;
    missing/corrupt/non-dict files fall back to ``default``)."""
    import os

    base = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(base, rel_path)) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else default
    except Exception:
        return default


def load_last_drill() -> dict:
    return _read_repo_json(_LAST_DRILL_PATH, {})


def load_last_measured() -> dict:
    return _read_repo_json(_LAST_MEASURED_PATH, LAST_MEASURED)


def record_measurement(payload: dict, refresh_last: bool = True) -> None:
    """Append the successful on-hardware line to the evidence log and (unless
    ``refresh_last=False`` — low-fidelity calibration runs) refresh
    last_measured.json, the authoritative line later diagnostics cite."""
    import os

    base = os.path.dirname(os.path.abspath(__file__))
    try:
        os.makedirs(os.path.join(base, "bench_results"), exist_ok=True)
        line = {"date": time.strftime("%Y-%m-%d"), **payload}
        with open(os.path.join(base, _MEASURED_LOG), "a") as f:
            f.write(json.dumps(line) + "\n")
        if refresh_last:
            with open(os.path.join(base, _LAST_MEASURED_PATH), "w") as f:
                json.dump(line, f, indent=1)
    except Exception as e:  # noqa: BLE001 — recording must never fail the bench
        log(f"bench: could not record measurement: {e}")


def json_float(v, ndigits: int = 4):
    """NaN/Inf-safe JSON scalar: json.dumps would emit bare ``NaN`` (invalid
    JSON) for exactly the diverging runs the health fields exist to flag."""
    import math

    if v is None or not isinstance(v, (int, float)):
        return v
    return round(float(v), ndigits) if math.isfinite(v) else repr(float(v))


def fail_json(err: str, provenance: dict | None = None, **extra) -> None:
    emit({
        "metric": "llama3_8B_pretrain_mfu",
        "value": 0.0,
        "unit": "percent_mfu",
        "vs_baseline": 0.0,
        "error": err[-2000:],
        "last_measured": load_last_measured(),
        # bench provenance (acquire mode, watchdog phase tag, handshake
        # timing, backend identity): a dead round must be diagnosable from
        # the artifact alone — rounds r02-r05 died before the backend and
        # left nothing but an rc
        "provenance": provenance or {},
        # no measurement happened, so there is nothing to check — but the
        # field must exist on every line (the emit contract)
        "perf_contract": {"verdict": "no_measurement"},
        **extra,
    })


_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices()[0];"
    "jnp.zeros(8).block_until_ready();"
    "print('PROBE_OK', d.platform)"
)


def acquire_device(retries: int = 2, probe_timeout_s: float = 100.0,
                   delay_s: float = 15.0, platform: str | None = None,
                   direct: bool = False, connect_timeout_s: float = 300.0):
    # worst-case acquire budget: direct (default) ~connect_timeout+10s;
    # legacy subprocess probe ~3.6 min.  Both stay comfortably inside the
    # driver's own bench timeout so a wedged chip yields the DIAGNOSTIC JSON
    # (with last_measured evidence), never an rc=124 with no output
    """Get a usable JAX device without risking an indefinite in-process hang.

    The tunnelled TPU backend can hang or be transiently UNAVAILABLE (round-1
    failure mode: rc=1 at driver bench time), and ``jax.devices()`` has no
    timeout — a hung call poisons the process.  Default (``direct=True``):
    connect in-process under a watchdog + killer pair (see below) so the
    bench itself is the one and only client connection — a throwaway probe's
    teardown can wedge the tunnelled backend (bench_results/r4_notes.md).
    Legacy (``direct=False``): probe availability in a SUBPROCESS with a hard
    timeout first.  Returns (device | None, diagnostic | None, provenance).

    ``provenance`` is the acquire's own forensic record — acquire mode, the
    watchdog phase tag actually reached, PJRT handshake + first-RPC timing,
    and the backend identity — persisted into EVERY bench JSON line so a
    dead round (cf. r02-r05: probe timeout / PJRT handshake hang with no
    artifact evidence) is diagnosable from the artifact alone.
    """
    import subprocess

    def _prov(mode: str, **kw) -> dict:
        out = {"acquire_mode": mode, "requested_platform": platform}
        try:
            import jax as _jax

            out["jax_version"] = _jax.__version__
        except Exception:  # noqa: BLE001 — provenance must never fail acquire
            pass
        out.update(kw)
        return out

    if platform == "cpu":
        # cpu is in-process safe (no tunnel involved); tpu still goes through
        # the subprocess probe below so a hung backend can't hang the bench
        import jax

        jax.config.update("jax_platforms", platform)
        d = jax.devices()[0]
        return d, None, _prov("in-process-cpu", connect_phase="connected",
                              platform=d.platform,
                              device_kind=d.device_kind)

    if direct:
        # Round-4 connection discipline: do NOT burn a throwaway probe
        # connection.  Evidence (bench_results/probe_r4.log): the tunnelled
        # backend answered the FIRST client after a quiet period, then wedged
        # for every subsequent client — so each client teardown appears to
        # cost a wedge window, and round-3's 7-minute probe cadence may have
        # perpetuated its outage.  Here the process that will run the bench
        # connects in-process, guarded by a watchdog thread: if jax.devices()
        # (which has no timeout and poisons the process when the tunnel
        # hangs) doesn't come back in ``connect_timeout_s``, exit(86) so the
        # outer retry loop can back off for a long quiet gap.
        import os
        import signal
        import threading

        # Failure-mode discrimination (VERDICT r4 item 8): each connect phase
        # logs on ENTRY (log() flushes stderr), so even when the killer has to
        # SIGKILL a GIL-held hang, the loop log shows the last phase reached —
        # "import" (local), "plugin-init" (PJRT handshake through the relay),
        # or "first-rpc" (listener accepted but the data path is wedged).
        phase = {"name": "import-jax", "t0": time.perf_counter()}

        def enter_phase(name: str) -> None:
            phase.update(name=name, t0=time.perf_counter())
            log(f"bench: connect phase: {name}")

        def _abort():
            log(f"bench: direct connect watchdog fired after "
                f"{connect_timeout_s:.0f}s — exiting 86 (hung in phase "
                f"'{phase['name']}' for "
                f"{time.perf_counter() - phase['t0']:.0f}s)")
            os._exit(86)

        watchdog = threading.Timer(connect_timeout_s, _abort)
        watchdog.daemon = True
        watchdog.start()
        # The Timer alone is not enough: a hung PJRT init can sit in a native
        # call that never releases the GIL (the tunnel client's gRPC path has
        # no gil_scoped_release), starving every Python thread including the
        # watchdog.  A separate killer PROCESS delivers SIGKILL regardless of
        # this process's GIL state (rc 137 instead of 86) — and because it
        # inherits stdout it is also the SOLE emitter of the hung-connect
        # diagnostic JSON: it can print it even when the parent is frozen,
        # preserving the "every error path emits the JSON line" contract.
        # Timeline: watchdog exits 86 at T (GIL-free hang) or the killer
        # SIGKILLs at T+10 (GIL-held hang); either way the killer prints the
        # diagnostic exactly once at T+10.  On success the killer dies first
        # and prints nothing.
        diag = json.dumps({
            "metric": "llama3_8B_pretrain_mfu",
            "value": 0.0,
            "unit": "percent_mfu",
            "vs_baseline": 0.0,
            "error": f"backend connect hung > {connect_timeout_s:.0f}s "
                     f"(direct in-process acquire)",
            "last_measured": load_last_measured(),
            # the killer prints this while the parent is FROZEN, so it
            # cannot know which phase wedged — the stderr loop log carries
            # the last "bench: connect phase:" line; this records that the
            # watchdog fired and with what budget
            "provenance": _prov(
                "direct",
                connect_phase="hung (watchdog kill; the stderr log's last "
                              "'bench: connect phase:' line names the "
                              "wedged phase)",
                connect_timeout_seconds=connect_timeout_s),
            "perf_contract": {"verdict": "no_measurement"},
        })
        # The killer verifies the target is still THIS process before SIGKILL
        # (ADVICE r4: the parent may have exited at T via the watchdog and its
        # PID been reused within the 10 s grace window on a busy host) by
        # comparing /proc/<pid>/stat's starttime field captured at spawn.
        def _starttime(pid: int) -> str:
            try:
                with open(f"/proc/{pid}/stat") as f:
                    return f.read().rsplit(")", 1)[1].split()[19]
            except Exception:  # noqa: BLE001 — non-Linux fallback: no check
                return ""

        me = os.getpid()
        killer = subprocess.Popen(
            [sys.executable, "-c",
             "import contextlib,os,sys,time,signal\n"
             f"time.sleep({connect_timeout_s + 10.0})\n"
             "print(sys.argv[1], flush=True)\n"
             "def _start(pid):\n"
             "    try:\n"
             "        with open(f'/proc/{pid}/stat') as f:\n"
             "            return f.read().rsplit(')', 1)[1].split()[19]\n"
             "    except Exception:\n"
             "        return sys.argv[2]\n"
             f"if _start({me}) == sys.argv[2]:\n"
             "    with contextlib.suppress(ProcessLookupError):\n"
             f"        os.kill({me}, signal.SIGKILL)\n",
             diag, _starttime(me)],
        )
        try:
            try:
                # Pin the platform BEFORE jax imports: with JAX_PLATFORMS
                # unset, a fast-failing plugin lets jax fall back to CPU
                # silently AND the want_tpu guard below reads the empty env
                # as "cpu is fine" — the two must agree so a CPU fallback can
                # never emit a success-shaped metric line (ADVICE r4).
                # platform is "tpu" or None here ("cpu" returned early above),
                # and the axon plugin is this image's TPU backend.
                os.environ.setdefault("JAX_PLATFORMS", "axon")

                import jax
                import jax.numpy as jnp

                enter_phase("plugin-init (jax.devices / PJRT handshake)")
                d = jax.devices()[0]
                t_init = time.perf_counter() - phase["t0"]
                enter_phase("first-rpc (tiny buffer round-trip)")
                jnp.zeros(8).block_until_ready()  # liveness, not just handshake
                t_rpc = time.perf_counter() - phase["t0"]
            finally:
                watchdog.cancel()
                killer.send_signal(signal.SIGKILL)
                killer.wait()  # reap — a zombie would linger for the whole run
        except Exception as e:  # noqa: BLE001 — ADVICE r4: a FAST-raising
            # connect (round-1 "transiently UNAVAILABLE, rc=1" mode) must
            # return a diagnostic, not crash past the only JSON emitter
            return None, (f"direct connect raised in phase '{phase['name']}': "
                          f"{type(e).__name__}: {e}"), _prov(
                "direct", connect_phase=phase["name"],
                connect_timeout_seconds=connect_timeout_s,
                error=f"{type(e).__name__}: {e}"[:300])
        # ADVICE r4: if the plugin fails fast JAX can silently fall back to
        # CPU and we'd emit a success-shaped CPU line.  JAX_PLATFORMS=axon in
        # the env should prevent that, but pin it explicitly.
        want_tpu = platform == "tpu" or (
            platform is None
            and os.environ.get("JAX_PLATFORMS", "").lower() not in ("", "cpu"))
        prov = _prov("direct", connect_phase="connected",
                     plugin_init_seconds=round(t_init, 3),
                     first_rpc_seconds=round(t_rpc, 3),
                     platform=d.platform, device_kind=d.device_kind,
                     connect_timeout_seconds=connect_timeout_s)
        if want_tpu and d.platform == "cpu":
            return None, "wanted tpu, got platform=cpu (silent CPU fallback)", \
                dict(prov, connect_phase="silent-cpu-fallback")
        log(f"bench: direct backend acquire ok ({d.platform} {d.device_kind}) "
            f"plugin-init={t_init:.2f}s first-rpc={t_rpc:.2f}s")
        return d, None, prov

    last = ""
    for attempt in range(retries):
        try:
            t_probe = time.perf_counter()
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=probe_timeout_s,
            )
            if "PROBE_OK" in r.stdout:
                log(f"bench: backend probe ok ({r.stdout.strip().split()[-1]})")
                import jax

                d = jax.devices()[0]
                return d, (last or None), _prov(
                    "probe-subprocess", connect_phase="connected",
                    probe_seconds=round(time.perf_counter() - t_probe, 3),
                    probe_attempts=attempt + 1,
                    platform=d.platform, device_kind=d.device_kind)
            last = (r.stderr or r.stdout).strip()[-500:]
        except subprocess.TimeoutExpired:
            last = f"backend probe timed out after {probe_timeout_s:.0f}s"
        except Exception as e:  # noqa: BLE001 — diagnostic path
            last = f"{type(e).__name__}: {e}"
        log(f"bench: backend attempt {attempt + 1}/{retries} failed: {last}")
        if attempt + 1 < retries:
            time.sleep(delay_s)
    return None, last, _prov("probe-subprocess",
                             connect_phase="probe-failed",
                             probe_attempts=retries, error=last[:300])


def layer_budget(hbm_bytes: int, bytes_per_param: float, *,
                 tied: bool = True, util: float = 0.55) -> int:
    """Estimated deepest Llama-3-8B layer stack fitting ``hbm_bytes``.

    ``util`` is deliberately conservative (0.55): on the tunnelled backend an
    OOM can WEDGE the chip for hours (round-2 post-mortem), and the driver's
    capture runs after ours — a too-deep first try can zero the official
    artifact.  Deeper stacks are probed only under ``--probe-deeper`` in
    manual sessions."""
    h, ffn, nh, nkv, vocab = 4096, 14336, 32, 8, 128256
    per_layer = h * (nh + 2 * nkv) * (h // nh) + nh * (h // nh) * h + 3 * h * ffn
    vocab_params = (1 if tied else 2) * vocab * h
    budget_params = hbm_bytes * util / bytes_per_param
    return max(1, min(32, int((budget_params - vocab_params) // per_layer)))


def make_config(llama, on_tpu: bool, attn_impl: str, seq: int, layers: int | None,
                hbm_bytes: int, bytes_per_param: float, *, tied: bool = True,
                block_q: int | None = None, block_kv: int | None = None):
    """Llama-3-8B per-layer shapes, layer count auto-sized to HBM.

    ``tied=True`` is the PINNED bench default (round-3 contract: one config,
    tied embeddings, multi-layer — VERDICT r2): the fp32 master+opt state of
    an untied 1.05B-param vocab pair alone eats ~2/3 of a 16G chip under
    mixed precision."""
    if on_tpu:
        h, ffn, nh, nkv, vocab = 4096, 14336, 32, 8, 128256
        if layers is None:
            layers = layer_budget(hbm_bytes, bytes_per_param, tied=tied)
        # long sequences: the [s, vocab] logits tensor (s*vocab*4B fp32)
        # dominates HBM — switch to the fused chunked head+CE, which never
        # materializes it (fusions.chunked_ce).  Fixed 8 GiB threshold, NOT a
        # fraction of measured HBM: the flagship seq-8192 point (~4.2 GB
        # logits) must always bench un-chunked so runs stay comparable to the
        # recorded baselines regardless of runtime HBM reservation.
        vocab_chunks = 16 if seq * vocab * 4 > 8 * 1024**3 else None
        if vocab_chunks:
            log(f"bench: seq {seq} logits exceed 8 GiB — chunked_ce x{vocab_chunks}")
        return llama.LlamaConfig(
            vocab_size=vocab,
            hidden_size=h,
            intermediate_size=ffn,
            num_layers=layers,
            num_attention_heads=nh,
            num_kv_heads=nkv,
            max_position_embeddings=seq,
            rope_theta=500000.0,
            tie_word_embeddings=tied,
            fuse_qkv=True,
            attention_impl=attn_impl,
            flash_block_q=block_q,
            flash_block_kv=block_kv,
            vocab_chunks=vocab_chunks,
            activations_checkpoint_granularity="selective",
        )
    return llama.LlamaConfig(
        vocab_size=1024,
        hidden_size=256,
        intermediate_size=704,
        num_layers=layers or 2,
        num_attention_heads=8,
        num_kv_heads=4,
        max_position_embeddings=seq,
        attention_impl=attn_impl,
        flash_block_q=block_q,
        flash_block_kv=block_kv,
    )


def run_bench(dev, cfg, policy, seq: int, mbs: int, steps: int, warmup: int,
              num_microbatches: int = 1, trace: bool = False,
              tensorstats: bool = False) -> dict:
    """One timed regime run; returns {ms_per_step, tokens_per_sec, mfu}.

    ``mbs`` is the TOTAL rows per step; ``num_microbatches > 1`` runs the
    trainer's real grad-accumulation scan (one optimizer update per step),
    which is what the autotune cost model prices — the plan-topk sweep
    passes it so predicted and measured steps are the same unit.
    ``trace=True`` additionally captures a short device-time trace window
    AFTER the timed loop (so profiling overhead never contaminates
    ms_per_step) and reports measured achieved_overlap /
    exposed_collective_seconds (telemetry.trace_analysis).
    ``tensorstats=True`` rides the in-graph tensor-numerics plane
    (telemetry.tensorstats) on the same compiled step and attaches a compact
    per-collective-class quant-readiness summary to the JSON line (joined
    with the trace's measured exposed seconds when ``trace`` is also on)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.optim.adamw import (
        AdamWConfig, init_opt_state, opt_state_specs,
    )
    from neuronx_distributed_training_tpu.optim.lr import constant_lr
    from neuronx_distributed_training_tpu.parallel import sharding as shd
    from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from neuronx_distributed_training_tpu.telemetry import HealthConfig
    from neuronx_distributed_training_tpu.trainer.step import (
        jit_train_step, make_train_step,
    )
    from neuronx_distributed_training_tpu.utils import perf

    mesh = build_mesh(MeshConfig(), devices=[dev])
    pspecs = llama.param_specs(cfg)
    with mesh, shd.use_mesh(mesh):
        params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)
        ns = functools.partial(NamedSharding, mesh)
        put = lambda tree, specs: jax.device_put(
            tree, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        params = put(params, pspecs)
        # numerics health rides the bench step exactly as it rides the
        # trainer's (telemetry.health): the in-graph finiteness counters let
        # the JSON line distinguish a fast-but-diverging run (nonfinite
        # steps, exploding final grad norm) from a healthy one
        # param_norm off: bench never reports it, and the full-parameter
        # norm reduction would sit inside the timed loop skewing ms_per_step
        health = HealthConfig(enabled=True, policy="dump_and_continue",
                              param_norm=False)
        ts_cfg = None
        if tensorstats:
            from neuronx_distributed_training_tpu.telemetry import (
                TensorStatsConfig,
            )

            ts_cfg = TensorStatsConfig(enabled=True)
        opt_state = init_opt_state(params, policy, health=True,
                                   tensorstats=ts_cfg)
        ospecs = opt_state_specs(params, pspecs, mesh, zero1=True, policy=policy,
                                 health=True, tensorstats=ts_cfg)
        opt_state = put(opt_state, ospecs)

        def loss_fn(p, batch, step_key):
            return llama.forward(p, batch, cfg, policy)

        step = make_train_step(loss_fn, AdamWConfig(), constant_lr(1e-4), policy,
                               num_microbatches=num_microbatches,
                               param_specs=pspecs, health_cfg=health,
                               tensorstats_cfg=ts_cfg)
        jstep = jit_train_step(step, mesh, pspecs, ospecs)

        ids = jax.random.randint(
            jax.random.PRNGKey(1), (mbs, seq), 0, cfg.vocab_size, dtype=jnp.int32
        )
        batch = {"input_ids": ids, "labels": ids}
        batch = jax.device_put(batch, ns(P(("data", "expert"))))
        key = jax.random.PRNGKey(2)

        # AOT compile first so the bench reports the trainer's telemetry
        # schema (compile_seconds + collective/memory census) and the timed
        # loop runs the very executable that was measured — zero extra
        # compiles (telemetry.census, same flow as Trainer._compile_census).
        from neuronx_distributed_training_tpu.telemetry import compile_census

        t_compile = time.perf_counter()
        lowered = jstep.lower(params, opt_state, batch, key)
        compiled = lowered.compile()
        compile_seconds = time.perf_counter() - t_compile
        census = compile_census(compiled, compile_seconds=compile_seconds)
        log(f"bench: compiled in {compile_seconds:.1f}s "
            f"collectives={census.get('collectives')}")

        # pre-flight graph audit of the very executable being measured
        # (analysis.graph_audit): a bench number from a step that silently
        # lost donation (or grew a stray collective) is not comparable to
        # the recorded baselines — the verdict rides the JSON line
        audit_summary = None
        try:
            from neuronx_distributed_training_tpu.analysis.graph_audit import (
                AuditContext, audit_executable,
            )

            ctx = AuditContext(
                cfg={"distributed_strategy": {"zero1": True}}, mesh=mesh,
                policy=policy, model_cfg=cfg,
                sched={"global_batch_size": mbs, "micro_batch_size": mbs},
                donate=True, params_tree=params, opt_tree=opt_state,
                pspecs=pspecs, ospecs=ospecs,
            )
            audit = audit_executable(
                ctx, compiled, lowered, log=lambda m: log(f"bench: {m}"))
            audit_summary = audit.summary()
        except Exception as e:  # noqa: BLE001 — audit must never fail the bench
            log(f"bench: graph audit unavailable: {e}")

        t_warm = time.perf_counter()
        for _ in range(warmup):
            params, opt_state, metrics = compiled(params, opt_state, batch, key)
        # A host scalar fetch is the only reliable execution fence on remote
        # (tunnelled) TPU backends — block_until_ready alone doesn't flush.
        log(f"bench: warmup done in {time.perf_counter() - t_warm:.1f}s "
            f"loss={float(metrics['loss']):.4f}")

        # Measure fetch round-trip on settled buffers: min of several samples so
        # a one-off connection-setup stall can't dominate the correction.
        # Only never-fetched buffers: a fetched jax.Array caches its host value.
        rtts = []
        for m in ("grad_norm", "lr"):
            t_rtt = time.perf_counter()
            _ = float(metrics[m])
            rtts.append(time.perf_counter() - t_rtt)
        rtt = min(rtts)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, metrics = compiled(params, opt_state, batch, key)
        _ = float(metrics["loss"])  # fence: forces the whole dependent chain
        elapsed = time.perf_counter() - t0
        # health counters: fetched AFTER the fence, outside the timed window
        nonfinite_steps = int(metrics["health/nonfinite_count"])
        skipped_updates = int(metrics["health/skipped_count"])
        final_grad_norm = float(metrics["grad_norm"])
        if nonfinite_steps:
            log(f"bench: WARNING {nonfinite_steps} non-finite steps — the "
                f"throughput number is for a DIVERGING run")
        # the rtt correction must stay a correction — never let it swallow the
        # measurement and report a fantasy number
        rtt = min(rtt, 0.1 * elapsed)
        dt = (elapsed - rtt) / steps
        log(f"bench: fetch rtt {rtt * 1e3:.0f} ms")

        # optional device-time trace window, AFTER the timed loop: measured
        # compute/comms overlap for the very executable just benchmarked
        trace_summary = None
        if trace:
            import tempfile

            from neuronx_distributed_training_tpu.telemetry.trace import (
                trace_steps,
            )

            def _traced_step(i):
                nonlocal params, opt_state, metrics
                params, opt_state, metrics = compiled(
                    params, opt_state, batch, key)
                _ = float(metrics["loss"])  # flush so the trace sees the step

            try:
                trace_summary = trace_steps(
                    _traced_step, min(3, max(steps, 1)),
                    tempfile.mkdtemp(prefix="nxdt_bench_trace_"))
            except Exception as e:  # noqa: BLE001 — trace must not fail the bench
                log(f"bench: trace capture failed: {e}")
            if trace_summary is not None:
                log(f"bench: trace achieved_overlap="
                    f"{trace_summary.get('achieved_overlap')} "
                    f"exposed_collective_seconds="
                    f"{trace_summary.get('exposed_collective_seconds')}")

        # quant-readiness: decode the streamed dynamic-range histograms
        # (fetched AFTER the fence, outside the timed window) and simulate
        # block-scaled int8 per collective class — compact enough to ride
        # the JSON line; tools/quant_readiness.py renders the full report
        quant_readiness = None
        if ts_cfg is not None:
            try:
                import numpy as np

                from neuronx_distributed_training_tpu.telemetry.quant_readiness import (  # noqa: E501
                    build_report,
                )
                from neuronx_distributed_training_tpu.telemetry.tensorstats import (  # noqa: E501
                    HIST_PREFIX, decode_cum,
                )

                groups = {
                    k[len(HIST_PREFIX):]: decode_cum(
                        np.asarray(v).tolist(), ts_cfg)
                    for k, v in metrics.items() if k.startswith(HIST_PREFIX)
                }
                rep = build_report(
                    {"step": steps, "groups": groups},
                    overlap_by_class=(trace_summary or {}).get(
                        "overlap_by_class"))
                best = str(rep["block_sizes"][-1])
                quant_readiness = {}
                for kind in rep["ranking"]:
                    e = rep["classes"][kind]
                    if "pooled" not in e \
                            and e.get("predicted_seconds_saved") is None:
                        continue
                    p = e.get("pooled", {}).get(best, {})
                    quant_readiness[kind] = {
                        "block_size": int(best),
                        "sqnr_db": json_float(p.get("sqnr_db")),
                        "rel_error_rms": json_float(
                            p.get("rel_error_rms"), 9),
                        "bytes_saved_frac": json_float(
                            e.get("bytes_saved_frac"), 6),
                        "predicted_seconds_saved": json_float(
                            e.get("predicted_seconds_saved"), 9),
                    }
            except Exception as e:  # noqa: BLE001 — telemetry must not fail the bench
                log(f"bench: quant-readiness summary unavailable: {e}")

    # measured peak HBM (telemetry.memory): the allocator's live watermark
    # after the timed loop when the backend reports one, else the compiled
    # memory_analysis() static estimate — the source is named so a reader
    # never mistakes a static bound for a live measurement
    peak_hbm_bytes = None
    hbm_headroom_fraction = None
    peak_hbm_source = None
    try:
        from neuronx_distributed_training_tpu.telemetry.memory import (
            device_memory_samples, memory_metrics,
        )

        mm = memory_metrics(device_memory_samples([dev]))
        peak_hbm_bytes = mm.get("memory/peak_bytes_max") \
            or mm.get("memory/bytes_in_use_max")
        hbm_headroom_fraction = mm.get("memory/hbm_headroom_fraction")
        if peak_hbm_bytes is not None:
            peak_hbm_source = "memory_stats"
    except Exception as e:  # noqa: BLE001 — sampling must not fail the bench
        log(f"bench: allocator sampling unavailable: {e}")
    if peak_hbm_bytes is None:
        ma = census.get("memory_analysis") or {}
        if ma.get("peak_bytes"):
            peak_hbm_bytes = float(ma["peak_bytes"])
            peak_hbm_source = "memory_analysis"

    tokens_per_sec = mbs * seq / dt
    fwd_ft = perf.flops_for_config(cfg, seq)
    step_ft = perf.train_step_flops_per_token(fwd_ft)
    peak = perf.detect_peak_tflops(dev)
    mfu = perf.mfu(tokens_per_sec, step_ft, peak)
    log(f"bench: {dt * 1e3:.1f} ms/step, {tokens_per_sec:,.0f} tok/s/chip, "
        f"MFU {100 * mfu:.1f}% (peak {peak} TF)")
    out = {
        "ms_per_step": round(dt * 1e3, 2),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu": mfu,
        "peak_tflops": peak,
        "num_layers": cfg.num_layers,
        # trainer-telemetry-schema fields (run_summary.json parity) so the
        # BENCH_*.json trajectory is comparable with training runs
        "compile_seconds": round(compile_seconds, 2),
        "collectives": census.get("collectives"),
        "memory_analysis": census.get("memory_analysis"),
        # measured memory (telemetry.memory / analysis.perf_contract PC501):
        # worst-device peak bytes + remaining headroom fraction
        "peak_hbm_bytes": json_float(peak_hbm_bytes, 1),
        "hbm_headroom_fraction": json_float(hbm_headroom_fraction, 4),
        "peak_hbm_source": peak_hbm_source,
        # numerics-health fields (telemetry.health): a throughput line from a
        # diverging run must be distinguishable from a healthy one
        "nonfinite_steps": nonfinite_steps,
        "skipped_updates": skipped_updates,
        "final_grad_norm": json_float(final_grad_norm),
        # pre-flight graph-audit verdict (rule hits by severity + donation
        # coverage) for the measured executable
        "graph_audit": audit_summary,
    }
    if quant_readiness is not None:
        # compact per-collective-class compression verdict (--tensorstats):
        # predicted SQNR / bytes saved at the largest simulated block size
        out["quant_readiness"] = quant_readiness
    if trace_summary is not None:
        # measured device-time facts (--trace): the achieved-overlap signal
        # the autotune cost model calibrates against
        out.update({
            "achieved_overlap": json_float(
                trace_summary.get("achieved_overlap"), 6),
            "exposed_collective_seconds": json_float(
                trace_summary.get("exposed_collective_seconds"), 6),
            "collective_seconds": json_float(
                trace_summary.get("collective_seconds"), 6),
            "overlap_by_class": {
                k: json_float(v.get("achieved_overlap"), 4)
                for k, v in (trace_summary.get("overlap_by_class")
                             or {}).items()
            },
        })
    return out


def plan_topk_measure(dev, base_cfg, policy, precision_block, seq: int,
                      mbs: int, steps: int, warmup: int, topk: int) -> dict:
    """Measure the autotune planner's top-N plans for the bench workload and
    score predicted-vs-measured rank agreement (Kendall tau).

    The single-chip lattice varies remat policy (and microbatch count when
    gbs allows), so this is a true end-to-end test of the cost model's
    compute/memory terms: every bench run that passes ``--plan-topk``
    appends a fresh calibration point to the JSON record.  A plan that
    fails to run (e.g. remat=none OOM) is recorded with ``measured_ms:
    null`` and excluded from tau."""
    import dataclasses

    from neuronx_distributed_training_tpu.autotune import (
        kendall_tau,
        plan_config,
    )

    raw = {
        "name": "bench", "model_source": "hf",
        "trainer": {"max_steps": 1},
        "distributed_strategy": {"tensor_model_parallel_size": 1,
                                 "zero1": True},
        "data": {"seq_length": seq, "global_batch_size": mbs,
                 "micro_batch_size": mbs, "synthetic": True},
        "model": {
            "architecture": "llama",
            "vocab_size": base_cfg.vocab_size,
            "hidden_size": base_cfg.hidden_size,
            "intermediate_size": base_cfg.intermediate_size,
            "num_layers": base_cfg.num_layers,
            "num_attention_heads": base_cfg.num_attention_heads,
            "num_key_value_heads": base_cfg.num_kv_heads,
            "max_position_embeddings": seq,
            "tie_word_embeddings": base_cfg.tie_word_embeddings,
            "activations_checkpoint_granularity":
                base_cfg.activations_checkpoint_granularity,
        },
        "precision": precision_block,
    }
    report = plan_config(raw, chips=1, audit=False, top_k=topk)
    rows = []
    predicted, measured = [], []
    for cand in report.candidates[:topk]:
        plan = cand.plan
        cfg_i = dataclasses.replace(
            base_cfg,
            activations_checkpoint_granularity=(
                None if plan.remat == "none" else plan.remat),
        )
        from neuronx_distributed_training_tpu.parallel.pipeline import (
            predicted_bubble_fraction,
        )

        row = {"plan": plan.describe(),
               "predicted_ms": round(cand.estimate.step_seconds * 1e3, 2),
               "predicted_hbm_gb": round(cand.estimate.hbm_bytes / 1024**3,
                                         3),
               "bubble_fraction_predicted": round(predicted_bubble_fraction(
                   plan.schedule, plan.pp, plan.num_microbatches, plan.vp), 6),
               "measured_ms": None}
        try:
            # measure the SAME unit the estimate prices: all nm microbatches
            # through the trainer's grad-accumulation scan with ONE
            # optimizer update (naive per-microbatch scaling would count nm
            # updates and bias the tau against small-mbs plans)
            r = run_bench(dev, cfg_i, policy, seq, mbs, steps, warmup,
                          num_microbatches=plan.num_microbatches)
            row["measured_ms"] = r["ms_per_step"]
            # measured memory beside the residual record: the per-plan
            # predicted-vs-measured HBM pair is a calibration point for the
            # cost model's transient constants (telemetry.memory)
            row["peak_hbm_bytes"] = r.get("peak_hbm_bytes")
            row["hbm_headroom_fraction"] = r.get("hbm_headroom_fraction")
            predicted.append(cand.estimate.step_seconds * 1e3)
            measured.append(r["ms_per_step"])
            # per-term predicted-vs-measured residuals: the cost model
            # audited against this benched plan (analysis.perf_contract;
            # comms/bubble terms stay None unless a trace/timeline measured
            # them — the audit never pretends)
            try:
                from neuronx_distributed_training_tpu.analysis.perf_contract import (  # noqa: E501
                    residual_report,
                )

                row["residuals"] = residual_report(
                    cand.estimate.to_dict(),
                    {"step_seconds": r["ms_per_step"] / 1e3,
                     "exposed_collective_seconds": r.get(
                         "exposed_collective_seconds"),
                     "bubble_fraction_measured": r.get(
                         "bubble_fraction_measured")})
            except Exception as e:  # noqa: BLE001 — residuals are advisory
                log(f"bench: residual report unavailable: {e}")
        except Exception as e:  # noqa: BLE001 — one failed plan must not
            # kill the sweep (and its failure is itself signal)
            row["error"] = f"{type(e).__name__}: {e}"[:300]
            log(f"bench: plan-topk candidate failed: {row['error']}")
        rows.append(row)
    tau = kendall_tau(predicted, measured)
    return {
        "plans": rows,
        "kendall_tau": json_float(tau) if tau is not None else None,
        "n_measured": len(measured),
    }


def schedule_sweep(steps: int, warmup: int, *, pp: int = 2, nm: int = 16,
                   vp: int = 2, trace: bool = True) -> dict:
    """Measure ALL FOUR pipeline schedules on one fixed tiny mesh and emit
    per-schedule ``{ms_per_step, bubble_fraction_measured,
    bubble_fraction_predicted, residual}`` rows — the one-command
    reproduction of the work-compacted executor's wall-clock claim
    (interleaved <= plain 1f1b at pp=2/nm=16/vp=2, the exact point the old
    lockstep executor lost by ~1.25x).

    The mesh is ``pipe=pp`` over every visible device (8 virtual CPU
    devices under ``XLA_FLAGS=--xla_force_host_platform_device_count=8``,
    real chips on hardware).  Every schedule runs the SAME flat layer
    stack (reshaped ``to_interleaved`` for vp>1) at identical per-step
    FLOPs, so the rows are directly comparable; each row also captures a
    short device-time trace window AFTER its timed loop and reports the
    timeline-measured bubble fraction beside the table's prediction
    (``analysis.perf_contract`` gates PC302 per row and the
    interleaved-vs-1f1b ordering as PC303)."""
    import functools as _ft

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.parallel import sharding as shd
    from neuronx_distributed_training_tpu.parallel.mesh import (
        MeshConfig, build_mesh,
    )
    from neuronx_distributed_training_tpu.parallel.pipeline import (
        MANUAL_VJP_SCHEDULES,
        pipeline_loss,
        pipeline_loss_and_grad,
        predicted_bubble_fraction,
        to_interleaved,
        work_table,
    )
    from neuronx_distributed_training_tpu.telemetry.step_timeline import (
        pipeline_facts,
    )
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    n_dev = len(jax.devices())
    if n_dev < pp or n_dev % pp:
        raise RuntimeError(
            f"--schedule-sweep needs a device count divisible by pp={pp} "
            f"(got {n_dev}; on CPU set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            f"jax imports)")

    policy = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                         softmax_dtype=jnp.float32)
    mb, seq = max(4, n_dev // pp), 64
    cfg = llama.LlamaConfig(
        vocab_size=512, hidden_size=256, intermediate_size=512,
        num_layers=2 * pp * vp, num_attention_heads=4, num_kv_heads=2,
        max_position_embeddings=seq,
        activations_checkpoint_granularity=None,
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)
    ids = jax.random.randint(jax.random.PRNGKey(1), (nm, mb, seq), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    mbs = {"input_ids": ids, "labels": ids}
    embed_fn, stage_fn, loss_fn = llama.pipeline_hooks(cfg, policy)
    hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(cfg, policy)

    def sharded(mesh, schedule_vp):
        specs = llama.param_specs(cfg, pipeline=True)
        p = params
        if schedule_vp > 1:
            p = {**p, "layers": to_interleaved(p["layers"], pp, schedule_vp)}
            specs = dict(specs)
            specs["layers"] = jax.tree_util.tree_map(
                lambda sp: P(None, sp[0], None, *tuple(sp)[1:]),
                specs["layers"], is_leaf=lambda x: isinstance(x, P))
        ns = _ft.partial(NamedSharding, mesh)
        shp = jax.device_put(p, jax.tree_util.tree_map(
            ns, specs, is_leaf=lambda x: isinstance(x, P)))
        shm = jax.device_put(mbs, ns(P(None, ("data", "expert"))))
        return shp, shm

    def loss_and_grad(mesh, schedule, schedule_vp):
        if schedule == "wavefront":
            def fn(p, m):
                return jax.value_and_grad(
                    lambda p_, m_: pipeline_loss(
                        p_, p_["layers"], m_, embed_fn=embed_fn,
                        stage_fn=stage_fn, loss_fn=loss_fn, mesh=mesh,
                        virtual_pipeline_size=schedule_vp))(p, m)
        else:
            def fn(p, m):
                return pipeline_loss_and_grad(
                    p, p["layers"], m, embed_fn=embed_fn, stage_fn=stage_fn,
                    head_hidden_fn=hh, head_params=hp_of(p),
                    head_weight=hw_of(p), mesh=mesh,
                    virtual_pipeline_size=schedule_vp,
                    zero_bubble=(schedule == "1f1b-zb"))
        return fn

    # wavefront measures at the SAME vp as the interleave (identical layer
    # layout and circular schedule — the apples-to-apples memory rival)
    matrix = [("wavefront", vp), ("1f1b", 1), ("1f1b-interleaved", vp),
              ("1f1b-zb", 1)]
    rows = []
    for schedule, svp in matrix:
        mesh = build_mesh(MeshConfig(
            pipeline_model_parallel_size=pp,
            virtual_pipeline_model_parallel_size=svp))
        shp, shm = sharded(mesh, svp)
        fn = loss_and_grad(mesh, schedule, svp)
        row = {"schedule": schedule, "pp": pp, "nm": nm, "vp": svp,
               "bubble_fraction_predicted": round(
                   predicted_bubble_fraction(schedule, pp, nm, svp), 6)}
        with mesh, shd.use_mesh(mesh):
            jfn = jax.jit(fn)
            t_c = time.perf_counter()
            out = jfn(shp, shm)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            row["compile_seconds"] = round(time.perf_counter() - t_c, 2)
            for _ in range(warmup):
                out = jfn(shp, shm)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            t0 = time.perf_counter()
            for _ in range(steps):
                out = jfn(shp, shm)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            row["ms_per_step"] = round(
                (time.perf_counter() - t0) / max(steps, 1) * 1e3, 2)
            loss = out[0]
            row["loss"] = json_float(float(loss), 5)
            if trace:
                import tempfile

                from neuronx_distributed_training_tpu.telemetry.trace import (
                    trace_steps,
                )

                def _step(i):
                    o = jfn(shp, shm)
                    # fence on the loss scalar only: a full-tree fetch
                    # would put host time inside the annotation window and
                    # inflate the measured idle
                    o[0].block_until_ready()

                ticks = (work_table(schedule, pp, nm, svp).tick_counts()
                         if schedule in MANUAL_VJP_SCHEDULES else None)
                try:
                    summary = trace_steps(
                        _step, 2,
                        tempfile.mkdtemp(prefix="nxdt_sweep_trace_"),
                        pipeline=pipeline_facts(
                            schedule, pp, nm, svp,
                            row["bubble_fraction_predicted"],
                            ticks_per_step=ticks))
                except Exception as e:  # noqa: BLE001 — one schedule's
                    # trace failure must not kill the sweep
                    summary = None
                    log(f"bench: sweep trace failed for {schedule}: {e}")
                pipe = (summary or {}).get("pipeline") or {}
                row["bubble_fraction_measured"] = json_float(
                    pipe.get("bubble_fraction_measured"), 6)
                row["bubble_residual"] = json_float(
                    pipe.get("bubble_residual"), 6)
                row["ticks_detected"] = pipe.get("ticks_detected")
        log(f"bench[sweep] {schedule:<17} {row['ms_per_step']:>8.2f} ms/step"
            f"  predicted_bubble={row['bubble_fraction_predicted']:.4f}"
            f"  measured={row.get('bubble_fraction_measured')}")
        rows.append(row)

    by_sched = {r["schedule"]: r for r in rows}
    ratio = None
    if by_sched.get("1f1b", {}).get("ms_per_step"):
        ratio = round(by_sched["1f1b-interleaved"]["ms_per_step"]
                      / by_sched["1f1b"]["ms_per_step"], 4)
    return {
        "rows": rows,
        "pp": pp, "nm": nm, "vp": vp,
        "micro_batch": mb, "seq_len": seq, "num_layers": cfg.num_layers,
        "interleaved_over_1f1b": ratio,
    }


def overlap_sweep(steps: int, warmup: int, *, trace: bool = True) -> dict:
    """Measure the engineered-overlap claim end to end: the SAME tiny
    dp-only ZeRO-1 training step at three ``distributed_strategy.overlap``
    settings — monolithic (``off``), one combined bucket (``bucketed-1``),
    and per-layer-group buckets (``bucketed-N``) — and emit per-variant
    ``{ms_per_step, exposed_collective_seconds, achieved overlap by class}``
    rows from a device-time trace window.

    Each variant goes through the REAL trainer assembly
    (``trainer.loop.assemble_step_program``): the bucket plan, the prefetch
    barrier chain, and the jitted step are exactly what a training run gets
    — nothing here is a bench-only reimplementation.  All variants share
    seed/model/data, so their losses must agree (reported per row; the
    parity matrix in tests/test_overlap.py pins it bitwise-level at
    tolerance).  ``analysis.perf_contract`` gates the ordering (PC203:
    bucketed exposed collective seconds at or below monolithic) and the
    committed ``<device>_overlap_sweep`` baseline ratchets per-row drift."""
    import functools as _ft

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.optim.adamw import init_opt_state
    from neuronx_distributed_training_tpu.optim.overlap import (
        build_bucket_plan,
    )
    from neuronx_distributed_training_tpu.parallel import sharding as shd
    from neuronx_distributed_training_tpu.telemetry.health import (
        grad_group_of,
    )
    from neuronx_distributed_training_tpu.trainer.loop import (
        assemble_step_program,
    )

    n_dev = len(jax.devices())
    if n_dev < 2:
        raise RuntimeError(
            f"--overlap-sweep needs >= 2 devices for dp collectives (got "
            f"{n_dev}; on CPU set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            f"jax imports)")

    seq, gbs = 128, n_dev
    base = {
        "name": "overlap_sweep",
        "model_source": "hf",
        "seed": 0,
        "trainer": {"max_steps": max(steps, 2)},
        "distributed_strategy": {"zero1": True},
        "data": {"seq_length": seq, "global_batch_size": gbs,
                 "micro_batch_size": 1, "synthetic": True},
        "model": {
            "architecture": "llama", "vocab_size": 2048,
            "hidden_size": 256, "intermediate_size": 512, "num_layers": 4,
            "num_attention_heads": 4, "num_key_value_heads": 2,
            "max_position_embeddings": seq,
            "optim": {"name": "adamw_fp32OptState", "lr": 1.0e-3,
                      "sched": {"name": "CosineAnnealing",
                                "warmup_steps": 2,
                                "max_steps": max(steps, 2)}},
        },
        "precision": {"type": "mixed_precision"},
    }
    # one combined bucket vs a bucket per layer group: the huge size
    # coalesces everything, the tiny size closes a bucket at every
    # grad_group_of boundary
    variants = [("off", None), ("bucketed-1", 1024.0), ("bucketed-N", 1e-6)]

    import numpy as _np

    ids = _np.random.default_rng(0).integers(
        0, base["model"]["vocab_size"], (gbs, seq), dtype=_np.int32)

    rows = []
    for variant, bucket_mb in variants:
        cfg_doc = json.loads(json.dumps(base))
        if bucket_mb is not None:
            cfg_doc["distributed_strategy"]["overlap"] = {
                "zero1_bucket_mb": bucket_mb, "prefetch_ag": True}
        cfg = load_config(cfg_doc)
        asm = assemble_step_program(cfg, build_data=False)
        mesh = asm.mesh
        ns = _ft.partial(NamedSharding, mesh)
        shardings = lambda specs: jax.tree_util.tree_map(  # noqa: E731
            ns, specs, is_leaf=lambda x: isinstance(x, P))
        row = {"variant": variant,
               "bucket_mb": bucket_mb, "n_buckets": 0}
        if bucket_mb is not None:
            plan = build_bucket_plan(
                asm.abstract_params, asm.pspecs, asm.ospecs["mu"], mesh,
                bucket_mb=bucket_mb, group_fn=grad_group_of)
            row["n_buckets"] = len(plan.buckets) if plan else 0
        with mesh, shd.use_mesh(mesh):
            params = jax.jit(asm.param_builder,
                             out_shardings=shardings(asm.pspecs))(asm.init_key)
            opt_state = jax.jit(
                _ft.partial(init_opt_state, policy=asm.policy,
                            ema=asm.ema_cfg is not None,
                            health=getattr(asm.health_cfg, "enabled", False)),
                out_shardings=shardings(asm.ospecs))(params)
            batch = jax.device_put(
                {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)},
                ns(P(("data", "expert"))))
            key = jax.random.PRNGKey(7)
            jstep = asm.jstep
            t_c = time.perf_counter()
            params, opt_state, metrics = jstep(params, opt_state, batch, key)
            metrics["loss"].block_until_ready()
            row["compile_seconds"] = round(time.perf_counter() - t_c, 2)
            for _ in range(warmup):
                params, opt_state, metrics = jstep(params, opt_state, batch,
                                                   key)
            metrics["loss"].block_until_ready()
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, metrics = jstep(params, opt_state, batch,
                                                   key)
            metrics["loss"].block_until_ready()
            row["ms_per_step"] = round(
                (time.perf_counter() - t0) / max(steps, 1) * 1e3, 2)
            row["loss"] = json_float(float(metrics["loss"]), 5)
            if trace:
                import tempfile

                from neuronx_distributed_training_tpu.telemetry.trace import (
                    trace_steps,
                )

                def _step(i):
                    nonlocal params, opt_state, metrics
                    params, opt_state, metrics = jstep(params, opt_state,
                                                       batch, key)
                    metrics["loss"].block_until_ready()

                try:
                    # 3 traced steps: per-step collective timings on the
                    # virtual-CPU mesh jitter with host scheduling, and the
                    # PC203 ordering gate needs the averaging
                    summary = trace_steps(
                        _step, 3, tempfile.mkdtemp(prefix="nxdt_ov_trace_"))
                except Exception as e:  # noqa: BLE001 — one variant's trace
                    # failure must not kill the sweep
                    summary = None
                    log(f"bench: overlap trace failed for {variant}: {e}")
                summary = summary or {}
                row["exposed_collective_seconds"] = json_float(
                    summary.get("exposed_collective_seconds"), 9)
                row["collective_seconds"] = json_float(
                    summary.get("collective_seconds"), 9)
                row["achieved_overlap"] = json_float(
                    summary.get("achieved_overlap"), 6)
                row["overlap_by_class"] = summary.get("overlap_by_class") or {}
        log(f"bench[overlap] {variant:<11} buckets={row['n_buckets']:<2} "
            f"{row['ms_per_step']:>8.2f} ms/step  "
            f"exposed={row.get('exposed_collective_seconds')}s")
        rows.append(row)

    by_var = {r["variant"]: r for r in rows}
    ratio = None
    off_exp = (by_var.get("off") or {}).get("exposed_collective_seconds")
    bn_exp = (by_var.get("bucketed-N") or {}).get(
        "exposed_collective_seconds")
    if off_exp and bn_exp is not None:
        ratio = round(bn_exp / off_exp, 4)
    return {
        "rows": rows,
        "dp": n_dev, "seq_len": seq, "global_batch": gbs,
        "num_layers": base["model"]["num_layers"],
        "bucketed_over_off_exposed": ratio,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--mbs", type=int, default=1)
    ap.add_argument("--attn", choices=["auto", "core", "flash"], default="auto")
    ap.add_argument("--block-q", type=int, default=None,
                    help="flash tile override (per-chip tuning sweep)")
    ap.add_argument("--block-kv", type=int, default=None)
    ap.add_argument("--regime", choices=["both", "mixed", "bf16"], default="both")
    ap.add_argument("--remat", choices=["selective", "full", "none"],
                    default="selective",
                    help="activation-checkpoint granularity for the bench "
                         "model (perf experiment knob)")
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                    help="force a platform (cpu for local smoke runs)")
    ap.add_argument("--untied", action="store_true",
                    help="untie embeddings/head (off the pinned bench config; "
                         "for comparison runs only)")
    ap.add_argument("--probe-deeper", action="store_true",
                    help="also try one layer past the HBM estimate (manual "
                         "sessions only — an OOM can wedge the tunnelled chip)")
    ap.add_argument("--direct", action="store_true", default=True,
                    help="connect in-process under a watchdog (exit 86 + "
                         "diagnostic JSON on a hung connect) instead of "
                         "burning a throwaway subprocess probe connection — "
                         "each client teardown can wedge the tunnelled "
                         "backend (bench_results/probe_r4.log). DEFAULT so "
                         "the driver's round-end capture is itself the one "
                         "and only client connection.")
    ap.add_argument("--probe-subprocess", dest="direct", action="store_false",
                    help="legacy acquire: probe availability in a subprocess "
                         "first (costs an extra client teardown)")
    ap.add_argument("--connect-timeout", type=float, default=300.0,
                    help="--direct watchdog budget for jax.devices()")
    ap.add_argument("--plan-topk", type=int, default=0, metavar="N",
                    help="additionally MEASURE the autotune planner's top-N "
                         "single-chip plans (remat/microbatch lattice) and "
                         "record predicted-vs-measured rank agreement "
                         "(Kendall tau) in the JSON line — every bench run "
                         "scores the cost model")
    ap.add_argument("--trace", action="store_true",
                    help="capture a short device-time trace window AFTER "
                         "the timed loop (telemetry.trace) and emit the "
                         "measured achieved_overlap / "
                         "exposed_collective_seconds in the JSON line — "
                         "the signal the autotune cost model's comms term "
                         "calibrates against")
    ap.add_argument("--tensorstats", action="store_true",
                    help="ride the in-graph tensor-numerics plane "
                         "(telemetry.tensorstats) on the bench step and "
                         "emit a compact per-collective-class "
                         "quant-readiness summary in the JSON line "
                         "(predicted SQNR / bytes saved for block-scaled "
                         "int8; combine with --trace to price the savings "
                         "in measured exposed seconds)")
    ap.add_argument("--contract-key", default=None, metavar="NAME",
                    help="perf-contract baseline key override (default: "
                         "derived from the device identity, e.g. cpu_bench "
                         "— analysis/perf_baselines/<key>.json)")
    ap.add_argument("--calibration", action="store_true",
                    help="low-fidelity connect-reliability run: append to the "
                         "measured log but do NOT refresh last_measured.json "
                         "(the authoritative headline line)")
    ap.add_argument("--schedule-sweep", action="store_true",
                    help="measure ALL FOUR pipeline schedules (wavefront, "
                         "1f1b, 1f1b-interleaved, 1f1b-zb) on a fixed tiny "
                         "pp=2/nm=16/vp=2 mesh and emit per-schedule "
                         "{ms_per_step, bubble_fraction_measured/predicted, "
                         "residual} rows in the JSON line — the one-command "
                         "reproduction of the work-compacted executor's "
                         "wall-clock ordering (runs INSTEAD of the headline "
                         "single-chip bench)")
    ap.add_argument("--overlap-sweep", action="store_true",
                    help="measure the engineered-overlap claim: the same "
                         "dp-only ZeRO-1 training step at overlap settings "
                         "{off, one bucket, per-group buckets} and emit "
                         "per-variant {ms_per_step, "
                         "exposed_collective_seconds, overlap by class} "
                         "rows in the JSON line — PC203 gates bucketed "
                         "exposed <= monolithic (runs INSTEAD of the "
                         "headline single-chip bench)")
    ap.add_argument("--comms", action="store_true",
                    help="run the interconnect sweep (telemetry.comms) "
                         "AFTER the timed loop on a small tp=2/pp=2 mesh "
                         "and embed per-axis fitted bandwidth + per-class "
                         "achieved_gbps in the headline JSON line "
                         "(verdict-gated via PC204; tools/comms_bench.py "
                         "is the standalone, full-control version)")
    args = ap.parse_args()

    if (args.schedule_sweep or args.overlap_sweep or args.comms) \
            and args.platform == "cpu":
        # the sweeps need a multi-device mesh; opportunistically request 8
        # virtual CPU devices — effective only when jax has not been
        # imported yet (the verify gate sets XLA_FLAGS in the environment,
        # which always works).  Merged against any user-provided XLA_FLAGS
        # with the user's flags WINNING on conflict — the old blind append
        # relied on XLA's silent duplicate-flag last-wins
        import os as _os

        from neuronx_distributed_training_tpu.optim.overlap import (
            merge_xla_flags,
        )

        merged, conflicts = merge_xla_flags(
            _os.environ.get("XLA_FLAGS", ""),
            ("--xla_force_host_platform_device_count=8",))
        for name, yours, dropped in conflicts:
            log(f"bench: XLA_FLAGS conflict on {name}: keeping your "
                f"{yours!r}, dropping {dropped!r}")
        _os.environ["XLA_FLAGS"] = merged

    dev, backend_err, provenance = acquire_device(
        platform=args.platform, direct=args.direct,
        connect_timeout_s=args.connect_timeout)
    if dev is None:
        fail_json(f"no backend available: {backend_err}",
                  provenance=provenance)
        return

    if args.schedule_sweep:
        from neuronx_distributed_training_tpu.analysis import (
            perf_contract as _pc,
        )

        on_tpu_sweep = dev.platform == "tpu"
        steps, warmup = (args.steps, args.warmup) if on_tpu_sweep \
            else (min(args.steps, 4), min(args.warmup, 1))
        try:
            sweep = schedule_sweep(steps, warmup)
        except Exception as e:  # noqa: BLE001 — the driver must get JSON
            traceback.print_exc()
            fail_json(f"schedule sweep failed: {type(e).__name__}: {e}",
                      provenance=provenance)
            return
        payload = {
            "metric": "pipeline_schedule_sweep",
            "value": sweep.get("interleaved_over_1f1b") or 0.0,
            "unit": "interleaved_over_1f1b_step_time_ratio",
            # the planner prices interleaved at or below plain 1f1b —
            # a ratio <= 1.0 is the measured-wall-clock win
            "vs_baseline": sweep.get("interleaved_over_1f1b") or 0.0,
            "device": dev.device_kind,
            "seq_len": sweep.get("seq_len"),
            "num_layers": sweep.get("num_layers"),
            "pipeline_schedule": "sweep",
            "schedule_sweep": sweep,
            "provenance": provenance,
            "note": ("all four pipeline schedules on one fixed mesh "
                     "(pp=2/nm=16/vp=2); per-row PC302 bubble calibration "
                     "and the PC303 interleaved<=1f1b ordering gate run in "
                     "tools/perf_contract.py --check"),
        }
        try:
            facts = _pc.perf_facts_from_bench(payload)
            key = args.contract_key or _pc.default_key(facts)
            payload["perf_contract"] = _pc.bench_verdict(key, facts)
            log(f"bench: perf contract [{key}]: "
                f"{payload['perf_contract']['verdict']}")
        except Exception as e:  # noqa: BLE001 — the verdict must not kill
            # the line, but its absence must be explained
            payload["perf_contract"] = {
                "verdict": "unavailable",
                "error": f"{type(e).__name__}: {e}"[:300],
            }
        emit(payload)
        return

    if args.overlap_sweep:
        from neuronx_distributed_training_tpu.analysis import (
            perf_contract as _pc,
        )

        on_tpu_ov = dev.platform == "tpu"
        steps, warmup = (args.steps, args.warmup) if on_tpu_ov \
            else (min(args.steps, 4), min(args.warmup, 1))
        try:
            sweep = overlap_sweep(steps, warmup)
        except Exception as e:  # noqa: BLE001 — the driver must get JSON
            traceback.print_exc()
            fail_json(f"overlap sweep failed: {type(e).__name__}: {e}",
                      provenance=provenance)
            return
        payload = {
            "metric": "zero1_overlap_sweep",
            "value": sweep.get("bucketed_over_off_exposed") or 0.0,
            "unit": "bucketed_over_off_exposed_collective_ratio",
            # bucketing + prefetch must EXPOSE less collective time than
            # the monolithic regather — a ratio <= 1.0 is the win
            "vs_baseline": sweep.get("bucketed_over_off_exposed") or 0.0,
            "device": dev.device_kind,
            "seq_len": sweep.get("seq_len"),
            "num_layers": sweep.get("num_layers"),
            "overlap_sweep": sweep,
            "provenance": provenance,
            "note": ("the same dp-only ZeRO-1 step at overlap settings "
                     "{off, bucketed-1, bucketed-N}; PC203 gates bucketed "
                     "exposed <= monolithic and the committed baseline "
                     "ratchets per-variant drift in tools/perf_contract.py "
                     "--check"),
        }
        try:
            facts = _pc.perf_facts_from_bench(payload)
            key = args.contract_key or _pc.default_key(facts)
            payload["perf_contract"] = _pc.bench_verdict(key, facts)
            log(f"bench: perf contract [{key}]: "
                f"{payload['perf_contract']['verdict']}")
        except Exception as e:  # noqa: BLE001 — the verdict must not kill
            # the line, but its absence must be explained
            payload["perf_contract"] = {
                "verdict": "unavailable",
                "error": f"{type(e).__name__}: {e}"[:300],
            }
        emit(payload)
        return

    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    on_tpu = dev.platform == "tpu"
    if args.attn == "auto":
        attn_impl = "flash" if on_tpu else "core"
    else:
        attn_impl = args.attn
    # Flash attention handles seq 8192; naive core attention's O(s^2)
    # transients need the shorter default on small-HBM chips.
    seq = args.seq or ((8192 if attn_impl == "flash" else 4096) if on_tpu else 512)
    steps, warmup = (args.steps, args.warmup) if on_tpu else (
        min(args.steps, 4), min(args.warmup, 1))
    try:
        hbm = dev.memory_stats()["bytes_limit"]
    except Exception:
        hbm = 16 << 30

    # Regime definitions (reference precision matrix,
    # training_orchestrator.py:104-137):
    #  - mixed_precision: bf16 compute, fp32 master + opt state (+fp32 grad
    #    accum) -> ~18 resident bytes/param incl. transient fp32 grads
    #  - bf16SR: everything bf16 -> ~8 bytes/param incl. transient grads
    # The raw blocks are the single source both the measured policy AND the
    # plan-topk ModelFacts derive from (they must agree or the predicted-vs-
    # measured comparison silently compares different precisions).
    precision_blocks = {
        "mixed_precision": "mixed_precision",
        "bf16": {"type": "bf16SR", "optimizer_dtype": "bf16",
                 "grad_accum_dtype": "bf16"},
    }
    regime_bytes_per_param = {"mixed_precision": 18.0, "bf16": 8.0}
    regimes = {
        name: (DtypePolicy.from_precision_config(block),
               regime_bytes_per_param[name])
        for name, block in precision_blocks.items()
    }
    if args.regime == "mixed":
        wanted = ["mixed_precision"]
    elif args.regime == "bf16":
        wanted = ["bf16"]
    else:
        wanted = ["mixed_precision", "bf16"] if on_tpu else ["mixed_precision"]

    import dataclasses

    tied = not args.untied
    results: dict[str, dict] = {}
    errors: dict[str, str] = {}
    used_cfgs: dict[str, object] = {}
    for name in wanted:
        policy, bpp = regimes[name]
        est = args.layers or layer_budget(hbm, bpp, tied=tied)
        cfg = make_config(llama, on_tpu, attn_impl, seq, est, hbm, bpp,
                          tied=tied, block_q=args.block_q, block_kv=args.block_kv)
        if args.remat != "selective":
            cfg = dataclasses.replace(
                cfg, activations_checkpoint_granularity=(
                    None if args.remat == "none" else args.remat))
        # deepest-stack search.  Default (driver-safe): start AT the
        # conservative estimate and walk DOWN on OOM — never deliberately
        # over-allocate, an OOM can wedge the tunnelled chip for hours and
        # zero the driver's own capture (round-2 post-mortem).
        # --probe-deeper (manual sessions only) additionally tries est+1.
        if args.layers:
            candidates = [args.layers]
        elif on_tpu:
            cand = {est, max(1, est - 1), 1}
            if args.probe_deeper:
                cand.add(est + 1)
            candidates = sorted(cand, reverse=True)
        else:
            candidates = [cfg.num_layers]
        log(f"bench[{name}]: device={dev.device_kind} layer candidates="
            f"{candidates} seq={seq} mbs={args.mbs} attn={cfg.attention_impl} "
            f"tied={tied}")
        for n_layers in candidates:
            try:
                cfg = dataclasses.replace(cfg, num_layers=n_layers)
                results[name] = run_bench(
                    dev, cfg, policy, seq, args.mbs, steps, warmup,
                    trace=args.trace, tensorstats=args.tensorstats)
                results[name]["tied_embeddings"] = tied
                used_cfgs[name] = cfg
                errors.pop(name, None)  # a successful backoff clears the record
                break
            except Exception as e:  # noqa: BLE001 — keep the other regime alive
                errors[name] = f"layers={n_layers}: {type(e).__name__}: {e}"
                log(f"bench[{name}] failed: {errors[name]}\n{traceback.format_exc()}")
                oom = any(s in errors[name] for s in
                          ("RESOURCE_EXHAUSTED", "Out of memory", "OOM",
                           "Allocat", "HBM"))
                if not oom:
                    break  # fewer layers won't fix a non-memory failure

    if not results:
        fail_json("; ".join(f"{k}: {v}" for k, v in errors.items()) or "no regime ran",
                  provenance=provenance,
                  device=getattr(dev, "device_kind", str(dev)))
        return

    # headline: prefer the baseline regime (mixed_precision), but a
    # single-layer stack never headlines over a multi-layer one — the
    # round-3 contract is a multi-layer, pinned-config number.  On a 16G
    # chip the mixed regime's fp32 master+opt state for the tied 0.53B-param
    # embedding alone (~9.5 GB) can cap it at 1 layer; the bf16 regime then
    # carries the multi-layer headline and mixed is reported alongside.
    def _pref(name: str) -> tuple:
        r = results[name]
        return (r["num_layers"] > 1, name == "mixed_precision", r["mfu"])

    headline = max(results, key=_pref)
    r = results[headline]
    payload = {
        "metric": "llama3_8B_pretrain_mfu",
        "value": round(100 * r["mfu"], 2),
        "unit": "percent_mfu",
        "vs_baseline": round(r["mfu"] / 0.45, 4),
        "regime": headline,
        "tokens_per_sec_per_chip": r["tokens_per_sec"],
        "ms_per_step": r["ms_per_step"],
        "device": dev.device_kind,
        "attn_impl": attn_impl,
        "num_layers": r["num_layers"],
        "tied_embeddings": r.get("tied_embeddings", tied),
        "seq_len": seq,
        # the trainer's telemetry schema (metrics.jsonl / run_summary.json
        # key names): mfu as a FRACTION alongside the percent headline, plus
        # the headline regime's compile census
        "mfu": round(r["mfu"], 6),
        "compile_seconds": r.get("compile_seconds"),
        "collectives": r.get("collectives"),
        "memory_analysis": r.get("memory_analysis"),
        # measured memory (telemetry.memory; perf-contract PC501 gates the
        # peak, PC502 the predicted-vs-measured agreement when a planner
        # prediction rides along)
        "peak_hbm_bytes": r.get("peak_hbm_bytes"),
        "hbm_headroom_fraction": r.get("hbm_headroom_fraction"),
        "peak_hbm_source": r.get("peak_hbm_source"),
        # numerics health (telemetry.health): fast-but-diverging vs healthy
        "nonfinite_steps": r.get("nonfinite_steps"),
        "skipped_updates": r.get("skipped_updates"),
        "final_grad_norm": r.get("final_grad_norm"),
        # headline regime's static graph-audit verdict (analysis.graph_audit)
        "graph_audit": r.get("graph_audit"),
        # measured device-time overlap (--trace; None when not captured)
        "achieved_overlap": r.get("achieved_overlap"),
        "exposed_collective_seconds": r.get("exposed_collective_seconds"),
        # pipeline-schedule telemetry (run_summary.json key names): the
        # single-chip bench runs unpipelined, so the headline prediction is
        # 0.0 — the field exists so the bench trajectory and trainer
        # summaries share a schema (plan-topk rows carry per-plan values)
        "pipeline_schedule": "none",
        "bubble_fraction_predicted": 0.0,
        # bench provenance: acquire mode, watchdog phase tag reached, PJRT
        # handshake + first-RPC timing, backend identity — on EVERY line, so
        # a dead round is diagnosable from the artifact alone (r02-r05)
        "provenance": provenance,
        "note": ("deepest Llama-3-8B-shape stack fitting single-chip HBM "
                 "(tied embeddings, pinned config); MFU is per-layer-shape-bound"),
    }
    for name, res in results.items():
        payload[f"mfu_{name}"] = round(100 * res["mfu"], 2)
        payload[f"layers_{name}"] = res["num_layers"]
        payload[f"graph_audit_{name}"] = res.get("graph_audit")
    if args.plan_topk and headline in used_cfgs:
        # measure the planner's top-N plans for the HEADLINE workload and
        # score the cost model's ranking against reality
        try:
            payload["plan_topk"] = plan_topk_measure(
                dev, used_cfgs[headline], regimes[headline][0],
                precision_blocks[headline], seq, args.mbs, steps, warmup,
                args.plan_topk,
            )
            log(f"bench: plan-topk kendall_tau="
                f"{payload['plan_topk']['kendall_tau']}")
        except Exception as e:  # noqa: BLE001 — the headline line must
            # survive a planner failure
            payload["plan_topk"] = {"error": f"{type(e).__name__}: {e}"[:500]}
            log(f"bench: plan-topk failed: {payload['plan_topk']['error']}")
    drill = load_last_drill()
    if drill.get("ok"):
        # elastic-resume drill trail (tools/elastic_drill.py): restart cost
        # and post-resume goodput from the last completed drill
        payload["restart_cost_seconds"] = drill.get("restart_cost_seconds")
        payload["goodput_fraction"] = drill.get("goodput_fraction")
        payload["drill"] = {
            k: drill.get(k)
            for k in ("date", "mode", "phase", "world", "resume_world",
                      "replanned", "max_loss_diff")
        }
        if drill.get("integrity"):
            # corruption-drill leg (elastic_drill --smoke): which injection
            # kind was survived and how far the walk-back went
            payload["drill"]["integrity"] = drill["integrity"]
    if errors:
        payload["regime_errors"] = errors
    if backend_err:
        payload["backend_retries"] = backend_err
    if args.comms:
        # interconnect sweep AFTER the timed loop (telemetry.comms): time
        # the collective classes on a small tp=2/pp=2 mesh, fit per-axis
        # bandwidth/latency, and embed the facts block — PC204 then rides
        # the same verdict the headline carries
        try:
            import jax as _jax

            from neuronx_distributed_training_tpu.autotune.topology import (
                resolve_topology,
            )
            from neuronx_distributed_training_tpu.parallel.mesh import (
                MeshConfig,
                build_mesh,
            )
            from neuronx_distributed_training_tpu.telemetry import (
                comms as _comms,
            )

            devs = _jax.devices()
            tp = 2 if len(devs) % 2 == 0 and len(devs) >= 2 else 1
            pp = 2 if len(devs) % (tp * 2) == 0 and len(devs) >= 4 else 1
            mesh = build_mesh(MeshConfig(tensor_model_parallel_size=tp,
                                         pipeline_model_parallel_size=pp),
                              devs)
            sizes = (1 << 18, 1 << 20) if not on_tpu else (1 << 22, 1 << 24)
            axis_results = _comms.run_comms_sweep(
                mesh, sizes_bytes=sizes, warmup=1, reps=3)
            topo = resolve_topology(device=devs[0])
            summary = _comms.build_comms_summary(
                axis_results, topology_name=topo.name,
                prior_bandwidth_bytes=topo.ici_bandwidth_bytes,
                prior_latency_seconds=topo.ici_latency_seconds,
                device_skew=_comms.measure_device_skew(devs))
            payload["comms"] = _comms.bench_comms_facts(summary)
            payload["comms_findings"] = summary.get("findings") or []
            log(f"bench: comms sweep fitted axes="
                f"{sorted((payload['comms'].get('axes') or {}))}")
        except Exception as e:  # noqa: BLE001 — the headline must survive
            payload["comms"] = None
            payload["comms_error"] = f"{type(e).__name__}: {e}"[:300]
            log(f"bench: comms sweep failed: {payload['comms_error']}")
    if args.calibration:
        # low-fidelity connect-reliability line — must be distinguishable
        # from headline measurements by any later reader of the jsonl
        payload["calibration"] = True
        payload["steps"] = steps
        payload["warmup"] = warmup
    # the perf-contract verdict: the measured line checked against the
    # committed per-topology baseline (analysis.perf_contract) — emit()
    # REFUSES a headline line without this field, and "no_baseline" is an
    # honest verdict where silence would not be
    try:
        from neuronx_distributed_training_tpu.analysis import (
            perf_contract as _pc,
        )

        facts = _pc.perf_facts_from_bench(payload)
        key = args.contract_key or _pc.default_key(facts)
        payload["perf_contract"] = _pc.bench_verdict(key, facts)
        log(f"bench: perf contract [{key}]: "
            f"{payload['perf_contract']['verdict']}")
    except Exception as e:  # noqa: BLE001 — the verdict must not kill the
        # line, but its absence must be explained
        payload["perf_contract"] = {
            "verdict": "unavailable",
            "error": f"{type(e).__name__}: {e}"[:300],
        }
    if on_tpu:
        record_measurement(payload, refresh_last=not args.calibration)
    emit(payload)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver must always get JSON
        traceback.print_exc()
        fail_json(f"{type(e).__name__}: {e}")
