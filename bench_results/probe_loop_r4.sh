#!/bin/bash
# Poll the tunnelled TPU backend until it answers a tiny matmul with a value fetch.
LOG=/root/repo/bench_results/probe_r4.log
for i in $(seq 1 200); do
  echo "=== attempt $i $(date -u +%H:%M:%S) ===" >> "$LOG"
  timeout 180 env PYTHONPATH=/root/.axon_site python -c "
import time, jax, jax.numpy as jnp
t0=time.time()
d = jax.devices()
x = jnp.ones((256,256), jnp.bfloat16)
v = float(jnp.sum(x @ x))
print('PROBE_OK', d[0].platform, d[0].device_kind, round(time.time()-t0,1))
" >> "$LOG" 2>&1
  if grep -q PROBE_OK "$LOG"; then echo "BACKEND HEALTHY at $(date -u +%H:%M:%S)" >> "$LOG"; exit 0; fi
  sleep 240
done
