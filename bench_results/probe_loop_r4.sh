#!/bin/bash
# Poll the tunnelled TPU backend until it answers a tiny matmul with a value
# fetch; on recovery, immediately run the self-recording bench (both regimes)
# so the driver-visible number exists even if no one is watching.
LOG=/root/repo/bench_results/probe_r4.log
BLOG=/root/repo/bench_results/bench_r4_auto.log
cd /root/repo || exit 1
for i in $(seq 1 400); do
  echo "=== attempt $i $(date -u +%H:%M:%S) ===" >> "$LOG"
  timeout 180 env PYTHONPATH=/root/.axon_site python -c "
import time, jax, jax.numpy as jnp
t0=time.time()
d = jax.devices()
x = jnp.ones((256,256), jnp.bfloat16)
v = float(jnp.sum(x @ x))
print('PROBE_OK', d[0].platform, d[0].device_kind, round(time.time()-t0,1))
" >> "$LOG" 2>&1
  if tail -5 "$LOG" | grep -q PROBE_OK; then
    echo "BACKEND HEALTHY at $(date -u +%H:%M:%S) - running bench" >> "$LOG"
    timeout 5400 env PYTHONPATH=/root/repo:/root/.axon_site \
      python bench.py >> "$BLOG" 2>&1
    rc=$?
    echo "bench rc=$rc done at $(date -u +%H:%M:%S)" >> "$LOG"
    if [ "$rc" = "0" ]; then
      # r3_notes follow-up 1 (small, never-over-allocate): EMA donation repro
      timeout 1200 env PYTHONPATH=/root/repo:/root/.axon_site \
        python tools/ema_donation_probe.py >> "$BLOG" 2>&1
      echo "ema_donation_probe rc=$? at $(date -u +%H:%M:%S)" >> "$LOG"
    fi
    exit 0
  fi
  sleep 240
done
