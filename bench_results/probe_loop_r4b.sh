#!/bin/bash
# Round-4 bench capture loop — connection-discipline revision.
#
# Evidence so far this round (probe_r4.log, bench_r4_auto.log):
#   15:43  relay (tunnel) restarted with the session
#   15:48  FIRST client after 5 quiet minutes: probe OK (matmul + value fetch)
#   15:50  next client (bench subprocess probe): hung -> 100s timeout
#   15:52  next client: hung
#   15:57  next client: hung
# Reading: the backend serves the first client after a quiet window, and a
# client teardown (clean exit OR killed probe) wedges the listener for some
# window T.  Round-3's loop probed every ~7 min and never connected in 5.5h —
# plausibly BECAUSE its own killed probes kept re-arming the wedge.
#
# Discipline:
#   - No throwaway probe connections.  Every attempt IS the bench process
#     (bench.py --direct), connecting in-process under a watchdog (exit 86 on
#     hung connect, SIGKILL backstop if the hang holds the GIL).  A successful
#     connect runs the full two-regime bench and self-records to
#     bench_results/{r4_measured.jsonl,last_measured.json}.
#   - 20 min of TOTAL TPU silence between attempts (nothing else in the
#     session may touch the TPU while this loop runs).
#   - After the first recorded full bench: up to 3 spaced-out --calibration
#     re-runs (append to the jsonl, do NOT clobber last_measured.json) to
#     calibrate connect reliability — can the driver's round-end bench.py
#     expect a live backend? — then permanent silence for the driver capture.
LOG=/root/repo/bench_results/probe_r4.log
BLOG=/root/repo/bench_results/bench_r4_auto.log
JSONL=/root/repo/bench_results/r4_measured.jsonl
cd /root/repo || exit 1
touch "$JSONL"
STAMP=$(date +%s)
success=0
post=0
echo "=== loop r4b(v2) start $(date -u +%H:%M:%S) — initial quiet gap ===" >> "$LOG"
sleep 1200
for i in $(seq 1 30); do
  phase=main; [ "$success" = 1 ] && phase=post
  echo "=== attempt $i phase=$phase $(date -u +%H:%M:%S) ===" >> "$LOG"
  if [ "$success" = 0 ]; then
    timeout 5400 env PYTHONPATH=/root/repo:/root/.axon_site \
      python bench.py --direct >> "$BLOG" 2>&1
  else
    timeout 1800 env PYTHONPATH=/root/repo:/root/.axon_site \
      python bench.py --direct --calibration --regime bf16 --steps 5 --warmup 2 \
      >> "$BLOG" 2>&1
  fi
  rc=$?
  echo "attempt $i rc=$rc at $(date -u +%H:%M:%S)" >> "$LOG"
  if [ "$(stat -c %Y "$JSONL")" -gt "$STAMP" ]; then
    STAMP=$(date +%s)
    if [ "$success" = 0 ]; then
      echo "FULL BENCH RECORDED at $(date -u +%H:%M:%S)" >> "$LOG"
      success=1
    else
      post=$((post + 1))
      echo "post-success connect check $post OK at $(date -u +%H:%M:%S)" >> "$LOG"
      if [ "$post" -ge 3 ]; then
        echo "3 post-success connects OK — going silent for driver capture" >> "$LOG"
        exit 0
      fi
    fi
    sleep 2400
  else
    sleep 1200
  fi
done
echo "=== loop r4b exhausted $(date -u +%H:%M:%S) ===" >> "$LOG"
