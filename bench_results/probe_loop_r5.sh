#!/bin/bash
# Round-5 patient capture loop — connection-discipline model, now with
# failure-mode discrimination (VERDICT r4 item 8).
#
# Evidence going in (probe_r5.log attempt 1, 19:52 UTC): first client of the
# round hung in phase 'plugin-init (PJRT handshake)' — while BOTH local relay
# ports (2024, 48271) accept raw TCP.  So "relay down" is ruled out; the
# listener behind the relay is wedged.  Each attempt here logs the hung phase
# (bench.py logs phase entry) plus before/after TCP state, giving the
# per-attempt evidence round 4 lacked.
#
# Discipline (bench_results/r4_notes.md): every attempt IS the bench process
# (one client, no throwaway probes); quiet gaps between attempts escalate
# 30/30/30/45/60/60… min since round-4's fixed 25-min cadence never landed a
# second connect (T > 25 min or permanent that day).  On the first recorded
# full bench, run the chip-gated queue in VERDICT order — kernel
# revalidation, --probe-deeper, EMA donation probe, flash tile re-sweep at
# depth — one client per quiet window, then go silent for the driver.
LOG=/root/repo/bench_results/probe_r5.log
BLOG=/root/repo/bench_results/bench_r5_auto.log
JSONL=/root/repo/bench_results/r5_measured.jsonl
cd /root/repo || exit 1
touch "$JSONL"
STAMP=$(stat -c %Y "$JSONL")

tcp_state() {
  local s=""
  for p in 2024 48271; do
    if timeout 3 bash -c "echo > /dev/tcp/127.0.0.1/$p" 2>/dev/null; then
      s="$s $p=open"
    else
      s="$s $p=closed"
    fi
  done
  echo "$s"
}

END=$(( $(date +%s) + 34200 ))   # permanent silence 9.5 h from loop start
gaps=(1800 1800 1800 2700 3600 3600)
i=0
echo "=== loop r5 start $(date -u +%H:%M:%S) ===" >> "$LOG"
while [ "$(date +%s)" -lt "$END" ]; do
  g=${gaps[$(( i < 5 ? i : 5 ))]}
  sleep "$g"
  i=$((i + 1))
  echo "=== attempt $i $(date -u +%H:%M:%S) tcp:$(tcp_state) ===" >> "$LOG"
  timeout 5400 env PYTHONPATH=/root/repo:/root/.axon_site \
    python bench.py --direct >> "$BLOG" 2>&1
  rc=$?
  echo "attempt $i rc=$rc at $(date -u +%H:%M:%S) tcp_after:$(tcp_state)" >> "$LOG"
  if [ "$(stat -c %Y "$JSONL")" -gt "$STAMP" ]; then
    STAMP=$(stat -c %Y "$JSONL")
    echo "FULL BENCH RECORDED at $(date -u +%H:%M:%S) — chip-gated queue" >> "$LOG"
    while read -r item; do
      [ -z "$item" ] && continue
      sleep 1500
      echo "--- queue: $item $(date -u +%H:%M:%S) tcp:$(tcp_state)" >> "$LOG"
      timeout 3600 env PYTHONPATH=/root/repo:/root/.axon_site \
        $item >> "$BLOG" 2>&1
      echo "--- queue rc=$? at $(date -u +%H:%M:%S)" >> "$LOG"
    done <<'QUEUE'
python tools/kernel_revalidation.py
python bench.py --probe-deeper
python tools/ema_donation_probe.py
python bench.py --calibration --regime bf16 --steps 6 --warmup 2 --block-kv 1024
python bench.py --calibration --regime bf16 --steps 6 --warmup 2 --block-kv 4096
QUEUE
    echo "queue done at $(date -u +%H:%M:%S) — silent for driver capture" >> "$LOG"
    exit 0
  fi
done
echo "loop expired without a recorded bench at $(date -u +%H:%M:%S)" >> "$LOG"
