#!/usr/bin/env python
"""Checkpoint converter CLI: HF <-> native Orbax checkpoints.

The reference's converter surface (``checkpoint_converter_scripts/
checkpoint_converter.py:1-53``: HF full-state <-> sharded, both directions,
Llama + Mixtral):

    python examples/checkpoint_converter.py \
        --model llama --direction hf2native \
        --config examples/conf/hf_llama3_8B_config.yaml \
        --input /path/to/hf_checkpoint_dir --output /path/to/native_ckpt

native2hf writes a ``model.safetensors`` (or .npz fallback) HF state dict.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _load_nnm_state(path: str, tp: int, pp: int, num_layers: int, glu: bool):
    """Load a NeMo-Megatron checkpoint: either a single state-dict file or the
    rank-sharded ``tp_rank_XX_pp_rank_XXX/model_optim_rng.ckpt`` layout the
    reference converter walks (``nnm_model_ckpt_to_nxdt...py:88-111``)."""
    from neuronx_distributed_training_tpu.tools import convert, convert_megatron

    p = Path(path)
    if p.is_file():
        return convert.load_torch_state_dict(str(p))
    shards = {}
    for r in range(tp):
        for s in range(pp):
            name = (f"tp_rank_{r:02d}_pp_rank_{s:03d}" if pp > 1
                    else f"mp_rank_{r:02d}")
            ck = p / name / "model_optim_rng.ckpt"
            if not ck.exists():
                ck = p / name / "model_weights.ckpt"
            import torch

            sd = torch.load(str(ck), map_location="cpu", weights_only=False)
            sd = sd.get("state_dict", sd)
            shards[(r, s)] = {
                k: v.float().numpy() for k, v in sd.items() if hasattr(v, "numpy")
            }
    return convert_megatron.merge_nnm_shards(
        shards, tp=tp, pp=pp, num_layers=num_layers, glu=glu
    )


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", choices=["llama", "mixtral", "gpt"], default="llama")
    ap.add_argument("--direction",
                    choices=["hf2native", "native2hf", "nnm2native", "native2nnm"],
                    required=True)
    ap.add_argument("--config", required=True, help="YAML config (reference schema)")
    ap.add_argument("--input", required=True)
    ap.add_argument("--output", required=True)
    ap.add_argument("--step", type=int, default=0,
                    help="checkpoint step number to write/read (native side)")
    ap.add_argument("--tp", type=int, default=1,
                    help="TP degree of a sharded NNM checkpoint dir")
    ap.add_argument("--pp", type=int, default=1,
                    help="PP degree of a sharded NNM checkpoint dir")
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # honor the env even when a sitecustomize pre-imported jax (the env
        # var alone is read too early to win; see tests/conftest.py) — layout
        # conversion is host work, CI forces cpu
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import orbax.checkpoint as ocp

    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.models import llama as llama_mod
    from neuronx_distributed_training_tpu.tools import convert

    cfg_yaml = load_config(args.config)
    model_block = dict(cfg_yaml.get("model", {}) or {})
    ds_block = dict(cfg_yaml.get("distributed_strategy", {}) or {})

    if args.direction in ("nnm2native", "native2nnm") or args.model == "gpt":
        from neuronx_distributed_training_tpu.models import gpt as gpt_mod
        from neuronx_distributed_training_tpu.tools import convert_megatron

        cfg = gpt_mod.GPTConfig.from_config(model_block, ds_block)
        to_native = lambda sd: convert_megatron.megatron_gpt_to_native(sd, cfg)
        to_hf = lambda p, layer_layout=None: convert_megatron.native_to_megatron_gpt(
            p, cfg, layer_layout=layer_layout)
    elif args.model == "llama":
        cfg = llama_mod.LlamaConfig.from_config(model_block, ds_block)
        to_native = lambda sd: convert.hf_llama_to_native(sd, cfg)
        to_hf = lambda p, layer_layout=None: convert.native_to_hf_llama(
            p, cfg, layer_layout=layer_layout)
    else:
        from neuronx_distributed_training_tpu.models import mixtral as mixtral_mod

        cfg = mixtral_mod.MixtralConfig.from_config(model_block, ds_block)
        to_native = lambda sd: convert.hf_mixtral_to_native(sd, cfg)
        to_hf = lambda p, layer_layout=None: convert.native_to_hf_mixtral(
            p, cfg, layer_layout=layer_layout)

    out = Path(args.output)
    if args.direction in ("hf2native", "nnm2native"):
        if args.direction == "nnm2native":
            state = _load_nnm_state(
                args.input, args.tp, args.pp,
                num_layers=int(model_block.get("num_layers", 12)),
                glu=str(model_block.get("activation", "gelu")) in
                    ("swiglu", "geglu", "reglu"),
            )
        else:
            state = convert.load_torch_state_dict(args.input)
        params = to_native(state)
        with ocp.CheckpointManager(out.absolute()) as mgr:
            mgr.save(args.step, args=ocp.args.Composite(
                params=ocp.args.StandardSave(params)))
            mgr.wait_until_finished()
        print(f"wrote native checkpoint: {out}/{args.step}/params")
    else:
        with ocp.CheckpointManager(Path(args.input).absolute()) as mgr:
            step = args.step or mgr.latest_step()
            layout = None
            try:
                meta = mgr.restore(step, args=ocp.args.Composite(
                    meta=ocp.args.JsonRestore()))["meta"]
                layout = (meta or {}).get("layer_layout")
            except Exception:
                pass  # metadata-less checkpoint: shape heuristic fallback
            restored = mgr.restore(step, args=ocp.args.Composite(
                params=ocp.args.StandardRestore()))
        sd = to_hf(restored["params"], layer_layout=layout)
        out.mkdir(parents=True, exist_ok=True)
        try:
            from safetensors.numpy import save_file

            save_file(sd, str(out / "model.safetensors"))
            print(f"wrote {out}/model.safetensors ({len(sd)} tensors)")
        except ImportError:
            import numpy as np

            np.savez(out / "model.npz", **sd)
            print(f"wrote {out}/model.npz ({len(sd)} tensors)")


if __name__ == "__main__":
    main()
