#!/usr/bin/env python
"""SFT evaluation CLI: checkpoint + eval records -> ROUGE-L / F1 / EM.

The runnable counterpart of the reference's ``examples/sft_evaluation/
evaluate.py`` (prompt templates, generation knobs, metric factory), driving
the KV-cached decoder:

    python examples/run_sft_evaluation.py \
        --config examples/conf/hf_llama3_8B_SFT_config.yaml \
        --checkpoint /path/to/native_ckpt --step 500 \
        --data /path/to/eval.jsonl --tokenizer /path/to/tok \
        --prompt-template "{input}" --max-new-tokens 256 \
        [--temperature 0.7 --top-p 0.9 --top-k 50]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True)
    ap.add_argument("--checkpoint", required=True, help="native Orbax ckpt dir")
    ap.add_argument("--step", type=int, default=0, help="0 = latest")
    ap.add_argument("--data", required=True, help="jsonl/json/arrow eval records")
    ap.add_argument("--tokenizer", required=True)
    ap.add_argument("--prompt-template", default="{input}")
    ap.add_argument("--target-field", default="output")
    ap.add_argument("--max-new-tokens", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=None)
    ap.add_argument("--top-p", type=float, default=None)
    ap.add_argument("--limit", type=int, default=None)
    args = ap.parse_args()

    import jax
    import numpy as np
    import orbax.checkpoint as ocp
    from transformers import AutoTokenizer

    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.data.modules import load_alignment_records
    from neuronx_distributed_training_tpu.models import decode, generate as gen
    from neuronx_distributed_training_tpu.tools.evaluate import (
        render_prompt,
        score,
    )
    from neuronx_distributed_training_tpu.trainer.loop import build_model
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    cfg = load_config(args.config)
    policy = DtypePolicy.from_precision_config(cfg.get("precision", {}))
    model_cfg, _, _, _ = build_model(cfg, policy)
    tok = AutoTokenizer.from_pretrained(args.tokenizer)
    eos = tok.eos_token_id or 0

    with ocp.CheckpointManager(Path(args.checkpoint).absolute()) as mgr:
        step = args.step or mgr.latest_step()
        params = mgr.restore(step, args=ocp.args.Composite(
            params=ocp.args.StandardRestore()))["params"]

    records = load_alignment_records(args.data)
    if args.limit:
        records = records[: args.limit]

    preds, refs = [], []
    for i in range(0, len(records), args.batch_size):
        batch = records[i:i + args.batch_size]
        prompts = [tok.encode(render_prompt(args.prompt_template, r))
                   for r in batch]
        ids, lens = gen.pad_prompts(prompts, pad_id=eos)
        out = decode.generate_cached(
            params, model_cfg, policy, ids, lens,
            max_new_tokens=args.max_new_tokens, eos_id=eos, pad_id=eos,
            temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
            key=jax.random.PRNGKey(i),
        )
        out = np.asarray(out)
        for b, r in enumerate(batch):
            gen_ids = out[b, int(lens[b]):]
            gen_ids = gen_ids[gen_ids != eos]
            preds.append(tok.decode(gen_ids))
            refs.append(str(r[args.target_field]))
        print(f"generated {min(i + args.batch_size, len(records))}/{len(records)}",
              file=sys.stderr)

    print(json.dumps(score(preds, refs), indent=2))


if __name__ == "__main__":
    main()
