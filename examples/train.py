#!/usr/bin/env python
"""Training launcher — thin wrapper over the packaged CLI.

    python examples/train.py --config examples/conf/hf_llama3_8B_config.yaml

See ``neuronx_distributed_training_tpu/trainer/cli.py`` (== ``nxdt-train``) for
the full surface: dotted ``--set`` overrides, ``--compile-only`` AOT warmup,
``TRAIN_ITERS`` test hook.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from neuronx_distributed_training_tpu.trainer.cli import main

if __name__ == "__main__":
    main()
