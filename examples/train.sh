#!/usr/bin/env bash
# Cluster launch wrapper — the counterpart of the reference's train.sh +
# train_setup.sh pair (reference examples/train.sh, train_setup.sh:8-67),
# redesigned for the TPU stack:
#
#   - NO torchrun / process manager: one python process per HOST (TPU hosts
#     drive all local chips through one process); the in-process rendezvous
#     (utils/launch.detect_cluster -> jax.distributed.initialize) reads the
#     SLURM / Open MPI / NXDT_* environment directly, so this script only
#     selects the config, shapes log paths, and execs python.
#   - COMPILE=1 -> --compile-only (AOT warm-up against the persistent XLA
#     compile cache; the neuron_parallel_compile equivalent).
#   - TRAIN_ITERS=N short-run override passes through to the CLI.
#
# Usage:
#   CONF_FILE=hf_llama3_8B_config ./train.sh [extra --set overrides...]
set -o pipefail
set -e

ulimit -n 65535 2>/dev/null || true

SCRIPT_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
REPO_ROOT="$(dirname "$SCRIPT_DIR")"
export PYTHONPATH="$REPO_ROOT:${PYTHONPATH:-}"

: "${CONF_FILE:=hf_llama3_8B_config}"
CONF_FILE_PATH="$SCRIPT_DIR/conf/${CONF_FILE}.yaml"
if [ ! -f "$CONF_FILE_PATH" ]; then
    echo "Error: YAML file '$CONF_FILE_PATH' not found!" >&2
    exit 1
fi

# Per-restart log dir (reference train_setup.sh:28-29; utils/launch.py
# restart_log_dir applies the same inside the process for exp_manager paths)
if [ -n "${SLURM_JOB_ID:-}" ]; then
    NODEID=${SLURM_NODEID:-0}
    LOG_PATH=logs/$SLURM_JOB_ID/${SLURM_RESTART_COUNT:-0}/$NODEID
elif [ -n "${OMPI_COMM_WORLD_RANK:-}" ]; then
    NODEID=$OMPI_COMM_WORLD_RANK
    LOG_PATH=logs/mpi/${POD_UID:-run}/$NODEID
else
    NODEID=0
    LOG_PATH=logs/local/$(date "+%Y-%m-%d_%H-%M-%S")
fi
mkdir -p "$LOG_PATH"

MAYBE_COMPILE=""
if [ "${COMPILE:-0}" = "1" ]; then
    echo "compile-only run (AOT warm-up of the persistent XLA cache)"
    MAYBE_COMPILE="--compile-only"
fi

exec python "$SCRIPT_DIR/train.py" \
    --config "$CONF_FILE_PATH" \
    $MAYBE_COMPILE \
    "$@" 2>&1 | tee -a "$LOG_PATH/log"
