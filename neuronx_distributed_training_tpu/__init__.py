"""neuronx_distributed_training_tpu — a TPU-native distributed LLM training framework.

A from-scratch JAX/XLA/Pallas re-design of the capability set of
aws-neuron/neuronx-distributed-training (the "reference"): YAML-driven pretraining,
SFT/LoRA and DPO/ORPO alignment for Llama/GPT/Mixtral-class models with
DP/TP/SP/PP/CP/EP parallelism, ZeRO-1 optimizer sharding, flash/ring attention,
mixed-precision regimes, sharded async checkpointing with auto-resume, and
throughput/MFU observability.

Architecture (reference layer map in SURVEY.md §1 → TPU-native):
  - one ``jax.sharding.Mesh`` with axes ``(data, pipe, context, model, expert)``
    replaces the NxD ``parallel_state`` machinery
  - GSPMD NamedSharding + ``shard_map`` collectives replace Neuron RT collectives
  - Pallas kernels replace the NKI flash/ring-attention kernels
  - the XLA persistent compilation cache replaces ``neuron_parallel_compile``
  - an explicit training loop replaces PyTorch-Lightning/NeMo
"""

__version__ = "0.1.0"

from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh  # noqa: F401
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy  # noqa: F401
