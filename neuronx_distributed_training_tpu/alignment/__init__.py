"""Model alignment — SFT / DPO / ORPO / KTO recipes."""

from neuronx_distributed_training_tpu.alignment.losses import (  # noqa: F401
    dpo_loss,
    orpo_loss,
    sequence_logprobs,
)
from neuronx_distributed_training_tpu.alignment.dpo import (  # noqa: F401
    compute_reference_logprobs,
    make_dpo_loss_fn,
)
from neuronx_distributed_training_tpu.alignment.orpo import (  # noqa: F401
    make_orpo_loss_fn,
)
from neuronx_distributed_training_tpu.alignment.kto import (  # noqa: F401
    compute_reference_logprobs_kto,
    make_kto_loss_fn,
)
from neuronx_distributed_training_tpu.alignment.losses import kto_loss  # noqa: F401
