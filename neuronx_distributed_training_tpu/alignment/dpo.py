"""DPO pre-fit reference pass + loss adapter.

The reference's DPO flow (``base_dpo.py:23-66``): before training, run the
frozen policy over the whole train set, compute chosen/rejected reference
log-probs, append them as dataset columns, and rebuild the dataloader
mid-fit (``fit_loop.setup_data(updated_data_source=...)``).  TPU-native: the
pre-fit pass is a jitted eval function mapped over the dataset once; the
"column append" is a plain numpy array carried next to the batches (no
dataloader surgery needed — batches are dicts).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_training_tpu.alignment.losses import dpo_loss, sequence_logprobs

# ForwardLogits: (params, batch[, rng]) -> logits [b, s, vocab], or
# (logits, reg_loss) where reg_loss is the model's auxiliary regularizer
# (MoE router balance) to keep alongside the preference objective
ForwardLogits = Callable[..., Any]


def _call_forward(forward_logits, params, batch, rng=None):
    try:
        out = forward_logits(params, batch, rng)
    except TypeError:  # two-arg legacy forward
        out = forward_logits(params, batch)
    if isinstance(out, tuple):
        return out
    return out, 0.0


def compute_reference_logprobs(
    params: Any,
    batches: Iterable[dict[str, np.ndarray]],
    forward_logits: ForwardLogits,
) -> dict[str, np.ndarray]:
    """Frozen-policy chosen/rejected log-probs over the train set.

    ``batches`` yield DPO-shaped dicts with ``chosen_input_ids``,
    ``chosen_loss_mask``, ``rejected_input_ids``, ``rejected_loss_mask``
    (the PaddedDPODataset key layout, reference ``PaddedDataset.py:60-103``).
    Returns the two reference-logp columns, concatenated in dataset order.
    """
    parts = list(iter_reference_logprobs(params, batches, forward_logits))
    return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}


def iter_reference_logprobs(
    params: Any,
    batches: Iterable[dict[str, np.ndarray]],
    forward_logits: ForwardLogits,
):
    """Streaming variant of ``compute_reference_logprobs``: yields the column
    dict per batch so the caller can log progress and spill incrementally
    (one jit compile shared across batches)."""

    @jax.jit
    def one(params, batch):
        out = {}
        for side in ("chosen", "rejected"):
            logits, _reg = _call_forward(
                forward_logits, params, {"input_ids": batch[f"{side}_input_ids"]}
            )
            out[side] = sequence_logprobs(
                logits, batch[f"{side}_input_ids"], batch.get(f"{side}_loss_mask")
            )
        return out

    for batch in batches:
        res = one(params, batch)
        yield {
            "reference_chosen_logps": np.asarray(res["chosen"]),
            "reference_rejected_logps": np.asarray(res["rejected"]),
        }


def preference_pipeline_hooks(embed_fn, stage_fn, head_fn, *, mode: str = "dpo",
                              beta: float = 0.1):
    """Wrap a model's pipeline hooks for DPO/ORPO under pipeline parallelism.

    The reference runs preference losses through NxDPPModel via the
    "concatenated forward" (``base_dpo.py:68-88`` stacks chosen+rejected into
    one batch so the pipelined model runs once).  Same trick here: the embed
    hook concatenates ``chosen_input_ids``/``rejected_input_ids`` along batch,
    the stages run the doubled microbatch, and the loss hook splits the final
    hidden states to compute per-sequence log-probs and the preference loss.
    ``head_fn(params, hidden) -> logits`` is the model's final-norm + lm-head.

    Returns hooks with the standard ``(loss_sum, denom)`` contract
    (pair-count-weighted so microbatch accumulation averages over pairs).
    """
    from neuronx_distributed_training_tpu.alignment.losses import (
        dpo_loss,
        orpo_loss,
    )

    def cat(mb):
        ids = jnp.concatenate(
            [mb["chosen_input_ids"], mb["rejected_input_ids"]], axis=0
        )
        out = {"input_ids": ids}
        for k in ("_rng", "_chunk"):
            if k in mb:
                out[k] = mb[k]
        return out

    def embed2(params, mb):
        return embed_fn(params, cat(mb))

    def stage2(local_layers, x, mb):
        return stage_fn(local_layers, x, cat(mb))

    def loss2(params, y, mb):
        logits = head_fn(params, y)
        b = mb["chosen_input_ids"].shape[0]
        avg = mode == "orpo"
        pc = sequence_logprobs(
            logits[:b], mb["chosen_input_ids"], mb.get("chosen_loss_mask"),
            average=avg,
        )
        pr = sequence_logprobs(
            logits[b:], mb["rejected_input_ids"], mb.get("rejected_loss_mask"),
            average=avg,
        )
        if mode == "dpo":
            loss, _ = dpo_loss(
                pc, pr,
                mb["reference_chosen_logps"], mb["reference_rejected_logps"],
                beta=beta,
            )
        else:
            loss, _ = orpo_loss(pc, pr, -jnp.mean(pc), beta=beta)
        return loss * b, jnp.asarray(b, jnp.float32)

    return embed2, stage2, loss2


def make_dpo_loss_fn(forward_logits: ForwardLogits, *, beta: float = 0.1):
    """Build a trainer-compatible loss_fn for DPO batches.

    Batch contract: ``chosen_input_ids``/``rejected_input_ids`` (+ loss masks)
    plus the precomputed ``reference_chosen_logps``/``reference_rejected_logps``
    columns from ``compute_reference_logprobs``.
    """

    def loss_fn(params, batch, key):
        kc = kr = None
        if key is not None:
            kc, kr = jax.random.split(key)
        lc, reg_c = _call_forward(
            forward_logits, params,
            {"input_ids": batch["chosen_input_ids"]}, kc)
        pc = sequence_logprobs(
            lc, batch["chosen_input_ids"], batch.get("chosen_loss_mask"),
        )
        lr, reg_r = _call_forward(
            forward_logits, params,
            {"input_ids": batch["rejected_input_ids"]}, kr)
        pr = sequence_logprobs(
            lr, batch["rejected_input_ids"], batch.get("rejected_loss_mask"),
        )
        loss, metrics = dpo_loss(
            pc, pr,
            batch["reference_chosen_logps"], batch["reference_rejected_logps"],
            beta=beta,
        )
        reg = 0.5 * (reg_c + reg_r)  # MoE router balance rides along
        return loss + reg, metrics

    return loss_fn
