"""KTO: unpaired preference alignment (arXiv:2402.01306).

Not in the reference (its alignment surface is SFT/DPO/ORPO,
``model_alignment_data_module.py:123-146``) — a TPU-native extension using
the same machinery as DPO: a frozen-policy reference pass before training
(``base_dpo.py:23-66`` pattern) and per-sequence completion log-probs from
the vocab-parallel helper.

Batch contract (``KTODataModule``): ``input_ids`` (prompt+completion),
``loss_mask`` (1 on completion tokens), ``kto_labels`` ([b], 1 desirable /
0 undesirable) plus the precomputed ``reference_logps`` column.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_training_tpu.alignment.dpo import _call_forward
from neuronx_distributed_training_tpu.alignment.losses import (
    kto_loss,
    sequence_logprobs,
)

ForwardLogits = Callable[..., Any]


def compute_reference_logprobs_kto(
    params: Any,
    batches: Iterable[dict[str, np.ndarray]],
    forward_logits: ForwardLogits,
) -> dict[str, np.ndarray]:
    """Frozen-policy completion log-probs over the train set -> one column."""
    parts = list(iter_reference_logprobs_kto(params, batches, forward_logits))
    return {"reference_logps": np.concatenate([p["reference_logps"] for p in parts])}


def iter_reference_logprobs_kto(
    params: Any,
    batches: Iterable[dict[str, np.ndarray]],
    forward_logits: ForwardLogits,
):
    """Streaming variant of ``compute_reference_logprobs_kto`` (per-batch
    yield; one shared jit)."""

    @jax.jit
    def one(params, batch):
        logits, _reg = _call_forward(
            forward_logits, params, {"input_ids": batch["input_ids"]}
        )
        return sequence_logprobs(
            logits, batch["input_ids"], batch.get("loss_mask")
        )

    for batch in batches:
        yield {"reference_logps": np.asarray(one(params, batch))}


def make_kto_loss_fn(
    forward_logits: ForwardLogits,
    *,
    beta: float = 0.1,
    desirable_weight: float = 1.0,
    undesirable_weight: float = 1.0,
):
    """Trainer-compatible loss_fn for KTO batches."""

    def loss_fn(params, batch, key):
        logits, reg = _call_forward(
            forward_logits, params, {"input_ids": batch["input_ids"]}, key
        )
        logps = sequence_logprobs(
            logits, batch["input_ids"], batch.get("loss_mask")
        )
        loss, metrics = kto_loss(
            logps, batch["reference_logps"], batch["kto_labels"],
            beta=beta, desirable_weight=desirable_weight,
            undesirable_weight=undesirable_weight,
        )
        return loss + reg, metrics

    return loss_fn


def kto_pipeline_hooks(embed_fn, stage_fn, head_fn, *, beta: float = 0.1,
                       desirable_weight: float = 1.0,
                       undesirable_weight: float = 1.0):
    """Wrap a model's pipeline hooks for KTO under pipeline parallelism.

    Unlike DPO/ORPO there is no chosen/rejected concatenation — KTO batches
    are single sequences — so the embed/stage hooks pass through untouched
    and only the loss hook changes: per-sequence completion log-probs from
    the final hidden states, then the KTO objective against the precomputed
    ``reference_logps`` column.  Returns the standard ``(loss_sum, denom)``
    contract (example-count weighted so microbatch accumulation averages
    over examples; the batch-mean KL baseline is per-MICRObatch, a finer
    estimate than the global batch — same detached-baseline semantics).
    """

    def loss2(params, y, mb):
        logits = head_fn(params, y)
        logps = sequence_logprobs(
            logits, mb["input_ids"], mb.get("loss_mask")
        )
        loss, _metrics = kto_loss(
            logps, mb["reference_logps"], mb["kto_labels"],
            beta=beta, desirable_weight=desirable_weight,
            undesirable_weight=undesirable_weight,
        )
        b = mb["input_ids"].shape[0]
        return loss * b, jnp.asarray(b, jnp.float32)

    return embed_fn, stage_fn, loss2
