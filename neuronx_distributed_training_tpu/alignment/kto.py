"""KTO: unpaired preference alignment (arXiv:2402.01306).

Not in the reference (its alignment surface is SFT/DPO/ORPO,
``model_alignment_data_module.py:123-146``) — a TPU-native extension using
the same machinery as DPO: a frozen-policy reference pass before training
(``base_dpo.py:23-66`` pattern) and per-sequence completion log-probs from
the vocab-parallel helper.

Batch contract (``KTODataModule``): ``input_ids`` (prompt+completion),
``loss_mask`` (1 on completion tokens), ``kto_labels`` ([b], 1 desirable /
0 undesirable) plus the precomputed ``reference_logps`` column.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_training_tpu.alignment.dpo import _call_forward
from neuronx_distributed_training_tpu.alignment.losses import (
    kto_loss,
    sequence_logprobs,
)

ForwardLogits = Callable[..., Any]


def compute_reference_logprobs_kto(
    params: Any,
    batches: Iterable[dict[str, np.ndarray]],
    forward_logits: ForwardLogits,
) -> dict[str, np.ndarray]:
    """Frozen-policy completion log-probs over the train set (plus the
    mismatched-KL column when the batches carry ``kl_input_ids``)."""
    parts = list(iter_reference_logprobs_kto(params, batches, forward_logits))
    return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}


def iter_reference_logprobs_kto(
    params: Any,
    batches: Iterable[dict[str, np.ndarray]],
    forward_logits: ForwardLogits,
):
    """Streaming variant of ``compute_reference_logprobs_kto`` (per-batch
    yield; one shared jit).  Batches carrying ``kl_input_ids`` (the
    mismatched-pair KL estimator) also get a ``reference_kl_logps`` column
    from the same frozen policy."""

    @jax.jit
    def one(params, batch):
        out = {}
        logits, _reg = _call_forward(
            forward_logits, params, {"input_ids": batch["input_ids"]}
        )
        out["reference_logps"] = sequence_logprobs(
            logits, batch["input_ids"], batch.get("loss_mask")
        )
        if "kl_input_ids" in batch:
            kl_logits, _ = _call_forward(
                forward_logits, params, {"input_ids": batch["kl_input_ids"]}
            )
            out["reference_kl_logps"] = sequence_logprobs(
                kl_logits, batch["kl_input_ids"], batch.get("kl_loss_mask")
            )
        return out

    for batch in batches:
        yield {k: np.asarray(v) for k, v in one(params, batch).items()}


def make_kto_loss_fn(
    forward_logits: ForwardLogits,
    *,
    beta: float = 0.1,
    desirable_weight: float = 1.0,
    undesirable_weight: float = 1.0,
    kl_estimator: str = "batch_mean",
):
    """Trainer-compatible loss_fn for KTO batches.

    ``kl_estimator="mismatched"`` runs a second forward over the batch's
    ``kl_input_ids`` (prompt_i + completion_{i+1}, built by ``KTODataModule``)
    and uses those rewards as the paper's off-policy z0 baseline (the
    gradient does not flow through z0, so the extra forward needs no
    backward — jax only differentiates what reaches the loss)."""

    def loss_fn(params, batch, key):
        logits, reg = _call_forward(
            forward_logits, params, {"input_ids": batch["input_ids"]}, key
        )
        logps = sequence_logprobs(
            logits, batch["input_ids"], batch.get("loss_mask")
        )
        kl_rewards = None
        if kl_estimator == "mismatched":
            if "kl_input_ids" not in batch:
                raise KeyError(
                    "kl_estimator=mismatched needs kl_input_ids batches — "
                    "build the data module with kl_estimator='mismatched'"
                )
            kl_logits, _ = _call_forward(
                forward_logits, params,
                {"input_ids": batch["kl_input_ids"]}, key,
            )
            kl_logps = sequence_logprobs(
                kl_logits, batch["kl_input_ids"], batch.get("kl_loss_mask")
            )
            kl_rewards = jax.lax.stop_gradient(
                beta * (kl_logps - batch["reference_kl_logps"])
            )
        loss, metrics = kto_loss(
            logps, batch["reference_logps"], batch["kto_labels"],
            beta=beta, desirable_weight=desirable_weight,
            undesirable_weight=undesirable_weight, kl_rewards=kl_rewards,
        )
        return loss + reg, metrics

    return loss_fn


def kto_pipeline_hooks(embed_fn, stage_fn, head_fn, *, beta: float = 0.1,
                       desirable_weight: float = 1.0,
                       undesirable_weight: float = 1.0):
    """Wrap a model's pipeline hooks for KTO under pipeline parallelism.

    Unlike DPO/ORPO there is no chosen/rejected concatenation — KTO batches
    are single sequences — so the embed/stage hooks pass through untouched
    and only the loss hook changes: per-sequence completion log-probs from
    the final hidden states, then the KTO objective against the precomputed
    ``reference_logps`` column.  Returns the standard ``(loss_sum, denom)``
    contract (example-count weighted so microbatch accumulation averages
    over examples; the batch-mean KL baseline is per-MICRObatch, a finer
    estimate than the global batch — same detached-baseline semantics).
    """

    def loss2(params, y, mb):
        logits = head_fn(params, y)
        logps = sequence_logprobs(
            logits, mb["input_ids"], mb.get("loss_mask")
        )
        loss, _metrics = kto_loss(
            logps, mb["reference_logps"], mb["kto_labels"],
            beta=beta, desirable_weight=desirable_weight,
            undesirable_weight=undesirable_weight,
        )
        b = mb["input_ids"].shape[0]
        return loss * b, jnp.asarray(b, jnp.float32)

    return embed_fn, stage_fn, loss2
