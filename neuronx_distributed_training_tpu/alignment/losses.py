"""DPO / ORPO losses.

Re-derivations of the reference's alignment losses:
- DPO: ``-logsigmoid(beta * (pi_logratios - ref_logratios))`` plus
  chosen/rejected reward metrics (reference ``base_dpo.py:90-109``);
- ORPO: NLL on the chosen response (length-averaged logps) + the odds-ratio
  term, no reference model (reference ``base_orpo.py:26-46``).

Both consume per-sequence log-probs from ``sequence_logprobs`` — the
vocab-parallel ``from_parallel_logits_to_logprobs`` analogue
(``ops.cross_entropy.logprobs_from_logits`` partitions over sharded vocab).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from neuronx_distributed_training_tpu.ops.cross_entropy import logprobs_from_logits


def sequence_logprobs(
    logits: jax.Array,  # [b, s, vocab] (vocab may be TP-sharded)
    labels: jax.Array,  # [b, s]
    loss_mask: Optional[jax.Array] = None,  # [b, s]; 1 on response tokens
    *,
    shift: bool = True,
    average: bool = False,
) -> jax.Array:
    """Per-sequence sum (or mean) log p(label) over response tokens -> [b]."""
    if shift:
        logits = logits[:, :-1]
        labels = labels[:, 1:]
        loss_mask = None if loss_mask is None else loss_mask[:, 1:]
    per_tok = logprobs_from_logits(logits, jnp.maximum(labels, 0))
    mask = (labels >= 0).astype(jnp.float32)
    if loss_mask is not None:
        mask = mask * loss_mask.astype(jnp.float32)
    total = jnp.sum(per_tok * mask, axis=-1)
    if average:
        return total / jnp.maximum(jnp.sum(mask, axis=-1), 1.0)
    return total


def dpo_loss(
    policy_chosen_logps: jax.Array,  # [b]
    policy_rejected_logps: jax.Array,
    reference_chosen_logps: jax.Array,
    reference_rejected_logps: jax.Array,
    *,
    beta: float = 0.1,
    label_smoothing: float = 0.0,
):
    """DPO sigmoid loss + reward metrics (reference ``base_dpo.py:90-109``)."""
    pi_logratios = policy_chosen_logps - policy_rejected_logps
    ref_logratios = reference_chosen_logps - reference_rejected_logps
    logits = pi_logratios - ref_logratios
    loss = (
        -jax.nn.log_sigmoid(beta * logits) * (1 - label_smoothing)
        - jax.nn.log_sigmoid(-beta * logits) * label_smoothing
    )
    chosen_rewards = beta * (policy_chosen_logps - reference_chosen_logps)
    rejected_rewards = beta * (policy_rejected_logps - reference_rejected_logps)
    metrics = {
        "rewards_chosen": jnp.mean(chosen_rewards),
        "rewards_rejected": jnp.mean(rejected_rewards),
        "reward_accuracy": jnp.mean((chosen_rewards > rejected_rewards).astype(jnp.float32)),
        "reward_margin": jnp.mean(chosen_rewards - rejected_rewards),
    }
    return jnp.mean(loss), metrics


def orpo_loss(
    chosen_avg_logps: jax.Array,  # [b] length-AVERAGED log p (reference base_orpo.py)
    rejected_avg_logps: jax.Array,
    chosen_nll: jax.Array,  # scalar NLL over chosen responses
    *,
    beta: float = 0.1,
):
    """ORPO: NLL(chosen) + beta * odds-ratio term (reference ``base_orpo.py:26-46``)."""
    # log odds ratio: log( odds(chosen) / odds(rejected) ),
    # odds(p) = p / (1 - p) computed in log space for stability
    log_odds = (chosen_avg_logps - rejected_avg_logps) - (
        jnp.log1p(-jnp.exp(jnp.clip(chosen_avg_logps, max=-1e-6)))
        - jnp.log1p(-jnp.exp(jnp.clip(rejected_avg_logps, max=-1e-6)))
    )
    ratio_term = -jax.nn.log_sigmoid(log_odds)
    loss = chosen_nll + beta * jnp.mean(ratio_term)
    metrics = {
        "orpo_nll": chosen_nll,
        "orpo_log_odds": jnp.mean(log_odds),
        "orpo_ratio": jnp.mean(ratio_term),
    }
    return loss, metrics


def kto_loss(
    policy_logps: jax.Array,  # [b] per-sequence completion log-probs
    reference_logps: jax.Array,  # [b] frozen-policy logps (pre-fit pass)
    labels: jax.Array,  # [b] 1.0 = desirable, 0.0 = undesirable
    *,
    beta: float = 0.1,
    desirable_weight: float = 1.0,
    undesirable_weight: float = 1.0,
    kl_rewards: jax.Array | None = None,  # [b] mismatched-pair rewards -> z0
):
    """KTO (Kahneman-Tversky Optimization, arXiv:2402.01306) for UNPAIRED
    preference data — an extension beyond the reference's DPO/ORPO pair-only
    surface.

    Per-example reward ``r = beta * (logp_policy - logp_ref)``; the KL
    baseline ``z0`` is the batch-mean reward clamped at 0 and detached.
    Desirable examples maximize ``sigmoid(r - z0)``, undesirable minimize via
    ``sigmoid(z0 - r)``, with the lambda_D/lambda_U class weights for
    imbalanced feedback.

    .. note:: **Two z0 estimators.**  With ``kl_rewards=None`` (the default
       ``kl_estimator: batch_mean``), ``z0`` is the batch-mean reward of the
       ACTUAL completions — cheap (no extra forward) but it deviates from
       arXiv:2402.01306 / TRL: as the policy improves on its own completions
       this baseline (and the ``kto_kl`` metric) grows with the mean reward
       itself instead of staying an off-policy KL estimate.  Pass
       ``kl_rewards`` (rewards of MISMATCHED (prompt_i, completion_j) pairs;
       ``kl_estimator: mismatched`` wires it, at the cost of a second
       forward per step) for the paper's estimator.
    """
    r = beta * (policy_logps - reference_logps)
    z0_src = r if kl_rewards is None else kl_rewards
    z0 = jax.lax.stop_gradient(jnp.maximum(jnp.mean(z0_src), 0.0))
    des = labels > 0.5
    value = jnp.where(des, jax.nn.sigmoid(r - z0), jax.nn.sigmoid(z0 - r))
    w = jnp.where(des, desirable_weight, undesirable_weight)
    loss = jnp.mean(w * (1.0 - value))
    n_des = jnp.maximum(jnp.sum(des.astype(jnp.float32)), 1.0)
    n_und = jnp.maximum(jnp.sum((~des).astype(jnp.float32)), 1.0)
    metrics = {
        "kto_kl": z0,
        "rewards_desirable": jnp.sum(jnp.where(des, r, 0.0)) / n_des,
        "rewards_undesirable": jnp.sum(jnp.where(des, 0.0, r)) / n_und,
    }
    return loss, metrics
