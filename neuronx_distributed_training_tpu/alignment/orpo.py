"""ORPO loss adapter — reference-model-free preference optimization.

The reference's ORPO recipe (``base_orpo.py:26-46``) reuses the DPO
concatenated-forward machinery but needs NO frozen reference policy: the loss
is ``NLL(chosen) + beta * (-logsigmoid(log_odds))`` where the log-odds ratio
is computed from length-AVERAGED policy log-probs alone.  That makes the
trainer wiring strictly simpler than DPO — no pre-fit pass, no sidecar
columns — and it consumes the same DPO-shaped batches
(``chosen_input_ids``/``rejected_input_ids`` + loss masks).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from neuronx_distributed_training_tpu.alignment.dpo import ForwardLogits
from neuronx_distributed_training_tpu.alignment.losses import (
    orpo_loss,
    sequence_logprobs,
)


def make_orpo_loss_fn(forward_logits: ForwardLogits, *, beta: float = 0.1):
    """Build a trainer-compatible loss_fn for ORPO batches.

    Batch contract: ``chosen_input_ids``/``rejected_input_ids`` (+ optional
    ``*_loss_mask``).  Unlike DPO there are no reference columns.
    """

    def loss_fn(params, batch, key):
        from neuronx_distributed_training_tpu.alignment.dpo import _call_forward

        kc = kr = None
        if key is not None:
            kc, kr = jax.random.split(key)
        lc, reg_c = _call_forward(
            forward_logits, params,
            {"input_ids": batch["chosen_input_ids"]}, kc)
        pc = sequence_logprobs(
            lc, batch["chosen_input_ids"], batch.get("chosen_loss_mask"),
            average=True,
        )
        lr, reg_r = _call_forward(
            forward_logits, params,
            {"input_ids": batch["rejected_input_ids"]}, kr)
        pr = sequence_logprobs(
            lr, batch["rejected_input_ids"], batch.get("rejected_loss_mask"),
            average=True,
        )
        # reference base_orpo.py:33 — the chosen NLL term is the mean of the
        # length-averaged chosen log-probs, negated
        nll = -jnp.mean(pc)
        loss, metrics = orpo_loss(pc, pr, nll, beta=beta)
        metrics["rewards_chosen"] = beta * jnp.mean(pc)
        metrics["rewards_rejected"] = beta * jnp.mean(pr)
        reg = 0.5 * (reg_c + reg_r)  # MoE router balance rides along
        return loss + reg, metrics

    return loss_fn
