"""Pre-flight static auditor — config→HLO contract checks + a JAX source lint.

NxDT's promise is that a YAML config reliably becomes a correctly-parallelized
training job.  On TPU the failure mode is silent: a mis-specified
PartitionSpec, a lost buffer donation, or a stray host sync costs memory and
step time without ever erroring.  Both halves of that promise are *statically
checkable* before a device-hour is spent (DeepCompile, arXiv:2504.09983;
GShard, arXiv:2004.13336):

- ``graph_audit`` AOT-lowers the train step for any config on abstract inputs
  (zero arrays materialized, no data files opened — it builds on
  ``trainer.loop.assemble_step_program``) and checks the compiled artifact
  against the config's declared contracts: donation actually aliased, the
  collective census the parallelism config implies, no oversized replicated
  intermediates, no f32 matmuls under bf16 regimes;
- ``graph_contract`` is the *relative* layer: a committed golden fingerprint
  per example config (``contracts/`` — collective census by kind×axis-group
  with per-collective provenance, donation map, matmul dtype census, memory
  bytes) and a semantic differ that explains any regression in config-level
  terms; growth must be declared in-file (``tools/graph_contract.py
  --update-contracts --justify``);
- ``jaxlint`` is an AST pass over the package flagging JAX pitfalls in jitted
  paths (hidden host syncs, tracer branching, wall-clock reads, PRNG key
  reuse, donated-buffer reuse, explicit f32 upcasts) with
  ``# jaxlint: disable=RULE`` suppressions and a committed ratchet baseline;
- ``tools/preflight_audit.py`` is the CLI gate over all of it.

Rule catalogue: ``docs/static_analysis.md``.
"""

from neuronx_distributed_training_tpu.analysis.report import (
    SEVERITIES,
    AuditReport,
    Finding,
)

__all__ = ["AuditReport", "Finding", "SEVERITIES"]
