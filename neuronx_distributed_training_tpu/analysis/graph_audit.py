"""Layer 1 of the pre-flight auditor: config→HLO contract checks.

``audit_config`` AOT-lowers the train step a YAML config describes — on
abstract inputs, with zero arrays materialized and no data files opened
(``trainer.loop.assemble_step_program(build_data=False)``) — and checks the
compiled artifact against the contracts the config declares:

- **GA001 donation**: every param/opt-state leaf the step donates must
  actually be aliased input→output in the compiled executable.  A "donated
  but copied" leaf silently doubles its resident bytes.
- **GA101/GA102 collective census**: the communication pattern GSPMD inserted
  must match the parallelism config — dp-only without ZeRO-1 has no business
  all-gathering anything; tp>1 without model-axis communication means the
  model silently replicated; dp>1 with no reduction means gradients never
  meet.
- **GA201 replication**: no intermediate tensor above an analytically derived
  per-device size budget (a replicated [b, s, vocab] logits block where a
  sharded one was intended is the classic silent OOM).
- **GA301 precision**: no f32×f32 matmuls in the traced program under a bf16
  compute regime (audited on the StableHLO, where dtypes are the program's
  own — backends may legitimately upcast later).

Each finding carries a rule ID, the offending HLO op, and a config-level
remediation hint (``docs/static_analysis.md`` is the catalogue).  Large
configs audit through ``shrink_overrides`` — dimensions shrink, parallel
degrees clamp to 2, but the *structure* (which axes exist, what is donated,
which collectives appear, which dtypes flow) is preserved.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import math
import re
from pathlib import Path
from typing import Any, Mapping, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.analysis.report import AuditReport

logger = logging.getLogger(__name__)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: HLO shape token: dtype[dims] — layout suffix excluded
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_ALIAS_PAIR_RE = re.compile(r"\{([0-9 ,]*)\}:\s*\(([0-9]+),")


# --------------------------------------------------------------------------
# abstract inputs
# --------------------------------------------------------------------------


def leaf_paths(tree: Any) -> list[str]:
    """Flatten-order leaf paths of a pytree — the names donation findings
    cite (flatten order matches XLA entry-parameter order for the leading
    donated arguments)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return ["/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            for path, _ in flat]


def abstract_batch(asm: Any) -> dict[str, jax.ShapeDtypeStruct]:
    """The train step's batch as ShapeDtypeStructs, keyed by what the
    config's loss actually reads (pretrain/SFT vs preference alignment)."""
    cfg = asm.cfg
    gbs = int(asm.sched["global_batch_size"])
    seq = int((cfg.get("data", {}) or {}).get("seq_length")
              or getattr(asm.model_cfg, "max_position_embeddings", 0)
              or getattr(getattr(asm.model_cfg, "llama", None),
                         "max_position_embeddings", 0)
              or 2048)
    ids = jax.ShapeDtypeStruct((gbs, seq), jnp.int32)
    scalar = jax.ShapeDtypeStruct((gbs,), jnp.float32)
    if asm.alignment in ("dpo", "orpo"):
        batch = {"chosen_input_ids": ids, "rejected_input_ids": ids}
        if asm.alignment == "dpo":
            batch["reference_chosen_logps"] = scalar
            batch["reference_rejected_logps"] = scalar
        return batch
    if asm.alignment == "kto":
        batch = {
            "input_ids": ids,
            "kto_labels": jax.ShapeDtypeStruct((gbs,), jnp.int32),
            "reference_logps": scalar,
        }
        if str(asm.align_params.get("kl_estimator", "batch_mean")) == "mismatched":
            batch["kl_input_ids"] = ids
            batch["reference_kl_logps"] = scalar
        return batch
    return {"input_ids": ids, "labels": ids}


def abstract_opt_state(asm: Any) -> Any:
    """Abstract optimizer state tree via ``eval_shape`` over the same
    ``init_opt_state`` the trainer materializes with."""
    from neuronx_distributed_training_tpu.optim.adamw import init_opt_state

    return jax.eval_shape(
        functools.partial(
            init_opt_state, policy=asm.policy, ema=asm.ema_cfg is not None,
            health=asm.health_cfg.enabled,
            tensorstats=getattr(asm, "tensorstats_cfg", None),
            tensorstats_bucket_groups=tuple(
                getattr(asm, "tensorstats_bucket_groups", ())),
        ),
        asm.abstract_params,
    )


def lower_step_program(asm: Any):
    """AOT lower + compile the assembled step on abstract inputs, inside the
    mesh context (outside it every ``shd.constrain`` in the traced program
    silently no-ops — the graph would not be the one training runs).

    Returns ``(stablehlo_text, compiled)``."""
    from neuronx_distributed_training_tpu.parallel import sharding as shd

    batch = abstract_batch(asm)
    opt = abstract_opt_state(asm)
    key = jax.random.PRNGKey(0)
    with asm.mesh, shd.use_mesh(asm.mesh):
        assert shd.active_mesh() is asm.mesh
        lowered = asm.jstep.lower(asm.abstract_params, opt, batch, key)
        compiled = lowered.compile()
    try:
        stablehlo = lowered.as_text()
    except Exception as e:  # noqa: BLE001 — dtype rule degrades, audit proceeds
        logger.warning("stablehlo text unavailable: %s", e)
        stablehlo = ""
    return stablehlo, compiled


# --------------------------------------------------------------------------
# the audit context: what the rules need, buildable from a StepProgram OR a
# live Trainer (the in-loop census audit)
# --------------------------------------------------------------------------


@dataclasses.dataclass
class AuditContext:
    cfg: Any                 # the (possibly shrunk) ConfigDict
    mesh: Any
    policy: Any              # DtypePolicy
    model_cfg: Any
    sched: Mapping[str, int]
    donate: Any              # True/"all" | "params" | False
    params_tree: Any         # abstract or real pytree (shapes/paths only)
    opt_tree: Any
    pspecs: Any = None
    ospecs: Any = None

    @classmethod
    def from_step_program(cls, asm: Any) -> "AuditContext":
        return cls(
            cfg=asm.cfg, mesh=asm.mesh, policy=asm.policy,
            model_cfg=asm.model_cfg, sched=asm.sched, donate=asm.donate,
            params_tree=asm.abstract_params, opt_tree=abstract_opt_state(asm),
            pspecs=asm.pspecs, ospecs=asm.ospecs,
        )

    @property
    def ds(self) -> dict:
        return dict(self.cfg.get("distributed_strategy", {}) or {})

    @property
    def fusions(self) -> dict:
        return dict((self.cfg.get("model", {}) or {}).get("fusions", {}) or {})

    def axis(self, name: str) -> int:
        return int(self.mesh.shape.get(name, 1))


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------


def parse_alias_map(hlo_text: str) -> dict[int, int]:
    """``input_output_alias={ {3}: (17, {}, may-alias), ... }`` ->
    ``{output_flat_index: entry_param_number}``.  Nested output indices
    (``{1, 0}``) use the leading index — donated trees flatten to one level
    in practice.  The map nests braces (``{}`` param index paths), so the
    span is found by depth scan, not regex."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return {}
    i = hlo_text.index("{", start)
    depth, j = 0, i
    for j in range(i, min(len(hlo_text), i + 1_000_000)):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
    body = hlo_text[i + 1: j]
    out: dict[int, int] = {}
    for om, pm in _ALIAS_PAIR_RE.findall(body):
        idx = [int(x) for x in om.replace(",", " ").split()]
        out[idx[0] if idx else 0] = int(pm)
    return out


def donation_map(ctx: AuditContext, hlo_texts: list[str]) -> dict[str, Any]:
    """Donation coverage accounting — the ONE implementation both GA001
    (``audit_donation``) and the graph-contract fingerprint (GC301,
    ``analysis.graph_contract``) read, so the absolute rule and the ratchet
    can never disagree about which leaves are donated or aliased.

    ``{"expected", "aliased", "coverage", "missing": [leaf paths]}`` —
    flatten order matches XLA entry-parameter order for the leading donated
    arguments."""
    donate = ctx.donate
    if donate in (False, "none", ()):
        return {"expected": 0, "aliased": 0, "coverage": 0.0, "missing": []}
    trees = [("params", ctx.params_tree)]
    if donate in (True, "all"):
        trees.append(("opt_state", ctx.opt_tree))
    paths: list[str] = []
    for name, tree in trees:
        paths.extend(f"{name}/{p}" for p in leaf_paths(tree))
    aliased: set[int] = set()
    for text in hlo_texts:
        aliased |= set(parse_alias_map(text).values())
    missing = [paths[i] for i in range(len(paths)) if i not in aliased]
    return {
        "expected": len(paths),
        "aliased": len(paths) - len(missing),
        "coverage": round(1.0 - len(missing) / max(len(paths), 1), 4),
        "missing": missing,
    }


def audit_donation(report: AuditReport, ctx: AuditContext,
                   hlo_texts: list[str]) -> None:
    """GA001: every donated param/opt leaf must be aliased input→output."""
    dm = donation_map(ctx, hlo_texts)
    if ctx.donate in (False, "none", ()):
        report.stats["donation_coverage"] = 0.0
        return
    report.stats["donated_expected"] = dm["expected"]
    report.stats["donated_aliased"] = dm["aliased"]
    report.stats["donation_coverage"] = dm["coverage"]
    for path in dm["missing"]:
        report.add(
            "GA001", "error",
            f"donated leaf {path}: its buffer is not reused by any "
            f"output in the compiled executable (donated-but-copied — the "
            f"bytes are resident twice)",
            location=f"donated leaf {path}",
            hint="a dtype/layout change between the input leaf and its "
                 "updated output defeats aliasing; keep the update "
                 "dtype-preserving (check DtypePolicy casts and optimizer "
                 "state dtypes)",
        )


def audit_collectives(report: AuditReport, ctx: AuditContext,
                      hlo_texts: list[str]) -> None:
    """GA101 (unexpected kind) / GA102 (missing kind) vs the parallelism
    config.  Count-level: the rules reason about which collective KINDS the
    config can explain, not their exact multiplicity."""
    from neuronx_distributed_training_tpu.utils.debug import (
        collective_counts_from_texts,
    )

    counts = collective_counts_from_texts(hlo_texts)
    report.stats["collectives"] = counts
    tp, pp, cp, ep = (ctx.axis("model"), ctx.axis("pipe"),
                      ctx.axis("context"), ctx.axis("expert"))
    dp = ctx.axis("data") * ep
    zero1 = bool(ctx.ds.get("zero1", True))
    seq_par = bool(ctx.ds.get("sequence_parallel", False))
    fus = ctx.fusions
    ulysses = bool(fus.get("ulysses_attention"))
    ring = bool(fus.get("ring_attention") or fus.get("zigzag_ring_attention"))
    moe = bool((ctx.cfg.get("model", {}) or {}).get("moe"))

    # -- unexpected kinds --------------------------------------------------
    # GSPMD legitimately reshards via all-to-all / collective-permute
    # whenever the sequence or expert dim changes owner mid-graph, so these
    # rules only bind in configs with NO sharded non-batch dim at all
    reshardy = (ep > 1 or cp > 1 or seq_par or moe
                or (ulysses and cp > 1))
    # ZeRO-1's shard/regather of updated params lowers partly as
    # collective-permute chains at higher dp degrees
    permutey = reshardy or (zero1 and dp > 1)
    if counts.get("all-to-all", 0) and not reshardy:
        report.add(
            "GA101", "warn",
            f"{counts['all-to-all']} all-to-all op(s) but no expert "
            f"parallelism, sequence/context sharding, or MoE configured to "
            f"explain them",
            location="all-to-all (HLO census)",
            hint="an unexplained all-to-all usually means GSPMD resolved a "
                 "sharding conflict by resharding; check PartitionSpecs at "
                 "the producer/consumer boundary",
        )
    if counts.get("collective-permute", 0) and pp == 1 and not permutey:
        report.add(
            "GA101", "warn",
            f"{counts['collective-permute']} collective-permute op(s) but "
            f"no pipeline stage transfers, ring attention, or "
            f"sequence/expert resharding is configured",
            location="collective-permute (HLO census)",
            hint="halo exchanges appear when a sharded dim is consumed with "
                 "a shifted index; check sequence-dim specs",
        )
    gather_kinds = counts.get("all-gather", 0) + counts.get("reduce-scatter", 0)
    if tp == 1 and cp == 1 and pp == 1 and ep == 1 and not seq_par:
        # dp-only: the only legal communication is gradient reduction —
        # plus the ZeRO-1 shard/regather pair when zero1 is on
        if gather_kinds and not zero1:
            report.add(
                "GA101", "error",
                f"dp-only config (zero1 off) has {counts.get('all-gather', 0)} "
                f"all-gather / {counts.get('reduce-scatter', 0)} "
                f"reduce-scatter op(s): something (likely full params or "
                f"optimizer state) is being regathered every step",
                location="all-gather/reduce-scatter (HLO census)",
                hint="a dp-only step should only all-reduce gradients; an "
                     "all-gather here means a param or activation was left "
                     "sharded/replicated inconsistently across the step "
                     "boundary (check param_specs vs opt_state_specs)",
            )
        if dp == 1 and any(counts.values()):
            report.add(
                "GA101", "warn",
                f"single-device program contains collectives: {counts}",
                location="HLO census",
                hint="collectives on a 1-device mesh are dead weight; check "
                     "for hand-rolled psum/shard_map over size-1 axes",
            )

    # -- missing kinds -----------------------------------------------------
    if tp > 1 and not any(counts.get(k, 0) for k in
                          ("all-reduce", "all-gather", "reduce-scatter")):
        report.add(
            "GA102", "error",
            f"tensor_model_parallel_size={tp} but the step has no model-axis "
            f"communication at all (no all-reduce/all-gather/reduce-scatter): "
            f"the model is either fully replicated or fully disconnected "
            f"across the model axis",
            location="HLO census",
            hint="check that param_specs actually name the 'model' axis and "
                 "that lowering happened inside the mesh context",
        )
    if dp > 1 and not any(counts.get(k, 0) for k in
                          ("all-reduce", "reduce-scatter")):
        report.add(
            "GA102", "error",
            f"data-parallel degree {dp} but no all-reduce or reduce-scatter "
            f"anywhere in the step: gradients are never reduced across "
            f"replicas",
            location="HLO census",
            hint="the loss must be a global mean over the dp-sharded batch; "
                 "check the batch PartitionSpec reaches the loss",
        )
    if dp > 1 and zero1 and not counts.get("all-gather", 0):
        report.add(
            "GA102", "warn",
            f"zero1 with dp={dp} but no all-gather in the step: updated "
            f"params are apparently not regathered from their optimizer "
            f"shards (or ZeRO-1 sharding never happened)",
            location="HLO census",
            hint="opt_state_specs should shard moments over (data, expert); "
                 "verify zero1 made it into opt_state_specs(zero1=...)",
        )
    if pp > 1 and not counts.get("collective-permute", 0):
        report.add(
            "GA102", "warn",
            f"pipeline_model_parallel_size={pp} but no collective-permute: "
            f"no inter-stage transfers were generated",
            location="HLO census",
            hint="the stage loop should shift activations over the 'pipe' "
                 "axis each tick; check the pipeline shard_map specs",
        )
    if seq_par and tp > 1 and not counts.get("all-gather", 0):
        # the reduce half may lower as all-reduce+slice rather than a
        # literal reduce-scatter op (backend-dependent), so only the gather
        # half is a hard expectation
        report.add(
            "GA102", "warn",
            f"sequence_parallel expects a pre-QKV all-gather over the model "
            f"axis; census has all-gather=0 (all-reduce="
            f"{counts.get('all-reduce', 0)})",
            location="HLO census",
            hint="activation specs between blocks should shard the seq dim "
                 "over 'model' (parallel.sharding.act_spec(sequence_parallel"
                 "=True))",
        )
    if moe and ep > 1 and not (counts.get("all-to-all", 0)
                               or counts.get("all-gather", 0)):
        report.add(
            "GA102", "warn",
            f"expert_model_parallel_size={ep} but no all-to-all/all-gather: "
            f"tokens are apparently never exchanged with their experts",
            location="HLO census",
            hint="expert specs should shard the expert dim over 'expert'; "
                 "check moe_param_specs reached the param tree",
        )


def _computation_blocks(hlo_text: str):
    """Yield ``(computation_name, [lines])`` — fusion bodies are separated so
    the replication rule can skip shapes that never materialize."""
    name, lines = "", []
    for line in hlo_text.splitlines():
        if line and not line[0].isspace() and "{" in line:
            if lines:
                yield name, lines
            name, lines = line.split("(", 1)[0].strip(), []
        elif line.strip() == "}":
            if lines:
                yield name, lines
            name, lines = "", []
        else:
            lines.append(line)
    if lines:
        yield name, lines


def _shape_bytes(dtype: str, dims: str) -> int:
    n = math.prod(int(d) for d in dims.split(",") if d) if dims else 1
    return n * _DTYPE_BYTES.get(dtype, 4)


def expected_max_device_bytes(ctx: AuditContext) -> int:
    """Analytic per-device budget: the largest tensor a CORRECTLY sharded
    step should materialize — max over sharded param/opt leaves, the local
    batch shard, and the known activation high-water candidates (ffn block,
    sharded logits, core-attention scores)."""
    mesh = ctx.mesh

    def sharded_leaf_bytes(tree, specs):
        best = 0
        if specs is None:
            return 0
        flat_t = jax.tree_util.tree_leaves(tree)
        flat_s = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P) or x is None)
        for leaf, spec in zip(flat_t, flat_s):
            nbytes = int(math.prod(leaf.shape) * leaf.dtype.itemsize)
            denom = 1
            if isinstance(spec, P):
                for ax in spec:
                    for a in (ax if isinstance(ax, tuple) else (ax,)):
                        if a is not None:
                            denom *= int(mesh.shape.get(a, 1))
            best = max(best, nbytes // max(denom, 1))
        return best

    candidates = [
        sharded_leaf_bytes(ctx.params_tree, ctx.pspecs),
        sharded_leaf_bytes(ctx.opt_tree, ctx.ospecs),
    ]

    mc = ctx.model_cfg
    lc = getattr(mc, "llama", mc)  # mixtral wraps a llama config
    tp, cp = ctx.axis("model"), ctx.axis("context")
    dp = ctx.axis("data") * ctx.axis("expert")
    seq = int((ctx.cfg.get("data", {}) or {}).get("seq_length")
              or getattr(lc, "max_position_embeddings", 2048))
    gbs = int(ctx.sched.get("global_batch_size", 1))
    mbs = int(ctx.sched.get("micro_batch_size", 1))
    b_local = max(gbs // max(dp, 1), mbs)
    # cotangents/accumulators run in grad_accum_dtype (f32 under mixed
    # precision), so activation candidates budget at the wider of the two
    abytes = max(jnp.dtype(ctx.policy.compute_dtype).itemsize,
                 jnp.dtype(getattr(ctx.policy, "grad_accum_dtype",
                                   jnp.float32)).itemsize)
    hidden = int(getattr(lc, "hidden_size", 0) or 0)
    ffn = int(getattr(lc, "intermediate_size", 0)
              or getattr(lc, "ffn_hidden_size", 0) or hidden)
    vocab = int(getattr(lc, "vocab_size", 0) or 0)
    heads = int(getattr(lc, "num_attention_heads", 1) or 1)
    n_layers = int(getattr(lc, "num_layers", 1) or 1)
    if hidden:
        # batch shard (int32 ids) and block-boundary / ffn activations
        candidates.append(b_local * seq * 4)
        candidates.append(b_local * seq * max(hidden, ffn) * abytes)
        # scan-over-layers remat saves a residual PER LAYER: the stacked
        # [L, b, s, h] carry is the activation-checkpoint high-water mark
        candidates.append(n_layers * b_local * seq * hidden * abytes)
        # lm-head logits, vocab sharded over model, f32 for the CE
        candidates.append(b_local * seq * max(vocab // max(tp, 1), 1) * 4)
        moe_cfg = getattr(mc, "moe", None)
        if moe_cfg is not None:
            # dropless routes [T*k] rows through the expert ffn
            k = int(getattr(moe_cfg, "top_k", 1) or 1)
            candidates.append(b_local * seq * k * max(ffn, hidden) * abytes)
        if getattr(lc, "attention_impl", "core") == "core":
            # naive scores materialize [b, heads/tp, s, s] in softmax dtype
            s_att = seq // max(cp, 1)
            candidates.append(
                b_local * max(heads // max(tp, 1), 1) * s_att * seq * 4)
    return max(candidates + [1])


def audit_replication(report: AuditReport, ctx: AuditContext,
                      hlo_texts: list[str], *, slack: float = 8.0,
                      max_findings: int = 8) -> None:
    """GA201: per-device tensors above ``slack``x the analytic budget.

    Post-SPMD HLO shapes are per-device, so an intermediate that dodged its
    PartitionSpec shows up ``axis_size``x larger than the budget — the rule
    catches replication factors above ``slack``.  Fusion bodies are skipped
    (their interior shapes never materialize)."""
    budget = expected_max_device_bytes(ctx)
    threshold = int(budget * slack)
    report.stats["replication_budget_bytes"] = budget
    report.stats["replication_threshold_bytes"] = threshold
    seen: set[str] = set()
    hits = 0
    for text in hlo_texts:
        for comp, lines in _computation_blocks(text):
            if "fused_computation" in comp:
                continue
            for line in lines:
                if "=" not in line:
                    continue
                lhs, _, rhs = line.partition("=")
                opname = lhs.strip()
                if opname in seen:
                    continue
                # first shape token after '=' is the op's output
                m = _SHAPE_RE.search(rhs.split("(")[0])
                if not m:
                    continue
                nbytes = _shape_bytes(m.group(1), m.group(2))
                if nbytes <= threshold:
                    continue
                # parameters are covered by the leaf budget; a parameter
                # larger than it means the leaf ISN'T sharded as specced,
                # which assert_tree_sharding owns — skip the noise here
                if " parameter(" in rhs:
                    continue
                seen.add(opname)
                hits += 1
                if hits <= max_findings:
                    report.add(
                        "GA201", "warn",
                        f"per-device intermediate {m.group(0)} is "
                        f"{nbytes / 1e6:.1f} MB — {nbytes / max(budget, 1):.1f}x "
                        f"the largest tensor a correctly-sharded step should "
                        f"hold ({budget / 1e6:.1f} MB)",
                        location=line.strip()[:160],
                        hint="an oversized intermediate usually means a "
                             "with_sharding_constraint was dropped (or "
                             "resolved to replicated); constrain the "
                             "producing activation's batch/seq dim",
                    )
    if hits > max_findings:
        report.add(
            "GA201", "info",
            f"{hits - max_findings} further oversized intermediates "
            f"suppressed (same probable root cause)",
        )


_STABLEHLO_DOT_RE = re.compile(
    r"stablehlo\.dot_general\s+(%[\w#]+),\s+(%[\w#]+)"
    r".*?:\s*\(tensor<([^>]*)>,\s*tensor<([^>]*)>\)"
)
_STABLEHLO_WIDEN_RE = re.compile(
    r"(%[\w#]+)\s*=\s*stablehlo\.convert\s.*?"
    r"\(tensor<[^>]*x(?:bf16|f16|f8\w*)>\)\s*->\s*tensor<[^>]*xf32>"
)


def audit_dtypes(report: AuditReport, ctx: AuditContext,
                 stablehlo_text: str, *, max_findings: int = 8) -> None:
    """GA301: f32×f32 matmuls in the traced program under a bf16 regime.

    Runs on StableHLO — the program as traced, before any backend-specific
    precision rewrites (CPU legitimately upcasts bf16 dots to f32 at the HLO
    level; that is not a config defect).  A dot whose f32 operand is a
    WIDENING convert from bf16 is the policy's own promotion (the f32
    softmax path meeting bf16 values — data is still bf16-precise) and is
    not flagged; the rule targets dots where both operands are genuinely
    f32-valued, i.e. the compute-dtype cast never happened."""
    if jnp.dtype(ctx.policy.compute_dtype) != jnp.dtype(jnp.bfloat16):
        return
    if not stablehlo_text:
        report.add(
            "GA301", "info",
            "StableHLO unavailable; f32-matmul check skipped",
        )
        return
    # the MoE router deliberately computes in f32 (routing decisions are
    # precision-sensitive); its dots are recognizable by the num_experts-
    # sized TRAILING dim one operand always carries ([h,E] fwd, [T,E] in
    # both transposes) — a genuine missed-cast matmul trails h/ffn/vocab
    moe_cfg = getattr(ctx.model_cfg, "moe", None) or (
        ctx.cfg.get("model", {}) or {}).get("moe")
    n_experts = int(getattr(moe_cfg, "num_experts", 0) or 0) if moe_cfg else 0

    def router_like(*type_strs: str) -> bool:
        if not n_experts:
            return False
        for t in type_strs:
            dims = [d for d in t.split("x")[:-1] if d.isdigit()]
            if dims and int(dims[-1]) == n_experts:
                return True
        return False

    hits = 0
    # MLIR SSA names (%N) are function-scoped: the widened-convert set is
    # rebuilt per func.func block so a convert in one function cannot
    # exempt an unrelated same-named dot operand in another
    for block in re.split(r"(?=^\s*func\.func\b)", stablehlo_text,
                          flags=re.M):
        widened = set(_STABLEHLO_WIDEN_RE.findall(block))
        for line in block.splitlines():
            m = _STABLEHLO_DOT_RE.search(line)
            if not m:
                continue
            lhs_name, rhs_name = m.group(1), m.group(2)
            e1 = m.group(3).rsplit("x", 1)[-1]
            e2 = m.group(4).rsplit("x", 1)[-1]
            if (e1 == "f32" and e2 == "f32"
                    and lhs_name not in widened and rhs_name not in widened
                    and not router_like(m.group(3), m.group(4))):
                hits += 1
                if hits <= max_findings:
                    report.add(
                        "GA301", "warn",
                        f"f32 x f32 matmul in a bf16 compute regime "
                        f"(tensor<{m.group(3)}> x tensor<{m.group(4)}>)",
                        location=line.strip()[:160],
                        hint="a dot whose BOTH operands are f32 under "
                             "precision.type mixed/bf16 bypasses the policy "
                             "cast — check the producing op applies "
                             "policy.compute_dtype",
                    )
    if hits > max_findings:
        report.add(
            "GA301", "info",
            f"{hits - max_findings} further f32 matmuls suppressed",
        )
    report.stats["f32_matmuls"] = hits


# --------------------------------------------------------------------------
# drivers
# --------------------------------------------------------------------------


def audit_artifacts(
    ctx: AuditContext,
    compiled: Any,
    stablehlo_text: str = "",
    *,
    replication_slack: float = 8.0,
    config_name: str = "",
) -> AuditReport:
    """Run every graph rule against an already-compiled executable.

    This is the shared core: the pre-flight CLI calls it on an abstract
    lowering, the trainer's compile census calls it on the very executable
    about to run, bench.py on its measured step."""
    from neuronx_distributed_training_tpu.telemetry.census import (
        hlo_texts_from_compiled,
    )

    from neuronx_distributed_training_tpu.telemetry.census import (
        memory_analysis_bytes,
    )

    report = AuditReport(config=config_name
                         or str(ctx.cfg.get("name", "") or ""))
    # XLA's own memory accounting rides every audit (the autotune planner
    # reads it back as the measured counterpart of its analytic HBM model;
    # arguments + temps is the resident figure — outputs alias donated args)
    mem = memory_analysis_bytes(compiled)
    if mem is not None:
        report.stats["memory_analysis"] = mem
        report.stats["memory_bytes"] = (
            mem.get("argument_size_in_bytes", 0)
            + mem.get("temp_size_in_bytes", 0)
        )
    try:
        hlo_texts = hlo_texts_from_compiled(compiled)
    except Exception as e:  # noqa: BLE001 — no HLO, no graph rules
        report.add(
            "GA000", "warn",
            f"compiled HLO unavailable ({type(e).__name__}: {e}); graph "
            f"rules skipped",
        )
        return report
    audit_donation(report, ctx, hlo_texts)
    audit_collectives(report, ctx, hlo_texts)
    audit_replication(report, ctx, hlo_texts, slack=replication_slack)
    audit_dtypes(report, ctx, stablehlo_text)
    return report


def audit_executable(ctx: AuditContext, compiled: Any, lowered: Any = None,
                     *, log=None, config_name: str = "") -> AuditReport:
    """One-call wrapper for callers holding a live ``(lowered, compiled)``
    pair — the trainer's in-loop census audit and bench.py share this so
    the as_text fallback and finding logging cannot drift apart."""
    stablehlo = ""
    if lowered is not None:
        try:
            stablehlo = lowered.as_text()
        except Exception as e:  # noqa: BLE001 — dtype rule degrades
            logger.debug("stablehlo text unavailable: %s", e)
    report = audit_artifacts(ctx, compiled, stablehlo,
                             config_name=config_name)
    if log is not None:
        for f in report.findings:
            log(f.format())
        log(f"graph audit: {report.worst() or 'clean'} (donation coverage "
            f"{100 * report.stats.get('donation_coverage', 0.0):.1f}%)")
    return report


def audit_step_program(asm: Any, *, replication_slack: float = 8.0,
                       config_name: str = "",
                       artifacts_out: Optional[dict] = None) -> AuditReport:
    """Lower + compile a :class:`StepProgram` abstractly and audit it.

    Spec lint (GA401) runs first: a spec naming an absent mesh axis (or
    double-using one) would die inside the partitioner with a message naming
    neither leaf nor axis — here it dies with both, and lowering is
    skipped.

    ``artifacts_out``, when given, receives ``{"ctx", "compiled",
    "stablehlo"}`` on a successful lowering — callers that ALSO fingerprint
    the artifact (the graph-contract ratchet riding a pre-flight sweep)
    reuse the one lowering instead of paying a second."""
    from neuronx_distributed_training_tpu.parallel.sharding import spec_errors

    errors = spec_errors({"params": asm.pspecs, "opt_state": asm.ospecs},
                         asm.mesh)
    if errors:
        report = AuditReport(config=config_name
                             or str(asm.cfg.get("name", "") or ""))
        for e in errors:
            report.add(
                "GA401", "error", f"invalid PartitionSpec: {e}",
                hint="fix the spec before lowering; axes must come from the "
                     "mesh and appear at most once per spec",
            )
        return report
    stablehlo, compiled = lower_step_program(asm)
    ctx = AuditContext.from_step_program(asm)
    if artifacts_out is not None:
        artifacts_out.update(ctx=ctx, compiled=compiled, stablehlo=stablehlo)
    return audit_artifacts(
        ctx, compiled, stablehlo, replication_slack=replication_slack,
        config_name=config_name,
    )


# --------------------------------------------------------------------------
# config shrinking: audit a 405B config in seconds, preserving structure
# --------------------------------------------------------------------------


def shrink_overrides(cfg: Mapping, *, max_devices: int = 8) -> dict[str, Any]:
    """Dotted-path overrides that shrink a resolved config to audit size.

    Parallel degrees clamp to 2 (any degree > 1 exercises the same contract
    structure: the axis exists, its collectives appear, its divisibility
    rules bind); model dims shrink to the smallest shapes satisfying the
    clamped degrees; batch shrinks to one microbatch per dp rank (pipeline
    configs keep ``pp`` microbatches so the stage loop is real).  Everything
    structural — which fusions are on, precision regime, zero1, alignment,
    MoE layout — is preserved."""
    ds = dict(cfg.get("distributed_strategy", {}) or {})
    model = dict(cfg.get("model", {}) or {})
    data = dict(cfg.get("data", {}) or {})
    fus = dict(model.get("fusions", {}) or {})

    def clamp(key, default=1):
        return min(int(ds.get(key) or default), 2)

    tp = clamp("tensor_model_parallel_size")
    pp = clamp("pipeline_model_parallel_size")
    cp = clamp("context_parallel_size")
    ep = clamp("expert_model_parallel_size")
    vp = clamp("virtual_pipeline_model_parallel_size")
    world = tp * pp * cp * ep
    if world > max_devices:
        raise ValueError(
            f"shrunk world {world} still exceeds max_devices={max_devices}"
        )
    data_mult = 2 if world * 2 <= max_devices else 1
    dp = data_mult * ep

    o: dict[str, Any] = {
        "distributed_strategy.tensor_model_parallel_size": tp,
        "distributed_strategy.pipeline_model_parallel_size": pp,
        "distributed_strategy.context_parallel_size": cp,
        "distributed_strategy.expert_model_parallel_size": ep,
        "distributed_strategy.virtual_pipeline_model_parallel_size": vp,
    }

    # heads/hidden: smallest GQA-shaped stack satisfying tp (weight splits)
    # and tp*cp (ulysses head budget)
    heads = 2 * tp * cp
    kv = tp * cp
    head_dim = 16
    o["model.num_attention_heads"] = heads
    for key in ("num_key_value_heads", "num_query_groups"):
        if key in model:
            o[f"model.{key}"] = kv
    o["model.hidden_size"] = heads * head_dim
    for key in ("intermediate_size", "ffn_hidden_size"):
        if key in model:
            o[f"model.{key}"] = 2 * heads * head_dim
    if "kv_channels" in model:
        o["model.kv_channels"] = head_dim
    o["model.vocab_size"] = 128 * tp
    if "sliding_window" in model and model.get("sliding_window"):
        o["model.sliding_window"] = 32

    # layers: one whole (MoE + dense) group per stage chunk
    moe = dict(model.get("moe", {}) or {})
    moe_freq = int(model.get("moe_frequency", moe.get("moe_frequency", 1)) or 1)
    chunks = max(pp * vp, 1)
    o["model.num_layers"] = max(moe_freq, 1) * max(chunks, 2 // max(moe_freq, 1))
    if moe:
        o["model.moe.num_experts"] = max(2 * ep, 4)
        if moe.get("top_k"):
            o["model.moe.top_k"] = min(int(moe["top_k"]), 2)

    # sequence/batch: divisibility by cp (and 2*cp for zigzag) at seq 64;
    # flash/blockwise kv tiles shrink with it
    seq = 64 * max(cp, 1)
    o["data.seq_length"] = seq
    if "max_position_embeddings" in model:
        o["model.max_position_embeddings"] = seq
    if "encoder_seq_length" in model:
        o["model.encoder_seq_length"] = seq
    for key in ("flash_block_q", "flash_block_kv"):
        if fus:
            o[f"model.fusions.{key}"] = 16
    nm = pp if pp > 1 else 1
    o["data.micro_batch_size"] = 1
    o["data.global_batch_size"] = dp * nm
    return o


def audit_config(
    source: str | Path | Mapping,
    *,
    devices: Optional[list] = None,
    shrink: bool = True,
    max_devices: Optional[int] = None,
    replication_slack: float = 8.0,
    overrides: Optional[Mapping] = None,
    artifacts_out: Optional[dict] = None,
) -> AuditReport:
    """Load a YAML config, (optionally) shrink it, AOT-lower its train step,
    and audit the compiled artifact.  The one-call entry the CLI and the
    per-example-config test sweep use.

    Config-level validation failures become a GA000 error finding rather
    than an exception: the audit's job is a verdict, not a traceback."""
    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.loop import (
        assemble_step_program,
    )

    name = Path(source).name if isinstance(source, (str, Path)) else str(
        dict(source).get("name", "<mapping>"))
    report = AuditReport(config=name)
    try:
        cfg = load_config(source, overrides)
    except Exception as e:  # noqa: BLE001 — config errors ARE the verdict
        report.add(
            "GA000", "error",
            f"config failed validation: {type(e).__name__}: {e}",
            hint="fix the config; the loader's message names the knob",
        )
        return report
    devices = devices if devices is not None else jax.devices()
    # shrunk audits run on a CANONICAL world (≤ 8 devices) END TO END: both
    # the shrink itself (data_mult / global_batch_size) and the lowering
    # pool below — the compiled artifact, and the graph-contract fingerprint
    # snapshotted from it, must not depend on how many virtual devices this
    # machine's pool happens to hold
    avail = min(len(devices), 8) if shrink else len(devices)
    if max_devices is None:
        max_devices = avail
    try:
        if shrink:
            shr = shrink_overrides(cfg, max_devices=max_devices)
            if overrides:
                shr.update(overrides)
            cfg = load_config(source, shr) if isinstance(
                source, (str, Path)) else load_config(dict(source), shr)
            report.stats["shrunk"] = True
        asm = assemble_step_program(
            cfg, devices=list(devices)[: _world_of(cfg, avail)],
            build_data=False,
        )
    except Exception as e:  # noqa: BLE001 — assembly errors ARE the verdict
        report.add(
            "GA000", "error",
            f"train step assembly failed: {type(e).__name__}: {e}",
            hint="the config lowers no further than assembly; the message "
                 "names the failing subsystem",
        )
        return report
    sub = audit_step_program(
        asm, replication_slack=replication_slack, config_name=name,
        artifacts_out=artifacts_out)
    report.extend(sub)
    return report


def _world_of(cfg: Mapping, available: int) -> int:
    """Smallest device count the config's mesh accepts: the model axes exactly,
    times the largest data factor that fits ``available``."""
    ds = dict(cfg.get("distributed_strategy", {}) or {})
    base = 1
    for k in ("tensor_model_parallel_size", "pipeline_model_parallel_size",
              "context_parallel_size", "expert_model_parallel_size"):
        base *= int(ds.get(k) or 1)
    if base > available:
        raise ValueError(
            f"config needs at least {base} devices for its parallel degrees; "
            f"{available} available (raise "
            f"--xla_force_host_platform_device_count)"
        )
    world = base
    while world * 2 <= available:
        world *= 2
    # keep dp = world/base a power-of-two multiple but small: one doubling
    # is enough to surface data-axis collectives
    return min(world, base * 2)
