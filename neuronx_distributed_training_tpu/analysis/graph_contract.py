"""Graph contracts: a compile-artifact regression ratchet with provenance.

The graph auditor (``analysis.graph_audit``) checks each compiled step
against *absolute* rules; nothing there catches *relative* drift — a
refactor can add an unplanned GSPMD reshard, drop a donated buffer, or
upcast a matmul and still pass every threshold.  This module makes the
compiled artifact itself a contract:

- ``fingerprint_artifacts`` extracts a **contract fingerprint** from a
  compiled train step: the collective census by kind × mesh-axis-group,
  per-collective **provenance** (each collective attributed to the declared
  source that explains it — tp/SP layer comms, ZeRO-1 RS+AG, pp hops, cp
  ring/ulysses, ep dispatch/weight-gather, MoE permutes — classified with
  the same ``utils.debug.AXIS_COLLECTIVE_KINDS`` table the autotune cost
  model prices and the trace analytics measure), the donation coverage map,
  ``memory_analysis()`` bytes, and the matmul dtype census.  A collective no
  declared source explains is a GSPMD-inserted reshard: the fingerprint
  records it unattributed, with the nearest named source op XLA's metadata
  points at.
- ``diff_fingerprint`` is the semantic differ: it explains a regression in
  config-level terms ("data-axis all-gather count 2→4: ZeRO-1 parameter
  all-gather duplicated; likely spec change in optim/zero1") rather than as
  an HLO text diff.
- Golden snapshots live under ``analysis/contracts/<config>.json``.  The
  ratchet only shrinks silently: an improvement (fewer collectives, tighter
  memory) updates without ceremony, growth refuses to commit without an
  in-file justification line, and unattributed collectives refuse to commit
  without an explicit waiver.

Surfaces: ``tools/graph_contract.py`` (CLI check/update over the example
configs), the trainer's in-loop ``telemetry.graph_audit`` verdict (the very
executable about to train gets its collectives attributed), and the verify
gate.  ``docs/static_analysis.md`` documents the workflow.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from pathlib import Path
from typing import Any, Mapping, Optional

from neuronx_distributed_training_tpu.analysis.report import AuditReport

logger = logging.getLogger(__name__)

#: committed golden snapshots, one per example config
CONTRACTS_DIR = Path(__file__).resolve().parent / "contracts"

#: fingerprint schema version — bump on incompatible shape changes (the
#: differ refuses to compare across versions)
FINGERPRINT_VERSION = 1

#: memory growth/shrink beyond this fraction of the committed resident bytes
#: is a finding (10% absorbs scheduler jitter across minor XLA changes while
#: catching a lost donation or a replicated tensor long before +20%)
MEMORY_TOLERANCE = 0.10


class ContractError(RuntimeError):
    """A config could not be fingerprinted (load/assembly/lowering failed)."""


# --------------------------------------------------------------------------
# mesh-axis resolution: which axes a replica-group partition spans
# --------------------------------------------------------------------------


def _mesh_partitions(mesh: Any) -> dict[frozenset, tuple[str, ...]]:
    """Canonical replica-group partition -> the mesh-axis subset spanning it.

    For every non-empty subset S of the mesh's non-trivial axes, the
    partition groups device ids that agree on every axis NOT in S.  A
    compiled collective whose ``replica_groups`` equal one of these
    partitions communicates exactly over S."""
    import itertools

    import numpy as np

    axes = list(mesh.axis_names)
    shape = [int(mesh.shape[a]) for a in axes]
    ids = np.empty(shape, dtype=np.int64)
    for idx in np.ndindex(*shape):
        ids[idx] = int(mesh.devices[idx].id)
    nontrivial = [i for i, s in enumerate(shape) if s > 1]
    out: dict[frozenset, tuple[str, ...]] = {}
    for r in range(1, len(nontrivial) + 1):
        for combo in itertools.combinations(nontrivial, r):
            keep = [i for i in range(len(axes)) if i not in combo]
            groups: dict[tuple, list[int]] = {}
            for idx in np.ndindex(*shape):
                key = tuple(idx[i] for i in keep)
                groups.setdefault(key, []).append(int(ids[idx]))
            part = frozenset(frozenset(g) for g in groups.values())
            out.setdefault(part, tuple(axes[i] for i in combo))
    # iteration order (dicts preserve insertion) is smallest-subset-first:
    # the covering fallback in _axes_of_op picks the MINIMAL axis set
    return out


def _axes_of_op(entry: Mapping[str, Any], mesh: Any,
                partitions: dict[frozenset, tuple[str, ...]],
                coords: dict[int, dict[str, int]]) -> Optional[tuple[str, ...]]:
    """Mesh axes one parsed collective op communicates over.

    ``None`` means the group structure matched no axis subset (an irregular
    partition — reported unattributed with its raw groups)."""
    pairs = entry.get("pairs")
    if pairs:
        axes: set[str] = set()
        moved = False
        for s, t in pairs:
            if s == t:
                continue  # identity pair: the no-op edge of a ring shift
            moved = True
            cs, ct = coords.get(s), coords.get(t)
            if cs is None or ct is None:
                return None
            axes |= {a for a in cs if cs[a] != ct[a]}
        if not moved:
            return ()  # all self-sends: no communication
        order = list(mesh.axis_names)
        return tuple(sorted(axes, key=order.index)) if axes else None
    groups = entry.get("groups")
    if groups is None:
        # replica_groups={}: every device in one group
        return tuple(a for a in mesh.axis_names if int(mesh.shape[a]) > 1)
    part = frozenset(frozenset(g) for g in groups if len(g) > 1)
    if not part:
        return ()  # singleton groups: a degenerate no-comm collective
    full = frozenset(frozenset(g) for g in groups)
    exact = partitions.get(full) or partitions.get(part)
    if exact is not None:
        return exact
    # No axis subset partitions EXACTLY this way — GSPMD sometimes emits
    # sub-axis collectives (e.g. groups spanning half the data axis when a
    # tensor dim splits across a bigger axis).  Attribute to the MINIMAL
    # axis subset whose partition covers every group: traffic confined
    # within an axis's blocks is still that axis's communication.
    # (_mesh_partitions iterates smallest subsets first.)
    for cand, axes_tuple in partitions.items():
        if all(any(g <= block for block in cand) for g in part):
            return axes_tuple
    return None


def _device_coords(mesh: Any) -> dict[int, dict[str, int]]:
    import numpy as np

    axes = list(mesh.axis_names)
    shape = [int(mesh.shape[a]) for a in axes]
    out: dict[int, dict[str, int]] = {}
    for idx in np.ndindex(*shape):
        out[int(mesh.devices[idx].id)] = dict(zip(axes, idx))
    return out


# --------------------------------------------------------------------------
# declared sources: the provenance classes a config can explain
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DeclaredComms:
    """What the config declares — the facts provenance classifies against.
    Derived identically to ``graph_audit.audit_collectives`` so the absolute
    rules and the ratchet can never disagree about a config's intent."""

    tp: int
    pp: int
    cp: int
    ep: int
    dp: int          # data axis only; the compound dp degree is dp * ep
    zero1: bool
    seq_par: bool
    moe: bool
    ulysses: bool
    ring: bool
    accum: bool = False  # gradient accumulation (num_microbatches > 1)
    zero1_bucket: bool = False  # engineered overlap: bucketed ZeRO-1 gathers

    @classmethod
    def from_ctx(cls, ctx: Any) -> "DeclaredComms":
        fus = ctx.fusions
        dp_total = ctx.axis("data") * ctx.axis("expert")
        gbs = int(ctx.sched.get("global_batch_size", 1) or 1)
        mbs = int(ctx.sched.get("micro_batch_size", 1) or 1)
        overlap = ctx.ds.get("overlap") or {}
        return cls(
            tp=ctx.axis("model"), pp=ctx.axis("pipe"),
            cp=ctx.axis("context"), ep=ctx.axis("expert"),
            dp=ctx.axis("data"),
            zero1=bool(ctx.ds.get("zero1", True)),
            zero1_bucket=(bool(ctx.ds.get("zero1", True))
                          and float(overlap.get("zero1_bucket_mb", 0) or 0) > 0),
            seq_par=bool(ctx.ds.get("sequence_parallel", False)),
            moe=bool((ctx.cfg.get("model", {}) or {}).get("moe")),
            ulysses=bool(fus.get("ulysses_attention")),
            ring=bool(fus.get("ring_attention")
                      or fus.get("zigzag_ring_attention")),
            accum=gbs > mbs * max(dp_total, 1),
        )

    @property
    def dp_total(self) -> int:
        return self.dp * self.ep


_DP_AXES = frozenset({"data", "expert"})
_BATCH_AXES = frozenset({"data", "expert", "context"})


def _src_any(*needles: str):
    """Source-op predicate: the metadata ``op_name`` of at least one op in
    the group mentions one of the needles (the corroborating evidence a
    sharper class demands)."""
    def pred(source_ops: list[str]) -> bool:
        return any(n in s for s in source_ops for n in needles)
    return pred


def declared_source_classes(d: DeclaredComms) -> list[tuple]:
    """Ordered ``(label, kinds, axes_predicate, src_predicate, grow_hint)``
    rules; the first rule matching a collective group's (kind, axis-set,
    source ops) names its source.  Kind sets come from
    ``utils.debug.AXIS_COLLECTIVE_KINDS`` — the same classes the autotune
    cost model prices per axis and the trace analytics measure, so all
    three surfaces agree on what each axis's traffic is.  ``src_predicate``
    (may be None) demands corroborating XLA ``op_name`` metadata — classes
    that would otherwise over-claim (embedding exchange, MoE routing) only
    match collectives whose nearest named op is the declared mechanism."""
    from neuronx_distributed_training_tpu.utils.debug import (
        AXIS_COLLECTIVE_KINDS as AK,
    )

    rules: list[tuple] = []

    def add(label, kinds, pred, hint, src=None):
        rules.append((label, tuple(kinds), pred, src, hint))

    if d.tp > 1:
        add("tp/SP layer collective", AK["tp"],
            lambda a: a == {"model"},
            "tensor-parallel layer communication changed; check the layer "
            "PartitionSpecs (parallel/sharding act_spec/param_specs) and "
            "model.fusions")
        if d.seq_par:
            add("SP seq<->hidden reshard", ("all-to-all",),
                lambda a: a == {"model"},
                "sequence-parallel boundary moved; check act_spec("
                "sequence_parallel=True) placement between blocks")
            # slicing/padding a seq-dim-sharded activation (rotary shifts,
            # causal masks) consumes neighbours' rows: a halo exchange
            add("SP halo permute", ("collective-permute",),
                lambda a: a == {"model"},
                "a sequence-parallel activation is consumed at a shifted "
                "index (halo); check seq-dim slicing under SP",
                src=_src_any("slice", "pad", "concatenate", "roll"))
    if d.tp > 1 or d.pp > 1:
        # vocab-parallel embedding: the token gather (and its scatter-add
        # transpose) crosses the model axis — composed with the batch axes,
        # and under pp additionally with the pipe axis (the embed/lm_head
        # stacks live on the edge stages)
        add("tp vocab/embedding exchange",
            ("collective-permute", "all-gather", "all-reduce"),
            lambda a: bool(a) and a <= (_BATCH_AXES | {"model", "pipe"}),
            "vocab-parallel embedding lookup traffic changed; check the "
            "embed/lm_head PartitionSpecs",
            src=_src_any("_take", "embed"))
    if d.dp_total > 1 or d.cp > 1:
        add("dp gradient/loss all-reduce", ("all-reduce",),
            lambda a: a and a <= _BATCH_AXES,
            "gradient/loss reduction over the batch axes changed; check "
            "that the loss stays a single global mean over the dp-sharded "
            "batch (trainer/step.py)")
    if d.zero1 and d.dp_total > 1:
        add("ZeRO-1 gradient reduce-scatter", ("reduce-scatter",),
            lambda a: a and a <= _DP_AXES,
            "ZeRO-1 gradient sharding changed; likely spec change in "
            "optim/zero1 (opt_state_specs)")
        if d.zero1_bucket:
            # engineered overlap (distributed_strategy.overlap.zero1_bucket_mb
            # > 0): the optimizer packs eligible leaves per layer-group bucket
            # and regathers each bucket with ONE combined all-gather under the
            # optim.overlap.BUCKET_AG_SCOPE named scope.  A named class so the
            # per-bucket collective-count growth is a justified fingerprint
            # change, not ZeRO-1 regather noise — ordered BEFORE the generic
            # rule; the scope corroboration keeps it from over-claiming.
            add("zero1-bucket combined all-gather", ("all-gather",),
                lambda a: a and a <= _DP_AXES,
                "bucketed ZeRO-1 regather changed; check distributed_"
                "strategy.overlap.zero1_bucket_mb and optim/overlap "
                "build_bucket_plan (one combined all-gather per bucket)",
                src=_src_any("zero1_bucket"))
        add("ZeRO-1 parameter all-gather", ("all-gather",),
            lambda a: a and a <= _DP_AXES,
            "ZeRO-1 resharding duplicated; likely spec change in optim/"
            "zero1 — updated params should regather exactly once per step")
        add("ZeRO-1 reshard permute", ("collective-permute",),
            lambda a: a and a <= _DP_AXES,
            "ZeRO-1 shard/regather permute chain changed; check "
            "opt_state_specs(zero1=...) against param_specs")
    if d.accum and d.dp_total > 1:
        # the grad-accumulation loop dynamic-slices microbatches out of the
        # dp-sharded global batch: re-tiling [gbs] rows from nm-per-device
        # to 1-per-device is an intra-data-axis exchange
        add("dp grad-accum microbatch reshard",
            ("all-to-all", "all-gather", "collective-permute"),
            lambda a: a and a <= _DP_AXES,
            "microbatch slicing across the dp-sharded batch changed; "
            "check the gradient-accumulation loop (trainer/step.py)")
    if d.pp > 1:
        add("pp stage hop", AK["pp"],
            lambda a: a == {"pipe"},
            "inter-stage transfer count changed; check the pipeline "
            "schedule's tick loop (parallel/pipeline.py)")
        # the stage loop psums partial losses/metrics across stages, and
        # shard_map boundaries regather stage-sharded values
        add("pp stage reduction", ("all-reduce",),
            lambda a: a == {"pipe"},
            "per-stage loss/metric reduction over the pipe axis changed; "
            "check the pipeline loss aggregation (parallel/pipeline.py)")
        # the stage body's manual-vjp psums (grads of values replicated
        # inside the shard_map) lower over the NON-pipe axes the body
        # replicates across
        add("pp stage-body grad reduction", ("all-reduce",),
            lambda a: bool(a) and "pipe" not in a,
            "the pipeline stage body's psum pattern changed; check the "
            "manual-vjp reductions in parallel/pipeline.py "
            "(pipeline_loss_and_grad)",
            src=_src_any("shmap_body"))
    if d.cp > 1:
        if d.ring:
            add("cp ring kv pass", ("collective-permute",),
                lambda a: a == {"context"},
                "ring-attention kv rotation changed; check parallel/"
                "ring_attention.py and the sequence-dim specs")
        if d.ulysses:
            add("cp ulysses head exchange", ("all-to-all",),
                lambda a: a == {"context"},
                "ulysses qkvo head exchange changed; check parallel/"
                "ulysses.py")
        add("cp sequence regather", ("all-gather",),
            lambda a: a == {"context"},
            "a sequence-sharded activation is being regathered over the "
            "context axis; check the seq-dim PartitionSpecs")
        # entering/leaving the CP fusion's shard_map regathers the
        # seq-sharded activation over the axes the body runs manual on
        add("cp shard_map boundary regather", ("all-gather",),
            lambda a: bool(a) and a <= {"context", "model"},
            "the CP fusion's shard_map boundary resharding changed; check "
            "the in/out specs of the ring/ulysses shard_map",
            src=_src_any("shard_map", "shmap"))
    if d.moe and d.ep > 1:
        add("ep token all-to-all", AK["ep"],
            lambda a: "expert" in a and a <= (_DP_AXES | {"model"}),
            "expert token dispatch changed; check moe_param_specs and the "
            "routing path (ops/moe.py)")
        add("ep expert weight gather", ("all-gather",),
            lambda a: a == {"expert"},
            "weight-gather EP changed; ops/moe.py moe_dropless gathers "
            "expert weights over 'expert' exactly once per MoE layer")
    if d.moe:
        # dropless routing sorts/top-ks token assignments against the
        # whole batch: the sort workspace regathers across every sharded
        # axis, and the combine scatter-adds back — declared cost of
        # dropless MoE (ops/moe.py), not a stray reshard
        add("MoE dropless routing gather", ("all-gather",),
            lambda a: bool(a),
            "dropless routing's sort/top-k workspace traffic changed; "
            "check the routing path (ops/moe.py moe_dropless)",
            src=_src_any("top_k", "sort", "argsort", "cumsum", "one_hot"))
        add("MoE dropless combine", ("all-reduce",),
            lambda a: bool(a),
            "dropless combine (scatter-add of expert outputs) changed; "
            "check ops/moe.py moe_dropless",
            src=_src_any("scatter", "add"))
        # dropped-mode dispatch/combine einsums contract the token dim
        # (sharded over batch axes and, under SP, the model axis): their
        # partial sums all-reduce over those axes; router aux losses reduce
        # the same way
        add("MoE dispatch/combine reduction", ("all-reduce",),
            lambda a: bool(a) and a <= (_BATCH_AXES | {"model"}),
            "MoE dispatch/combine einsum or router-loss reduction changed; "
            "check ops/moe.py and the router aux-loss path",
            src=_src_any("dot_general", "reduce_sum", "einsum"))
        add("MoE permute", ("collective-permute", "all-to-all"),
            lambda a: a and "expert" in a,
            "MoE token permute pattern changed; check the dropless "
            "routing path (ops/moe.py)")
    return rules


def attribute(kind: str, axes: Optional[tuple[str, ...]],
              source_ops: list[str],
              rules: list[tuple]) -> Optional[tuple[str, str]]:
    """``(source_label, grow_hint)`` of the first declared class explaining
    this collective group; ``None`` -> GSPMD-inserted, unattributed."""
    if axes is None:
        return None
    aset = set(axes)
    for label, kinds, pred, src, hint in rules:
        if kind not in kinds or not pred(aset):
            continue
        if src is not None and not src(source_ops):
            continue
        return label, hint
    return None


# --------------------------------------------------------------------------
# the fingerprint
# --------------------------------------------------------------------------


def _matmul_dtype_census(stablehlo_text: str) -> dict[str, Any]:
    """{``lhs_dtype x rhs_dtype``: count} over every ``dot_general`` in the
    traced program, plus one sample location per pair (what a dtype-upcast
    finding names)."""
    from neuronx_distributed_training_tpu.analysis.graph_audit import (
        _STABLEHLO_DOT_RE,
    )

    census: dict[str, int] = {}
    samples: dict[str, str] = {}
    for m in _STABLEHLO_DOT_RE.finditer(stablehlo_text):
        e1 = m.group(3).rsplit("x", 1)[-1]
        e2 = m.group(4).rsplit("x", 1)[-1]
        key = f"{e1}x{e2}"
        census[key] = census.get(key, 0) + 1
        samples.setdefault(
            key, f"dot_general (tensor<{m.group(3)}> x tensor<{m.group(4)}>)")
    return {"counts": dict(sorted(census.items())),
            "samples": dict(sorted(samples.items()))}


# donation accounting is shared with GA001: analysis.graph_audit.donation_map
# is the one implementation, so the absolute rule and this ratchet can never
# disagree about which leaves are donated or aliased


def fingerprint_artifacts(ctx: Any, compiled: Any, stablehlo_text: str = "",
                          *, config_name: str = "") -> dict[str, Any]:
    """Extract the contract fingerprint of a compiled train step.

    ``ctx`` is the same :class:`~.graph_audit.AuditContext` the absolute
    rules read; the fingerprint is pure host-side artifact inspection — no
    device work, no extra compiles — and is byte-stable across identical
    compiles (the snapshot tests pin this)."""
    from neuronx_distributed_training_tpu.analysis.graph_audit import (
        donation_map,
    )
    from neuronx_distributed_training_tpu.telemetry.census import (
        collective_ops_from_texts,
        hlo_texts_from_compiled,
        memory_analysis_bytes,
    )

    hlo_texts = hlo_texts_from_compiled(compiled)
    ops = collective_ops_from_texts(hlo_texts)
    partitions = _mesh_partitions(ctx.mesh)
    coords = _device_coords(ctx.mesh)
    rules = declared_source_classes(DeclaredComms.from_ctx(ctx))
    order = list(ctx.mesh.axis_names)

    # group by kind x axis-set first: attribution sees every group member's
    # source-op metadata (sharper classes demand corroborating evidence)
    grouped: dict[str, dict[str, Any]] = {}
    for entry in ops:
        axes = _axes_of_op(entry, ctx.mesh, partitions, coords)
        if axes == ():
            continue  # degenerate singleton-group op: no communication
        label = "+".join(axes) if axes is not None else "?"
        key = f"{entry['kind']}|{label}"
        g = grouped.setdefault(key, {"kind": entry["kind"], "axes": axes,
                                     "ops": [], "source_ops": []})
        g["ops"].append(entry["op"])
        if entry["source_op"]:
            g["source_ops"].append(entry["source_op"])

    collectives: dict[str, dict[str, Any]] = {}
    for key, g in grouped.items():
        src = attribute(g["kind"], g["axes"], g["source_ops"], rules)
        collectives[key] = {
            "count": len(g["ops"]),
            "source": src[0] if src else None,
            "hint": src[1] if src else "",
            "sample_ops": g["ops"][:2],
            "sample_source_ops": g["source_ops"][:2],
        }

    mem = memory_analysis_bytes(compiled) or {}
    memory = {k: int(mem[k]) for k in
              ("argument_size_in_bytes", "temp_size_in_bytes",
               "output_size_in_bytes") if k in mem}
    if memory:
        memory["resident_bytes"] = (
            memory.get("argument_size_in_bytes", 0)
            + memory.get("temp_size_in_bytes", 0))

    return {
        "version": FINGERPRINT_VERSION,
        "config": config_name or str(ctx.cfg.get("name", "") or ""),
        "mesh": {a: int(ctx.mesh.shape[a]) for a in order},
        "collectives": dict(sorted(collectives.items())),
        "donation": donation_map(ctx, hlo_texts),
        "matmul_dtypes": (_matmul_dtype_census(stablehlo_text)
                          if stablehlo_text else None),
        "memory": memory,
    }


def unattributed_entries(fp: Mapping[str, Any]) -> dict[str, dict[str, Any]]:
    return {k: v for k, v in (fp.get("collectives") or {}).items()
            if v.get("source") is None}


_GSPMD_HINT = (
    "an unattributed collective is a GSPMD-inserted reshard: the partitioner "
    "resolved a PartitionSpec conflict at this op's producer/consumer "
    "boundary by moving data; constrain the producing activation "
    "(shd.constrain) or declare the communication — or waive it explicitly "
    "with tools/graph_contract.py --update-contracts --justify"
)


def attribution_report(fp: Mapping[str, Any], *,
                       waivers: Mapping[str, str] | None = None,
                       config_name: str = "") -> AuditReport:
    """GC201 findings for every unattributed collective in a fingerprint —
    the provenance half of the contract, usable without a committed
    snapshot (the trainer's in-loop verdict)."""
    report = AuditReport(config=config_name or str(fp.get("config", "")))
    waivers = dict(waivers or {})
    unattributed = unattributed_entries(fp)
    report.stats["collectives_total"] = sum(
        v["count"] for v in (fp.get("collectives") or {}).values())
    report.stats["collectives_unattributed"] = sum(
        v["count"] for v in unattributed.values())
    for key, rec in sorted(unattributed.items()):
        if key in waivers:
            continue
        kind, _, axes = key.partition("|")
        near = rec.get("sample_source_ops") or rec.get("sample_ops") or []
        report.add(
            "GC201", "error",
            f"{rec['count']} {kind} op(s) over mesh axes [{axes}] have no "
            f"declared source in this config (GSPMD-inserted reshard); "
            f"nearest named op: {near[0] if near else '<unknown>'}",
            location=", ".join(rec.get("sample_ops", [])[:2]),
            hint=_GSPMD_HINT,
        )
    return report


# --------------------------------------------------------------------------
# the semantic differ
# --------------------------------------------------------------------------


def _explain_key(key: str) -> tuple[str, str]:
    kind, _, axes = key.partition("|")
    return kind, axes


def diff_fingerprint(old: Mapping[str, Any], new: Mapping[str, Any], *,
                     memory_tolerance: float = MEMORY_TOLERANCE,
                     waivers: Mapping[str, str] | None = None,
                     config_name: str = "") -> AuditReport:
    """Compare a fresh fingerprint against the committed contract.

    Error findings are regressions (the ratchet's fail condition); info
    findings are improvements the snapshot can tighten to.  Every message is
    config-level: it names the provenance class that regressed and the
    offending HLO ops, not an HLO text span."""
    report = AuditReport(config=config_name or str(new.get("config", "")))
    waivers = dict(waivers or {})

    if old.get("version") != new.get("version"):
        report.add(
            "GC002", "error",
            f"fingerprint version changed "
            f"{old.get('version')} -> {new.get('version')}: the committed "
            f"contract predates the current schema",
            hint="regenerate: tools/graph_contract.py --update-contracts",
        )
        return report
    if old.get("mesh") != new.get("mesh"):
        report.add(
            "GC002", "error",
            f"mesh changed {old.get('mesh')} -> {new.get('mesh')}: the "
            f"committed contract describes a different parallel layout",
            hint="a deliberate parallelism change must re-baseline: "
                 "tools/graph_contract.py --update-contracts --justify "
                 "'<why>'",
        )
        return report

    # -- collectives: per kind x axis-group counts + provenance ------------
    oc = dict(old.get("collectives") or {})
    nc = dict(new.get("collectives") or {})
    for key in sorted(set(oc) | set(nc)):
        a = int(oc.get(key, {}).get("count", 0))
        b = int(nc.get(key, {}).get("count", 0))
        kind, axes = _explain_key(key)
        rec = nc.get(key) or oc.get(key) or {}
        src = rec.get("source")
        if b > a:
            if src is None and key not in waivers:
                continue  # unattributed growth is GC201's finding below
            what = (f"{src} grew" if src
                    else f"waived reshard ({waivers.get(key, '')}) grew")
            near = rec.get("sample_ops", [])
            report.add(
                "GC101", "error",
                f"[{axes}]-axis {kind} count {a} -> {b}: {what} beyond the "
                f"committed contract"
                + (f" (e.g. {near[0]})" if near else ""),
                location=", ".join(near[:2]),
                hint=rec.get("hint") or
                "declare the change: tools/graph_contract.py "
                "--update-contracts --justify '<why the graph grew>'",
            )
        elif b < a:
            report.add(
                "GC110", "info",
                f"[{axes}]-axis {kind} count {a} -> {b}"
                f"{f' ({src})' if src else ''}: the graph got cheaper — "
                f"tighten the contract with --update-contracts",
            )

    # -- unattributed: every new-side reshard must be waived ---------------
    for key, rec in sorted(unattributed_entries(new).items()):
        if key in waivers:
            continue
        a = int(oc.get(key, {}).get("count", 0))
        b = int(rec.get("count", 0))
        kind, axes = _explain_key(key)
        near = rec.get("sample_source_ops") or rec.get("sample_ops") or []
        report.add(
            "GC201", "error",
            f"{b} {kind} op(s) over mesh axes [{axes}] have no declared "
            f"source (GSPMD-inserted reshard"
            + (f", count {a} -> {b}" if a else ", new")
            + f"); nearest named op: {near[0] if near else '<unknown>'}",
            location=", ".join(rec.get("sample_ops", [])[:2]),
            hint=_GSPMD_HINT,
        )

    # -- donation ----------------------------------------------------------
    od = dict(old.get("donation") or {})
    nd = dict(new.get("donation") or {})
    newly_missing = [p for p in nd.get("missing", [])
                     if p not in set(od.get("missing", []))]
    for path in newly_missing:
        report.add(
            "GC301", "error",
            f"donated leaf {path} lost its input->output alias (donation "
            f"regression: its bytes are now resident twice)",
            location=path,
            hint="a dtype/layout change between the input leaf and its "
                 "updated output defeats aliasing; keep the update "
                 "dtype-preserving (DtypePolicy casts, optimizer state "
                 "dtypes)",
        )
    if not newly_missing and float(nd.get("coverage", 0)) \
            < float(od.get("coverage", 0)):
        report.add(
            "GC301", "error",
            f"donation coverage fell {od.get('coverage')} -> "
            f"{nd.get('coverage')} "
            f"({nd.get('aliased')}/{nd.get('expected')} leaves aliased)",
            hint="the donated tree changed shape AND lost aliasing; "
                 "--update-contracts --justify after fixing or accepting it",
        )
    elif float(nd.get("coverage", 0)) > float(od.get("coverage", 0)):
        report.add(
            "GC110", "info",
            f"donation coverage improved {od.get('coverage')} -> "
            f"{nd.get('coverage')} — tighten with --update-contracts",
        )

    # -- matmul dtypes -----------------------------------------------------
    om = (old.get("matmul_dtypes") or {}).get("counts")
    nm = (new.get("matmul_dtypes") or {}).get("counts")
    if om is not None and nm is not None:
        samples = (new.get("matmul_dtypes") or {}).get("samples", {})
        for pair in sorted(set(om) | set(nm)):
            a, b = int(om.get(pair, 0)), int(nm.get(pair, 0))
            if b <= a:
                if b < a:
                    report.add(
                        "GC110", "info",
                        f"matmul dtype census {pair}: {a} -> {b}",
                    )
                continue
            # ANY growth of a wide-dtype pair is an upcast regression: an
            # upcast on a config that already carries legit f32 dots (the
            # router) shows up as count growth, not a new key, so both
            # forms must fail until declared.  Non-wide pair growth is
            # drift worth declaring but not a precision break (warn).
            widened = "f32" in pair or "f64" in pair
            report.add(
                "GC401", "error" if widened else "warn",
                f"matmul dtype census {pair}: {a} -> {b}"
                + (" — a matmul was upcast beyond the committed precision "
                   "regime" if widened and not a else ""),
                location=samples.get(pair, ""),
                hint="an upcast dot bypasses the compute-dtype policy "
                     "(the GA301 pitfall); check the producing op applies "
                     "policy.compute_dtype — or declare the change with "
                     "--update-contracts --justify" if widened else
                     "matmul count grew; declare the graph change with "
                     "--update-contracts --justify",
            )

    # -- memory ------------------------------------------------------------
    oldb = int((old.get("memory") or {}).get("resident_bytes", 0))
    newb = int((new.get("memory") or {}).get("resident_bytes", 0))
    if oldb and newb:
        ratio = newb / oldb - 1.0
        if ratio > memory_tolerance:
            report.add(
                "GC501", "error",
                f"compiled resident bytes grew {oldb} -> {newb} "
                f"(+{100 * ratio:.1f}% > {100 * memory_tolerance:.0f}% "
                f"tolerance)",
                hint="memory_analysis() argument+temp bytes regressed; the "
                     "usual causes are a lost donation (see GC301), a "
                     "dropped sharding constraint, or a remat policy "
                     "change — declare deliberate growth with "
                     "--update-contracts --justify",
            )
        elif ratio < -memory_tolerance:
            report.add(
                "GC110", "info",
                f"compiled resident bytes shrank {oldb} -> {newb} "
                f"({100 * ratio:.1f}%) — tighten with --update-contracts",
            )
    report.stats["memory_resident_bytes"] = newb
    return report


# --------------------------------------------------------------------------
# snapshots: load / check / update-with-justification
# --------------------------------------------------------------------------


def contract_path(config_name: str,
                  contracts_dir: Optional[Path] = None) -> Path:
    stem = Path(config_name).name
    for suffix in (".yaml", ".yml", ".json"):
        if stem.endswith(suffix):
            stem = stem[: -len(suffix)]
    return (contracts_dir or CONTRACTS_DIR) / f"{stem}.json"


def load_contract(config_name: str,
                  contracts_dir: Optional[Path] = None
                  ) -> Optional[dict[str, Any]]:
    path = contract_path(config_name, contracts_dir)
    if not path.exists():
        return None
    return json.loads(path.read_text())


def write_contract(config_name: str, fingerprint: Mapping[str, Any], *,
                   justifications: list[str],
                   waivers: Mapping[str, str] | None = None,
                   contracts_dir: Optional[Path] = None) -> Path:
    """Byte-stable snapshot write (sorted keys, fixed indent) — reruns with
    an identical artifact produce an identical file."""
    path = contract_path(config_name, contracts_dir)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {
        "comment": "graph contract snapshot — regenerate with "
                   "tools/graph_contract.py --update-contracts; growth "
                   "must carry a --justify line (the ratchet only shrinks "
                   "silently)",
        "config": Path(config_name).name,
        "justifications": list(justifications),
        "waivers": dict(sorted((waivers or {}).items())),
        "fingerprint": fingerprint,
    }
    path.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
    return path


def check_contract(config_name: str, fingerprint: Mapping[str, Any], *,
                   memory_tolerance: float = MEMORY_TOLERANCE,
                   contracts_dir: Optional[Path] = None) -> AuditReport:
    """The ratchet's read side: diff a fresh fingerprint against the
    committed snapshot (plus the provenance check its waivers gate)."""
    name = Path(config_name).name
    snap = load_contract(config_name, contracts_dir)
    if snap is None:
        report = AuditReport(config=name)
        report.add(
            "GC000", "error",
            f"no committed contract for {name} "
            f"({contract_path(config_name, contracts_dir)})",
            hint="baseline it: tools/graph_contract.py --config <cfg> "
                 "--update-contracts",
        )
        return report
    report = diff_fingerprint(
        snap.get("fingerprint") or {}, fingerprint,
        memory_tolerance=memory_tolerance,
        waivers=snap.get("waivers") or {}, config_name=name,
    )
    report.stats["contract_path"] = str(
        contract_path(config_name, contracts_dir))
    return report


def update_contract(config_name: str, fingerprint: Mapping[str, Any], *,
                    justify: Optional[str] = None,
                    memory_tolerance: float = MEMORY_TOLERANCE,
                    contracts_dir: Optional[Path] = None
                    ) -> tuple[Path, AuditReport]:
    """The ratchet's write side.

    Shrinking (or identical) fingerprints commit silently, keeping existing
    justifications.  GROWTH — more collectives, lost donation, wider
    matmuls, more memory, or any unattributed collective — refuses to
    commit unless ``justify`` explains it; the justification is recorded
    in-file, and unattributed collectives become named waivers."""
    name = Path(config_name).name
    snap = load_contract(config_name, contracts_dir)
    old_just = list((snap or {}).get("justifications")
                    or ["initial contract baseline"])
    old_waivers = dict((snap or {}).get("waivers") or {})

    if snap is None:
        rep = AuditReport(config=name)
    else:
        rep = diff_fingerprint(
            snap.get("fingerprint") or {}, fingerprint,
            memory_tolerance=memory_tolerance, waivers=old_waivers,
            config_name=name,
        )
    unattributed = unattributed_entries(fingerprint)
    needs_justify = rep.failed("error") or any(
        k not in old_waivers for k in unattributed)
    if needs_justify and not justify:
        raise ContractError(
            f"{name}: the new fingerprint GROWS the contract "
            f"({', '.join(sorted({f.rule for f in rep.findings if f.severity == 'error'})) or 'unattributed collectives'}) "
            f"— growth must be declared: pass --justify '<why>' "
            f"(the ratchet only shrinks silently)\n{rep.format()}"
        )
    justifications = old_just + ([justify] if justify and (
        needs_justify or snap is None) else [])
    waivers = {k: v for k, v in old_waivers.items()
               if k in unattributed}  # stale waivers drop with the reshard
    for k in sorted(unattributed):
        waivers.setdefault(k, justify or old_waivers.get(k, ""))
    path = write_contract(config_name, fingerprint,
                          justifications=justifications, waivers=waivers,
                          contracts_dir=contracts_dir)
    return path, rep


# --------------------------------------------------------------------------
# config driver (the CLI / sweep entry)
# --------------------------------------------------------------------------


def fingerprint_config(
    source: str | Path | Mapping,
    *,
    devices: Optional[list] = None,
    shrink: bool = True,
    max_devices: Optional[int] = None,
    overrides: Optional[Mapping] = None,
) -> dict[str, Any]:
    """Load a YAML config, (optionally) shrink it with the graph auditor's
    ``shrink_overrides``, AOT-lower its train step on abstract inputs, and
    fingerprint the compiled artifact.  Raises :class:`ContractError` when
    the config cannot be lowered (the CLI turns that into a GC000 finding)."""
    import jax

    from neuronx_distributed_training_tpu.analysis.graph_audit import (
        AuditContext,
        _world_of,
        lower_step_program,
        shrink_overrides,
    )
    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.loop import (
        assemble_step_program,
    )

    name = Path(source).name if isinstance(source, (str, Path)) else str(
        dict(source).get("name", "<mapping>"))
    devices = devices if devices is not None else jax.devices()
    # canonical ≤8-device world under shrink, END TO END: the shrink itself
    # (data_mult / global_batch_size) and the lowering pool — the
    # fingerprint (and the committed snapshot diffed against it) must not
    # depend on the size of this machine's virtual device pool
    avail = min(len(devices), 8) if shrink else len(devices)
    if max_devices is None:
        max_devices = avail
    try:
        cfg = load_config(source, overrides)
        if shrink:
            shr = shrink_overrides(cfg, max_devices=max_devices)
            if overrides:
                shr.update(overrides)
            cfg = load_config(source, shr) if isinstance(
                source, (str, Path)) else load_config(dict(source), shr)
        asm = assemble_step_program(
            cfg, devices=list(devices)[: _world_of(cfg, avail)],
            build_data=False,
        )
        stablehlo, compiled = lower_step_program(asm)
    except ContractError:
        raise
    except Exception as e:  # noqa: BLE001 — the CLI reports, not tracebacks
        raise ContractError(
            f"{name}: could not fingerprint: {type(e).__name__}: {e}"
        ) from e
    ctx = AuditContext.from_step_program(asm)
    fp = fingerprint_artifacts(ctx, compiled, stablehlo, config_name=name)
    fp["shrunk"] = bool(shrink)
    return fp
