"""Layer 2 of the pre-flight auditor: an AST lint for JAX pitfalls.

The graph audit sees what the compiler produced; this pass sees what the
*source* is about to feed it.  It walks the package's Python modules and
flags the pitfalls that cost memory or step time without ever erroring —
each only in the scope where it is actually a pitfall:

- **JL101 hidden host sync** (graph scope): ``.item()`` / ``.tolist()`` on
  anything, ``np.asarray``/``np.array`` applied to a traced function
  parameter, ``float()``/``int()``/``bool()`` wrapped directly around a
  ``jnp``/``jax`` call.  Inside a jitted path each of these blocks dispatch
  on a device round-trip (or silently constant-folds a tracer).
- **JL102 tracer branch** (graph scope): ``if``/``while`` whose test is a
  ``jnp``/``jax`` call (``if jnp.any(mask):``) — Python control flow cannot
  branch on a tracer; this either crashes late or retraces per value.
- **JL103 wall clock** (graph scope): ``time.time()``/``perf_counter()``/
  ``datetime.now()`` inside a step function traces to a constant — the
  timestamp of tracing, not of execution.
- **JL106 f32 upcast in graph scope** (graph scope): an explicit
  ``.astype(jnp.float32)`` / ``jnp.astype(x, jnp.float32)`` inside traced
  code — the source-level twin of the graph audit's GA301: a bf16 value
  widened to f32 mid-graph doubles its bytes and usually marks a matmul
  that will run f32×f32 under a bf16 regime.  Deliberate widenings (the f32
  router, softmax accumulators) are baselined in the ratchet rather than
  suppressed, so NEW upcasts still fail.
- **JL104 PRNG key reuse** (all scopes): the same key variable fed to two
  ``jax.random`` consumers without a ``split``/``fold_in`` reassignment in
  between — correlated randomness, the classic silent statistics bug.
- **JL105 donated-buffer reuse** (all scopes): reading a variable again
  after passing it to a function built with ``donate_argnums``/
  ``jit_train_step`` without rebinding it — the buffer may already be
  aliased over.

Scope model: modules whose package path matches ``GRAPH_MODULES`` are graph
scope (their code is overwhelmingly traced); any function wrapped in a jax
transform (``jax.jit``/``jax.grad``/``shard_map``/``lax.scan`` ...) is graph
scope regardless of module; a ``# jaxlint: host`` (or ``graph``) comment in
a file's first 5 lines overrides.  Suppress a single finding with
``# jaxlint: disable=RULE`` on the offending line.  ``baseline.json`` is the
committed ratchet: pre-existing findings pass, NEW findings fail, and a
baseline entry that no longer matches anything is STALE and fails too (the
baseline only shrinks).  See ``docs/static_analysis.md``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Iterable, Optional

from neuronx_distributed_training_tpu.analysis.report import (
    AuditReport,
    Finding,
)

#: package-relative glob-ish prefixes whose modules are graph scope: their
#: functions run under jit/shard_map in the trained program
GRAPH_MODULES = (
    "models/", "ops/", "optim/", "alignment/", "peft/",
    "parallel/pipeline", "parallel/ring_attention", "parallel/ulysses",
    "trainer/step",
)

#: jax transforms whose function argument becomes traced code
_TRANSFORMS = {
    "jit", "grad", "value_and_grad", "vjp", "jvp", "vmap", "pmap",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "shard_map",
    "scan", "while_loop", "fori_loop", "cond", "switch", "associated_scan",
    "eval_shape", "linearize",
}

#: jax.random constructors (NOT consumers — these mint keys)
_KEY_MAKERS = {"PRNGKey", "key", "wrap_key_data", "clone"}

_SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Z0-9, ]+)")
_MODE_RE = re.compile(r"#\s*jaxlint:\s*(graph|host)\b")

_DONATING_BUILDERS = {"jit_train_step"}  # package-local donating factories


def _dotted(node: ast.AST) -> str:
    """``jax.random.normal`` -> "jax.random.normal"; non-dotted -> ""."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jaxish(call: ast.AST) -> bool:
    """A call spelled through a jax/jnp/lax namespace (the linter's cheap
    "this produces/handles a traced value" signal)."""
    if not isinstance(call, ast.Call):
        return False
    head = _dotted(call.func).split(".")[0]
    return head in ("jnp", "jax", "lax")


@dataclasses.dataclass
class LintContext:
    path: Path            # file being linted
    rel: str              # package-relative posix path
    source_lines: list[str]
    graph_default: bool   # module-level scope
    report: AuditReport = dataclasses.field(default_factory=AuditReport)

    def suppressed(self, lineno: int, rule: str) -> bool:
        def match(ln: int) -> bool:
            if not 1 <= ln <= len(self.source_lines):
                return False
            m = _SUPPRESS_RE.search(self.source_lines[ln - 1])
            return bool(m and rule in
                        {r.strip() for r in m.group(1).split(",")})

        if match(lineno):
            return True
        # a standalone `# jaxlint: disable=...` comment line covers the NEXT
        # line; an inline disable on the previous line covers only itself
        prev = (self.source_lines[lineno - 2].strip()
                if lineno >= 2 and lineno - 2 < len(self.source_lines) else "")
        return prev.startswith("#") and match(lineno - 1)

    def add(self, rule: str, severity: str, message: str, node: ast.AST,
            hint: str = "") -> None:
        lineno = getattr(node, "lineno", 0)
        if self.suppressed(lineno, rule):
            return
        snippet = ""
        if 1 <= lineno <= len(self.source_lines):
            snippet = self.source_lines[lineno - 1].strip()[:120]
        self.report.findings.append(Finding(
            rule=rule, severity=severity,
            message=f"{message}: `{snippet}`" if snippet else message,
            location=f"{self.rel}:{lineno}",
            hint=hint,
        ))


def module_is_graph(rel: str, source: str) -> bool:
    head = "\n".join(source.splitlines()[:5])
    m = _MODE_RE.search(head)
    if m:
        return m.group(1) == "graph"
    return any(rel.startswith(g) or f"/{g}" in rel for g in GRAPH_MODULES)


# --------------------------------------------------------------------------
# per-function pass
# --------------------------------------------------------------------------


class _FunctionLinter:
    """Lints one function body.  ``graph`` marks traced scope (JL101-103)."""

    def __init__(self, ctx: LintContext, fn: ast.AST, graph: bool):
        self.ctx = ctx
        self.fn = fn
        self.graph = graph
        self.params = {
            a.arg for a in (
                list(fn.args.posonlyargs) + list(fn.args.args)
                + list(fn.args.kwonlyargs)
                + ([fn.args.vararg] if fn.args.vararg else [])
                + ([fn.args.kwarg] if fn.args.kwarg else [])
            )
        } if hasattr(fn, "args") else set()

    # -- helpers -----------------------------------------------------------

    def _walk_shallow(self, node: ast.AST) -> Iterable[ast.AST]:
        """Walk without descending into nested function definitions (they
        are linted separately, with their own scope)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                stack.extend(ast.iter_child_nodes(n))

    # -- rules -------------------------------------------------------------

    def lint(self) -> None:
        if self.graph:
            self._lint_host_sync()
            self._lint_tracer_branch()
            self._lint_wall_clock()
            self._lint_f32_upcast()
        self._lint_key_reuse()

    def _lint_host_sync(self) -> None:
        for n in self._walk_shallow(self.fn):
            if not isinstance(n, ast.Call):
                continue
            name = _dotted(n.func)
            # x.item() / x.tolist() — device fetch, whatever x is
            if isinstance(n.func, ast.Attribute) and n.func.attr in (
                    "item", "tolist") and not name.startswith(("np.", "math.")):
                self.ctx.add(
                    "JL101", "warn",
                    "host sync in a jitted path (device fetch)", n,
                    hint="return the value from the jitted fn and fetch it "
                         "at a logging boundary instead",
                )
            # np.asarray/np.array on a traced parameter
            elif name in ("np.asarray", "np.array", "numpy.asarray",
                          "numpy.array") and n.args:
                a = n.args[0]
                if (isinstance(a, ast.Name) and a.id in self.params) \
                        or _is_jaxish(a):
                    self.ctx.add(
                        "JL101", "warn",
                        "np.asarray on a traced value forces a device "
                        "round-trip inside the graph", n,
                        hint="keep the computation in jnp; convert on host "
                             "after the fetch",
                    )
            # float(jnp.sum(x)) — blocks on the reduction
            elif name in ("float", "int", "bool") and n.args \
                    and _is_jaxish(n.args[0]):
                self.ctx.add(
                    "JL101", "warn",
                    f"{name}() around a jax call blocks dispatch on a "
                    f"device round-trip", n,
                    hint="keep it a jnp scalar in-graph; cast with "
                         ".astype() if a dtype is needed",
                )

    def _lint_tracer_branch(self) -> None:
        for n in self._walk_shallow(self.fn):
            if isinstance(n, (ast.If, ast.While)) and _test_is_traced(n.test):
                self.ctx.add(
                    "JL102", "warn",
                    "Python control flow on a traced value", n,
                    hint="use jnp.where / lax.cond / lax.select — Python "
                         "`if` freezes the branch at trace time (or raises "
                         "ConcretizationTypeError)",
                )

    def _lint_wall_clock(self) -> None:
        for n in self._walk_shallow(self.fn):
            if isinstance(n, ast.Call) and _dotted(n.func) in (
                "time.time", "time.perf_counter", "time.monotonic",
                "time.process_time", "datetime.now", "datetime.datetime.now",
                "datetime.utcnow", "datetime.datetime.utcnow",
            ):
                self.ctx.add(
                    "JL103", "warn",
                    "wall-clock read inside a jitted path traces to a "
                    "constant (the time of TRACING, not execution)", n,
                    hint="measure on host around the dispatch, or thread a "
                         "step counter through the graph",
                )

    def _lint_f32_upcast(self) -> None:
        """JL106: explicit widening to f32 inside traced code — the GA301
        pitfall caught at source level, before lowering.  Flags
        ``x.astype(<f32>)`` and ``jnp.astype(x, <f32>)`` where the target is
        literally float32; dtype-preserving casts (``.astype(p.dtype)``,
        ``policy.compute_dtype``) are not upcasts and pass."""

        def is_f32(node: ast.AST) -> bool:
            if isinstance(node, ast.Constant):
                return node.value in ("float32", "f32")
            name = _dotted(node)
            if name.rsplit(".", 1)[-1] == "float32":
                return True
            # jnp.dtype("float32")
            if isinstance(node, ast.Call) \
                    and _dotted(node.func).rsplit(".", 1)[-1] == "dtype" \
                    and node.args and isinstance(node.args[0], ast.Constant):
                return node.args[0].value in ("float32", "f32")
            return False

        for n in self._walk_shallow(self.fn):
            if not isinstance(n, ast.Call):
                continue
            name = _dotted(n.func)
            target = None
            if name in ("jnp.astype", "jax.numpy.astype") \
                    and len(n.args) >= 2:
                # module form: jnp.astype(x, dtype)
                target = n.args[1]
            elif isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "astype" \
                    and not name.startswith(("jnp.", "jax.", "np.",
                                             "numpy.")) and n.args:
                # method form: x.astype(dtype)
                target = n.args[0]
            for kw in n.keywords or []:
                if kw.arg == "dtype" and target is None \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "astype":
                    target = kw.value
            if target is not None and is_f32(target):
                self.ctx.add(
                    "JL106", "warn",
                    "explicit f32 upcast inside graph scope (the GA301 "
                    "pitfall at source level)", n,
                    hint="widen through policy.compute_dtype / "
                         "grad_accum_dtype instead of a literal float32, "
                         "or baseline a deliberate widening (f32 router, "
                         "softmax accumulator) via --update-baseline",
                )

    def _lint_key_reuse(self) -> None:
        """Same key Name consumed by >= 2 jax.random calls with no
        reassignment between — statement-ordered scan of this body.
        ``if``/``try`` branches are mutually exclusive at runtime, so the
        use-timeline FORKS there and re-merges after (one consumer per
        branch is not reuse)."""

        def shallow(stmt: ast.AST) -> Iterable[ast.AST]:
            # nested defs are linted as their own functions; descending here
            # would merge sibling closures' key uses into one timeline
            stack = [stmt]
            while stack:
                n = stack.pop()
                yield n
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                stack.extend(ast.iter_child_nodes(n))

        def check_uses(node: ast.AST, used: dict[str, ast.Call]) -> None:
            for n in shallow(node):
                if not isinstance(n, ast.Call):
                    continue
                name = _dotted(n.func)
                if not name.startswith(("jax.random.", "jrandom.", "jr.")):
                    continue
                tail = name.rsplit(".", 1)[-1]
                if tail in _KEY_MAKERS or tail in ("split", "fold_in"):
                    # split/fold_in DERIVE keys: feeding one base key to
                    # many fold_in(key, i) calls is the idiom, not the bug
                    continue
                if not n.args or not isinstance(n.args[0], ast.Name):
                    continue
                key = n.args[0].id
                if key in used:
                    self.ctx.add(
                        "JL104", "warn",
                        f"PRNG key `{key}` reused by a second "
                        f"jax.random sampler without split/fold_in",
                        n,
                        hint="derive fresh keys: `k1, k2 = "
                             "jax.random.split(key)` (reusing a key "
                             "correlates the two draws)",
                    )
                else:
                    used[key] = n

        def clear_rebinds(node: ast.AST, used: dict[str, ast.Call]) -> None:
            for n in shallow(node):
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    tgts = (n.targets if isinstance(n, ast.Assign)
                            else [n.target])
                    for t in tgts:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                used.pop(leaf.id, None)
                elif isinstance(n, ast.For):
                    for leaf in ast.walk(n.target):
                        if isinstance(leaf, ast.Name):
                            used.pop(leaf.id, None)

        def scan(body: list[ast.stmt], used: dict[str, ast.Call]) -> None:
            for stmt in body:
                if isinstance(stmt, ast.If):
                    check_uses(stmt.test, used)
                    u1, u2 = dict(used), dict(used)
                    scan(stmt.body, u1)
                    scan(stmt.orelse, u2)
                    used.clear()
                    used.update({**u1, **u2})
                    continue
                if isinstance(stmt, ast.Try):
                    scan(stmt.body, used)
                    u_h = dict(used)
                    for h in stmt.handlers:
                        scan(h.body, u_h)
                    used.update(u_h)
                    scan(stmt.orelse, used)
                    scan(stmt.finalbody, used)
                    continue
                # simple (or loop/with) statement: uses first (the RHS
                # evaluates before targets bind), then rebinds clear
                check_uses(stmt, used)
                clear_rebinds(stmt, used)

        body = getattr(self.fn, "body", [])
        scan(body if isinstance(body, list) else [], {})


def _test_is_traced(test: ast.AST) -> bool:
    """True when an if/while test is visibly a jax value: a jnp/jax call, a
    comparison with one, or a boolean combination thereof."""
    #: metadata queries that return Python values even on tracers
    static_tails = {"ndim", "isinstance", "len", "dtype", "issubdtype",
                    "result_type", "promote_types", "can_cast", "shape",
                    "size", "isdtype"}

    def _static(call: ast.AST) -> bool:
        return (_dotted(call.func).rsplit(".", 1)[-1]  # type: ignore
                in static_tails)

    if _is_jaxish(test):
        # jnp.any(...) etc. — except explicitly-static metadata queries
        return not _static(test)
    if isinstance(test, ast.Compare):
        sides = [test.left, *test.comparators]
        if any(_is_jaxish(s) and _static(s) for s in sides):
            return False
        return any(_is_jaxish(c) for c in sides)
    if isinstance(test, ast.BoolOp):
        return any(_test_is_traced(v) for v in test.values)
    if isinstance(test, ast.UnaryOp):
        return _test_is_traced(test.operand)
    return False


# --------------------------------------------------------------------------
# module pass: scope resolution + donated-buffer rule
# --------------------------------------------------------------------------


def _transform_wrapped(tree: ast.Module) -> set[str]:
    """Function names passed to (or decorated with) a jax transform anywhere
    in the module — graph scope even inside host modules."""
    graph: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in n.decorator_list:
                head = dec.func if isinstance(dec, ast.Call) else dec
                if _dotted(head).rsplit(".", 1)[-1] in _TRANSFORMS:
                    graph.add(n.name)
        if isinstance(n, ast.Call):
            tail = _dotted(n.func).rsplit(".", 1)[-1]
            if tail in _TRANSFORMS:
                for a in n.args[:1]:
                    if isinstance(a, ast.Name):
                        graph.add(a.id)
    return graph


def _lint_donated_reuse(ctx: LintContext, tree: ast.Module) -> None:
    """JL105: donated callable's argument read again afterwards, per
    function body, source order."""
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        donating: set[str] = set()
        donated_vars: dict[str, int] = {}  # name -> line of the donation
        for stmt in fn.body if isinstance(fn.body, list) else []:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                    callee = _dotted(n.value.func)
                    is_donating = callee.rsplit(".", 1)[-1] in \
                        _DONATING_BUILDERS or (
                            callee.rsplit(".", 1)[-1] == "jit"
                            and any(kw.arg in ("donate_argnums", "donate")
                                    for kw in n.value.keywords))
                    if is_donating:
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                donating.add(t.id)
            # a call to a donating fn marks its Name args donated
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                        and n.func.id in donating:
                    for a in n.args:
                        if isinstance(a, ast.Name):
                            donated_vars[a.id] = n.lineno
            # reads of donated names AFTER the donating call
            for n in ast.walk(stmt):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in donated_vars \
                        and n.lineno > donated_vars[n.id]:
                    ctx.add(
                        "JL105", "warn",
                        f"`{n.id}` read after being passed to a donating "
                        f"call (its buffer may already be reused)", n,
                        hint="rebind the result over the donated name "
                             "(`params, ... = step(params, ...)`) before "
                             "any further use",
                    )
                    donated_vars.pop(n.id)
            # rebinds clear donation
            for n in ast.walk(stmt):
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    tgts = (n.targets if isinstance(n, ast.Assign)
                            else [n.target])
                    for t in tgts:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                donated_vars.pop(leaf.id, None)


def lint_file(path: Path, package_root: Path) -> AuditReport:
    source = path.read_text()
    rel = path.relative_to(package_root).as_posix()
    ctx = LintContext(
        path=path, rel=rel, source_lines=source.splitlines(),
        graph_default=module_is_graph(rel, source),
    )
    ctx.report.config = rel
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        ctx.report.add("JL000", "error", f"unparseable: {e}",
                       location=f"{rel}:{e.lineno or 0}")
        return ctx.report
    wrapped = _transform_wrapped(tree)
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            graph = ctx.graph_default or fn.name in wrapped
            _FunctionLinter(ctx, fn, graph).lint()
    _lint_donated_reuse(ctx, tree)
    return ctx.report


def lint_package(
    root: Optional[Path] = None,
    *,
    files: Optional[list[Path]] = None,
) -> AuditReport:
    """Lint the whole package (or an explicit file list).  ``root`` defaults
    to the installed ``neuronx_distributed_training_tpu`` package dir."""
    if root is None:
        root = Path(__file__).resolve().parent.parent
    report = AuditReport(config=str(root))
    targets = files if files is not None else sorted(root.rglob("*.py"))
    for f in targets:
        if "analysis" in f.relative_to(root).parts[:1]:
            # the linter's own fixtures/baselines stay out of scope; the
            # analysis package is host-side tooling by definition
            continue
        sub = lint_file(f, root)
        report.findings.extend(sub.findings)
    report.stats["files_linted"] = len(targets)
    return report


# --------------------------------------------------------------------------
# ratchet baseline
# --------------------------------------------------------------------------

BASELINE_PATH = Path(__file__).resolve().parent / "jaxlint_baseline.json"


def fingerprint(f: Finding) -> str:
    """Line-number-free identity: rule + file + the code snippet from the
    message (stable across unrelated edits above the finding)."""
    file = f.location.rsplit(":", 1)[0]
    snippet = f.message.split("`")[1] if "`" in f.message else ""
    return f"{f.rule}|{file}|{snippet}"


def load_baseline(path: Path = BASELINE_PATH) -> list[str]:
    if not path.exists():
        return []
    return list(json.loads(path.read_text()).get("findings", []))


def write_baseline(report: AuditReport, path: Path = BASELINE_PATH) -> None:
    """Sorted AND deduplicated: reruns over an unchanged tree are
    byte-stable, and repeated identical snippets in one file (which share a
    line-number-free fingerprint) collapse to the one entry the ratchet can
    actually match."""
    path.write_text(json.dumps(
        {"comment": "jaxlint ratchet baseline — may only shrink; "
                    "regenerate with tools/preflight_audit.py "
                    "--update-baseline",
         "findings": sorted({fingerprint(f) for f in report.findings})},
        indent=1,
    ) + "\n")


def apply_ratchet(report: AuditReport,
                  baseline: list[str]) -> tuple[AuditReport, list[str]]:
    """Split lint findings against the baseline.

    Returns ``(fresh_report, stale_entries)``: ``fresh_report`` holds only
    NEW findings (escalated to error — the ratchet's fail condition), and
    ``stale_entries`` are baseline lines that matched nothing (the code got
    cleaner; the baseline must shrink to match, so staleness fails too).

    The baseline is a SET: fingerprints are line-number-free, so repeated
    identical snippets in one file share one entry and all match it (the
    file stores entries deduplicated — ``write_baseline``)."""
    base = set(baseline)
    matched: set[str] = set()
    fresh = AuditReport(config=report.config, stats=dict(report.stats))
    for f in report.findings:
        fp = fingerprint(f)
        if fp in base:
            matched.add(fp)
        else:
            fresh.findings.append(Finding(
                rule=f.rule, severity="error",
                message=f.message, location=f.location,
                hint=f.hint or "new finding (not in the committed baseline): "
                               "fix it or suppress with # jaxlint: disable=",
            ))
    stale = sorted(base - matched)
    fresh.stats["baselined"] = len(report.findings) - len(fresh.findings)
    fresh.stats["stale_baseline_entries"] = len(stale)
    return fresh, stale
