"""Perf contracts: a measured-runtime regression ratchet with noise bands.

Graph contracts (``analysis.graph_contract``) gate what the compiled step
*contains*; trace analytics (``telemetry.trace_analysis``) measure where
device time *went*.  This module closes the loop the ROADMAP demands: the
measured numbers themselves become a committed contract, so a step-time,
overlap, or bubble regression fails CI with a *named* finding instead of
silently eroding the recorded baselines.

- **facts** — the canonical measured-runtime record of one workload:
  step time, MFU/throughput, achieved overlap per collective class,
  exposed collective seconds, and the measured pipeline bubble fraction
  (``telemetry.step_timeline``).  Extracted uniformly from a ``bench.py``
  JSON line, a run dir (``run_summary.json`` + ``metrics.jsonl`` +
  ``trace_summary.json``), or a bare ``trace_summary.json``.
- **baselines** — committed per-topology snapshots under
  ``analysis/perf_baselines/<key>.json`` carrying the facts plus explicit
  *noise bands* (runtime is noisy where compile artifacts are exact; every
  band is visible in-file, not folded into the code).
- **the differ** — ``diff_facts`` explains a regression in subsystem terms
  (PC101 step time, PC102 throughput/MFU, PC201 per-class achieved
  overlap, PC202 exposed collective seconds naming the collective class,
  PC203 engineered-overlap ordering — multi-bucket + prefetch ZeRO-1
  variants must expose at most the monolithic regather's collective
  seconds within one ``--overlap-sweep`` run, PC204 per-class/per-axis
  achieved interconnect bandwidth dropping beyond its band
  (``telemetry.comms``), PC301 measured bubble growth, PC302
  measured-vs-predicted bubble outside the calibration band, PC401
  cost-model residual drift, PC501 measured peak-HBM growth, PC502
  measured peak HBM beyond the planner's predicted total x the calibration
  band — PC203/PC302/PC502 are baseline-independent);
  improvements are PC110 info findings the snapshot can tighten to.
- **the ratchet** — same workflow as graph contracts:
  ``tools/perf_contract.py --check`` fails on any error finding;
  ``--update-baselines`` commits improvements silently and refuses to
  commit a regression without ``--justify`` (recorded in-file).
- **residuals** — ``residual_report`` audits the autotune cost model term
  by term (compute/comms/bubble) against a measured plan, the record
  ``bench.py --plan-topk`` persists per benched plan and
  ``tools/plan.py --calibrate-from`` surfaces next to the priors it
  replaces.

``docs/observability.md`` ("Perf contracts") documents the workflow.
"""

from __future__ import annotations

import json
import math
from pathlib import Path
from typing import Any, Mapping, Optional

from neuronx_distributed_training_tpu.analysis.report import AuditReport

#: committed measured-runtime baselines, one per (topology, workload) key
BASELINES_DIR = Path(__file__).resolve().parent / "perf_baselines"

#: facts schema version — the differ refuses to compare across versions
FACTS_VERSION = 1

#: default noise bands.  Runtime numbers jitter run-to-run (scheduler,
#: clocks, host load) where compile artifacts don't; each band says how much
#: drift is noise and is recorded IN the baseline file so a topology can
#: carry its own (CPU smoke baselines need far wider time bands than a
#: pinned TPU chip).
DEFAULT_NOISE: dict[str, float] = {
    "step_time_frac": 0.25,       # step-time growth beyond this fails
    "throughput_frac": 0.25,      # tokens/sec shrink beyond this fails
    "mfu_abs": 0.03,              # MFU points (fraction) lost beyond this
    "overlap_abs": 0.10,          # per-class achieved-overlap drop
    "exposed_frac": 0.50,         # per-class exposed-seconds growth...
    "exposed_min_seconds": 0.002,  # ...with an absolute floor under it
    "bubble_abs": 0.08,           # measured bubble-fraction growth; ALSO the
                                  # measured-vs-predicted calibration band
    "residual_frac": 0.30,        # cost-model total-residual drift
    "peak_hbm_frac": 0.10,        # measured peak-HBM growth beyond this fails
    "hbm_predicted_frac": 0.25,   # measured peak vs planner-predicted HBM:
                                  # the calibration band PC502 gates on
                                  # (baseline-independent; the analytic model
                                  # documents +-15% agreement, this band adds
                                  # runtime/fragmentation slack)
    "sweep_order_frac": 0.10,     # schedule-sweep ordering slack: PC303
                                  # fails when interleaved measures slower
                                  # than plain 1f1b beyond this fraction
                                  # (the planner prices it at-or-below)
    "overlap_order_frac": 0.25,   # overlap-sweep ordering slack: PC203 fails
                                  # when the engineered (multi-bucket +
                                  # prefetch) variant exposes more collective
                                  # seconds than the monolithic regather
                                  # beyond this fraction. Wider than
                                  # sweep_order_frac because exposed seconds
                                  # come from trace intervals, which jitter
                                  # harder under host scheduling than whole
                                  # step times do.
    "comms_bw_frac": 0.50,        # per-class/per-axis achieved interconnect
                                  # bandwidth drop beyond this fraction fails
                                  # PC204 (telemetry.comms) — wide by
                                  # default: wire timings jitter harder than
                                  # step times, and committed CPU baselines
                                  # widen it further in-file
}

#: which subsystem a measured collective class's regression points at —
#: measured traces know kinds, not mesh axes, so the finding names the
#: likely axes and the code that owns them (the same kind->axis table the
#: cost model and graph contracts share: utils.debug.AXIS_COLLECTIVE_KINDS)
CLASS_HINTS: dict[str, tuple[str, str]] = {
    "reduce-scatter": ("dp", "ZeRO-1 gradient reduce-scatter stopped hiding "
                             "under compute; check optim/zero1 and the "
                             "update-boundary issue order"),
    "all-gather": ("dp/tp", "ZeRO-1 parameter all-gather / SP layer-gather "
                            "overlap regressed; check optim/zero1 and the "
                            "layer PartitionSpecs"),
    "all-reduce": ("dp/tp", "gradient/loss or plain-TP layer reduction "
                            "overlap regressed; check trainer/step.py and "
                            "the layer collectives"),
    "collective-permute": ("pp/cp", "pipeline stage-hop / ring-attention "
                                    "kv-pass overlap regressed; check "
                                    "parallel/pipeline.py scheduling"),
    "all-to-all": ("ep/cp", "expert dispatch / ulysses head-exchange "
                            "overlap regressed; check ops/moe.py and "
                            "parallel/ulysses.py"),
}

_RATCHET_HINT = (
    "declare a deliberate change: tools/perf_contract.py --update-baselines "
    "--justify '<why the measured number moved>' (the ratchet only "
    "improves silently)"
)


class PerfContractError(RuntimeError):
    """A facts source could not be read, or the ratchet refused an update."""


def _num(v: Any) -> Optional[float]:
    if isinstance(v, bool) or v is None:
        return None
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


# --------------------------------------------------------------------------
# facts extraction
# --------------------------------------------------------------------------


def _class_record(entry: Any) -> Optional[dict[str, Any]]:
    """Normalize one overlap_by_class value: trace summaries carry full
    {wire,hidden,exposed,achieved_overlap} records, bench lines carry bare
    fractions."""
    if isinstance(entry, Mapping):
        out = {}
        for src, dst in (("achieved_overlap", "achieved_overlap"),
                         ("exposed_seconds", "exposed_seconds"),
                         ("wire_seconds", "wire_seconds")):
            v = _num(entry.get(src))
            if v is not None:
                out[dst] = v
        return out or None
    v = _num(entry)
    return {"achieved_overlap": v} if v is not None else None


def _overlap_classes(mapping: Any) -> dict[str, dict[str, Any]]:
    out: dict[str, dict[str, Any]] = {}
    for kind, entry in dict(mapping or {}).items():
        rec = _class_record(entry)
        if rec:
            out[str(kind)] = rec
    return out


def _comms_facts(block: Any) -> Optional[dict[str, Any]]:
    """Normalize a comms block (telemetry.comms) into canonical facts.

    Accepts either shape the observatory emits: a bench/comms_bench facts
    block ({"classes": ..., "axes": ...}) or the trainer's trace/run summary
    ``comms`` section ({"classes": {kind: {achieved_gbps, efficiency, ...}}}).
    Returns {"classes", "axes"} with only the numeric fields PC204 diffs,
    or None when the block carries nothing usable."""
    if not isinstance(block, Mapping):
        return None
    classes: dict[str, dict[str, float]] = {}
    for kind, entry in dict(block.get("classes") or {}).items():
        if not isinstance(entry, Mapping):
            continue
        rec = {}
        for field in ("achieved_gbps", "efficiency"):
            v = _num(entry.get(field))
            if v is not None:
                rec[field] = v
        if rec:
            classes[str(kind)] = rec
    axes: dict[str, dict[str, float]] = {}
    for axis, entry in dict(block.get("axes") or {}).items():
        if not isinstance(entry, Mapping):
            continue
        rec = {}
        for field in ("bandwidth_gbps", "latency_us", "bandwidth_ratio"):
            v = _num(entry.get(field))
            if v is not None:
                rec[field] = v
        if rec:
            axes[str(axis)] = rec
    if not classes and not axes:
        return None
    out: dict[str, Any] = {"classes": classes}
    if axes:
        out["axes"] = axes
    peak = _num(block.get("peak_bandwidth_gbps"))
    if peak is not None:
        out["peak_bandwidth_gbps"] = peak
    return out


def perf_facts_from_bench(payload: Mapping[str, Any]) -> dict[str, Any]:
    """Canonical facts out of one ``bench.py`` headline JSON line."""
    mfu = _num(payload.get("mfu"))
    if mfu is None and _num(payload.get("value")) is not None \
            and payload.get("unit") == "percent_mfu":
        mfu = _num(payload.get("value")) / 100.0
    pipe = payload.get("pipeline") if isinstance(
        payload.get("pipeline"), Mapping) else {}
    return {
        "version": FACTS_VERSION,
        "workload": {
            "source": "bench",
            "metric": payload.get("metric"),
            "device": payload.get("device"),
            "regime": payload.get("regime"),
            "seq_len": payload.get("seq_len"),
            "num_layers": payload.get("num_layers"),
            "schedule": payload.get("pipeline_schedule"),
        },
        "step_time_ms": _num(payload.get("ms_per_step")),
        "mfu": mfu,
        "tokens_per_sec": _num(payload.get("tokens_per_sec_per_chip")),
        "achieved_overlap": _num(payload.get("achieved_overlap")),
        "exposed_collective_seconds": _num(
            payload.get("exposed_collective_seconds")),
        "overlap_by_class": _overlap_classes(payload.get("overlap_by_class")),
        "bubble_fraction_measured": _num(
            payload.get("bubble_fraction_measured")
            if payload.get("bubble_fraction_measured") is not None
            else pipe.get("bubble_fraction_measured")),
        "bubble_fraction_predicted": _num(
            payload.get("bubble_fraction_predicted")),
        "peak_hbm_bytes": _num(payload.get("peak_hbm_bytes")),
        "hbm_headroom_fraction": _num(payload.get("hbm_headroom_fraction")),
        "predicted_hbm_bytes": _num(payload.get("predicted_hbm_bytes")),
        "residuals": payload.get("residuals")
        if isinstance(payload.get("residuals"), Mapping) else None,
        "schedule_sweep": _sweep_rows(payload.get("schedule_sweep")),
        "overlap_sweep": _overlap_rows(payload.get("overlap_sweep")),
        "comms": _comms_facts(payload.get("comms")),
    }


def _overlap_rows(sweep: Any) -> Optional[list[dict[str, Any]]]:
    """Normalize a ``bench.py --overlap-sweep`` block into canonical
    per-variant rows (None when the payload carries no sweep)."""
    if not isinstance(sweep, Mapping):
        return None
    rows = []
    for row in sweep.get("rows") or []:
        if not isinstance(row, Mapping) or not row.get("variant"):
            continue
        rows.append({
            "variant": str(row["variant"]),
            "n_buckets": int(row.get("n_buckets") or 0),
            "step_time_ms": _num(row.get("ms_per_step")),
            "exposed_collective_seconds": _num(
                row.get("exposed_collective_seconds")),
            "achieved_overlap": _num(row.get("achieved_overlap")),
            "overlap_by_class": _overlap_classes(
                row.get("overlap_by_class")),
        })
    return rows or None


def _sweep_rows(sweep: Any) -> Optional[list[dict[str, Any]]]:
    """Normalize a ``bench.py --schedule-sweep`` block into canonical
    per-schedule rows (None when the payload carries no sweep)."""
    if not isinstance(sweep, Mapping):
        return None
    rows = []
    for row in sweep.get("rows") or []:
        if not isinstance(row, Mapping) or not row.get("schedule"):
            continue
        rows.append({
            "schedule": str(row["schedule"]),
            "step_time_ms": _num(row.get("ms_per_step")),
            "bubble_fraction_measured": _num(
                row.get("bubble_fraction_measured")),
            "bubble_fraction_predicted": _num(
                row.get("bubble_fraction_predicted")),
            "bubble_residual": _num(row.get("bubble_residual")),
        })
    return rows or None


def perf_facts_from_trace_summary(summary: Mapping[str, Any]
                                  ) -> dict[str, Any]:
    """Facts out of a bare ``trace_summary.json`` payload (no step time /
    MFU — those need the run's metrics or a bench line)."""
    pipe = summary.get("pipeline") if isinstance(
        summary.get("pipeline"), Mapping) else {}
    return {
        "version": FACTS_VERSION,
        "workload": {
            "source": "trace",
            "schedule": pipe.get("schedule"),
        },
        "step_time_ms": None,
        "mfu": None,
        "tokens_per_sec": None,
        "achieved_overlap": _num(summary.get("achieved_overlap")),
        "exposed_collective_seconds": _num(
            summary.get("exposed_collective_seconds")),
        "overlap_by_class": _overlap_classes(summary.get("overlap_by_class")),
        "bubble_fraction_measured": _num(pipe.get("bubble_fraction_measured")),
        "bubble_fraction_predicted": _num(
            pipe.get("bubble_fraction_predicted")),
        "peak_hbm_bytes": None,
        "hbm_headroom_fraction": None,
        "predicted_hbm_bytes": None,
        "residuals": None,
        "comms": _comms_facts(summary.get("comms")),
    }


def perf_facts_from_run(run_dir: str | Path) -> dict[str, Any]:
    """Facts out of a training run dir: ``run_summary.json`` run facts +
    ``trace_summary.json`` measurements + the last ``metrics.jsonl``
    boundary record (throughput/MFU)."""
    run_dir = Path(run_dir)
    try:
        run_summary = json.loads((run_dir / "run_summary.json").read_text())
    except (OSError, ValueError) as e:
        raise PerfContractError(
            f"no readable run_summary.json under {run_dir}: {e}") from e
    trace = {}
    try:
        trace = json.loads((run_dir / "trace_summary.json").read_text())
    except (OSError, ValueError):
        pass
    last_metrics: dict[str, Any] = {}
    try:
        for line in (run_dir / "metrics.jsonl").read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail from a live run
            if isinstance(rec, dict):
                last_metrics.update(
                    {k: v for k, v in rec.items()
                     if isinstance(v, (int, float))})
    except OSError:
        pass
    facts = perf_facts_from_trace_summary(trace)
    tokens = _num(last_metrics.get("tokens_per_sec_per_chip"))
    seq = _num(run_summary.get("seq_len"))
    gbs = _num(run_summary.get("global_batch_size"))
    chips = _num(run_summary.get("n_chips"))
    step_ms = None
    if tokens and seq and gbs and chips:
        # one source of truth: step time derives from the same throughput
        # window MFU does (tokens/sec/chip x chips = tokens/sec)
        step_ms = gbs * seq / (tokens * chips) * 1e3
    facts.update({
        "workload": {
            "source": "run",
            "model_family": run_summary.get("model_family"),
            "n_chips": run_summary.get("n_chips"),
            "seq_len": run_summary.get("seq_len"),
            "schedule": run_summary.get("pipeline_schedule"),
        },
        "step_time_ms": step_ms,
        "mfu": _num(last_metrics.get("mfu")),
        "tokens_per_sec": tokens,
        "bubble_fraction_predicted": _num(
            run_summary.get("bubble_fraction_predicted"))
        if _num(run_summary.get("bubble_fraction_predicted")) is not None
        else facts.get("bubble_fraction_predicted"),
    })
    if facts.get("bubble_fraction_measured") is None:
        facts["bubble_fraction_measured"] = _num(
            run_summary.get("bubble_fraction_measured"))
    # measured memory (telemetry.memory): the live allocator stream's
    # worst-device watermark wins; the memory_summary.json profile is the
    # fallback (per-device units either way — what PC501/PC502 compare)
    facts["hbm_headroom_fraction"] = _num(
        last_metrics.get("memory/hbm_headroom_fraction"))
    peak = _num(last_metrics.get("memory/peak_hbm_bytes"))
    predicted = None
    try:
        mem = json.loads((run_dir / "memory_summary.json").read_text())
    except (OSError, ValueError):
        mem = {}
    if isinstance(mem, dict) and mem:
        if peak is None:
            peak = _num((mem.get("sampled") or {}).get("peak_hbm_bytes"))
        if peak is None:
            by_dev = (mem.get("profile") or {}).get("by_device") or {}
            vals = [_num(v) for v in by_dev.values()]
            vals = [v for v in vals if v]
            if vals:
                peak = max(vals)
            else:
                # the profile total spans ALL local devices — divide so
                # PC501/PC502 stay in the per-device units the baselines
                # and the planner's predicted total use
                total = _num((mem.get("profile") or {}).get("total_bytes"))
                n_dev = max(int((mem.get("profile") or {}).get(
                    "num_devices", 1) or 1), 1)
                peak = total / n_dev if total else None
        predicted = _num((mem.get("predicted") or {}).get("total"))
    facts["peak_hbm_bytes"] = peak
    facts["predicted_hbm_bytes"] = predicted
    if facts.get("comms") is None:
        # the trainer writes the comms section into run_summary.json even
        # when no trace window fired (the in-loop join needs only metrics)
        facts["comms"] = _comms_facts(run_summary.get("comms"))
    return facts


def load_facts(source: Any) -> dict[str, Any]:
    """Facts from any accepted source: an already-canonical facts mapping, a
    bench JSON line (mapping or file), a run dir, a ``trace_summary.json``,
    or a ``.jsonl`` whose LAST parseable line is a bench record."""
    if isinstance(source, Mapping):
        doc = dict(source)
    else:
        p = Path(source)
        if p.is_dir():
            if (p / "run_summary.json").exists():
                return perf_facts_from_run(p)
            if (p / "trace_summary.json").exists():
                doc = json.loads((p / "trace_summary.json").read_text())
            else:
                raise PerfContractError(
                    f"{p}: no run_summary.json or trace_summary.json — "
                    f"nothing to extract perf facts from")
        else:
            try:
                text = p.read_text()
            except OSError as e:
                raise PerfContractError(f"unreadable facts source {p}: {e}") \
                    from e
            doc = None
            if p.suffix == ".jsonl":
                for line in reversed(text.splitlines()):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                        break
                    except ValueError:
                        continue
            else:
                try:
                    doc = json.loads(text)
                except ValueError:
                    # a bench stdout capture: the JSON line is the last
                    # parseable line (the tools/_jsonout contract)
                    for line in reversed(text.splitlines()):
                        try:
                            doc = json.loads(line.strip())
                            break
                        except ValueError:
                            continue
            if not isinstance(doc, dict):
                raise PerfContractError(
                    f"{p}: no parseable JSON object found")
    if doc.get("version") == FACTS_VERSION and "workload" in doc:
        return doc
    if "metric" in doc and "value" in doc:
        return perf_facts_from_bench(doc)
    if "overlap_by_class" in doc or "top_ops" in doc:
        return perf_facts_from_trace_summary(doc)
    raise PerfContractError(
        "unrecognized facts source: expected a bench JSON line, a "
        "trace_summary.json, a run dir, or a canonical facts record")


def default_key(facts: Mapping[str, Any]) -> str:
    """Baseline key for a facts record: the device identity slug (the
    baseline is per-topology) plus the source kind."""
    w = dict(facts.get("workload") or {})
    dev = str(w.get("device") or w.get("model_family") or "unknown")
    slug = "".join(c if c.isalnum() else "_" for c in dev.lower()).strip("_")
    while "__" in slug:
        slug = slug.replace("__", "_")
    src = str(w.get("source") or "bench")
    if src == "bench" and w.get("metric") == "pipeline_schedule_sweep":
        # the schedule sweep is its own workload: it must never be diffed
        # against the single-chip headline baseline (PC001 would fire)
        return f"{slug}_schedule_sweep"
    if src == "bench" and w.get("metric") == "zero1_overlap_sweep":
        # likewise the engineered-overlap sweep (bench.py --overlap-sweep)
        return f"{slug}_overlap_sweep"
    if src == "bench" and w.get("metric") == "comms_bench_sweep":
        # and the interconnect sweep (tools/comms_bench.py)
        return f"{slug}_comms"
    return f"{slug}_{src}" if src != "bench" else f"{slug}_bench"


# --------------------------------------------------------------------------
# the semantic differ (PC findings)
# --------------------------------------------------------------------------


def _fmt(v: Optional[float], nd: int = 4) -> str:
    return "n/a" if v is None else f"{round(float(v), nd):g}"


def calibration_findings(facts: Mapping[str, Any],
                         noise: Mapping[str, float],
                         report: AuditReport) -> None:
    """Baseline-independent gates: measured vs the planner's OWN prediction.

    PC302 — the measured bubble fraction must stay within the calibration
    band of the predicted fill/drain price (ROADMAP item 1's success metric
    as a gate).  PC502 — the measured peak HBM must stay within the
    calibration band of the planner's predicted per-device total: a
    workload whose real residency outruns the HBM model's pricing fails
    here even on a freshly baselined topology (the model's OOM pruning is
    lying about this workload)."""
    m_hbm = _num(facts.get("peak_hbm_bytes"))
    p_hbm = _num(facts.get("predicted_hbm_bytes"))
    if m_hbm is not None and p_hbm:
        band = float(noise.get("hbm_predicted_frac",
                               DEFAULT_NOISE["hbm_predicted_frac"]))
        if m_hbm > p_hbm * (1.0 + band):
            report.add(
                "PC502", "error",
                f"measured peak HBM {m_hbm / 1024**3:.3f}G exceeds the "
                f"planner's predicted {p_hbm / 1024**3:.3f}G by more than "
                f"the {100 * band:.0f}% calibration band "
                f"({m_hbm / p_hbm:.2f}x)",
                hint="the HBM model under-prices this workload — inspect "
                     "memory_summary.json's attribution for the class "
                     "carrying the excess, and recalibrate the transient "
                     "constants with tools/plan.py --calibrate-from "
                     "memory_summary.json (docs/observability.md 'Memory "
                     "observability')",
            )
    _sweep_findings(facts, noise, report)
    _overlap_sweep_findings(facts, noise, report)
    measured = _num(facts.get("bubble_fraction_measured"))
    predicted = _num(facts.get("bubble_fraction_predicted"))
    if measured is None or predicted is None:
        return
    band = float(noise.get("bubble_abs", DEFAULT_NOISE["bubble_abs"]))
    if measured > predicted + band:
        sched = (facts.get("workload") or {}).get("schedule")
        report.add(
            "PC302", "error",
            f"measured pipeline bubble fraction {_fmt(measured)} exceeds "
            f"the planner's prediction {_fmt(predicted)} by more than the "
            f"{_fmt(band)} calibration band"
            + (f" (schedule {sched})" if sched else ""),
            hint="the executor is idling beyond the priced fill/drain "
                 "bubble (straggler stage, masked-tick burn, or a broken "
                 "bubble price) — see trace_summary.json 'pipeline' "
                 "straggler attribution, and parallel/pipeline.py "
                 "bubble_multiplier if the price itself is wrong",
        )


def _sweep_findings(facts: Mapping[str, Any], noise: Mapping[str, float],
                    report: AuditReport) -> None:
    """Baseline-independent gates over ``bench.py --schedule-sweep`` rows.

    Per row: PC302 — each schedule's measured bubble fraction must stay
    within the calibration band of its own prediction.  Across rows:
    PC303 — the measured wall-clock ordering must match the planner's
    pricing: ``1f1b-interleaved`` at or below plain ``1f1b`` (within the
    ``sweep_order_frac`` noise band).  The lockstep executor lost exactly
    this gate (~1.25x at pp=2/nm=16/vp=2); the work-compacted executor is
    what makes it green."""
    rows = facts.get("schedule_sweep") or []
    band = float(noise.get("bubble_abs", DEFAULT_NOISE["bubble_abs"]))
    by_sched: dict[str, Mapping[str, Any]] = {}
    for row in rows:
        if not isinstance(row, Mapping):
            continue
        sched = str(row.get("schedule"))
        by_sched[sched] = row
        m = _num(row.get("bubble_fraction_measured"))
        p = _num(row.get("bubble_fraction_predicted"))
        if m is not None and p is not None and m > p + band:
            report.add(
                "PC302", "error",
                f"[schedule sweep] {sched}: measured bubble fraction "
                f"{_fmt(m)} exceeds its prediction {_fmt(p)} by more than "
                f"the {_fmt(band)} calibration band",
                location=sched,
                hint="parallel/pipeline.py work_table prices this "
                     "schedule's compacted execution — the executor is "
                     "idling (or burning masked work) beyond it",
            )
    f1b = by_sched.get("1f1b")
    il = by_sched.get("1f1b-interleaved")
    if f1b and il:
        a = _num(f1b.get("step_time_ms"))
        b = _num(il.get("step_time_ms"))
        oband = float(noise.get("sweep_order_frac",
                                DEFAULT_NOISE["sweep_order_frac"]))
        if a and b and b > a * (1.0 + oband):
            report.add(
                "PC303", "error",
                f"[schedule sweep] measured ordering contradicts the "
                f"planner's pricing: 1f1b-interleaved {_fmt(b, 2)}ms > "
                f"plain 1f1b {_fmt(a, 2)}ms x (1 + {oband:g}) — the "
                f"interleave's priced bubble win is not realized in "
                f"wall-clock",
                location="1f1b-interleaved",
                hint="the work-compacted executor (parallel/pipeline.py "
                     "_onef1b_body) is supposed to cash the interleave's "
                     "fill/drain win — check the m-major work-table "
                     "ordering and the per-kind cond gates",
            )


def _overlap_sweep_findings(facts: Mapping[str, Any],
                            noise: Mapping[str, float],
                            report: AuditReport) -> None:
    """Baseline-independent gates over ``bench.py --overlap-sweep`` rows.

    PC203 — within one sweep run, the engineered configuration (multiple
    buckets, i.e. a real prefetch-stagger chain) must EXPOSE at most the
    monolithic (``off``) variant's collective seconds (within the
    ``overlap_order_frac`` band, above the ``exposed_min_seconds`` floor):
    overall AND per dp collective class.  Only rows with ``n_buckets > 1``
    are gated: a single-bucket row has no stagger chain (nothing to
    prefetch ahead of), so it carries no ordering claim — it is still
    ratcheted row-by-row against the committed baseline (PC101/PC202 in
    ``diff_facts``), just not ordered against ``off`` here.  This is the
    engineered-overlap claim as a gate — bucketed ZeRO-1 regathers + the
    prefetch stagger must not expose MORE wire time than the monolithic
    gather they replace."""
    rows = facts.get("overlap_sweep") or []
    by_var = {str(r.get("variant")): r for r in rows
              if isinstance(r, Mapping)}
    off = by_var.get("off")
    if not off:
        return
    band = float(noise.get("overlap_order_frac",
                           DEFAULT_NOISE["overlap_order_frac"]))
    floor = float(noise.get("exposed_min_seconds",
                            DEFAULT_NOISE["exposed_min_seconds"]))

    def gate(variant: str, label: str, a: Optional[float],
             b: Optional[float]) -> None:
        if a is None or b is None:
            return
        if b > a * (1.0 + band) and b - a > floor:
            report.add(
                "PC203", "error",
                f"[overlap sweep] {variant}: exposed {label} collective "
                f"seconds {_fmt(b)}s exceed monolithic {_fmt(a)}s x "
                f"(1 + {band:g}) — bucketing exposes MORE wire time than "
                f"the monolithic regather it replaces",
                location=variant,
                hint="optim/overlap.py bucketed_update owes each bucket's "
                     "all-gather an overlap window (the prefetch barrier "
                     "chain) and ONE combined collective per bucket — "
                     "check the zero1-bucket class census in the graph "
                     "contract and the bucket coalescing "
                     "(zero1_bucket_mb)",
            )

    for variant, row in by_var.items():
        if variant == "off" or not isinstance(row, Mapping):
            continue
        if int(row.get("n_buckets") or 0) <= 1:
            continue
        gate(variant, "total",
             _num(off.get("exposed_collective_seconds")),
             _num(row.get("exposed_collective_seconds")))
        oc = _overlap_classes(off.get("overlap_by_class"))
        nc = _overlap_classes(row.get("overlap_by_class"))
        for kind in ("all-gather", "reduce-scatter"):
            if kind in oc and kind in nc:
                gate(variant, kind,
                     _num(oc[kind].get("exposed_seconds")),
                     _num(nc[kind].get("exposed_seconds")))


def diff_facts(old: Mapping[str, Any], new: Mapping[str, Any], *,
               noise: Optional[Mapping[str, float]] = None,
               config_name: str = "") -> AuditReport:
    """Compare fresh measured facts against a committed baseline.

    Error findings are regressions beyond the noise band (the ratchet's
    fail condition); info findings (PC110) are improvements the baseline
    can tighten to.  Every message names the measured quantity, both
    values, and the band it broke."""
    report = AuditReport(config=config_name)
    bands = dict(DEFAULT_NOISE, **(noise or {}))

    if old.get("version") != new.get("version"):
        report.add(
            "PC001", "error",
            f"facts version changed {old.get('version')} -> "
            f"{new.get('version')}: the committed baseline predates the "
            f"current schema",
            hint="regenerate: tools/perf_contract.py --update-baselines",
        )
        return report
    ow, nw = dict(old.get("workload") or {}), dict(new.get("workload") or {})
    mismatched = {
        k: (ow.get(k), nw.get(k))
        for k in ("device", "seq_len", "num_layers", "schedule", "regime",
                  "n_chips", "model_family")
        if ow.get(k) is not None and nw.get(k) is not None
        and ow.get(k) != nw.get(k)
    }
    if mismatched:
        detail = ", ".join(f"{k}: {a!r} -> {b!r}"
                           for k, (a, b) in sorted(mismatched.items()))
        report.add(
            "PC001", "error",
            f"workload identity changed ({detail}): these measurements are "
            f"not comparable to the committed baseline",
            hint="a deliberate workload change must re-baseline: "
                 "tools/perf_contract.py --update-baselines --justify "
                 "'<why>'",
        )
        return report

    # -- PC101: step time --------------------------------------------------
    a, b = _num(old.get("step_time_ms")), _num(new.get("step_time_ms"))
    if a and b:
        band = bands["step_time_frac"]
        if b > a * (1.0 + band):
            report.add(
                "PC101", "error",
                f"step time grew {_fmt(a, 2)}ms -> {_fmt(b, 2)}ms "
                f"(+{100 * (b / a - 1):.0f}% > {100 * band:.0f}% noise band)",
                hint=_RATCHET_HINT,
            )
        elif b < a * (1.0 - band):
            report.add(
                "PC110", "info",
                f"step time improved {_fmt(a, 2)}ms -> {_fmt(b, 2)}ms — "
                f"tighten the baseline with --update-baselines",
            )

    # -- PC101 per sweep row: schedule-sweep step times ---------------------
    o_rows = {r.get("schedule"): r for r in old.get("schedule_sweep") or []
              if isinstance(r, Mapping)}
    n_rows = {r.get("schedule"): r for r in new.get("schedule_sweep") or []
              if isinstance(r, Mapping)}
    for sched in sorted(set(o_rows) & set(n_rows)):
        a = _num(o_rows[sched].get("step_time_ms"))
        b = _num(n_rows[sched].get("step_time_ms"))
        if a and b:
            band = bands["step_time_frac"]
            if b > a * (1.0 + band):
                report.add(
                    "PC101", "error",
                    f"[schedule sweep] {sched} step time grew "
                    f"{_fmt(a, 2)}ms -> {_fmt(b, 2)}ms "
                    f"(+{100 * (b / a - 1):.0f}% > {100 * band:.0f}% noise "
                    f"band)",
                    location=sched,
                    hint=_RATCHET_HINT,
                )
            elif b < a * (1.0 - band):
                report.add(
                    "PC110", "info",
                    f"[schedule sweep] {sched} step time improved "
                    f"{_fmt(a, 2)}ms -> {_fmt(b, 2)}ms — tighten with "
                    f"--update-baselines",
                )

    # -- PC101/PC202 per overlap-sweep row: step time + exposed seconds ----
    o_rows = {r.get("variant"): r for r in old.get("overlap_sweep") or []
              if isinstance(r, Mapping)}
    n_rows = {r.get("variant"): r for r in new.get("overlap_sweep") or []
              if isinstance(r, Mapping)}
    for variant in sorted(set(o_rows) & set(n_rows)):
        a = _num(o_rows[variant].get("step_time_ms"))
        b = _num(n_rows[variant].get("step_time_ms"))
        if a and b:
            band = bands["step_time_frac"]
            if b > a * (1.0 + band):
                report.add(
                    "PC101", "error",
                    f"[overlap sweep] {variant} step time grew "
                    f"{_fmt(a, 2)}ms -> {_fmt(b, 2)}ms "
                    f"(+{100 * (b / a - 1):.0f}% > {100 * band:.0f}% noise "
                    f"band)",
                    location=variant,
                    hint=_RATCHET_HINT,
                )
            elif b < a * (1.0 - band):
                report.add(
                    "PC110", "info",
                    f"[overlap sweep] {variant} step time improved "
                    f"{_fmt(a, 2)}ms -> {_fmt(b, 2)}ms — tighten with "
                    f"--update-baselines",
                )
        a = _num(o_rows[variant].get("exposed_collective_seconds"))
        b = _num(n_rows[variant].get("exposed_collective_seconds"))
        if a is not None and b is not None:
            band = bands["exposed_frac"]
            floor = bands["exposed_min_seconds"]
            if b > a * (1.0 + band) and b - a > floor:
                report.add(
                    "PC202", "error",
                    f"[overlap sweep] {variant} exposed collective seconds "
                    f"grew {_fmt(a)}s -> {_fmt(b)}s "
                    f"(+{100 * (b / a - 1):.0f}% > {100 * band:.0f}% band)"
                    if a > 0 else
                    f"[overlap sweep] {variant} exposed collective seconds "
                    f"appeared: {_fmt(a)}s -> {_fmt(b)}s",
                    location=variant,
                    hint=_RATCHET_HINT,
                )
            elif b < a * (1.0 - band) and a - b > floor:
                report.add(
                    "PC110", "info",
                    f"[overlap sweep] {variant} exposed collective seconds "
                    f"shrank {_fmt(a)}s -> {_fmt(b)}s — tighten with "
                    f"--update-baselines",
                )

    # -- PC102: MFU / throughput -------------------------------------------
    a, b = _num(old.get("mfu")), _num(new.get("mfu"))
    if a is not None and b is not None:
        band = bands["mfu_abs"]
        if b < a - band:
            report.add(
                "PC102", "error",
                f"MFU fell {_fmt(a)} -> {_fmt(b)} "
                f"(-{a - b:.4f} > {band:g} noise band)",
                hint=_RATCHET_HINT,
            )
        elif b > a + band:
            report.add(
                "PC110", "info",
                f"MFU improved {_fmt(a)} -> {_fmt(b)} — tighten the "
                f"baseline with --update-baselines",
            )
    else:
        a, b = _num(old.get("tokens_per_sec")), _num(new.get("tokens_per_sec"))
        if a and b:
            band = bands["throughput_frac"]
            if b < a * (1.0 - band):
                report.add(
                    "PC102", "error",
                    f"throughput fell {_fmt(a, 1)} -> {_fmt(b, 1)} "
                    f"tokens/sec (-{100 * (1 - b / a):.0f}% > "
                    f"{100 * band:.0f}% noise band)",
                    hint=_RATCHET_HINT,
                )
            elif b > a * (1.0 + band):
                report.add(
                    "PC110", "info",
                    f"throughput improved {_fmt(a, 1)} -> {_fmt(b, 1)} "
                    f"tokens/sec — tighten with --update-baselines",
                )

    # -- PC201/PC202: per-collective-class overlap and exposed seconds -----
    oc = _overlap_classes(old.get("overlap_by_class"))
    ncl = _overlap_classes(new.get("overlap_by_class"))
    for kind in sorted(set(oc) & set(ncl)):
        axes, subsystem = CLASS_HINTS.get(
            kind, ("?", "collective overlap regressed"))
        a = _num(oc[kind].get("achieved_overlap"))
        b = _num(ncl[kind].get("achieved_overlap"))
        if a is not None and b is not None:
            band = bands["overlap_abs"]
            if b < a - band:
                report.add(
                    "PC201", "error",
                    f"[{axes}]-axis {kind} achieved overlap fell "
                    f"{_fmt(a)} -> {_fmt(b)} (beyond the {band:g} band): "
                    f"{subsystem}",
                    location=kind,
                    hint=_RATCHET_HINT,
                )
            elif b > a + band:
                report.add(
                    "PC110", "info",
                    f"[{axes}]-axis {kind} achieved overlap improved "
                    f"{_fmt(a)} -> {_fmt(b)} — tighten with "
                    f"--update-baselines",
                )
        a = _num(oc[kind].get("exposed_seconds"))
        b = _num(ncl[kind].get("exposed_seconds"))
        if a is not None and b is not None:
            band = bands["exposed_frac"]
            floor = bands["exposed_min_seconds"]
            if b > a * (1.0 + band) and b - a > floor:
                report.add(
                    "PC202", "error",
                    f"[{axes}]-axis exposed {kind} seconds grew "
                    f"{_fmt(a)}s -> {_fmt(b)}s "
                    f"(+{100 * (b / a - 1):.0f}% > {100 * band:.0f}% band): "
                    f"{subsystem}" if a > 0 else
                    f"[{axes}]-axis exposed {kind} seconds appeared: "
                    f"{_fmt(a)}s -> {_fmt(b)}s: {subsystem}",
                    location=kind,
                    hint=_RATCHET_HINT,
                )
            elif b < a * (1.0 - band) and a - b > floor:
                report.add(
                    "PC110", "info",
                    f"[{axes}]-axis exposed {kind} seconds shrank "
                    f"{_fmt(a)}s -> {_fmt(b)}s — tighten with "
                    f"--update-baselines",
                )

    # -- PC204: per-class / per-axis achieved interconnect bandwidth -------
    # telemetry.comms joins wire times with the cost model's byte volumes
    # (in-loop) or times the collectives directly (tools/comms_bench.py);
    # either way achieved_gbps dropping beyond the band means the wire got
    # slower for the SAME traffic — a degraded link, a lost overlap slot,
    # or a topology misconfiguration, not a workload change.
    ocomms = old.get("comms") if isinstance(old.get("comms"), Mapping) else {}
    ncomms = new.get("comms") if isinstance(new.get("comms"), Mapping) else {}
    band = bands["comms_bw_frac"]
    oclasses = dict(ocomms.get("classes") or {})
    nclasses = dict(ncomms.get("classes") or {})
    for kind in sorted(oclasses):
        axes, subsystem = CLASS_HINTS.get(
            kind, ("?", "unattributed collective class"))
        a = _num((oclasses.get(kind) or {}).get("achieved_gbps"))
        b = _num((nclasses.get(kind) or {}).get("achieved_gbps"))
        if not a or b is None:
            continue
        if b < a * (1.0 - band):
            report.add(
                "PC204", "error",
                f"[{axes}]-axis achieved {kind} bandwidth dropped "
                f"{_fmt(a, 3)} -> {_fmt(b, 3)} GB/s "
                f"(-{100 * (1 - b / a):.0f}% > {100 * band:.0f}% band): "
                f"the interconnect got slower for {subsystem}",
                location=kind,
                hint="tools/comms_bench.py isolates the wire from the "
                     "workload (per-axis fit + per-device skew names a "
                     "degraded link); " + _RATCHET_HINT,
            )
        elif b > a * (1.0 + band):
            report.add(
                "PC110", "info",
                f"[{axes}]-axis achieved {kind} bandwidth improved "
                f"{_fmt(a, 3)} -> {_fmt(b, 3)} GB/s — tighten with "
                f"--update-baselines",
            )
    oaxes = dict(ocomms.get("axes") or {})
    naxes = dict(ncomms.get("axes") or {})
    for axis in sorted(oaxes):
        a = _num((oaxes.get(axis) or {}).get("bandwidth_gbps"))
        b = _num((naxes.get(axis) or {}).get("bandwidth_gbps"))
        if not a or b is None:
            continue
        if b < a * (1.0 - band):
            report.add(
                "PC204", "error",
                f"fitted {axis}-axis bandwidth dropped {_fmt(a, 3)} -> "
                f"{_fmt(b, 3)} GB/s (-{100 * (1 - b / a):.0f}% > "
                f"{100 * band:.0f}% band): the sweep's linear fit says this "
                f"mesh axis's wire decalibrated",
                location=axis,
                hint="comms_summary.json's device_skew findings name a "
                     "degraded device when one host is the cause; "
                     + _RATCHET_HINT,
            )
        elif b > a * (1.0 + band):
            report.add(
                "PC110", "info",
                f"fitted {axis}-axis bandwidth improved {_fmt(a, 3)} -> "
                f"{_fmt(b, 3)} GB/s — tighten with --update-baselines",
            )

    # overall exposed wire time (catches a class that vanished from the
    # per-class table by being renamed)
    a = _num(old.get("exposed_collective_seconds"))
    b = _num(new.get("exposed_collective_seconds"))
    if a is not None and b is not None:
        band, floor = bands["exposed_frac"], bands["exposed_min_seconds"]
        if b > a * (1.0 + band) and b - a > floor:
            report.add(
                "PC202", "error",
                f"total exposed collective seconds grew {_fmt(a)}s -> "
                f"{_fmt(b)}s (+{100 * (b / a - 1):.0f}% > "
                f"{100 * band:.0f}% band)" if a > 0 else
                f"total exposed collective seconds appeared: {_fmt(a)}s -> "
                f"{_fmt(b)}s",
                location="overall",
                hint=_RATCHET_HINT,
            )

    # -- PC301: measured bubble fraction -----------------------------------
    a = _num(old.get("bubble_fraction_measured"))
    b = _num(new.get("bubble_fraction_measured"))
    if a is not None and b is not None:
        band = bands["bubble_abs"]
        if b > a + band:
            report.add(
                "PC301", "error",
                f"measured pipeline bubble fraction grew {_fmt(a)} -> "
                f"{_fmt(b)} (beyond the {band:g} band): the pipeline is "
                f"idling more than the committed baseline",
                hint="trace_summary.json 'pipeline' names the straggler "
                     "stage and the per-tick busy/idle split; "
                     + _RATCHET_HINT,
            )
        elif b < a - band:
            report.add(
                "PC110", "info",
                f"measured bubble fraction improved {_fmt(a)} -> {_fmt(b)} "
                f"— tighten with --update-baselines",
            )

    # -- PC501: measured peak HBM ------------------------------------------
    a = _num(old.get("peak_hbm_bytes"))
    b = _num(new.get("peak_hbm_bytes"))
    if a and b:
        band = bands["peak_hbm_frac"]
        if b > a * (1.0 + band):
            report.add(
                "PC501", "error",
                f"measured peak HBM grew {a / 1024**3:.3f}G -> "
                f"{b / 1024**3:.3f}G (+{100 * (b / a - 1):.0f}% > "
                f"{100 * band:.0f}% noise band): this workload's live "
                f"residency regressed",
                hint="memory_summary.json's attribution names the subsystem "
                     "that grew (params / opt state / activations / "
                     "chunk-store / MoE workspace); " + _RATCHET_HINT,
            )
        elif b < a * (1.0 - band):
            report.add(
                "PC110", "info",
                f"measured peak HBM improved {a / 1024**3:.3f}G -> "
                f"{b / 1024**3:.3f}G — tighten with --update-baselines",
            )

    # -- PC302/PC502: measured vs predicted (baseline-independent) ---------
    calibration_findings(new, bands, report)

    # -- PC401: cost-model residual drift ----------------------------------
    orr = (old.get("residuals") or {}).get("total") or {}
    nrr = (new.get("residuals") or {}).get("total") or {}
    a, b = _num(orr.get("ratio")), _num(nrr.get("ratio"))
    if a and b:
        band = bands["residual_frac"]
        if b / a > 1.0 + band or b / a < 1.0 / (1.0 + band):
            report.add(
                "PC401", "error",
                f"cost-model total residual (measured/predicted step time) "
                f"drifted {_fmt(a, 3)} -> {_fmt(b, 3)}: the planner's "
                f"pricing decalibrated beyond the {band:g} band",
                hint="re-audit the cost model terms against the per-plan "
                     "residual records (bench.py --plan-topk) and "
                     "recalibrate priors with tools/plan.py "
                     "--calibrate-from; " + _RATCHET_HINT,
            )

    report.stats["step_time_ms"] = _num(new.get("step_time_ms"))
    report.stats["bubble_fraction_measured"] = _num(
        new.get("bubble_fraction_measured"))
    report.stats["peak_hbm_bytes"] = _num(new.get("peak_hbm_bytes"))
    return report


# --------------------------------------------------------------------------
# residuals: the cost model audited term by term
# --------------------------------------------------------------------------


def residual_report(estimate: Mapping[str, Any],
                    measured: Mapping[str, Any]) -> dict[str, Any]:
    """Predicted-vs-measured residuals per cost-model term for one benched
    plan.

    ``estimate`` is a :class:`~autotune.cost_model.PlanEstimate` dict
    (``to_dict()``); ``measured`` carries whatever was actually observed:
    ``step_seconds`` (required), optionally ``exposed_collective_seconds``
    (trace-measured — the comms term's ground truth) and
    ``bubble_fraction_measured`` (timeline-measured).  Terms without a
    measurement report ``measured: None`` rather than pretending — the
    planner's priors are audited only where evidence exists."""
    pred_total = _num(estimate.get("step_seconds"))
    m_total = _num(measured.get("step_seconds"))
    out: dict[str, Any] = {
        "total": {
            "predicted_seconds": pred_total,
            "measured_seconds": m_total,
            "ratio": round(m_total / pred_total, 4)
            if pred_total and m_total else None,
        }
    }
    pred_comms = _num(estimate.get("comms_seconds"))
    m_exposed = _num(measured.get("exposed_collective_seconds"))
    out["comms"] = {
        "predicted_seconds": pred_comms,
        "measured_exposed_seconds": m_exposed,
        "ratio": round(m_exposed / pred_comms, 4)
        if pred_comms and m_exposed is not None else None,
    }
    # achieved interconnect bandwidth (telemetry.comms): how fast the wire
    # actually moved the bytes the cost model priced — None rows when the
    # run carried no comms section (the join needs the byte-volume facts)
    mcomms = (measured.get("comms")
              if isinstance(measured.get("comms"), Mapping) else {}) or {}
    mclasses = dict(mcomms.get("classes") or {})
    ach = {k: _num(v.get("achieved_gbps"))
           for k, v in mclasses.items() if isinstance(v, Mapping)}
    ach = {k: v for k, v in ach.items() if v is not None}
    effs = [_num(v.get("efficiency")) for v in mclasses.values()
            if isinstance(v, Mapping)]
    effs = [e for e in effs if e is not None]
    out["comms_bandwidth"] = {
        "peak_gbps": _num(mcomms.get("peak_bandwidth_gbps")),
        "achieved_gbps_by_class":
            {k: round(v, 6) for k, v in sorted(ach.items())} or None,
        "mean_efficiency": round(sum(effs) / len(effs), 6) if effs else None,
    }
    pred_bubble_s = _num(estimate.get("bubble_seconds"))
    pred_bubble_frac = (round(pred_bubble_s / pred_total, 6)
                        if pred_total and pred_bubble_s is not None else None)
    m_bubble_frac = _num(measured.get("bubble_fraction_measured"))
    out["bubble"] = {
        "predicted_fraction": pred_bubble_frac,
        "measured_fraction": m_bubble_frac,
        "residual": round(m_bubble_frac - pred_bubble_frac, 6)
        if m_bubble_frac is not None and pred_bubble_frac is not None
        else None,
    }
    pred_compute = _num(estimate.get("compute_seconds"))
    m_compute = None
    if m_total is not None and m_exposed is not None \
            and m_bubble_frac is not None:
        m_compute = max(m_total - m_exposed - m_bubble_frac * m_total, 0.0)
    out["compute"] = {
        "predicted_seconds": pred_compute,
        "measured_seconds": round(m_compute, 9)
        if m_compute is not None else None,
        "ratio": round(m_compute / pred_compute, 4)
        if pred_compute and m_compute is not None else None,
    }
    return out


# --------------------------------------------------------------------------
# baselines: load / check / update-with-justification
# --------------------------------------------------------------------------


def baseline_path(key: str, baselines_dir: Optional[Path] = None) -> Path:
    stem = Path(key).name
    if stem.endswith(".json"):
        stem = stem[: -len(".json")]
    return (baselines_dir or BASELINES_DIR) / f"{stem}.json"


def load_baseline(key: str, baselines_dir: Optional[Path] = None
                  ) -> Optional[dict[str, Any]]:
    path = baseline_path(key, baselines_dir)
    if not path.exists():
        return None
    return json.loads(path.read_text())


def _round_floats(v: Any, nd: int = 6) -> Any:
    if isinstance(v, bool):
        return v
    if isinstance(v, float):
        return round(v, nd)
    if isinstance(v, Mapping):
        return {k: _round_floats(x, nd) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_round_floats(x, nd) for x in v]
    return v


def write_baseline(key: str, facts: Mapping[str, Any], *,
                   justifications: list[str],
                   noise: Optional[Mapping[str, float]] = None,
                   baselines_dir: Optional[Path] = None) -> Path:
    """Byte-stable snapshot write (sorted keys, fixed indent, rounded
    floats) — reruns with identical measurements produce identical files."""
    path = baseline_path(key, baselines_dir)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {
        "comment": "perf-contract baseline — regenerate with "
                   "tools/perf_contract.py --update-baselines; a regression "
                   "beyond the noise bands must carry a --justify line "
                   "(the ratchet only improves silently)",
        "key": Path(key).name.removesuffix(".json"),
        "justifications": list(justifications),
        "noise": dict(sorted(dict(DEFAULT_NOISE, **(noise or {})).items())),
        "facts": _round_floats(dict(facts)),
    }
    path.write_text(json.dumps(payload, indent=1, sort_keys=True) + "\n")
    return path


def check_perf(key: str, facts: Mapping[str, Any], *,
               baselines_dir: Optional[Path] = None,
               noise: Optional[Mapping[str, float]] = None) -> AuditReport:
    """The ratchet's read side: diff fresh facts against the committed
    baseline (PC000 when none exists — plus the baseline-independent
    calibration check, which needs no snapshot to fire)."""
    name = Path(key).name.removesuffix(".json")
    snap = load_baseline(key, baselines_dir)
    if snap is None:
        report = AuditReport(config=name)
        report.add(
            "PC000", "error",
            f"no committed perf baseline for {name!r} "
            f"({baseline_path(key, baselines_dir)})",
            hint="baseline it: tools/perf_contract.py --update-baselines "
                 "<facts source> --key " + name,
        )
        calibration_findings(facts, dict(DEFAULT_NOISE, **(noise or {})),
                             report)
        report.stats["no_baseline"] = True
        return report
    bands = dict(DEFAULT_NOISE, **(snap.get("noise") or {}), **(noise or {}))
    report = diff_facts(snap.get("facts") or {}, facts, noise=bands,
                        config_name=name)
    report.stats["baseline_path"] = str(baseline_path(key, baselines_dir))
    return report


def update_baseline(key: str, facts: Mapping[str, Any], *,
                    justify: Optional[str] = None,
                    baselines_dir: Optional[Path] = None,
                    noise: Optional[Mapping[str, float]] = None
                    ) -> tuple[Path, AuditReport]:
    """The ratchet's write side.

    Improving (or in-band) facts commit silently, keeping existing
    justifications.  A REGRESSION — any error finding against the committed
    baseline — refuses to commit unless ``justify`` explains it; the
    justification is recorded in-file."""
    name = Path(key).name.removesuffix(".json")
    snap = load_baseline(key, baselines_dir)
    old_just = list((snap or {}).get("justifications")
                    or ["initial perf baseline"])
    old_noise = dict((snap or {}).get("noise") or {})
    bands = {**DEFAULT_NOISE, **old_noise, **(noise or {})}
    if snap is None:
        rep = AuditReport(config=name)
        calibration_findings(facts, bands, rep)
    else:
        rep = diff_facts(snap.get("facts") or {}, facts, noise=bands,
                         config_name=name)
    if rep.failed("error") and not justify:
        rules = sorted({f.rule for f in rep.findings
                        if f.severity == "error"})
        raise PerfContractError(
            f"{name}: the new measurement REGRESSES the committed baseline "
            f"({', '.join(rules)}) — a regression must be declared: pass "
            f"--justify '<why>' (the ratchet only improves silently)\n"
            f"{rep.format()}"
        )
    justifications = old_just + (
        [justify] if justify and (rep.failed("error") or snap is None) else [])
    path = write_baseline(key, facts, justifications=justifications,
                          noise=dict(old_noise, **(noise or {})),
                          baselines_dir=baselines_dir)
    return path, rep


def verdict_of(report: AuditReport) -> str:
    """One report -> one verdict word: ``no_baseline`` when the ONLY
    finding is the missing snapshot, else the worst severity (``clean``
    when none).  The single derivation the bench line and the CLI share —
    the two surfaces must never disagree about what a report means."""
    if report.stats.get("no_baseline") \
            and {f.rule for f in report.findings} <= {"PC000"}:
        return "no_baseline"
    return report.worst() or "clean"


def bench_verdict(key: str, facts: Mapping[str, Any], *,
                  baselines_dir: Optional[Path] = None) -> dict[str, Any]:
    """The compact contract-verdict block every bench headline line must
    carry (``bench.py`` refuses to emit one without it): the key checked,
    ``no_baseline`` / ``clean`` / ``info`` / ``error``, and the named
    findings when any fired."""
    report = check_perf(key, facts, baselines_dir=baselines_dir)
    no_baseline = bool(report.stats.get("no_baseline"))
    out: dict[str, Any] = {
        "key": Path(key).name.removesuffix(".json"),
        "verdict": verdict_of(report),
    }
    findings = [{"rule": f.rule, "message": f.message}
                for f in report.findings
                if f.severity == "error" and f.rule != "PC000"]
    if findings:
        out["findings"] = findings
    if no_baseline:
        out["no_baseline"] = True
    return out
