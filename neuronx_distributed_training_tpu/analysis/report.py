"""Shared finding/report types for the static auditor (graph audit + jaxlint).

A :class:`Finding` is one rule violation: rule ID, severity, a one-line
message, the offending location (an HLO op for graph rules, ``file:line`` for
source rules), and a config-level remediation hint.  :class:`AuditReport`
aggregates findings plus the audit's summary statistics (donation coverage,
collective census) and renders both the terminal and JSON forms the
``tools/preflight_audit.py`` CLI emits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

#: escalation order; ``fail_level("warn")`` fails on warn AND error
SEVERITIES = ("info", "warn", "error")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str            # rule ID, e.g. "GA101" / "JL201" (docs/static_analysis.md)
    severity: str        # "info" | "warn" | "error"
    message: str         # one-line statement of the defect
    location: str = ""   # offending HLO op (graph) or file:line (lint)
    hint: str = ""       # config-level remediation

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"finding severity must be one of {SEVERITIES}, "
                f"got {self.severity!r}"
            )

    def to_dict(self) -> dict[str, str]:
        return dataclasses.asdict(self)

    def format(self, *, max_location: int = 100) -> str:
        loc = self.location
        if len(loc) > max_location:
            loc = loc[: max_location - 3] + "..."
        line = f"[{self.severity.upper():5s}] {self.rule}: {self.message}"
        if loc:
            line += f"\n        at: {loc}"
        if self.hint:
            line += f"\n        fix: {self.hint}"
        return line


@dataclasses.dataclass
class AuditReport:
    """One audit run's result: findings + the stats the rules derived from.

    ``stats`` carries whatever the producing audit measured (donation
    coverage, collective counts, per-device byte threshold, ...) so the JSON
    artifact is self-describing; ``config`` names the audited config."""

    findings: list[Finding] = dataclasses.field(default_factory=list)
    config: str = ""
    stats: dict[str, Any] = dataclasses.field(default_factory=dict)

    def add(self, *args: Any, **kwargs: Any) -> None:
        self.findings.append(Finding(*args, **kwargs))

    def extend(self, other: "AuditReport") -> None:
        self.findings.extend(other.findings)
        self.stats.update(other.stats)

    def count(self, severity: str) -> int:
        return sum(1 for f in self.findings if f.severity == severity)

    def by_severity(self) -> dict[str, int]:
        return {s: self.count(s) for s in SEVERITIES if self.count(s)}

    def worst(self) -> Optional[str]:
        for s in reversed(SEVERITIES):
            if self.count(s):
                return s
        return None

    def failed(self, fail_on: str = "error") -> bool:
        """True when any finding is at or above ``fail_on`` severity."""
        threshold = SEVERITIES.index(fail_on)
        return any(SEVERITIES.index(f.severity) >= threshold
                   for f in self.findings)

    def to_dict(self) -> dict[str, Any]:
        return {
            "config": self.config,
            "verdict": self.worst() or "clean",
            "counts": self.by_severity(),
            "findings": [f.to_dict() for f in self.findings],
            "stats": self.stats,
        }

    def summary(self) -> dict[str, Any]:
        """The compact verdict block bench.py embeds in its JSON line."""
        out: dict[str, Any] = {
            "verdict": self.worst() or "clean",
            "rule_hits": self.by_severity(),
        }
        if "donation_coverage" in self.stats:
            out["donation_coverage"] = self.stats["donation_coverage"]
        return out

    def format(self) -> str:
        lines = []
        name = f" [{self.config}]" if self.config else ""
        if not self.findings:
            lines.append(f"audit{name}: clean (0 findings)")
        else:
            counts = ", ".join(f"{n} {s}" for s, n in self.by_severity().items())
            lines.append(f"audit{name}: {counts}")
            order = {s: i for i, s in enumerate(reversed(SEVERITIES))}
            for f in sorted(self.findings, key=lambda f: order[f.severity]):
                lines.append(f.format())
        if "donation_coverage" in self.stats:
            lines.append(
                f"donation coverage: {100 * self.stats['donation_coverage']:.1f}% "
                f"({self.stats.get('donated_aliased', '?')}/"
                f"{self.stats.get('donated_expected', '?')} leaves aliased)"
            )
        if "collectives" in self.stats:
            lines.append(f"collectives: {self.stats['collectives']}")
        return "\n".join(lines)
