"""Autotune — the compile-time parallelism & remat planner.

Given a model config and a chip count, the planner:

1. enumerates the legal plan lattice (``space``): factorizations of the world
   into dp x tp x pp x cp x ep respecting every divisibility rule the runtime
   enforces, microbatch counts compatible with the global batch, remat policy,
   and pipeline schedule (honoring the ``supports_1f1b`` gate) — all pruned
   statically, before any lowering;
2. scores each plan with an analytic roofline (``cost_model``): compute time
   from the per-component FLOPs breakdown, comms time from per-collective
   byte volumes mapped onto an ICI bandwidth/latency table (``topology``),
   pipeline bubble from the schedule, and a per-device HBM estimate;
3. AOT-lowers the top-k shrunk (``planner``, reusing the graph auditor's
   ``shrink_overrides``) to replace estimates with measured
   ``memory_analysis()`` bytes and the real collective census, discards plans
   that fail the audit, and emits a :class:`PlanReport`.

Surfaces: ``tools/plan.py`` CLI, ``nxdt-train --autotune``, and
``bench.py --plan-topk`` (which scores the cost model against reality).
``docs/autotuning.md`` is the manual.
"""

from neuronx_distributed_training_tpu.autotune.cost_model import (  # noqa: F401
    PlanEstimate,
    estimate_hbm_bytes,
    estimate_plan,
    kendall_tau,
    overlap_from_trace_summary,
    resolve_overlap,
)
from neuronx_distributed_training_tpu.autotune.planner import (  # noqa: F401
    PlanCandidate,
    PlanReport,
    plan_config,
    rank_plans,
)
from neuronx_distributed_training_tpu.autotune.space import (  # noqa: F401
    ModelFacts,
    Plan,
    enumerate_plans,
)
from neuronx_distributed_training_tpu.autotune.topology import (  # noqa: F401
    TOPOLOGIES,
    ChipTopology,
    resolve_topology,
)
