"""Analytic roofline + memory model: predicted step time & HBM per plan.

Three independent terms per plan, each a closed-form function of the
:class:`~.space.ModelFacts`, the :class:`~.space.Plan`, and the
:class:`~.topology.ChipTopology` — no lowering anywhere:

- **compute**: the per-component FLOPs breakdown
  (``utils.perf.flops_breakdown_for_model`` — the same accounting MFU uses)
  x the fwd+2xbwd convention x a remat recompute multiplier, over
  ``chips x peak x efficiency``.
- **comms**: per-collective byte volumes (tp/SP layer collectives, dp
  gradient reduction + ZeRO-1 regather, pp stage hops, cp ring/all-to-all
  passes, ep token exchange) priced on the topology's ring model
  ``bytes x (N-1)/(N x bw) + hops x latency``.
- **bubble**: the schedule's fill/drain fraction of the in-pipeline work
  (``parallel.pipeline.bubble_multiplier`` — the one table telemetry also
  reports): ``(pp-1)/nm`` for plain 1F1B and the vp=1 wavefront,
  ``(pp-1)/(nm*vp)`` under a virtual pipeline (wavefront or
  ``1f1b-interleaved`` — the interleave IS the bubble win), and
  ``(pp-1)/(3*nm)`` for ``1f1b-zb`` (the deferred-wgrad tail fills the
  cooldown; the warmup third remains).  Schedules additionally differ in
  MEMORY, which the HBM model accounts separately.

The HBM estimate mirrors the runtime's actual residency: params in
``param_dtype`` (sharded tp x pp, experts additionally ep), gradients in
``grad_accum_dtype``, AdamW moments (+ master when params are low-precision)
under ZeRO-1's dp sharding, the local batch shard, scan-stacked remat
residuals per policy, logits, and the dropless-MoE gathered-expert transient.
``tests/test_autotune.py::TestMemoryCalibration`` pins it within +-15% of
compiled ``memory_analysis()`` bytes on tiny configs so the planner's OOM
pruning cannot drift from XLA reality.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any, Mapping, Optional

from neuronx_distributed_training_tpu.autotune.space import ModelFacts, Plan
from neuronx_distributed_training_tpu.autotune.topology import ChipTopology

logger = logging.getLogger(__name__)


def _policy_for(facts: ModelFacts):
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    return DtypePolicy.from_precision_config(facts.precision)


def _dtype_bytes(dt) -> int:
    import jax.numpy as jnp

    return int(jnp.dtype(dt).itemsize)


# --------------------------------------------------------------------------
# parameter accounting (counts, with their shard denominators)
# --------------------------------------------------------------------------


def param_components(facts: ModelFacts, plan: Plan) -> dict[str, float]:
    """Per-device parameter COUNTS by component, already divided by the
    shard factors the specs apply (tp over weight matrices, pp over the
    layer stack, ep x tp over expert stacks; norms replicated)."""
    h, d = facts.hidden, facts.head_dim
    nh, nkv = facts.num_heads, facts.num_kv_heads
    tp, pp, ep = plan.tp, plan.pp, plan.ep
    L = facts.num_layers

    embed = facts.vocab * h / tp
    qkv = h * (nh + 2 * nkv) * d / tp
    o = nh * d * h / tp
    norms = 2.0 * h  # input + post-attention norms, replicated over tp
    if facts.num_experts:
        n_moe = L // max(facts.moe_frequency, 1)
        n_dense = L - n_moe
        dense_mlp = n_dense * 3.0 * h * facts.ffn / tp
        experts = n_moe * facts.num_experts * 3.0 * h * facts.ffn / (ep * tp)
        router = n_moe * float(h * facts.num_experts)
    else:
        dense_mlp = L * 3.0 * h * facts.ffn / tp
        experts = router = 0.0
    out = {
        "embed": embed,
        "layers": (L * (qkv + o + norms) + dense_mlp) / pp,
        "experts": experts / pp,
        "router": router / pp,
        "final_norm": float(h),
    }
    if not facts.tied_embeddings:
        out["lm_head"] = facts.vocab * h / tp
    return out


def params_per_device(facts: ModelFacts, plan: Plan) -> float:
    return sum(param_components(facts, plan).values())


# --------------------------------------------------------------------------
# HBM model
# --------------------------------------------------------------------------

#: temp-accounting constants, calibrated against compiled
#: ``memory_analysis()`` on tiny configs across dp/tp/pp/ep meshes
#: (tests/test_autotune.py pins the agreement at +-15%).  The decomposition
#: was identified by one-dimension-at-a-time sweeps: scaling ONLY num_layers,
#: ONLY seq, ONLY width, ONLY vocab isolates each coefficient.
#:
#: GRAD_TRANSIENTS: param-tree-sized grad-dtype buffers live at the update
#: peak — the microbatch grad, the accumulator carry, and the AdamW update's
#: not-yet-donated mu/nu/param outputs.
_GRAD_TRANSIENTS = 4.5
#: vocab-row-sized f32 buffers per token at the CE peak (logits, softmax,
#: one-hot/dlogits, dlogits-carry)
_HEAD_BUFFERS = 4.0
#: f32 score-shaped arrays live per layer under naive core attention
#: (scores, softmax output, bwd dscores); "full" remat frees them between
#: layers, the other policies leave them at the scheduler's peak
_SCORE_BUFFERS = 3.0
#: dropless-MoE routing workspace: f32 gate/up/activation rows plus
#: gather/scatter hidden copies per routed token ([T*k, ffn] and [T*k, h])
_MOE_ROUTE_BUFFERS = 6.0
#: pipeline stage-loop buffering per LOCAL layer per microbatch-token: the
#: tick loop's stacked carries + per-tick vjp residuals.  Empirically
#: nm-independent and IDENTICAL across schedules and remat policies on the
#: compiled artifact (the stage functions do not fold the remat policy into
#: the tick loop), so under pp the activation term uses the selective-shaped
#: per-token cost times this factor — calibrated at pp=2; it over-estimates
#: (conservative for OOM pruning) at deeper pp (docs/autotuning.md).
_PP_STAGE_BUFFERS = 5.3


def hbm_breakdown(facts: ModelFacts, plan: Plan,
                  policy: Any = None,
                  calibration: Optional[Mapping[str, float]] = None
                  ) -> dict[str, float]:
    """Per-device resident bytes by category.  ``total`` is what the planner
    budgets against (and what the calibration test compares to XLA's
    ``argument_size + temp_size``); the categories make PlanReports explain
    themselves.

    ``calibration`` maps category -> measured/prior ratio
    (:func:`hbm_calibration_from_memory_summary`): each named category is
    scaled by its MEASURED ratio before totalling, shrinking the documented
    transient-constant blind spots on topologies a ``telemetry.memory``
    capture has covered."""
    import jax.numpy as jnp

    policy = policy or _policy_for(facts)
    pbytes = _dtype_bytes(policy.param_dtype)
    gbytes = _dtype_bytes(policy.grad_accum_dtype)
    obytes = _dtype_bytes(policy.optimizer_dtype)
    abytes = _dtype_bytes(policy.compute_dtype)

    n_params = params_per_device(facts, plan)
    dp_state = plan.dp if facts.zero1 else 1

    # AdamW: two moments, plus a master copy when params are low-precision
    opt_mult = 2 + (1 if jnp.dtype(policy.param_dtype)
                    != jnp.dtype(policy.optimizer_dtype) else 0)

    tokens_mb = plan.micro_batch_size * facts.seq / plan.cp
    sp_div = plan.tp if (facts.sequence_parallel and plan.tp > 1) else 1
    h, ffn, d = facts.hidden, facts.ffn, facts.head_dim
    nh, nkv = facts.num_heads, facts.num_kv_heads
    layers_local = facts.num_layers / plan.pp

    # residual bytes saved per token per layer, by remat policy: "full"
    # keeps only the scan carry (the layer input); "selective" additionally
    # keeps the projection/MLP intermediates but recomputes the attention
    # core; "none" keeps everything the backward reads
    qkv_width = (nh + 2 * nkv) * d / plan.tp
    is_moe = bool(facts.num_experts)
    mlp_width = (facts.top_k if is_moe and facts.moe_frequency == 1
                 else 1) * ffn / plan.tp
    remat = "selective" if plan.pp > 1 else plan.remat  # pp ignores remat
    if remat == "full":
        c_tok = (h / sp_div) * abytes
    elif remat == "selective":
        c_tok = (2.0 * h / sp_div + qkv_width + 2.0 * mlp_width) * abytes
    else:
        c_tok = (3.0 * h / sp_div + qkv_width + 2 * nh * d / plan.tp
                 + 3.0 * mlp_width) * abytes
    # naive core attention materializes [b, nh/tp, s/cp, s] f32 scores; flash
    # (a real kernel on TPU) tiles them away.  "full" remat frees them
    # between layers; the other policies keep them at the scheduler's peak.
    impl = getattr(facts.model_cfg, "attention_impl",
                   getattr(getattr(facts.model_cfg, "llama", None),
                           "attention_impl", "core"))
    if impl == "core" and remat != "full":
        c_tok += _SCORE_BUFFERS * (nh / plan.tp) * (facts.seq / plan.cp) * 4
    if is_moe:
        # dropless routing workspace rides every MoE layer in f32
        moe_share = 1.0 / max(facts.moe_frequency, 1)
        c_tok += _MOE_ROUTE_BUFFERS * moe_share * max(facts.top_k, 1) \
            * (ffn + h) / plan.tp * 4

    act = layers_local * c_tok * tokens_mb
    pipe_rings = 0.0
    if plan.pp > 1:
        # asymptotic in-flight residency: the manual-vjp family drains a
        # microbatch's residuals after at most pp ticks, the autodiff
        # wavefront holds every microbatch's forward until its backward
        # arrives (all nm*vp work items under a virtual pipeline).  At tiny
        # depths/counts the stage loop's own fixed buffering dominates (the
        # calibrated floor — compiled temps there are nm- and
        # schedule-independent); max() keeps the floor AND the asymptote.
        if plan.schedule in ("1f1b", "1f1b-zb", "1f1b-interleaved"):
            in_flight = min(plan.pp, plan.num_microbatches)
        else:
            in_flight = plan.num_microbatches * max(plan.vp, 1)
        act *= max(_PP_STAGE_BUFFERS, float(in_flight))
        # stage-input-sized rings the manual-vjp variants add on top of
        # plain 1f1b (whose own buffering the _PP_STAGE_BUFFERS calibration
        # already absorbs).  Priced from the work-compacted executor's
        # ACTUAL interval-allocated ring sizes (pipeline.ring_slot_counts):
        # the m-major interleave bounds the chunk-input store by the
        # schedule's true in-flight window — O(pp*vp), independent of nm —
        # instead of the old lockstep executor's O(vp*nm) store (the term
        # that priced interleaved out of tight-HBM meshes at large nm).
        input_bytes = tokens_mb * (h / sp_div) * abytes
        if plan.schedule in ("1f1b-interleaved", "1f1b-zb"):
            from neuronx_distributed_training_tpu.parallel.pipeline import (
                ring_slot_counts,
            )

            vp = max(plan.vp, 1) if plan.schedule == "1f1b-interleaved" else 1
            extra_slots = (
                ring_slot_counts(plan.schedule, plan.pp,
                                 plan.num_microbatches, vp)["total"]
                - ring_slot_counts("1f1b", plan.pp,
                                   plan.num_microbatches, 1)["total"]
            )
            pipe_rings = max(extra_slots, 0) * input_bytes

    logits = _HEAD_BUFFERS * tokens_mb * facts.vocab / plan.tp * 4
    batch = (facts.global_batch_size / plan.dp) * facts.seq * 4 * 2

    out = {
        "params": n_params * pbytes,
        "grads": _GRAD_TRANSIENTS * n_params * gbytes,
        "opt_state": opt_mult * n_params * obytes / dp_state,
        "batch": batch,
        "activations": act,
        "logits": logits,
    }
    if pipe_rings:
        out["pipeline_rings"] = pipe_rings
    if facts.num_experts and plan.ep > 1:
        # dropless MoE computes against the ep-GATHERED expert weights
        # (ops/moe.py weight-gather EP); the gathered copy is a transient
        comp = param_components(facts, plan)
        out["gathered_experts"] = comp["experts"] * plan.ep * abytes
    if calibration:
        for cat, ratio in calibration.items():
            if cat in out:
                out[cat] *= _clamp_ratio(ratio)
    out["total"] = sum(out.values())
    return out


def estimate_hbm_bytes(facts: ModelFacts, plan: Plan,
                       policy: Any = None) -> float:
    return hbm_breakdown(facts, plan, policy)["total"]


#: sanity clamp on measured/prior HBM calibration ratios — a degenerate
#: measurement (empty profile, wrong units) must not zero a category out of
#: the OOM pruning or blow it up 100x
_HBM_RATIO_BOUNDS = (0.05, 20.0)


def _clamp_ratio(v: Any) -> float:
    lo, hi = _HBM_RATIO_BOUNDS
    return min(max(float(v), lo), hi)


def hbm_calibration_from_memory_summary(summary: Any) -> dict[str, float]:
    """Measured/prior HBM ratios out of a ``memory_summary.json`` payload
    (the dict, its file path, or a run dir containing it) — the memory
    analogue of :func:`overlap_from_trace_summary`.

    The summary carries the planner's PREDICTED per-device breakdown for
    the resolved plan (written by the trainer at capture time); the
    MEASURED side comes from the ONE shared join
    (``telemetry.memory.measured_hbm_categories`` — exact tree bytes for
    the state categories, profile attribution for the transients, the
    worst-device allocator watermark for the total — everything in
    per-device units).  Only categories with BOTH sides > 0 produce a
    ratio — the calibration never pretends.  Raises ``ValueError`` when
    the summary carries no usable pair (the planner turns that into a
    report error)."""
    from neuronx_distributed_training_tpu.telemetry.memory import (
        load_memory_summary,
        measured_hbm_categories,
    )

    summary = load_memory_summary(summary)
    predicted = dict(summary.get("predicted") or {})
    per_category, peak = measured_hbm_categories(summary)
    out: dict[str, float] = {}
    for cat, measured in per_category.items():
        pred = predicted.get(cat)
        if pred and measured > 0:
            out[cat] = _clamp_ratio(measured / float(pred))
    # the total ratio is the headline predicted-vs-actual audit number
    # (reported, and what PC502 gates on)
    if peak and predicted.get("total"):
        out["total"] = _clamp_ratio(float(peak) / float(predicted["total"]))
    if not out:
        raise ValueError(
            "memory summary carries no calibratable categories (no "
            "predicted breakdown, or empty attribution) — nothing to "
            "calibrate the HBM model from"
        )
    return out


#: categories whose measured bytes come from the live-buffer profile — a
#: BOUNDARY capture sees freed step transients as absent, so a small
#: measured value proves nothing about the in-step peak.  Pricing treats
#: their ratios as grow-only (a boundary capture can prove a term
#: UNDER-priced — resident buffers the model didn't charge — but never
#: over-priced); the state categories are exact tree bytes and move both
#: ways.
_TRANSIENT_CATEGORIES = frozenset(
    {"activations", "pipeline_rings", "gathered_experts", "grads",
     "logits", "batch"})


def priced_hbm_calibration(cal: Mapping[str, float]) -> dict[str, float]:
    """The PRICEABLE subset of a measured ratio set: ``total`` (the audit
    headline) is dropped, and transient-category ratios floor at 1.0 —
    conservative for OOM pruning (see :data:`_TRANSIENT_CATEGORIES`)."""
    out: dict[str, float] = {}
    for cat, ratio in cal.items():
        if cat == "total":
            continue
        out[cat] = (max(float(ratio), 1.0)
                    if cat in _TRANSIENT_CATEGORIES else float(ratio))
    return out


def predicted_breakdown_for_config(cfg: Mapping, chips: int
                                   ) -> Optional[dict[str, float]]:
    """The planner's per-device HBM breakdown for a LOADED config's declared
    plan — what the trainer stamps into ``memory_summary.json`` and the OOM
    bundle so predicted-vs-actual lives in one artifact.  ``None`` when the
    config's degrees admit no resolved plan (never raises)."""
    try:
        facts = ModelFacts.from_config(cfg)
        plan = facts.declared_plan_for(int(chips))
        if plan is None:
            return None
        return {k: round(v, 1)
                for k, v in hbm_breakdown(facts, plan).items()}
    except Exception:  # noqa: BLE001 — the stamp is best-effort context
        logger.debug("predicted HBM breakdown unavailable", exc_info=True)
        return None


# --------------------------------------------------------------------------
# compute/comms overlap model
# --------------------------------------------------------------------------

# Hiding fraction the engineered overlap chain (bucketed ZeRO-1 gathers +
# prefetch stagger, distributed_strategy.overlap) is designed to reach on the
# dp axis: each bucket's all-gather gets the next bucket's update math as its
# overlap window, so near-total hiding is the target rather than the topology
# prior.  Kept below resolve_overlap's 0.99 clamp — the residual exposed
# slice is the per-bucket launch cost that bucketing can't remove.
ENGINEERED_DP_OVERLAP = 0.9


def _axis_kinds() -> dict[str, tuple[str, ...]]:
    """Which measured collective classes dominate each comms axis's wire
    time — the shared table in ``utils.debug.AXIS_COLLECTIVE_KINDS``, so the
    cost model's per-axis byte classes, the trace analytics'
    measured-overlap mapping, and the graph-contract provenance attribution
    can never drift apart (one surface renaming a class would silently
    decalibrate the rest).  Imported lazily: ``utils.debug`` pulls in jax,
    and this module's plan math stays importable without it."""
    from neuronx_distributed_training_tpu.utils.debug import (
        AXIS_COLLECTIVE_KINDS,
    )

    return AXIS_COLLECTIVE_KINDS


def resolve_overlap(overlap: Any, topo: ChipTopology) -> dict[str, float]:
    """Normalize an overlap input into ``{axis: hidden_fraction}`` over the
    comms axes (+ ``"default"``).

    ``None`` -> the topology table's per-generation prior for every axis; a
    float -> that fraction everywhere; a mapping -> per-axis fractions with
    ``"default"`` (else the topology prior) filling unnamed axes.  Values
    clamp to [0, 0.99] — a measured 1.0 would price comms as literally free
    and hide every comms regression from the ranking."""
    base = float(topo.comms_overlap)
    if overlap is None:
        per_axis: dict[str, Any] = {}
    elif isinstance(overlap, (int, float)):
        base = float(overlap)
        per_axis = {}
    else:
        per_axis = dict(overlap)
        base = float(per_axis.pop("default", base))
    clamp = lambda v: min(max(float(v), 0.0), 0.99)
    out = {"default": clamp(base)}
    for axis in _axis_kinds():
        out[axis] = clamp(per_axis.get(axis, base))
    return out


def overlap_from_trace_summary(summary: Any) -> dict[str, float]:
    """Measured per-axis overlap calibration out of a ``trace_summary.json``
    payload (the dict, its file path, or a run dir containing it).

    Each comms axis takes the wire-time-weighted achieved overlap of its
    collective classes (``_axis_kinds``); axes whose classes were absent
    from the trace fall back to the overall ``achieved_overlap``.  The
    result feeds :func:`estimate_plan`'s ``overlap`` parameter — predicted
    comms cost then uses OBSERVED hiding instead of the topology prior."""
    from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
        load_trace_summary,
    )

    from typing import Mapping as _Mapping

    summary = load_trace_summary(summary)
    by_class = dict(summary.get("overlap_by_class") or {})
    for kind, c in by_class.items():
        # malformed shapes must surface as ValueError (the planner turns
        # that into a report error, not a CLI traceback)
        if not isinstance(c, _Mapping):
            raise ValueError(
                f"malformed trace summary: overlap_by_class[{kind!r}] must "
                f"be a mapping with wire_seconds/hidden_seconds, got "
                f"{type(c).__name__}"
            )
    out: dict[str, float] = {}
    overall = summary.get("achieved_overlap")
    if overall is not None:
        out["default"] = float(overall)
    for axis, kinds in _axis_kinds().items():
        wire = hidden = 0.0
        for kind in kinds:
            c = by_class.get(kind)
            if c and c.get("wire_seconds"):
                wire += float(c["wire_seconds"])
                hidden += float(c.get("hidden_seconds", 0.0))
        if wire > 0:
            out[axis] = hidden / wire
    if not out:
        raise ValueError(
            "trace summary carries no collective overlap data (no "
            "collectives in the traced window?) — nothing to calibrate from"
        )
    return out


#: sanity clamp on measured/prior interconnect bandwidth ratios — a
#: degenerate sweep (one noisy rep, a collapsed fit) must not price an axis
#: as free or as 50x the wire
_COMMS_RATIO_BOUNDS = (0.02, 50.0)


def _clamp_comms_ratio(v: Any) -> float:
    lo, hi = _COMMS_RATIO_BOUNDS
    return min(max(float(v), lo), hi)


def comms_calibration_from_summary(summary: Any) -> dict[str, float]:
    """Measured/prior per-axis bandwidth ratios out of a
    ``comms_summary.json`` payload (the dict, its file path, or a run dir
    containing it) — the interconnect analogue of
    :func:`overlap_from_trace_summary` / :func:`hbm_calibration_from_memory_summary`.

    The summary records the topology prior it was benched against
    (``prior.ici_bandwidth_bytes``) alongside each axis's fitted bandwidth
    (``telemetry.comms.build_comms_summary``), so the extraction is
    self-contained: ratio = fitted / prior, clamped to
    :data:`_COMMS_RATIO_BOUNDS`.  Only axes with a usable fit produce a
    ratio — calibration never pretends.  Raises ``ValueError`` when the
    summary carries no usable axis (the planner turns that into a report
    error)."""
    from neuronx_distributed_training_tpu.telemetry.comms import (
        load_comms_summary,
    )

    summary = load_comms_summary(summary)
    prior = (summary.get("prior") or {}).get("ici_bandwidth_bytes")
    try:
        prior = float(prior or 0.0)
    except (TypeError, ValueError):
        prior = 0.0
    axes = summary.get("axes") or {}
    if not isinstance(axes, Mapping):
        raise ValueError(
            "malformed comms summary: 'axes' must be a mapping of per-axis "
            f"sweep results, got {type(axes).__name__}"
        )
    out: dict[str, float] = {}
    for axis, entry in axes.items():
        if not isinstance(entry, Mapping):
            raise ValueError(
                f"malformed comms summary: axes[{axis!r}] must be a mapping "
                f"with a 'fit' block, got {type(entry).__name__}"
            )
        ratio = entry.get("bandwidth_ratio")
        if ratio is None and prior > 0:
            fit = entry.get("fit") or {}
            bw = fit.get("bandwidth_bytes_per_s") \
                if isinstance(fit, Mapping) else None
            if bw:
                ratio = float(bw) / prior
        if ratio is not None:
            out[str(axis)] = _clamp_comms_ratio(ratio)
    if not out:
        raise ValueError(
            "comms summary carries no fitted per-axis bandwidth (empty "
            "sweep, or no prior recorded) — nothing to calibrate the "
            "interconnect model from"
        )
    return out


def _comms_topos(topo: ChipTopology,
                 calibration: Optional[Mapping[str, float]]
                 ) -> dict[str, ChipTopology]:
    """Per-axis topologies with MEASURED bandwidth substituted for the
    table prior (``ici_bandwidth_bytes x clamped ratio``); axes without a
    measurement keep the prior.  Latency stays the table's — the fitted
    intercepts are too rep-noisy to price against (docs/autotuning.md)."""
    if not calibration:
        return {}
    out: dict[str, ChipTopology] = {}
    for axis, ratio in calibration.items():
        out[str(axis)] = dataclasses.replace(
            topo,
            ici_bandwidth_bytes=topo.ici_bandwidth_bytes
            * _clamp_comms_ratio(ratio),
        )
    return out


# --------------------------------------------------------------------------
# time model
# --------------------------------------------------------------------------


def _ring_seconds(bytes_full: float, n: int, topo: ChipTopology,
                  *, allreduce: bool = False, hops: Optional[int] = None
                  ) -> float:
    """Ring-collective time for a ``bytes_full``-sized logical tensor over
    ``n`` ranks: all-gather/reduce-scatter move ``B(n-1)/n`` per rank,
    all-reduce twice that."""
    if n <= 1 or bytes_full <= 0:
        return 0.0
    factor = 2.0 if allreduce else 1.0
    wire = factor * bytes_full * (n - 1) / (n * topo.ici_bandwidth_bytes)
    return wire + (hops if hops is not None else factor * (n - 1)) \
        * topo.ici_latency_seconds


@dataclasses.dataclass
class PlanEstimate:
    """The cost model's verdict on one plan (seconds / bytes, per step)."""

    compute_seconds: float
    comms_seconds: float
    bubble_seconds: float
    hbm_bytes: float
    comms_breakdown: dict[str, float]
    hbm_breakdown: dict[str, float]
    fits: bool = True

    @property
    def step_seconds(self) -> float:
        return self.compute_seconds + self.comms_seconds + self.bubble_seconds

    def to_dict(self) -> dict[str, Any]:
        return {
            "step_seconds": round(self.step_seconds, 6),
            "compute_seconds": round(self.compute_seconds, 6),
            "comms_seconds": round(self.comms_seconds, 6),
            "bubble_seconds": round(self.bubble_seconds, 6),
            "hbm_bytes": int(self.hbm_bytes),
            "fits": self.fits,
            "comms_breakdown": {k: round(v, 6)
                                for k, v in self.comms_breakdown.items()},
            "hbm_breakdown": {k: int(v)
                              for k, v in self.hbm_breakdown.items()},
        }


def estimate_plan(facts: ModelFacts, plan: Plan, topo: ChipTopology,
                  *, hbm_headroom: float = 0.9,
                  overlap: Any = None,
                  hbm_calibration: Optional[Mapping[str, float]] = None,
                  comms_calibration: Optional[Mapping[str, float]] = None
                  ) -> PlanEstimate:
    """Score one plan.  ``fits`` is False when the HBM estimate exceeds
    ``hbm_headroom`` x the topology's capacity (the runtime and fragmentation
    own the rest).  ``overlap`` — None (topology default), a fraction, or a
    per-axis mapping (:func:`overlap_from_trace_summary`) — sets how much of
    each axis's collective wire time is priced as hidden under compute.
    ``hbm_calibration`` — measured/prior ratios per HBM category
    (:func:`hbm_calibration_from_memory_summary`) — reprices the memory
    model with what a ``telemetry.memory`` capture actually observed.
    ``comms_calibration`` — measured/prior per-axis bandwidth ratios
    (:func:`comms_calibration_from_summary`) — reprices each comms axis at
    the bandwidth a ``tools/comms_bench.py`` sweep actually measured on the
    wire instead of the topology table's peak."""
    from neuronx_distributed_training_tpu.utils.perf import (
        flops_breakdown_for_model,
    )

    policy = _policy_for(facts)
    abytes = _dtype_bytes(policy.compute_dtype)
    chips = plan.world
    bd = flops_breakdown_for_model(facts.model_cfg, facts.seq)
    fwd = sum(bd.values())
    # attention core (score/context) FLOPs — what "selective" recomputes
    core = 2.0 * facts.seq * facts.num_heads * facts.head_dim \
        * facts.num_layers  # causal-halved scores+context per token
    step_flops_tok = 3.0 * fwd
    if plan.remat == "full":
        step_flops_tok += fwd          # one full extra forward in bwd
    elif plan.remat == "selective":
        step_flops_tok += core
    if plan.schedule == "1f1b-zb":
        # the deferred wgrad pass re-linearizes the stage against the saved
        # input: one extra stage forward (everything but the head) per
        # microbatch — the remat trade zb makes to empty the cooldown bubble
        step_flops_tok += fwd - bd.get("head", 0.0)
    total_flops = facts.global_batch_size * facts.seq * step_flops_tok
    compute = total_flops / (chips * topo.peak_flops
                             * topo.compute_efficiency)

    # ---- comms ----
    tokens_chip = facts.global_batch_size * facts.seq / (plan.dp * plan.cp)
    h = facts.hidden
    comms: dict[str, float] = {}
    # measured-bandwidth substitution: each axis prices against its own
    # (possibly comms_bench-calibrated) topology view
    ctopo = _comms_topos(topo, comms_calibration)
    axis_topo = lambda axis: ctopo.get(axis, topo)

    # tp: per layer, fwd+bwd move ~4 gathered-activation volumes each way
    # (SP's AG/RS pairs; plain TP's all-reduces cost the same wire bytes)
    if plan.tp > 1:
        per_layer_bytes = 4.0 * tokens_chip * h * abytes
        comms["tp"] = 2.0 * facts.num_layers / plan.pp * _ring_seconds(
            per_layer_bytes, plan.tp, axis_topo("tp"))
        # vocab-parallel CE: two tiny [tokens] all-reduces per microbatch
        comms["tp"] += plan.num_microbatches * _ring_seconds(
            2.0 * tokens_chip / plan.num_microbatches * 4, plan.tp,
            axis_topo("tp"), allreduce=True)

    # dp: ZeRO-1 reduce-scatter(grads f32) + all-gather(params); plain dp
    # all-reduces grads.  Engineered overlap (distributed_strategy.overlap.
    # zero1_bucket_mb > 0) splits the parameter gather into per-bucket
    # collectives: wire bytes are unchanged, but each bucket pays its own
    # ring-latency walk — the honest price of bucketing the ranker weighs
    # against the lifted hiding prior below.
    n_buckets = 1
    if facts.zero1 and getattr(facts, "overlap_bucket_mb", 0.0) > 0:
        master_bytes = params_per_device(facts, plan) * 4.0  # fp32 master
        n_buckets = max(1, math.ceil(
            master_bytes / (float(facts.overlap_bucket_mb) * 2**20)))
    if plan.dp > 1:
        grad_bytes = params_per_device(facts, plan) \
            * _dtype_bytes(policy.reduce_dtype)
        if facts.zero1:
            comms["dp"] = _ring_seconds(grad_bytes, plan.dp,
                                        axis_topo("dp")) \
                + _ring_seconds(
                    params_per_device(facts, plan)
                    * _dtype_bytes(policy.param_dtype), plan.dp,
                    axis_topo("dp"), hops=n_buckets * (plan.dp - 1))
        else:
            comms["dp"] = _ring_seconds(grad_bytes, plan.dp,
                                        axis_topo("dp"), allreduce=True)

    # pp: 2*nm point-to-point hidden hops per chip (fwd + bwd)
    if plan.pp > 1:
        hop = plan.micro_batch_size * (facts.seq / plan.cp) * h * abytes
        pp_topo = axis_topo("pp")
        comms["pp"] = 2.0 * plan.num_microbatches * (
            hop / pp_topo.ici_bandwidth_bytes + pp_topo.ici_latency_seconds)

    # cp: ring kv passes (ring/zigzag) or qkvo all-to-alls (ulysses),
    # fwd + 2x bwd
    if plan.cp > 1:
        kv_bytes = 2.0 * tokens_chip * facts.num_kv_heads * facts.head_dim \
            * abytes
        if facts.cp_fusion == "ulysses":
            a2a = 2.0 * tokens_chip * h * abytes
            comms["cp"] = 3.0 * facts.num_layers / plan.pp * _ring_seconds(
                a2a, plan.cp, axis_topo("cp"))
        else:
            comms["cp"] = 3.0 * facts.num_layers / plan.pp * _ring_seconds(
                kv_bytes, plan.cp, axis_topo("cp"))

    # ep: token dispatch + combine all-to-alls, fwd + 2x bwd
    if plan.ep > 1 and facts.num_experts:
        n_moe = facts.num_layers // max(facts.moe_frequency, 1)
        route_bytes = tokens_chip * max(facts.top_k, 1) * h * abytes
        comms["ep"] = 3.0 * n_moe / plan.pp * _ring_seconds(
            route_bytes, plan.ep, axis_topo("ep"))

    # XLA overlaps collectives with compute aggressively (async collective
    # fusion; per-layer SP gathers hide under the matmuls that consume
    # them), so only a fraction of the wire time is EXPOSED step time.
    # The fraction is per axis: the topology table's prior by default, or
    # the MEASURED per-collective-class overlap when a telemetry.trace
    # calibration is supplied (overlap_from_trace_summary) — scheduled
    # overlap windows themselves are still a documented blind spot of the
    # analytic ranking (docs/autotuning.md).
    hidden = resolve_overlap(overlap, topo)
    if (facts.zero1 and n_buckets > 1
            and getattr(facts, "overlap_prefetch_ag", True)):
        # bucketed + prefetched ZeRO-1: the staggered bucket chain gives the
        # latency-hiding scheduler per-bucket windows to hide the gathers in,
        # so the dp prior lifts toward the engineered target — never below a
        # measured calibration that already says better
        hidden["dp"] = max(hidden.get("dp", hidden["default"]),
                           ENGINEERED_DP_OVERLAP)
    comms = {k: v * (1.0 - hidden.get(k, hidden["default"]))
             for k, v in comms.items()}
    comms_total = sum(comms.values())

    # ---- bubble ----
    # per-schedule fill/drain multiplier (parallel.pipeline.bubble_multiplier
    # — one table shared with run_summary/bench telemetry): (pp-1)/nm for
    # plain 1f1b / vp=1 wavefront, /(nm*vp) under a virtual pipeline,
    # /(3*nm) for the zero-bubble split's residual warmup third
    bubble = 0.0
    if plan.pp > 1 and plan.num_microbatches > 0:
        from neuronx_distributed_training_tpu.parallel.pipeline import (
            bubble_multiplier,
        )

        inner = compute + comms_total - comms.get("dp", 0.0)
        bubble = bubble_multiplier(
            plan.schedule, plan.pp, plan.num_microbatches, plan.vp) * inner

    mem = hbm_breakdown(facts, plan, policy, calibration=hbm_calibration)
    fits = mem["total"] <= hbm_headroom * topo.hbm_bytes
    return PlanEstimate(
        compute_seconds=compute, comms_seconds=comms_total,
        bubble_seconds=bubble, hbm_bytes=mem["total"],
        comms_breakdown=comms, hbm_breakdown=mem, fits=fits,
    )


# --------------------------------------------------------------------------
# per-collective byte volumes (telemetry.quant_readiness join)
# --------------------------------------------------------------------------


def collective_byte_volumes(facts: ModelFacts, plan: Plan
                            ) -> dict[str, dict[str, float]]:
    """Logical wire-byte volume per step, per axis, keyed by collective kind
    (the ``AXIS_COLLECTIVE_KINDS`` vocabulary).

    The SAME byte math as :func:`estimate_plan`, minus the time model: these
    are the ``bytes_full`` arguments its ``_ring_seconds`` calls price, so a
    compression study (``telemetry.quant_readiness``) can ask "how many bytes
    does each collective class move?" without re-deriving the sharding
    arithmetic.  Under SP the per-layer tp volume is an AG/RS pair — split
    evenly between the two kinds; plain-TP all-reduces move the same wire
    bytes, so the split stays an honest upper bound either way."""
    policy = _policy_for(facts)
    abytes = _dtype_bytes(policy.compute_dtype)
    tokens_chip = facts.global_batch_size * facts.seq / (plan.dp * plan.cp)
    h = facts.hidden
    out: dict[str, dict[str, float]] = {}

    if plan.tp > 1:
        layer_total = 4.0 * tokens_chip * h * abytes \
            * 2.0 * facts.num_layers / plan.pp
        out["tp"] = {
            "all-gather": layer_total / 2.0,
            "reduce-scatter": layer_total / 2.0,
            # vocab-parallel CE: two [tokens] f32 all-reduces per microbatch
            "all-reduce": 2.0 * 2.0 * tokens_chip * 4.0,
        }

    if plan.dp > 1:
        grad_bytes = params_per_device(facts, plan) \
            * _dtype_bytes(policy.reduce_dtype)
        if facts.zero1:
            out["dp"] = {
                "reduce-scatter": grad_bytes,
                "all-gather": params_per_device(facts, plan)
                * _dtype_bytes(policy.param_dtype),
            }
        else:
            out["dp"] = {"all-reduce": grad_bytes}

    if plan.pp > 1:
        hop = plan.micro_batch_size * (facts.seq / plan.cp) * h * abytes
        out["pp"] = {
            "collective-permute": 2.0 * plan.num_microbatches * hop,
        }

    if plan.cp > 1:
        if facts.cp_fusion == "ulysses":
            out["cp"] = {
                "all-to-all": 3.0 * facts.num_layers / plan.pp
                * 2.0 * tokens_chip * h * abytes,
            }
        else:
            out["cp"] = {
                "collective-permute": 3.0 * facts.num_layers / plan.pp
                * 2.0 * tokens_chip * facts.num_kv_heads * facts.head_dim
                * abytes,
            }

    if plan.ep > 1 and facts.num_experts:
        n_moe = facts.num_layers // max(facts.moe_frequency, 1)
        out["ep"] = {
            "all-to-all": 3.0 * n_moe / plan.pp
            * tokens_chip * max(facts.top_k, 1) * h * abytes,
        }

    return out


# --------------------------------------------------------------------------
# rank agreement (bench.py --plan-topk)
# --------------------------------------------------------------------------


def kendall_tau(a: list[float], b: list[float]) -> Optional[float]:
    """Kendall rank correlation between two paired score lists (tau-a; ties
    count as discordant-neutral).  None for fewer than 2 pairs."""
    n = min(len(a), len(b))
    if n < 2:
        return None
    conc = disc = 0
    for i in range(n):
        for j in range(i + 1, n):
            da = a[i] - a[j]
            db = b[i] - b[j]
            s = da * db
            if s > 0:
                conc += 1
            elif s < 0:
                disc += 1
    total = n * (n - 1) / 2
    return (conc - disc) / total
