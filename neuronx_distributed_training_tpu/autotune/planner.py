"""The planner: rank the lattice, audit the survivors, emit a PlanReport.

``plan_config`` is the one-call entry every surface uses (``tools/plan.py``,
``nxdt-train --autotune``, ``bench.py --plan-topk``):

1. load + validate the YAML, extract :class:`~.space.ModelFacts`;
2. enumerate the legal lattice and score every plan analytically
   (:func:`rank_plans` — pure host math, hundreds of plans in milliseconds);
3. AOT-lower the top-k SHRUNK (``analysis.graph_audit.shrink_overrides`` —
   degrees clamp to 2, dims to minimal legal shapes, structure preserved) and
   replace estimates with the compiled artifact's facts: the graph-audit
   verdict, the real collective census, and measured ``memory_analysis()``
   bytes (recorded as a calibration ratio against the analytic model at the
   same shrunk size).  Plans whose audit reaches error severity are discarded
   and the next-ranked plan is promoted;
4. emit a :class:`PlanReport`: the ranked table, per-plan
   compute/comms/bubble/HBM breakdowns, and the winning knob block as a YAML
   override snippet (``--apply`` writes it into a copy of the config).

Plans sharing a shrunk-audit structure (same >1-axis pattern, remat,
schedule) lower identically, so each structure is audited once and the
verdict shared — the audit stage costs a handful of ~2s lowerings, not
top_k of them.
"""

from __future__ import annotations

import dataclasses
import logging
from pathlib import Path
from typing import Any, Mapping, Optional

from neuronx_distributed_training_tpu.autotune.cost_model import (
    PlanEstimate,
    estimate_hbm_bytes,
    estimate_plan,
    comms_calibration_from_summary,
    hbm_calibration_from_memory_summary,
    overlap_from_trace_summary,
    priced_hbm_calibration,
    resolve_overlap,
)
from neuronx_distributed_training_tpu.autotune.space import (
    ModelFacts,
    Plan,
    enumerate_plans,
)
from neuronx_distributed_training_tpu.autotune.topology import (
    ChipTopology,
    resolve_topology,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class PlanCandidate:
    """One ranked plan: the analytic estimate plus (after the audit stage)
    the compiled artifact's own facts."""

    plan: Plan
    estimate: PlanEstimate
    rank: int = 0
    audit_verdict: Optional[str] = None      # clean | info | warn | error
    audit_counts: dict = dataclasses.field(default_factory=dict)
    measured_collectives: Optional[dict] = None
    measured_memory_bytes: Optional[int] = None
    #: analytic-vs-measured HBM at the SHRUNK size (the cost model's own
    #: calibration score for this structure; ~1.0 is good)
    memory_calibration: Optional[float] = None
    discarded: Optional[str] = None          # reason, when audit rejected it

    def to_dict(self) -> dict[str, Any]:
        d = {
            "rank": self.rank,
            "plan": dataclasses.asdict(self.plan),
            "estimate": self.estimate.to_dict(),
        }
        if self.audit_verdict is not None:
            d["audit"] = {"verdict": self.audit_verdict,
                          "counts": self.audit_counts}
        if self.measured_collectives is not None:
            d["measured_collectives"] = self.measured_collectives
        if self.measured_memory_bytes is not None:
            d["measured_memory_bytes"] = self.measured_memory_bytes
        if self.memory_calibration is not None:
            d["memory_calibration"] = round(self.memory_calibration, 3)
        if self.discarded:
            d["discarded"] = self.discarded
        return d


@dataclasses.dataclass
class PlanReport:
    """The planner's deliverable: ranked candidates + the winning knobs."""

    config: str
    chips: int
    topology: str
    candidates: list[PlanCandidate]
    n_plans: int                      # lattice size before ranking
    n_fit: int                        # plans inside the HBM budget
    facts: Optional[ModelFacts] = None
    error: Optional[str] = None
    #: per-axis compute/comms overlap the ranking priced with ("default" +
    #: comms axes); "measured" marks a telemetry.trace calibration vs the
    #: topology-table prior
    overlap: Optional[dict] = None
    #: measured facts the calibration source carried beyond overlap
    #: (exposed collective seconds, measured pipeline bubble fraction) —
    #: the audit trail that keeps planner priors auditable, not trusted
    #: (analysis.perf_contract residuals; docs/observability.md)
    calibration_facts: Optional[dict] = None
    #: measured/prior HBM ratios the ranking priced with (a
    #: ``telemetry.memory`` capture via ``--calibrate-from
    #: memory_summary.json``); ``total`` is the headline predicted-vs-
    #: actual audit ratio — reported, not applied per-category
    hbm_calibration: Optional[dict] = None
    #: measured/prior per-axis interconnect bandwidth ratios the ranking
    #: priced with (a ``tools/comms_bench.py`` sweep via ``--calibrate-from
    #: comms_summary.json`` — ``cost_model.comms_calibration_from_summary``)
    comms_calibration: Optional[dict] = None

    @property
    def winner(self) -> Optional[PlanCandidate]:
        for c in self.candidates:
            if not c.discarded:
                return c
        return None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "config": self.config,
            "chips": self.chips,
            "topology": self.topology,
            "n_plans": self.n_plans,
            "n_fit": self.n_fit,
            "candidates": [c.to_dict() for c in self.candidates],
        }
        if self.overlap is not None:
            d["overlap"] = {k: (round(v, 4) if isinstance(v, float) else v)
                            for k, v in self.overlap.items()}
        if self.calibration_facts is not None:
            d["calibration_facts"] = self.calibration_facts
        if self.hbm_calibration is not None:
            d["hbm_calibration"] = {k: round(float(v), 4)
                                    for k, v in self.hbm_calibration.items()}
        if self.comms_calibration is not None:
            d["comms_calibration"] = {
                k: round(float(v), 4)
                for k, v in self.comms_calibration.items()}
        w = self.winner
        d["winner"] = dataclasses.asdict(w.plan) if w else None
        if self.error:
            d["error"] = self.error
        return d

    def summary(self) -> dict[str, Any]:
        """Compact block for run_summary.json / bench JSON lines."""
        w = self.winner
        return {
            "chips": self.chips,
            "topology": self.topology,
            "n_plans": self.n_plans,
            "n_fit": self.n_fit,
            "winner": w.plan.describe() if w else None,
            "predicted_step_seconds": (round(w.estimate.step_seconds, 6)
                                       if w else None),
        }

    def yaml_snippet(self) -> str:
        """The winning knob block, ready to paste (or ``--apply``)."""
        w = self.winner
        if w is None or self.facts is None:
            return "# no surviving plan\n"
        import yaml

        tree: dict[str, Any] = {}
        _expand_dotted(w.plan.overrides(self.facts), tree)
        return yaml.safe_dump(tree, sort_keys=False)

    def format(self, *, top: Optional[int] = None) -> str:
        lines = [
            f"plan [{self.config}] chips={self.chips} "
            f"topology={self.topology}: {self.n_plans} legal plans, "
            f"{self.n_fit} inside the HBM budget"
        ]
        if self.overlap is not None:
            src = ("measured" if self.overlap.get("measured")
                   else "topology default")
            axes = ", ".join(
                f"{k}={v:.2f}" for k, v in sorted(self.overlap.items())
                if isinstance(v, float))
            lines.append(f"comms overlap ({src}): {axes}")
        if self.hbm_calibration:
            ratios = ", ".join(
                f"{k}={float(v):.2f}"
                for k, v in sorted(self.hbm_calibration.items()))
            lines.append(
                f"HBM calibration (measured/prior): {ratios}")
        if self.comms_calibration:
            ratios = ", ".join(
                f"{k}={float(v):.2f}"
                for k, v in sorted(self.comms_calibration.items()))
            lines.append(
                f"comms bandwidth (measured/prior): {ratios}")
        cf = self.calibration_facts or {}
        if cf:
            bits = []
            if cf.get("exposed_collective_seconds") is not None:
                bits.append(f"exposed_collective_seconds="
                            f"{cf['exposed_collective_seconds']:.4g}")
            if cf.get("bubble_fraction_measured") is not None:
                bits.append(f"bubble_fraction_measured="
                            f"{cf['bubble_fraction_measured']:.4g}")
            if cf.get("winner_bubble_residual") is not None:
                bits.append(f"winner bubble residual "
                            f"{cf['winner_bubble_residual']:+.4g} "
                            f"(measured - predicted)")
            if bits:
                lines.append("calibration audit: " + ", ".join(bits))
        if self.error:
            lines.append(f"ERROR: {self.error}")
            return "\n".join(lines)
        hdr = (f"{'rank':>4}  {'predicted':>10}  {'compute':>8}  "
               f"{'comms':>8}  {'bubble':>8}  {'hbm':>8}  {'audit':<7} plan")
        lines.append(hdr)
        for c in self.candidates[: top or len(self.candidates)]:
            e = c.estimate
            audit = c.audit_verdict or "-"
            if c.discarded:
                audit = "REJECT"
            lines.append(
                f"{c.rank:>4}  {e.step_seconds * 1e3:>8.1f}ms  "
                f"{e.compute_seconds * 1e3:>6.1f}ms  "
                f"{e.comms_seconds * 1e3:>6.1f}ms  "
                f"{e.bubble_seconds * 1e3:>6.1f}ms  "
                f"{e.hbm_bytes / 1024**3:>6.2f}G  {audit:<7} "
                f"{c.plan.describe()}"
            )
            if c.discarded:
                lines.append(f"      discarded: {c.discarded}")
        w = self.winner
        if w is not None:
            lines.append("winning knob block:")
            lines.extend("  " + ln for ln in
                         self.yaml_snippet().rstrip().splitlines())
        else:
            lines.append("no plan survived the audit stage")
        return "\n".join(lines)


def rank_plans(
    facts: ModelFacts,
    chips: int,
    topo: ChipTopology,
    *,
    hbm_headroom: float = 0.9,
    max_mbs: int = 8,
    overlap: Any = None,
    hbm_calibration: Optional[Mapping[str, float]] = None,
    comms_calibration: Optional[Mapping[str, float]] = None,
) -> tuple[list[PlanCandidate], int, int]:
    """Enumerate + score the lattice.  Returns (ranked candidates, lattice
    size, fitting count).  Plans over the HBM budget rank strictly below
    every fitting plan (they are kept so a too-small topology still yields a
    ranked report instead of nothing).  ``overlap`` threads straight into
    :func:`~.cost_model.estimate_plan` — a measured calibration reprices
    every plan's comms term and can reorder the ranking; ``hbm_calibration``
    (measured/prior ratios from a ``telemetry.memory`` capture) reprices
    the memory model the same way; ``comms_calibration`` (measured/prior
    per-axis bandwidth from a ``tools/comms_bench.py`` sweep) reprices each
    comms axis at the bandwidth the wire actually delivered."""
    plans = enumerate_plans(facts, chips, max_mbs=max_mbs)
    scored = [(p, estimate_plan(facts, p, topo, hbm_headroom=hbm_headroom,
                                overlap=overlap,
                                hbm_calibration=hbm_calibration,
                                comms_calibration=comms_calibration))
              for p in plans]
    n_fit = sum(1 for _, e in scored if e.fits)
    scored.sort(key=lambda pe: (not pe[1].fits, pe[1].step_seconds)
                + pe[0].key())
    out = [PlanCandidate(plan=p, estimate=e, rank=i + 1)
           for i, (p, e) in enumerate(scored)]
    return out, len(plans), n_fit


def _audit_structure(source: Any, facts: ModelFacts, plan: Plan,
                     *, max_devices: int) -> dict[str, Any]:
    """Lower one plan's SHRUNK structure and harvest: audit verdict/counts,
    the real collective census, measured memory bytes, and the analytic
    model's calibration ratio at the same shrunk size."""
    from neuronx_distributed_training_tpu.analysis.graph_audit import (
        _world_of,
        audit_config,
        shrink_overrides,
    )
    from neuronx_distributed_training_tpu.config.loader import load_config

    plan_cfg = load_config(source, plan.overrides(facts))
    rep = audit_config(plan_cfg, shrink=True, max_devices=max_devices)
    out: dict[str, Any] = {
        "verdict": rep.worst() or "clean",
        "counts": rep.by_severity(),
        "failed": rep.failed("error"),
        "collectives": rep.stats.get("collectives"),
        "memory_bytes": rep.stats.get("memory_bytes"),
    }
    if out["memory_bytes"]:
        try:
            shr = shrink_overrides(plan_cfg, max_devices=max_devices)
            shrunk_cfg = load_config(plan_cfg, shr)
            sfacts = ModelFacts.from_config(shrunk_cfg)
            world = _world_of(shrunk_cfg, max_devices)
            splan = sfacts.declared_plan_for(world)
            if splan is not None:
                analytic = estimate_hbm_bytes(sfacts, splan)
                out["calibration"] = analytic / max(out["memory_bytes"], 1)
        except Exception as e:  # noqa: BLE001 — calibration is advisory
            logger.debug("shrunk calibration unavailable: %s", e)
    return out


def audit_candidates(
    source: Any,
    facts: ModelFacts,
    candidates: list[PlanCandidate],
    top_k: int,
    *,
    max_devices: int = 8,
) -> list[PlanCandidate]:
    """Walk the ranked list until ``top_k`` candidates carry a PASSING audit
    (or the list runs out); audits are shared across plans with the same
    shrunk structure.  Returns the audited prefix (passes AND rejects, so
    the report shows what was discarded and why)."""
    from neuronx_distributed_training_tpu.autotune.space import (
        iter_unique_structures,
    )

    cache: dict[tuple, dict[str, Any]] = {}
    out: list[PlanCandidate] = []
    passed = 0
    for cand in candidates:
        if passed >= top_k:
            break
        key = next(iter_unique_structures([cand.plan]))[0]
        if key not in cache:
            try:
                cache[key] = _audit_structure(source, facts, cand.plan,
                                              max_devices=max_devices)
            except Exception as e:  # noqa: BLE001 — an unlowererable plan is
                # a REJECT verdict, not a planner crash
                cache[key] = {"verdict": "error", "counts": {"error": 1},
                              "failed": True,
                              "exception": f"{type(e).__name__}: {e}"}
        res = cache[key]
        cand.audit_verdict = res["verdict"]
        cand.audit_counts = dict(res.get("counts") or {})
        cand.measured_collectives = res.get("collectives")
        cand.measured_memory_bytes = res.get("memory_bytes")
        cand.memory_calibration = res.get("calibration")
        if res.get("failed"):
            cand.discarded = (res.get("exception")
                              or "graph audit reached error severity")
        else:
            passed += 1
        out.append(cand)
    return out


def plan_config(
    source: str | Path | Mapping,
    *,
    chips: Optional[int] = None,
    topology: Optional[str] = None,
    top_k: int = 5,
    audit: bool = True,
    overrides: Optional[Mapping] = None,
    hbm_headroom: float = 0.9,
    max_mbs: int = 8,
    max_devices: int = 8,
    calibration: Any = None,
) -> PlanReport:
    """Plan a launch for ``source`` on ``chips`` devices — the one-call
    entry.  ``chips`` defaults to the config's ``trainer.devices``, else the
    smallest world its declared degrees need.  With ``audit=False`` the
    report is analytic-only (the ``--check`` gate's fast path).

    ``calibration`` — a ``trace_summary.json`` (``telemetry.trace``), a
    ``memory_summary.json`` (``telemetry.memory``), a ``comms_summary.json``
    (``tools/comms_bench.py``), a run dir holding any of them, or a loaded
    dict of any — replaces the topology table's comms-overlap prior with
    the MEASURED per-collective-class overlap, the HBM model's transient
    constants with MEASURED per-category ratios, and/or the per-axis
    interconnect bandwidth with MEASURED wire rates, so predicted cost
    reflects what this workload actually did
    (``tools/plan.py --calibrate-from``)."""
    from neuronx_distributed_training_tpu.config.loader import load_config

    name = (Path(source).name if isinstance(source, (str, Path))
            else str(dict(source).get("name", "<mapping>")))
    try:
        cfg = load_config(source, overrides)
        facts = ModelFacts.from_config(cfg)
    except Exception as e:  # noqa: BLE001 — config errors ARE the verdict
        return PlanReport(config=name, chips=chips or 0,
                          topology=topology or "?", candidates=[],
                          n_plans=0, n_fit=0,
                          error=f"config failed to load: "
                                f"{type(e).__name__}: {e}")
    if chips is None:
        declared = facts.declared
        chips = int((cfg.get("trainer", {}) or {}).get("devices", 0) or 0) \
            or (declared.tp * declared.pp * declared.cp
                * max(declared.ep, 1) if declared else 1)
    topo = resolve_topology(topology) if topology else resolve_topology(
        device=_first_device())
    overlap = None
    measured = False
    calibration_facts: Optional[dict] = None
    hbm_cal: Optional[dict] = None
    comms_cal: Optional[dict] = None
    if calibration is not None:
        try:
            trace_doc, memory_doc, comms_doc = _resolve_calibration(
                calibration)
        except (OSError, ValueError) as e:
            return PlanReport(config=name, chips=chips, topology=topo.name,
                              candidates=[], n_plans=0, n_fit=0, facts=facts,
                              error=f"calibration source failed to load: "
                                    f"{type(e).__name__}: {e}")
        if trace_doc is not None:
            try:
                overlap = overlap_from_trace_summary(trace_doc)
                measured = True
            except (OSError, ValueError) as e:
                return PlanReport(
                    config=name, chips=chips, topology=topo.name,
                    candidates=[], n_plans=0, n_fit=0, facts=facts,
                    error=f"overlap calibration failed: "
                          f"{type(e).__name__}: {e}")
            try:
                # the calibration source's measured facts beyond overlap —
                # the audit trail (exposed seconds, measured bubble) that
                # lets the report show the priors AND what contradicts them
                pipe = trace_doc.get("pipeline") or {}
                calibration_facts = {
                    k: v for k, v in {
                        "achieved_overlap": trace_doc.get("achieved_overlap"),
                        "exposed_collective_seconds": trace_doc.get(
                            "exposed_collective_seconds"),
                        "bubble_fraction_measured": pipe.get(
                            "bubble_fraction_measured"),
                        "schedule_measured": pipe.get("schedule"),
                    }.items() if v is not None
                } or None
            except Exception as e:  # noqa: BLE001 — the trail is advisory
                logger.debug("calibration facts unavailable: %s", e)
        if memory_doc is not None:
            try:
                hbm_cal = hbm_calibration_from_memory_summary(memory_doc)
            except (OSError, ValueError) as e:
                return PlanReport(
                    config=name, chips=chips, topology=topo.name,
                    candidates=[], n_plans=0, n_fit=0, facts=facts,
                    error=f"HBM calibration failed: "
                          f"{type(e).__name__}: {e}")
        if comms_doc is not None:
            try:
                comms_cal = comms_calibration_from_summary(comms_doc)
            except (OSError, ValueError) as e:
                return PlanReport(
                    config=name, chips=chips, topology=topo.name,
                    candidates=[], n_plans=0, n_fit=0, facts=facts,
                    error=f"comms calibration failed: "
                          f"{type(e).__name__}: {e}")
        if trace_doc is None and memory_doc is None and comms_doc is None:
            return PlanReport(
                config=name, chips=chips, topology=topo.name,
                candidates=[], n_plans=0, n_fit=0, facts=facts,
                error="calibration source carries neither a trace summary, "
                      "a memory summary, nor a comms summary — nothing to "
                      "calibrate from")
    overlap_used = dict(resolve_overlap(overlap, topo), measured=measured)
    # the report shows the RAW measured ratios; pricing uses the
    # conservative subset — "total" is the audit headline (not a
    # category), and transient-category ratios floor at 1.0 because a
    # boundary capture cannot see freed step transients
    # (cost_model.priced_hbm_calibration)
    priced_cal = (priced_hbm_calibration(hbm_cal) if hbm_cal else None)
    ranked, n_plans, n_fit = rank_plans(
        facts, chips, topo, hbm_headroom=hbm_headroom, max_mbs=max_mbs,
        overlap=overlap, hbm_calibration=priced_cal or None,
        comms_calibration=comms_cal or None)
    if not ranked:
        return PlanReport(config=name, chips=chips, topology=topo.name,
                          candidates=[], n_plans=0, n_fit=0, facts=facts,
                          overlap=overlap_used,
                          calibration_facts=calibration_facts,
                          hbm_calibration=hbm_cal,
                          comms_calibration=comms_cal,
                          error="no legal plan for this chip count "
                                "(check divisibility of heads/layers/batch)")
    if audit:
        # always audit from the LOADED config (caller overrides included)
        candidates = audit_candidates(cfg, facts, ranked, top_k,
                                      max_devices=max_devices)
    else:
        candidates = ranked[:top_k]
    report = PlanReport(config=name, chips=chips, topology=topo.name,
                        candidates=candidates, n_plans=n_plans, n_fit=n_fit,
                        facts=facts, overlap=overlap_used,
                        calibration_facts=calibration_facts,
                        hbm_calibration=hbm_cal,
                        comms_calibration=comms_cal)
    w = report.winner
    if calibration_facts is not None and w is not None \
            and calibration_facts.get("bubble_fraction_measured") is not None \
            and w.estimate.step_seconds > 0:
        # audit the winner's bubble price against the measured fraction —
        # the residual analysis.perf_contract's PC302 gates on
        predicted = w.estimate.bubble_seconds / w.estimate.step_seconds
        calibration_facts["winner_bubble_fraction_predicted"] = round(
            predicted, 6)
        calibration_facts["winner_bubble_residual"] = round(
            float(calibration_facts["bubble_fraction_measured"]) - predicted,
            6)
    return report


def _resolve_calibration(source: Any) -> tuple[Optional[dict],
                                               Optional[dict],
                                               Optional[dict]]:
    """``--calibrate-from`` source -> ``(trace_doc, memory_doc,
    comms_doc)`` — any may be None.  A run dir yields every summary that
    exists in it; a file or loaded dict is classified by content
    (``telemetry.comms.is_comms_summary`` first — its kind marker is
    explicit — then ``telemetry.memory.is_memory_summary``, else a trace
    summary)."""
    import json

    from neuronx_distributed_training_tpu.telemetry.comms import (
        is_comms_summary,
    )
    from neuronx_distributed_training_tpu.telemetry.memory import (
        is_memory_summary,
    )

    def _classify(doc: dict) -> tuple[Optional[dict], Optional[dict],
                                      Optional[dict]]:
        if is_comms_summary(doc):
            return None, None, doc
        if is_memory_summary(doc):
            return None, doc, None
        return doc, None, None

    if isinstance(source, Mapping):
        return _classify(dict(source))
    p = Path(source)
    if p.is_dir():
        trace_doc = memory_doc = comms_doc = None
        tp = p / "trace_summary.json"
        mp = p / "memory_summary.json"
        cp = p / "comms_summary.json"
        if tp.exists():
            trace_doc = json.loads(tp.read_text())
        if mp.exists():
            memory_doc = json.loads(mp.read_text())
        if cp.exists():
            comms_doc = json.loads(cp.read_text())
        return trace_doc, memory_doc, comms_doc
    doc = json.loads(p.read_text())
    if not isinstance(doc, dict):
        raise ValueError(f"{p}: not a summary document")
    return _classify(doc)


def _first_device():
    try:
        import jax

        return jax.devices()[0]
    except Exception:  # noqa: BLE001 — planning must work with no backend
        return None


def apply_plan(source: str | Path, dest: str | Path, plan: Plan,
               facts: ModelFacts) -> None:
    """Write a copy of the YAML with the plan's knobs imposed (``--apply``).

    Comments are not preserved (plain yaml round-trip) — the copy is a
    launchable artifact, the original stays the documented source."""
    import yaml

    with open(source) as f:
        raw = yaml.safe_load(f) or {}
    _expand_dotted(plan.overrides(facts), raw)
    with open(dest, "w") as f:
        yaml.safe_dump(raw, f, sort_keys=False)


def _expand_dotted(overrides: Mapping[str, Any], into: dict) -> dict:
    """Materialize ``{"a.b.c": v}`` dotted overrides into a nested mapping —
    the ONE expansion ``yaml_snippet`` and ``apply_plan`` share (two copies
    would let the printed knob block and the --apply artifact drift)."""
    for dotted, v in overrides.items():
        cur = into
        parts = dotted.split(".")
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return into
