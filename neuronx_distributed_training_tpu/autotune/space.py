"""The plan lattice: every legal launch configuration, statically pruned.

A :class:`Plan` is one point in the launch space the operator would otherwise
hand-pick: the mesh factorization (tp/pp/cp/ep and the derived dp), the
microbatch size (hence microbatch count), the remat policy, and the pipeline
schedule.  :func:`enumerate_plans` emits the legal set for a given
:class:`ModelFacts` + chip count — deterministic order, no duplicates, no
lowering — applying the SAME divisibility and support rules the runtime
enforces (``config.loader.validate_config``, ``parallel.mesh``,
``parallel.pipeline.supports_1f1b``), so every emitted plan loads, validates,
and lowers.

Divisibility catalog (the static pruning):

- ``tp`` divides Q heads, ffn, and vocab; KV heads either divide into tp
  shards (``kv % tp == 0``) or replicate over it (``tp % kv == 0`` — the
  standard GQA layout; the flagship's tp=32 over 8 KV heads).
- ``pp`` divides the layer stack (whole MoE+dense groups when
  ``moe_frequency > 1``); zigzag attention forbids pp entirely.
- ``cp`` only exists when the config carries a context-parallel attention
  fusion; divides seq (2*cp for zigzag), respects the ulysses head budget,
  and under pp respects the blockwise kv-tile smoothness rule.
- ``ep`` divides both the expert count and dp (EP carves DP, mesh.py).
- ``dp = chips / (tp*pp*cp)`` exactly; ``gbs % (mbs * dp) == 0``.
- schedule: the manual-vjp family (``1f1b``, its zero-bubble split
  ``1f1b-zb``, and the circular interleave ``1f1b-interleaved`` with
  ``vp > 1``) only where ``supports_1f1b`` says so; ``wavefront`` always
  legal under pp.  Interleaved plans additionally need
  ``num_layers % (pp*vp) == 0`` and ``nm >= pp`` (the runtime's
  circular-store hazard rule).  ``wavefront`` with ``vp > 1`` is priced
  when DECLARED by a config but not enumerated: at equal (pp, nm, vp) the
  interleave dominates it on both bubble and memory, so the lattice emits
  only the dominant point.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, Mapping, Optional

#: remat lattice dimension, cheapest-memory-last
REMAT_POLICIES = ("none", "selective", "full")

#: virtual-pipeline chunk counts the interleaved schedule explores — small
#: on purpose: the bubble win is (pp-1)/(nm*vp), already 4x-diminished at
#: vp=4, while per-chunk layer slices thin out (and chunk-input storage
#: grows) linearly
_VP_CANDIDATES = (2, 4)


def divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


@dataclasses.dataclass(frozen=True)
class Plan:
    """One launch configuration — hashable, ordered, YAML-projectable."""

    tp: int = 1
    pp: int = 1
    cp: int = 1
    ep: int = 1
    dp: int = 1
    vp: int = 1                       # virtual pipeline (interleave) chunks
    micro_batch_size: int = 1
    num_microbatches: int = 1
    remat: str = "selective"          # none | selective | full
    # none (pp==1) | wavefront | 1f1b | 1f1b-interleaved | 1f1b-zb
    schedule: str = "none"

    @property
    def world(self) -> int:
        return self.dp * self.tp * self.pp * self.cp

    def key(self) -> tuple:
        """Canonical sort key — the deterministic enumeration order."""
        return (self.tp, self.pp, self.cp, self.ep, self.vp,
                self.micro_batch_size, REMAT_POLICIES.index(self.remat),
                self.schedule)

    @property
    def mesh(self) -> tuple[int, int, int, int, int]:
        """(tp, pp, cp, ep, dp) — the parallelism tuple --check compares."""
        return (self.tp, self.pp, self.cp, self.ep, self.dp)

    def overrides(self, facts: "ModelFacts") -> dict[str, Any]:
        """Dotted-path config overrides that impose this plan on a YAML —
        what ``--apply`` writes and what the audit stage lowers."""
        o: dict[str, Any] = {
            "distributed_strategy.tensor_model_parallel_size": self.tp,
            "distributed_strategy.pipeline_model_parallel_size": self.pp,
            "distributed_strategy.context_parallel_size": self.cp,
            "distributed_strategy.expert_model_parallel_size": self.ep,
            "distributed_strategy.virtual_pipeline_model_parallel_size":
                self.vp,
            # SP rides TP (the loader rejects sequence_parallel at tp=1)
            "distributed_strategy.sequence_parallel": (
                facts.sequence_parallel and self.tp > 1),
            "data.micro_batch_size": self.micro_batch_size,
            "model.activations_checkpoint_granularity": (
                None if self.remat == "none" else self.remat),
        }
        if self.pp > 1:
            o["distributed_strategy.pipeline.schedule"] = self.schedule
        return o

    def describe(self) -> str:
        s = (f"dp={self.dp} tp={self.tp} pp={self.pp} cp={self.cp} "
             f"ep={self.ep} mbs={self.micro_batch_size} "
             f"nm={self.num_microbatches} remat={self.remat}")
        if self.vp > 1:
            s += f" vp={self.vp}"
        if self.pp > 1:
            s += f" sched={self.schedule}"
        return s


@dataclasses.dataclass(frozen=True)
class ModelFacts:
    """Everything the lattice + cost model need, extracted once from a
    loaded config mapping — no arrays, no lowering."""

    family: str                      # llama | mistral | mixtral | gpt
    model_cfg: Any                   # the family's config dataclass
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    hidden: int
    ffn: int
    vocab: int
    seq: int
    global_batch_size: int
    tied_embeddings: bool
    # MoE (0 experts <=> dense)
    num_experts: int = 0
    top_k: int = 0
    moe_frequency: int = 1
    # context-parallel attention fusion the config carries (gates cp > 1)
    cp_fusion: Optional[str] = None  # ring | ulysses | zigzag | None
    #: fusions.flash_block_kv — the kv tile the loader's cp-under-pp
    #: smoothness rule validates against (None -> the kernels' default 512)
    flash_block_kv: Optional[int] = None
    sequence_parallel: bool = False
    zero1: bool = True
    alignment: Optional[str] = None  # None/sft vs dpo/orpo/kto
    lora: bool = False
    precision: Any = None            # raw precision block (cost model)
    declared: Optional[Plan] = None  # the config's own launch choice
    # engineered overlap (distributed_strategy.overlap): the cost model
    # prices the bucketed ZeRO-1 collective structure and lifts the dp
    # hiding prior when the knobs are on
    overlap_bucket_mb: float = 0.0
    overlap_prefetch_ag: bool = True

    @classmethod
    def from_config(cls, cfg: Mapping) -> "ModelFacts":
        """Extract facts from a LOADED (validated, interpolation-resolved)
        config mapping."""
        from neuronx_distributed_training_tpu.data.build import (
            alignment_strategy,
        )

        model = dict(cfg.get("model", {}) or {})
        ds = dict(cfg.get("distributed_strategy", {}) or {})
        data = dict(cfg.get("data", {}) or {})
        fusions = dict(model.get("fusions", {}) or {})
        source = str(cfg.get("model_source", "hf")).lower()
        arch = str(model.get("architecture",
                             model.get("model_type", "llama"))).lower()

        if arch == "mixtral":
            from neuronx_distributed_training_tpu.models import mixtral

            mc: Any = mixtral.MixtralConfig.from_config(model, ds)
            lc = mc.llama
            family = "mixtral"
            experts = int(mc.moe.num_experts)
            top_k = int(mc.moe.top_k)
            moe_freq = int(mc.moe_frequency or 1)
            heads, kv = lc.num_attention_heads, lc.kv_heads
            head_dim, hidden = lc.head_size, lc.hidden_size
            ffn, vocab = lc.intermediate_size, lc.vocab_size
            layers, tied = lc.num_layers, lc.tie_word_embeddings
        elif arch == "gpt" or source == "megatron":
            from neuronx_distributed_training_tpu.models import gpt

            mc = gpt.GPTConfig.from_config(model, ds)
            family = "gpt"
            experts = int(mc.moe.num_experts) if mc.moe is not None else 0
            top_k = int(mc.moe.top_k) if mc.moe is not None else 0
            moe_freq = int(getattr(mc, "moe_frequency", 1) or 1)
            heads, kv = mc.num_attention_heads, mc.kv_heads
            head_dim, hidden = mc.head_size, mc.hidden_size
            ffn, vocab = mc.ffn_size, mc.vocab_size
            layers = mc.num_layers
            tied = bool(getattr(mc, "share_embeddings_and_output_weights",
                                True))
        else:
            from neuronx_distributed_training_tpu.models import llama

            mc = llama.LlamaConfig.from_config(model, ds)
            family = "mistral" if arch == "mistral" else "llama"
            experts = top_k = 0
            moe_freq = 1
            heads, kv = mc.num_attention_heads, mc.kv_heads
            head_dim, hidden = mc.head_size, mc.hidden_size
            ffn, vocab = mc.intermediate_size, mc.vocab_size
            layers, tied = mc.num_layers, mc.tie_word_embeddings

        if fusions.get("ulysses_attention"):
            cp_fusion: Optional[str] = "ulysses"
        elif fusions.get("zigzag_ring_attention"):
            cp_fusion = "zigzag"
        elif fusions.get("ring_attention"):
            cp_fusion = "ring"
        else:
            cp_fusion = None

        try:
            alignment, _ = alignment_strategy(cfg)
        except ValueError:
            alignment = None

        seq = int(data.get("seq_length")
                  or getattr(mc, "max_position_embeddings", 0)
                  or getattr(getattr(mc, "llama", None),
                             "max_position_embeddings", 0) or 2048)
        gbs = int(data.get("global_batch_size", 1))

        facts = cls(
            family=family, model_cfg=mc, num_layers=int(layers),
            num_heads=int(heads), num_kv_heads=int(kv), head_dim=int(head_dim),
            hidden=int(hidden), ffn=int(ffn), vocab=int(vocab), seq=seq,
            global_batch_size=gbs, tied_embeddings=bool(tied),
            num_experts=experts, top_k=top_k, moe_frequency=moe_freq,
            cp_fusion=cp_fusion,
            flash_block_kv=(int(fusions["flash_block_kv"])
                            if fusions.get("flash_block_kv") else None),
            sequence_parallel=bool(ds.get("sequence_parallel", False)),
            zero1=bool(ds.get("zero1", True)),
            alignment=alignment,
            lora=bool(dict(model.get("lora", {}) or {})),
            precision=cfg.get("precision", {}),
            overlap_bucket_mb=float(
                (ds.get("overlap") or {}).get("zero1_bucket_mb", 0.0) or 0.0),
            overlap_prefetch_ag=bool(
                (ds.get("overlap") or {}).get("prefetch_ag", True)),
        )
        declared = facts._declared_plan(ds, data, model)
        return dataclasses.replace(facts, declared=declared)

    def _declared_plan(self, ds: Mapping, data: Mapping,
                       model: Mapping) -> Plan:
        """The config's own launch choice as a Plan (dp left 0 — it depends
        on the chip count; ``declared_plan_for`` resolves it)."""
        remat = model.get("activations_checkpoint_granularity", "selective")
        pipe = dict(ds.get("pipeline", {}) or {})
        return Plan(
            tp=int(ds.get("tensor_model_parallel_size", 1) or 1),
            pp=int(ds.get("pipeline_model_parallel_size", 1) or 1),
            cp=int(ds.get("context_parallel_size", 1) or 1),
            ep=int(ds.get("expert_model_parallel_size", 1) or 1),
            vp=int(ds.get("virtual_pipeline_model_parallel_size", 1) or 1),
            dp=0,
            micro_batch_size=int(data.get("micro_batch_size", 1) or 1),
            num_microbatches=0,
            remat=(remat if remat in REMAT_POLICIES else "none"),
            schedule=str(pipe.get("schedule", "auto")),
        )

    def declared_plan_for(self, chips: int) -> Optional[Plan]:
        """The declared launch config resolved against a chip count (dp and
        microbatch count filled in); None when it doesn't divide."""
        d = self.declared
        if d is None:
            return None
        denom = d.tp * d.pp * d.cp
        if denom == 0 or chips % denom:
            return None
        dp = chips // denom
        if dp < 1 or (d.ep and dp % d.ep):
            return None
        if self.global_batch_size % (d.micro_batch_size * dp):
            return None
        nm = self.global_batch_size // (d.micro_batch_size * dp)
        sched = d.schedule
        if d.pp > 1 and sched == "auto":
            from neuronx_distributed_training_tpu.parallel.pipeline import (
                resolve_schedule,
            )

            sched = resolve_schedule("auto", self.model_cfg,
                                     self._parallel_cfg(d))
        return dataclasses.replace(
            d, dp=dp, num_microbatches=nm,
            schedule=(sched if d.pp > 1 else "none"))

    def _parallel_cfg(self, plan: Plan) -> dict:
        """The ``supports_1f1b`` context dict for a candidate plan."""
        return {
            "pipeline_model_parallel_size": plan.pp,
            "virtual_pipeline_model_parallel_size": plan.vp,
            "context_parallel_size": plan.cp,
            "alignment": (self.alignment
                          if self.alignment in ("dpo", "orpo", "kto")
                          else None),
            "lora": self.lora,
        }

    @property
    def moe_groups(self) -> int:
        """Whole (MoE + dense) layer groups — the pipeline's slicing unit."""
        return self.num_layers // max(self.moe_frequency, 1)


def _tp_candidates(facts: ModelFacts, chips: int) -> list[int]:
    out = []
    for tp in divisors(chips):
        if facts.num_heads % tp:
            continue
        # GQA: kv heads shard over tp, or replicate across it (tp % kv == 0)
        if facts.num_kv_heads % tp and tp % facts.num_kv_heads:
            continue
        # vocab/ffn/seq need no divisibility pruning: GSPMD pads those
        # shardings (GPT-2's 50257 vocab shards over any tp); heads and
        # layers are the structural constraints
        out.append(tp)
    return out


def _pp_candidates(facts: ModelFacts, avail: int) -> list[int]:
    if facts.cp_fusion == "zigzag":
        return [1]  # zigzag attention is pp-incompatible (loader rule)
    out = []
    for pp in divisors(avail):
        if pp > facts.num_layers:
            continue
        if facts.moe_frequency > 1:
            if facts.moe_groups % pp:
                continue
        elif facts.num_layers % pp:
            continue
        if pp > 1 and facts.alignment == "kto":
            # only the batch_mean estimator pipelines; stay conservative and
            # keep KTO off pp in the lattice (the loader rejects mismatched)
            continue
        out.append(pp)
    return out


def _cp_candidates(facts: ModelFacts, avail: int, tp: int, pp: int) -> list[int]:
    if facts.cp_fusion is None:
        return [1]
    out = []
    for cp in divisors(avail):
        if cp > 1:
            if facts.seq % cp:
                continue
            if facts.cp_fusion == "zigzag" and facts.seq % (2 * cp):
                continue
            if facts.cp_fusion == "ulysses" and facts.num_heads % (tp * cp):
                continue
            if pp > 1:
                # blockwise attention under pp needs a smooth kv tile —
                # same knob/default the loader validates (flash_block_kv,
                # kernels default 512) or the lattice and validate_config
                # would disagree about which cp meshes are legal
                from neuronx_distributed_training_tpu.parallel.ring_attention import (  # noqa: E501
                    pick_bkv,
                )

                _, degraded = pick_bkv(facts.seq,
                                       facts.flash_block_kv or 512)
                if degraded:
                    continue
        out.append(cp)
    return out


def _mbs_candidates(facts: ModelFacts, dp: int, *, max_mbs: int = 8,
                    pp: int = 1) -> list[int]:
    per_dp = facts.global_batch_size // dp
    if facts.global_batch_size % dp:
        return []
    cands = [m for m in divisors(per_dp) if m <= max_mbs]
    if pp > 1:
        # a pipeline with fewer microbatches than stages leaves whole stages
        # idle every tick — statically prune mbs that push nm below pp
        cands = [m for m in cands if per_dp // m >= pp] or cands[:1]
    return cands


def enumerate_plans(
    facts: ModelFacts,
    chips: int,
    *,
    max_mbs: int = 8,
    remat_policies: tuple[str, ...] = REMAT_POLICIES,
) -> list[Plan]:
    """The legal plan lattice for ``facts`` on ``chips`` devices —
    deterministic order (``Plan.key``), no duplicates, statically pruned."""
    from neuronx_distributed_training_tpu.parallel.pipeline import (
        supports_1f1b,
    )

    plans: list[Plan] = []
    for tp in _tp_candidates(facts, chips):
        for pp in _pp_candidates(facts, chips // tp):
            for cp in _cp_candidates(facts, chips // (tp * pp), tp, pp):
                if chips % (tp * pp * cp):
                    continue
                dp = chips // (tp * pp * cp)
                ep_opts = [1]
                if facts.num_experts:
                    ep_opts = [e for e in divisors(facts.num_experts)
                               if dp % e == 0]
                for ep in ep_opts:
                    for mbs in _mbs_candidates(facts, dp, max_mbs=max_mbs,
                                               pp=pp):
                        nm = facts.global_batch_size // (mbs * dp)
                        # (schedule, vp) candidates: the manual-vjp family
                        # where the gate admits it, plus the always-legal
                        # wavefront.  1f1b-zb shares 1f1b's shape constraints
                        # (vp == 1); 1f1b-interleaved carries its own vp
                        # lattice dimension (layer-divisible, nm >= pp).
                        scheds: list[tuple[str, int]]
                        if pp == 1:
                            scheds = [("none", 1)]
                        else:
                            base = Plan(tp=tp, pp=pp, cp=cp, ep=ep, dp=dp)
                            ok, _ = supports_1f1b(
                                facts.model_cfg, facts._parallel_cfg(base))
                            scheds = [("wavefront", 1)]
                            if ok:
                                scheds += [("1f1b", 1), ("1f1b-zb", 1)]
                                layer_unit = (facts.moe_groups
                                              if facts.moe_frequency > 1
                                              else facts.num_layers)
                                for vpc in _VP_CANDIDATES:
                                    if (nm >= pp
                                            and layer_unit % (pp * vpc) == 0):
                                        scheds.append(
                                            ("1f1b-interleaved", vpc))
                        # the pipeline stage loop does not fold the remat
                        # policy into its tick structure (compiled temps are
                        # identical across policies under pp — cost_model),
                        # so pp plans carry one canonical remat value
                        # instead of three cost-identical clones
                        if pp > 1:
                            remats: tuple[str, ...] = (
                                ("selective",) if "selective"
                                in remat_policies else remat_policies[:1])
                        else:
                            remats = remat_policies
                        for remat in remats:
                            for sched, vpc in scheds:
                                plans.append(Plan(
                                    tp=tp, pp=pp, cp=cp, ep=ep, dp=dp,
                                    vp=vpc,
                                    micro_batch_size=mbs, num_microbatches=nm,
                                    remat=remat, schedule=sched,
                                ))
    plans.sort(key=Plan.key)
    return plans


def iter_unique_structures(plans: list[Plan]) -> Iterator[tuple[tuple, Plan]]:
    """Yield one representative plan per SHRUNK-audit structure: after
    ``shrink_overrides`` clamps degrees to 2, plans differing only in degree
    magnitude (or microbatch count) lower to the same program shape — audit
    each shape once."""
    seen = set()
    for p in plans:
        key = (min(p.tp, 2), min(p.pp, 2), min(p.cp, 2), min(p.ep, 2),
               min(p.vp, 2), p.remat, p.schedule)
        if key in seen:
            continue
        seen.add(key)
        yield key, p
