"""Chip/interconnect facts the cost model prices plans against.

One :class:`ChipTopology` per TPU generation: peak matmul throughput (the
same public figures ``utils.perf.PEAK_TFLOPS_PER_CHIP`` uses for MFU — one
source of truth via ``peak_tflops_key``), HBM capacity, and the ICI numbers
analytic collective costs are built from.  ``ici_bandwidth_bytes`` is the
usable per-chip bisection-ish figure for ring collectives (per direction,
per link, derated for protocol overhead), ``ici_latency_seconds`` the
per-hop software+wire latency that dominates small transfers.

A ``cpu`` entry exists so the planner is exercisable (and testable) off
hardware: the ratios are chosen to keep ranking behavior realistic (compute
slow, comms slower still) rather than to model any real host fabric.

``dcn_bandwidth_bytes`` prices the slow inter-slice fabric for worlds larger
than one slice; the planner currently treats the whole world as one ICI
domain and leaves multi-slice pricing as a documented blind spot
(docs/autotuning.md).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ChipTopology:
    """Static per-chip facts of one TPU generation."""

    name: str
    #: key into utils.perf.PEAK_TFLOPS_PER_CHIP (MFU's table — shared)
    peak_tflops_key: str
    hbm_bytes: int
    #: usable ring-collective bandwidth per chip, bytes/s (per direction)
    ici_bandwidth_bytes: float
    #: per-hop latency floor, seconds
    ici_latency_seconds: float
    #: inter-slice (DCN) bandwidth per chip, bytes/s
    dcn_bandwidth_bytes: float = 25.0e9 / 8
    #: matmul efficiency the compute roofline assumes (achievable MFU on
    #: large well-tiled matmuls, not the marketing peak)
    compute_efficiency: float = 0.55
    #: default fraction of collective wire time the XLA scheduler hides
    #: under concurrent compute on this generation (async collective fusion,
    #: per-layer gather-matmul pipelining) — the cost model's prior when no
    #: MEASURED calibration is supplied (``telemetry.trace`` writes the
    #: measured figure to ``trace_summary.json``; ``tools/plan.py
    #: --calibrate-from`` feeds it back in and overrides this)
    comms_overlap: float = 0.5

    @property
    def peak_flops(self) -> float:
        from neuronx_distributed_training_tpu.utils.perf import (
            PEAK_TFLOPS_PER_CHIP,
        )

        return PEAK_TFLOPS_PER_CHIP[self.peak_tflops_key] * 1e12


#: the topology table --apply/--topology select from.  ICI figures are the
#: public per-chip numbers derated to ~80% usable; HBM leaves the runtime's
#: own reservation alone (the planner applies its headroom separately).
TOPOLOGIES: dict[str, ChipTopology] = {
    "v5e": ChipTopology(
        name="v5e",
        peak_tflops_key="v5e",
        hbm_bytes=16 * 1024**3,
        # 2D torus, ~45 GB/s/dir/link; a ring collective drives both
        # directions of one axis -> ~90 GB/s effective per chip
        ici_bandwidth_bytes=90e9,
        ici_latency_seconds=1e-6,
        comms_overlap=0.5,
    ),
    "v5p": ChipTopology(
        name="v5p",
        peak_tflops_key="v5p",
        hbm_bytes=95 * 1024**3,
        # 3D torus, ~90 GB/s/dir/link, bidirectional ring
        ici_bandwidth_bytes=180e9,
        ici_latency_seconds=1e-6,
        # 3D torus: more ring axes available to schedule around, and the
        # latency-hiding scheduler has deeper HBM headroom for prefetch
        comms_overlap=0.55,
    ),
    "v6e": ChipTopology(
        name="v6e",
        peak_tflops_key="v6e",
        hbm_bytes=32 * 1024**3,
        ici_bandwidth_bytes=180e9,
        ici_latency_seconds=1e-6,
        comms_overlap=0.55,
    ),
    "v4": ChipTopology(
        name="v4",
        peak_tflops_key="v4",
        hbm_bytes=32 * 1024**3,
        # 3D torus, ~45 GB/s/dir/link, bidirectional ring
        ici_bandwidth_bytes=90e9,
        ici_latency_seconds=1e-6,
        comms_overlap=0.45,
    ),
    # off-hardware planning/test fallback: ratios realistic, magnitudes not
    "cpu": ChipTopology(
        name="cpu",
        peak_tflops_key="cpu",
        hbm_bytes=8 * 1024**3,
        ici_bandwidth_bytes=2e9,
        ici_latency_seconds=20e-6,
        compute_efficiency=0.5,
    ),
}


def resolve_topology(name: Optional[str] = None,
                     device: Optional[Any] = None) -> ChipTopology:
    """Topology by explicit name, else detected from a live jax device, else
    the ``cpu`` fallback.  Unknown names raise with the valid set (the CLI's
    ``--topology`` funnels through here)."""
    if name:
        key = str(name).lower()
        if key not in TOPOLOGIES:
            raise ValueError(
                f"unknown topology {name!r}; known: "
                f"{'/'.join(sorted(TOPOLOGIES))}"
            )
        return TOPOLOGIES[key]
    if device is not None:
        kind = getattr(device, "device_kind", device.platform).lower()
        for key in ("v6e", "v6", "v5p", "v5e", "v4"):
            if key in kind or (key == "v5e" and "lite" in kind):
                return TOPOLOGIES["v6e" if key.startswith("v6") else key]
        if device.platform == "tpu":
            # an unrecognized generation priced with the wrong HBM table
            # would approve plans that OOM — be loud, not silently wrong
            logger.warning(
                "unrecognized TPU device_kind %r: pricing as v5p — pass an "
                "explicit topology (known: %s) if that table is wrong for "
                "this chip", kind, "/".join(sorted(TOPOLOGIES)),
            )
            return TOPOLOGIES["v5p"]
    return TOPOLOGIES["cpu"]
