"""Checkpointing — sharded async save/load, top-k retention, auto-resume.

TPU-native re-design of the reference's checkpoint stack
(``NLPCheckpointIO`` → ``nxd.save_checkpoint/load_checkpoint``, reference
``nlp_overrides.py:535-639``; resume discovery in ``exp_manager.py:333-404``),
built on Orbax/TensorStore: every host writes its own shards (the xser
tensor-streaming role), async save runs in a background thread (the
``async_checkpointing`` role), retention keeps top-k + last.
"""

from neuronx_distributed_training_tpu.checkpoint.integrity import (  # noqa: F401
    CheckpointIntegrityError,
    IntegrityConfig,
    StepVerification,
    inject_corruption,
)
from neuronx_distributed_training_tpu.checkpoint.manager import (  # noqa: F401
    CheckpointConfig,
    Checkpointer,
    TrainState,
    is_transient_save_error,
)
