"""Checkpoint integrity: end-to-end digests, verified restore, quarantine.

PR 9 closed the *availability* half of the NxDT resilience story (a failed
save never shadows the last good one, elastic resume reshards onto the live
fleet).  This module closes the *correctness* half: a save that committed
successfully yet is **corrupt** — bitrot on the store, a truncated object
after a partial upload, a torn multi-host write, version-skewed
serialization — must be detected and walked past, not crash-looped into.

Mechanics (docs/elasticity.md "Integrity & walk-back"):

- every save carries an ``integrity`` sidecar item (:func:`build_sidecar`):
  per-leaf-group content digests (``params``, ``opt_state/mu``,
  ``opt_state/master``, EMA, health, …) over the serialized bytes of every
  leaf, digests of the ``meta``/``manifest`` JSON items, and a
  tree-structure/shape/dtype summary — all computed host-side from the very
  trees handed to orbax (after the ``save_bf16`` cast, so the digests match
  the on-disk bytes);
- restore verifies the sidecar **before** imposing a mesh
  (:func:`verify_step` is template-free: items are read back with no target
  tree and re-hashed), and on mismatch the step is **quarantined** (the step
  dir is renamed ``quarantined.<step>.<reason>`` — invisible to orbax step
  discovery and to ``latest_version`` parsing — plus a ledger entry) and the
  walk-back continues to the newest step that verifies;
- a checkpoint that predates this subsystem (no sidecar) restores with a
  warning, never a crash;
- an optional post-commit **save audit** (:class:`SaveAuditor`, behind
  ``exp_manager.checkpoint.integrity.audit``) re-reads committed steps on a
  background thread so corruption is caught at save time, not days later.

The knob block (validated at config load with did-you-mean hints):

.. code-block:: yaml

    exp_manager:
      checkpoint:
        integrity:
          enabled: true                 # digest sidecar in every save
          verify_restore: true          # verify + walk back before restore
          quarantine: true              # rename + ledger corrupt steps
          audit: false                  # post-commit read-back audit
          audit_deadline_seconds: 120.0 # teardown drain bound
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import queue
import re
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Callable, Mapping, Optional

import numpy as np

logger = logging.getLogger(__name__)

#: sidecar schema version (bump on breaking layout changes)
INTEGRITY_FORMAT = 1

#: orbax item name of the sidecar inside every save
INTEGRITY_ITEM = "integrity"

#: digest algorithm recorded in the sidecar (verification refuses a sidecar
#: hashed with an algorithm this build does not know)
DIGEST_ALGO = "blake2b-128"

#: quarantined step dirs are renamed ``quarantined.<step>.<reason>`` — the
#: leading prefix is non-numeric, so orbax step discovery and the
#: exp-manager ``version_N`` parse both skip them by construction
QUARANTINE_PREFIX = "quarantined."

#: quarantine ledger filename (checkpoint-root sibling of the step dirs)
LEDGER_NAME = "quarantine_ledger.json"

#: corruption kinds the drill harness can inject (tools/elastic_drill.py)
CORRUPTION_KINDS = ("byte_flip", "truncate", "delete_item", "stale_sidecar")

#: knob name -> default — the single source of truth the validator,
#: ``from_config``, and docs/elasticity.md share
INTEGRITY_KNOBS: dict[str, Any] = {
    "enabled": True,
    "verify_restore": True,
    "quarantine": True,
    "audit": False,
    "audit_deadline_seconds": 120.0,
}

#: keys the ``exp_manager.checkpoint`` block accepts
CHECKPOINT_BLOCK_KEYS = frozenset({"integrity"})


class CheckpointIntegrityError(RuntimeError):
    """No retained checkpoint verifies: every step in the retention chain is
    corrupt (or quarantined).  Carries the per-step verdicts so the operator
    sees *what* failed where instead of an opaque restore crash."""

    def __init__(self, message: str, verdicts: Optional[list] = None):
        super().__init__(message)
        self.verdicts = list(verdicts or [])


@dataclasses.dataclass(frozen=True)
class IntegrityConfig:
    """``exp_manager.checkpoint.integrity`` — checkpoint-integrity policy."""

    enabled: bool = True
    verify_restore: bool = True
    quarantine: bool = True
    audit: bool = False
    audit_deadline_seconds: float = 120.0

    @classmethod
    def from_config(cls, block: Any) -> "IntegrityConfig":
        """Parse (and validate) an ``exp_manager.checkpoint.integrity``
        block.  Accepts ``None``/``{}`` (defaults) or a mapping; a bare bool
        toggles ``enabled``.  Unknown keys and ill-typed values raise
        ``ValueError`` with a did-you-mean hint — a typo'd knob must not
        silently run with defaults."""
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.checkpoint.integrity must be a mapping of "
                f"{sorted(INTEGRITY_KNOBS)} (or a single bool), got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - set(INTEGRITY_KNOBS)
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown exp_manager.checkpoint.integrity keys "
                f"{sorted(unknown)}; supported: {sorted(INTEGRITY_KNOBS)}"
                + did_you_mean(unknown, INTEGRITY_KNOBS)
            )
        values: dict[str, Any] = {}
        for k, v in block.items():
            default = INTEGRITY_KNOBS[k]
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(
                        f"exp_manager.checkpoint.integrity.{k} must be a "
                        f"boolean, got {v!r}"
                    )
                values[k] = v
            else:
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(
                        f"exp_manager.checkpoint.integrity.{k} must be a "
                        f"number, got {v!r}"
                    )
                values[k] = float(v)
                if values[k] < 0.0:
                    raise ValueError(
                        f"exp_manager.checkpoint.integrity.{k} must be >= 0, "
                        f"got {v!r}"
                    )
        return cls(**values)


def parse_checkpoint_block(block: Any) -> IntegrityConfig:
    """Validate an ``exp_manager.checkpoint`` block and return its parsed
    :class:`IntegrityConfig`.  ``None`` → defaults.  Unknown sub-blocks are
    rejected with a did-you-mean hint (``checkpoint_callback_params`` keeps
    its separate reference-schema home — this block is for the NEW validated
    knobs only)."""
    if block is None:
        return IntegrityConfig()
    if not isinstance(block, Mapping):
        raise ValueError(
            f"exp_manager.checkpoint must be a mapping of "
            f"{sorted(CHECKPOINT_BLOCK_KEYS)}, got {type(block).__name__}"
        )
    unknown = set(block) - CHECKPOINT_BLOCK_KEYS
    if unknown:
        from neuronx_distributed_training_tpu.config.loader import (
            did_you_mean,
        )

        raise ValueError(
            f"unknown exp_manager.checkpoint keys {sorted(unknown)}; "
            f"supported: {sorted(CHECKPOINT_BLOCK_KEYS)}"
            + did_you_mean(unknown, CHECKPOINT_BLOCK_KEYS)
        )
    return IntegrityConfig.from_config(block.get("integrity"))


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------


def _hasher():
    return hashlib.blake2b(digest_size=16)


def json_digest(obj: Any) -> str:
    """Digest of a JSON-serializable object over its *normalized* form (one
    ``dumps``/``loads`` round-trip first, so the digest of the in-memory dict
    matches the digest of what ``JsonRestore`` hands back)."""
    normalized = json.loads(json.dumps(obj, default=str))
    h = _hasher()
    h.update(json.dumps(normalized, sort_keys=True,
                        separators=(",", ":")).encode())
    return h.hexdigest()


def _leaf_entries(tree: Any) -> list[tuple[str, Any]]:
    """``(path, leaf)`` pairs sorted by path — the canonical leaf order both
    the save-side and verify-side hashing walk."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    entries = [(jax.tree_util.keystr(kp), leaf) for kp, leaf in flat]
    entries.sort(key=lambda e: e[0])
    return entries


def _group_of(item: str, path: str, split_top_level: bool) -> str:
    """Leaf-group name: ``params`` stays one group; ``opt_state`` splits on
    its top-level key (``opt_state/mu``, ``opt_state/master``, …) so a
    mismatch names the damaged subtree."""
    if not split_top_level:
        return item
    m = re.match(r"\['([^']+)'\]", path)
    return f"{item}/{m.group(1)}" if m else item


def tree_digest_groups(
    item: str, tree: Any, *, split_top_level: bool = False
) -> tuple[dict[str, dict[str, Any]], dict[str, dict[str, Any]], bool]:
    """Per-leaf-group content digests + structure summary for one item tree.

    Returns ``(groups, structure, content)``: ``groups`` maps group name →
    ``{digest, leaves, bytes}``; ``structure`` maps leaf path →
    ``{dtype, shape}``; ``content`` is False when the leaf bytes could not be
    fetched (non-fully-addressable arrays on a multi-host run — integrity
    then degrades to the structure summary, with a warning)."""
    hashers: dict[str, Any] = {}
    counts: dict[str, int] = {}
    sizes: dict[str, int] = {}
    structure: dict[str, dict[str, Any]] = {}
    content = True
    for path, leaf in _leaf_entries(tree):
        arr_meta_shape = tuple(getattr(leaf, "shape", ()) or ())
        arr_meta_dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        structure[path] = {"dtype": arr_meta_dtype,
                           "shape": list(arr_meta_shape)}
        group = _group_of(item, path, split_top_level)
        h = hashers.setdefault(group, _hasher())
        counts[group] = counts.get(group, 0) + 1
        header = f"{path}|{arr_meta_dtype}|{arr_meta_shape}".encode()
        h.update(header)
        if not content:
            continue
        try:
            arr = np.ascontiguousarray(np.asarray(leaf))
        except Exception as e:  # noqa: BLE001 — non-addressable (multi-host)
            logger.warning(
                "integrity: cannot fetch %s/%s for hashing (%s: %s) — "
                "digests degrade to structure-only for this save",
                item, path, type(e).__name__, e,
            )
            content = False
            continue
        data = arr.tobytes()
        h.update(data)
        sizes[group] = sizes.get(group, 0) + len(data)
    groups = {
        g: {
            "digest": h.hexdigest(),
            "leaves": counts[g],
            "bytes": sizes.get(g, 0),
        }
        for g, h in hashers.items()
    }
    return groups, structure, content


def build_sidecar(
    *,
    step: int,
    params: Any,
    opt_state: Any,
    meta: Mapping[str, Any],
    manifest: Optional[Mapping[str, Any]] = None,
) -> dict[str, Any]:
    """The ``integrity`` sidecar item saved with every checkpoint: content
    digests per leaf-group over the exact trees handed to orbax (call AFTER
    the ``save_bf16`` cast / master drop), JSON digests for meta + manifest,
    and the tree-structure summary."""
    p_groups, p_struct, p_content = tree_digest_groups("params", params)
    o_groups, o_struct, o_content = tree_digest_groups(
        "opt_state", opt_state, split_top_level=True)
    return {
        "format": INTEGRITY_FORMAT,
        "algo": DIGEST_ALGO,
        "step": int(step),
        "content": bool(p_content and o_content),
        "groups": {**p_groups, **o_groups},
        "tree": {"params": p_struct, "opt_state": o_struct},
        "meta_digest": json_digest(dict(meta)),
        "manifest_digest": (json_digest(dict(manifest))
                            if manifest is not None else None),
    }


# ---------------------------------------------------------------------------
# verification
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StepVerification:
    """One step's integrity verdict.  ``status``:

    - ``ok``      sidecar present, every digest matches;
    - ``legacy``  no sidecar (pre-integrity checkpoint) — restorable, warned;
    - ``corrupt`` sidecar/digest mismatch or an unreadable item;
    - ``gone``    the step dir vanished mid-verify (retention race — the
      audit thread treats this as "nothing to verify", not corruption).
    """

    step: int
    status: str
    failures: list[str] = dataclasses.field(default_factory=list)
    groups_checked: int = 0
    seconds: float = 0.0

    @property
    def passed(self) -> bool:
        """Restorable?  ``ok`` and ``legacy`` both restore (legacy with a
        warning); ``gone`` is vacuously passed — there is nothing to
        quarantine."""
        return self.status != "corrupt"

    def to_dict(self) -> dict[str, Any]:
        return {
            "step": self.step, "status": self.status,
            "failures": list(self.failures),
            "groups_checked": self.groups_checked,
            "seconds": round(self.seconds, 3),
        }


def open_readonly_manager(directory) -> Any:
    """A fresh synchronous orbax manager over an EXISTING checkpoint dir for
    template-free verification reads — the offline CLI and the audit thread
    each open their own (orbax managers are not thread-shareable)."""
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(
            enable_async_checkpointing=False, save_interval_steps=1),
    )


def _step_dir(directory, step: int):
    return directory / str(int(step))


def verify_step(directory, step: int, *, mgr: Any = None) -> StepVerification:
    """Template-free integrity verification of one retained step.

    Reads the sidecar, re-reads every digested item with NO target tree
    (params/opt_state restore as plain host arrays, meta/manifest as JSON),
    re-hashes, and compares.  Any read failure on a digested item IS a
    verification failure — a truncated or missing file surfaces here as a
    curated verdict instead of a restore-time crash.

    Runs before any mesh exists: safe at discovery time, in the offline CLI,
    and on the audit thread.  NOTE the read materializes each item unsharded
    on the host — the cost of end-to-end verification.
    """
    import orbax.checkpoint as ocp

    t0 = time.perf_counter()
    sdir = _step_dir(directory, step)
    if not sdir.exists():
        return StepVerification(step=int(step), status="gone",
                                seconds=time.perf_counter() - t0)
    own_mgr = mgr is None
    if own_mgr:
        mgr = open_readonly_manager(directory)
    failures: list[str] = []
    groups_checked = 0
    try:
        if not (sdir / INTEGRITY_ITEM).exists():
            return StepVerification(
                step=int(step), status="legacy",
                seconds=time.perf_counter() - t0)
        try:
            sidecar = dict(mgr.restore(
                int(step),
                args=ocp.args.Composite(
                    **{INTEGRITY_ITEM: ocp.args.JsonRestore()}),
            )[INTEGRITY_ITEM])
        except Exception as e:  # noqa: BLE001 — an unreadable sidecar is
            # itself corruption (the item exists but cannot be parsed) —
            # unless the whole step dir vanished under the read (see the
            # 'gone' recheck below)
            return StepVerification(
                step=int(step),
                status="corrupt" if sdir.exists() else "gone",
                failures=([f"integrity sidecar unreadable: "
                           f"{type(e).__name__}: {e}"]
                          if sdir.exists() else []),
                seconds=time.perf_counter() - t0)
        if sidecar.get("algo") != DIGEST_ALGO:
            return StepVerification(
                step=int(step), status="corrupt",
                failures=[f"unknown digest algo {sidecar.get('algo')!r} "
                          f"(this build computes {DIGEST_ALGO})"],
                seconds=time.perf_counter() - t0)
        if int(sidecar.get("step", -1)) != int(step):
            failures.append(
                f"stale sidecar: records step {sidecar.get('step')} but "
                f"lives in step {step}")

        def read_json(item):
            return mgr.restore(
                int(step),
                args=ocp.args.Composite(**{item: ocp.args.JsonRestore()}),
            )[item]

        def read_tree(item):
            # DEVICE-INDEPENDENT read: restore every leaf as plain numpy via
            # explicit RestoreArgs.  The template-free StandardRestore would
            # pin to the sharding metadata saved with the arrays — and fail
            # outright on a host whose device count differs from the saving
            # fleet (exactly where offline verification runs)
            import jax as _jax

            ckpt = ocp.PyTreeCheckpointer()
            try:
                md = ckpt.metadata(sdir / item)
                is_arr = lambda x: hasattr(x, "shape")  # noqa: E731
                ra = _jax.tree_util.tree_map(
                    lambda x: ocp.RestoreArgs(restore_type=np.ndarray),
                    md, is_leaf=is_arr)
                return ckpt.restore(sdir / item, restore_args=ra)
            finally:
                try:
                    ckpt.close()
                except Exception:  # noqa: BLE001 — read-only teardown
                    pass

        # meta / manifest JSON digests
        for item, want in (("meta", sidecar.get("meta_digest")),
                           ("manifest", sidecar.get("manifest_digest"))):
            if want is None:
                continue
            groups_checked += 1
            try:
                have = json_digest(dict(read_json(item)))
            except Exception as e:  # noqa: BLE001 — read failure = corrupt
                failures.append(
                    f"{item}: unreadable ({type(e).__name__}: {e})")
                continue
            if have != want:
                failures.append(f"{item}: digest mismatch "
                                f"(saved {want}, read back {have})")

        # array items: re-read template-free, re-hash with the same walk
        expected = dict(sidecar.get("groups") or {})
        tree_summary = dict(sidecar.get("tree") or {})
        has_content = bool(sidecar.get("content", True))
        for item in ("params", "opt_state"):
            item_groups = {g: v for g, v in expected.items()
                           if g == item or g.startswith(item + "/")}
            if not item_groups:
                continue
            try:
                tree = read_tree(item)
            except Exception as e:  # noqa: BLE001 — read failure = corrupt
                failures.append(
                    f"{item}: unreadable ({type(e).__name__}: {e})")
                continue
            got_groups, got_struct, got_content = tree_digest_groups(
                item, tree, split_top_level=(item == "opt_state"))
            want_struct = dict(tree_summary.get(item) or {})
            for path in sorted(set(want_struct) | set(got_struct))[:2048]:
                w, g = want_struct.get(path), got_struct.get(path)
                if w != g:
                    failures.append(
                        f"{item}{path}: structure drift "
                        f"(saved {w}, read back {g})")
            if not (has_content and got_content):
                # save-side (multi-host) or read-side degraded to
                # structure-only: digests are not comparable
                groups_checked += len(item_groups)
                continue
            for g in sorted(item_groups):
                groups_checked += 1
                want_d = item_groups[g].get("digest")
                have_d = (got_groups.get(g) or {}).get("digest")
                if have_d != want_d:
                    failures.append(
                        f"{g}: content digest mismatch "
                        f"(saved {want_d}, read back {have_d})")
        status = "corrupt" if failures else "ok"
        if status == "corrupt" and not sdir.exists():
            # the step dir was deleted UNDER the read (top-k retention or a
            # concurrent quarantine on another actor): the read failures are
            # an artifact of the race, not corruption — the 'gone' status
            # exists precisely for this
            return StepVerification(
                step=int(step), status="gone",
                seconds=time.perf_counter() - t0)
        return StepVerification(
            step=int(step), status=status, failures=failures,
            groups_checked=groups_checked,
            seconds=time.perf_counter() - t0)
    finally:
        if own_mgr:
            try:
                mgr.close()
            except Exception:  # noqa: BLE001 — read-only teardown
                pass


# ---------------------------------------------------------------------------
# quarantine
# ---------------------------------------------------------------------------


def _reason_slug(reason: str) -> str:
    slug = re.sub(r"[^A-Za-z0-9]+", "-", reason).strip("-").lower()
    return (slug or "corrupt")[:48]


def quarantine_name(step: int, reason: str) -> str:
    return f"{QUARANTINE_PREFIX}{int(step)}.{_reason_slug(reason)}"


def parse_quarantine_name(name: str) -> Optional[int]:
    """Step number of a quarantined dir name, or ``None`` for anything else
    (the round-trip the discovery tests pin: a quarantined name must never
    parse as a live step, and this parse must recover the original step)."""
    if not name.startswith(QUARANTINE_PREFIX):
        return None
    rest = name[len(QUARANTINE_PREFIX):]
    head = rest.split(".", 1)[0]
    return int(head) if head.isdigit() else None


def read_ledger(directory) -> list[dict[str, Any]]:
    """Entries of the quarantine ledger (empty when none)."""
    path = directory / LEDGER_NAME
    try:
        if not path.exists():
            return []
        data = json.loads(path.read_text())
        return list(data.get("entries") or [])
    except Exception as e:  # noqa: BLE001 — a torn ledger must not block
        logger.warning("quarantine ledger %s unreadable: %s", path, e)
        return []


def _append_ledger(directory, entry: dict[str, Any]) -> None:
    path = directory / LEDGER_NAME
    entries = read_ledger(directory)
    entries.append(entry)
    payload = json.dumps({"entries": entries}, indent=1, sort_keys=True) + "\n"
    if isinstance(path, Path):
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(payload)
        tmp.replace(path)
    else:  # remote store: whole-object writes commit atomically
        path.write_text(payload)


def apply_quarantine(directory, step: int, *, reason: str,
                     failures: Optional[list[str]] = None) -> bool:
    """Rename ``<dir>/<step>`` out of the discovery namespace and record the
    ledger entry.  Returns True when the step dir was actually moved (False:
    already gone, or the rename failed — the ledger entry is written either
    way so the event is never silent)."""
    src = _step_dir(directory, step)
    dst = directory / quarantine_name(step, reason)
    moved = False
    try:
        if src.exists():
            src.rename(dst)
            moved = True
    except Exception as e:  # noqa: BLE001 — a failed rename (exotic remote
        # store) must not turn detection into a crash; the ledger + logs
        # still carry the verdict
        logger.error(
            "quarantine of step %d failed to rename %s -> %s: %s "
            "(the corrupt step remains discoverable — remove it by hand)",
            step, src, dst, e)
    entry = {
        "step": int(step),
        "reason": reason,
        "failures": list(failures or [])[:16],
        "quarantined_to": dst.name if moved else None,
        "time": time.strftime("%Y-%m-%d %H:%M:%S"),
    }
    try:
        _append_ledger(directory, entry)
    except Exception as e:  # noqa: BLE001 — best-effort record
        logger.warning("quarantine ledger write failed for step %d: %s",
                       step, e)
    logger.error(
        "checkpoint step %d QUARANTINED (%s): %s", step, reason,
        "; ".join((failures or ["no detail"])[:4]))
    return moved


# ---------------------------------------------------------------------------
# corruption injection (the drill harness's bitrot switch)
# ---------------------------------------------------------------------------


def inject_corruption(directory, step: int, kind: str, *,
                      item: str = "params") -> str:
    """Deliberately damage a COMMITTED checkpoint step — the drill harness's
    stand-in for bitrot/truncated-upload/torn-write/stale-metadata.  Returns
    a description of what was done (drill reports carry it).

    - ``byte_flip``      flip one byte in the middle of the largest data
      file of ``item``;
    - ``truncate``       cut the largest data file of ``item`` in half;
    - ``delete_item``    remove the whole ``item`` directory;
    - ``stale_sidecar``  replace the step's ``integrity`` sidecar with the
      next-older step's (falls back to tampering a digest when no older
      sidecar exists).
    """
    if kind not in CORRUPTION_KINDS:
        raise ValueError(
            f"unknown corruption kind {kind!r}; supported: "
            f"{'/'.join(CORRUPTION_KINDS)}")
    sdir = _step_dir(directory, step)
    if not sdir.exists():
        raise FileNotFoundError(f"no committed step {step} under {directory}")

    def data_files(root):
        # prefer the OCDBT data payloads the manifest actually READS.  Newer
        # orbax/tensorstore merges per-process writes into a top-level
        # "<item>/d/<hash>" kvstore and restores through that; the
        # "ocdbt.process_N/d/" copies become write-side staging, so damaging
        # one is invisible to both restore and verification.  Older layouts
        # keep the payloads only under the process dirs — fall back there,
        # then to any file (largest first)
        top = root / "d"
        files = ([p for p in top.glob("*") if p.is_file()]
                 if top.is_dir() else [])
        if not files:
            files = [p for p in root.rglob("*")
                     if p.is_file() and p.parent.name == "d"]
        if not files:
            files = [p for p in root.rglob("*") if p.is_file()]
        files.sort(key=lambda p: p.stat().st_size, reverse=True)
        return files

    if kind in ("byte_flip", "truncate"):
        root = sdir / item
        files = data_files(root) if root.exists() else []
        if not files:
            raise FileNotFoundError(f"no files under {root} to corrupt")
        target = files[0]
        size = target.stat().st_size
        if kind == "byte_flip":
            pos = max(size // 2 - 1, 0)
            with open(target, "r+b") as f:
                f.seek(pos)
                b = f.read(1)
                f.seek(pos)
                f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
            return (f"byte_flip: flipped byte {pos} of "
                    f"{target.relative_to(sdir)} ({size} bytes)")
        with open(target, "r+b") as f:
            f.truncate(max(size // 2, 1))
        return (f"truncate: {target.relative_to(sdir)} "
                f"{size} -> {max(size // 2, 1)} bytes")
    if kind == "delete_item":
        root = sdir / item
        if not root.exists():
            raise FileNotFoundError(f"no item {item} under {sdir}")
        shutil.rmtree(root)
        return f"delete_item: removed {item}/"
    # stale_sidecar
    dst = sdir / INTEGRITY_ITEM / "metadata"
    if not dst.exists():
        raise FileNotFoundError(
            f"step {step} has no integrity sidecar to go stale")
    older = sorted(
        (int(p.name) for p in directory.iterdir()
         if p.name.isdigit() and int(p.name) < int(step)
         and (p / INTEGRITY_ITEM / "metadata").exists()),
        reverse=True)
    if older:
        src = directory / str(older[0]) / INTEGRITY_ITEM / "metadata"
        dst.write_text(src.read_text())
        return f"stale_sidecar: copied step {older[0]}'s sidecar over {step}'s"
    side = json.loads(dst.read_text())
    for g in side.get("groups", {}).values():
        g["digest"] = "0" * 32
    dst.write_text(json.dumps(side))
    return "stale_sidecar: zeroed every group digest (no older sidecar)"


# ---------------------------------------------------------------------------
# post-commit save audit
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AuditStats:
    audited: int = 0
    failed: int = 0
    seconds: float = 0.0
    incomplete: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {"audited": self.audited, "failed": self.failed,
                "seconds": round(self.seconds, 3),
                "incomplete": self.incomplete}


class SaveAuditor:
    """Background post-commit read-back verification of committed steps.

    The trainer's hot path never blocks on it: :meth:`schedule` enqueues a
    COMMITTED step; a daemon thread re-reads and re-hashes it
    (:func:`verify_step` with its own read-only manager); :meth:`poll`
    returns completed verdicts without waiting — the SNAPSHOT the emergency
    save path takes at the stop boundary (an in-flight audit keeps running;
    a finished failure still gets its quarantine even while the run is
    stopping).  :meth:`drain` bounds the teardown wait by the configured
    deadline; jobs still unfinished then are counted ``incomplete``, never
    joined unboundedly — the grace window cannot deadlock on an audit.
    """

    def __init__(self, directory, *,
                 verify_fn: Optional[Callable[[Any, int],
                                              StepVerification]] = None):
        self.directory = directory
        self._verify = verify_fn or (lambda d, s: verify_step(d, s))
        self._q: "queue.Queue[Optional[int]]" = queue.Queue()
        self._cond = threading.Condition()
        self._pending = 0  # queued + in-flight (under _cond)
        self._done: list[StepVerification] = []
        self.stats = AuditStats()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="nxdt-ckpt-audit")
            self._thread.start()

    def _run(self) -> None:
        while True:
            step = self._q.get()
            if step is None:
                return
            t0 = time.perf_counter()
            try:
                v = self._verify(self.directory, int(step))
            except Exception as e:  # noqa: BLE001 — the audit itself failing
                # is a verdict, not a crash (e.g. store unreachable)
                v = StepVerification(
                    step=int(step), status="corrupt",
                    failures=[f"audit error: {type(e).__name__}: {e}"])
            v.seconds = time.perf_counter() - t0
            with self._cond:
                self._done.append(v)
                self.stats.audited += 1
                self.stats.seconds += v.seconds
                if v.status == "corrupt":
                    self.stats.failed += 1
                self._pending -= 1
                self._cond.notify_all()

    def schedule(self, step: int) -> None:
        """Enqueue a committed step for background verification."""
        if self._closed:
            return
        self._ensure_thread()
        with self._cond:
            self._pending += 1
        self._q.put(int(step))

    def poll(self) -> list[StepVerification]:
        """Completed verdicts so far — non-blocking (the boundary/emergency
        snapshot).  Clears the internal list."""
        with self._cond:
            out, self._done = self._done, []
            return out

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait (bounded) for in-flight audits; True when everything
        finished.  Unfinished jobs are recorded ``incomplete``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending > 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    self.stats.incomplete += self._pending
                    logger.warning(
                        "save audit: %d verification(s) still running at the "
                        "drain deadline — verdicts will be lost with this "
                        "process (raise audit_deadline_seconds to wait "
                        "longer)", self._pending)
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def close(self, timeout: Optional[float] = None) -> list[StepVerification]:
        """Drain (bounded), stop the worker, and return the final verdicts."""
        self._closed = True
        self.drain(timeout)
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
        return self.poll()
