"""Orbax-backed checkpoint manager.

Capability map to the reference (SURVEY.md §5.4):

- per-rank sharded save / tensor streaming (``save_xser``/``load_xser``,
  reference ``nlp_overrides.py:1141-1155``)      -> Orbax OCDBT/TensorStore,
  every process writes its own shards, restore is sharding-aware;
- ``async_checkpointing`` (forked writer process, ``known_issues.rst:53-81``)
  -> Orbax async checkpointing (background thread + commit future);
- top-k retention + auto-delete (``config_overview.rst:243-249``)
  -> ``max_to_keep`` + ``best_fn`` on the monitored metric;
- auto-resume from newest checkpoint (``exp_manager.py:333-404``)
  -> ``latest_step()`` + ``restore``;
- filename-encoded ``consumed_samples`` (``data/base.py:40-47``)
  -> explicit ``meta`` JSON item per step (no regex parsing needed; the value
  rides inside the checkpoint);
- ``weight_init_only`` warm start (``nlp_overrides.py:541-568``)
  -> ``restore_params_only``.
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import time
from pathlib import Path
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.checkpoint import integrity as ck_integrity
from neuronx_distributed_training_tpu.checkpoint.integrity import (
    CheckpointIntegrityError,
    IntegrityConfig,
    SaveAuditor,
)

logger = logging.getLogger(__name__)

#: errno values treated as TRANSIENT save-I/O failures (full disk being
#: cleaned by retention, a flaky NFS/FUSE mount, an object-store hiccup) —
#: worth a bounded retry with backoff.  Anything else (bad tree, permission,
#: programming error) re-raises immediately.
TRANSIENT_SAVE_ERRNOS = frozenset({
    errno.ENOSPC, errno.EIO, errno.EAGAIN, errno.EBUSY, errno.ETIMEDOUT,
    errno.EINTR, errno.EDQUOT,
})


def is_transient_save_error(exc: BaseException) -> bool:
    """Is ``exc`` (or anything in its cause/context chain) a transient I/O
    error worth retrying?  Orbax wraps the underlying ``OSError`` in its own
    exception types, so the chain is walked, not just the top."""
    seen: set[int] = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, TimeoutError):
            return True
        if isinstance(cur, OSError) and cur.errno in TRANSIENT_SAVE_ERRNOS:
            return True
        cur = cur.__cause__ or cur.__context__
    return False


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Mirrors the reference's ``exp_manager.checkpoint_callback_params`` +
    ``save_xser``/``async_checkpointing`` knobs (``config_overview.rst:243-308``)."""

    dir: str | Path = "checkpoints"
    save_top_k: int = 3
    every_n_train_steps: int = 100
    async_save: bool = True
    monitor: str = "loss"  # metric whose *lowest* value defines "best"
    # reference exp_manager.save_bf16 (exp_manager.py:58): store model weights
    # in bf16 — halves params bytes; restore casts back up (resume is no
    # longer bitwise, the knob's inherent trade)
    save_bf16: bool = False
    # reference checkpoint_callback_params.use_master_weights_in_ckpt
    # (exp_manager.py:46, base.py:131): keep the fp32 master copy in the
    # checkpoint.  Default True here (bitwise resume); False drops the master
    # tree from the save and restore re-seeds it from the saved params.
    use_master_weights_in_ckpt: bool = True
    # elastic-resume hardening (``exp_manager.elastic``, docs/elasticity.md):
    # bounded retry with exponential backoff on TRANSIENT save I/O errors
    # (ENOSPC/EIO/...), with partial-save cleanup so a failed save never
    # shadows the last good one
    save_retries: int = 3
    save_retry_backoff_seconds: float = 0.5
    # checkpoint-integrity policy (``exp_manager.checkpoint.integrity``,
    # docs/elasticity.md "Integrity & walk-back"): digest sidecar in every
    # save, verified restore with walk-back + quarantine, optional
    # post-commit read-back audit
    integrity: IntegrityConfig = dataclasses.field(
        default_factory=IntegrityConfig)

    @classmethod
    def from_config(cls, cfg: dict[str, Any]) -> "CheckpointConfig":
        em = dict(cfg.get("exp_manager", {}) or {})
        cb = dict(em.get("checkpoint_callback_params", {}) or {})
        # retry knobs flow through the validated exp_manager.elastic block —
        # ElasticConfig owns the defaults (trainer/elastic.py ELASTIC_KNOBS),
        # so the checkpointer cannot diverge from the documented knob block
        from neuronx_distributed_training_tpu.trainer.elastic import (
            ElasticConfig,
        )

        el = ElasticConfig.from_config(em.get("elastic"))
        return cls(
            dir=em.get("explicit_log_dir") or em.get("exp_dir") or "checkpoints",
            save_top_k=int(cb.get("save_top_k", 3)),
            every_n_train_steps=int(cb.get("every_n_train_steps", 100)),
            async_save=bool(cb.get("async_checkpointing", em.get("async_checkpointing", True))),
            monitor=str(cb.get("monitor", "loss")),
            save_bf16=bool(em.get("save_bf16", cb.get("save_bf16", False))),
            use_master_weights_in_ckpt=bool(
                cb.get("use_master_weights_in_ckpt", True)),
            save_retries=el.save_retries,
            save_retry_backoff_seconds=el.save_retry_backoff_seconds,
            integrity=ck_integrity.parse_checkpoint_block(em.get("checkpoint")),
        )


@dataclasses.dataclass
class TrainState:
    """Everything a resume needs (the reference spreads this across the PTL
    checkpoint dict, loop progress, and the ckpt filename)."""

    params: Any
    opt_state: Any
    step: int
    consumed_samples: int
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)


def resolve_checkpoint_dir(d: str | Path):
    """Local paths -> absolute ``pathlib.Path``; remote URIs (``gs://`` etc.)
    -> ``etils.epath.Path`` so Orbax streams through TensorStore instead of
    silently writing a local directory literally named ``gs:`` (the failure
    mode of ``Path(uri).absolute()``)."""
    s = str(d)
    if "://" not in s:
        return Path(s).absolute()
    from etils import epath

    try:
        return epath.Path(s)
    except KeyError as e:
        raise ValueError(
            f"unsupported checkpoint URI scheme in {s!r}; epath supports "
            f"gs:// and s3:// (local paths need no scheme)"
        ) from e


def _abstract_like(tree: Any, specs: Any, mesh: Optional[Mesh]) -> Any:
    """ShapeDtypeStruct pytree (with shardings when a mesh is given) for
    sharding-aware restore."""

    def one(x, s):
        sharding = NamedSharding(mesh, s) if mesh is not None else None
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return jax.tree_util.tree_map(
        one, tree, specs, is_leaf=lambda x: isinstance(x, P)
    )


def _abstract_from_tree(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
        tree,
    )


def _bf16_read_templates(abs_tree: Any) -> Any:
    """Downcast floating abstract leaves to bf16 — the on-disk dtype of a
    ``save_bf16`` checkpoint (integer leaves, e.g. opt step, untouched)."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: (jax.ShapeDtypeStruct(a.shape, jnp.bfloat16, sharding=a.sharding)
                   if jnp.issubdtype(a.dtype, jnp.floating) else a),
        abs_tree,
    )


def _cast_like(tree: Any, abs_tree: Any) -> Any:
    """Cast restored arrays up to the template dtype/sharding."""
    return jax.tree_util.tree_map(
        lambda x, a: (jax.device_put(x.astype(a.dtype), a.sharding)
                      if a.sharding is not None else x.astype(a.dtype)),
        tree, abs_tree,
    )


class Checkpointer:
    """Save/restore ``TrainState`` with retention + async + auto-resume."""

    def __init__(self, config: CheckpointConfig, *, keep_last: bool = True):
        self.config = config
        directory = resolve_checkpoint_dir(config.dir)
        try:
            from orbax.checkpoint.checkpoint_managers import (  # noqa: F401
                preservation_policy as _pp,
            )

            have_preservation = True
        except Exception:  # noqa: BLE001 — older orbax: module absent
            have_preservation = False
        #: does this orbax ship the preservation-policy retention API?
        #: (best-N-by-metric + latest).  Without it we degrade to newest-N
        #: retention instead of refusing to construct — an elastic resume on
        #: an old image must still be able to save and restore.
        self.preservation_api = have_preservation

        if have_preservation:
            preservation = None
            if config.save_top_k > 0:
                from orbax.checkpoint.checkpoint_managers import (
                    preservation_policy as pp,
                )

                def metric_fn(metrics: Any) -> float:
                    return float((metrics or {}).get(self.config.monitor, float("inf")))

                policies = [
                    # reverse=True keeps the *lowest* metric values (loss-like)
                    pp.BestN(get_metric_fn=metric_fn, n=config.save_top_k, reverse=True),
                ]
                if keep_last:
                    # "last" must survive top-k eviction for auto-resume correctness
                    # (the reference keeps top-k AND last, exp_manager.py:517-579)
                    policies.append(pp.LatestN(n=1))
                preservation = pp.AnyPreservationPolicy(policies)

            options = ocp.CheckpointManagerOptions(
                preservation_policy=preservation,
                enable_async_checkpointing=config.async_save,
                save_interval_steps=1,  # step gating is the trainer's job
            )
        else:
            # legacy retention: newest (top_k + 1) checkpoints — the "+1"
            # approximates the keep-last guarantee; best-by-metric needs the
            # preservation API (those tests stay environment-gated)
            if config.save_top_k > 0:
                logger.warning(
                    "orbax without preservation_policy: retention degrades "
                    "to newest-%d (best-by-%s needs a newer orbax)",
                    config.save_top_k + int(keep_last), config.monitor,
                )
            options = ocp.CheckpointManagerOptions(
                max_to_keep=(config.save_top_k + int(keep_last)
                             if config.save_top_k > 0 else None),
                enable_async_checkpointing=config.async_save,
                save_interval_steps=1,
            )
        self._mgr = ocp.CheckpointManager(directory, options=options)
        #: integrity bookkeeping — the restore/audit trail the trainer
        #: persists into ``run_summary.json``'s ``integrity`` section
        self.integrity_trail: dict[str, Any] = {}
        #: steps saved but not yet handed to the post-commit audit (they
        #: commit at the next ``wait()``/``save()``; the audit only ever sees
        #: COMMITTED steps)
        self._audit_pending: list[int] = []
        self._auditor: Optional[SaveAuditor] = None
        if config.integrity.enabled and config.integrity.audit:
            self._auditor = SaveAuditor(self.directory)

    def _trail(self) -> dict[str, Any]:
        self.integrity_trail.setdefault("quarantined_steps", [])
        self.integrity_trail.setdefault("verify_seconds", 0.0)
        return self.integrity_trail

    @property
    def directory(self):
        """Local dirs as ``pathlib.Path``; remote stores keep orbax's
        ``epath.Path`` — re-wrapping in ``Path()`` would mangle ``gs://``
        into ``gs:/`` and make every ``exists()``/``glob()`` a silent no-op."""
        d = self._mgr.directory
        return d if "://" in str(d) else Path(str(d))

    # -- save ---------------------------------------------------------------

    def save(
        self,
        state: TrainState,
        *,
        metrics: Optional[dict[str, float]] = None,
        force: bool = False,
        manifest: Optional[dict[str, Any]] = None,
    ) -> bool:
        """Schedule (async) or perform (sync) one save.

        ``manifest`` — the world-size-agnostic topology/plan manifest
        (``trainer.elastic.build_manifest``): mesh axes, parallelism plan,
        model identity.  Stored as its own JSON item so a restart can read
        it WITHOUT templates (the restart-time replanner does exactly that
        before any model state exists).

        When integrity is enabled the save also carries the ``integrity``
        digest sidecar (docs/elasticity.md "Integrity & walk-back"), and —
        with the post-commit audit on — previously COMMITTED steps are
        handed to the background auditor here, with any finished
        audit-failure verdict applied (quarantine) before the new save
        starts.  The verdict application is a non-blocking snapshot: an
        audit still in flight never delays (or deadlocks) a save, emergency
        or periodic."""
        if self._auditor is not None:
            # the implicit wait also commits any in-flight async save, so
            # the steps kicked to the auditor are guaranteed on disk; orbax
            # would serialize on the previous save here anyway
            self._mgr.wait_until_finished()
            self._kick_audits()
            self._apply_audit_verdicts()
        params = state.params
        if self.config.save_bf16:
            import jax.numpy as jnp

            params = jax.tree_util.tree_map(
                lambda x: (x.astype(jnp.bfloat16)
                           if jnp.issubdtype(x.dtype, jnp.floating) else x),
                params,
            )
        opt_state = state.opt_state
        if not self.config.use_master_weights_in_ckpt and "master" in opt_state:
            opt_state = {k: v for k, v in opt_state.items() if k != "master"}
        meta = {
            "step": int(state.step),
            "consumed_samples": int(state.consumed_samples),
            # restore branches on these (templates must match what was saved)
            "save_bf16": bool(self.config.save_bf16),
            "master_in_ckpt": "master" in opt_state,
            **{k: v for k, v in state.extra.items()},
        }
        items: dict[str, Any] = {
            "params": ocp.args.StandardSave(params),
            "opt_state": ocp.args.StandardSave(opt_state),
            "meta": ocp.args.JsonSave(meta),
        }
        if manifest is not None:
            items["manifest"] = ocp.args.JsonSave(manifest)
        if self.config.integrity.enabled:
            # digests over the EXACT trees handed to orbax (post save_bf16
            # cast / master drop) so restore verification re-hashes the same
            # bytes it reads back from disk.  COST: a synchronous
            # device->host fetch + hash of the full state on this thread —
            # comparable to the host snapshot an async save itself takes,
            # but paid twice; at very large scale where that matters, turn
            # the sidecar off (integrity.enabled: false) or budget the
            # checkpoint cadence for it (docs/elasticity.md)
            try:
                items[ck_integrity.INTEGRITY_ITEM] = ocp.args.JsonSave(
                    ck_integrity.build_sidecar(
                        step=int(state.step), params=params,
                        opt_state=opt_state, meta=meta, manifest=manifest))
            except Exception as e:  # noqa: BLE001 — a sidecar failure must
                # not block the save itself (the step then restores as
                # legacy/unverified, with the warning)
                logger.warning(
                    "integrity sidecar build failed at step %d (saving "
                    "without): %s", state.step, e)
        saved = self._mgr.save(
            int(state.step),
            args=ocp.args.Composite(**items),
            metrics={k: float(v) for k, v in (metrics or {}).items()},
            force=force,
        )
        if saved and self._auditor is not None:
            self._audit_pending.append(int(state.step))
        return saved

    # -- post-commit save audit --------------------------------------------

    def _kick_audits(self) -> None:
        """Hand every pending (now committed) step to the background
        auditor.  Callers guarantee no async save is in flight."""
        if self._auditor is None:
            return
        pending, self._audit_pending = self._audit_pending, []
        for step in pending:
            self._auditor.schedule(step)

    def _apply_audit_verdicts(self) -> list[int]:
        """Snapshot the auditor's COMPLETED verdicts (non-blocking) and
        quarantine any audit failure.  Safe only when no save is in flight
        (quarantine reloads the manager's step registry)."""
        if self._auditor is None:
            return []
        quarantined: list[int] = []
        trail = self._trail()
        for v in self._auditor.poll():
            if v.status != "corrupt":
                continue
            logger.error(
                "post-commit save audit FAILED for step %d: %s",
                v.step, "; ".join(v.failures[:4]))
            if self.config.integrity.quarantine:
                ck_integrity.apply_quarantine(
                    self.directory, v.step, reason="save-audit",
                    failures=v.failures)
                self._mgr.reload()
                quarantined.append(v.step)
                trail.setdefault("audit_quarantined", []).append(v.step)
                if v.step not in trail["quarantined_steps"]:
                    trail["quarantined_steps"].append(v.step)
            else:
                trail.setdefault("corrupt_steps_unquarantined", [])
                if v.step not in trail["corrupt_steps_unquarantined"]:
                    trail["corrupt_steps_unquarantined"].append(v.step)
        if self._auditor is not None:
            trail["audit"] = self._auditor.stats.to_dict()
        return quarantined

    def save_with_retry(
        self,
        state: TrainState,
        *,
        metrics: Optional[dict[str, float]] = None,
        force: bool = False,
        manifest: Optional[dict[str, Any]] = None,
        retries: Optional[int] = None,
        backoff_seconds: Optional[float] = None,
        deadline: Optional[float] = None,
        drain: bool = False,
    ) -> bool:
        """:meth:`save` with bounded retry + exponential backoff on TRANSIENT
        I/O errors (:func:`is_transient_save_error`), cleaning up the partial
        save between attempts so a failed save never shadows the last good
        checkpoint.

        - ``drain=True`` additionally waits for the async commit INSIDE the
          retry loop, so background write errors count as save failures too —
          the emergency/final-save path uses this; periodic saves keep the
          async overlap and surface commit errors at the next ``wait()``.
        - ``deadline`` (a ``time.monotonic()`` instant) bounds the whole
          attempt sequence — the SIGTERM grace window passes the moment the
          preemption notice expires.  The first attempt always runs.

        Non-transient errors re-raise immediately (after cleanup); exhausted
        retries re-raise the LAST transient error."""
        attempts = 1 + max(int(self.config.save_retries
                               if retries is None else retries), 0)
        delay = float(self.config.save_retry_backoff_seconds
                      if backoff_seconds is None else backoff_seconds)
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            try:
                saved = self.save(state, metrics=metrics, force=force,
                                  manifest=manifest)
                if drain:
                    self.wait()
                return saved
            except Exception as e:  # noqa: BLE001 — classified below
                self._cleanup_failed_save(int(state.step))
                if not is_transient_save_error(e):
                    raise
                last = e
                remaining = attempts - 1 - attempt
                if remaining == 0:
                    break
                if deadline is not None and time.monotonic() + delay >= deadline:
                    logger.warning(
                        "checkpoint save at step %d: grace deadline reached "
                        "after attempt %d/%d", state.step, attempt + 1, attempts,
                    )
                    break
                logger.warning(
                    "checkpoint save at step %d failed transiently (%s: %s); "
                    "retrying in %.2fs (%d attempt%s left)",
                    state.step, type(e).__name__, e, delay, remaining,
                    "s" if remaining != 1 else "",
                )
                time.sleep(delay)
                delay *= 2.0
        assert last is not None
        raise last

    def _cleanup_failed_save(self, step: int) -> None:
        """Best-effort removal of a failed save's leftovers so the next
        attempt (or the next run's auto-resume) sees only COMMITTED steps:
        orbax writes into ``<step>.orbax-checkpoint-tmp-*`` staging dirs and
        renames on commit, so stale staging dirs (plus an uncommitted final
        ``<step>`` dir with no commit marker under an interrupted rename)
        are the two shadows to clear.  ``latest_step`` ignores tmp dirs, but
        a crashed retry loop must not leave the directory accumulating
        half-written staging trees on a full disk.

        The error a ``save()`` call surfaces may belong to a PREVIOUS step's
        background commit (async saves report at the next manager call), so
        the sweep drains the async manager first — after which no healthy
        save can be in flight — and then clears EVERY stale staging dir, not
        just the current step's."""
        import shutil

        try:
            try:
                self._mgr.wait_until_finished()
            except Exception:  # noqa: BLE001 — the failure is already being
                pass  # handled by the retry loop; the drain is for safety
            # the directory property keeps epath for gs://-style stores —
            # a plain Path() wrap would mangle the scheme and turn the
            # remote sweep into a silent no-op
            root = self.directory
            if not root.exists():
                return
            for p in root.glob("*.orbax-checkpoint-tmp-*"):
                if isinstance(p, Path):
                    shutil.rmtree(p, ignore_errors=True)
                else:
                    try:
                        p.rmtree()  # epath: remote store
                    except Exception:  # noqa: BLE001 — best-effort sweep
                        pass
            # an interrupted save can leave the manager believing the step
            # exists; drop it from the registry so the retry can re-save it
            try:
                if step in (self._mgr.all_steps() or []):
                    final = root / str(step)
                    if not final.exists():
                        self._mgr.reload()
            except Exception:  # noqa: BLE001 — registry probe is best-effort
                pass
        except Exception as e:  # noqa: BLE001 — cleanup must never mask the save error
            logger.warning("partial-save cleanup at step %d failed: %s", step, e)

    def wait(self) -> None:
        """Block until any in-flight async save commits.  With the
        post-commit audit on, the freshly committed steps are handed to the
        background auditor here and any finished verdict is applied — still
        without ever blocking on an audit in flight."""
        self._mgr.wait_until_finished()
        if self._auditor is not None:
            self._kick_audits()
            self._apply_audit_verdicts()

    # -- restore ------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def verify_step(self, step: int) -> "ck_integrity.StepVerification":
        """Template-free integrity verification of one retained step
        (:func:`checkpoint.integrity.verify_step` over this manager)."""
        return ck_integrity.verify_step(self.directory, step, mgr=self._mgr)

    def verified_latest_step(
        self, *, quarantine: Optional[bool] = None
    ) -> Optional[int]:
        """The newest retained step that passes integrity verification,
        walking BACK through the retention chain past corrupt steps (each
        quarantined: renamed out of the discovery namespace + ledger entry,
        so restore, elastic replan, and every later discovery agree on the
        same step).  ``None`` when no checkpoint exists at all; raises
        :class:`CheckpointIntegrityError` with the per-step verdicts when
        steps exist but NONE verifies.

        A step without a sidecar (pre-integrity checkpoint) verifies as
        ``legacy`` — restorable with a warning, never a crash."""
        icfg = self.config.integrity
        quarantine = icfg.quarantine if quarantine is None else quarantine
        steps = sorted(self._mgr.all_steps() or [], reverse=True)
        if not steps:
            return None
        trail = self._trail()
        verdicts: list[ck_integrity.StepVerification] = []
        walked = 0
        for step in steps:
            v = self.verify_step(step)
            verdicts.append(v)
            trail["verify_seconds"] = round(
                trail["verify_seconds"] + v.seconds, 3)
            if v.status == "gone":
                # the dir vanished between the step listing and the read
                # (concurrent quarantine/retention on another actor):
                # nothing to restore OR quarantine — keep walking
                logger.warning(
                    "checkpoint step %d vanished mid-verification — "
                    "skipping (concurrent retention/quarantine?)", step)
                continue
            if v.passed:
                if v.status == "legacy":
                    logger.warning(
                        "checkpoint step %d predates integrity sidecars — "
                        "restoring UNVERIFIED (legacy checkpoint; the next "
                        "save will carry digests)", step)
                    trail["legacy_restore"] = True
                if walked:
                    logger.warning(
                        "integrity walk-back: restored step is %d, %d newer "
                        "step(s) quarantined as corrupt", step, walked)
                trail["verified_step"] = int(step)
                trail["walk_back_count"] = walked
                return int(step)
            walked += 1
            if quarantine:
                ck_integrity.apply_quarantine(
                    self.directory, step, reason=v.failures[0] if v.failures
                    else "digest-mismatch", failures=v.failures)
                self._mgr.reload()
                if step not in trail["quarantined_steps"]:
                    trail["quarantined_steps"].append(int(step))
            else:
                # walked past but deliberately NOT renamed/ledgered
                # (quarantine: false, or a warm start in someone else's run
                # dir) — the trail must not claim a quarantine that never
                # happened
                trail.setdefault("corrupt_steps_unquarantined", [])
                if step not in trail["corrupt_steps_unquarantined"]:
                    trail["corrupt_steps_unquarantined"].append(int(step))
        if all(v.status == "gone" for v in verdicts):
            # every listed step vanished under us: nothing to restore
            return None
        detail = "; ".join(
            f"step {v.step}: {v.failures[0] if v.failures else v.status}"
            for v in verdicts)
        raise CheckpointIntegrityError(
            f"every retained checkpoint under {self.directory} failed "
            f"integrity verification ({detail}) — auto-resume cannot "
            f"proceed; restore from an older backup or relaunch fresh "
            f"(quarantined step dirs keep the evidence, see "
            f"{ck_integrity.LEDGER_NAME})", verdicts)

    def read_manifest(self, step: Optional[int] = None) -> Optional[dict]:
        """The topology/plan manifest saved alongside ``step`` (newest when
        ``None``), or ``None`` when the checkpoint predates manifests (or no
        checkpoint exists).  Template-free: safe to call before any model
        state exists — the restart-time replanner's first read."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        try:
            out = self._mgr.restore(
                step, args=ocp.args.Composite(manifest=ocp.args.JsonRestore())
            )["manifest"]
            return dict(out) if out is not None else None
        except Exception as e:  # noqa: BLE001 — pre-elastic checkpoints have
            # no manifest item, but a CORRUPT manifest or a transient remote
            # read error must be distinguishable in the logs: a silent None
            # here means "no replan", and the run would restore onto a stale
            # declared mesh with an opaque shape crash
            logger.warning(
                "manifest read at step %s failed (%s: %s) — treating as "
                "no-manifest; a pre-elastic checkpoint is expected here, "
                "anything else deserves a look", step, type(e).__name__, e)
            return None

    def restore(
        self,
        params_template: Any,
        opt_template: Any,
        *,
        step: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        param_specs: Any = None,
        opt_specs: Any = None,
        verify: Optional[bool] = None,
    ) -> TrainState:
        """Restore the newest (or given) step.  Templates are live pytrees or
        ShapeDtypeStructs; pass mesh+specs to restore direct-to-sharded.

        ``verify`` (default: the ``exp_manager.checkpoint.integrity`` knobs)
        — verify the integrity sidecar BEFORE imposing the mesh: newest-step
        restores walk back past corrupt steps (:meth:`verified_latest_step`);
        an explicitly requested corrupt ``step`` raises
        :class:`CheckpointIntegrityError` instead of restoring bad bytes."""
        icfg = self.config.integrity
        do_verify = (icfg.enabled and icfg.verify_restore
                     if verify is None else bool(verify))
        if step is None:
            step = (self.verified_latest_step() if do_verify
                    else self.latest_step())
        elif do_verify:
            v = self.verify_step(step)
            if not v.passed:
                raise CheckpointIntegrityError(
                    f"checkpoint step {step} under {self.directory} failed "
                    f"integrity verification: "
                    f"{'; '.join(v.failures[:4]) or v.status}", [v])
            if v.status == "legacy":
                logger.warning(
                    "checkpoint step %d predates integrity sidecars — "
                    "restoring UNVERIFIED (legacy checkpoint)", step)
                self._trail()["legacy_restore"] = True
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        # meta first: the save-time knobs (save_bf16, master dropped) change
        # what templates must look like
        meta = dict(self._mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )["meta"])
        saved_bf16 = bool(meta.pop("save_bf16", False))
        master_in = bool(meta.pop("master_in_ckpt", True))
        if mesh is not None and param_specs is not None:
            p_abs = _abstract_like(params_template, param_specs, mesh)
            o_abs = _abstract_like(opt_template, opt_specs, mesh)
        else:
            p_abs = _abstract_from_tree(params_template)
            o_abs = _abstract_from_tree(opt_template)
        p_abs_read = _bf16_read_templates(p_abs) if saved_bf16 else p_abs
        master_abs = None
        if not master_in and isinstance(o_abs, dict) and "master" in o_abs:
            master_abs = o_abs["master"]
            o_abs = {k: v for k, v in o_abs.items() if k != "master"}
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(p_abs_read),
                opt_state=ocp.args.StandardRestore(o_abs),
            ),
        )
        params = restored["params"]
        if saved_bf16:
            # cast back up to the template dtype (resume continues in the
            # run's own precision regime; bf16 rounding is the knob's cost)
            params = _cast_like(params, p_abs)
        opt_state = dict(restored["opt_state"])
        if master_abs is not None:
            # master dropped at save time: re-seed fp32 master from the saved
            # weights (the reference's use_master_weights_in_ckpt=False path)
            opt_state["master"] = _cast_like(params, master_abs)
        return TrainState(
            params=params,
            opt_state=opt_state,
            step=int(meta.pop("step")),
            consumed_samples=int(meta.pop("consumed_samples")),
            extra=meta,
        )

    def restore_params_only(
        self,
        params_template: Any,
        *,
        step: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        param_specs: Any = None,
        verify: Optional[bool] = None,
    ) -> Any:
        """The reference's ``weight_init_only`` warm start
        (``nlp_overrides.py:565-568``): weights without optimizer/loop state.

        Integrity verification applies here too, but WITHOUT quarantine by
        default — the warm-start source is usually someone else's run dir
        (or a converter's output, which has no sidecar and restores as
        legacy); renaming steps there is not this run's call."""
        icfg = self.config.integrity
        do_verify = (icfg.enabled and icfg.verify_restore
                     if verify is None else bool(verify))
        if step is None and do_verify:
            step = self.verified_latest_step(quarantine=False)
        elif step is not None and do_verify:
            v = self.verify_step(step)
            if not v.passed:
                raise CheckpointIntegrityError(
                    f"warm-start checkpoint step {step} under "
                    f"{self.directory} failed integrity verification: "
                    f"{'; '.join(v.failures[:4]) or v.status}", [v])
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        if mesh is not None and param_specs is not None:
            p_abs = _abstract_like(params_template, param_specs, mesh)
        else:
            p_abs = _abstract_from_tree(params_template)
        saved_bf16 = False
        try:
            m = self._mgr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
            )["meta"]
            saved_bf16 = bool((m or {}).get("save_bf16", False))
        except Exception:
            pass  # converter-written checkpoints carry no meta item
        p_abs_read = _bf16_read_templates(p_abs) if saved_bf16 else p_abs
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(params=ocp.args.StandardRestore(p_abs_read))
        )
        params = restored["params"]
        if saved_bf16:
            params = _cast_like(params, p_abs)
        return params

    def close(self) -> None:
        if self._auditor is not None:
            # the teardown drain is DEADLINE-BOUNDED (integrity.
            # audit_deadline_seconds): a hung store read on the audit thread
            # must not wedge process exit — unfinished audits are counted
            # ``incomplete`` in the trail instead
            try:
                self._mgr.wait_until_finished()
                self._kick_audits()
                self._auditor.drain(
                    self.config.integrity.audit_deadline_seconds)
                self._apply_audit_verdicts()
            except Exception as e:  # noqa: BLE001 — teardown must finish
                logger.warning("save-audit teardown drain failed: %s", e)
            self._auditor.close(timeout=0)
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.wait()
        self.close()
