"""Orbax-backed checkpoint manager.

Capability map to the reference (SURVEY.md §5.4):

- per-rank sharded save / tensor streaming (``save_xser``/``load_xser``,
  reference ``nlp_overrides.py:1141-1155``)      -> Orbax OCDBT/TensorStore,
  every process writes its own shards, restore is sharding-aware;
- ``async_checkpointing`` (forked writer process, ``known_issues.rst:53-81``)
  -> Orbax async checkpointing (background thread + commit future);
- top-k retention + auto-delete (``config_overview.rst:243-249``)
  -> ``max_to_keep`` + ``best_fn`` on the monitored metric;
- auto-resume from newest checkpoint (``exp_manager.py:333-404``)
  -> ``latest_step()`` + ``restore``;
- filename-encoded ``consumed_samples`` (``data/base.py:40-47``)
  -> explicit ``meta`` JSON item per step (no regex parsing needed; the value
  rides inside the checkpoint);
- ``weight_init_only`` warm start (``nlp_overrides.py:541-568``)
  -> ``restore_params_only``.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Mirrors the reference's ``exp_manager.checkpoint_callback_params`` +
    ``save_xser``/``async_checkpointing`` knobs (``config_overview.rst:243-308``)."""

    dir: str | Path = "checkpoints"
    save_top_k: int = 3
    every_n_train_steps: int = 100
    async_save: bool = True
    monitor: str = "loss"  # metric whose *lowest* value defines "best"
    # reference exp_manager.save_bf16 (exp_manager.py:58): store model weights
    # in bf16 — halves params bytes; restore casts back up (resume is no
    # longer bitwise, the knob's inherent trade)
    save_bf16: bool = False
    # reference checkpoint_callback_params.use_master_weights_in_ckpt
    # (exp_manager.py:46, base.py:131): keep the fp32 master copy in the
    # checkpoint.  Default True here (bitwise resume); False drops the master
    # tree from the save and restore re-seeds it from the saved params.
    use_master_weights_in_ckpt: bool = True

    @classmethod
    def from_config(cls, cfg: dict[str, Any]) -> "CheckpointConfig":
        em = dict(cfg.get("exp_manager", {}) or {})
        cb = dict(em.get("checkpoint_callback_params", {}) or {})
        return cls(
            dir=em.get("explicit_log_dir") or em.get("exp_dir") or "checkpoints",
            save_top_k=int(cb.get("save_top_k", 3)),
            every_n_train_steps=int(cb.get("every_n_train_steps", 100)),
            async_save=bool(cb.get("async_checkpointing", em.get("async_checkpointing", True))),
            monitor=str(cb.get("monitor", "loss")),
            save_bf16=bool(em.get("save_bf16", cb.get("save_bf16", False))),
            use_master_weights_in_ckpt=bool(
                cb.get("use_master_weights_in_ckpt", True)),
        )


@dataclasses.dataclass
class TrainState:
    """Everything a resume needs (the reference spreads this across the PTL
    checkpoint dict, loop progress, and the ckpt filename)."""

    params: Any
    opt_state: Any
    step: int
    consumed_samples: int
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)


def resolve_checkpoint_dir(d: str | Path):
    """Local paths -> absolute ``pathlib.Path``; remote URIs (``gs://`` etc.)
    -> ``etils.epath.Path`` so Orbax streams through TensorStore instead of
    silently writing a local directory literally named ``gs:`` (the failure
    mode of ``Path(uri).absolute()``)."""
    s = str(d)
    if "://" not in s:
        return Path(s).absolute()
    from etils import epath

    try:
        return epath.Path(s)
    except KeyError as e:
        raise ValueError(
            f"unsupported checkpoint URI scheme in {s!r}; epath supports "
            f"gs:// and s3:// (local paths need no scheme)"
        ) from e


def _abstract_like(tree: Any, specs: Any, mesh: Optional[Mesh]) -> Any:
    """ShapeDtypeStruct pytree (with shardings when a mesh is given) for
    sharding-aware restore."""

    def one(x, s):
        sharding = NamedSharding(mesh, s) if mesh is not None else None
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return jax.tree_util.tree_map(
        one, tree, specs, is_leaf=lambda x: isinstance(x, P)
    )


def _abstract_from_tree(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
        tree,
    )


def _bf16_read_templates(abs_tree: Any) -> Any:
    """Downcast floating abstract leaves to bf16 — the on-disk dtype of a
    ``save_bf16`` checkpoint (integer leaves, e.g. opt step, untouched)."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: (jax.ShapeDtypeStruct(a.shape, jnp.bfloat16, sharding=a.sharding)
                   if jnp.issubdtype(a.dtype, jnp.floating) else a),
        abs_tree,
    )


def _cast_like(tree: Any, abs_tree: Any) -> Any:
    """Cast restored arrays up to the template dtype/sharding."""
    return jax.tree_util.tree_map(
        lambda x, a: (jax.device_put(x.astype(a.dtype), a.sharding)
                      if a.sharding is not None else x.astype(a.dtype)),
        tree, abs_tree,
    )


class Checkpointer:
    """Save/restore ``TrainState`` with retention + async + auto-resume."""

    def __init__(self, config: CheckpointConfig, *, keep_last: bool = True):
        self.config = config
        directory = resolve_checkpoint_dir(config.dir)
        preservation = None
        if config.save_top_k > 0:
            from orbax.checkpoint.checkpoint_managers import preservation_policy as pp

            def metric_fn(metrics: Any) -> float:
                return float((metrics or {}).get(self.config.monitor, float("inf")))

            policies = [
                # reverse=True keeps the *lowest* metric values (loss-like)
                pp.BestN(get_metric_fn=metric_fn, n=config.save_top_k, reverse=True),
            ]
            if keep_last:
                # "last" must survive top-k eviction for auto-resume correctness
                # (the reference keeps top-k AND last, exp_manager.py:517-579)
                policies.append(pp.LatestN(n=1))
            preservation = pp.AnyPreservationPolicy(policies)

        options = ocp.CheckpointManagerOptions(
            preservation_policy=preservation,
            enable_async_checkpointing=config.async_save,
            save_interval_steps=1,  # step gating is the trainer's job
        )
        self._mgr = ocp.CheckpointManager(directory, options=options)

    @property
    def directory(self) -> Path:
        return Path(self._mgr.directory)

    # -- save ---------------------------------------------------------------

    def save(
        self,
        state: TrainState,
        *,
        metrics: Optional[dict[str, float]] = None,
        force: bool = False,
    ) -> bool:
        params = state.params
        if self.config.save_bf16:
            import jax.numpy as jnp

            params = jax.tree_util.tree_map(
                lambda x: (x.astype(jnp.bfloat16)
                           if jnp.issubdtype(x.dtype, jnp.floating) else x),
                params,
            )
        opt_state = state.opt_state
        if not self.config.use_master_weights_in_ckpt and "master" in opt_state:
            opt_state = {k: v for k, v in opt_state.items() if k != "master"}
        meta = {
            "step": int(state.step),
            "consumed_samples": int(state.consumed_samples),
            # restore branches on these (templates must match what was saved)
            "save_bf16": bool(self.config.save_bf16),
            "master_in_ckpt": "master" in opt_state,
            **{k: v for k, v in state.extra.items()},
        }
        return self._mgr.save(
            int(state.step),
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
                meta=ocp.args.JsonSave(meta),
            ),
            metrics={k: float(v) for k, v in (metrics or {}).items()},
            force=force,
        )

    def wait(self) -> None:
        """Block until any in-flight async save commits."""
        self._mgr.wait_until_finished()

    # -- restore ------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(
        self,
        params_template: Any,
        opt_template: Any,
        *,
        step: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        param_specs: Any = None,
        opt_specs: Any = None,
    ) -> TrainState:
        """Restore the newest (or given) step.  Templates are live pytrees or
        ShapeDtypeStructs; pass mesh+specs to restore direct-to-sharded."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        # meta first: the save-time knobs (save_bf16, master dropped) change
        # what templates must look like
        meta = dict(self._mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )["meta"])
        saved_bf16 = bool(meta.pop("save_bf16", False))
        master_in = bool(meta.pop("master_in_ckpt", True))
        if mesh is not None and param_specs is not None:
            p_abs = _abstract_like(params_template, param_specs, mesh)
            o_abs = _abstract_like(opt_template, opt_specs, mesh)
        else:
            p_abs = _abstract_from_tree(params_template)
            o_abs = _abstract_from_tree(opt_template)
        p_abs_read = _bf16_read_templates(p_abs) if saved_bf16 else p_abs
        master_abs = None
        if not master_in and isinstance(o_abs, dict) and "master" in o_abs:
            master_abs = o_abs["master"]
            o_abs = {k: v for k, v in o_abs.items() if k != "master"}
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(p_abs_read),
                opt_state=ocp.args.StandardRestore(o_abs),
            ),
        )
        params = restored["params"]
        if saved_bf16:
            # cast back up to the template dtype (resume continues in the
            # run's own precision regime; bf16 rounding is the knob's cost)
            params = _cast_like(params, p_abs)
        opt_state = dict(restored["opt_state"])
        if master_abs is not None:
            # master dropped at save time: re-seed fp32 master from the saved
            # weights (the reference's use_master_weights_in_ckpt=False path)
            opt_state["master"] = _cast_like(params, master_abs)
        return TrainState(
            params=params,
            opt_state=opt_state,
            step=int(meta.pop("step")),
            consumed_samples=int(meta.pop("consumed_samples")),
            extra=meta,
        )

    def restore_params_only(
        self,
        params_template: Any,
        *,
        step: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        param_specs: Any = None,
    ) -> Any:
        """The reference's ``weight_init_only`` warm start
        (``nlp_overrides.py:565-568``): weights without optimizer/loop state."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        if mesh is not None and param_specs is not None:
            p_abs = _abstract_like(params_template, param_specs, mesh)
        else:
            p_abs = _abstract_from_tree(params_template)
        saved_bf16 = False
        try:
            m = self._mgr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
            )["meta"]
            saved_bf16 = bool((m or {}).get("save_bf16", False))
        except Exception:
            pass  # converter-written checkpoints carry no meta item
        p_abs_read = _bf16_read_templates(p_abs) if saved_bf16 else p_abs
        restored = self._mgr.restore(
            step, args=ocp.args.Composite(params=ocp.args.StandardRestore(p_abs_read))
        )
        params = restored["params"]
        if saved_bf16:
            params = _cast_like(params, p_abs)
        return params

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.wait()
        self.close()
