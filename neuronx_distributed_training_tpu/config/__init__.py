"""YAML config system preserving the reference's config schema."""

from neuronx_distributed_training_tpu.config.loader import (  # noqa: F401
    ConfigDict,
    load_config,
    validate_config,
)
