"""YAML config loading with the reference's schema and interpolation syntax.

The reference is driven by Hydra/OmegaConf YAML whose root keys are
``name, model_source, seed, trainer, exp_manager, distributed_strategy, data,
model, precision, compiler_*`` (reference ``config_overview.rst:10-41``).  We keep
that schema (so a reference user's configs translate 1:1) but replace
Hydra/OmegaConf with a ~200-line loader: plain YAML + ``${a.b.c}`` interpolation +
the ``${multiply:x,y}`` resolver the shipped configs use
(``hf_llama3_8B_config.yaml:33``).

Neuron-only knobs (``compiler_flags``, ``neuron_rt_*`` …) are accepted and ignored
with a warning, so unmodified reference configs still load.
"""

from __future__ import annotations

import copy
import logging
import math
import re
from pathlib import Path
from typing import Any, Mapping

import yaml

logger = logging.getLogger(__name__)

_INTERP = re.compile(r"\$\{([^${}]+)\}")

# Accepted-and-ignored reference keys (Neuron runtime/compiler specific).
_IGNORED_ROOT_KEYS = {
    "compiler_flags",
    "compiler_cache_url",
    "aync_exec_max_inflight_requests",  # sic — typo is in the reference schema
    "async_exec_max_inflight_requests",
    "bucket_size_collectives",
    "neuron_rt_exec_timeout",
    "neuron_experimental_compress_rg",
}


def did_you_mean(unknown, options) -> str:
    """`` (did you mean: 'schedul' -> 'schedule'?)`` suffix for unknown-key
    rejections — every validated knob block appends it so a typo'd knob
    fails with its correction, not just a list to eyeball."""
    import difflib

    hints = []
    for u in sorted(str(k) for k in unknown):
        close = difflib.get_close_matches(u, [str(o) for o in options],
                                          n=1, cutoff=0.6)
        if close:
            hints.append(f"{u!r} -> {close[0]!r}")
    return f" (did you mean: {', '.join(hints)}?)" if hints else ""


class ConfigDict(dict):
    """dict with attribute access and safe ``get`` chaining (``cfg.model.optim.lr``)."""

    def __getattr__(self, k: str) -> Any:
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e

    def __setattr__(self, k: str, v: Any) -> None:
        self[k] = v

    def get_path(self, dotted: str, default: Any = None) -> Any:
        """Dotted-path lookup, the analogue of the reference's
        ``get_attribute_from_cfg`` (``utils/utils.py:79-149``)."""
        cur: Any = self
        for part in dotted.split("."):
            if isinstance(cur, Mapping) and part in cur:
                cur = cur[part]
            else:
                return default
        return cur


def _wrap(obj: Any) -> Any:
    if isinstance(obj, Mapping):
        return ConfigDict({k: _wrap(v) for k, v in obj.items()})
    if isinstance(obj, list):
        return [_wrap(v) for v in obj]
    return obj


def _lookup(root: Mapping, dotted: str) -> Any:
    cur: Any = root
    for part in dotted.split("."):
        cur = cur[part]
    return cur


def _resolve_value(root: Mapping, value: Any) -> Any:
    if not isinstance(value, str):
        return value
    # iterate innermost-out so nested forms like ${multiply:${a},${b}} resolve
    for _ in range(16):
        m = _INTERP.fullmatch(value.strip())
        if m:
            result = _resolve_expr(root, m.group(1))
            if isinstance(result, str) and _INTERP.search(result):
                value = result
                continue
            return result
        if _INTERP.search(value):
            value = _INTERP.sub(lambda mm: str(_resolve_expr(root, mm.group(1))), value)
            continue
        return value
    raise ValueError(f"config interpolation did not converge: {value!r}")


def _resolve_expr(root: Mapping, expr: str) -> Any:
    if ":" in expr:
        fn, _, argstr = expr.partition(":")
        args = [_resolve_value(root, a.strip()) for a in argstr.split(",")]
        if fn == "multiply":
            return math.prod(int(a) for a in args)
        if fn == "add":
            return sum(int(a) for a in args)
        raise ValueError(f"unknown config resolver ${{{expr}}}")
    return _resolve_value(root, _lookup(root, expr))


def _resolve_tree(root: Mapping, obj: Any) -> Any:
    if isinstance(obj, Mapping):
        return {k: _resolve_tree(root, v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_resolve_tree(root, v) for v in obj]
    return _resolve_value(root, obj)


def load_config(source: str | Path | Mapping, overrides: Mapping | None = None) -> ConfigDict:
    """Load a YAML config file (or mapping), resolve interpolations, apply
    dotted-path overrides, and validate."""
    if isinstance(source, (str, Path)):
        with open(source) as f:
            raw = yaml.safe_load(f)
    else:
        raw = copy.deepcopy(dict(source))  # never mutate the caller's mapping
    if raw is None:
        raw = {}
    if overrides:
        for dotted, v in overrides.items():
            _set_path(raw, dotted, v)
    resolved = _resolve_tree(raw, raw)
    cfg = _wrap(resolved)
    for k in list(cfg.keys()):
        if k in _IGNORED_ROOT_KEYS:
            logger.debug("ignoring Neuron-specific config key %r", k)
    validate_config(cfg)
    return cfg


def _set_path(tree: dict, dotted: str, value: Any) -> None:
    parts = dotted.split(".")
    cur = tree
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value


def validate_config(cfg: ConfigDict) -> None:
    """The central config-validation catalog: every unsupported combination is
    rejected here, before any compilation, with a curated message — the
    counterpart of the reference's ``_validate_and_override_config``
    (``megatron_base_model.py:71-129``) plus its orchestrator checks
    (``training_orchestrator.py:60-102``, ``base.py:54-57``).  Runtime code
    keeps thin backstop guards, but a bad config should die HERE, not as an
    opaque GSPMD partitioner error."""
    ds = cfg.get("distributed_strategy", {}) or {}
    data = cfg.get("data", {}) or {}
    model = cfg.get("model", {}) or {}
    fusions = dict(model.get("fusions", {}) or {})

    tp = int(ds.get("tensor_model_parallel_size", 1))
    pp = int(ds.get("pipeline_model_parallel_size", 1))
    cp = int(ds.get("context_parallel_size", 1))
    if ds.get("sequence_parallel") and tp == 1:
        raise ValueError("sequence_parallel requires tensor_model_parallel_size > 1")
    vp = ds.get("virtual_pipeline_model_parallel_size") or 1
    if int(vp) > 1 and pp == 1:
        raise ValueError("virtual pipeline requires pipeline_model_parallel_size > 1")
    n_layers = model.get("num_layers")
    if n_layers is not None and pp > 1:
        chunks = pp * int(vp)
        if int(n_layers) % chunks != 0:
            raise ValueError(
                f"num_layers={n_layers} must divide evenly into pp*vp={chunks} chunks"
            )
    gbs = data.get("global_batch_size")
    mbs = data.get("micro_batch_size")
    if gbs is not None and mbs is not None and int(gbs) % int(mbs) != 0:
        raise ValueError(f"global_batch_size {gbs} not divisible by micro_batch_size {mbs}")

    # ---- pipeline schedule ------------------------------------------------
    # distributed_strategy.pipeline.schedule: auto | 1f1b | 1f1b-interleaved |
    # 1f1b-zb | wavefront.  The full model-aware gate is
    # parallel.pipeline.supports_1f1b (resolved at trainer build); the
    # config-shape constraints die here with curated messages.
    pipe_raw = ds.get("pipeline", {}) or {}
    if not isinstance(pipe_raw, Mapping):
        raise ValueError(
            f"distributed_strategy.pipeline must be a mapping of knobs "
            f"(schedule: auto/1f1b/1f1b-interleaved/1f1b-zb/wavefront), got "
            f"{type(pipe_raw).__name__}: {pipe_raw!r}"
        )
    pipe_knobs = dict(pipe_raw)
    if pipe_knobs:
        from neuronx_distributed_training_tpu.parallel.pipeline import (
            MANUAL_VJP_SCHEDULES,
            PIPELINE_SCHEDULES,
            blocked_1f1b_reason,
        )

        unknown = set(pipe_knobs) - {"schedule"}
        if unknown:
            raise ValueError(
                f"unknown distributed_strategy.pipeline keys {sorted(unknown)}; "
                f"supported: schedule ({'/'.join(PIPELINE_SCHEDULES)})"
                + did_you_mean(unknown, {"schedule"})
            )
        sched_knob = str(pipe_knobs.get("schedule", "auto")).lower()
        if sched_knob not in PIPELINE_SCHEDULES:
            raise ValueError(
                f"pipeline.schedule must be one of "
                f"{'/'.join(PIPELINE_SCHEDULES)}, got {sched_knob!r}"
            )
        if sched_knob in MANUAL_VJP_SCHEDULES:
            # same catalog the trainer-build gate uses (supports_1f1b); the
            # model-FAMILY constraints need the built model config and fire
            # at resolve_schedule instead
            from neuronx_distributed_training_tpu.data.build import (
                alignment_strategy,
            )

            try:
                alignment, _ = alignment_strategy(cfg)
            except ValueError:
                # malformed alignment block: the alignment catalog below
                # rejects it with its own curated message
                alignment = None
            blocked = blocked_1f1b_reason({
                "pipeline_model_parallel_size": pp,
                "virtual_pipeline_model_parallel_size": int(vp),
                "context_parallel_size": cp,
                "alignment": alignment,
                "lora": bool(dict(model.get("lora", {}) or {})),
            }, sched_knob)
            if blocked is not None:
                raise ValueError(f"pipeline.schedule: {sched_knob}: {blocked}")

    # ---- engineered overlap ----------------------------------------------
    # distributed_strategy.overlap: {zero1_bucket_mb, prefetch_ag,
    # pp_double_buffer, xla_lhs}.  Full validation (unknown-key did-you-mean,
    # type checks) lives with the knobs' consumer in optim.overlap; rejecting
    # here keeps the die-before-compile contract.
    overlap_raw = ds.get("overlap")
    if overlap_raw is not None:
        from neuronx_distributed_training_tpu.optim.overlap import (
            OverlapConfig,
        )

        ov = OverlapConfig.from_config(
            dict(overlap_raw) if isinstance(overlap_raw, Mapping)
            else overlap_raw
        )
        if ov.zero1_bucket_mb > 0 and ds.get("zero1", True) is False:
            raise ValueError(
                "distributed_strategy.overlap.zero1_bucket_mb > 0 requires "
                "zero1: true — bucketing decomposes the ZeRO-1 collectives; "
                "there is nothing to bucket without sharded optimizer state"
            )
        if ov.pp_double_buffer and pp <= 1:
            raise ValueError(
                "distributed_strategy.overlap.pp_double_buffer requires "
                "pipeline_model_parallel_size > 1 (there are no stage hops "
                "to double-buffer)"
            )

    # ---- MoE --------------------------------------------------------------
    moe = model.get("moe", {}) or {}
    if moe.get("dropless") and (moe.get("capacity_factor") or 0) > 0:
        # reference validates dropless implies no capacity factor
        # (training_orchestrator.py:60-102)
        raise ValueError("moe.dropless=True requires capacity_factor unset/0")
    moe_freq = int(moe.get("moe_frequency", 1) or 1)
    if moe_freq > 1 and n_layers is not None:
        if int(n_layers) % moe_freq != 0:
            raise ValueError(
                f"num_layers={n_layers} must be a multiple of "
                f"moe.moe_frequency={moe_freq} (whole MoE+dense groups)"
            )
        groups = int(n_layers) // moe_freq
        if pp * int(vp) > 1 and groups % (pp * int(vp)) != 0:
            raise ValueError(
                f"num_layers {n_layers} / moe_frequency {moe_freq} = {groups} "
                f"MoE+dense groups, not divisible by pp*vp = {pp}*{vp}: the "
                f"pipeline slices whole groups per stage chunk"
            )

    # ---- context parallelism & attention kernels --------------------------
    seq = data.get("seq_length")
    zigzag = bool(fusions.get("zigzag_ring_attention"))
    ulysses = bool(fusions.get("ulysses_attention"))
    cp_aware = zigzag or ulysses or bool(fusions.get("ring_attention"))
    if cp > 1 and not cp_aware:
        raise ValueError(
            f"context_parallel_size={cp} requires a context-parallel attention "
            f"fusion: set fusions.ring_attention, fusions.ulysses_attention, "
            f"or fusions.zigzag_ring_attention (flash_attention alone is "
            f"single-chip and core attention would materialize the full "
            f"O(seq^2) scores)"
        )
    if cp > 1 and seq is not None and int(seq) % cp != 0:
        raise ValueError(
            f"data.seq_length={seq} must be divisible by "
            f"context_parallel_size={cp}"
        )
    if zigzag:
        if pp > 1:
            raise ValueError(
                "zigzag_ring_attention is not supported under pipeline "
                "parallelism; use fusions.ring_attention for pp + cp configs"
            )
        if model.get("sliding_window"):
            raise ValueError(
                "zigzag_ring_attention does not support sliding_window; use "
                "fusions.ring_attention (contiguous layout) for windowed models"
            )
        if cp > 1 and seq is not None and int(seq) % (2 * cp) != 0:
            raise ValueError(
                f"zigzag_ring_attention needs data.seq_length={seq} divisible "
                f"by 2*context_parallel_size = {2 * cp} (two half-chunks per "
                f"rank)"
            )
    n_heads = model.get("num_attention_heads")
    if ulysses and cp > 1 and n_heads is not None and int(n_heads) % (tp * cp) != 0:
        raise ValueError(
            f"ulysses_attention: num_attention_heads={n_heads} must be "
            f"divisible by tp*cp = {tp}*{cp} (use ring_attention when cp "
            f"exceeds the head budget)"
        )
    if cp > 1 and pp > 1 and cp_aware and seq is not None:
        # CP under PP routes attention to blockwise_gspmd_attention (the
        # nested-shard_map backward hazard), whose kv block must divide the
        # GLOBAL sequence; a non-smooth length degrades to a tiny block and
        # an s/bkv-step scan.  Seq len is static in every config, so reject
        # the cliff here instead of warning at trace time.
        from neuronx_distributed_training_tpu.parallel.ring_attention import (
            pick_bkv,
        )

        # same knob the kernels receive: fusions.flash_block_kv (threaded by
        # ops.attention to ring/ulysses, blockwise default 512 when unset)
        want = int(fusions.get("flash_block_kv") or 512)
        s = int(seq)
        bkv, degraded = pick_bkv(s, want)
        if degraded:
            raise ValueError(
                f"context-parallel-under-pipeline attention needs "
                f"data.seq_length={s} to have a divisor near the kv block "
                f"size {want} (largest available: {bkv}, an {s // bkv}-step "
                f"scan with pathological compile/step time); pad seq_length "
                f"to a smoother length (e.g. a multiple of {want})"
            )

    # ---- megatron block layout -------------------------------------------
    bt = model.get("transformer_block_type")
    if bt is not None and bt not in ("pre_ln", "post_ln", "normformer", "gpt_j"):
        raise ValueError(
            f"unknown transformer_block_type {bt!r}; supported: pre_ln, "
            f"post_ln, normformer, gpt_j (reference transformer.py:1567)"
        )
    if bt == "normformer" and model.get("moe"):
        raise ValueError(
            "normformer blocks are dense-only (the mid-MLP norm has no "
            "expert equivalent); use pre_ln or post_ln with MoE"
        )

    # ---- precision --------------------------------------------------------
    prec = cfg.get("precision", {}) or {}
    ptype = prec.get("type") if isinstance(prec, Mapping) else prec
    known = ("mixed_precision", "mixed_precisionsr", "mixed", "bf16sr",
             "bf16", "autocast", "fp32", "fp32_paramsonly", "manual")
    if ptype is not None and str(ptype).lower() not in known:
        raise ValueError(
            f"unknown precision.type {ptype!r}; supported regimes: "
            f"mixed_precision, bf16SR, autocast, fp32, manual"
        )

    # ---- autotune ---------------------------------------------------------
    # the compile-time launch planner's knob block (docs/autotuning.md):
    # root-level ``autotune: {enabled, top_k, topology, hbm_headroom,
    # max_micro_batch_size}``.  Validated here so a typo'd knob dies at load,
    # not silently mid-plan; the planner itself re-reads the block.
    at = cfg.get("autotune", None)
    if at is not None:
        if not isinstance(at, Mapping):
            raise ValueError(
                f"autotune must be a mapping of knobs (enabled/top_k/"
                f"topology/hbm_headroom/max_micro_batch_size), got "
                f"{type(at).__name__}: {at!r}"
            )
        _AT_KEYS = {"enabled", "top_k", "topology", "hbm_headroom",
                    "max_micro_batch_size"}
        unknown = set(at) - _AT_KEYS
        if unknown:
            raise ValueError(
                f"unknown autotune keys {sorted(unknown)}; supported: "
                f"{sorted(_AT_KEYS)}" + did_you_mean(unknown, _AT_KEYS)
            )
        if "top_k" in at and int(at["top_k"]) < 1:
            raise ValueError(f"autotune.top_k must be >= 1, got {at['top_k']}")
        if "hbm_headroom" in at:
            hr = float(at["hbm_headroom"])
            if not 0.0 < hr <= 1.0:
                raise ValueError(
                    f"autotune.hbm_headroom must be in (0, 1], got {hr}"
                )
        if at.get("topology") is not None:
            from neuronx_distributed_training_tpu.autotune.topology import (
                TOPOLOGIES,
            )

            if str(at["topology"]).lower() not in TOPOLOGIES:
                raise ValueError(
                    f"unknown autotune.topology {at['topology']!r}; known: "
                    f"{'/'.join(sorted(TOPOLOGIES))}"
                    + did_you_mean([at["topology"]], TOPOLOGIES)
                )

    # ---- exp_manager.telemetry -------------------------------------------
    # the unified step-telemetry knob block (spans/mfu/compile_census/
    # device_memory/goodput/batch_stats) plus the nested blocks — ``health``
    # (flight recorder: enabled/policy/ring_buffer_steps/watchdog_*),
    # ``trace`` (windowed device-time capture), ``fleet`` (per-host beacons
    # + aggregation: enabled/stale_after_seconds/aggregate/max_windows), and
    # the ``alerts`` rule list (metric/window/threshold|below|rel_drop/
    # action) — each validated by its own parser through this one call; a
    # typo'd knob, policy, or alert rule must die here, not silently run
    # with defaults (or silently never alert)
    em = cfg.get("exp_manager", {}) or {}
    if isinstance(em, Mapping) and "telemetry" in em:
        from neuronx_distributed_training_tpu.telemetry import TelemetryConfig

        TelemetryConfig.from_config(em.get("telemetry"))

    # ---- exp_manager.elastic ---------------------------------------------
    # elastic-resume policy knobs (docs/elasticity.md): replan-on-resume,
    # SIGTERM grace window, save retry/backoff.  ElasticConfig.from_config
    # rejects unknown keys with a did-you-mean hint and ill-typed values —
    # a typo'd grace_period must not silently run with the default
    if isinstance(em, Mapping) and "elastic" in em:
        from neuronx_distributed_training_tpu.trainer.elastic import (
            ElasticConfig,
        )

        ElasticConfig.from_config(em.get("elastic"))

    # ---- exp_manager.checkpoint ------------------------------------------
    # checkpoint-integrity policy knobs (docs/elasticity.md "Integrity &
    # walk-back"): digest sidecars, verified restore + walk-back/quarantine,
    # post-commit save audit.  parse_checkpoint_block rejects unknown keys
    # with a did-you-mean hint — a typo'd knob must not silently run with
    # defaults.  (The reference-schema ``checkpoint_callback_params`` block
    # keeps its separate, permissive home.)
    if isinstance(em, Mapping) and "checkpoint" in em:
        from neuronx_distributed_training_tpu.checkpoint.integrity import (
            parse_checkpoint_block,
        )

        parse_checkpoint_block(em.get("checkpoint"))

    # ---- model alignment --------------------------------------------------
    # root-level key (reference hf_llama3_8B_DPO_config.yaml:7); accepts a
    # bare string ("dpo") or a one-key block ({dpo: {beta: ...}})
    _ALIGN = ("sft", "dpo", "orpo", "kto")
    if isinstance(model, Mapping) and "model_alignment_strategy" in model:
        raise ValueError(
            "model_alignment_strategy must sit at the config ROOT (the "
            "reference schema, hf_llama3_8B_DPO_config.yaml:7), not under "
            "model: — nested it would be silently ignored"
        )
    align = cfg.get("model_alignment_strategy", None)
    if isinstance(align, str):
        if align.lower() not in _ALIGN:  # build.py lowercases the bare form
            # a typo'd string would otherwise silently run plain pretraining
            raise ValueError(
                f"unknown model_alignment_strategy {align!r}; supported: "
                f"{'/'.join(_ALIGN)}"
            )
    elif isinstance(align, Mapping) and align:
        chosen = [k for k in _ALIGN if k in align]
        if len(chosen) > 1:
            raise ValueError(
                f"model_alignment_strategy must name exactly one of "
                f"{'/'.join(_ALIGN)}, got {chosen}"
            )
        if not chosen:
            raise ValueError(
                f"model_alignment_strategy block names none of "
                f"{'/'.join(_ALIGN)}: got keys {sorted(align)}"
            )
        kto_blk = dict(align.get("kto") or {})
        if (str(kto_blk.get("kl_estimator", "batch_mean")) == "mismatched"
                and pp > 1):
            raise ValueError(
                "kto.kl_estimator: mismatched is not supported under pipeline "
                "parallelism (the KL forward would need its own pipelined "
                "pass); use the default batch_mean estimator with pp"
            )
        sft_blk = dict(align.get("sft") or {})
        if sft_blk.get("segment_mask") and (cp > 1 or cp_aware):
            raise ValueError(
                "sft.segment_mask: true (block-diagonal attention inside "
                "packed rows) is supported by the flash and core attention "
                "paths only — not under context parallelism "
                f"(context_parallel_size={cp} / ring, ulysses or zigzag "
                "fusions); disable the CP fusion or segment_mask"
            )


def batch_schedule(cfg: ConfigDict, n_devices: int) -> dict[str, int]:
    """Derived batch math, identical to the reference (``base.py:54-57``):
    ``dp = world/(tp*pp*cp)``; ``num_microbatches = gbs/(mbs*dp)``."""
    ds = cfg.get("distributed_strategy", {}) or {}
    tp = int(ds.get("tensor_model_parallel_size", 1))
    pp = int(ds.get("pipeline_model_parallel_size", 1))
    cp = int(ds.get("context_parallel_size", 1))
    dp = n_devices // (tp * pp * cp)
    if dp < 1:
        raise ValueError(
            f"world size {n_devices} too small for tp*pp*cp={tp * pp * cp}"
        )
    gbs = int(cfg.data.global_batch_size)
    mbs = int(cfg.data.micro_batch_size)
    if gbs % (mbs * dp) != 0:
        raise ValueError(
            f"global_batch_size {gbs} not divisible by micro_batch_size*dp = {mbs}*{dp}"
        )
    return {
        "dp_size": dp,
        "num_microbatches": gbs // (mbs * dp),
        "micro_batch_size": mbs,
        "global_batch_size": gbs,
    }
