"""Data layer — host-side pipeline feeding fixed-shape sharded global batches.

TPU-native re-design of the reference's ``lightning_modules/data/`` package
(BaseDataModule / HFDataModule / ModelAlignmentDataModule + datasets/):
pure-Python/numpy pipeline, deterministic per-DP-shard sampling, consumed-samples
bookkeeping, greedy packing and fixed-length padding (all batches same shape —
the reference's load-bearing rule for XLA graph reuse).
"""

from neuronx_distributed_training_tpu.data.sampler import (  # noqa: F401
    PretrainingSampler,
    RandomSampler,
)
from neuronx_distributed_training_tpu.data.packing import (  # noqa: F401
    pack_sequences,
    pad_sequences,
)
from neuronx_distributed_training_tpu.data.loader import (  # noqa: F401
    BatchStats,
    DataModule,
    DataStallError,
    HFDataModule,
    PrefetchIterator,
    SyntheticDataModule,
    batch_token_stats,
    process_global_batch,
)
