"""Compile-on-demand ctypes loading for the native (C++) data helpers.

One place for the pattern both ``data/megatron/index.py`` and
``data/packing.py`` need: rebuild the ``.so`` when the source is newer,
compile to a per-pid temp file and ``os.replace`` into place (concurrent
dataloader workers racing one output path can otherwise leave a corrupt
library whose fresh mtime pins the numpy fallback forever), and return
``None`` — never raise — when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path
from typing import Optional

logger = logging.getLogger(__name__)


def compile_and_load(src: Path) -> Optional[ctypes.CDLL]:
    """Build ``src`` (.cpp) into a sibling ``.so`` if stale, and load it."""
    lib_path = src.with_suffix(".so")
    try:
        if not lib_path.exists() or lib_path.stat().st_mtime < src.stat().st_mtime:
            tmp = lib_path.with_suffix(f".{os.getpid()}.tmp.so")
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", str(src), "-o", str(tmp)],
                check=True, capture_output=True,
            )
            os.replace(tmp, lib_path)
        return ctypes.CDLL(str(lib_path))
    except Exception as e:  # noqa: BLE001 — the numpy fallback is always correct
        logger.debug("native helper unavailable (%s): %s", src.name, e)
        return None
