"""Config -> DataModule dispatch: the ``cfg.data`` wiring layer.

The reference selects and builds the real data pipeline from YAML
(``examples/training.py:71-91``): ``model_source`` + ``model_alignment_strategy``
pick between ``HFDataModule`` (pretokenized arrow dir,
``hf_data_module.py:15-44``), ``MegatronDataModule`` (mmap ``data_prefix``),
and ``ModelAlignmentDataModule`` (jsonl/arrow SFT/DPO/ORPO).  This module is
that dispatch for the TPU stack:

    train_dm, val_dm = build_data_module(cfg, sched, seed=seed)

Synthetic data is used ONLY when explicitly configured (``data.synthetic:
true``); a config with no data source is an error, not a silent random-token
run.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from neuronx_distributed_training_tpu.data.loader import (
    DataModule,
    HFDataModule,
    SyntheticDataModule,
)
from neuronx_distributed_training_tpu.data.modules import (
    DPODataModule,
    KTODataModule,
    MegatronDataModule,
    SFTDataModule,
)

logger = logging.getLogger(__name__)


def alignment_strategy(cfg: Any) -> tuple[str, dict]:
    """Normalize ``model_alignment_strategy`` to ``(name, params)``.

    The reference uses a dict block (``hf_llama3_8B_SFT_config.yaml:108-110``:
    ``model_alignment_strategy: {sft: {packing: true}}``); a bare string form
    is also accepted.
    """
    blk = cfg.get("model_alignment_strategy", None)
    if not blk:
        return "", {}
    if isinstance(blk, str):
        return blk.lower(), {}
    for name in ("sft", "dpo", "orpo", "kto"):
        if name in blk:
            return name, dict(blk.get(name) or {})
    raise ValueError(
        f"model_alignment_strategy must be a string or contain one of "
        f"sft/dpo/orpo/kto, got keys {list(blk)}"
    )


class CharTokenizer:
    """Offline char-level tokenizer (``tokenizer.library: char``) for smoke
    runs and tests where no HF tokenizer files exist."""

    bos_token_id = 1
    eos_token_id = 2

    def __init__(self, vocab_size: int = 512):
        self.vocab_size = vocab_size

    def encode(self, text: str) -> list[int]:
        return [3 + (b % (self.vocab_size - 3)) for b in text.encode()]


def build_tokenizer(data_cfg: dict) -> Any:
    """Tokenizer from ``data.tokenizer`` (reference builds NeMo/HF tokenizers
    from ``cfg.data.tokenizer.type``, ``megatron/data_module.py:318-339``)."""
    tok_cfg = dict(data_cfg.get("tokenizer") or {})
    library = str(tok_cfg.get("library", "huggingface")).lower()
    if library == "char":
        return CharTokenizer(int(tok_cfg.get("vocab_size", 512)))
    name = tok_cfg.get("type") or tok_cfg.get("name")
    if not name:
        raise ValueError("data.tokenizer.type is required for this data path")
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(str(name))


def build_data_module(
    cfg: Any,
    sched: dict,
    *,
    seed: int = 1234,
    vocab_size: Optional[int] = None,
) -> tuple[Optional[DataModule], Optional[DataModule]]:
    """(train, val) DataModules from ``cfg.data`` (+ alignment strategy).

    Returns ``(None, None)`` only for ``data.synthetic: true`` with no vocab
    hint — the caller then builds SyntheticDataModule once the model config
    (and its vocab size) exists.
    """
    data = dict(cfg.get("data", {}) or {})
    gbs = sched["global_batch_size"]
    seq = int(data.get("seq_length")
              or (cfg.get("model", {}) or {}).get("encoder_seq_length")
              or (cfg.get("model", {}) or {}).get("max_position_embeddings")
              or 2048)
    strategy, strat_params = alignment_strategy(cfg)
    train_dir = data.get("train_dir")
    val_dir = data.get("val_dir")
    data_prefix = data.get("data_prefix")
    max_steps = int((cfg.get("trainer", {}) or {}).get("max_steps", 1000))

    if strategy in ("sft",):
        from neuronx_distributed_training_tpu.data.templates import build_template

        tokenizer = build_tokenizer(data)
        packing = bool(strat_params.get("packing", True))
        segment_mask = bool(strat_params.get("segment_mask", False))
        n_head = data.get("dev_choose_samples")
        template = build_template(data, tokenizer)

        def sft(path):
            from neuronx_distributed_training_tpu.data.modules import (
                load_alignment_records,
            )

            records = load_alignment_records(path)
            if n_head:
                records = records[: int(n_head)]
            return SFTDataModule(
                records, tokenizer, seq, gbs, packing=packing,
                segment_mask=segment_mask, seed=seed,
                template=template,
            )

        if not train_dir:
            raise ValueError("SFT needs data.train_dir (jsonl/json/arrow)")
        return sft(train_dir), (sft(val_dir) if val_dir else None)

    if strategy in ("dpo", "orpo", "kto"):
        tokenizer = build_tokenizer(data)
        # kto: unpaired (prompt, completion, label) records — an extension
        # beyond the reference's pair-only surface (see alignment/kto.py)
        module_cls = KTODataModule if strategy == "kto" else DPODataModule

        def pref(path):
            extra = {}
            if strategy == "kto":
                extra["kl_estimator"] = str(
                    strat_params.get("kl_estimator", "batch_mean"))
            return module_cls(
                path, tokenizer, seq, gbs, seed=seed,
                max_prompt_length=strat_params.get("max_prompt_length"),
                truncation_mode=str(strat_params.get("truncation_mode", "keep_start")),
                **extra,
            )

        if not train_dir:
            raise ValueError(f"{strategy.upper()} needs data.train_dir (jsonl/json/arrow)")
        return pref(train_dir), (pref(val_dir) if val_dir else None)

    if data_prefix:
        # Megatron mmap pretraining (reference megatron/data_module.py:89-130);
        # data_prefix may be [weight, path, weight, path, ...] — the blended
        # multi-corpus form (reference :227-290)
        prefix = data_prefix
        if isinstance(prefix, (list, tuple)):
            items = list(prefix)
            if len(items) == 1:
                prefix = items[0]
            else:
                try:
                    if len(items) % 2 != 0:
                        raise ValueError("odd length")
                    pairs = [
                        (float(items[i]), str(items[i + 1]))
                        for i in range(0, len(items), 2)
                    ]
                except (TypeError, ValueError) as e:
                    raise ValueError(
                        f"multi-corpus data_prefix must be [weight, path, "
                        f"weight, path, ...] pairs with numeric weights, "
                        f"got {items}"
                    ) from e
                from neuronx_distributed_training_tpu.data.modules import (
                    BlendedMegatronDataModule,
                )

                return BlendedMegatronDataModule(
                    pairs, seq, gbs, max_steps=max_steps, seed=seed,
                ), None
        train = MegatronDataModule(
            prefix, seq, gbs, max_steps=max_steps, seed=seed,
        )
        return train, None

    if train_dir:
        # HF pretokenized-arrow pretraining (reference hf_data_module.py:15-44)
        train = HFDataModule(train_dir, gbs, seed=seed)
        val = HFDataModule(val_dir, gbs, seed=seed) if val_dir else None
        return train, val

    if data.get("synthetic"):
        if vocab_size is None:
            return None, None  # caller builds it with the model's vocab
        return (
            SyntheticDataModule(
                vocab_size=vocab_size, seq_len=seq, global_batch_size=gbs, seed=seed
            ),
            None,
        )

    raise ValueError(
        "cfg.data has no data source: set data.train_dir (HF arrow dir or "
        "jsonl for alignment), data.data_prefix (Megatron mmap), or "
        "data.synthetic: true for random-token smoke runs"
    )
