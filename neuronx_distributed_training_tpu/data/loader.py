"""DataModules: dataset -> fixed-shape sharded global batches.

Re-design of the reference's ``BaseDataModule``/``HFDataModule``
(``data/base.py``, ``hf_data_module.py``): a DataModule owns a dataset + sampler
+ batch math and yields device-ready global batches.  Differences from the
reference, by design:

- no torch DataLoader / MpDeviceLoader: batches are numpy on host, transferred
  once per step via ``jax.make_array_from_process_local_data`` (multi-host
  correct — each process contributes its DP-local rows);
- the global batch goes to device **whole**; microbatching happens inside the
  jitted step (``trainer/step.py:microbatch_split``), where the reference loops
  microbatches on host (``base.py:330-350``).
"""

from __future__ import annotations

import errno
import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.data.packing import IGNORE_INDEX
from neuronx_distributed_training_tpu.data.sampler import PretrainingSampler, RandomSampler
from neuronx_distributed_training_tpu.parallel.mesh import DATA_AXES


logger = logging.getLogger(__name__)

#: errno values treated as TRANSIENT data-READ failures (an NFS/FUSE mount
#: flap, a stale handle, an object-store hiccup, a wedged-but-recovering
#: arrow page-in) — worth a bounded retry with backoff on the prefetch
#: thread.  Anything else (missing file, bad index, programming error)
#: re-raises immediately.  The WRITE-side sibling table lives in
#: ``checkpoint.manager.TRANSIENT_SAVE_ERRNOS``.
TRANSIENT_READ_ERRNOS = frozenset({
    errno.EIO, errno.EAGAIN, errno.EBUSY, errno.ETIMEDOUT, errno.EINTR,
    errno.ESTALE, errno.ENETDOWN, errno.ENETUNREACH, errno.ECONNRESET,
})


def is_transient_io_error(exc: BaseException) -> bool:
    """Is ``exc`` (or anything in its cause/context chain) a transient read
    I/O error worth retrying?  Dataset libraries (arrow, fsspec, datasets)
    wrap the underlying ``OSError``, so the chain is walked — the same
    classifier shape as ``checkpoint.manager.is_transient_save_error``."""
    seen: set[int] = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, TimeoutError):
            return True
        if isinstance(cur, OSError) and cur.errno in TRANSIENT_READ_ERRNOS:
            return True
        cur = cur.__cause__ or cur.__context__
    return False


class DataStallError(RuntimeError):
    """The upstream data iterator produced nothing for longer than the
    configured ``data_wait`` timeout — a dead mount, a wedged arrow page-in,
    a remote store hang.  Raised by :class:`PrefetchIterator` instead of
    blocking the step boundary forever; the trainer dumps a hang-watchdog
    forensic bundle before re-raising (``exp_manager.telemetry.health.
    data_wait_timeout_seconds``, docs/observability.md)."""


class PrefetchIterator:
    """Bounded background prefetch over a batch iterator.

    The reference overlaps host batch prep with device compute via
    ``MpDeviceLoader`` (``base.py:330-350``); here JAX's async dispatch covers
    most of it, but a slow ``fetch_rows`` (arrow page-in, mmap faults) on the
    loop thread still stalls dispatch.  A daemon thread keeps ``depth``
    batches ready in a queue; exceptions propagate to the consumer at the
    point they would have occurred.  ``close()`` (or GC) stops the thread.

    ``timeout_seconds`` (> 0) arms the data-stall watchdog: a ``__next__``
    that finds nothing for that long raises :class:`DataStallError` with a
    curated diagnosis instead of freezing the run silently.  The timeout is
    per-batch wait, not cumulative — a healthy-but-slow source that keeps
    producing within the bound never trips it.

    ``activity_fn`` (e.g. ``DataModule.last_io_activity``) is the retry
    handshake: while the producer side is actively RETRYING a transient
    read error (bounded exponential backoff on the prefetch thread —
    ``DataModule._fetch_with_retry``), the stall timer defers, so
    :class:`DataStallError` fires only after the retries are exhausted or
    the source is genuinely silent — never mid-recovery.
    """

    _DONE = object()

    def __init__(self, it: Iterator, depth: int = 2,
                 timeout_seconds: Optional[float] = None,
                 activity_fn: Optional[Callable[[], float]] = None):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._timeout = (float(timeout_seconds)
                         if timeout_seconds and timeout_seconds > 0 else None)
        self._activity = activity_fn
        # the thread target captures ONLY the queue/event/sentinel — never
        # self — so an abandoned iterator stays collectible: __del__ then
        # fires, stops the thread, and the queued device batches are freed
        q, stop, done = self._q, self._stop, PrefetchIterator._DONE

        def put(item) -> bool:
            """Enqueue unless close() intervened — EVERY producer put (data,
            terminal sentinel, exception) must honor the stop event, or the
            daemon thread blocks forever on a full queue after close(),
            pinning the queued device batches for process lifetime."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def run() -> None:
            try:
                for item in it:
                    if not put(item):
                        return
                put(done)
            except BaseException as e:  # noqa: BLE001 — re-raised at consumer
                put(e)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="nxdt-prefetch")
        self._thread.start()

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self):
        # timeout loop so a consumer blocked here wakes up after close()
        # (the producer may have died without enqueueing the sentinel)
        waited_from = time.monotonic() if self._timeout is not None else None
        while True:
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._stop.is_set():
                    raise StopIteration
                if (waited_from is not None
                        and time.monotonic() - waited_from > self._timeout):
                    if self._activity is not None:
                        try:
                            act = float(self._activity() or 0.0)
                        except Exception:  # noqa: BLE001 — a seam, not load-bearing
                            act = 0.0
                        if act and time.monotonic() - act <= self._timeout:
                            # the producer is mid-retry (transient I/O
                            # backoff): defer the stall verdict until the
                            # retries themselves go silent
                            waited_from = time.monotonic()
                            continue
                    state = ("still running — the source itself is hung "
                             "(dead mount? wedged arrow page-in? remote "
                             "store stall?)" if self._thread.is_alive()
                             else "DEAD without raising")
                    raise DataStallError(
                        f"data_wait exceeded {self._timeout:.0f}s with no "
                        f"batch from the upstream iterator (prefetch thread "
                        f"{state}); raise exp_manager.telemetry.health."
                        f"data_wait_timeout_seconds for a legitimately "
                        f"slower source, or 0 to disable this watchdog"
                    )
        if item is self._DONE:
            # terminal: mark stopped so REPEAT next() calls keep raising
            # StopIteration (iterator protocol) instead of polling forever
            self._stop.set()
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()  # producer is dead; further next() terminates
            raise item
        return item

    def close(self) -> None:
        self._stop.set()

    def __del__(self) -> None:  # pragma: no cover — belt and braces
        self._stop.set()


def batch_token_stats(
    batch: dict[str, np.ndarray], *, pad_id: Optional[int] = None
) -> dict[str, float]:
    """Per-global-batch data-pipeline stats from the HOST numpy batch
    (docs/observability.md "Data-pipeline stats").

    - ``data/padding_fraction``: fraction of token positions contributing
      nothing — ``input_ids == pad_id`` when the pad token is known, else
      ``loss_mask == 0`` positions (which for SFT also counts masked prompt
      tokens; the glossary documents the distinction).
    - ``data/packing_efficiency``: mean effective row length / row width,
      where effective length is the index of the last active position + 1 —
      how much of each row the packer actually filled (1.0 = fully packed).
    - ``data/seq_len_{mean,p50,min,max}``: the per-row effective-length
      spread (the histogram summary a terminal can read).

    Computed host-side from the already-materialized batch — zero device
    work; the accumulator below runs it on the prefetch thread so not even
    host time lands between dispatches.
    """
    ids = batch.get("input_ids")
    if ids is None:
        return {}
    ids = np.asarray(ids)
    if ids.ndim != 2 or ids.size == 0:
        return {}
    if pad_id is not None:
        active = ids != pad_id
    elif "loss_mask" in batch:
        active = np.asarray(batch["loss_mask"]) > 0
    else:
        active = np.ones_like(ids, dtype=bool)
    rows, width = active.shape
    # effective length: last active position + 1 (0 for an all-pad row)
    any_active = active.any(axis=1)
    last = width - 1 - np.argmax(active[:, ::-1], axis=1)
    eff = np.where(any_active, last + 1, 0).astype(np.float64)
    return {
        "data/padding_fraction": float(1.0 - active.mean()),
        "data/packing_efficiency": float(eff.mean() / width),
        "data/seq_len_mean": float(eff.mean()),
        "data/seq_len_p50": float(np.median(eff)),
        "data/seq_len_min": float(eff.min()),
        "data/seq_len_max": float(eff.max()),
    }


class BatchStats:
    """Thread-safe accumulator of :func:`batch_token_stats` across the
    batches between two logging boundaries.

    The prefetch thread calls :meth:`update` per global batch (inside
    ``DataModule.global_batches``); the trainer drains the running means at
    each boundary into the metric stream.  Means average across batches;
    min/max extremes survive the window."""

    def __init__(self, *, pad_id: Optional[int] = None) -> None:
        self.pad_id = pad_id
        self._lock = threading.Lock()
        self._sums: dict[str, float] = {}
        self._mins: dict[str, float] = {}
        self._maxs: dict[str, float] = {}
        self._n = 0

    def update(self, batch: dict[str, np.ndarray]) -> None:
        stats = batch_token_stats(batch, pad_id=self.pad_id)
        if not stats:
            return
        with self._lock:
            self._n += 1
            for k, v in stats.items():
                self._sums[k] = self._sums.get(k, 0.0) + v
                if k.endswith("_min"):
                    self._mins[k] = min(self._mins.get(k, v), v)
                elif k.endswith("_max"):
                    self._maxs[k] = max(self._maxs.get(k, v), v)

    def drain(self) -> dict[str, float]:
        """Stats for the batches seen since the last drain ({} when none)."""
        with self._lock:
            if self._n == 0:
                return {}
            out = {k: v / self._n for k, v in self._sums.items()}
            out.update(self._mins)
            out.update(self._maxs)
            self._sums, self._mins, self._maxs = {}, {}, {}
            self._n = 0
        return out


def process_global_batch(
    batch: dict[str, np.ndarray],
    *,
    input_names: Sequence[str] = ("input_ids", "labels", "loss_mask"),
    pad_id: Optional[int] = None,
    derive_loss_mask: bool = True,
) -> dict[str, np.ndarray]:
    """Filter to model ``input_names`` and derive missing ``labels``/``loss_mask``
    (reference ``hf_data_module.py:49-58``, ``model_alignment_data_module.py:239-255``).

    ``pad_id`` must only be set when the dataset actually pads with that token —
    it additionally masks those positions out of the loss.  Leave ``None`` for
    packed/unpadded data where the pad token id is a legitimate vocab token.
    """
    out: dict[str, np.ndarray] = {}
    ids = np.asarray(batch["input_ids"], dtype=np.int32)
    out["input_ids"] = ids
    if "labels" in input_names:
        labels = np.asarray(batch.get("labels", ids), dtype=np.int32)
        out["labels"] = labels
        if "loss_mask" in input_names:
            if "loss_mask" in batch:
                out["loss_mask"] = np.asarray(batch["loss_mask"], dtype=np.float32)
            elif derive_loss_mask:
                mask = labels != IGNORE_INDEX
                if pad_id is not None:
                    mask &= ids != pad_id
                out["loss_mask"] = mask.astype(np.float32)
    for k in input_names:
        if k not in out and k in batch:
            out[k] = np.asarray(batch[k])
    return out


def shard_batch(
    batch: dict[str, np.ndarray], mesh: Mesh, spec: Optional[P] = None
) -> dict[str, jax.Array]:
    """Host numpy **global** batch -> sharded device arrays.

    Every process holds the full global batch (samplers are deterministic, so
    all hosts compute identical batches — reference keeps the global batch on
    CPU the same way, ``data/base.py:58-64``); each process device_puts only the
    slices its addressable devices own, so multi-host needs no communication.
    Replaces the reference's MpDeviceLoader host->device move (``base.py:330-350``).
    """
    spec = spec if spec is not None else P(DATA_AXES)
    sharding = NamedSharding(mesh, spec)
    out: dict[str, jax.Array] = {}
    for k, v in batch.items():
        idx_map = sharding.addressable_devices_indices_map(v.shape)
        shards = [jax.device_put(v[idx], d) for d, idx in idx_map.items()]
        out[k] = jax.make_array_from_single_device_arrays(v.shape, sharding, shards)
    return out


class DataModule:
    """Base: sampler + gather + batch math.  Subclasses implement ``fetch_rows``."""

    def __init__(
        self,
        total_samples: int,
        global_batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 1234,
        consumed_samples: int = 0,
        input_names: Sequence[str] = ("input_ids", "labels", "loss_mask"),
        pad_id: Optional[int] = None,
        io_retries: int = 3,
        io_retry_backoff_seconds: float = 0.5,
    ):
        self.global_batch_size = global_batch_size
        self.input_names = tuple(input_names)
        self.pad_id = pad_id
        # data-pipeline stats hook (telemetry.batch_stats): the trainer
        # attaches a BatchStats accumulator here; global_batches feeds it
        # on the prefetch thread and the boundary drains it into metrics
        self.batch_stats: Optional[BatchStats] = None
        # transient-read retry policy (``data.io_retries`` /
        # ``data.io_retry_backoff_seconds``; the trainer imposes the config
        # values post-construction).  ``io_retry_count`` is the cumulative
        # counter the boundary surfaces as the ``data/io_retries`` metric.
        self.io_retries = int(io_retries)
        self.io_retry_backoff_seconds = float(io_retry_backoff_seconds)
        self.io_retry_count = 0
        self._io_lock = threading.Lock()
        self._io_activity = 0.0
        if shuffle:
            self.sampler: Any = RandomSampler(
                total_samples, global_batch_size, seed=seed, consumed_samples=consumed_samples
            )
        else:
            self.sampler = PretrainingSampler(
                total_samples, global_batch_size, consumed_samples=consumed_samples
            )

    @property
    def consumed_samples(self) -> int:
        """Single integer of resume state (reference ``data/base.py:33-47``)."""
        return self.sampler.consumed_samples

    def fetch_rows(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def last_io_activity(self) -> float:
        """Monotonic timestamp of the last transient-retry attempt — the
        data-stall watchdog's handshake (``PrefetchIterator(activity_fn=``):
        a stall verdict is deferred while retries are still in flight."""
        return self._io_activity

    def _fetch_with_retry(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        """``fetch_rows`` with bounded exponential-backoff retry on
        transient read errors (:func:`is_transient_io_error` — the
        cause-chain classifier).  Runs on the PREFETCH thread, so neither
        the backoff sleeps nor a recovered page-in ever lands between
        dispatches.  Non-transient errors and exhausted retries re-raise;
        only then can the consumer see a failure."""
        delay = self.io_retry_backoff_seconds
        for attempt in range(self.io_retries + 1):
            try:
                return self.fetch_rows(idx)
            except Exception as e:  # noqa: BLE001 — classified below
                if attempt >= self.io_retries or not is_transient_io_error(e):
                    raise
                with self._io_lock:
                    self.io_retry_count += 1
                logger.warning(
                    "data: transient read error (%s: %s) — retry %d/%d in "
                    "%.1fs", type(e).__name__, e, attempt + 1,
                    self.io_retries, delay)
                # sleep in short slices, refreshing the activity timestamp
                # each one: a backoff delay LONGER than the stall timeout
                # must still defer the stall verdict — the contract is
                # "DataStallError only after retries are exhausted", not
                # "unless the backoff outgrew the timeout"
                deadline = time.monotonic() + delay
                while True:
                    self._io_activity = time.monotonic()
                    remaining = deadline - self._io_activity
                    if remaining <= 0:
                        break
                    time.sleep(min(remaining, 0.25))
                delay *= 2
                self._io_activity = time.monotonic()
        raise AssertionError("unreachable")  # pragma: no cover

    def global_batches(self) -> Iterator[dict[str, np.ndarray]]:
        """Yield processed host-side global batches (numpy)."""
        for idx in self.sampler:
            batch = process_global_batch(
                self._fetch_with_retry(idx), input_names=self.input_names,
                pad_id=self.pad_id
            )
            if self.batch_stats is not None:
                self.batch_stats.update(batch)
            yield batch

    def sharded_batches(
        self, mesh: Mesh, spec: Optional[P] = None
    ) -> Iterator[dict[str, jax.Array]]:
        for batch in self.global_batches():
            yield shard_batch(batch, mesh, spec)


class HFDataModule(DataModule):
    """HF-datasets-on-disk module (reference ``hf_data_module.py:15-44``:
    ``load_from_disk`` + per-DP sharding, fixed-length rows expected)."""

    def __init__(self, dataset_or_path: Any, global_batch_size: int, **kw: Any):
        if isinstance(dataset_or_path, (str, os.PathLike)):
            import datasets  # lazy: heavy import

            self.dataset = datasets.load_from_disk(str(dataset_or_path))
        else:
            self.dataset = dataset_or_path
        super().__init__(len(self.dataset), global_batch_size, **kw)

    def fetch_rows(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        rows = self.dataset[[int(i) for i in idx]]
        return {k: np.asarray(v) for k, v in rows.items() if not k.startswith("__")}


class SyntheticDataModule(DataModule):
    """Deterministic synthetic causal-LM data (for benchmarks, smoke tests, and
    the reference's TRAIN_ITERS-style short-run integration tests)."""

    def __init__(
        self,
        vocab_size: int,
        seq_len: int,
        global_batch_size: int,
        *,
        total_samples: int = 1 << 16,
        seed: int = 0,
        **kw: Any,
    ):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self._seed = seed
        super().__init__(total_samples, global_batch_size, **kw)

    def fetch_rows(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        # content is a pure function of the row index -> reproducible across
        # hosts and resumes without storing anything
        rows = np.empty((len(idx), self.seq_len), dtype=np.int32)
        for r, i in enumerate(idx):
            rng = np.random.Generator(np.random.PCG64(self._seed * 1_000_003 + int(i)))
            rows[r] = rng.integers(0, self.vocab_size, self.seq_len, dtype=np.int32)
        return {"input_ids": rows}
