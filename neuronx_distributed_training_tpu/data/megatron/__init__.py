"""Megatron-style mmap pretraining datasets (.bin/.idx) with C++ index building."""

from neuronx_distributed_training_tpu.data.megatron.dataset import (  # noqa: F401
    GPTDataset,
    IndexedDataset,
    write_indexed_dataset,
)
from neuronx_distributed_training_tpu.data.megatron.index import (  # noqa: F401
    build_doc_idx,
    build_sample_idx,
    build_shuffle_idx,
)
