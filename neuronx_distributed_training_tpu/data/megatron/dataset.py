"""Mmap indexed dataset (.bin/.idx) + GPTDataset sample assembly.

The reference consumes NeMo/Megatron-core ``MMapIndexedDataset`` (binary token
file + index, built offline by ``preprocess_data``) through its forked
``GPTDataset`` (``gpt_dataset_patch.py:53-570``).  Same storage format here so
existing Megatron-preprocessed corpora load unchanged:

.idx layout (Megatron MMIDIDX v1):
  magic ``MMIDIDX\\x00\\x00`` | u64 version=1 | u8 dtype_code | u64 count
  | u64 doc_count | i32 sizes[count] | i64 pointers[count]
  | i64 doc_idx[doc_count]

Reading is numpy memmap (zero-copy); the expensive sample-index construction
is the C++ loop in ``index_builder.cpp``.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Optional

import numpy as np

from neuronx_distributed_training_tpu.data.megatron.index import (
    build_doc_idx,
    build_sample_idx,
    build_shuffle_idx,
)

_MAGIC = b"MMIDIDX\x00\x00"
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def write_indexed_dataset(path_prefix: str | Path, docs: list[np.ndarray]) -> None:
    """Write .bin/.idx in Megatron format (the offline preprocess step)."""
    path_prefix = Path(path_prefix)
    docs = [np.asarray(d) for d in docs]
    dtype = docs[0].dtype if docs else np.dtype(np.int32)
    with open(path_prefix.with_suffix(".bin"), "wb") as f:
        for d in docs:
            f.write(d.astype(dtype).tobytes(order="C"))
    sizes = np.array([len(d) for d in docs], np.int32)
    itemsize = dtype.itemsize
    pointers = np.zeros(len(docs), np.int64)
    if len(docs) > 1:
        pointers[1:] = np.cumsum(sizes[:-1].astype(np.int64) * itemsize)
    doc_idx = np.arange(len(docs) + 1, dtype=np.int64)  # Megatron stores n+1 entries
    with open(path_prefix.with_suffix(".idx"), "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", 1))
        f.write(struct.pack("<B", _DTYPE_CODES[np.dtype(dtype)]))
        f.write(struct.pack("<Q", len(docs)))
        f.write(struct.pack("<Q", len(doc_idx)))
        f.write(sizes.tobytes())
        f.write(pointers.tobytes())
        f.write(doc_idx.tobytes())


class IndexedDataset:
    """Zero-copy mmap reader for Megatron .bin/.idx pairs."""

    def __init__(self, path_prefix: str | Path):
        path_prefix = Path(path_prefix)
        with open(path_prefix.with_suffix(".idx"), "rb") as f:
            magic = f.read(9)
            if magic != _MAGIC:
                raise ValueError(f"bad index magic in {path_prefix}.idx")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != 1:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(_DTYPES[code])
            (count,) = struct.unpack("<Q", f.read(8))
            (doc_count,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx = np.memmap(path_prefix.with_suffix(".idx"), mode="r", offset=offset)
        self.sizes = np.frombuffer(idx, np.int32, count, 0)
        ptr_off = count * 4
        self.pointers = np.frombuffer(idx, np.int64, count, ptr_off)
        self._bin = np.memmap(path_prefix.with_suffix(".bin"), dtype=self.dtype, mode="r")

    def __len__(self) -> int:
        return len(self.sizes)

    def get(self, doc: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        start = self.pointers[doc] // self.dtype.itemsize + offset
        n = (self.sizes[doc] - offset) if length is None else length
        return np.asarray(self._bin[start : start + n])


class GPTDataset:
    """Fixed-length causal-LM samples over an IndexedDataset.

    Deterministic in (seed, seq_length, num_samples); index mappings cached as
    .npy next to the data (the reference builds on rank 0 and mmaps elsewhere —
    here every host builds deterministically OR hits the same cache files).
    """

    def __init__(
        self,
        path_prefix: str | Path,
        seq_length: int,
        num_samples: int,
        *,
        seed: int = 1234,
        cache_dir: Optional[str | Path] = None,
    ):
        self.indexed = IndexedDataset(path_prefix)
        self.seq_length = seq_length
        tokens_total = int(self.indexed.sizes.sum())
        tokens_per_epoch = max(tokens_total, 1)
        num_epochs = int(np.ceil((num_samples * (seq_length + 1)) / tokens_per_epoch)) + 1

        cache = Path(cache_dir) if cache_dir else Path(str(path_prefix) + "_cache")
        cache.mkdir(parents=True, exist_ok=True)
        tag = f"s{seed}_l{seq_length}_n{num_samples}"
        doc_p = cache / f"doc_idx_{tag}.npy"
        samp_p = cache / f"sample_idx_{tag}.npy"
        shuf_p = cache / f"shuffle_idx_{tag}.npy"
        if doc_p.exists() and samp_p.exists() and shuf_p.exists():
            self.doc_idx = np.load(doc_p, mmap_mode="r")
            self.sample_idx = np.load(samp_p, mmap_mode="r")
            self.shuffle_idx = np.load(shuf_p, mmap_mode="r")
        else:
            self.doc_idx = build_doc_idx(len(self.indexed), num_epochs, seed)
            self.sample_idx = build_sample_idx(
                self.indexed.sizes, self.doc_idx, num_samples, seq_length
            )
            self.shuffle_idx = build_shuffle_idx(len(self.sample_idx) - 1, seed)
            # atomic writes (tmp + rename): another host may be racing on the
            # same cache dir; a reader must never see a partially-written .npy
            import os

            for path, arr in ((doc_p, self.doc_idx), (samp_p, self.sample_idx),
                              (shuf_p, self.shuffle_idx)):
                tmp = path.with_suffix(f".tmp{os.getpid()}.npy")
                np.save(tmp, arr)
                os.replace(tmp, path)

    def __len__(self) -> int:
        return len(self.shuffle_idx)

    def __getitem__(self, i: int) -> dict[str, np.ndarray]:
        i = int(self.shuffle_idx[i % len(self.shuffle_idx)])
        (doc_a, off_a), (doc_b, off_b) = self.sample_idx[i], self.sample_idx[i + 1]
        parts = []
        if doc_a == doc_b:
            parts.append(self.indexed.get(self.doc_idx[doc_a], off_a,
                                          off_b - off_a + 1))
        else:
            parts.append(self.indexed.get(self.doc_idx[doc_a], off_a))
            for d in range(doc_a + 1, doc_b):
                parts.append(self.indexed.get(self.doc_idx[d]))
            parts.append(self.indexed.get(self.doc_idx[doc_b], 0, off_b + 1))
        tokens = np.concatenate(parts).astype(np.int32)
        assert len(tokens) == self.seq_length + 1, (
            f"sample {i}: got {len(tokens)} tokens, want {self.seq_length + 1}"
        )
        return {"input_ids": tokens[:-1], "labels": tokens[1:]}
