"""Doc/sample/shuffle index building for mmap GPT datasets.

The reference forks NeMo's GPTDataset to patch ``_build_index_mappings``
(``gpt_dataset_patch.py:53-570``): doc_idx (shuffled docs per epoch),
sample_idx (seq_length-token walk over the doc stream — built by a C++
extension upstream), shuffle_idx (shuffled sample order), built once on rank 0
and mmap'ed by other ranks.  Same design here: deterministic numpy for
doc/shuffle, the C++ ``index_builder.cpp`` loop (ctypes) for sample_idx with a
numpy fallback, and .npy caching keyed by (seed, seq_length, num_samples).
"""

from __future__ import annotations

import ctypes
import logging
from pathlib import Path
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_SRC = Path(__file__).with_name("index_builder.cpp")
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the C++ builder; None if no toolchain."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    try:
        from neuronx_distributed_training_tpu.data._native import compile_and_load

        lib = compile_and_load(_SRC)
        if lib is None:
            raise OSError("native index builder unavailable")
        lib.build_sample_idx.restype = ctypes.c_int64
        lib.build_sample_idx.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
    except Exception as e:  # noqa: BLE001 — numpy fallback keeps working
        logger.warning("C++ index builder unavailable (%s); using numpy fallback", e)
    return _lib


def build_doc_idx(num_docs: int, num_epochs: int, seed: int) -> np.ndarray:
    """Shuffled document order, per epoch (reference ``gpt_dataset_patch.py``)."""
    rng = np.random.Generator(np.random.PCG64(seed))
    parts = []
    for _ in range(num_epochs):
        parts.append(rng.permutation(num_docs).astype(np.int32))
    return np.concatenate(parts)


def _sample_idx_numpy(doc_lens, doc_idx, num_samples, seq_length):
    out = np.zeros((num_samples + 1, 2), np.int64)
    cursor, offset, sample = 0, 0, 0
    n = len(doc_idx)
    while sample < num_samples:
        remaining = seq_length + 1
        while remaining > 0:
            if cursor >= n:
                return out[: sample + 1]
            doc_len = int(doc_lens[doc_idx[cursor]]) - offset
            if doc_len >= remaining:  # boundary stays inside the doc on exact fill
                offset += remaining - 1
                remaining = 0
            else:
                remaining -= doc_len
                cursor += 1
                offset = 0
        sample += 1
        out[sample] = (cursor, offset)
    return out


def build_sample_idx(
    doc_lens: np.ndarray, doc_idx: np.ndarray, num_samples: int, seq_length: int
) -> np.ndarray:
    """``[num_samples+1, 2]`` (doc_idx_index, doc_offset) sample boundaries."""
    doc_lens = np.ascontiguousarray(doc_lens, np.int32)
    doc_idx = np.ascontiguousarray(doc_idx, np.int32)
    lib = _load_native()
    if lib is None:
        return _sample_idx_numpy(doc_lens, doc_idx, num_samples, seq_length)
    out = np.zeros((num_samples + 1, 2), np.int64)
    n = lib.build_sample_idx(
        doc_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        doc_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(doc_idx),
        num_samples,
        seq_length,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out[: n + 1]


def build_shuffle_idx(num_samples: int, seed: int) -> np.ndarray:
    rng = np.random.Generator(np.random.PCG64(seed + 1))
    return rng.permutation(num_samples).astype(np.int64)
