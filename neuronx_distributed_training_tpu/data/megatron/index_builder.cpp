// Sample-index builder for mmap GPT datasets.
//
// TPU-native counterpart of the Megatron-core `helpers` C++ extension the
// reference builds in install_setup.sh:6-12 (`make` inside
// megatron/core/datasets; failure mode documented in known_issues.rst:92-143).
// The hot loop: walk shuffled documents token-by-token and emit one
// (doc_idx_index, doc_offset) pair per training sample of `seq_length` tokens.
// Python/numpy does this in minutes for trillion-token corpora; this loop does
// it in seconds.  Exposed extern "C" for ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -shared -fPIC index_builder.cpp -o _index_builder.so

#include <cstdint>

extern "C" {

// sample_idx out buffer must hold (num_samples + 1) * 2 int64s.
// doc_lens[i] is the token length of document doc_idx[i] (already shuffled
// order).  Returns the number of samples actually emitted (== num_samples
// unless the corpus runs out, which the caller sizes against).
int64_t build_sample_idx(const int32_t* doc_lens,
                         const int32_t* doc_idx,
                         int64_t num_docs,
                         int64_t num_samples,
                         int64_t seq_length,
                         int64_t* sample_idx /* out */) {
  int64_t sample = 0;
  int64_t doc_cursor = 0;     // index into doc_idx
  int64_t doc_offset = 0;     // token offset inside current document
  sample_idx[0] = doc_cursor;
  sample_idx[1] = doc_offset;
  // +1 token: each sample needs seq_length + 1 tokens (input + shifted label)
  while (sample < num_samples) {
    int64_t remaining = seq_length + 1;
    while (remaining > 0) {
      if (doc_cursor >= num_docs) {
        return sample;  // corpus exhausted
      }
      int64_t doc_len = doc_lens[doc_idx[doc_cursor]] - doc_offset;
      if (doc_len >= remaining) {
        // boundary stays INSIDE this doc even on exact fill (offset = len-1):
        // the boundary token is shared between consecutive samples (Megatron
        // semantics; keeps every sample exactly seq_length+1 tokens)
        doc_offset += remaining - 1;
        remaining = 0;
      } else {
        remaining -= doc_len;
        ++doc_cursor;
        doc_offset = 0;
      }
    }
    ++sample;
    sample_idx[2 * sample] = doc_cursor;
    sample_idx[2 * sample + 1] = doc_offset;
  }
  return sample;
}

}  // extern "C"
