"""Higher-level DataModules: Megatron pretraining + SFT/DPO alignment.

Counterparts of the reference's ``MegatronDataModule``
(``data/megatron/data_module.py``: tokenizer build, mmap GPT dataset build with
train/valid/test sample counts from ``max_steps x gbs``, per-DP samplers) and
``ModelAlignmentDataModule`` (``model_alignment_data_module.py``: jsonl/arrow
load, prompt templates, per-algorithm tokenization, packing/padding dataloader
build).
"""

from __future__ import annotations

import json
import warnings
from pathlib import Path
from typing import Any, Optional, Sequence

import numpy as np

from neuronx_distributed_training_tpu.data.loader import DataModule
from neuronx_distributed_training_tpu.data.packing import (
    mask_prompt_labels,
    pack_sequences,
    pad_sequences,
)


class MegatronDataModule(DataModule):
    """Mmap GPT pretraining data (reference ``megatron/data_module.py:89-173``).

    ``num_samples`` defaults to ``max_steps * global_batch_size`` the way the
    reference sizes its train split (``:89-130``).

    ``labels_pre_shifted``: GPTDataset emits ``input_ids = tokens[:-1]``,
    ``labels = tokens[1:]`` (the reference's Megatron convention,
    ``gpt_dataset_patch.py``), so the trainer must run the model with
    ``shift_labels=False`` — ``Trainer.from_config`` reads this attribute.
    """

    labels_pre_shifted = True

    def __init__(
        self,
        path_prefix: str | Path,
        seq_length: int,
        global_batch_size: int,
        *,
        max_steps: int = 1000,
        num_samples: Optional[int] = None,
        seed: int = 1234,
        **kw: Any,
    ):
        from neuronx_distributed_training_tpu.data.megatron import GPTDataset

        n = num_samples or max_steps * global_batch_size
        self.dataset = GPTDataset(path_prefix, seq_length, n, seed=seed)
        super().__init__(len(self.dataset), global_batch_size,
                         input_names=("input_ids", "labels", "loss_mask"), **kw)

    def fetch_rows(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        rows = [self.dataset[int(i)] for i in idx]
        return {
            "input_ids": np.stack([r["input_ids"] for r in rows]),
            "labels": np.stack([r["labels"] for r in rows]),
        }


class BlendedMegatronDataModule(DataModule):
    """Weighted blend of several mmap corpora (the reference's
    ``MemoryEfficientBlendableDataset`` flow, ``megatron/data_module.py:
    227-290``: ``data_prefix: [w1, p1, w2, p2, ...]`` with
    ``get_datasets_weights_and_num_samples`` sizing each corpus).

    Sampling: a seeded multinomial assigns each global sample index to a
    corpus (deterministic across restarts — resume-safe the same way the
    sampler's consumed-samples counter is); the per-corpus inner index is the
    running count of prior assignments, so every corpus is consumed in order
    with its own shuffle.
    """

    labels_pre_shifted = True

    def __init__(
        self,
        prefixes_and_weights: Sequence[tuple[float, str | Path]],
        seq_length: int,
        global_batch_size: int,
        *,
        max_steps: int = 1000,
        num_samples: Optional[int] = None,
        seed: int = 1234,
        **kw: Any,
    ):
        from neuronx_distributed_training_tpu.data.megatron import GPTDataset

        if not prefixes_and_weights:
            raise ValueError("blended data needs at least one (weight, prefix)")
        n = num_samples or max_steps * global_batch_size
        w = np.asarray([float(wt) for wt, _ in prefixes_and_weights], np.float64)
        if np.any(w <= 0):
            raise ValueError(f"blend weights must be positive, got {w}")
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        self.choices = rng.choice(len(w), size=n, p=w).astype(np.int8)
        # inner index: per-corpus running count (vectorized one-hot cumsum)
        self.inner = np.zeros(n, np.int64)
        counts = []
        for k in range(len(w)):
            m = self.choices == k
            self.inner[m] = np.arange(int(m.sum()))
            counts.append(int(m.sum()))
        self.datasets = [
            GPTDataset(p, seq_length, max(c, 1), seed=seed + 17 * k)
            for k, ((_, p), c) in enumerate(zip(prefixes_and_weights, counts))
        ]
        super().__init__(n, global_batch_size,
                         input_names=("input_ids", "labels", "loss_mask"), **kw)

    def fetch_rows(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        rows = [
            self.datasets[int(self.choices[i])][int(self.inner[i])] for i in idx
        ]
        return {
            "input_ids": np.stack([r["input_ids"] for r in rows]),
            "labels": np.stack([r["labels"] for r in rows]),
        }


def load_alignment_records(path: str | Path) -> list[dict[str, Any]]:
    """Load jsonl / json / arrow-dir alignment data
    (reference ``model_alignment_data_module.py:67-92``)."""
    p = Path(path)
    if p.is_dir():
        import datasets

        return [dict(r) for r in datasets.load_from_disk(str(p))]
    if p.suffix == ".jsonl":
        return [json.loads(line) for line in p.read_text().splitlines() if line.strip()]
    if p.suffix == ".json":
        data = json.loads(p.read_text())
        return data if isinstance(data, list) else data["data"]
    raise ValueError(f"unsupported alignment data format: {p}")


class SFTDataModule(DataModule):
    """SFT data: tokenize prompt/completion pairs, mask prompt labels, then
    greedy-pack (``packing: true``) or pad to fixed length
    (reference ``model_alignment_data_module.py:148-160, 186-224``).

    Records need ``input``/``output`` keys (or ``prompt``/``completion``).
    ``tokenizer`` is any callable ``str -> list[int]`` or an HF tokenizer.
    """

    def __init__(
        self,
        records: Sequence[dict[str, Any]] | str | Path,
        tokenizer: Any,
        seq_length: int,
        global_batch_size: int,
        *,
        packing: bool = True,
        segment_mask: bool = False,  # block-diagonal attention within chunks
        bos_id: Optional[int] = None,
        eos_id: Optional[int] = None,
        pad_id: int = 0,
        template: Optional[Any] = None,  # data.templates.Template
        **kw: Any,
    ):
        if isinstance(records, (str, Path)):
            records = load_alignment_records(records)
        encode = tokenizer.encode if hasattr(tokenizer, "encode") else tokenizer
        if eos_id is None:
            eos_id = getattr(tokenizer, "eos_token_id", 0) or 0
        if bos_id is None:
            bos_id = getattr(tokenizer, "bos_token_id", None)

        ids_list, lbl_list = [], []
        for r in records:
            if template is not None:
                # prompt-template pass before tokenization (reference
                # model_alignment_data_module.py:94-121 prompt_datasets)
                r = template(r)
            src = r.get("input", r.get("prompt", ""))
            dst = r.get("output", r.get("completion", ""))
            # bos+src / dst+eos split (reference :148-160)
            prompt_toks = ([bos_id] if bos_id is not None else []) + list(encode(src))
            resp_toks = list(encode(dst))
            ids, lbl = mask_prompt_labels(prompt_toks, resp_toks)
            ids_list.append(ids)
            lbl_list.append(lbl)

        if packing:
            self.arrays = pack_sequences(
                ids_list, seq_length, eos_id, label_lists=lbl_list, pad_id=pad_id
            )
            if segment_mask:
                # block-diagonal attention within packed chunks (beyond the
                # reference: ConcatDataset packs WITHOUT masking, records
                # causally attend across boundaries)
                from neuronx_distributed_training_tpu.data.packing import (
                    packed_segment_ids,
                )

                self.arrays["segment_ids"] = packed_segment_ids(
                    ids_list, seq_length)
                # the replay must track pack_sequences' layout exactly — a
                # future divergence (e.g. a C++-only packing rule change)
                # must fail loudly, not train with a corrupted mask
                if (self.arrays["segment_ids"].shape
                        != self.arrays["input_ids"].shape):
                    raise AssertionError(
                        f"packed_segment_ids layout drifted from "
                        f"pack_sequences: {self.arrays['segment_ids'].shape} "
                        f"vs {self.arrays['input_ids'].shape}"
                    )
        else:
            if segment_mask:
                raise ValueError(
                    "sft segment_mask requires packing: true (unpacked rows "
                    "are single records; the causal mask already isolates them)"
                )
            padded = pad_sequences(
                ids_list, seq_length, pad_id, label_lists=lbl_list
            )
            self.arrays = {k: padded[k] for k in ("input_ids", "labels", "loss_mask")}
        n = len(self.arrays["input_ids"])
        if n < global_batch_size:
            raise ValueError(
                f"SFT dataset too small: {n} packed rows < global_batch_size "
                f"{global_batch_size}"
            )
        # input_names drives process_global_batch's filter: segment_ids must
        # be listed or the loader silently drops it and the mask no-ops
        super().__init__(n, global_batch_size, shuffle=kw.pop("shuffle", True),
                         input_names=tuple(self.arrays), **kw)

    def fetch_rows(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}


def _encode_prompt_completion(encode, eos, prompt, completion, seq_length,
                              max_prompt_length, truncation_mode):
    """(ids, labels) for one prompt+completion pair: prompt-length cap +
    overlong truncation (reference ``model_alignment_data_module.py``
    max_prompt_length / truncation_mode keep_start|keep_end) + prompt-masked
    labels.  Shared by the DPO and KTO modules so the truncation policy
    can't drift between them."""
    p_toks = list(encode(prompt))
    if max_prompt_length and len(p_toks) > int(max_prompt_length):
        m = int(max_prompt_length)
        p_toks = p_toks[:m] if truncation_mode == "keep_start" else p_toks[-m:]
    c_toks = list(encode(completion)) + [eos]
    if len(p_toks) + len(c_toks) > seq_length:
        keep = seq_length - len(c_toks)
        if keep <= 0:
            p_toks, c_toks = [], c_toks[-seq_length:]
        elif truncation_mode == "keep_end":
            p_toks = p_toks[-keep:]
        else:
            p_toks = p_toks[:keep]
    return mask_prompt_labels(p_toks, c_toks)


class DPODataModule(DataModule):
    """DPO/ORPO preference data: chosen/rejected pairs, prompt left-pad
    convention (reference ``PaddedDPODataset``, ``PaddedDataset.py:60-103``).

    Records need ``prompt``, ``chosen``, ``rejected`` keys.  After construction,
    call ``attach_reference_logprobs`` with the pre-fit pass output
    (``alignment.dpo.compute_reference_logprobs``).
    """

    def __init__(
        self,
        records: Sequence[dict[str, Any]] | str | Path,
        tokenizer: Any,
        seq_length: int,
        global_batch_size: int,
        *,
        pad_id: int = 0,
        max_prompt_length: Optional[int] = None,
        truncation_mode: str = "keep_start",
        **kw: Any,
    ):
        if isinstance(records, (str, Path)):
            records = load_alignment_records(records)
        encode = tokenizer.encode if hasattr(tokenizer, "encode") else tokenizer
        eos = getattr(tokenizer, "eos_token_id", 0) or 0

        arrays: dict[str, list] = {}
        for side in ("chosen", "rejected"):
            ids_list, lbl_list = [], []
            for r in records:
                ids, lbl = _encode_prompt_completion(
                    encode, eos, r["prompt"], r[side], seq_length,
                    max_prompt_length, truncation_mode,
                )
                ids_list.append(ids)
                lbl_list.append(lbl)
            padded = pad_sequences(ids_list, seq_length, pad_id, label_lists=lbl_list)
            arrays[f"{side}_input_ids"] = padded["input_ids"]
            arrays[f"{side}_loss_mask"] = padded["loss_mask"]
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        n = len(records)
        super().__init__(
            n, global_batch_size, shuffle=kw.pop("shuffle", True),
            input_names=tuple(self.arrays), **kw,
        )

    def attach_reference_logprobs(self, columns: dict[str, np.ndarray]) -> None:
        """The reference's mid-fit dataset-column append (``base_dpo.py:61-62``)."""
        for k, v in columns.items():
            if len(v) != len(self.arrays["chosen_input_ids"]):
                raise ValueError(f"column {k} length {len(v)} != dataset size")
            self.arrays[k] = np.asarray(v, np.float32)
        self.input_names = tuple(self.arrays)

    def fetch_rows(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}

    def global_batches(self):
        # DPO batches bypass causal-LM label derivation
        for idx in self.sampler:
            yield self.fetch_rows(idx)


def _mismatched_pairing(prompts: Sequence[tuple], rng) -> list[int]:
    """Seeded pairing ``i -> j`` for KTO's mismatched-KL estimator: each
    record borrows the completion of a record with a DIFFERENT prompt.

    Records are grouped by prompt tokens, seeded-shuffled within and among
    groups, laid out group-contiguously (largest group first), and paired by
    a cyclic shift of the largest group's size: a block of size ``m_i <= m1``
    shifted by ``m1`` can only land back on itself via wraparound, which
    needs ``m_i + m1 > n`` — so whenever the largest group fits in half the
    dataset the result is a BIJECTION with zero matched pairs (every
    completion weighs into the z0 baseline exactly once).  If one prompt
    owns more than half the records no such bijection exists (Hall), and the
    pairing falls back to walking a shuffled cyclic order past same-prompt
    records — not injective, but still free of matched pairs — with a
    warning.  All-identical prompts degenerate to the cyclic successor
    (warned: the estimator then approximates batch_mean).
    """
    n = len(prompts)
    groups: dict[tuple, list[int]] = {}
    for i, p in enumerate(prompts):
        groups.setdefault(p, []).append(i)
    if len(groups) == 1:
        warnings.warn(
            "kto kl_estimator='mismatched': every record shares one "
            "prompt, so no truly mismatched pair exists — the KL "
            "baseline degenerates toward batch_mean",
            stacklevel=3,
        )
        order = rng.permutation(n)
        pos = np.empty(n, dtype=np.int64)
        pos[order] = np.arange(n)
        return [int(order[(pos[i] + 1) % n]) for i in range(n)]
    glist = list(groups.values())
    for g in glist:
        rng.shuffle(g)
    rng.shuffle(glist)
    glist.sort(key=len, reverse=True)  # stable: random tiebreak survives
    m1 = len(glist[0])
    flat = [i for g in glist for i in g]
    if 2 * m1 <= n:
        pair = [0] * n
        for p, i in enumerate(flat):
            pair[i] = flat[(p + m1) % n]
        return pair
    warnings.warn(
        f"kto kl_estimator='mismatched': one prompt owns {m1} of {n} "
        f"records, so no one-to-one mismatched pairing exists — falling "
        f"back to a non-injective pairing (some completions weigh more "
        f"than once in the z0 KL baseline)",
        stacklevel=3,
    )
    order = rng.permutation(n)
    pos = np.empty(n, dtype=np.int64)
    pos[order] = np.arange(n)
    pair = []
    for i in range(n):
        j = int(order[(pos[i] + 1) % n])
        while prompts[j] == prompts[i]:
            j = int(order[(pos[j] + 1) % n])
        pair.append(j)
    return pair


class KTODataModule(DataModule):
    """KTO unpaired preference data: single (prompt, completion, label)
    records (arXiv:2402.01306) — an extension beyond the reference's
    DPO/ORPO pair surface, reusing the same tokenize/pad machinery.

    Records need ``prompt``, ``completion`` and a boolean-ish ``label``
    (1/true = desirable).  After construction, call
    ``attach_reference_logprobs`` with the pre-fit pass output
    (``alignment.kto.compute_reference_logprobs_kto``).
    """

    def __init__(
        self,
        records: Sequence[dict[str, Any]] | str | Path,
        tokenizer: Any,
        seq_length: int,
        global_batch_size: int,
        *,
        pad_id: int = 0,
        max_prompt_length: Optional[int] = None,
        truncation_mode: str = "keep_start",
        kl_estimator: str = "batch_mean",  # "batch_mean" | "mismatched"
        **kw: Any,
    ):
        if isinstance(records, (str, Path)):
            records = load_alignment_records(records)
        encode = tokenizer.encode if hasattr(tokenizer, "encode") else tokenizer
        eos = getattr(tokenizer, "eos_token_id", 0) or 0

        ids_list, lbl_list, kto_labels = [], [], []
        for r in records:
            ids, lbl = _encode_prompt_completion(
                encode, eos, r["prompt"], r["completion"], seq_length,
                max_prompt_length, truncation_mode,
            )
            ids_list.append(ids)
            lbl_list.append(lbl)
            if "label" in r:
                label = r["label"]
            elif "desirable" in r:
                label = r["desirable"]
            else:
                # a missing label must be loud: defaulting silently trains
                # every record as desirable and the objective degenerates
                raise KeyError(
                    f"KTO record missing 'label' (or 'desirable') key: "
                    f"{sorted(r)}"
                )
            kto_labels.append(1.0 if label else 0.0)
        padded = pad_sequences(ids_list, seq_length, pad_id, label_lists=lbl_list)
        self.arrays = {
            "input_ids": np.asarray(padded["input_ids"]),
            "loss_mask": np.asarray(padded["loss_mask"]),
            "kto_labels": np.asarray(kto_labels, np.float32),
        }
        if kl_estimator not in ("batch_mean", "mismatched"):
            raise ValueError(
                f"kto kl_estimator must be batch_mean or mismatched, "
                f"got {kl_estimator!r}"
            )
        if kl_estimator == "mismatched":
            # the paper's KL estimate (arXiv:2402.01306 / TRL): rewards of
            # MISMATCHED (prompt_i, completion_j) pairs.  The pairing is a
            # SEEDED prompt-group-aware derangement (_mismatched_pairing),
            # not a fixed (i+1)%n shift: KTO files commonly list several
            # completions per prompt consecutively, and a fixed shift would
            # pair prompt_i with an on-policy completion — a matched pair —
            # biasing the z0 baseline toward the on-policy mean (TRL
            # shuffles its KL pairs for the same reason).  The columns are
            # still precomputed once (reference logps ride the same pre-fit
            # pass as the matched column).
            from neuronx_distributed_training_tpu.data.packing import (
                IGNORE_INDEX,
                mask_prompt_labels,
            )

            n = len(ids_list)
            if n < 2:
                raise ValueError(
                    "kto kl_estimator='mismatched' needs at least 2 records "
                    "(with 1 the 'mismatched' pair IS the matched pair and "
                    "the estimator silently degenerates to batch_mean)"
                )
            cuts = [
                next((k for k, v in enumerate(lbl) if v != IGNORE_INDEX),
                     len(lbl))
                for lbl in lbl_list
            ]
            # group by the RAW encoded prompt, not the truncated row prefix:
            # overlong rows trim the prompt by their own completion's length
            # (_encode_prompt_completion), so two records sharing a prompt
            # can carry different row prefixes — keying on those would pair
            # them together, a matched pair in disguise
            prompts = [tuple(encode(r["prompt"])) for r in records]
            rng = np.random.default_rng(int(kw.get("seed", 1234)))
            pair = _mismatched_pairing(prompts, rng)
            kl_ids, kl_lbl = [], []
            for i in range(n):
                j = pair[i]
                prompt_i = list(ids_list[i][: cuts[i]])
                comp_j = list(ids_list[j][cuts[j]:])
                # same keep-completion truncation rule as the matched rows
                # (_encode_prompt_completion): an overlong splice trims the
                # PROMPT — tail-truncating comp_j would zero the row's KL
                # reward and bias z0 toward 0 on long-sequence datasets
                if len(prompt_i) + len(comp_j) > seq_length:
                    keep = seq_length - len(comp_j)
                    if keep <= 0:
                        prompt_i, comp_j = [], comp_j[-seq_length:]
                    else:
                        prompt_i = prompt_i[:keep]
                ids_kl, lbl_kl = mask_prompt_labels(prompt_i, comp_j)
                kl_ids.append(ids_kl)
                kl_lbl.append(lbl_kl)
            kl_padded = pad_sequences(kl_ids, seq_length, pad_id,
                                      label_lists=kl_lbl)
            self.arrays["kl_input_ids"] = np.asarray(kl_padded["input_ids"])
            self.arrays["kl_loss_mask"] = np.asarray(kl_padded["loss_mask"])
        self.kl_estimator = kl_estimator
        super().__init__(
            len(records), global_batch_size, shuffle=kw.pop("shuffle", True),
            input_names=tuple(self.arrays), **kw,
        )

    def attach_reference_logprobs(self, columns: dict[str, np.ndarray]) -> None:
        for k, v in columns.items():
            if len(v) != len(self.arrays["input_ids"]):
                raise ValueError(f"column {k} length {len(v)} != dataset size")
            self.arrays[k] = np.asarray(v, np.float32)
        self.input_names = tuple(self.arrays)

    def fetch_rows(self, idx: np.ndarray) -> dict[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}

    def global_batches(self):
        # KTO batches bypass causal-LM label derivation
        for idx in self.sampler:
            yield self.fetch_rows(idx)
