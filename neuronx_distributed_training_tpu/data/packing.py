"""Sequence packing and fixed-length padding.

Re-design of the reference's ``datasets/ConcatDataset.py`` (greedy packing to
``chunk_size`` with EOS separators, overflow-record drop, reference
``ConcatDataset.py:7-81``) and ``datasets/PaddedDataset.py`` (fixed-length
padding so every batch has the same shape → one XLA graph; DPO variant pads
chosen/rejected/prompt keys with left-padded prompts, reference
``PaddedDataset.py:9-103``) as numpy batch transforms.
"""

from __future__ import annotations

import ctypes
import logging
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

IGNORE_INDEX = -100  # loss-masked label value, HF convention used by the reference

_SRC = Path(__file__).with_name("packing_native.cpp")
_lib = None
_lib_tried = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the C++ packer; None if no toolchain."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    _lib_tried = True
    from neuronx_distributed_training_tpu.data._native import compile_and_load

    lib = compile_and_load(_SRC)
    if lib is not None:
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.pack_count.restype = ctypes.c_int64
        lib.pack_count.argtypes = [i32p, ctypes.c_int64, ctypes.c_int64]
        lib.pack_fill.restype = ctypes.c_int64
        lib.pack_fill.argtypes = [
            i32p, i32p, i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, i32p, i32p,
        ]
    _lib = lib
    return _lib


def _pack_sequences_native(token_lists, chunk_size, eos_id, label_lists, pad_id):
    lib = _load_native()
    if lib is None:
        return None
    from itertools import chain

    lens = np.asarray([len(t) for t in token_lists], np.int32)
    offsets = np.zeros(len(token_lists) + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    if label_lists is not None:
        # length check BEFORE flattening: fromiter(count=N) silently
        # truncates an over-long iterator, which would shift every
        # subsequent record's labels
        if len(label_lists) != len(token_lists) or any(
            len(l) != len(t) for l, t in zip(label_lists, token_lists)
        ):
            return None  # ragged label mismatch; the python path reports clearly
    flat_ids = np.fromiter(
        chain.from_iterable(token_lists), np.int32, count=total)
    if label_lists is not None:
        flat_lbl = np.fromiter(
            chain.from_iterable(label_lists), np.int32, count=total)
    else:
        flat_lbl = flat_ids
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    n_chunks = lib.pack_count(
        lens.ctypes.data_as(i32p), len(lens), chunk_size)
    ids = np.empty((max(int(n_chunks), 0), chunk_size), np.int32)
    lbl = np.empty_like(ids)
    if n_chunks:
        flat_ids = np.ascontiguousarray(flat_ids)
        flat_lbl = np.ascontiguousarray(flat_lbl)
        written = lib.pack_fill(
            flat_ids.ctypes.data_as(i32p), flat_lbl.ctypes.data_as(i32p),
            offsets.ctypes.data_as(i64p), len(lens), chunk_size,
            eos_id, pad_id, IGNORE_INDEX,
            ids.ctypes.data_as(i32p), lbl.ctypes.data_as(i32p),
        )
        assert written == n_chunks, (written, n_chunks)
    loss_mask = (lbl != IGNORE_INDEX).astype(np.float32)
    return {"input_ids": ids, "labels": lbl, "loss_mask": loss_mask}


def pack_sequences(
    token_lists: Sequence[Sequence[int]],
    chunk_size: int,
    eos_id: int,
    *,
    label_lists: Optional[Sequence[Sequence[int]]] = None,
    pad_id: int = 0,
) -> dict[str, np.ndarray]:
    """Greedy-pack variable-length sequences into fixed ``chunk_size`` rows.

    Mirrors the reference ConcatDataset semantics: append ``eos_id`` after each
    record, start a new chunk when the next record doesn't fit, and **drop**
    records longer than ``chunk_size`` (reference ``ConcatDataset.py:30-58``).
    Returns ``input_ids`` ``labels`` ``loss_mask`` arrays ``[n_chunks, chunk_size]``.
    ``labels`` carry ``IGNORE_INDEX`` over padding; per-record labels may be
    supplied (SFT prompt masking), defaulting to the input tokens.

    The hot loop runs in C++ when the toolchain is available (the same
    compile-on-demand ctypes pattern as ``data/megatron/index.py``; the
    reference keeps its dataset loops native too) with a bit-identical numpy
    fallback.
    """
    native = _pack_sequences_native(
        token_lists, chunk_size, eos_id, label_lists, pad_id)
    if native is not None:
        return native
    chunks_ids: list[np.ndarray] = []
    chunks_lbl: list[np.ndarray] = []

    cur_ids: list[int] = []
    cur_lbl: list[int] = []

    def flush() -> None:
        if not cur_ids:
            return
        n = len(cur_ids)
        ids = np.full(chunk_size, pad_id, dtype=np.int32)
        lbl = np.full(chunk_size, IGNORE_INDEX, dtype=np.int32)
        ids[:n] = cur_ids
        lbl[:n] = cur_lbl
        chunks_ids.append(ids)
        chunks_lbl.append(lbl)
        cur_ids.clear()
        cur_lbl.clear()

    for i, toks in enumerate(token_lists):
        toks = list(toks) + [eos_id]
        lbls = (list(label_lists[i]) + [eos_id]) if label_lists is not None else list(toks)
        if len(toks) > chunk_size:
            continue  # overflow record dropped (reference ConcatDataset.py:44-47)
        if len(cur_ids) + len(toks) > chunk_size:
            flush()
        cur_ids.extend(toks)
        cur_lbl.extend(lbls)
    flush()

    if not chunks_ids:
        return {
            "input_ids": np.zeros((0, chunk_size), np.int32),
            "labels": np.zeros((0, chunk_size), np.int32),
            "loss_mask": np.zeros((0, chunk_size), np.float32),
        }
    input_ids = np.stack(chunks_ids)
    labels = np.stack(chunks_lbl)
    loss_mask = (labels != IGNORE_INDEX).astype(np.float32)
    return {"input_ids": input_ids, "labels": labels, "loss_mask": loss_mask}


def pad_sequences(
    token_lists: Sequence[Sequence[int]],
    max_length: int,
    pad_id: int,
    *,
    label_lists: Optional[Sequence[Sequence[int]]] = None,
    left_pad: bool = False,
    truncate: bool = True,
) -> dict[str, np.ndarray]:
    """Pad (or truncate) every sequence to exactly ``max_length``.

    The reference's PaddedDataset rule: all batches the same length so XLA
    compiles one graph (``PaddedDataset.py:9-35``).  ``left_pad`` matches the
    DPO prompt convention (``PaddedDataset.py:60-80``).
    """
    n = len(token_lists)
    input_ids = np.full((n, max_length), pad_id, dtype=np.int32)
    labels = np.full((n, max_length), IGNORE_INDEX, dtype=np.int32)
    attn = np.zeros((n, max_length), dtype=np.float32)
    for i, toks in enumerate(token_lists):
        toks = list(toks)
        lbls = list(label_lists[i]) if label_lists is not None else list(toks)
        if truncate:
            toks, lbls = toks[:max_length], lbls[:max_length]
        elif len(toks) > max_length:
            raise ValueError(f"sequence {i} length {len(toks)} > max_length {max_length}")
        m = len(toks)
        if left_pad:
            input_ids[i, max_length - m :] = toks
            labels[i, max_length - m :] = lbls
            attn[i, max_length - m :] = 1.0
        else:
            input_ids[i, :m] = toks
            labels[i, :m] = lbls
            attn[i, :m] = 1.0
    loss_mask = (labels != IGNORE_INDEX).astype(np.float32)
    return {
        "input_ids": input_ids,
        "labels": labels,
        "loss_mask": loss_mask,
        "attention_mask": attn,
    }


def mask_prompt_labels(
    prompt_tokens: Sequence[int], response_tokens: Sequence[int]
) -> tuple[list[int], list[int]]:
    """SFT tokenization rule: input = prompt+response, labels = IGNORE over the
    prompt (reference ``model_alignment_data_module.py:148-160``)."""
    ids = list(prompt_tokens) + list(response_tokens)
    lbl = [IGNORE_INDEX] * len(prompt_tokens) + list(response_tokens)
    return ids, lbl


def packed_segment_ids(
    token_lists: Sequence[Sequence[int]], chunk_size: int
) -> np.ndarray:
    """Per-position record ids for ``pack_sequences``' chunks: [n, chunk]
    int32, records numbered 1.. within each chunk, padding 0.

    Replays the packer's deterministic greedy layout from the record lengths
    (so the C++ and numpy packer paths both stay untouched).  Feed to
    ``attention(segment_ids=...)`` for block-diagonal packed-sequence masking
    — the correctness upgrade over the reference's ConcatDataset, whose
    packed records causally attend across record boundaries.
    """
    rows: list[np.ndarray] = []
    cur: list[int] = []
    sid = 1

    def flush() -> None:
        nonlocal sid
        if not cur:
            return
        row = np.zeros(chunk_size, np.int32)
        row[: len(cur)] = cur
        rows.append(row)
        cur.clear()
        sid = 1

    for toks in token_lists:
        ln = len(toks) + 1  # + eos, matching pack_sequences
        if ln > chunk_size:
            continue  # dropped record
        if len(cur) + ln > chunk_size:
            flush()
        cur.extend([sid] * ln)
        sid += 1
    flush()
    if not rows:
        return np.zeros((0, chunk_size), np.int32)
    return np.stack(rows)
