// Greedy sequence packer — native counterpart of data/packing.py
// pack_sequences (reference ConcatDataset.py:30-58 semantics: +eos per
// record, new chunk when the next record doesn't fit, drop overflow
// records).  The reference keeps its dataset hot loops in C++
// (megatron helpers); SFT packing over millions of records is the same
// class of loop, so it lives here too.  Two-pass API so the caller
// allocates exactly n_chunks rows:
//
//   pack_count(lens, n, chunk)            -> number of chunks
//   pack_fill(tokens, labels, offsets, n, chunk, eos, pad, ignore,
//             out_ids, out_lbl)           -> chunks written
//
// lens[i]/offsets[] describe records WITHOUT the eos (added here).

#include <cstdint>

extern "C" {

int64_t pack_count(const int32_t* lens, int64_t n, int64_t chunk_size) {
    int64_t chunks = 0;
    int64_t cur = 0;
    for (int64_t i = 0; i < n; ++i) {
        int64_t need = static_cast<int64_t>(lens[i]) + 1;  // +eos
        if (need > chunk_size) continue;  // overflow record dropped
        if (cur + need > chunk_size) {
            if (cur > 0) ++chunks;
            cur = 0;
        }
        cur += need;
    }
    if (cur > 0) ++chunks;
    return chunks;
}

int64_t pack_fill(const int32_t* tokens, const int32_t* labels,
                  const int64_t* offsets, int64_t n, int64_t chunk_size,
                  int32_t eos_id, int32_t pad_id, int32_t ignore_index,
                  int32_t* out_ids, int32_t* out_lbl) {
    int64_t chunk = 0;
    int64_t cur = 0;  // fill position within the current chunk

    auto pad_tail = [&]() {
        if (cur == 0) return;
        int32_t* ids = out_ids + chunk * chunk_size;
        int32_t* lbl = out_lbl + chunk * chunk_size;
        for (int64_t j = cur; j < chunk_size; ++j) {
            ids[j] = pad_id;
            lbl[j] = ignore_index;
        }
        ++chunk;
        cur = 0;
    };

    for (int64_t i = 0; i < n; ++i) {
        int64_t start = offsets[i];
        int64_t len = offsets[i + 1] - start;
        int64_t need = len + 1;
        if (need > chunk_size) continue;
        if (cur + need > chunk_size) pad_tail();
        int32_t* ids = out_ids + chunk * chunk_size + cur;
        int32_t* lbl = out_lbl + chunk * chunk_size + cur;
        for (int64_t j = 0; j < len; ++j) {
            ids[j] = tokens[start + j];
            lbl[j] = labels[start + j];
        }
        ids[len] = eos_id;
        lbl[len] = eos_id;
        cur += need;
    }
    pad_tail();
    return chunk;
}

}  // extern "C"
