"""Deterministic samplers with consumed-samples resume.

The reference uses NeMo's ``MegatronPretrainingBatchSampler`` /
``MegatronPretrainingRandomBatchSampler`` keyed by DP rank/size and
``consumed_samples`` (reference ``megatron/data_module.py:132-173``), plus torch
``DistributedSampler`` for the HF path (``hf_data_module.py:15-44``).  Resume
exactness comes from ``compute_consumed_samples`` and the
filename-encoded consumed-samples restore (``data/base.py:33-47``).

Here a sampler is a deterministic pure function ``(epoch, index) -> dataset row``;
"consumed samples" is the single integer of state.  Every DP rank computes the
same global order and slices its own rows, so there is no cross-host coordination.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Iterator, Optional

import numpy as np

# The reference encodes progress in checkpoint names, e.g.
# ``…-step=1000-consumed_samples=128000.0.ckpt`` (data/base.py:40-47).
_CONSUMED_RE = re.compile(r"consumed_samples[=_](\d+(?:\.\d+)?)")


def consumed_samples_from_name(name: str) -> Optional[int]:
    """Extract consumed-samples from a checkpoint tag/filename
    (reference ``data/base.py:40-47``)."""
    m = _CONSUMED_RE.search(name)
    return int(float(m.group(1))) if m else None


@dataclasses.dataclass
class PretrainingSampler:
    """Sequential sampler over an (optionally shuffled-once) dataset.

    Yields **global-batch index arrays** of shape ``[global_batch_size]``; the
    caller slices the DP-rank-local rows.  Equivalent to NeMo's
    ``MegatronPretrainingBatchSampler`` (reference ``megatron/data_module.py:141-155``):
    wraps around the dataset epoch-by-epoch, restartable from ``consumed_samples``.
    """

    total_samples: int
    global_batch_size: int
    consumed_samples: int = 0

    def __iter__(self) -> Iterator[np.ndarray]:
        idx = self.consumed_samples
        while True:
            batch = np.arange(idx, idx + self.global_batch_size) % self.total_samples
            idx += self.global_batch_size
            self.consumed_samples = idx
            yield batch

    def state(self) -> int:
        return self.consumed_samples


@dataclasses.dataclass
class RandomSampler:
    """Per-epoch-shuffled sampler, deterministic in ``(seed, epoch)``.

    Equivalent to NeMo's ``MegatronPretrainingRandomBatchSampler`` /
    torch ``DistributedSampler(shuffle=True)`` (reference
    ``model_alignment_data_module.py:186-224``): every rank derives the same
    permutation from the seed, so resume only needs ``consumed_samples``.
    """

    total_samples: int
    global_batch_size: int
    seed: int = 1234
    consumed_samples: int = 0

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        rng = np.random.Generator(np.random.PCG64(self.seed + epoch))
        return rng.permutation(self.total_samples)

    def __iter__(self) -> Iterator[np.ndarray]:
        # batches never straddle epochs: partial trailing batches are dropped,
        # matching drop_last semantics of the reference samplers
        batches_per_epoch = self.total_samples // self.global_batch_size
        if batches_per_epoch == 0:
            raise ValueError(
                f"dataset of {self.total_samples} rows smaller than "
                f"global_batch_size {self.global_batch_size}"
            )
        samples_per_epoch = batches_per_epoch * self.global_batch_size
        while True:
            epoch = self.consumed_samples // samples_per_epoch
            offset = self.consumed_samples % samples_per_epoch
            # resuming with a changed global_batch_size can leave the offset
            # mid-batch; align down (re-reads a few samples) rather than yield
            # a short batch that would break the fixed-shape contract
            offset -= offset % self.global_batch_size
            perm = self._epoch_perm(epoch)
            for start in range(offset, samples_per_epoch, self.global_batch_size):
                # state updated BEFORE yield so consumed_samples is correct at
                # checkpoint time even mid-iteration
                self.consumed_samples += self.global_batch_size
                yield perm[start : start + self.global_batch_size]

    def state(self) -> int:
        return self.consumed_samples


def dp_shard(batch_idx: np.ndarray, dp_rank: int, dp_size: int) -> np.ndarray:
    """Slice one DP rank's rows out of a global-batch index array (the
    ``DistributedSampler(num_replicas=dp, rank=r)`` role, reference
    ``hf_data_module.py:16-22``)."""
    if batch_idx.shape[0] % dp_size != 0:
        raise ValueError(
            f"global batch {batch_idx.shape[0]} not divisible by dp_size {dp_size}"
        )
    per = batch_idx.shape[0] // dp_size
    return batch_idx[dp_rank * per : (dp_rank + 1) * per]
