"""Prompt templates for alignment data.

The reference's ``prompt_datasets`` step (``model_alignment_data_module.py:
94-121``) maps raw dataset records through a template before tokenization:
promptsource templates when ``data.dataset_name``/``prompt_name`` are set, "any
f-string format" otherwise.  TPU-native equivalents, in dispatch order:

1. ``data.prompt_template: {input: "...{field}...", output: "...{field}..."}``
   — format-string templates over record fields (the f-string path, no
   external dependency);
2. ``data.chat_template: true`` — HF tokenizer ``apply_chat_template`` over
   ``messages``-style records;
3. ``data.dataset_name`` + ``prompt_name`` — promptsource, if installed
   (the reference gates the same import).

``build_template`` returns ``record -> record`` (with ``input``/``output``
keys populated) or ``None`` when no template is configured.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

Template = Callable[[dict], dict]


class FormatTemplate:
    """``{field}``-style format templates for input/output columns."""

    def __init__(self, input_template: str, output_template: str = "{output}"):
        self.input_template = input_template
        self.output_template = output_template

    def __call__(self, record: dict) -> dict:
        out = dict(record)
        out["input"] = self.input_template.format(**record)
        out["output"] = self.output_template.format(**record)
        return out


class ChatTemplate:
    """HF-tokenizer chat template over ``messages`` records.

    The last assistant turn becomes ``output`` (the trained completion);
    everything before it renders — with generation prompt — into ``input``.
    """

    def __init__(self, tokenizer: Any):
        if not hasattr(tokenizer, "apply_chat_template"):
            raise ValueError(
                "data.chat_template needs an HF tokenizer with a chat template"
            )
        self.tokenizer = tokenizer

    def __call__(self, record: dict) -> dict:
        msgs = record["messages"]
        if not msgs or msgs[-1].get("role") != "assistant":
            raise ValueError("chat records must end with an assistant turn")
        out = dict(record)
        out["input"] = self.tokenizer.apply_chat_template(
            msgs[:-1], tokenize=False, add_generation_prompt=True
        )
        out["output"] = msgs[-1]["content"]
        return out


class PromptsourceTemplate:
    """promptsource bridge (reference ``model_alignment_data_module.py:111-117``)."""

    def __init__(self, dataset_name: str, prompt_name: str,
                 subset_name: Optional[str] = None):
        try:
            from promptsource.templates import DatasetTemplates
        except ImportError as e:  # same soft gate as the reference
            raise ImportError(
                "data.dataset_name/prompt_name need the optional promptsource "
                "package; use data.prompt_template format strings instead"
            ) from e
        self.template = DatasetTemplates(dataset_name, subset_name)[prompt_name]

    def __call__(self, record: dict) -> dict:
        out = dict(record)
        rendered = self.template.apply(record)
        # promptsource returns [input] or [input, target]
        out["input"] = rendered[0]
        if len(rendered) > 1:
            out["output"] = rendered[1]
        return out


def build_template(data_cfg: dict, tokenizer: Any = None) -> Optional[Template]:
    """Template from the ``cfg.data`` block; None when none is configured."""
    d = dict(data_cfg or {})
    pt = d.get("prompt_template")
    if pt:
        if isinstance(pt, str):
            return FormatTemplate(pt)
        return FormatTemplate(
            str(pt.get("input", "{input}")), str(pt.get("output", "{output}"))
        )
    if d.get("chat_template"):
        return ChatTemplate(tokenizer)
    if d.get("dataset_name") and d.get("prompt_name"):
        return PromptsourceTemplate(
            str(d["dataset_name"]), str(d["prompt_name"]), d.get("subset_name")
        )
    return None
