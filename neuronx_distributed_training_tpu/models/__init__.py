"""Model definitions (sharded, functional, scan-over-layers)."""
