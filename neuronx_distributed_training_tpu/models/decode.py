"""KV-cache autoregressive decoding (Llama / Mixtral / Megatron-GPT).

The reference's SFT-evaluation inference path is a traced decoder with KV
caching (``sft_evaluation/models/nxd_llama.py`` LlamaRunner); the plain
``models.generate`` here re-runs the full prefix per token — fine for tiny
evals, O(n^2 · L) wrong for real generation.  This module is the cached
path:

- ``prefill``: one causal forward over the right-padded prompts that also
  captures each layer's rotated K and V into the cache;
- ``decode_step``: a single-token forward attending over ``cache[: pos+1]``
  per row (static ``max_len`` buffer + position mask — XLA-friendly, no
  dynamic shapes);
- ``generate_cached``: drop-in for ``generate`` (same right-padded /
  front-writing convention, so generated tokens land exactly on the cache
  slots the row's prompt padding occupied, and the position mask keeps stale
  pad entries invisible).

Parity with the uncached path is test-enforced (greedy outputs must match
``models.generate`` exactly).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.ops import linear as linear_ops
from neuronx_distributed_training_tpu.ops import norm as norm_ops
from neuronx_distributed_training_tpu.ops import rope as rope_ops
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy


def _qkv(lp, x, cfg: llama.LlamaConfig):
    b, s, _ = x.shape
    nh, nkv, d = cfg.num_attention_heads, cfg.kv_heads, cfg.head_size
    if cfg.fuse_qkv:
        qkv = linear_ops.apply_linear(lp["qkv"], x)
        q, k, v = jnp.split(qkv, [nh * d, (nh + nkv) * d], axis=-1)
    else:
        q = linear_ops.apply_linear(lp["q"], x)
        k = linear_ops.apply_linear(lp["k"], x)
        v = linear_ops.apply_linear(lp["v"], x)
    return (q.reshape(b, s, nh, d), k.reshape(b, s, nkv, d),
            v.reshape(b, s, nkv, d))


def prefill(params, input_ids: jax.Array, cfg: llama.LlamaConfig,
            policy: DtypePolicy, *, max_len: Optional[int] = None):
    """Causal forward capturing the KV cache.

    Returns ``(hidden [b, s, h], cache {"k","v"}: [L, b, max_len, kvh, d])``
    with rotated keys; cache tail beyond ``s`` is zeros (masked out by
    position during decode).  Callers take logits where they need them
    (``llama.logits_fn``) — generation only reads ONE position per row, and a
    full [b, s, vocab] logits tensor is the dominant prefill allocation.

    The layer math is ``llama._decoder_layer(return_kv=True)`` — shared code,
    shared sharding constraints, so TP/SP prefill shards like training.
    """
    s = input_ids.shape[1]
    max_len = max_len or s
    aspec = shd.act_spec(cfg.sequence_parallel, cfg.context_parallel)
    x = linear_ops.apply_embedding(
        params["embed"], input_ids, compute_dtype=policy.compute_dtype
    )
    x = shd.constrain(x, aspec)
    cos, sin = llama._rope_for(input_ids, cfg)
    layer_stack = policy.cast_to_compute(params["layers"])

    def body(x, lp):
        x, (k, v) = llama._decoder_layer(lp, x, cos, sin, cfg, policy,
                                         return_kv=True)
        # pad the cached block out to max_len (static)
        pad = [(0, 0), (0, max_len - s), (0, 0), (0, 0)]
        return x, (jnp.pad(k, pad), jnp.pad(v, pad))

    x, (ck, cv) = jax.lax.scan(body, x, layer_stack)
    h = norm_ops.apply_rms_norm(params["final_norm"], x, eps=cfg.rms_norm_eps)
    return h, {"k": ck, "v": cv}


def _cached_attn(q, k_new, v_new, ck, cv, pos, *, sliding_window,
                 softmax_dtype):
    """Write this step's KV at ``pos`` per row, attend q over ``<= pos``.

    q/k_new/v_new [b, 1, heads, d]; ck/cv [b, max_len, kvh, d].
    Returns (out [b, 1, nh*d], ck, cv).
    """
    b, _, nh, d = q.shape
    nkv = ck.shape[2]
    max_len = ck.shape[1]
    rows = jnp.arange(b)
    ck = ck.at[rows, pos].set(k_new[:, 0].astype(ck.dtype))
    cv = cv.at[rows, pos].set(v_new[:, 0].astype(cv.dtype))
    kk = jnp.repeat(ck, nh // nkv, axis=2) if nkv != nh else ck
    vv = jnp.repeat(cv, nh // nkv, axis=2) if nkv != nh else cv
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, kk, preferred_element_type=softmax_dtype
    ) * (1.0 / (d ** 0.5))
    valid = jnp.arange(max_len)[None, :] <= pos[:, None]
    if sliding_window is not None:
        valid = valid & (jnp.arange(max_len)[None, :]
                         > pos[:, None] - sliding_window)
    neg = jnp.asarray(jnp.finfo(softmax_dtype).min / 2, softmax_dtype)
    scores = jnp.where(valid[:, None, None, :], scores, neg)
    probs = jax.nn.softmax(scores.astype(softmax_dtype), axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vv.dtype), vv)
    return out.reshape(b, 1, nh * d).astype(q.dtype), ck, cv


def decode_step(params, cache: dict, tokens: jax.Array, pos: jax.Array,
                cfg: llama.LlamaConfig, policy: DtypePolicy):
    """One token per row: write KV at ``pos[b]``, attend over ``<= pos[b]``.

    ``tokens [b]`` int32, ``pos [b]`` the buffer position being filled.
    Returns ``(logits [b, vocab], new_cache)``.
    """
    x = linear_ops.apply_embedding(
        params["embed"], tokens[:, None], compute_dtype=policy.compute_dtype
    )
    inv_freq = rope_ops.rope_frequencies(
        cfg.head_size, theta=cfg.rope_theta,
        position_interpolation_factor=cfg.rope_interpolation_factor,
    )
    cos, sin = rope_ops.rope_cos_sin(pos[:, None], inv_freq, dtype=jnp.float32)
    layer_stack = policy.cast_to_compute(params["layers"])

    def body(x, inp):
        lp, ck, cv = inp  # ck/cv [b, max_len, nkv, d]
        residual = x
        hidden = norm_ops.apply_rms_norm(lp["input_norm"], x, eps=cfg.rms_norm_eps)
        q, k, v = _qkv(lp["attn"], hidden, cfg)  # [b, 1, ., d]
        q = rope_ops.apply_rope(q, cos, sin)
        k = rope_ops.apply_rope(k, cos, sin)
        out, ck, cv = _cached_attn(
            q, k, v, ck, cv, pos, sliding_window=cfg.sliding_window,
            softmax_dtype=policy.softmax_dtype,
        )
        x = residual + linear_ops.apply_linear(lp["attn"]["o"], out.astype(x.dtype))
        residual = x
        hidden = norm_ops.apply_rms_norm(lp["post_attn_norm"], x, eps=cfg.rms_norm_eps)
        x = residual + llama._mlp_block(lp["mlp"], hidden)
        return x, (ck, cv)

    x, (ck, cv) = jax.lax.scan(body, x, (layer_stack, cache["k"], cache["v"]))
    h = norm_ops.apply_rms_norm(params["final_norm"], x, eps=cfg.rms_norm_eps)
    logits = llama.logits_fn(params, h, cfg, policy)
    return logits[:, 0], {"k": ck, "v": cv}


# ---------------------------------------------------------------------------
# Mixtral / Megatron-GPT families
# ---------------------------------------------------------------------------


def prefill_mixtral(params, input_ids, cfg, policy, *, max_len=None):
    """Mixtral prefill: llama structure with the MoE MLP slot.

    ``moe_frequency > 1``: the grouped [G]-scan runs (1 MoE + f-1 dense
    llama) layers per step and re-flattens the captured KV to the flat
    ``[L, ...]`` cache layout, so ``decode_step_mixtral`` sees one uniform
    cache regardless of interleave.
    """
    from neuronx_distributed_training_tpu.models import mixtral

    if not cfg.moe.dropless:
        # capacity-factor routing computes capacity over the CURRENT batch:
        # a b-token decode step would contend for a tiny capacity and zero
        # over-capacity tokens, silently diverging from generate()
        raise NotImplementedError(
            "cached decode with dropped (capacity-factor) MoE; use dropless"
        )
    lc = cfg.llama
    s = input_ids.shape[1]
    max_len = max_len or s
    aspec = shd.act_spec(lc.sequence_parallel, lc.context_parallel)
    x = linear_ops.apply_embedding(
        params["embed"], input_ids, compute_dtype=policy.compute_dtype
    )
    x = shd.constrain(x, aspec)
    cos, sin = llama._rope_for(input_ids, lc)
    layer_stack = policy.cast_to_compute(params["layers"])
    pad = [(0, 0), (0, max_len - s), (0, 0), (0, 0)]

    if cfg.moe_frequency > 1:

        def gbody(x, gp):
            x, _aux, (k0, v0) = mixtral._decoder_layer(
                gp["moe"], x, cos, sin, cfg, policy, return_kv=True
            )

            def dense_body(x2, dlp):
                x2, (k, v) = llama._decoder_layer(
                    dlp, x2, cos, sin, lc, policy, return_kv=True
                )
                return x2, (k, v)

            x, (kd, vd) = jax.lax.scan(dense_body, x, gp["dense"])
            k = jnp.concatenate([k0[None], kd], axis=0)  # [f, b, s, kvh, d]
            v = jnp.concatenate([v0[None], vd], axis=0)
            return x, (jnp.pad(k, [(0, 0)] + pad), jnp.pad(v, [(0, 0)] + pad))

        x, (ck, cv) = jax.lax.scan(gbody, x, mixtral._group_xs(cfg, layer_stack))
        # [G, f, ...] -> flat [L, ...] (groups are contiguous layer runs)
        ck = ck.reshape((-1,) + ck.shape[2:])
        cv = cv.reshape((-1,) + cv.shape[2:])
    else:

        def body(x, lp):
            x, _aux, (k, v) = mixtral._decoder_layer(
                lp, x, cos, sin, cfg, policy, return_kv=True
            )
            return x, (jnp.pad(k, pad), jnp.pad(v, pad))

        x, (ck, cv) = jax.lax.scan(body, x, layer_stack)
    h = norm_ops.apply_rms_norm(params["final_norm"], x, eps=lc.rms_norm_eps)
    return h, {"k": ck, "v": cv}


def _llama_attn_step(lp, x, ck, cv, pos, lc, policy, cos, sin):
    """Shared cached-attention sublayer for llama-structured decode bodies."""
    residual = x
    hidden = norm_ops.apply_rms_norm(lp["input_norm"], x, eps=lc.rms_norm_eps)
    q, k, v = _qkv(lp["attn"], hidden, lc)
    q = rope_ops.apply_rope(q, cos, sin)
    k = rope_ops.apply_rope(k, cos, sin)
    out, ck, cv = _cached_attn(
        q, k, v, ck, cv, pos, sliding_window=lc.sliding_window,
        softmax_dtype=policy.softmax_dtype,
    )
    x = residual + linear_ops.apply_linear(lp["attn"]["o"], out.astype(x.dtype))
    return x, ck, cv


def decode_step_mixtral(params, cache, tokens, pos, cfg, policy):
    from neuronx_distributed_training_tpu.models import mixtral
    from neuronx_distributed_training_tpu.ops import moe as moe_ops

    lc = cfg.llama
    x = linear_ops.apply_embedding(
        params["embed"], tokens[:, None], compute_dtype=policy.compute_dtype
    )
    inv_freq = rope_ops.rope_frequencies(
        lc.head_size, theta=lc.rope_theta,
        position_interpolation_factor=lc.rope_interpolation_factor,
    )
    cos, sin = rope_ops.rope_cos_sin(pos[:, None], inv_freq, dtype=jnp.float32)
    layer_stack = policy.cast_to_compute(params["layers"])

    def moe_mlp(lp, x):
        residual = x
        hidden = norm_ops.apply_rms_norm(lp["post_attn_norm"], x, eps=lc.rms_norm_eps)
        hidden, _aux = moe_ops.moe_block(
            lp["mlp"], hidden, cfg.moe, compute_dtype=policy.compute_dtype
        )
        return residual + hidden

    def dense_mlp(lp, x):
        residual = x
        hidden = norm_ops.apply_rms_norm(lp["post_attn_norm"], x, eps=lc.rms_norm_eps)
        return residual + llama._mlp_block(lp["mlp"], hidden)

    if cfg.moe_frequency > 1:
        f = cfg.moe_frequency
        gk = cache["k"].reshape((-1, f) + cache["k"].shape[1:])
        gv = cache["v"].reshape((-1, f) + cache["v"].shape[1:])

        def gbody(x, inp):
            gp, ck, cv = inp  # ck/cv [f, b, max_len, kvh, d]
            x, ck0, cv0 = _llama_attn_step(
                gp["moe"], x, ck[0], cv[0], pos, lc, policy, cos, sin)
            x = moe_mlp(gp["moe"], x)

            def dense_body(x2, dinp):
                dlp, dk, dv = dinp
                x2, dk, dv = _llama_attn_step(
                    dlp, x2, dk, dv, pos, lc, policy, cos, sin)
                return dense_mlp(dlp, x2), (dk, dv)

            x, (ckd, cvd) = jax.lax.scan(dense_body, x, (gp["dense"], ck[1:], cv[1:]))
            return x, (jnp.concatenate([ck0[None], ckd], axis=0),
                       jnp.concatenate([cv0[None], cvd], axis=0))

        x, (ck, cv) = jax.lax.scan(
            gbody, x, (mixtral._group_xs(cfg, layer_stack), gk, gv))
        ck = ck.reshape((-1,) + ck.shape[2:])
        cv = cv.reshape((-1,) + cv.shape[2:])
    else:

        def body(x, inp):
            lp, ck, cv = inp
            x, ck, cv = _llama_attn_step(lp, x, ck, cv, pos, lc, policy, cos, sin)
            return moe_mlp(lp, x), (ck, cv)

        x, (ck, cv) = jax.lax.scan(body, x, (layer_stack, cache["k"], cache["v"]))
    h = norm_ops.apply_rms_norm(params["final_norm"], x, eps=lc.rms_norm_eps)
    logits = llama.logits_fn(params, h, lc, policy)
    return logits[:, 0], {"k": ck, "v": cv}


def prefill_gpt(params, input_ids, cfg, policy, *, max_len=None):
    """Megatron-GPT prefill (learned-abs or rope, ln/rms, bias, tied head)."""
    from neuronx_distributed_training_tpu.models import gpt

    if cfg.moe is not None and not cfg.moe.dropless:
        raise NotImplementedError(
            "cached decode with dropped (capacity-factor) MoE; use dropless"
        )
    s = input_ids.shape[1]
    max_len = max_len or s
    positions = llama.positions_for(input_ids)
    x = linear_ops.apply_embedding(
        params["embed"], input_ids, compute_dtype=policy.compute_dtype
    )
    if cfg.position_embedding_type == "learned_absolute":
        x = x + jnp.take(
            params["pos_embed"]["embedding"], positions, axis=0
        ).astype(x.dtype)
    cos, sin = gpt._rope_for(cfg, input_ids, positions=positions)
    layer_stack = policy.cast_to_compute(params["layers"])
    pad = [(0, 0), (0, max_len - s), (0, 0), (0, 0)]

    if cfg.moe is not None and cfg.moe_frequency > 1:
        # grouped [G]-scan; KV re-flattened to [L, ...] (see prefill_mixtral)
        def gbody(x, gp):
            x, _aux, (k0, v0) = gpt._decoder_layer(
                cfg, gp["moe"], x, cos, sin, policy, None, return_kv=True
            )

            def dense_body(x2, dlp):
                x2, _a, (k, v) = gpt._decoder_layer(
                    cfg, dlp, x2, cos, sin, policy, None, return_kv=True
                )
                return x2, (k, v)

            x, (kd, vd) = jax.lax.scan(dense_body, x, gp["dense"])
            k = jnp.concatenate([k0[None], kd], axis=0)
            v = jnp.concatenate([v0[None], vd], axis=0)
            return x, (jnp.pad(k, [(0, 0)] + pad), jnp.pad(v, [(0, 0)] + pad))

        x, (ck, cv) = jax.lax.scan(gbody, x, gpt._group_xs(cfg, layer_stack))
        ck = ck.reshape((-1,) + ck.shape[2:])
        cv = cv.reshape((-1,) + cv.shape[2:])
    else:

        def body(x, lp):
            x, _aux, (k, v) = gpt._decoder_layer(
                cfg, lp, x, cos, sin, policy, None, return_kv=True
            )
            return x, (jnp.pad(k, pad), jnp.pad(v, pad))

        x, (ck, cv) = jax.lax.scan(body, x, layer_stack)
    h = (x if cfg.transformer_block_type == "post_ln"
         else gpt._apply_norm(cfg, params["final_norm"], x))
    return h, {"k": ck, "v": cv}


def decode_step_gpt(params, cache, tokens, pos, cfg, policy):
    from neuronx_distributed_training_tpu.models import gpt

    b = tokens.shape[0]
    nh, nkv, d = cfg.num_attention_heads, cfg.kv_heads, cfg.head_size
    x = linear_ops.apply_embedding(
        params["embed"], tokens[:, None], compute_dtype=policy.compute_dtype
    )
    if cfg.position_embedding_type == "learned_absolute":
        x = x + jnp.take(
            params["pos_embed"]["embedding"], pos[:, None], axis=0
        ).astype(x.dtype)
        cos = sin = None
    else:
        rot_dim = int(cfg.head_size * cfg.rotary_percentage) // 2 * 2
        inv_freq = rope_ops.rope_frequencies(rot_dim, theta=cfg.rope_theta)
        cos, sin = rope_ops.rope_cos_sin(pos[:, None], inv_freq, dtype=jnp.float32)
    layer_stack = policy.cast_to_compute(params["layers"])

    def attn_part(lp, hidden, ck, cv):
        """Cached attention on a pre-normed (or raw, post_ln) input ->
        (o_proj output, updated cache)."""
        qkv = linear_ops.apply_linear(lp["attn"]["qkv"], hidden)
        q, k, v = jnp.split(qkv, [nh * d, (nh + nkv) * d], axis=-1)
        q = q.reshape(b, 1, nh, d)
        k = k.reshape(b, 1, nkv, d)
        v = v.reshape(b, 1, nkv, d)
        if cos is not None:
            if cfg.rotary_percentage < 1.0:
                rot = int(d * cfg.rotary_percentage) // 2 * 2
                q = jnp.concatenate(
                    [rope_ops.apply_rope(q[..., :rot], cos, sin), q[..., rot:]], -1)
                k = jnp.concatenate(
                    [rope_ops.apply_rope(k[..., :rot], cos, sin), k[..., rot:]], -1)
            else:
                q = rope_ops.apply_rope(q, cos, sin)
                k = rope_ops.apply_rope(k, cos, sin)
        out, ck, cv = _cached_attn(
            q, k, v, ck, cv, pos, sliding_window=cfg.sliding_window,
            softmax_dtype=policy.softmax_dtype,
        )
        return linear_ops.apply_linear(lp["attn"]["o"], out.astype(hidden.dtype)), ck, cv

    def layer_step(lp, x, ck, cv):
        # same four layouts as gpt._decoder_layer, with cached attention
        bt = cfg.transformer_block_type
        if bt == "gpt_j":
            a, ck, cv = attn_part(lp, gpt._apply_norm(cfg, lp["input_norm"], x),
                                  ck, cv)
            m, _aux = gpt._mlp_block(
                cfg, lp["mlp"], gpt._apply_norm(cfg, lp["post_attn_norm"], x),
                policy)
            return x + a + m, ck, cv
        if bt == "post_ln":
            a, ck, cv = attn_part(lp, x, ck, cv)
            x = gpt._apply_norm(cfg, lp["input_norm"], x + a)
            m, _aux = gpt._mlp_block(cfg, lp["mlp"], x, policy)
            return gpt._apply_norm(cfg, lp["post_attn_norm"], x + m), ck, cv
        a, ck, cv = attn_part(lp, gpt._apply_norm(cfg, lp["input_norm"], x),
                              ck, cv)
        if bt == "normformer":
            a = gpt._apply_norm(cfg, lp["nf_attn_norm"], a)
        x = x + a
        m, _aux = gpt._mlp_block(
            cfg, lp["mlp"], gpt._apply_norm(cfg, lp["post_attn_norm"], x),
            policy,
            mid_norm=lp.get("nf_mlp_norm") if bt == "normformer" else None,
        )
        return x + m, ck, cv

    if cfg.moe is not None and cfg.moe_frequency > 1:
        f = cfg.moe_frequency
        gk = cache["k"].reshape((-1, f) + cache["k"].shape[1:])
        gv = cache["v"].reshape((-1, f) + cache["v"].shape[1:])

        def gbody(x, inp):
            gp, ck, cv = inp
            x, ck0, cv0 = layer_step(gp["moe"], x, ck[0], cv[0])

            def dense_body(x2, dinp):
                dlp, dk, dv = dinp
                x2, dk, dv = layer_step(dlp, x2, dk, dv)
                return x2, (dk, dv)

            x, (ckd, cvd) = jax.lax.scan(
                dense_body, x, (gp["dense"], ck[1:], cv[1:]))
            return x, (jnp.concatenate([ck0[None], ckd], axis=0),
                       jnp.concatenate([cv0[None], cvd], axis=0))

        x, (ck, cv) = jax.lax.scan(
            gbody, x, (gpt._group_xs(cfg, layer_stack), gk, gv))
        ck = ck.reshape((-1,) + ck.shape[2:])
        cv = cv.reshape((-1,) + cv.shape[2:])
    else:

        def body(x, inp):
            lp, ck, cv = inp
            x, ck, cv = layer_step(lp, x, ck, cv)
            return x, (ck, cv)

        x, (ck, cv) = jax.lax.scan(body, x, (layer_stack, cache["k"], cache["v"]))
    h = (x if cfg.transformer_block_type == "post_ln"
         else gpt._apply_norm(cfg, params["final_norm"], x))
    logits = gpt._logits_from_hidden(params, h, cfg, policy)
    return logits[:, 0], {"k": ck, "v": cv}


def _family(cfg):
    """(prefill_fn, decode_fn, logits_cfg_for_head) by config type."""
    from neuronx_distributed_training_tpu.models import gpt, mixtral

    if isinstance(cfg, mixtral.MixtralConfig):
        return (prefill_mixtral, decode_step_mixtral,
                lambda params, h, policy: llama.logits_fn(
                    params, h, cfg.llama, policy))
    if isinstance(cfg, gpt.GPTConfig):
        return (prefill_gpt, decode_step_gpt,
                lambda params, h, policy: gpt._logits_from_hidden(
                    params, h, cfg, policy))
    return (prefill, decode_step,
            lambda params, h, policy: llama.logits_fn(params, h, cfg, policy))


def generate_cached(
    params: Any,
    cfg: llama.LlamaConfig,
    policy: DtypePolicy,
    prompt_ids: jax.Array,   # [b, plen] RIGHT-padded
    prompt_lens: jax.Array,  # [b]
    *,
    max_new_tokens: int,
    eos_id: int,
    pad_id: int = 0,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """KV-cached counterpart of ``models.generate.generate`` (same contract)."""
    from neuronx_distributed_training_tpu.models.generate import filter_logits

    b, plen = prompt_ids.shape
    total = plen + max_new_tokens
    lens = prompt_lens.astype(jnp.int32)
    rows = jnp.arange(b)

    buf = jnp.full((b, total), pad_id, dtype=prompt_ids.dtype)
    buf = buf.at[:, :plen].set(prompt_ids)
    if max_new_tokens <= 0:  # same no-op contract as generate()
        return buf
    prefill_fn, decode_fn, head_fn = _family(cfg)
    h, cache = prefill_fn(params, prompt_ids, cfg, policy, max_len=total)
    # logits ONLY at each row's last prompt position ([b, 1, h] -> [b, vocab])
    logits = head_fn(params, h[rows, lens - 1][:, None], policy)[:, 0]
    key = key if key is not None else jax.random.PRNGKey(0)

    def pick(next_logits, key):
        if temperature > 0:
            key, sub = jax.random.split(key)
            scaled = filter_logits(
                next_logits / temperature, top_k=top_k, top_p=top_p
            )
            return jax.random.categorical(sub, scaled, axis=-1), key
        return jnp.argmax(next_logits, axis=-1), key

    # token 0 comes from the prefill logits at each row's last prompt position
    first, key = pick(logits, key)
    first = first.astype(buf.dtype)
    buf = buf.at[rows, lens].set(first)  # the EOS itself stays visible
    done0 = first == eos_id

    def step(i, carry):
        buf, cache, done, key = carry
        pos = lens + i  # position holding the PREVIOUS token
        prev = buf[rows, pos]
        logits, cache = decode_fn(params, cache, prev, pos, cfg, policy)
        nxt, key = pick(logits, key)
        nxt = jnp.where(done, jnp.asarray(pad_id, buf.dtype), nxt.astype(buf.dtype))
        buf = buf.at[rows, pos + 1].set(nxt)
        done = done | (nxt == eos_id)
        return buf, cache, done, key

    buf, _, _, _ = jax.lax.fori_loop(
        0, max_new_tokens - 1, step, (buf, cache, done0, key)
    )
    return buf
