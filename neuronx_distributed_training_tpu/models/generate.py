"""Autoregressive generation (greedy / temperature sampling).

Backs the SFT evaluation harness the way the reference's traced-inference
``LlamaRunner`` backs ``sft_evaluation/evaluate.py`` (reference
``examples/sft_evaluation/models/nxd_llama.py``).  XLA-friendly: one fixed
``[batch, max_len]`` token buffer, ``lax.fori_loop`` over positions, full-prefix
forward per step (static shapes; a KV-cache decode path is a later perf
optimization — eval harness workloads are small).

Prompts are RIGHT-padded: row ``b`` holds its prompt at positions
``[0, prompt_lens[b])``.  Generated tokens are written at each row's own
front (``prompt_lens[b] + i``), so causal attention never sees padding (pad
positions are strictly ahead of every query) and RoPE positions are the
natural ``0..L`` — no attention mask or per-row position offsets needed.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# logits_of: (params, input_ids [b, L]) -> logits [b, L, vocab]
LogitsFn = Callable[[Any, jax.Array], jax.Array]


def pad_prompts(prompts: list[list[int]], pad_id: int = 0):
    """Right-pad variable-length prompts -> (ids [b, max_len], lens [b])."""
    import numpy as np

    lens = np.asarray([len(p) for p in prompts], np.int32)
    ids = np.full((len(prompts), int(lens.max())), pad_id, np.int32)
    for i, p in enumerate(prompts):
        ids[i, : len(p)] = p
    return jnp.asarray(ids), jnp.asarray(lens)


def generate(
    params: Any,
    prompt_ids: jax.Array,  # [b, prompt_len] RIGHT-padded with pad_id
    prompt_lens: jax.Array,  # [b] true prompt lengths
    logits_of: LogitsFn,
    *,
    max_new_tokens: int,
    eos_id: int,
    pad_id: int = 0,
    temperature: float = 0.0,  # 0 = greedy
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate up to ``max_new_tokens``; returns ``[b, prompt_len + max_new]``.

    Row ``b``'s completion occupies ``[prompt_lens[b], prompt_lens[b] + n)``;
    positions after a generated EOS (and unused tail) hold ``pad_id``.
    """
    b, plen = prompt_ids.shape
    total = plen + max_new_tokens
    buf = jnp.full((b, total), pad_id, dtype=prompt_ids.dtype)
    buf = buf.at[:, :plen].set(prompt_ids)
    done0 = jnp.zeros((b,), bool)
    key = key if key is not None else jax.random.PRNGKey(0)
    rows = jnp.arange(b)
    lens = prompt_lens.astype(jnp.int32)

    def step(i, carry):
        buf, done, key = carry
        pos = lens + i  # [b] next position to fill, per row
        logits = logits_of(params, buf)  # [b, total, vocab]
        # row b predicts from its own front: logits at position pos[b]-1
        next_logits = logits[rows, pos - 1, :]
        if temperature > 0:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(next_logits, axis=-1)
        nxt = nxt.astype(buf.dtype)
        nxt = jnp.where(done, jnp.asarray(pad_id, buf.dtype), nxt)
        buf = buf.at[rows, pos].set(nxt)
        done = done | (nxt == eos_id)
        return buf, done, key

    buf, _, _ = jax.lax.fori_loop(0, max_new_tokens, step, (buf, done0, key))
    return buf
