"""Autoregressive generation (greedy / temperature sampling).

Backs the SFT evaluation harness the way the reference's traced-inference
``LlamaRunner`` backs ``sft_evaluation/evaluate.py`` (reference
``examples/sft_evaluation/models/nxd_llama.py``).  XLA-friendly: one fixed
``[batch, max_len]`` token buffer, ``lax.fori_loop`` over positions, full-prefix
forward per step (static shapes; a KV-cache decode path is a later perf
optimization — eval harness workloads are small).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# logits_of: (params, input_ids [b, L]) -> logits [b, L, vocab]
LogitsFn = Callable[[Any, jax.Array], jax.Array]


def generate(
    params: Any,
    prompt_ids: jax.Array,  # [b, prompt_len] left-padded with pad_id
    prompt_lens: jax.Array,  # [b] true prompt lengths
    logits_of: LogitsFn,
    *,
    max_new_tokens: int,
    eos_id: int,
    pad_id: int = 0,
    temperature: float = 0.0,  # 0 = greedy
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate up to ``max_new_tokens``; returns ``[b, prompt_len + max_new]``.

    Positions after a generated EOS are filled with ``pad_id``.
    """
    b, plen = prompt_ids.shape
    total = plen + max_new_tokens
    buf = jnp.full((b, total), pad_id, dtype=prompt_ids.dtype)
    buf = buf.at[:, :plen].set(prompt_ids)
    done0 = jnp.zeros((b,), bool)
    key = key if key is not None else jax.random.PRNGKey(0)

    def step(i, carry):
        buf, done, key = carry
        pos = plen + i  # next position to fill
        logits = logits_of(params, buf)  # [b, total, vocab]
        next_logits = logits[:, pos - 1, :]
        if temperature > 0:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(next_logits, axis=-1)
        nxt = nxt.astype(buf.dtype)
        nxt = jnp.where(done, jnp.asarray(pad_id, buf.dtype), nxt)
        buf = buf.at[:, pos].set(nxt)
        done = done | (nxt == eos_id)
        return buf, done, key

    buf, _, _ = jax.lax.fori_loop(0, max_new_tokens, step, (buf, done0, key))
    return buf
