"""Autoregressive generation (greedy / temperature sampling).

Backs the SFT evaluation harness the way the reference's traced-inference
``LlamaRunner`` backs ``sft_evaluation/evaluate.py`` (reference
``examples/sft_evaluation/models/nxd_llama.py``).  XLA-friendly: one fixed
``[batch, max_len]`` token buffer, ``lax.fori_loop`` over positions, full-prefix
forward per step (static shapes; a KV-cache decode path is a later perf
optimization — eval harness workloads are small).

Prompts are RIGHT-padded: row ``b`` holds its prompt at positions
``[0, prompt_lens[b])``.  Generated tokens are written at each row's own
front (``prompt_lens[b] + i``), so causal attention never sees padding (pad
positions are strictly ahead of every query) and RoPE positions are the
natural ``0..L`` — no attention mask or per-row position offsets needed.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# logits_of: (params, input_ids [b, L]) -> logits [b, L, vocab]
LogitsFn = Callable[[Any, jax.Array], jax.Array]


def pad_prompts(prompts: list[list[int]], pad_id: int = 0):
    """Right-pad variable-length prompts -> (ids [b, max_len], lens [b])."""
    import numpy as np

    lens = np.asarray([len(p) for p in prompts], np.int32)
    ids = np.full((len(prompts), int(lens.max())), pad_id, np.int32)
    for i, p in enumerate(prompts):
        ids[i, : len(p)] = p
    return jnp.asarray(ids), jnp.asarray(lens)


def filter_logits(
    logits: jax.Array,  # [b, vocab]
    *,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jax.Array:
    """Top-k / nucleus (top-p) filtering: non-kept tokens -> -inf.

    The reference eval harness exposes the same knobs
    (``sft_evaluation/evaluate.py:245-266``).  Both filters are threshold
    computations (no scatter): top-k keeps logits >= the k-th largest; top-p
    keeps the smallest prefix of the descending-sorted distribution whose
    cumulative probability reaches ``top_p`` (the first token always kept).
    """
    neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
    if top_k is not None and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        # f32 throughout: a bf16 cumsum over a 32k+ vocab loses tail mass and
        # misplaces the cutoff (~0.004 resolution near 1.0)
        sorted_logits = jnp.sort(logits.astype(jnp.float32), axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep while the cumulative mass BEFORE this token is < top_p
        keep = (cum - probs) < top_p
        # threshold = smallest kept logit in sorted order
        thresh = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits.astype(jnp.float32) < thresh, neg, logits)
    return logits


def generate(
    params: Any,
    prompt_ids: jax.Array,  # [b, prompt_len] RIGHT-padded with pad_id
    prompt_lens: jax.Array,  # [b] true prompt lengths
    logits_of: LogitsFn,
    *,
    max_new_tokens: int,
    eos_id: int,
    pad_id: int = 0,
    temperature: float = 0.0,  # 0 = greedy
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate up to ``max_new_tokens``; returns ``[b, prompt_len + max_new]``.

    Row ``b``'s completion occupies ``[prompt_lens[b], prompt_lens[b] + n)``;
    positions after a generated EOS (and unused tail) hold ``pad_id``.
    """
    b, plen = prompt_ids.shape
    total = plen + max_new_tokens
    buf = jnp.full((b, total), pad_id, dtype=prompt_ids.dtype)
    buf = buf.at[:, :plen].set(prompt_ids)
    done0 = jnp.zeros((b,), bool)
    key = key if key is not None else jax.random.PRNGKey(0)
    rows = jnp.arange(b)
    lens = prompt_lens.astype(jnp.int32)

    def step(i, carry):
        buf, done, key = carry
        pos = lens + i  # [b] next position to fill, per row
        logits = logits_of(params, buf)  # [b, total, vocab]
        # row b predicts from its own front: logits at position pos[b]-1
        next_logits = logits[rows, pos - 1, :]
        if temperature > 0:
            key, sub = jax.random.split(key)
            # temperature FIRST, then the nucleus — top-p must be computed on
            # the distribution actually sampled (HF/reference semantics)
            scaled = filter_logits(
                next_logits / temperature, top_k=top_k, top_p=top_p
            )
            nxt = jax.random.categorical(sub, scaled, axis=-1)
        else:
            nxt = jnp.argmax(next_logits, axis=-1)
        nxt = nxt.astype(buf.dtype)
        nxt = jnp.where(done, jnp.asarray(pad_id, buf.dtype), nxt)
        buf = buf.at[rows, pos].set(nxt)
        done = done | (nxt == eos_id)
        return buf, done, key

    buf, _, _ = jax.lax.fori_loop(0, max_new_tokens, step, (buf, done0, key))
    return buf
