"""Megatron-family GPT model, TPU-native.

Functional re-design of the reference's Megatron model source
(``models/megatron/gpt_model.py`` + ``language_model.py`` + ``transformer.py``,
~3500 LoC of NeMo-Megatron-on-NxD): the architecture-knob surface of
``megatron_gpt_model.py:79-147`` reduced to the knobs that change math —

- position embedding: ``rope`` | ``learned_absolute``
  (``language_model.py:194-328`` Embedding + RotaryEmbedding);
- normalization: ``layernorm`` (with bias) | ``rmsnorm``
  (``fused_layer_norm.py:14-36``);
- activation: ``gelu`` | ``swiglu`` | ``geglu`` | ``reglu``
  (``transformer.py:89-245`` ParallelMLP variants);
- biased linears (Megatron default) vs bias-free;
- GQA / MQA via ``num_query_groups`` (``transformer.py:470-777``);
- optional sliding-window attention; dropout (embedding/hidden) with explicit
  PRNG threading;
- MoE layers (``NeuronSwitchMLP``, ``transformer.py:376-467``) via
  ``ops.moe`` with top-k or sinkhorn routing;
- transformer block layouts ``pre_ln`` (default) | ``post_ln`` | ``normformer``
  | ``gpt_j`` (``transformer.py:1468-2084``) and optional tokentype
  embeddings (``language_model.py:194-328``).

Normformer deviation: the reference computes the mid-MLP LayerNorm
per-TP-partition (width ``ffn/tp``, no cross-shard stats); here it is a true
LayerNorm over the full ffn width — GSPMD inserts the reduction, and the
numerics don't change with tp.

Loss is the same vocab-parallel CE as Llama.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from neuronx_distributed_training_tpu.ops import cross_entropy as ce_ops
from neuronx_distributed_training_tpu.ops import attention as attn_ops
from neuronx_distributed_training_tpu.ops import linear as linear_ops
from neuronx_distributed_training_tpu.ops import moe as moe_ops
from neuronx_distributed_training_tpu.ops import norm as norm_ops
from neuronx_distributed_training_tpu.ops import rope as rope_ops
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """The ``megatron`` ``model:`` block (reference ``megatron_gpt_model.py:79-147``)."""

    vocab_size: int = 50257
    hidden_size: int = 1024
    ffn_hidden_size: Optional[int] = None  # default 4*h (or 8/3*h for glu acts)
    num_layers: int = 12
    num_attention_heads: int = 16
    num_query_groups: Optional[int] = None  # GQA; 1 = MQA; None = MHA
    max_position_embeddings: int = 2048
    position_embedding_type: str = "rope"  # "rope" | "learned_absolute"
    rotary_percentage: float = 1.0
    rope_theta: float = 10000.0
    normalization: str = "layernorm"  # "layernorm" | "rmsnorm"
    layernorm_epsilon: float = 1e-5
    activation: str = "gelu"  # "gelu" | "swiglu" | "geglu" | "reglu"
    bias: bool = True
    hidden_dropout: float = 0.0
    embedding_dropout: float = 0.0
    sliding_window: Optional[int] = None
    # block layout: "pre_ln" | "post_ln" | "normformer" | "gpt_j"
    # (reference transformer.py:1468-2084)
    transformer_block_type: str = "pre_ln"
    # tokentype (segment) embeddings; 0 = none (language_model.py:194-328)
    num_tokentypes: int = 0
    share_embeddings_and_output_weights: bool = True  # Megatron default tying
    initializer_range: float = 0.02
    attention_impl: str = "core"
    flash_block_q: Optional[int] = None   # Pallas tile knobs, fusions.flash_block_*
    flash_block_kv: Optional[int] = None  # (also the blockwise/ring kv block)
    sequence_parallel: bool = False
    activations_checkpoint_granularity: Optional[str] = "selective"
    # MoE (NeuronSwitchMLP equivalent); None -> dense
    moe: Optional[moe_ops.MoEConfig] = None
    moe_frequency: int = 1  # MoE every Nth layer (reference megatron_gpt_model.py:137)

    @property
    def kv_heads(self) -> int:
        return self.num_query_groups or self.num_attention_heads

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn_size(self) -> int:
        if self.ffn_hidden_size:
            return self.ffn_hidden_size
        return 4 * self.hidden_size

    @property
    def is_glu(self) -> bool:
        return self.activation in ("swiglu", "geglu", "reglu")

    @classmethod
    def from_config(cls, model_cfg: dict[str, Any], ds_cfg: dict[str, Any] | None = None):
        m = dict(model_cfg or {})
        ds = dict(ds_cfg or {})
        fusions = dict(m.get("fusions", {}) or {})
        moe_block = m.get("moe") or (
            {"num_experts": m["num_moe_experts"]} if m.get("num_moe_experts") else None
        )
        moe_freq = int((moe_block or {}).get("frequency", 1) or 1)
        return cls(
            vocab_size=int(m.get("vocab_size", 50257)),
            hidden_size=int(m.get("hidden_size", 1024)),
            ffn_hidden_size=m.get("ffn_hidden_size"),
            num_layers=int(m.get("num_layers", 12)),
            num_attention_heads=int(m.get("num_attention_heads", 16)),
            num_query_groups=m.get("num_query_groups", m.get("num_kv_heads")),
            max_position_embeddings=int(m.get("max_position_embeddings", 2048)),
            position_embedding_type=str(m.get("position_embedding_type", "rope")),
            rotary_percentage=float(m.get("rotary_percentage", 1.0)),
            rope_theta=float(m.get("rotary_base", m.get("rope_theta", 10000.0))),
            normalization=str(m.get("normalization", "layernorm")),
            layernorm_epsilon=float(m.get("layernorm_epsilon", 1e-5)),
            activation=str(m.get("activation", "gelu")),
            bias=bool(m.get("has_bias", m.get("bias", True))),
            hidden_dropout=float(m.get("hidden_dropout", 0.0)),
            embedding_dropout=float(m.get("embedding_dropout", m.get("hidden_dropout", 0.0))),
            sliding_window=m.get(
                "sliding_window_size", m.get("window_size", m.get("sliding_window"))
            ),
            transformer_block_type=str(m.get("transformer_block_type", "pre_ln")),
            num_tokentypes=int(m.get("num_tokentypes", 0) or 0),
            share_embeddings_and_output_weights=bool(
                m.get("share_embeddings_and_output_weights", True)
            ),
            attention_impl="flash" if fusions.get("flash_attention") else "core",
            flash_block_q=fusions.get("flash_block_q"),
            flash_block_kv=fusions.get("flash_block_kv"),
            sequence_parallel=bool(ds.get("sequence_parallel", False)),
            activations_checkpoint_granularity=m.get(
                "activations_checkpoint_granularity", "selective"
            ),
            moe=moe_ops.MoEConfig.from_config(moe_block) if moe_block else None,
            moe_frequency=moe_freq,
        )


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


BLOCK_TYPES = ("pre_ln", "post_ln", "normformer", "gpt_j")


def _norm_init(cfg: GPTConfig, dtype, width: Optional[int] = None):
    width = width or cfg.hidden_size
    if cfg.normalization == "rmsnorm":
        return norm_ops.init_rms_norm(width, dtype=dtype)[0]
    return norm_ops.init_layer_norm(width, dtype=dtype)[0]


def _apply_norm(cfg: GPTConfig, params, x):
    if cfg.normalization == "rmsnorm":
        return norm_ops.apply_rms_norm(params, x, eps=cfg.layernorm_epsilon)
    return norm_ops.apply_layer_norm(params, x, eps=cfg.layernorm_epsilon)


def _init_layer(key: jax.Array, cfg: GPTConfig, dtype, *, moe_layer=None):
    """``moe_layer`` overrides the MLP kind (None -> cfg.moe decides)."""
    keys = jax.random.split(key, 6)
    h, d = cfg.hidden_size, cfg.head_size
    nh, nkv = cfg.num_attention_heads, cfg.kv_heads
    std = cfg.initializer_range
    bias = cfg.bias
    if cfg.transformer_block_type not in BLOCK_TYPES:
        raise ValueError(
            f"unknown transformer_block_type {cfg.transformer_block_type!r}; "
            f"supported: {BLOCK_TYPES}"
        )
    if cfg.transformer_block_type == "normformer" and cfg.moe is not None:
        raise ValueError(
            "normformer blocks are dense-only (the mid-MLP norm has no "
            "expert equivalent); use pre_ln or post_ln with MoE"
        )
    p: dict[str, Any] = {
        "input_norm": _norm_init(cfg, dtype),
        # every layout keeps both norms — gpt_j's parallel residual norms the
        # attn branch with input_norm and the MLP branch with post_attn_norm
        # (two independent parameter sets, reference transformer.py:1908-1914)
        "post_attn_norm": _norm_init(cfg, dtype),
    }
    if cfg.transformer_block_type == "normformer":
        # extra norms: after the attention output (h) and after the MLP
        # activation (ffn width) — reference transformer.py normformer layout
        p["nf_attn_norm"] = _norm_init(cfg, dtype)
        p["nf_mlp_norm"] = _norm_init(cfg, dtype, width=cfg.ffn_size)
    p["attn"] = {
        "qkv": linear_ops.init_linear(
            keys[0], h, (nh + 2 * nkv) * d, shard="column", dtype=dtype,
            stddev=std, use_bias=bias,
        )[0],
        "o": linear_ops.init_linear(
            keys[1], nh * d, h, shard="row", dtype=dtype, stddev=std, use_bias=bias
        )[0],
    }
    is_moe = (cfg.moe is not None) if moe_layer is None else moe_layer
    if is_moe:
        p["mlp"] = moe_ops.init_moe_params(
            keys[2], h, cfg.ffn_size, cfg.moe, dtype=dtype, stddev=std
        )
    else:
        width = 2 * cfg.ffn_size if cfg.is_glu else cfg.ffn_size
        p["mlp"] = {
            "up": linear_ops.init_linear(
                keys[2], h, width, shard="column", dtype=dtype, stddev=std,
                use_bias=bias,
            )[0],
            "down": linear_ops.init_linear(
                keys[3], cfg.ffn_size, h, shard="row", dtype=dtype, stddev=std,
                use_bias=bias,
            )[0],
        }
    return p


def num_moe_layers(cfg: GPTConfig) -> int:
    """Layer ``i`` is MoE iff ``i % moe_frequency == 0`` (reference
    ``megatron_gpt_model.py:137`` + mixtral's interleave rule)."""
    f = cfg.moe_frequency
    if cfg.num_layers % f != 0:
        raise ValueError(
            f"num_layers {cfg.num_layers} must divide by moe frequency {f}"
        )
    return cfg.num_layers // f


def init_params(key: jax.Array, cfg: GPTConfig, policy: DtypePolicy | None = None):
    policy = policy or DtypePolicy()
    dtype = policy.param_dtype
    kemb, kpos, klayers, khead = jax.random.split(key, 4)
    params: dict[str, Any] = {}
    params["embed"], _ = linear_ops.init_embedding(
        kemb, cfg.vocab_size, cfg.hidden_size, dtype=dtype, stddev=cfg.initializer_range
    )
    if cfg.position_embedding_type == "learned_absolute":
        params["pos_embed"] = {
            "embedding": (
                cfg.initializer_range
                * jax.random.truncated_normal(
                    kpos, -2.0, 2.0, (cfg.max_position_embeddings, cfg.hidden_size)
                )
            ).astype(dtype)
        }
    if cfg.num_tokentypes > 0:
        # segment embeddings (reference language_model.py:194-328)
        params["tokentype_embed"] = {
            "embedding": (
                cfg.initializer_range
                * jax.random.truncated_normal(
                    jax.random.fold_in(kpos, 7), -2.0, 2.0,
                    (cfg.num_tokentypes, cfg.hidden_size),
                )
            ).astype(dtype)
        }
    layer_keys = jax.random.split(klayers, cfg.num_layers)
    if cfg.moe is not None and cfg.moe_frequency > 1:
        f, g = cfg.moe_frequency, num_moe_layers(cfg)
        dense_stack = jax.vmap(
            lambda k: _init_layer(k, cfg, dtype, moe_layer=False)
        )(layer_keys)
        moe_keys = jax.random.split(jax.random.fold_in(klayers, 999), g)
        moe_mlp = jax.vmap(
            lambda k: moe_ops.init_moe_params(
                k, cfg.hidden_size, cfg.ffn_size, cfg.moe,
                dtype=dtype, stddev=cfg.initializer_range,
            )
        )(moe_keys)
        dense_mlp = jax.tree_util.tree_map(
            lambda x: x.reshape((g, f) + x.shape[1:])[:, 1:],
            dense_stack["mlp"],
        )
        dense_stack["mlp"] = {"moe": moe_mlp, "dense": dense_mlp}
        params["layers"] = dense_stack
    else:
        params["layers"] = jax.vmap(lambda k: _init_layer(k, cfg, dtype))(layer_keys)
    if cfg.transformer_block_type != "post_ln":
        # post_ln layers end with their own LN — the reference builds no
        # final_layernorm for that layout (transformer.py:2478, 2569-2570)
        params["final_norm"] = _norm_init(cfg, dtype)
    if not cfg.share_embeddings_and_output_weights:
        params["lm_head"], _ = linear_ops.init_linear(
            khead, cfg.hidden_size, cfg.vocab_size, shard="column", dtype=dtype,
            stddev=cfg.initializer_range,
        )
    return params


def _norm_specs(cfg: GPTConfig):
    if cfg.normalization == "rmsnorm":
        return {"scale": P(None)}
    return {"scale": P(None), "bias": P(None)}


def param_specs(cfg: GPTConfig, *, pipeline: bool = False):
    n = _norm_specs(cfg)
    attn: dict[str, Any] = {
        "qkv": {"w": P(None, "model")},
        "o": {"w": P("model", None)},
    }
    if cfg.bias:
        attn["qkv"]["bias"] = P("model")
        attn["o"]["bias"] = P(None)
    dense_mlp = {"up": {"w": P(None, "model")}, "down": {"w": P("model", None)}}
    if cfg.bias:
        dense_mlp["up"]["bias"] = P("model")
        dense_mlp["down"]["bias"] = P(None)
    if cfg.moe is not None and cfg.moe_frequency > 1:
        mlp = None  # grouped; filled below after stacking
    elif cfg.moe is not None:
        mlp = moe_ops.moe_param_specs(cfg.moe)
    else:
        mlp = dense_mlp
    layer = {"input_norm": n, "post_attn_norm": n, "attn": attn,
             "mlp": mlp if mlp is not None else dense_mlp}
    if cfg.transformer_block_type == "normformer":
        layer["nf_attn_norm"] = n
        layer["nf_mlp_norm"] = n
    lead = "pipe" if pipeline else None
    stacked = jax.tree_util.tree_map(
        lambda s: P(*((lead,) + tuple(s))), layer, is_leaf=lambda x: isinstance(x, P)
    )
    if cfg.moe is not None and cfg.moe_frequency > 1:
        # grouped layout: moe leads [G] and dense [G, f-1]; under pipeline
        # both lead with "pipe" (pp slices whole MoE+dense groups, matching
        # the flat [L] attn/norm slices since L/pp == (G/pp)*f)
        moe_specs = jax.tree_util.tree_map(
            lambda s: P(*((lead,) + tuple(s))), moe_ops.moe_param_specs(cfg.moe),
            is_leaf=lambda x: isinstance(x, P),
        )
        grouped_dense = jax.tree_util.tree_map(
            lambda s: P(*((tuple(s)[0], None) + tuple(s)[1:])), stacked["mlp"],
            is_leaf=lambda x: isinstance(x, P),
        )
        stacked["mlp"] = {"moe": moe_specs, "dense": grouped_dense}
    specs: dict[str, Any] = {
        "embed": {"embedding": P("model", None)},
        "layers": stacked,
    }
    if cfg.transformer_block_type != "post_ln":
        specs["final_norm"] = _norm_specs(cfg)
    if cfg.position_embedding_type == "learned_absolute":
        specs["pos_embed"] = {"embedding": P(None, None)}
    if cfg.num_tokentypes > 0:
        specs["tokentype_embed"] = {"embedding": P(None, None)}
    if not cfg.share_embeddings_and_output_weights:
        specs["lm_head"] = {"w": P(None, "model")}
    return specs


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _activation(cfg: GPTConfig, x: jax.Array) -> jax.Array:
    if cfg.is_glu:
        a, b = jnp.split(x, 2, axis=-1)
        gate = {"swiglu": jax.nn.silu, "geglu": jax.nn.gelu,
                "reglu": jax.nn.relu}[cfg.activation](a)
        return gate * b
    return jax.nn.gelu(x)


def _dropout(x, rate, key):
    if rate <= 0.0 or key is None:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)


def _attention_block(cfg, lp, x, cos, sin, policy, attention_mask=None,
                     segment_ids=None, return_kv=False):
    b, s, h = x.shape
    nh, nkv, d = cfg.num_attention_heads, cfg.kv_heads, cfg.head_size
    qkv = linear_ops.apply_linear(lp["qkv"], x)
    q, k, v = jnp.split(qkv, [nh * d, (nh + nkv) * d], axis=-1)
    q = q.reshape(b, s, nh, d)
    k = k.reshape(b, s, nkv, d)
    v = v.reshape(b, s, nkv, d)
    q = shd.constrain(q, shd.heads_spec(False))
    if cos is not None:
        if cfg.rotary_percentage < 1.0:
            rot = int(d * cfg.rotary_percentage) // 2 * 2
            q = jnp.concatenate(
                [rope_ops.apply_rope(q[..., :rot], cos, sin), q[..., rot:]], -1
            )
            k = jnp.concatenate(
                [rope_ops.apply_rope(k[..., :rot], cos, sin), k[..., rot:]], -1
            )
        else:
            q = rope_ops.apply_rope(q, cos, sin)
            k = rope_ops.apply_rope(k, cos, sin)
    out = attn_ops.attention(
        q, k, v, impl=cfg.attention_impl, causal=True,
        sliding_window=cfg.sliding_window, softmax_dtype=policy.softmax_dtype,
        attention_mask=attention_mask, segment_ids=segment_ids,
        block_q=cfg.flash_block_q, block_kv=cfg.flash_block_kv,
    )
    out = linear_ops.apply_linear(lp["o"], out.reshape(b, s, nh * d))
    if return_kv:
        return out, (k, v)
    return out


def _mlp_block(cfg, lp, x, policy, mid_norm=None):
    if cfg.moe is not None and "router" in lp:
        y, aux = moe_ops.moe_block(lp, x, cfg.moe, compute_dtype=policy.compute_dtype)
        aux_loss = moe_ops.weighted_router_loss(
            aux["router_logits"], aux["expert_idx"], cfg.moe
        )
        return y, aux_loss
    y = linear_ops.apply_linear(lp["up"], x)
    y = _activation(cfg, y)
    if mid_norm is not None:
        # normformer mid-MLP norm (full ffn width; see module docstring for
        # the per-partition deviation from the reference)
        y = _apply_norm(cfg, mid_norm, y)
    return linear_ops.apply_linear(lp["down"], y), jnp.zeros((), jnp.float32)


def _decoder_layer(cfg, lp, x, cos, sin, policy, dropout_key,
                   attention_mask=None, segment_ids=None, return_kv=False):
    """One transformer block in the configured layout
    (reference ``transformer.py:1468-2084``):

    - ``pre_ln``      x += drop(attn(LN1(x)));        x += drop(mlp(LN2(x)))
    - ``post_ln``     x = LN1(x + drop(attn(x)));     x = LN2(x + drop(mlp(x)))
    - ``normformer``  x += drop(LNa(attn(LN1(x))));   x += drop(mlp_mid(LN2(x)))
    - ``gpt_j``       x += drop(attn(LN1(x))) + drop(mlp(LN2(x)))
      (parallel residual; LN1/LN2 are two independent norms, reference
      ``transformer.py:1908-1914``)
    """
    aspec = shd.act_spec(cfg.sequence_parallel, False)
    bt = cfg.transformer_block_type
    k1 = k2 = None
    if dropout_key is not None:
        k1, k2 = jax.random.split(dropout_key)

    if bt == "gpt_j":
        attn_in = _apply_norm(cfg, lp["input_norm"], x)
        attn_out = _attention_block(cfg, lp["attn"], attn_in, cos, sin, policy,
                                    attention_mask=attention_mask,
                                    segment_ids=segment_ids,
                                    return_kv=return_kv)
        kv = None
        if return_kv:
            attn_out, kv = attn_out
        mlp_in = _apply_norm(cfg, lp["post_attn_norm"], x)
        mlp_out, aux_loss = _mlp_block(cfg, lp["mlp"], mlp_in, policy)
        x = shd.constrain(
            x + _dropout(attn_out, cfg.hidden_dropout, k1)
            + _dropout(mlp_out, cfg.hidden_dropout, k2), aspec)
        if return_kv:
            return x, aux_loss, kv
        return x, aux_loss

    residual = x
    attn_in = x if bt == "post_ln" else _apply_norm(cfg, lp["input_norm"], x)
    hidden = _attention_block(cfg, lp["attn"], attn_in, cos, sin, policy,
                              attention_mask=attention_mask,
                              segment_ids=segment_ids,
                              return_kv=return_kv)
    kv = None
    if return_kv:
        hidden, kv = hidden
    if bt == "normformer":
        hidden = _apply_norm(cfg, lp["nf_attn_norm"], hidden)
    x = residual + _dropout(hidden, cfg.hidden_dropout, k1)
    if bt == "post_ln":
        x = _apply_norm(cfg, lp["input_norm"], x)
    x = shd.constrain(x, aspec)

    residual = x
    mlp_in = x if bt == "post_ln" else _apply_norm(cfg, lp["post_attn_norm"], x)
    hidden, aux_loss = _mlp_block(
        cfg, lp["mlp"], mlp_in, policy,
        mid_norm=lp.get("nf_mlp_norm") if bt == "normformer" else None,
    )
    x = residual + _dropout(hidden, cfg.hidden_dropout, k2)
    if bt == "post_ln":
        x = _apply_norm(cfg, lp["post_attn_norm"], x)
    x = shd.constrain(x, aspec)
    if return_kv:
        return x, aux_loss, kv
    return x, aux_loss


def _add_tokentype(cfg: GPTConfig, params, x, tokentype_ids):
    """Add segment embeddings (reference ``language_model.py:194-328``):
    ids present without a table is a config error; a table without ids adds
    nothing (the reference's optional-tokentype contract)."""
    if tokentype_ids is None:
        return x
    if cfg.num_tokentypes <= 0:
        raise ValueError(
            "batch has tokentype_ids but model.num_tokentypes is 0; set "
            "num_tokentypes to the number of segment types"
        )
    return x + jnp.take(
        params["tokentype_embed"]["embedding"], tokentype_ids, axis=0
    ).astype(x.dtype)


def _rope_for(cfg: GPTConfig, input_ids: jax.Array, positions=None):
    if cfg.position_embedding_type == "learned_absolute":
        return None, None
    if positions is None:
        from neuronx_distributed_training_tpu.models.llama import positions_for

        positions = positions_for(input_ids)
    rot_dim = int(cfg.head_size * cfg.rotary_percentage) // 2 * 2
    inv_freq = rope_ops.rope_frequencies(rot_dim, theta=cfg.rope_theta)
    return rope_ops.rope_cos_sin(positions, inv_freq, dtype=jnp.float32)


def _group_xs(cfg: GPTConfig, layer_stack):
    """Grouped scan inputs (see ``ops.moe.group_interleaved_stack``)."""
    return moe_ops.group_interleaved_stack(cfg.moe_frequency, layer_stack)


def _grouped_scan(cfg: GPTConfig, layer_stack, cos, sin, policy,
                  layer_keys=None, attention_mask=None, segment_ids=None):
    """(xs, body) for the dense/MoE interleave scan over [G] groups.

    Shared by ``forward`` and the pipeline ``stage_fn`` (mirrors
    ``mixtral._grouped_scan``; the body differs by GPT's dropout-key
    threading).  Each group runs one MoE layer then ``f-1`` dense layers;
    groups are contiguous runs of ``f`` layers, so any contiguous slice of
    the flat attn/norm stack aligns with the matching moe/dense group slices
    — which is what makes the layout pipeline-sliceable.  Dropout keys group
    as ``[g, f]`` so every layer keeps a unique key.
    """
    f = cfg.moe_frequency
    g = jax.tree_util.tree_leaves(layer_stack["mlp"]["moe"])[0].shape[0]
    grouped = _group_xs(cfg, layer_stack)
    moe_xs, dense_xs = grouped["moe"], grouped["dense"]
    gkeys = (
        layer_keys.reshape((g, f) + layer_keys.shape[1:])
        if layer_keys is not None else None
    )

    def body(carry, inp):
        x, aux_acc = carry
        if gkeys is not None:
            mxs, dxs, keys_g = inp
            k0 = keys_g[0]
        else:
            mxs, dxs = inp
            k0 = None
        # per-group cast inside the scan (one group's bf16 copy live at a time)
        mxs = policy.cast_to_compute(mxs)
        x, aux = _decoder_layer(cfg, mxs, x, cos, sin, policy, k0,
                                attention_mask=attention_mask,
                                segment_ids=segment_ids)

        def dense_body(carry2, dinp):
            x2, acc2 = carry2
            if gkeys is not None:
                dlp, dk = dinp
            else:
                dlp, dk = dinp, None
            dlp = policy.cast_to_compute(dlp)
            x2, a2 = _decoder_layer(cfg, dlp, x2, cos, sin, policy, dk,
                                    attention_mask=attention_mask,
                                    segment_ids=segment_ids)
            return (x2, acc2 + a2), None

        dxs_in = (dxs, keys_g[1:]) if gkeys is not None else dxs
        (x, aux_acc2), _ = jax.lax.scan(
            dense_body, (x, jnp.zeros((), jnp.float32)), dxs_in)
        return (x, aux_acc + aux + aux_acc2), None

    xs = ((moe_xs, dense_xs, gkeys) if gkeys is not None
          else (moe_xs, dense_xs))
    return xs, body


def _logits_from_hidden(params, hidden, cfg: GPTConfig, policy: DtypePolicy):
    if cfg.share_embeddings_and_output_weights:
        w = params["embed"]["embedding"].astype(policy.compute_dtype)
        logits = hidden @ w.T
    else:
        logits = linear_ops.apply_linear(
            params["lm_head"], hidden, compute_dtype=policy.compute_dtype
        )
    return shd.constrain(logits, shd.logits_spec(False))


def pipeline_hooks(cfg: GPTConfig, policy: DtypePolicy, *, shift_labels: bool = True):
    """(embed_fn, stage_fn, loss_fn) for ``parallel.pipeline.pipeline_loss``.

    Dropout PRNG: the trainer threads per-microbatch keys via ``mb["_rng"]``
    (uint32 ``[2]`` leaves); each stage folds in its pipe rank and vp chunk
    (``mb["_chunk"]``) so every (layer, microbatch) pair gets a unique key —
    the reference's per-stage dropout seeding under NxDPPModel.  ``stage_fn``
    returns ``(x, aux)``; pass ``stage_aux=True`` (aux is the MoE router loss,
    0 for dense).
    """
    aspec = shd.act_spec(cfg.sequence_parallel, False)

    def embed_fn(params, mb):
        ids = mb["input_ids"]
        s = ids.shape[1]
        x = linear_ops.apply_embedding(
            params["embed"], ids, compute_dtype=policy.compute_dtype,
        )
        if cfg.position_embedding_type == "learned_absolute":
            x = x + jnp.take(
                params["pos_embed"]["embedding"], jnp.arange(s), axis=0
            ).astype(x.dtype)[None]
        x = _add_tokentype(cfg, params, x, mb.get("tokentype_ids"))
        rng = mb.get("_rng")
        if rng is not None and cfg.embedding_dropout > 0.0:
            x = _dropout(x, cfg.embedding_dropout, jax.random.fold_in(rng, 0x0E))
        return shd.constrain(x, aspec)

    def stage_fn(local_layers, x, mb):
        cos, sin = _rope_for(cfg, mb["input_ids"])
        grouped = cfg.moe is not None and cfg.moe_frequency > 1
        if grouped:
            # local layer count = local groups x f (flat attn/norm slices)
            n_local = (
                jax.tree_util.tree_leaves(local_layers["mlp"]["moe"])[0].shape[0]
                * cfg.moe_frequency
            )
        else:
            n_local = jax.tree_util.tree_leaves(local_layers)[0].shape[0]
        rng = mb.get("_rng")
        layer_keys = None
        if rng is not None and cfg.hidden_dropout > 0.0:
            try:
                rank = jax.lax.axis_index("pipe")
            except NameError:
                rank = 0  # pp == 1 fallback path (no manual pipe axis)
            stage_rng = jax.random.fold_in(
                jax.random.fold_in(rng, rank), mb.get("_chunk", 0)
            )
            layer_keys = jax.random.split(stage_rng, n_local)
        if grouped:
            # grouped interleave on the LOCAL slice (see _grouped_scan)
            xs, body = _grouped_scan(cfg, local_layers, cos, sin, policy,
                                     layer_keys=layer_keys)
        elif layer_keys is not None:

            def body(carry, inp):
                x, aux_acc = carry
                lp, lkey = inp
                lp = policy.cast_to_compute(lp)
                x, aux = _decoder_layer(cfg, lp, x, cos, sin, policy, lkey)
                return (x, aux_acc + aux), None

            xs = (local_layers, layer_keys)
        else:

            def body(carry, lp):
                x, aux_acc = carry
                lp = policy.cast_to_compute(lp)
                x, aux = _decoder_layer(cfg, lp, x, cos, sin, policy, None)
                return (x, aux_acc + aux), None

            xs = local_layers
        (x, aux_sum), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)), xs)
        return x, aux_sum

    def loss_fn(params, y, mb):
        hidden = (y if cfg.transformer_block_type == "post_ln"
                  else _apply_norm(cfg, params["final_norm"], y))
        logits = _logits_from_hidden(params, hidden, cfg, policy)
        labels = mb["labels"]
        loss_mask = mb.get("loss_mask")
        if shift_labels:
            logits, labels, loss_mask = ce_ops.shift_for_next_token(
                logits, labels, loss_mask
            )
        loss_sum = ce_ops.cross_entropy_loss(
            logits, labels, loss_mask=loss_mask, reduction="sum"
        )
        valid = (labels != -100).astype(jnp.float32)
        if loss_mask is not None:
            valid = valid * loss_mask.astype(jnp.float32)
        return loss_sum, jnp.sum(valid)

    return embed_fn, stage_fn, loss_fn


def forward(
    params,
    batch: dict[str, jax.Array],
    cfg: GPTConfig,
    policy: DtypePolicy,
    *,
    rng: Optional[jax.Array] = None,  # dropout PRNG; None = eval/deterministic
    shift_labels: bool = True,
    return_logits: bool = False,
):
    """Causal-LM forward -> (loss, aux) (or (logits, aux) without labels)."""
    from neuronx_distributed_training_tpu.models.llama import positions_for

    input_ids = batch["input_ids"]
    attention_mask = batch.get("attention_mask")
    segment_ids = batch.get("segment_ids")
    b, s = input_ids.shape
    aspec = shd.act_spec(cfg.sequence_parallel, False)
    positions = positions_for(input_ids, attention_mask, segment_ids)
    x = linear_ops.apply_embedding(
        params["embed"], input_ids, compute_dtype=policy.compute_dtype
    )
    if cfg.position_embedding_type == "learned_absolute":
        x = x + jnp.take(
            params["pos_embed"]["embedding"], positions, axis=0
        ).astype(x.dtype)
    x = _add_tokentype(cfg, params, x, batch.get("tokentype_ids"))
    cos, sin = _rope_for(cfg, input_ids, positions=positions)
    if rng is not None:
        rng, kemb = jax.random.split(rng)
        x = _dropout(x, cfg.embedding_dropout, kemb)
    x = shd.constrain(x, aspec)

    layer_stack = params["layers"]
    layer_keys = (
        jax.random.split(rng, cfg.num_layers) if rng is not None else None
    )

    if cfg.moe is not None and cfg.moe_frequency > 1:
        # grouped interleave: scan over [L/f] groups of (MoE + f-1 dense)
        xs, body = _grouped_scan(cfg, layer_stack, cos, sin, policy,
                                 layer_keys=layer_keys,
                                 attention_mask=attention_mask,
                                 segment_ids=segment_ids)
    else:

        def body(carry, inp):
            x, aux_acc = carry
            if layer_keys is not None:
                lp, lkey = inp
            else:
                lp, lkey = inp, None
            lp = policy.cast_to_compute(lp)  # per-layer cast (see llama)
            x, aux = _decoder_layer(cfg, lp, x, cos, sin, policy, lkey,
                                    attention_mask=attention_mask,
                                    segment_ids=segment_ids)
            return (x, aux_acc + aux), None

        xs = (layer_stack, layer_keys) if layer_keys is not None else layer_stack

    from neuronx_distributed_training_tpu.models.llama import _remat_policy

    remat = _remat_policy(cfg.activations_checkpoint_granularity)
    if remat is not None:
        body = jax.checkpoint(body, policy=remat, prevent_cse=False)
    (x, aux_sum), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)), xs)
    # post_ln layers already end normalized; the reference has no final LN
    # for that layout (transformer.py:2478, 2569-2570)
    hidden = (x if cfg.transformer_block_type == "post_ln"
              else _apply_norm(cfg, params["final_norm"], x))
    logits = _logits_from_hidden(params, hidden, cfg, policy)

    aux: dict[str, Any] = {}
    if cfg.moe is not None:
        # already coefficient-weighted (weighted_router_loss); averaged over
        # the layers that HAVE routers
        aux["router_aux_loss"] = aux_sum / num_moe_layers(cfg)
    if return_logits:
        aux["logits"] = logits
    labels = batch.get("labels")
    if labels is None:
        return logits, aux
    loss_mask = batch.get("loss_mask")
    if attention_mask is not None:
        # padded positions never contribute to the loss
        am = attention_mask.astype(jnp.float32)
        loss_mask = am if loss_mask is None else loss_mask * am
    if shift_labels:
        logits, labels, loss_mask = ce_ops.shift_for_next_token(logits, labels, loss_mask)
    loss = ce_ops.cross_entropy_loss(logits, labels, loss_mask=loss_mask)
    if cfg.moe is not None:
        loss = loss + aux["router_aux_loss"]
    return loss, aux
