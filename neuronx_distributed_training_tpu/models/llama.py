"""Llama-family decoder, TPU-native.

Functional re-design of the reference's ``models/hf_models/modeling_llama.py``
(873 LoC of NxD-parallel ``nn.Module``s): the same architecture — vocab-sharded
embedding, fused-QKV or GQA attention with RoPE, fused gate/up SwiGLU MLP,
RMSNorm, no-gather lm_head + vocab-parallel cross-entropy — expressed as pure
functions over a parameter pytree:

- layers are *stacked* (leading ``[num_layers, ...]`` dim) and executed with
  ``jax.lax.scan`` — one compiled block regardless of depth (compile time and
  HLO size independent of num_layers, and the natural substrate for pipeline
  stage splitting later);
- TP/SP/CP are PartitionSpecs (see ``parallel/sharding.py``), not wrapper
  modules: what the reference does with ColumnParallel/RowParallel layers and
  explicit scatter/gather (``modeling_llama.py:296-357``, ``:398-400``) GSPMD
  derives from the weight/activation specs;
- activation checkpointing maps the reference's
  ``activations_checkpoint_granularity: selective|full`` +
  ``activations_checkpoint_recompute: [CoreAttention]``
  (``hf_llama3_8B_config.yaml:76-93``) onto ``jax.checkpoint`` policies over the
  scanned block: "selective" saves everything except tagged attention
  internals, "full" saves nothing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from neuronx_distributed_training_tpu.ops import attention as attn_ops
from neuronx_distributed_training_tpu.ops import cross_entropy as ce_ops
from neuronx_distributed_training_tpu.ops import linear as linear_ops
from neuronx_distributed_training_tpu.ops import norm as norm_ops
from neuronx_distributed_training_tpu.ops import rope as rope_ops
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Architecture + parallel-behavior knobs, mirroring the reference's
    ``model:`` YAML block + HF ``config.json`` fields (``llama_model.py:24-74``)."""

    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_attention_heads: int = 32
    num_kv_heads: Optional[int] = None  # None -> MHA
    head_dim: Optional[int] = None
    max_position_embeddings: int = 8192
    rope_theta: float = 10000.0
    rope_interpolation_factor: Optional[float] = None
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    sliding_window: Optional[int] = None
    # parallel / fusion behavior
    fuse_qkv: bool = True
    attention_impl: str = "core"  # "core" | "flash" | "ring" | "ulysses"
    flash_block_q: Optional[int] = None   # Pallas tile override (perf tuning)
    flash_block_kv: Optional[int] = None
    vocab_chunks: Optional[int] = None    # fusions.chunked_ce: fused head+CE
    sequence_parallel: bool = False
    context_parallel: bool = False
    activations_checkpoint_granularity: Optional[str] = "selective"

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_attention_heads

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @classmethod
    def from_config(cls, model_cfg: dict[str, Any], ds_cfg: dict[str, Any] | None = None) -> "LlamaConfig":
        """Build from the reference-schema ``model:`` + ``distributed_strategy:``
        config blocks (plus optional HF-config-style keys)."""
        m = dict(model_cfg or {})
        ds = dict(ds_cfg or {})
        fusions = dict(m.get("fusions", {}) or {})
        if fusions.get("ulysses_attention"):
            # all-to-all CP attention — NOT in the reference's fusion set
            # (SURVEY.md §2.11: no Ulysses); a TPU-native extension
            impl = "ulysses"
        elif fusions.get("zigzag_ring_attention"):
            # balanced causal ring over the zig-zag layout — also an extension
            impl = "zigzag_ring"
        elif fusions.get("ring_attention"):
            impl = "ring"
        elif fusions.get("flash_attention"):
            impl = "flash"
        else:
            impl = "core"
        return cls(
            vocab_size=int(m.get("vocab_size", 32000)),
            hidden_size=int(m.get("hidden_size", 4096)),
            intermediate_size=int(m.get("intermediate_size", m.get("ffn_hidden_size", 11008))),
            num_layers=int(m.get("num_layers", m.get("num_hidden_layers", 32))),
            num_attention_heads=int(m.get("num_attention_heads", 32)),
            num_kv_heads=(
                int(m["num_key_value_heads"]) if m.get("num_key_value_heads") is not None else None
            ),
            max_position_embeddings=int(m.get("max_position_embeddings", 8192)),
            rope_theta=float(m.get("rope_theta", 10000.0)),
            rope_interpolation_factor=m.get("position_interpolation_factor"),
            rms_norm_eps=float(m.get("rms_norm_eps", 1e-5)),
            tie_word_embeddings=bool(m.get("tie_word_embeddings", False)),
            sliding_window=m.get("sliding_window"),
            fuse_qkv=bool(m.get("fuse_qkv", True)),
            attention_impl=impl,
            flash_block_q=fusions.get("flash_block_q"),
            flash_block_kv=fusions.get("flash_block_kv"),
            vocab_chunks=(int(fusions["chunked_ce"])
                          if fusions.get("chunked_ce") else None),
            sequence_parallel=bool(ds.get("sequence_parallel", False)),
            context_parallel=int(ds.get("context_parallel_size", 1)) > 1,
            activations_checkpoint_granularity=m.get(
                "activations_checkpoint_granularity", "selective"
            ),
        )


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def _init_layer(key: jax.Array, cfg: LlamaConfig, dtype):
    """One decoder layer's params (unstacked). Returns (params, specs)."""
    keys = jax.random.split(key, 6)
    h, d = cfg.hidden_size, cfg.head_size
    nh, nkv = cfg.num_attention_heads, cfg.kv_heads
    params: dict[str, Any] = {}
    specs: dict[str, Any] = {}

    params["input_norm"], specs["input_norm"] = norm_ops.init_rms_norm(h, dtype=dtype)
    params["post_attn_norm"], specs["post_attn_norm"] = norm_ops.init_rms_norm(h, dtype=dtype)

    std = cfg.initializer_range
    attn_p: dict[str, Any] = {}
    attn_s: dict[str, Any] = {}
    if cfg.fuse_qkv:
        # fused qkv ColumnParallel (reference modeling_llama.py:296-308)
        attn_p["qkv"], attn_s["qkv"] = linear_ops.init_linear(
            keys[0], h, (nh + 2 * nkv) * d, shard="column", dtype=dtype, stddev=std
        )
    else:
        attn_p["q"], attn_s["q"] = linear_ops.init_linear(
            keys[0], h, nh * d, shard="column", dtype=dtype, stddev=std
        )
        attn_p["k"], attn_s["k"] = linear_ops.init_linear(
            keys[1], h, nkv * d, shard="column", dtype=dtype, stddev=std
        )
        attn_p["v"], attn_s["v"] = linear_ops.init_linear(
            keys[2], h, nkv * d, shard="column", dtype=dtype, stddev=std
        )
    attn_p["o"], attn_s["o"] = linear_ops.init_linear(
        keys[3], nh * d, h, shard="row", dtype=dtype, stddev=std
    )
    params["attn"], specs["attn"] = attn_p, attn_s

    # fused gate_up ColumnParallel(stride=2) + RowParallel down
    # (reference modeling_llama.py:164-223)
    mlp_p: dict[str, Any] = {}
    mlp_s: dict[str, Any] = {}
    mlp_p["gate_up"], mlp_s["gate_up"] = linear_ops.init_linear(
        keys[4], h, 2 * cfg.intermediate_size, shard="column", dtype=dtype, stddev=std
    )
    mlp_p["down"], mlp_s["down"] = linear_ops.init_linear(
        keys[5], cfg.intermediate_size, h, shard="row", dtype=dtype, stddev=std
    )
    params["mlp"], specs["mlp"] = mlp_p, mlp_s
    return params, specs


def init_params(key: jax.Array, cfg: LlamaConfig, policy: DtypePolicy | None = None):
    """Init the full parameter pytree (layers stacked on a leading dim)."""
    policy = policy or DtypePolicy()
    dtype = policy.param_dtype
    kemb, klayers, khead = jax.random.split(key, 3)

    params: dict[str, Any] = {}
    params["embed"], _ = linear_ops.init_embedding(
        kemb, cfg.vocab_size, cfg.hidden_size, dtype=dtype, stddev=cfg.initializer_range
    )
    layer_keys = jax.random.split(klayers, cfg.num_layers)
    params["layers"] = jax.vmap(lambda k: _init_layer(k, cfg, dtype)[0])(layer_keys)
    params["final_norm"], _ = norm_ops.init_rms_norm(cfg.hidden_size, dtype=dtype)
    if not cfg.tie_word_embeddings:
        # no-gather ColumnParallel lm_head (reference modeling_llama.py:808)
        params["lm_head"], _ = linear_ops.init_linear(
            khead, cfg.hidden_size, cfg.vocab_size, shard="column", dtype=dtype,
            stddev=cfg.initializer_range,
        )
    return params


def _layer_specs(cfg: LlamaConfig):
    """PartitionSpec tree matching one (unstacked) ``_init_layer`` output."""
    attn_s: dict[str, Any] = (
        {"qkv": {"w": P(None, "model")}}
        if cfg.fuse_qkv
        else {
            "q": {"w": P(None, "model")},
            "k": {"w": P(None, "model")},
            "v": {"w": P(None, "model")},
        }
    )
    attn_s["o"] = {"w": P("model", None)}
    return {
        "input_norm": {"scale": P(None)},
        "post_attn_norm": {"scale": P(None)},
        "attn": attn_s,
        "mlp": {"gate_up": {"w": P(None, "model")}, "down": {"w": P("model", None)}},
    }


def param_specs(cfg: LlamaConfig, *, pipeline: bool = False):
    """PartitionSpec pytree matching ``init_params`` output.

    ``pipeline=True`` shards the stacked-layer dim over ``pipe`` — that single
    spec change IS the pipeline partitioning (equal cuts at layer granularity,
    the reference's ``auto_partition``, ``base.py:136-157``)."""
    stacked = jax.tree_util.tree_map(
        lambda s: P(*(("pipe" if pipeline else None,) + tuple(s))), _layer_specs(cfg),
        is_leaf=lambda x: isinstance(x, P),
    )
    specs: dict[str, Any] = {
        "embed": {"embedding": P("model", None)},
        "layers": stacked,
        "final_norm": {"scale": P(None)},
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = {"w": P(None, "model")}
    return specs


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _attention_block(lp, x, cos, sin, cfg: LlamaConfig, policy: DtypePolicy,
                     attention_mask=None, segment_ids=None, return_kv=False):
    b, s, h = x.shape
    nh, nkv, d = cfg.num_attention_heads, cfg.kv_heads, cfg.head_size
    if cfg.fuse_qkv:
        qkv = linear_ops.apply_linear(lp["qkv"], x)
        q, k, v = jnp.split(qkv, [nh * d, (nh + nkv) * d], axis=-1)
    else:
        q = linear_ops.apply_linear(lp["q"], x)
        k = linear_ops.apply_linear(lp["k"], x)
        v = linear_ops.apply_linear(lp["v"], x)
    q = q.reshape(b, s, nh, d)
    k = k.reshape(b, s, nkv, d)
    v = v.reshape(b, s, nkv, d)
    q = shd.constrain(q, shd.heads_spec(cfg.context_parallel))
    q = rope_ops.apply_rope(q, cos, sin)
    k = rope_ops.apply_rope(k, cos, sin)
    out = attn_ops.attention(
        q, k, v,
        impl=cfg.attention_impl,
        causal=True,
        sliding_window=cfg.sliding_window,
        softmax_dtype=policy.softmax_dtype,
        attention_mask=attention_mask,
        segment_ids=segment_ids,
        block_q=cfg.flash_block_q,
        block_kv=cfg.flash_block_kv,
    )
    out = out.reshape(b, s, nh * d)
    # RowParallel o_proj; reduce(-scatter under SP) inserted by GSPMD
    # (reference modeling_llama.py:475)
    out = linear_ops.apply_linear(lp["o"], out)
    if return_kv:
        return out, (k, v)  # rotated keys — the KV-cache contract
    return out


def _mlp_block(lp, x):
    gate_up = linear_ops.apply_linear(lp["gate_up"], x)
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return linear_ops.apply_linear(lp["down"], jax.nn.silu(gate) * up)


def _decoder_layer(layer_params, x, cos, sin, cfg: LlamaConfig, policy: DtypePolicy,
                   attention_mask=None, segment_ids=None, return_kv=False):
    aspec = shd.act_spec(cfg.sequence_parallel, cfg.context_parallel)
    residual = x
    hidden = norm_ops.apply_rms_norm(layer_params["input_norm"], x, eps=cfg.rms_norm_eps)
    hidden = _attention_block(layer_params["attn"], hidden, cos, sin, cfg, policy,
                              attention_mask=attention_mask,
                              segment_ids=segment_ids, return_kv=return_kv)
    kv = None
    if return_kv:
        hidden, kv = hidden
    x = shd.constrain(residual + hidden, aspec)
    residual = x
    hidden = norm_ops.apply_rms_norm(layer_params["post_attn_norm"], x, eps=cfg.rms_norm_eps)
    hidden = _mlp_block(layer_params["mlp"], hidden)
    x = shd.constrain(residual + hidden, aspec)
    if return_kv:
        return x, kv
    return x


def _remat_policy(granularity: Optional[str]):
    if granularity == "full":
        return jax.checkpoint_policies.nothing_saveable
    if granularity == "selective":
        # recompute the O(s^2) attention internals only — the reference's
        # activations_checkpoint_recompute: [CoreAttention]
        return jax.checkpoint_policies.save_anything_except_these_names(
            "attn_scores", "attn_probs"
        )
    return None


def hidden_states(
    params,
    input_ids: jax.Array,  # [batch, seq] (seq may be the per-CP-shard slice)
    cfg: LlamaConfig,
    policy: DtypePolicy,
    *,
    positions: Optional[jax.Array] = None,
    layers: Optional[Any] = None,  # override stacked layer params (pipeline stages)
    attention_mask: Optional[jax.Array] = None,  # [b, s] 1 = real token
    segment_ids: Optional[jax.Array] = None,  # [b, s] packed-record segments
) -> jax.Array:
    """Embedding + scanned decoder stack + final norm -> [batch, seq, hidden]."""
    aspec = shd.act_spec(cfg.sequence_parallel, cfg.context_parallel)
    x = linear_ops.apply_embedding(params["embed"], input_ids, compute_dtype=policy.compute_dtype)
    x = shd.constrain(x, aspec)

    if positions is None:
        # HF position_ids convention for padded batches (see positions_for);
        # packed chunks (segment_ids) reset RoPE phases per record
        positions = positions_for(input_ids, attention_mask, segment_ids)
    inv_freq = rope_ops.rope_frequencies(
        cfg.head_size,
        theta=cfg.rope_theta,
        position_interpolation_factor=cfg.rope_interpolation_factor,
    )
    cos, sin = rope_ops.rope_cos_sin(positions, inv_freq, dtype=jnp.float32)

    layer_stack = params["layers"] if layers is None else layers

    def body(carry, lp):
        # cast INSIDE the scan body (and remat boundary): only one layer's
        # bf16 copy is ever live, instead of a whole-stack bf16 duplicate —
        # ~2 bytes/param of HBM back under mixed precision
        lp = policy.cast_to_compute(lp)
        return _decoder_layer(lp, carry, cos, sin, cfg, policy,
                              attention_mask=attention_mask,
                              segment_ids=segment_ids), None

    remat = _remat_policy(cfg.activations_checkpoint_granularity)
    if remat is not None:
        body = jax.checkpoint(body, policy=remat, prevent_cse=False)
    x, _ = jax.lax.scan(body, x, layer_stack)
    return norm_ops.apply_rms_norm(params["final_norm"], x, eps=cfg.rms_norm_eps)


def logits_fn(params, hidden: jax.Array, cfg: LlamaConfig, policy: DtypePolicy) -> jax.Array:
    if cfg.tie_word_embeddings:
        w = params["embed"]["embedding"].astype(policy.compute_dtype)
        logits = hidden @ w.T
    else:
        logits = linear_ops.apply_linear(
            params["lm_head"], hidden, compute_dtype=policy.compute_dtype
        )
    return shd.constrain(logits, shd.logits_spec(cfg.context_parallel))


# ---------------------------------------------------------------------------
# pipeline-parallel hooks (parallel/pipeline.py contract)
# ---------------------------------------------------------------------------


def positions_for(input_ids: jax.Array, attention_mask=None,
                  segment_ids=None) -> jax.Array:
    """RoPE/absolute position ids [b, s]: plain arange, or — for padded
    batches — the HF convention of counting real tokens only
    (``cumsum(attention_mask) - 1``), keeping left-padded rows phase-aligned.
    ``segment_ids`` (packed chunks) reset positions at each record start so
    every packed record sees the RoPE phases it would see unpacked."""
    if segment_ids is not None:
        s = input_ids.shape[1]
        idx = jnp.arange(s, dtype=jnp.int32)[None, :]
        start = jnp.where(
            jnp.concatenate(
                [jnp.ones_like(segment_ids[:, :1], dtype=bool),
                 segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1),
            idx, 0,
        )
        # segments are contiguous runs: running max of start indices
        start = jax.lax.associative_scan(jnp.maximum, start, axis=1)
        return idx - start
    if attention_mask is not None:
        m = attention_mask.astype(jnp.int32)
        return jnp.clip(jnp.cumsum(m, axis=1) - 1, 0, None)
    positions = jnp.arange(input_ids.shape[1], dtype=jnp.int32)[None, :]
    return jnp.broadcast_to(positions, input_ids.shape)


def _rope_for(input_ids: jax.Array, cfg: LlamaConfig, positions=None):
    if positions is None:
        positions = positions_for(input_ids)
    inv_freq = rope_ops.rope_frequencies(
        cfg.head_size,
        theta=cfg.rope_theta,
        position_interpolation_factor=cfg.rope_interpolation_factor,
    )
    return rope_ops.rope_cos_sin(positions, inv_freq, dtype=jnp.float32)


def pipeline_hooks(cfg: LlamaConfig, policy: DtypePolicy, *, shift_labels: bool = True):
    """(embed_fn, stage_fn, loss_fn) for ``parallel.pipeline.pipeline_loss``.

    The decoder stack is the pipelined region; embedding and lm-head/loss run
    outside it (replicated over ``pipe``, still TP-sharded), replacing the
    reference's stage-0/stage-N module placement + ``run_train`` engine
    (``base.py:374-383``).
    """
    aspec = shd.act_spec(cfg.sequence_parallel, cfg.context_parallel)

    def embed_fn(params, mb):
        x = linear_ops.apply_embedding(
            params["embed"], mb["input_ids"], compute_dtype=policy.compute_dtype,
        )
        return shd.constrain(x, aspec)

    def stage_fn(local_layers, x, mb):
        cos, sin = _rope_for(mb["input_ids"], cfg)

        def body(carry, lp):
            # per-layer cast inside the scan: one layer's bf16 copy live at
            # a time (see forward())
            lp = policy.cast_to_compute(lp)
            return _decoder_layer(lp, carry, cos, sin, cfg, policy), None

        x, _ = jax.lax.scan(body, x, local_layers)
        return x

    def loss_fn(params, y, mb):
        h = norm_ops.apply_rms_norm(params["final_norm"], y, eps=cfg.rms_norm_eps)
        labels = mb["labels"]
        loss_mask = mb.get("loss_mask")
        head_plain = cfg.tie_word_embeddings or (
            "lm_head" in params and "lora_a" not in params["lm_head"]
        )
        if cfg.vocab_chunks and head_plain:
            # fused head+CE per microbatch: the [mb, s, vocab] logits never
            # materialize — this is where the 405B-class config needs it
            if shift_labels:
                h2, labels2 = h[:, :-1], labels[:, 1:]
                lm2 = None if loss_mask is None else loss_mask[:, 1:]
            else:
                h2, labels2, lm2 = h, labels, loss_mask
            head_w = (params["embed"]["embedding"].T
                      if cfg.tie_word_embeddings else params["lm_head"]["w"])
            loss_sum = ce_ops.chunked_cross_entropy_from_hidden(
                h2, head_w, labels2, num_chunks=cfg.vocab_chunks,
                loss_mask=lm2, reduction="sum",
            )
            valid = (labels2 != -100).astype(jnp.float32)
            if lm2 is not None:
                valid = valid * lm2.astype(jnp.float32)
            return loss_sum, jnp.sum(valid)
        logits = logits_fn(params, h, cfg, policy)
        if shift_labels:
            logits, labels, loss_mask = ce_ops.shift_for_next_token(
                logits, labels, loss_mask
            )
        loss_sum = ce_ops.cross_entropy_loss(
            logits, labels, loss_mask=loss_mask, reduction="sum"
        )
        valid = (labels != -100).astype(jnp.float32)
        if loss_mask is not None:
            valid = valid * loss_mask.astype(jnp.float32)
        return loss_sum, jnp.sum(valid)

    return embed_fn, stage_fn, loss_fn


def onef1b_head_hooks(cfg: LlamaConfig, policy: DtypePolicy):
    """Head wiring for ``parallel.pipeline.pipeline_loss_and_grad`` (1F1B).

    Returns ``(head_hidden_fn, head_params_of, head_weight_of, fold_grads)``:
    the hidden hook (final RMS norm), extractors for the head-param subtree
    and the [V, H] head matrix (tied embed or transposed ``lm_head.w`` —
    matching ``logits_fn``), and the folder that merges the 1F1B grad entries
    ``head_params``/``head_weight`` back into a params-shaped grad tree.
    Shared by the mixtral family (same top-level param layout, ``cfg.llama``).
    """
    tied = cfg.tie_word_embeddings

    def head_hidden_fn(hp, y):
        return norm_ops.apply_rms_norm(hp["final_norm"], y, eps=cfg.rms_norm_eps)

    def head_params_of(params):
        return {"final_norm": params["final_norm"]}

    def head_weight_of(params):
        w = (params["embed"]["embedding"] if tied else params["lm_head"]["w"].T)
        return w.astype(policy.compute_dtype)

    def fold_grads(grads, d_head_params, d_head_weight):
        grads = dict(grads)
        grads["final_norm"] = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(a.dtype),
            grads["final_norm"], d_head_params["final_norm"],
        )
        if tied:
            emb = grads["embed"]["embedding"]
            grads["embed"] = {
                **grads["embed"],
                "embedding": emb + d_head_weight.astype(emb.dtype),
            }
        else:
            w = grads["lm_head"]["w"]
            grads["lm_head"] = {
                **grads["lm_head"],
                "w": w + d_head_weight.T.astype(w.dtype),
            }
        return grads

    return head_hidden_fn, head_params_of, head_weight_of, fold_grads


def forward(
    params,
    batch: dict[str, jax.Array],
    cfg: LlamaConfig,
    policy: DtypePolicy,
    *,
    positions: Optional[jax.Array] = None,
    shift_labels: bool = True,
    return_logits: bool = False,
):
    """Full causal-LM forward -> (loss, aux).

    ``batch`` keys follow the reference's HF input_names contract:
    ``input_ids``, optional ``labels``, optional ``loss_mask``
    (``llama_model.py:94-101``).  Under CP, callers pre-shift labels on host and
    pass ``shift_labels=False`` (reference ``modeling_llama.py:815-823``).
    """
    input_ids = batch["input_ids"]
    attention_mask = batch.get("attention_mask")
    segment_ids = batch.get("segment_ids")
    hidden = hidden_states(params, input_ids, cfg, policy, positions=positions,
                           attention_mask=attention_mask,
                           segment_ids=segment_ids)
    labels = batch.get("labels")
    head_plain = cfg.tie_word_embeddings or (
        "lm_head" in params and "lora_a" not in params["lm_head"]
    )
    if (cfg.vocab_chunks and labels is not None and not return_logits
            and head_plain):  # an lm_head LoRA adapter needs apply_linear
        # fused head+CE: the [b, s, vocab] logits are never materialized
        # (see ce_ops.chunked_cross_entropy_from_hidden)
        loss_mask = batch.get("loss_mask")
        if attention_mask is not None:
            am = attention_mask.astype(jnp.float32)
            loss_mask = am if loss_mask is None else loss_mask * am
        if shift_labels:
            hidden = hidden[:, :-1]
            labels = labels[:, 1:]
            loss_mask = None if loss_mask is None else loss_mask[:, 1:]
        if cfg.tie_word_embeddings:
            head_w = params["embed"]["embedding"].T
        else:
            head_w = params["lm_head"]["w"]
        loss = ce_ops.chunked_cross_entropy_from_hidden(
            hidden, head_w, labels,
            num_chunks=cfg.vocab_chunks, loss_mask=loss_mask,
        )
        return loss, {}
    logits = logits_fn(params, hidden, cfg, policy)
    aux: dict[str, Any] = {}
    if return_logits:
        aux["logits"] = logits
    if labels is None:
        return logits, aux
    loss_mask = batch.get("loss_mask")
    if attention_mask is not None:
        # padded positions never contribute to the loss
        am = attention_mask.astype(jnp.float32)
        loss_mask = am if loss_mask is None else loss_mask * am
    if shift_labels:
        logits, labels, loss_mask = ce_ops.shift_for_next_token(logits, labels, loss_mask)
    loss = ce_ops.cross_entropy_loss(logits, labels, loss_mask=loss_mask)
    return loss, aux
