"""Mixtral-family decoder (MoE), TPU-native.

Functional re-design of the reference's ``models/hf_models/modeling_mixtral.py``
(893 LoC): Llama-style attention blocks (sliding-window causal) with the MLP
replaced by a routed mixture of SwiGLU experts, the router-logit threading that
feeds the load-balancing aux loss (reference ``modeling_mixtral.py:440-549``
threads ``past_router_logits`` through layers; here the scan carry accumulates
the per-layer aux loss directly, which is PP-friendly for the same reason), and
``router_aux_loss_coef`` scaling at the loss (``modeling_mixtral.py:872-878``).

Shares the attention/norm/rope machinery with ``models.llama`` — the decoder
differs only in the MLP slot.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.ops import cross_entropy as ce_ops
from neuronx_distributed_training_tpu.ops import linear as linear_ops
from neuronx_distributed_training_tpu.ops import moe as moe_ops
from neuronx_distributed_training_tpu.ops import norm as norm_ops
from neuronx_distributed_training_tpu.ops import rope as rope_ops
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    """Mixtral architecture = Llama knobs + MoE block + sliding window
    (reference ``mixtral_model.py:24-96``, ``hf_mixtral_8x7b_config.yaml``)."""

    llama: llama.LlamaConfig = dataclasses.field(default_factory=llama.LlamaConfig)
    moe: moe_ops.MoEConfig = dataclasses.field(default_factory=moe_ops.MoEConfig)
    moe_frequency: int = 1  # every Nth layer is MoE; 1 = all (Mixtral)

    # architecture passthroughs (perf estimation, data-module sizing)
    @property
    def vocab_size(self) -> int:
        return self.llama.vocab_size

    @property
    def hidden_size(self) -> int:
        return self.llama.hidden_size

    @property
    def intermediate_size(self) -> int:
        return self.llama.intermediate_size

    @property
    def num_layers(self) -> int:
        return self.llama.num_layers

    @property
    def num_attention_heads(self) -> int:
        return self.llama.num_attention_heads

    @property
    def num_kv_heads(self):
        return self.llama.num_kv_heads

    @classmethod
    def from_config(cls, model_cfg: dict[str, Any], ds_cfg: dict[str, Any] | None = None):
        m = dict(model_cfg or {})
        base = llama.LlamaConfig.from_config(m, ds_cfg)
        # Mixtral defaults that differ from Llama
        if m.get("sliding_window") is None and m.get("use_sliding_window", False):
            base = dataclasses.replace(base, sliding_window=4096)
        return cls(
            llama=base,
            moe=moe_ops.MoEConfig.from_config(m.get("moe", {})),
            moe_frequency=int(m.get("moe", {}).get("frequency", 1) or 1),
        )


def num_moe_layers(cfg: MixtralConfig) -> int:
    """Layer ``i`` is MoE iff ``i % moe_frequency == 0`` (reference
    ``modeling_mixtral.py:444-451``)."""
    f = cfg.moe_frequency
    if cfg.llama.num_layers % f != 0:
        raise ValueError(
            f"num_layers {cfg.llama.num_layers} must divide by moe "
            f"frequency {f}"
        )
    return cfg.llama.num_layers // f


def init_params(key: jax.Array, cfg: MixtralConfig, policy: DtypePolicy | None = None):
    """Llama skeleton with MoE MLPs every ``moe_frequency``-th layer.

    ``moe_frequency == 1`` (Mixtral proper): every layer's MLP is
    router+experts, stacked ``[L, ...]``.  ``> 1``: the stack is grouped as
    ``[L/f]`` groups of (1 MoE layer + f-1 dense layers); attention/norm
    params stay flat ``[L, ...]`` and ``layers.mlp`` becomes
    ``{"moe": [L/f, ...], "dense": [L/f, f-1, ...]}``.
    """
    policy = policy or DtypePolicy()
    dtype = policy.param_dtype
    lc = cfg.llama
    params = llama.init_params(key, lc, policy)

    def init_layer_moe(k):
        return moe_ops.init_moe_params(
            k, lc.hidden_size, lc.intermediate_size, cfg.moe,
            dtype=dtype, stddev=lc.initializer_range,
        )

    g = num_moe_layers(cfg)
    moe_keys = jax.random.split(jax.random.fold_in(key, 999), g)
    moe = jax.vmap(init_layer_moe)(moe_keys)
    if cfg.moe_frequency == 1:
        params["layers"]["mlp"] = moe
    else:
        f = cfg.moe_frequency
        dense = jax.tree_util.tree_map(
            lambda x: x.reshape((g, f) + x.shape[1:])[:, 1:],
            params["layers"]["mlp"],
        )
        params["layers"]["mlp"] = {"moe": moe, "dense": dense}
    return params


def param_specs(cfg: MixtralConfig, *, pipeline: bool = False):
    specs = llama.param_specs(cfg.llama, pipeline=pipeline)
    lead = "pipe" if pipeline else None
    moe_specs = jax.tree_util.tree_map(
        lambda s: P(*((lead,) + tuple(s))), moe_ops.moe_param_specs(cfg.moe),
        is_leaf=lambda x: isinstance(x, P),
    )
    if cfg.moe_frequency == 1:
        specs["layers"]["mlp"] = moe_specs
    else:
        # dense leaves gain the inner (f-1) group dim after the layer dim
        dense_specs = jax.tree_util.tree_map(
            lambda s: P(*((tuple(s)[0], None) + tuple(s)[1:])),
            specs["layers"]["mlp"],
            is_leaf=lambda x: isinstance(x, P),
        )
        specs["layers"]["mlp"] = {"moe": moe_specs, "dense": dense_specs}
    return specs


def _group_xs(cfg: MixtralConfig, layer_stack):
    """Grouped scan inputs (see ``ops.moe.group_interleaved_stack``)."""
    return moe_ops.group_interleaved_stack(cfg.moe_frequency, layer_stack)


def _grouped_scan(cfg: MixtralConfig, layer_stack, cos, sin, policy,
                  attention_mask=None, segment_ids=None):
    """(xs, body) for the dense/MoE interleave scan over [G] groups.

    Shared by ``forward`` and the pipeline ``stage_fn``: each group runs one
    MoE layer then ``f-1`` dense llama layers (see ``_group_xs``).
    """
    lc = cfg.llama
    xs = _group_xs(cfg, layer_stack)

    def body(carry, gp):
        x, aux_acc = carry
        # per-group cast inside the scan (one group's bf16 copy live at a time)
        x, aux = _decoder_layer(policy.cast_to_compute(gp["moe"]), x, cos, sin,
                                cfg, policy, attention_mask=attention_mask,
                                segment_ids=segment_ids)

        def dense_body(x2, dlp):
            return llama._decoder_layer(
                policy.cast_to_compute(dlp), x2, cos, sin, lc, policy,
                attention_mask=attention_mask, segment_ids=segment_ids,
            ), None

        x, _ = jax.lax.scan(dense_body, x, gp["dense"])
        return (x, aux_acc + aux), None

    return xs, body


def _decoder_layer(lp, x, cos, sin, cfg: MixtralConfig, policy: DtypePolicy,
                   attention_mask=None, segment_ids=None, return_kv=False):
    """Pre-LN attention + MoE block; returns (x, aux_loss[, (k, v)])."""
    lc = cfg.llama
    aspec = shd.act_spec(lc.sequence_parallel, lc.context_parallel)
    residual = x
    hidden = norm_ops.apply_rms_norm(lp["input_norm"], x, eps=lc.rms_norm_eps)
    hidden = llama._attention_block(lp["attn"], hidden, cos, sin, lc, policy,
                                    attention_mask=attention_mask,
                                    segment_ids=segment_ids,
                                    return_kv=return_kv)
    kv = None
    if return_kv:
        hidden, kv = hidden
    x = shd.constrain(residual + hidden, aspec)
    residual = x
    hidden = norm_ops.apply_rms_norm(lp["post_attn_norm"], x, eps=lc.rms_norm_eps)
    hidden, aux = moe_ops.moe_block(
        lp["mlp"], hidden, cfg.moe, compute_dtype=policy.compute_dtype
    )
    aux_loss = moe_ops.weighted_router_loss(aux["router_logits"], aux["expert_idx"], cfg.moe)
    x = shd.constrain(residual + hidden, aspec)
    if return_kv:
        return x, aux_loss, kv
    return x, aux_loss


def pipeline_hooks(cfg: MixtralConfig, policy: DtypePolicy, *,
                   shift_labels: bool = True):
    """(embed_fn, stage_fn, loss_fn) for ``parallel.pipeline.pipeline_loss``.

    ``stage_fn`` returns ``(x, aux)`` (use ``stage_aux=True``): the router
    aux-loss accumulates per stage and crosses pipe ranks as a psum'd scalar —
    the TPU-native form of the reference threading ``past_router_logits``
    through pipeline stages (``modeling_mixtral.py:440-549``).  The caller
    scales the psum'd total by ``1 / (num_microbatches * num_moe_layers(cfg))``
    (only router-bearing layers contribute).
    """
    lc = cfg.llama
    aspec = shd.act_spec(lc.sequence_parallel, lc.context_parallel)

    def embed_fn(params, mb):
        x = linear_ops.apply_embedding(
            params["embed"], mb["input_ids"], compute_dtype=policy.compute_dtype,
        )
        return shd.constrain(x, aspec)

    def stage_fn(local_layers, x, mb):
        cos, sin = llama._rope_for(mb["input_ids"], lc)
        ll = local_layers

        if cfg.moe_frequency == 1:

            def body(carry, lp):
                x, aux_acc = carry
                lp = policy.cast_to_compute(lp)  # per-layer cast (see llama)
                x, aux = _decoder_layer(lp, x, cos, sin, cfg, policy)
                return (x, aux_acc + aux), None

            xs = ll
        else:
            # grouped interleave on the LOCAL slice (see _grouped_scan)
            xs, body = _grouped_scan(cfg, ll, cos, sin, policy)

        (x, aux_sum), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), xs
        )
        return x, aux_sum

    def loss_fn(params, y, mb):
        h = norm_ops.apply_rms_norm(params["final_norm"], y, eps=lc.rms_norm_eps)
        logits = llama.logits_fn(params, h, lc, policy)
        labels = mb["labels"]
        loss_mask = mb.get("loss_mask")
        if shift_labels:
            logits, labels, loss_mask = ce_ops.shift_for_next_token(
                logits, labels, loss_mask
            )
        loss_sum = ce_ops.cross_entropy_loss(
            logits, labels, loss_mask=loss_mask, reduction="sum"
        )
        valid = (labels != -100).astype(jnp.float32)
        if loss_mask is not None:
            valid = valid * loss_mask.astype(jnp.float32)
        return loss_sum, jnp.sum(valid)

    return embed_fn, stage_fn, loss_fn


def onef1b_head_hooks(cfg: MixtralConfig, policy: DtypePolicy):
    """1F1B head wiring — identical top-level param layout to llama
    (embed / final_norm / optional lm_head), so delegate."""
    return llama.onef1b_head_hooks(cfg.llama, policy)


def forward(
    params,
    batch: dict[str, jax.Array],
    cfg: MixtralConfig,
    policy: DtypePolicy,
    *,
    shift_labels: bool = True,
    return_logits: bool = False,
):
    """Causal-LM forward -> (loss, aux).  Adds ``router_aux_loss_coef`` x mean
    per-layer load-balancing loss (reference ``modeling_mixtral.py:872-878``)."""
    lc = cfg.llama
    input_ids = batch["input_ids"]
    attention_mask = batch.get("attention_mask")
    segment_ids = batch.get("segment_ids")
    aspec = shd.act_spec(lc.sequence_parallel, lc.context_parallel)
    x = linear_ops.apply_embedding(
        params["embed"], input_ids, compute_dtype=policy.compute_dtype
    )
    x = shd.constrain(x, aspec)
    cos, sin = llama._rope_for(
        input_ids, lc,
        positions=llama.positions_for(input_ids, attention_mask, segment_ids)
    )
    layer_stack = params["layers"]
    remat = llama._remat_policy(lc.activations_checkpoint_granularity)

    if cfg.moe_frequency == 1:

        def body(carry, lp):
            x, aux_acc = carry
            lp = policy.cast_to_compute(lp)  # per-layer cast (see llama)
            x, aux = _decoder_layer(lp, x, cos, sin, cfg, policy,
                                    attention_mask=attention_mask,
                                    segment_ids=segment_ids)
            return (x, aux_acc + aux), None

        xs = layer_stack
    else:
        # grouped interleave: scan over [L/f] groups of (MoE + f-1 dense)
        xs, body = _grouped_scan(cfg, layer_stack, cos, sin, policy,
                                 attention_mask=attention_mask,
                                 segment_ids=segment_ids)

    if remat is not None:
        body = jax.checkpoint(body, policy=remat, prevent_cse=False)
    (x, aux_sum), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)), xs)
    hidden = norm_ops.apply_rms_norm(params["final_norm"], x, eps=lc.rms_norm_eps)
    logits = llama.logits_fn(params, hidden, lc, policy)

    # router_aux_loss is already coefficient-weighted (weighted_router_loss);
    # averaged over the layers that HAVE routers
    aux: dict[str, Any] = {"router_aux_loss": aux_sum / num_moe_layers(cfg)}
    if return_logits:
        aux["logits"] = logits
    labels = batch.get("labels")
    if labels is None:
        return logits, aux
    loss_mask = batch.get("loss_mask")
    if attention_mask is not None:
        am = attention_mask.astype(jnp.float32)
        loss_mask = am if loss_mask is None else loss_mask * am
    if shift_labels:
        logits, labels, loss_mask = ce_ops.shift_for_next_token(logits, labels, loss_mask)
    lm_loss = ce_ops.cross_entropy_loss(logits, labels, loss_mask=loss_mask)
    loss = lm_loss + aux["router_aux_loss"]
    aux["lm_loss"] = lm_loss
    return loss, aux
