"""Core sharded ops: parallel linears/embedding, norms, RoPE, attention, losses."""
