"""Attention ops.

``core_attention`` is the numerics-reference implementation, the counterpart of
the reference's ``CoreAttention`` (naive attention, causal mask, fp32 softmax —
``modeling_llama.py:226-251``).  ``attention`` dispatches between it and the
Pallas flash/ring kernels the same way the reference dispatches
``nki_flash_attn_func`` / ``nki_ring_attn_func`` / ``CoreAttention``
(``modeling_llama.py:482-489``), controlled by the ``fusions`` config block.

Layout is ``[batch, seq, heads, head_dim]`` throughout (the TPU-friendly layout;
the reference's ``transpose_nki_inputs`` permutation concern disappears because
Pallas block specs handle layout inside the kernel).

GQA: K/V carry ``kv_heads`` heads and are repeated to ``heads`` on the fly.
For the GSPMD core/flash paths the reference's ``kv_shared_group_size`` KV
replication trick (``modeling_llama.py:310-320``) is unnecessary — XLA
replicates KV shards from the specs when ``tp > kv_heads``.  The explicit
shard_map ring path implements the replication itself (see
``parallel.ring_attention``).
"""

from __future__ import annotations

from typing import Optional

import logging

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

logger = logging.getLogger(__name__)
_warned: set = set()


def _warn_fallback(impl: str) -> None:
    if impl not in _warned:
        _warned.add(impl)
        logger.warning(
            "%s attention kernel unavailable; falling back to core attention", impl
        )


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[b, s, kv_heads, d] -> [b, s, kv_heads * n_rep, d]."""
    if n_rep == 1:
        return x
    b, s, kvh, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, kvh, n_rep, d))
    return x.reshape(b, s, kvh * n_rep, d)


def causal_mask_bias(
    q_len: int,
    kv_len: int,
    *,
    q_offset: int = 0,
    sliding_window: Optional[int] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Additive attention bias ``[q_len, kv_len]``: 0 where visible, large
    negative where masked.  ``q_offset`` is the absolute position of query row 0
    (used by context parallelism).  ``sliding_window`` adds the Mixtral-style
    window mask (reference ``modeling_mixtral.py:145-148``)."""
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    kv_pos = jnp.arange(kv_len)[None, :]
    visible = kv_pos <= q_pos
    if sliding_window is not None:
        visible = visible & (kv_pos > q_pos - sliding_window)
    # -10000-style finite fill like the reference (modeling_llama.py:226-251)
    # is unnecessary; use a dtype-safe large negative.
    neg = jnp.asarray(jnp.finfo(dtype).min / 2, dtype)
    return jnp.where(visible, jnp.asarray(0, dtype), neg)


def core_attention(
    q: jax.Array,  # [b, sq, h, d]
    k: jax.Array,  # [b, skv, kvh, d]
    v: jax.Array,  # [b, skv, kvh, d]
    *,
    causal: bool = True,
    q_offset: int = 0,
    sliding_window: Optional[int] = None,
    bias: Optional[jax.Array] = None,
    softmax_dtype=jnp.float32,
) -> jax.Array:
    """Naive attention with fp32 (configurable) softmax; the numerics gate for
    the Pallas kernels."""
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        k = repeat_kv(k, h // kvh)
        v = repeat_kv(v, h // kvh)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, softmax_dtype))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=softmax_dtype)
    scores = scores.astype(softmax_dtype) * scale
    if causal:
        scores = scores + causal_mask_bias(
            sq, k.shape[1], q_offset=q_offset, sliding_window=sliding_window, dtype=softmax_dtype
        )
    if bias is not None:
        scores = scores + bias.astype(softmax_dtype)
    # Tag the O(s^2) internals so the "selective" remat policy recomputes them
    # in backward instead of saving them (the reference's
    # activations_checkpoint_recompute: [CoreAttention]).
    scores = checkpoint_name(scores, "attn_scores")
    probs = jax.nn.softmax(scores, axis=-1)
    probs = checkpoint_name(probs, "attn_probs")
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def padding_mask_bias(attention_mask: jax.Array, dtype=jnp.float32) -> jax.Array:
    """``attention_mask`` [b, skv] (1 = real token) -> additive bias
    [b, 1, 1, skv] masking padded KEYS (the HF contract; reference
    ``llama_model.py:94-101`` includes ``attention_mask`` in input_names)."""
    neg = jnp.asarray(jnp.finfo(dtype).min / 2, dtype)
    bias = jnp.where(attention_mask.astype(bool), jnp.asarray(0, dtype), neg)
    return bias[:, None, None, :]


def segment_mask_bias(segment_ids: jax.Array, dtype=jnp.float32) -> jax.Array:
    """``segment_ids`` [b, s] -> additive bias [b, 1, s, s] restricting
    attention to same-segment (packed-record) pairs — the numerics reference
    for the flash kernel's block-diagonal segment mask."""
    neg = jnp.asarray(jnp.finfo(dtype).min / 2, dtype)
    same = segment_ids[:, :, None] == segment_ids[:, None, :]
    return jnp.where(same, jnp.asarray(0, dtype), neg)[:, None, :, :]


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "core",  # "core" | "flash" | "ring" | "ulysses"
    causal: bool = True,
    q_offset: int = 0,
    sliding_window: Optional[int] = None,
    softmax_dtype=jnp.float32,
    attention_mask: Optional[jax.Array] = None,  # [b, skv] 1 = attend
    segment_ids: Optional[jax.Array] = None,  # [b, s] packed-record segments
    block_q: Optional[int] = None,   # Pallas flash tile sizes (None = default;
    block_kv: Optional[int] = None,  # a per-chip tuning knob, fusions.flash_block_*)
) -> jax.Array:
    """Dispatch mirroring the reference's flash/ring/Core selection
    (``modeling_llama.py:482-489``).  Falls back to ``core_attention`` (with a
    one-time warning) if the requested kernel is unavailable, so reference
    configs with ``fusions.flash_attention: true`` still run.

    ``attention_mask`` (padded KEYS, the HF contract) is supported in-kernel
    by the flash, ring, ulysses, and core paths — padded SFT/DPO batches stay
    on the O(seq)-memory kernels (the reference runs its NKI flash kernel on
    ``attention_mask`` batches too, ``llama_model.py:94-101``).  Only
    zigzag_ring rejects it: the batch is zig-zag permuted and a key-position
    mask would be wrong in that layout."""
    if attention_mask is not None and impl == "zigzag_ring":
        # a core fallback would be WRONG here (the batch is zig-zag permuted
        # and core's causal mask assumes contiguous order) — so raise
        raise ValueError(
            "zigzag_ring does not support attention_mask (padded batches); "
            "use fusions.ring_attention"
        )
    if segment_ids is not None and impl in ("ring", "ulysses", "zigzag_ring"):
        # the CP bodies don't implement the block-diagonal segment mask;
        # a silent core fallback would defeat the CP memory bound — raise
        raise ValueError(
            f"segment_ids (packed-sequence masking) is supported by the "
            f"flash and core paths only, not {impl!r}"
        )
    if impl == "flash":
        try:
            from neuronx_distributed_training_tpu.ops.flash_attention import flash_attention
        except ImportError:
            _warn_fallback("flash")
        else:
            return flash_attention(
                q, k, v, causal=causal, sliding_window=sliding_window,
                q_offset=q_offset, attention_mask=attention_mask,
                segment_ids=segment_ids, block_q=block_q, block_kv=block_kv,
            )
    if impl == "ring":
        try:
            from neuronx_distributed_training_tpu.parallel.ring_attention import ring_attention
        except ImportError:
            _warn_fallback("ring")
        else:
            if q_offset:
                raise ValueError(
                    "ring attention derives global positions from the mesh; "
                    "an explicit q_offset is not meaningful here"
                )
            return ring_attention(
                q, k, v, causal=causal, sliding_window=sliding_window,
                block_kv=block_kv or 512, attention_mask=attention_mask,
            )
    if impl == "ulysses":
        try:
            from neuronx_distributed_training_tpu.parallel.ulysses import ulysses_attention
        except ImportError:
            _warn_fallback("ulysses")
        else:
            if q_offset:
                raise ValueError(
                    "ulysses attention derives global positions from the mesh; "
                    "an explicit q_offset is not meaningful here"
                )
            return ulysses_attention(
                q, k, v, causal=causal, sliding_window=sliding_window,
                block_kv=block_kv or 512, attention_mask=attention_mask,
            )
    if impl == "zigzag_ring":
        from neuronx_distributed_training_tpu.parallel.ring_attention import (
            zigzag_ring_attention,
        )

        if q_offset:
            raise ValueError(
                "zigzag ring derives positions from the layout; an explicit "
                "q_offset is not meaningful here"
            )
        if sliding_window is not None:
            raise ValueError(
                "zigzag ring does not support sliding_window; use "
                "ring_attention (contiguous layout) for windowed models"
            )
        return zigzag_ring_attention(q, k, v, causal=causal)
    bias = None
    if attention_mask is not None:
        bias = padding_mask_bias(attention_mask, softmax_dtype)
    if segment_ids is not None:
        seg_bias = segment_mask_bias(segment_ids, softmax_dtype)
        bias = seg_bias if bias is None else bias + seg_bias
    return core_attention(
        q,
        k,
        v,
        causal=causal,
        q_offset=q_offset,
        sliding_window=sliding_window,
        bias=bias,
        softmax_dtype=softmax_dtype,
    )
