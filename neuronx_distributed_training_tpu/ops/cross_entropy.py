"""Vocab-parallel cross-entropy.

The reference computes the loss on vocab-sharded logits with NxD's
``parallel_cross_entropy`` (reference ``modeling_llama.py:79,825-833``,
``gpt_model.py:34-67``) — an explicit max/sum all-reduce over the TP group.
Under GSPMD the same program falls out of a plain stable cross-entropy written
with full-axis reductions over the (sharded) vocab dim: XLA partitions the
reductions and inserts the TP collectives.  The label-logit gather is expressed
as a masked sum (iota == label) so it partitions cleanly instead of becoming a
cross-shard gather.

Also provides ``logprobs_from_logits`` — the vocab-parallel log-prob helper DPO
needs (reference ``from_parallel_logits_to_logprobs``, ``base_dpo.py:34-46``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _label_logit_and_lse(logits: jax.Array, labels: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-token (label_logit, logsumexp) in fp32. logits [..., vocab], labels [...]."""
    logits = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = logits - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + jnp.squeeze(m, -1)
    vocab = logits.shape[-1]
    onehot_mask = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1) == labels[
        ..., None
    ]
    label_logit = jnp.sum(jnp.where(onehot_mask, logits, 0.0), axis=-1)
    return label_logit, lse


def cross_entropy_loss(
    logits: jax.Array,  # [batch, seq, vocab] (vocab may be sharded over "model")
    labels: jax.Array,  # [batch, seq] int; ignore_index entries masked out
    *,
    loss_mask: Optional[jax.Array] = None,  # [batch, seq] {0,1}
    ignore_index: int = -100,
    reduction: str = "mean",  # "mean" | "sum" | "none"
) -> jax.Array:
    """Stable CE over (possibly sharded) vocab; masked mean over valid tokens."""
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    label_logit, lse = _label_logit_and_lse(logits, safe_labels)
    per_tok = lse - label_logit
    mask = valid.astype(jnp.float32)
    if loss_mask is not None:
        mask = mask * loss_mask.astype(jnp.float32)
    per_tok = per_tok * mask
    if reduction == "none":
        return per_tok
    total = jnp.sum(per_tok)
    if reduction == "sum":
        return total
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom


def shift_for_next_token(
    logits: jax.Array, labels: jax.Array, loss_mask: Optional[jax.Array] = None
):
    """Standard causal-LM shift: predict token t+1 from position t.

    Context-parallel runs pre-shift labels on the host instead and skip this
    (reference ``modeling_llama.py:815-823``)."""
    shifted_logits = logits[:, :-1, :]
    shifted_labels = labels[:, 1:]
    shifted_mask = None if loss_mask is None else loss_mask[:, 1:]
    return shifted_logits, shifted_labels, shifted_mask


def logprobs_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-token log p(label) from (sharded) logits — the DPO/ORPO helper
    (reference ``from_parallel_logits_to_logprobs``, ``base_dpo.py:34-46``)."""
    label_logit, lse = _label_logit_and_lse(logits, labels)
    return label_logit - lse


def chunked_cross_entropy_from_hidden(
    hidden: jax.Array,   # [batch, seq, h] (compute dtype)
    head_w: jax.Array,   # [h, vocab] lm-head weight (tied: embedding.T)
    labels: jax.Array,   # [batch, seq]
    *,
    num_chunks: int = 8,
    loss_mask: Optional[jax.Array] = None,
    ignore_index: int = -100,
    reduction: str = "mean",
) -> jax.Array:
    """CE fused with the lm-head matmul, scanned over vocab chunks.

    Never materializes the full ``[batch, seq, vocab]`` logits: each scan step
    computes one ``[batch, seq, vocab/num_chunks]`` block, folds it into an
    online logsumexp, and is rematerialized in backward (``jax.checkpoint``) —
    peak activation memory drops from O(s·V) to O(s·V/num_chunks) at the cost
    of one extra head-matmul pass in backward (~1/(3·num_layers) of step
    FLOPs).  The memory lever for 128k-vocab models at long seq (the
    405B-class config) and for PP loss hooks, opt-in via
    ``model.fusions.chunked_ce``.

    Note: designed for the unsharded-vocab case; under vocab-parallel TP the
    standard ``cross_entropy_loss`` already partitions its reductions cleanly.
    """
    v = head_w.shape[-1]
    if v % num_chunks != 0:
        raise ValueError(f"vocab {v} not divisible by num_chunks {num_chunks}")
    vc = v // num_chunks
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)

    # static chunk layout: scan consumes [num_chunks, h, vc] as xs, so the
    # partitioner sees analyzable slices (a traced dynamic_slice over a
    # vocab-sharded weight would force a full all-gather per step)
    w_chunks = jnp.moveaxis(
        head_w.reshape(head_w.shape[0], num_chunks, vc), 1, 0
    )

    def body(carry, xs):
        c, w_c = xs
        m, l, label_logit = carry
        logits_c = (hidden @ w_c.astype(hidden.dtype)).astype(jnp.float32)
        m_c = jax.lax.stop_gradient(jnp.max(logits_c, axis=-1))
        m_new = jnp.maximum(m, m_c)
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits_c - m_new[..., None]), axis=-1
        )
        in_chunk = jax.lax.broadcasted_iota(
            jnp.int32, logits_c.shape, logits_c.ndim - 1
        ) == (safe_labels - c * vc)[..., None]
        label_logit = label_logit + jnp.sum(
            jnp.where(in_chunk, logits_c, 0.0), axis=-1
        )
        return (m_new, l, label_logit), None

    b, s = labels.shape
    init = (
        jnp.full((b, s), -jnp.inf, jnp.float32),
        jnp.zeros((b, s), jnp.float32),
        jnp.zeros((b, s), jnp.float32),
    )
    (m, l, label_logit), _ = jax.lax.scan(
        jax.checkpoint(body), init, (jnp.arange(num_chunks), w_chunks)
    )
    per_tok = (m + jnp.log(l)) - label_logit
    mask = valid.astype(jnp.float32)
    if loss_mask is not None:
        mask = mask * loss_mask.astype(jnp.float32)
    per_tok = per_tok * mask
    if reduction == "none":
        return per_tok
    total = jnp.sum(per_tok)
    if reduction == "sum":
        return total
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom
