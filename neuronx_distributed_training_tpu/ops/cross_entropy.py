"""Vocab-parallel cross-entropy.

The reference computes the loss on vocab-sharded logits with NxD's
``parallel_cross_entropy`` (reference ``modeling_llama.py:79,825-833``,
``gpt_model.py:34-67``) — an explicit max/sum all-reduce over the TP group.
Under GSPMD the same program falls out of a plain stable cross-entropy written
with full-axis reductions over the (sharded) vocab dim: XLA partitions the
reductions and inserts the TP collectives.  The label-logit gather is expressed
as a masked sum (iota == label) so it partitions cleanly instead of becoming a
cross-shard gather.

Also provides ``logprobs_from_logits`` — the vocab-parallel log-prob helper DPO
needs (reference ``from_parallel_logits_to_logprobs``, ``base_dpo.py:34-46``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _label_logit_and_lse(logits: jax.Array, labels: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-token (label_logit, logsumexp) in fp32. logits [..., vocab], labels [...]."""
    logits = logits.astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = logits - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + jnp.squeeze(m, -1)
    vocab = logits.shape[-1]
    onehot_mask = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1) == labels[
        ..., None
    ]
    label_logit = jnp.sum(jnp.where(onehot_mask, logits, 0.0), axis=-1)
    return label_logit, lse


def cross_entropy_loss(
    logits: jax.Array,  # [batch, seq, vocab] (vocab may be sharded over "model")
    labels: jax.Array,  # [batch, seq] int; ignore_index entries masked out
    *,
    loss_mask: Optional[jax.Array] = None,  # [batch, seq] {0,1}
    ignore_index: int = -100,
    reduction: str = "mean",  # "mean" | "sum" | "none"
) -> jax.Array:
    """Stable CE over (possibly sharded) vocab; masked mean over valid tokens."""
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    label_logit, lse = _label_logit_and_lse(logits, safe_labels)
    per_tok = lse - label_logit
    mask = valid.astype(jnp.float32)
    if loss_mask is not None:
        mask = mask * loss_mask.astype(jnp.float32)
    per_tok = per_tok * mask
    if reduction == "none":
        return per_tok
    total = jnp.sum(per_tok)
    if reduction == "sum":
        return total
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom


def shift_for_next_token(
    logits: jax.Array, labels: jax.Array, loss_mask: Optional[jax.Array] = None
):
    """Standard causal-LM shift: predict token t+1 from position t.

    Context-parallel runs pre-shift labels on the host instead and skip this
    (reference ``modeling_llama.py:815-823``)."""
    shifted_logits = logits[:, :-1, :]
    shifted_labels = labels[:, 1:]
    shifted_mask = None if loss_mask is None else loss_mask[:, 1:]
    return shifted_logits, shifted_labels, shifted_mask


def logprobs_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-token log p(label) from (sharded) logits — the DPO/ORPO helper
    (reference ``from_parallel_logits_to_logprobs``, ``base_dpo.py:34-46``)."""
    label_logit, lse = _label_logit_and_lse(logits, labels)
    return label_logit - lse
