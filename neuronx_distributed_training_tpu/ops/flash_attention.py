"""Pallas flash attention (fwd + custom-vjp bwd) for TPU.

The TPU-native replacement for the reference's NKI flash-attention kernel
(``neuronx_distributed.kernels.flash_attn``, called at reference
``modeling_llama.py:70,486`` behind the ``fusions.flash_attention`` YAML flag).
Online-softmax blockwise attention: O(seq) memory instead of the O(seq^2)
score/prob materialization of ``core_attention``, with the backward pass
recomputing probabilities per block (no saved probs at all — strictly better
than the reference's "selective recompute of CoreAttention").

Design notes (see /opt/skills/guides/pallas_guide.md):
- grid = (batch, q_heads, q_blocks, kv_blocks); the kv dimension is innermost
  and sequential ("arbitrary"), carrying the online-softmax state (m, l, acc)
  in VMEM scratch across kv steps.
- causality is exploited at block granularity: fully-masked kv blocks are
  predicated off with ``pl.when`` (the MXU never sees them), matching the
  2x FLOP saving the reference's kernel gets from causal masking.
- GQA: the kv BlockSpec index-maps query-head ``h`` -> kv-head
  ``h // (nh // nkv)`` so K/V are never physically repeated (the reference
  replicates KV via ``kv_shared_group_size`` instead — unnecessary here).
- backward: two kernels (dq with kv innermost; dkv with q innermost), both
  recomputing p = exp(s - lse) from the saved logsumexp, FlashAttention-2
  style.  dk/dv are produced per KV-head: the GQA q-head group is a sequential
  grid dim accumulated in fp32 VMEM scratch.

Layout contract matches ``core_attention``: q [b, sq, nh, d], k/v
[b, skv, nkv, d], output [b, sq, nh, d].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the TPU compiler-params dataclass was renamed TPUCompilerParams ->
# CompilerParams across pallas versions; accept either spelling
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

LANES = 128  # TPU lane width; scratch minor dims and block sizes align to it
SUBLANES = 8  # minor dim for per-row stats (lse/delta): the smallest legal
# Mosaic block minor dim — 16x less HBM than a full 128-lane broadcast
DEFAULT_BLOCK_Q = 512
# v5e sweep (Llama-3-8B layer shapes, seq 8192, 2026-07-30, recorded in
# bench_results/r2_v5e_measured.jsonl): kv 2048 beats 512 by ~3 MFU points in
# both regimes (68.3->71.4 bf16, 64.0->66.6 mixed); 4096 fails to fit.  Larger
# KV blocks amortize the q-block revisit cost; still a per-chip knob via
# fusions.flash_block_kv.
DEFAULT_BLOCK_KV = 2048
NEG_INF = -1e30


def _block_sizes(sq: int, skv: int, bq: Optional[int], bkv: Optional[int]):
    bq = bq or min(DEFAULT_BLOCK_Q, sq)
    bkv = bkv or min(DEFAULT_BLOCK_KV, skv)
    while sq % bq:
        bq //= 2
    while skv % bkv:
        bkv //= 2
    return max(bq, 1), max(bkv, 1)


def _tileable(sq: int, skv: int, d: int, bq: int, bkv: int) -> bool:
    return (
        sq % bq == 0
        and skv % bkv == 0
        and bq % LANES == 0
        and bkv % LANES == 0
        and d % LANES == 0
    )


def _visible(qi, ki, bq, bkv, causal: bool, window: Optional[int], q_offset: int):
    """Block-level visibility predicate (trace-time on program ids)."""
    q_lo = qi * bq + q_offset
    q_hi = q_lo + bq - 1
    kv_lo = ki * bkv
    kv_hi = kv_lo + bkv - 1
    vis = jnp.bool_(True)
    if causal:
        vis = jnp.logical_and(vis, kv_lo <= q_hi)
    if window is not None:
        vis = jnp.logical_and(vis, kv_hi > q_lo - window)
    return vis


def _inner_mask(bq, bkv, qi, ki, causal, window, q_offset):
    """Within-block additive mask [bq, bkv] (0 / NEG_INF)."""
    if not causal and window is None:
        return None
    q_pos = q_offset + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
    kv_pos = ki * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
    ok = jnp.bool_(True)
    if causal:
        ok = jnp.logical_and(ok, kv_pos <= q_pos)
    if window is not None:
        ok = jnp.logical_and(ok, kv_pos > q_pos - window)
    return jnp.where(ok, 0.0, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, *refs,
    sm_scale, causal, window, q_offset, bq, bkv, num_kv, masked, segmented,
):
    refs = list(refs)
    kvm_ref = refs.pop(0) if masked else None
    segq_ref = refs.pop(0) if segmented else None
    segk_ref = refs.pop(0) if segmented else None
    o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    vis = _visible(qi, ki, bq, bkv, causal, window, q_offset)
    if kvm_ref is not None:
        # skip kv blocks that are entirely padding (long pad tails cost 0 MXU)
        vis = jnp.logical_and(vis, jnp.any(kvm_ref[...] > 0))
    if segq_ref is not None:
        # packed-chunk segments are contiguous non-decreasing runs: a kv
        # block strictly ahead of every query segment can't match anything
        vis = jnp.logical_and(vis, jnp.min(segk_ref[...]) <= jnp.max(segq_ref[...]))

    @pl.when(vis)
    def _compute():
        q = q_ref[0, 0]  # [bq, d]
        k = k_ref[0, 0]  # [bkv, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale
        mask = _inner_mask(bq, bkv, qi, ki, causal, window, q_offset)
        if mask is not None:
            s = s + mask
        if kvm_ref is not None:
            # padded KEYS masked (the HF attention_mask contract) — [1, bkv]
            # broadcasts over query rows
            s = jnp.where(kvm_ref[...] > 0, s, NEG_INF)
        if segq_ref is not None:
            # block-diagonal packed-sequence mask: attend only within the
            # same segment ([bq, 1] vs [1, bkv] broadcast)
            s = jnp.where(
                segq_ref[...].reshape(-1, 1) == segk_ref[...].reshape(1, -1),
                s, NEG_INF,
            )
        m_prev = m_scr[:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        p = jnp.exp(s - m_new)  # [bq, bkv]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = alpha * acc_scr[:] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_kv - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        # a row with NO visible key anywhere keeps m ~= NEG_INF: its p values
        # were exp(s - m) over masked-only scores (garbage, since the finite
        # NEG_INF cancels) -> force output 0 and lse = NEG_INF.  Rows masked in
        # one block but visible in another self-correct via alpha rescaling.
        row_visible = m_scr[:, :1] > NEG_INF / 2
        o_ref[0, 0] = jnp.where(
            row_visible, acc_scr[:] / l_safe, 0.0
        ).astype(o_ref.dtype)
        lse = jnp.where(row_visible, m_scr[:, :1] + jnp.log(l_safe), NEG_INF)
        lse_ref[0, 0] = jnp.broadcast_to(lse, (lse.shape[0], SUBLANES))


def _fwd_pallas(q, k, v, kvm, seg, *, sm_scale, causal, window, q_offset, bq, bkv,
                interpret):
    """q [b, nh, sq, d]; k/v [b, nkv, skv, d]; kvm None or [b, skv] int32
    (1 = real key); seg None or [b, s] int32 segment ids (self-attention
    packed chunks) -> (o [b, nh, sq, d], lse [b, nh, sq, SUBLANES])."""
    b, nh, sq, d = q.shape
    nkv, skv = k.shape[1], k.shape[2]
    group = nh // nkv
    num_q, num_kv = sq // bq, skv // bkv

    grid = (b, nh, num_q, num_kv)
    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale, causal=causal, window=window, q_offset=q_offset,
        bq=bq, bkv=bkv, num_kv=num_kv, masked=kvm is not None,
        segmented=seg is not None,
    )
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
        pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
    ]
    in_arrays = [q, k, v]
    if kvm is not None:
        in_specs.append(pl.BlockSpec((1, bkv), lambda bi, hi, qi, ki: (bi, ki)))
        in_arrays.append(kvm)
    if seg is not None:
        # same [b, s] array read twice: query rows and key cols
        in_specs.append(pl.BlockSpec((1, bq), lambda bi, hi, qi, ki: (bi, qi)))
        in_arrays.append(seg)
        in_specs.append(pl.BlockSpec((1, bkv), lambda bi, hi, qi, ki: (bi, ki)))
        in_arrays.append(seg)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, SUBLANES), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, nh, sq, SUBLANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*in_arrays)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
    sm_scale, causal, window, q_offset, bq, bkv, num_kv, masked, segmented,
):
    refs = list(refs)
    kvm_ref = refs.pop(0) if masked else None
    segq_ref = refs.pop(0) if segmented else None
    segk_ref = refs.pop(0) if segmented else None
    dq_ref, acc_scr = refs
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    vis = _visible(qi, ki, bq, bkv, causal, window, q_offset)
    if kvm_ref is not None:
        vis = jnp.logical_and(vis, jnp.any(kvm_ref[...] > 0))
    if segq_ref is not None:
        vis = jnp.logical_and(vis, jnp.min(segk_ref[...]) <= jnp.max(segq_ref[...]))

    @pl.when(vis)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        mask = _inner_mask(bq, bkv, qi, ki, causal, window, q_offset)
        if mask is not None:
            s = s + mask
        if kvm_ref is not None:
            # re-apply the key padding mask — p must be 0 on padded keys or
            # dq leaks gradient through them
            s = jnp.where(kvm_ref[...] > 0, s, NEG_INF)
        if segq_ref is not None:
            s = jnp.where(
                segq_ref[...].reshape(-1, 1) == segk_ref[...].reshape(1, -1),
                s, NEG_INF,
            )
        # rows with no visible key anywhere carry lse = NEG_INF; exp(s - lse)
        # would be garbage there, so zero them (matches fwd's 0 output)
        p = jnp.where(lse > NEG_INF / 2, jnp.exp(s - lse), 0.0)  # [bq, bkv]
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        # keep ds in fp32 for the dq matmul — same accumulation precision as
        # the dk/dv path (a bf16 downcast here systematically biases dq)
        acc_scr[:] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_kv - 1)
    def _finish():
        dq_ref[0, 0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
    sm_scale, causal, window, q_offset, bq, bkv, num_q, group, masked, segmented,
):
    refs = list(refs)
    kvm_ref = refs.pop(0) if masked else None
    segq_ref = refs.pop(0) if segmented else None
    segk_ref = refs.pop(0) if segmented else None
    dk_ref, dv_ref, dk_scr, dv_scr = refs
    ki = pl.program_id(2)
    g = pl.program_id(3)
    qi = pl.program_id(4)

    @pl.when(jnp.logical_and(g == 0, qi == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    vis = _visible(qi, ki, bq, bkv, causal, window, q_offset)
    if kvm_ref is not None:
        vis = jnp.logical_and(vis, jnp.any(kvm_ref[...] > 0))
    if segq_ref is not None:
        vis = jnp.logical_and(vis, jnp.min(segk_ref[...]) <= jnp.max(segq_ref[...]))

    @pl.when(vis)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        mask = _inner_mask(bq, bkv, qi, ki, causal, window, q_offset)
        if mask is not None:
            s = s + mask
        if kvm_ref is not None:
            s = jnp.where(kvm_ref[...] > 0, s, NEG_INF)
        if segq_ref is not None:
            s = jnp.where(
                segq_ref[...].reshape(-1, 1) == segk_ref[...].reshape(1, -1),
                s, NEG_INF,
            )
        p = jnp.where(lse > NEG_INF / 2, jnp.exp(s - lse), 0.0)  # [bq, bkv]
        # dv += p^T @ do
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale  # [bq, bkv]
        # dk += ds^T @ q
        dk_scr[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jnp.logical_and(g == group - 1, qi == num_q - 1))
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_pallas(res, g, *, sm_scale, causal, window, q_offset, bq, bkv, interpret,
                dlse=None):
    q, k, v, kvm, seg, o, lse = res  # q [b, nh, sq, d]; k/v [b, nkv, skv, d]
    b, nh, sq, d = q.shape
    nkv, skv = k.shape[1], k.shape[2]
    group = nh // nkv
    num_q, num_kv = sq // bq, skv // bkv

    do = g.astype(jnp.float32)
    delta = jnp.sum(do * o.astype(jnp.float32), axis=-1)  # [b, nh, sq]
    if dlse is not None:
        # lse exposed as a differentiable output (ring merge): d lse / d s = p,
        # so ds = p*(dp - delta + dlse) — fold dlse into the delta operand
        delta = delta - dlse
    delta = jnp.broadcast_to(delta[..., None], (b, nh, sq, SUBLANES))

    common = dict(sm_scale=sm_scale, causal=causal, window=window, q_offset=q_offset,
                  bq=bq, bkv=bkv, masked=kvm is not None,
                  segmented=seg is not None)
    in_arrays = (q, k, v, g, lse, delta) + ((kvm,) if kvm is not None else ())
    if seg is not None:
        in_arrays = in_arrays + (seg, seg)

    dq_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
        pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
        pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bq, SUBLANES), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bq, SUBLANES), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
    ]
    if kvm is not None:
        dq_specs.append(pl.BlockSpec((1, bkv), lambda bi, hi, qi, ki: (bi, ki)))
    if seg is not None:
        dq_specs.append(pl.BlockSpec((1, bq), lambda bi, hi, qi, ki: (bi, qi)))
        dq_specs.append(pl.BlockSpec((1, bkv), lambda bi, hi, qi, ki: (bi, ki)))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, num_kv=num_kv, **common),
        grid=(b, nh, num_q, num_kv),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, nh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*in_arrays)

    # dk/dv per KV-head: the q-head group is a sequential grid dim, accumulated
    # in the fp32 VMEM scratch — 1x HBM writes and no bf16 intermediate in the
    # GQA group sum.
    dkv_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda bi, kh, ki, g, qi: (bi, kh * group + g, qi, 0)),
        pl.BlockSpec((1, 1, bkv, d), lambda bi, kh, ki, g, qi: (bi, kh, ki, 0)),
        pl.BlockSpec((1, 1, bkv, d), lambda bi, kh, ki, g, qi: (bi, kh, ki, 0)),
        pl.BlockSpec((1, 1, bq, d), lambda bi, kh, ki, g, qi: (bi, kh * group + g, qi, 0)),
        pl.BlockSpec((1, 1, bq, SUBLANES), lambda bi, kh, ki, g, qi: (bi, kh * group + g, qi, 0)),
        pl.BlockSpec((1, 1, bq, SUBLANES), lambda bi, kh, ki, g, qi: (bi, kh * group + g, qi, 0)),
    ]
    if kvm is not None:
        dkv_specs.append(pl.BlockSpec((1, bkv), lambda bi, kh, ki, g, qi: (bi, ki)))
    if seg is not None:
        dkv_specs.append(pl.BlockSpec((1, bq), lambda bi, kh, ki, g, qi: (bi, qi)))
        dkv_specs.append(pl.BlockSpec((1, bkv), lambda bi, kh, ki, g, qi: (bi, ki)))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, num_q=num_q, group=group, **common),
        grid=(b, nkv, num_kv, group, num_q),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bkv, d), lambda bi, kh, ki, g, qi: (bi, kh, ki, 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda bi, kh, ki, g, qi: (bi, kh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, nkv, skv, d), k.dtype),
            jax.ShapeDtypeStruct((b, nkv, skv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, d), jnp.float32),
            pltpu.VMEM((bkv, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(*in_arrays)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API (custom_vjp over the [b, s, h, d] layout)
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10)
)
def _flash(q, k, v, kvm, seg, causal, window, q_offset, bq, bkv, interpret):
    o, _ = _fwd_pallas(
        q, k, v, kvm, seg, sm_scale=1.0 / (q.shape[-1] ** 0.5), causal=causal,
        window=window, q_offset=q_offset, bq=bq, bkv=bkv, interpret=interpret,
    )
    return o


def _flash_fwd(q, k, v, kvm, seg, causal, window, q_offset, bq, bkv, interpret):
    o, lse = _fwd_pallas(
        q, k, v, kvm, seg, sm_scale=1.0 / (q.shape[-1] ** 0.5), causal=causal,
        window=window, q_offset=q_offset, bq=bq, bkv=bkv, interpret=interpret,
    )
    return o, (q, k, v, kvm, seg, o, lse)


def _mask_cotangent(kvm):
    """Zero cotangent for the (non-differentiable) int32 key mask: integer
    primals carry ``float0`` tangents in JAX."""
    if kvm is None:
        return None
    import numpy as np

    return np.zeros(kvm.shape, dtype=jax.dtypes.float0)


def _flash_bwd(causal, window, q_offset, bq, bkv, interpret, res, g):
    q = res[0]
    dq, dk, dv = _bwd_pallas(
        res, g, sm_scale=1.0 / (q.shape[-1] ** 0.5), causal=causal, window=window,
        q_offset=q_offset, bq=bq, bkv=bkv, interpret=interpret,
    )
    return dq, dk, dv, _mask_cotangent(res[3]), _mask_cotangent(res[4])


_flash.defvjp(_flash_fwd, _flash_bwd)


# -- lse-exposing variant (the ring-attention building block) ----------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_lse(q, k, v, kvm, seg, causal, window, q_offset, bq, bkv, interpret):
    """Like ``_flash`` but returns ``(o, lse)`` with lse differentiable.

    ``lse [b, nh, sq]`` is the per-row logsumexp of the (scaled, masked)
    scores; rows with no visible key carry ``NEG_INF`` and o = 0.  Exposing it
    lets callers merge partial attention over KV chunks (context-parallel ring)
    with exact autodiff: the merge is plain JAX, and this op's vjp folds the
    lse cotangent into the kernel's delta operand.
    """
    o, lse = _fwd_pallas(
        q, k, v, kvm, seg, sm_scale=1.0 / (q.shape[-1] ** 0.5), causal=causal,
        window=window, q_offset=q_offset, bq=bq, bkv=bkv, interpret=interpret,
    )
    return o, lse[..., 0]


def _flash_lse_fwd(q, k, v, kvm, seg, causal, window, q_offset, bq, bkv, interpret):
    o, lse = _fwd_pallas(
        q, k, v, kvm, seg, sm_scale=1.0 / (q.shape[-1] ** 0.5), causal=causal,
        window=window, q_offset=q_offset, bq=bq, bkv=bkv, interpret=interpret,
    )
    return (o, lse[..., 0]), (q, k, v, kvm, seg, o, lse)


def _flash_lse_bwd(causal, window, q_offset, bq, bkv, interpret, res, g):
    do, dlse = g
    q = res[0]
    dq, dk, dv = _bwd_pallas(
        res, do, sm_scale=1.0 / (q.shape[-1] ** 0.5), causal=causal, window=window,
        q_offset=q_offset, bq=bq, bkv=bkv, interpret=interpret, dlse=dlse,
    )
    return dq, dk, dv, _mask_cotangent(res[3]), _mask_cotangent(res[4])


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_tileable(sq: int, skv: int, d: int, nh: int, nkv: int,
                   block_q: Optional[int] = None,
                   block_kv: Optional[int] = None) -> bool:
    """True when these shapes can run the Pallas kernels (no fallback)."""
    bq, bkv = _block_sizes(sq, skv, block_q, block_kv)
    return _tileable(sq, skv, d, bq, bkv) and nh % nkv == 0


def _prep_mask(attention_mask, b, skv):
    """Normalize ``attention_mask`` [b, skv] (1 = real key) to int32 or None."""
    if attention_mask is None:
        return None
    if attention_mask.shape != (b, skv):
        raise ValueError(
            f"attention_mask must be [batch, kv_len] = ({b}, {skv}); got "
            f"{attention_mask.shape}"
        )
    return attention_mask.astype(jnp.int32)


def flash_attention_with_lse(
    q: jax.Array,  # [b, sq, nh, d]
    k: jax.Array,  # [b, skv, nkv, d]
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    q_offset: int = 0,
    attention_mask: Optional[jax.Array] = None,  # [b, skv] 1 = real key
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """(o [b, sq, nh, d], lse [b, nh, sq]) — the ring building block.

    No core fallback: callers must check ``flash_tileable`` first (the ring
    body needs lse, which core attention does not produce).
    """
    b, sq, nh, d = q.shape
    skv, nkv = k.shape[1], k.shape[2]
    # NOTE: unlike ``flash_attention``, sliding_window is honored even when
    # causal=False — the ring's fully-visible past chunks need exactly that
    # (window mask at a static relative offset, no causal mask)
    bq, bkv = _block_sizes(sq, skv, block_q, block_kv)
    if not _tileable(sq, skv, d, bq, bkv) or nh % nkv != 0:
        raise ValueError(
            f"flash_attention_with_lse: shapes not tileable "
            f"(sq={sq}, skv={skv}, d={d}, nh={nh}, nkv={nkv})"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    kvm = _prep_mask(attention_mask, b, skv)
    o, lse = _flash_lse(qt, kt, vt, kvm, None, causal, sliding_window, q_offset,
                        bq, bkv, interpret)
    return jnp.swapaxes(o, 1, 2), lse


def flash_attention(
    q: jax.Array,  # [b, sq, nh, d]
    k: jax.Array,  # [b, skv, nkv, d]
    v: jax.Array,  # [b, skv, nkv, d]
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    q_offset: int = 0,
    attention_mask: Optional[jax.Array] = None,  # [b, skv] 1 = real key
    segment_ids: Optional[jax.Array] = None,  # [b, s] packed-chunk segments
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention in the model's [b, s, h, d] layout.

    ``attention_mask`` masks padded KEYS (the HF contract, reference
    ``llama_model.py:94-101``) inside the kernel — padded SFT/DPO batches stay
    on the flash path instead of falling back to the O(s^2) core attention.
    ``segment_ids`` makes attention block-diagonal over packed-chunk segments
    (tokens attend only within their own record) — a correctness upgrade over
    the reference's ConcatDataset, whose packed records causally attend
    ACROSS record boundaries.
    Falls back to ``core_attention`` when shapes don't tile (tiny test models,
    odd head dims) — the dispatch contract of ``ops.attention``.
    ``interpret`` defaults to True off-TPU so tests run on CPU.
    """
    b, sq, nh, d = q.shape
    skv, nkv = k.shape[1], k.shape[2]
    if not causal:
        sliding_window = None  # window is causal-only, matching core_attention
    bq, bkv = _block_sizes(sq, skv, block_q, block_kv)
    if not _tileable(sq, skv, d, bq, bkv) or nh % nkv != 0:
        from neuronx_distributed_training_tpu.ops.attention import (
            core_attention,
            padding_mask_bias,
            segment_mask_bias,
        )

        bias = None
        if attention_mask is not None:
            bias = padding_mask_bias(attention_mask)
        if segment_ids is not None:
            sb = segment_mask_bias(segment_ids)
            bias = sb if bias is None else bias + sb
        return core_attention(
            q, k, v, causal=causal, q_offset=q_offset, sliding_window=sliding_window,
            bias=bias,
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qt = jnp.swapaxes(q, 1, 2)  # [b, nh, sq, d]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    kvm = _prep_mask(attention_mask, b, skv)
    seg = None
    if segment_ids is not None:
        if sq != skv:
            raise ValueError(
                "segment_ids need self-attention (sq == skv); got "
                f"sq={sq}, skv={skv}"
            )
        if segment_ids.shape != (b, sq):
            raise ValueError(
                f"segment_ids must be [batch, seq] = ({b}, {sq}); got "
                f"{segment_ids.shape}"
            )
        seg = segment_ids.astype(jnp.int32)
    o = _flash(qt, kt, vt, kvm, seg, causal, sliding_window, q_offset, bq, bkv,
               interpret)
    return jnp.swapaxes(o, 1, 2)
