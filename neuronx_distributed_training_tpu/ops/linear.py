"""Sharded linear / embedding primitives.

TPU-native counterparts of NxD's ``ColumnParallelLinear`` / ``RowParallelLinear`` /
``ParallelEmbedding`` (used throughout the reference, e.g. ``modeling_llama.py:
74-78, 185-203, 296-357``).  There is no wrapper class and no hand-written
collective: a "column-parallel" linear is a plain matmul whose weight carries a
``P(None, "model")`` spec; a "row-parallel" linear's weight carries
``P("model", None)`` and GSPMD inserts the reduce(-scatter).  Fused variants
(``fuse_qkv``, fused ``gate_up_proj`` — reference ``modeling_llama.py:164-223,
296-348``) are just wider column-parallel weights.

Each ``init_*`` returns ``(params, specs)`` — a param pytree and a matching
PartitionSpec pytree.  Weights are stored ``[in, out]`` (column-major for the
MXU-friendly ``x @ w`` contraction).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _normal_init(key, shape, dtype, stddev: float):
    return (stddev * jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)).astype(dtype)


def init_linear(
    key: jax.Array,
    in_dim: int,
    out_dim: int,
    *,
    shard: str,  # "column" | "row" | "replicated"
    dtype=jnp.float32,
    stddev: float = 0.02,
    use_bias: bool = False,
):
    """Init a linear layer's params and specs.

    ``shard="column"`` shards the output dim over ``model`` (NxD
    ColumnParallelLinear); ``"row"`` shards the input dim (RowParallelLinear);
    ``"replicated"`` shards nothing.
    """
    wkey, _ = jax.random.split(key)
    params = {"w": _normal_init(wkey, (in_dim, out_dim), dtype, stddev)}
    if shard == "column":
        wspec = P(None, "model")
        bspec = P("model")
    elif shard == "row":
        wspec = P("model", None)
        bspec = P(None)
    elif shard == "replicated":
        wspec = P(None, None)
        bspec = P(None)
    else:
        raise ValueError(f"unknown shard mode {shard!r}")
    specs = {"w": wspec}
    if use_bias:
        params["bias"] = jnp.zeros((out_dim,), dtype)
        specs["bias"] = bspec
    return params, specs


def apply_linear(params, x: jax.Array, *, compute_dtype=None) -> jax.Array:
    w = params["w"]
    if compute_dtype is not None:
        w = w.astype(compute_dtype)
        x = x.astype(compute_dtype)
    y = x @ w
    if "lora_a" in params:
        # low-rank adapter path (peft/lora.py): y += x @ A @ B * (alpha/r).
        # scaling is stored in the (tiny, fp32) "lora_scale" leaf so apply
        # stays a pure function of params.
        a = params["lora_a"].astype(y.dtype)
        b = params["lora_b"].astype(y.dtype)
        y = y + ((x @ a) @ b) * params["lora_scale"].astype(y.dtype)
    if "bias" in params:
        b = params["bias"]
        y = y + (b.astype(y.dtype) if compute_dtype is not None else b)
    return y


def init_embedding(
    key: jax.Array,
    vocab_size: int,
    hidden: int,
    *,
    dtype=jnp.float32,
    stddev: float = 0.02,
):
    """Vocab-sharded embedding table (NxD ``ParallelEmbedding``,
    reference ``modeling_llama.py:550,634``): ``[vocab, hidden]`` with vocab over
    ``model``.  The lookup is a gather; GSPMD resolves out-of-shard rows with the
    same masked-sum trick NxD implements by hand."""
    params = {"embedding": _normal_init(key, (vocab_size, hidden), dtype, stddev)}
    specs = {"embedding": P("model", None)}
    return params, specs


def apply_embedding(params, ids: jax.Array, *, compute_dtype=None,
                    via_matmul: bool = False) -> jax.Array:
    """Embedding lookup.

    ``via_matmul`` computes ``one_hot(ids) @ table`` instead of a gather: the
    backward pass is then a ``dot_general`` rather than a scatter-add.  XLA's
    SPMD partitioner CHECK-crashes partitioning the gather-transpose scatter
    when its consumer is DP-resharded (ZeRO-1 moments) inside the manual
    ``pipe`` submesh (spmd_partitioner_util.cc:495) — the pipeline used this
    form until the embed hook moved OUTSIDE the manual region
    (``parallel/pipeline.py``), where the cheap gather partitions fine; the
    option remains for any future in-manual-region embedding.  With a
    TP-sharded table the contraction form is also exactly Megatron's
    vocab-parallel embedding (mask-local-vocab + all-reduce), done by GSPMD.
    """
    table = params["embedding"]
    if via_matmul:
        dtype = compute_dtype or table.dtype
        oh = jax.nn.one_hot(ids, table.shape[0], dtype=dtype)
        return oh @ table.astype(dtype)
    out = jnp.take(table, ids, axis=0)
    if compute_dtype is not None:
        out = out.astype(compute_dtype)
    return out


def pad_vocab_size(vocab_size: int, make_divisible_by: int, tp: int) -> int:
    """Pad vocab so it divides evenly across TP shards — the reference's
    ``make_vocab_size_divisible_by * tp`` padding (``data/base.py:66-89``)."""
    multiple = make_divisible_by * tp
    return ((vocab_size + multiple - 1) // multiple) * multiple
