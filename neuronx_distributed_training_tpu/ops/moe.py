"""Mixture-of-Experts: routers, expert compute (dropped & dropless), aux losses.

TPU-native re-design of the NxD MoE stack the reference consumes
(``RouterTopK`` / ``RouterSinkhorn`` + ``ExpertMLPs`` + ``MoE`` modules, built at
reference ``modeling_mixtral.py:342-374`` and ``transformer.py:376-467``, with
the dropped-vs-dropless validation at ``training_orchestrator.py:60-102``):

- **router**: top-k softmax routing (Mixtral) or sinkhorn (Megatron top-1)
  over token logits; router always computed in fp32 (routing decisions must
  not flip under bf16);
- **dropped** (capacity factor): dense dispatch/combine einsums against a
  ``[tokens, experts, capacity]`` one-hot — MXU-friendly, static shapes,
  tokens beyond ``capacity_factor * tokens/experts`` per expert are dropped
  exactly like the reference's ``ExpertMLPs(capacity_factor=...)``;
- **dropless**: sort-by-expert + ``jax.lax.ragged_dot`` grouped matmul — every
  token is processed regardless of load (the reference's
  ``dropless=True`` mode), no capacity hyperparameter;
- **aux load-balancing loss**: Mixtral's ``load_balancing_loss_func``
  (reference ``modeling_mixtral.py:872-878``) — mean(expert_fraction *
  router_prob_fraction) * num_experts, plus optional router z-loss;
- **EP**: expert-major weight tensors carry their expert dim sharded over the
  ``expert`` mesh axis (see ``expert_specs``); GSPMD inserts the
  all-to-alls the reference gets from NxD's token-shuffle machinery.

SwiGLU experts (``glu_mlp`` in the reference): w_gate/w_up fused as one
``[E, h, 2*ff]`` tensor, w_down ``[E, ff, h]``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Mirrors the reference's ``model.moe`` YAML block
    (``hf_mixtral_8x7b_config.yaml:45-52``, ``megatron_gpt_model.py:133-147``)."""

    num_experts: int = 8
    top_k: int = 2
    capacity_factor: Optional[float] = None  # None/0 -> dropless
    dropless: bool = True
    router_type: str = "top_k"  # "top_k" | "sinkhorn"
    router_aux_loss_coef: float = 0.02
    router_z_loss_coef: float = 0.0
    normalize_top_k_affinities: bool = True  # Mixtral renormalizes top-k probs
    sinkhorn_iterations: int = 8
    # de-bias capacity drops from sequence position (reference
    # token_shuffle_group_size, transformer.py:410-411); dropped path only
    token_shuffle_group_size: int = 0

    @classmethod
    def from_config(cls, moe_cfg: dict[str, Any]) -> "MoEConfig":
        m = dict(moe_cfg or {})
        cap = m.get("capacity_factor")
        dropless = bool(m.get("dropless", not cap))
        return cls(
            num_experts=int(m.get("num_experts", m.get("num_moe_experts", 8))),
            top_k=int(m.get("top_k", m.get("moe_top_k", 2))),
            capacity_factor=None if dropless else float(cap or 1.0),
            dropless=dropless,
            router_type=str(m.get("router_type", "top_k")),
            router_aux_loss_coef=float(m.get("router_aux_loss_coef", 0.02)),
            router_z_loss_coef=float(m.get("router_z_loss_coef", 0.0)),
            normalize_top_k_affinities=bool(m.get("normalize_top_k_affinities", True)),
            token_shuffle_group_size=int(m.get("token_shuffle_group_size", 0) or 0),
        )


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------


def init_moe_params(key: jax.Array, hidden: int, ffn: int, cfg: MoEConfig,
                    dtype=jnp.float32, stddev: float = 0.02):
    """Router + fused SwiGLU expert weights, expert-major ``[E, ...]``."""
    kr, kgu, kd = jax.random.split(key, 3)
    e = cfg.num_experts
    return {
        "router": {"w": (jax.random.normal(kr, (hidden, e)) * stddev).astype(jnp.float32)},
        "experts": {
            "gate_up": (jax.random.normal(kgu, (e, hidden, 2 * ffn)) * stddev).astype(dtype),
            "down": (jax.random.normal(kd, (e, ffn, hidden)) * stddev).astype(dtype),
        },
    }


def moe_param_specs(cfg: MoEConfig):
    """Expert dim over ``expert`` axis (EP); ffn dim over ``model`` (TP inside
    each expert) — composing EP x TP exactly like NxD's expert sharding."""
    return {
        "router": {"w": P(None, None)},
        "experts": {
            "gate_up": P("expert", None, "model"),
            "down": P("expert", "model", None),
        },
    }


def group_interleaved_stack(moe_frequency: int, layer_stack):
    """Split a grouped dense/MoE layer stack into scan inputs.

    Layout shared by the mixtral and gpt families for ``moe_frequency > 1``:
    attn/norm leaves are flat ``[L, ...]``, ``mlp`` is ``{"moe": [G, ...],
    "dense": [G, f-1, ...]}`` with ``G = L / f``.  Returns ``{"moe": [G, ...],
    "dense": [G, f-1, ...]}`` scan inputs — groups are contiguous runs of
    ``f`` layers (MoE first), so any contiguous slice of the flat attn/norm
    stack aligns with the matching moe/dense group slices, which is what makes
    the layout pipeline-sliceable.
    """
    f = moe_frequency
    g = jax.tree_util.tree_leaves(layer_stack["mlp"]["moe"])[0].shape[0]
    shared = {k: v for k, v in layer_stack.items() if k != "mlp"}
    head = jax.tree_util.tree_map(
        lambda a: a.reshape((g, f) + a.shape[1:])[:, 0], shared)
    tail = jax.tree_util.tree_map(
        lambda a: a.reshape((g, f) + a.shape[1:])[:, 1:], shared)
    return {"moe": {**head, "mlp": layer_stack["mlp"]["moe"]},
            "dense": {**tail, "mlp": layer_stack["mlp"]["dense"]}}


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------


def _sinkhorn(cost: jax.Array, n_iters: int) -> jax.Array:
    """Sinkhorn normalization of router logits (Megatron top-1 balanced routing,
    reference ``transformer.py:376-467`` RouterSinkhorn)."""
    cost = jnp.exp(cost)
    d0 = jnp.ones(cost.shape[:-1] + (1,), cost.dtype)
    d1 = jnp.ones(cost.shape[-1:], cost.dtype)
    eps = 1e-8
    for _ in range(n_iters):
        d0 = 1.0 / (jnp.sum(d1 * cost, axis=-1, keepdims=True) + eps)
        d1 = 1.0 / (jnp.sum(d0 * cost, axis=-2, keepdims=True).squeeze(-2) / cost.shape[-2] + eps)
    return d0 * cost * d1


def route(router_params, x: jax.Array, cfg: MoEConfig):
    """Token -> expert routing.

    x [tokens, hidden] -> (probs [tokens, k], idx [tokens, k],
    router_logits [tokens, E]).  fp32 throughout.
    """
    logits = x.astype(jnp.float32) @ router_params["w"].astype(jnp.float32)
    if cfg.router_type == "sinkhorn":
        # balanced assignment for selection; gate values from plain softmax
        norm = _sinkhorn(logits, cfg.sinkhorn_iterations)
        _, idx = jax.lax.top_k(norm, cfg.top_k)
        probs_full = jax.nn.softmax(logits, axis=-1)
        probs = jnp.take_along_axis(probs_full, idx, axis=-1)
    else:
        probs_full = jax.nn.softmax(logits, axis=-1)
        probs, idx = jax.lax.top_k(probs_full, cfg.top_k)
    if cfg.normalize_top_k_affinities and cfg.top_k > 1:
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return probs, idx, logits


def load_balancing_loss(router_logits: jax.Array, idx: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Switch/Mixtral aux loss: E * mean_e(frac_tokens_e * frac_prob_e)
    (reference ``load_balancing_loss_func``, ``modeling_mixtral.py:872-878``).
    Unweighted; combine with coefficients via ``weighted_router_loss``."""
    e = cfg.num_experts
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T, E]
    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [T, k, E]
    frac_tokens = jnp.mean(jnp.sum(onehot, axis=1), axis=0)  # [E]
    frac_probs = jnp.mean(probs, axis=0)  # [E]
    return e * jnp.sum(frac_tokens * frac_probs) / max(cfg.top_k, 1)


def router_z_loss(router_logits: jax.Array) -> jax.Array:
    """ST-MoE router z-loss: mean(logsumexp(logits)^2) — keeps logits bounded."""
    z = jax.nn.logsumexp(router_logits.astype(jnp.float32), axis=-1)
    return jnp.mean(z**2)


def weighted_router_loss(router_logits: jax.Array, idx: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Per-layer auxiliary loss with coefficients already applied:
    ``aux_coef * load_balancing + z_coef * z``.  Models add the per-layer mean
    of this directly to the LM loss (no further scaling)."""
    loss = cfg.router_aux_loss_coef * load_balancing_loss(router_logits, idx, cfg)
    if cfg.router_z_loss_coef > 0:
        loss = loss + cfg.router_z_loss_coef * router_z_loss(router_logits)
    return loss


# ---------------------------------------------------------------------------
# expert compute
# ---------------------------------------------------------------------------


def _swiglu_experts(expert_params, x_e: jax.Array, compute_dtype) -> jax.Array:
    """Dense per-expert SwiGLU: x_e [E, cap, h] -> [E, cap, h]."""
    gu = jnp.einsum(
        "ech,ehf->ecf", x_e, expert_params["gate_up"].astype(compute_dtype)
    )
    gate, up = jnp.split(gu, 2, axis=-1)
    act = jax.nn.silu(gate) * up
    return jnp.einsum("ecf,efh->ech", act, expert_params["down"].astype(compute_dtype))


def moe_dropped(params, x: jax.Array, cfg: MoEConfig, *, compute_dtype=jnp.bfloat16):
    """Capacity-factor MoE: tokens over capacity are dropped (pass through 0).

    x [tokens, hidden] -> (y [tokens, hidden], router_logits).
    Dense dispatch/combine einsums (GShard style): static shapes, MXU-friendly,
    and under EP the ``[E, cap, h]`` dispatch tensor all-to-alls over the
    ``expert`` axis automatically.
    """
    t, h = x.shape
    e, k = cfg.num_experts, cfg.top_k
    cap = int(max(1, round((cfg.capacity_factor or 1.0) * t * k / e)))
    probs, idx, logits = route(params["router"], x, cfg)

    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [T, k, E]
    # position of each (token, k) within its expert's queue
    pos = jnp.cumsum(onehot.reshape(t * k, e), axis=0).reshape(t, k, e) - 1.0
    keep = (pos < cap) * onehot  # drop over-capacity
    pos_cap = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)  # [T,k,E,cap]
    dispatch = jnp.einsum("tke,tkec->tec", keep, pos_cap)  # [T, E, cap] 0/1
    combine = jnp.einsum("tk,tke,tkec->tec", probs.astype(jnp.float32), keep, pos_cap)

    x_e = jnp.einsum("tec,th->ech", dispatch.astype(compute_dtype), x.astype(compute_dtype))
    y_e = _swiglu_experts(params["experts"], x_e, compute_dtype)
    y = jnp.einsum("tec,ech->th", combine.astype(compute_dtype), y_e)
    return y.astype(x.dtype), (probs, idx, logits)


def moe_dropless(params, x: jax.Array, cfg: MoEConfig, *, compute_dtype=jnp.bfloat16):
    """Dropless MoE: sort tokens by expert, grouped-matmul via ``lax.ragged_dot``.

    Every token is processed (the reference's ``dropless=True``); group sizes
    are data-dependent but shapes are static ([T*k] rows).
    """
    t, h = x.shape
    e, k = cfg.num_experts, cfg.top_k
    probs, idx, logits = route(params["router"], x, cfg)

    flat_expert = idx.reshape(-1)  # [T*k]
    order = jnp.argsort(flat_expert)  # stable sort by expert
    token_of = order // k  # original token index per sorted row
    xs = x.astype(compute_dtype)[token_of]  # [T*k, h] gathered rows
    group_sizes = jnp.bincount(flat_expert, length=e)

    # XLA's SPMD partitioner has no rule for ragged_dot's GROUP dimension:
    # with the expert dim sharded it computes each shard's local expert
    # slice against the GLOBAL group offsets — silently wrong values, no
    # error (full-signal corruption on any mesh where the expert axis is
    # strided, e.g. EP x TP; verified empirically on jax 0.4.x).  Constrain
    # the weights to be gathered over 'expert' for the compute — weight-
    # gather EP: the resident weights and optimizer state stay sharded per
    # expert_specs, GSPMD inserts one all-gather per layer, and the ffn
    # dim's 'model' sharding (which ragged_dot partitions correctly) is
    # preserved.  Sharded-vs-unsharded parity: tests/test_mixtral.py.
    from neuronx_distributed_training_tpu.parallel import sharding as shd

    gu_w = shd.constrain(
        params["experts"]["gate_up"].astype(compute_dtype),
        P(None, None, "model"))
    down_w = shd.constrain(
        params["experts"]["down"].astype(compute_dtype),
        P(None, "model", None))

    gu = jax.lax.ragged_dot(xs, gu_w, group_sizes)
    gate, up = jnp.split(gu, 2, axis=-1)
    act = jax.nn.silu(gate) * up
    ys = jax.lax.ragged_dot(act, down_w, group_sizes)  # [T*k, h]

    w = probs.reshape(-1)[order].astype(compute_dtype)  # gate weight per row
    y = jnp.zeros((t, h), compute_dtype).at[token_of].add(ys * w[:, None])
    return y.astype(x.dtype), (probs, idx, logits)


def _shuffle_permutation(t: int, group: int) -> jnp.ndarray:
    """Deterministic stride (interleave) permutation of ``t`` tokens.

    The reference's ``token_shuffle_group_size`` (``transformer.py:410-411``)
    randomly shuffles tokens before capacity-factor dispatch so over-capacity
    DROPS are not biased toward late sequence positions (the expert queue
    position is a cumsum in token order).  A fixed stride permutation —
    read the flat token stream as ``[group, t/group]`` column-major — achieves
    the same positional de-correlation deterministically: adjacent sequence
    positions land ``t/group`` apart in the queue.  No PRNG threading, no
    cross-step nondeterminism, exact inverse by transposition.
    """
    g = max(1, min(group, t))
    while t % g:
        g -= 1  # largest divisor <= group (tiny/odd token counts)
    return jnp.arange(t).reshape(t // g, g).T.reshape(-1)


def moe_block(params, x: jax.Array, cfg: MoEConfig, *, compute_dtype=jnp.bfloat16):
    """[b, s, h] wrapper dispatching dropped/dropless; returns (y, router_logits)."""
    b, s, h = x.shape
    flat = x.reshape(b * s, h)
    shuffle = (not cfg.dropless) and (cfg.token_shuffle_group_size or 0) > 1
    if shuffle:
        # only the dropped path is order-dependent (queue-position cumsum);
        # dropless processes every token, so shuffling there is a no-op cost
        perm = _shuffle_permutation(b * s, int(cfg.token_shuffle_group_size))
        inv = jnp.argsort(perm)
        flat = flat[perm]
    fn = moe_dropless if cfg.dropless else moe_dropped
    y, (probs, idx, logits) = fn(params, flat, cfg, compute_dtype=compute_dtype)
    if shuffle:
        y, idx, logits = y[inv], idx[inv], logits[inv]
    return y.reshape(b, s, h), {"router_logits": logits, "expert_idx": idx}
