"""Normalization layers.

RMSNorm with fp32 internals regardless of compute dtype — the reference's
``LlamaRMSNorm`` upcasts to the cast-dtype before the variance reduction
(``modeling_llama.py:145-161``); here the upcast is explicit and local.
The fused-kernel concern of ``fused_layer_norm.py`` (apex MixedFusedLayerNorm /
MixedFusedRMSNorm, reference ``fused_layer_norm.py:14-36``) is handled by XLA
fusion on TPU; a Pallas fused variant exists for the flash-attention path where
profiling warrants it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_rms_norm(hidden: int, *, dtype=jnp.float32):
    return {"scale": jnp.ones((hidden,), dtype)}, {"scale": P(None)}


def apply_rms_norm(params, x: jax.Array, *, eps: float = 1e-5) -> jax.Array:
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * params["scale"].astype(jnp.float32)).astype(orig_dtype)


def init_layer_norm(hidden: int, *, dtype=jnp.float32):
    return (
        {"scale": jnp.ones((hidden,), dtype), "bias": jnp.zeros((hidden,), dtype)},
        {"scale": P(None), "bias": P(None)},
    )


def apply_layer_norm(params, x: jax.Array, *, eps: float = 1e-5) -> jax.Array:
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = normed * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
    return out.astype(orig_dtype)
