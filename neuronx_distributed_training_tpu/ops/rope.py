"""Rotary position embeddings.

Covers the reference's two RoPE implementations: the HF-style
``LlamaRotaryEmbedding`` with fp64-precision inv-freq override
(``modeling_llama.py:847-873``) and Megatron's ``rotary_pos_embedding.py`` with
position-interpolation and ABF base scaling (``rotary_pos_embedding.py:22-81``).
Frequencies are computed in fp64 on host at trace time (static) then applied in
fp32 — matching the reference's precision discipline without any global flag.

Context parallelism offsets positions per CP shard (reference
``modeling_llama.py:619-629``); callers pass explicit ``positions`` so the same
code serves CP, packed sequences, and inference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    *,
    theta: float = 10000.0,
    position_interpolation_factor: float | None = None,
    abf_scale: float | None = None,
) -> np.ndarray:
    """Inverse frequencies ``[head_dim/2]`` in fp64 (host-side, static).

    ``abf_scale`` scales the base theta (adjusted-base-frequency, reference
    ``rotary_pos_embedding.py``); ``position_interpolation_factor`` divides
    positions at application time.
    """
    base = float(theta)
    if abf_scale is not None:
        base = base * abf_scale
    exponent = np.arange(0, head_dim, 2, dtype=np.float64) / head_dim
    inv_freq = 1.0 / (base**exponent)
    if position_interpolation_factor:
        inv_freq = inv_freq / float(position_interpolation_factor)
    return inv_freq


def rope_cos_sin(
    positions: jax.Array,  # [batch, seq] or [seq]
    inv_freq: np.ndarray,
    *,
    dtype=jnp.float32,
):
    """cos/sin tables for given positions: ``[..., seq, head_dim/2]``."""
    angles = positions.astype(jnp.float32)[..., None] * jnp.asarray(inv_freq, jnp.float32)
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate ``x: [batch, seq, heads, head_dim]`` (HF half-rotation layout).

    cos/sin are ``[batch, seq, head_dim/2]`` (or ``[seq, head_dim/2]``).
    """
    orig_dtype = x.dtype
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    if cos.ndim == 2:  # [seq, half] -> broadcast over batch
        cos_b = cos[None, :, None, :]
        sin_b = sin[None, :, None, :]
    else:  # [batch, seq, half]
        cos_b = cos[:, :, None, :]
        sin_b = sin[:, :, None, :]
    out1 = x1 * cos_b - x2 * sin_b
    out2 = x2 * cos_b + x1 * sin_b
    return jnp.concatenate([out1, out2], axis=-1).astype(orig_dtype)
