"""Optimizers and LR schedules."""

from neuronx_distributed_training_tpu.optim.adamw import (  # noqa: F401
    AdamWConfig,
    adamw_update,
    init_opt_state,
    opt_state_specs,
)
from neuronx_distributed_training_tpu.optim.lr import build_lr_schedule  # noqa: F401
