"""AdamW with fp32 optimizer state, master weights, global-norm clipping, and
ZeRO-1 sharding specs.

The reference gets its optimizer from the NeMo registry (``adamw_fp32OptState``,
reference ``base.py:305``) and wraps it with NxD's ZeRO-1
``ZeroRedundancyOptimizer`` which shards optimizer state over DP ranks, clips
gradients internally, and all-gathers updated params (``base.py:127-143,
321-325``; ``nlp_overrides.py:203-216``).

TPU-native: the optimizer is a pure function; ZeRO-1 is *just a sharding spec* —
``opt_state_specs`` shards the fp32 moments/master weights over the compound DP
axis ``(data, expert)`` on a dimension the param spec leaves unsharded.  XLA's
weight-update sharding then performs exactly the reduce-scatter → sharded-update
→ all-gather dance the NxD wrapper hand-codes (cf. "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training", arXiv:2004.13336).

Grad clipping happens inside the update (global norm over the whole grad tree)
and the pre-clip ``grad_norm`` is returned for logging, matching the reference's
``log_gradient_norm`` semantics (``exp_manager.py``, ``base.py:227``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy


@dataclasses.dataclass(frozen=True)
class AdamWConfig:
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.01
    grad_clip_norm: Optional[float] = 1.0
    # params whose tree-path matches one of these substrings get no weight decay
    # (reference BaseHfModel: no decay on bias/norm params, base_model.py:18-54)
    no_decay_substrings: tuple = ("norm", "bias", "scale")

    @classmethod
    def from_config(cls, optim_cfg: dict[str, Any], trainer_cfg: dict[str, Any] | None = None,
                    do_layer_norm_weight_decay: bool = False) -> "AdamWConfig":
        o = dict(optim_cfg or {})
        t = dict(trainer_cfg or {})
        betas = o.get("betas", [0.9, 0.999])
        return cls(
            beta1=float(betas[0]),
            beta2=float(betas[1]),
            eps=float(o.get("eps", 1e-8)),
            weight_decay=float(o.get("weight_decay", 0.01)),
            grad_clip_norm=t.get("gradient_clip_val", 1.0),
            no_decay_substrings=() if do_layer_norm_weight_decay else ("norm", "bias", "scale"),
        )


@dataclasses.dataclass(frozen=True)
class EMAConfig:
    """Weight EMA (the reference's NeMo ``EMA`` callback wired from
    ``exp_manager.ema``, ``utils/exp_manager.py:298-305``).  TPU-native the
    EMA tree lives INSIDE the optimizer state so it is jitted, donated,
    ZeRO-1-sharded, and checkpointed with everything else."""

    decay: float = 0.9999
    apply_every_n_steps: int = 1
    start_step: int = 0
    evaluate_ema_weights_instead: bool = False

    @classmethod
    def from_config(cls, ema_cfg: dict[str, Any]) -> "EMAConfig":
        e = dict(ema_cfg or {})
        return cls(
            decay=float(e.get("decay", 0.9999)),
            apply_every_n_steps=int(e.get("apply_ema_every_n_steps", 1)),
            start_step=int(e.get("start_step", 0)),
            evaluate_ema_weights_instead=bool(
                e.get("evaluate_ema_weights_instead", False)
            ),
        )


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path).lower()


def decay_mask(params, cfg: AdamWConfig):
    """1.0 where weight decay applies, 0.0 for bias/norm-type params."""

    def leaf_mask(path, x):
        p = _path_str(path)
        if any(s in p for s in cfg.no_decay_substrings):
            return 0.0
        return 1.0

    return jax.tree_util.tree_map_with_path(leaf_mask, params)


#: scalar counters threaded through ``opt_state["health"]`` when the numerics
#: flight recorder is enabled: they ride the donated state step-to-step,
#: survive checkpoints, and reach the host for free inside the boundary
#: metric fetch (``last_nonfinite_step`` starts at -1 = "never")
HEALTH_STATE_KEYS = (
    "steps_seen", "nonfinite_count", "skipped_count", "last_nonfinite_step",
)


def init_health_state():
    return {
        "steps_seen": jnp.zeros((), jnp.int32),
        "nonfinite_count": jnp.zeros((), jnp.int32),
        "skipped_count": jnp.zeros((), jnp.int32),
        "last_nonfinite_step": jnp.full((), -1, jnp.int32),
    }


def init_opt_state(params, policy: DtypePolicy | None = None, *, ema: bool = False,
                   health: bool = False, tensorstats=None,
                   tensorstats_bucket_groups: tuple = ()):
    """Opt state: step counter, fp32 moments, fp32 master weights when the
    params themselves are stored in a lower precision, (optionally) the
    weight-EMA tree, (optionally) the numerics-health counters, and
    (optionally) the tensor-numerics-observatory cumulative record
    (``tensorstats`` — a ``telemetry.tensorstats.TensorStatsConfig``;
    ``tensorstats_bucket_groups`` names the ZeRO-1 bucket slots when the
    bucket phase is on)."""
    policy = policy or DtypePolicy()
    odt = policy.optimizer_dtype

    def zeros_like_in(x):
        return jnp.zeros(x.shape, odt)

    state = {
        "step": jnp.zeros((), jnp.int32),
        "mu": jax.tree_util.tree_map(zeros_like_in, params),
        "nu": jax.tree_util.tree_map(zeros_like_in, params),
    }
    if jnp.dtype(policy.param_dtype) != jnp.dtype(odt):
        state["master"] = jax.tree_util.tree_map(lambda x: x.astype(odt), params)
    if ema:
        state["ema"] = jax.tree_util.tree_map(lambda x: x.astype(odt), params)
    if health:
        state["health"] = init_health_state()
    if tensorstats is not None and getattr(tensorstats, "enabled", False):
        from neuronx_distributed_training_tpu.telemetry.tensorstats import (
            init_tensorstats_state,
        )

        state["tensorstats"] = init_tensorstats_state(
            tensorstats, params,
            bucket_groups=tuple(tensorstats_bucket_groups))
    return state


def global_norm(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    )


def grouped_sq_norms(tree, group_fn: Callable) -> dict[str, jax.Array]:
    """Per-group sums of squares over a pytree (fp32).

    ``group_fn(path) -> str`` names each leaf's group.  The per-leaf squared
    sums are the SAME reductions ``global_norm`` performs — the caller derives
    the global norm as ``sqrt(sum(values))``, so grouped health norms and the
    clipping norm share one reduction pass (one source of truth)."""
    sums: dict[str, jax.Array] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = group_fn(path)
        s = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        sums[key] = sums[key] + s if key in sums else s
    return sums


def adamw_update(
    params,
    grads,
    opt_state,
    lr,
    cfg: AdamWConfig,
    policy: DtypePolicy | None = None,
    trainable_mask=None,
    ema_cfg: Optional[EMAConfig] = None,
    *,
    grad_group_fn: Optional[Callable] = None,
    skip_nonfinite: bool = False,
    extra_finite=None,
    bucket_plan=None,
    prefetch_ag: bool = True,
    tensorstats_cfg=None,
):
    """One AdamW step. Returns (new_params, new_opt_state, metrics).

    ``trainable_mask`` (pytree of 0/1, e.g. ``peft.lora.trainable_mask``)
    freezes masked-out params completely: no grad, no moment update, no weight
    decay — the LoRA/PEFT freeze.

    Numerics-health hooks (``telemetry.health``):

    - ``grad_group_fn(path) -> str``: when set, metrics gains ``group_norms``
      (per-layer-group pre-clip grad norms) and the global clipping norm is
      DERIVED from the same per-leaf squared sums — one reduction pass, not a
      second one.
    - ``skip_nonfinite=True``: the whole update (params, moments, master, EMA,
      step counter) is replaced leaf-wise by the incoming state when the
      update is non-finite — an in-graph ``select``, so a poisoned batch
      leaves params bitwise-unchanged with no recompile and no host
      round-trip (the grad-scaler-skip behavior without a dynamic scale).
    - ``extra_finite``: extra boolean ANDed into the finite flag (the caller
      passes loss finiteness so a NaN loss with, e.g., masked-to-zero grads
      still counts as a skip).

    ``metrics["updates_finite"]`` (bool) is reported whenever any hook is
    active.

    ``bucket_plan`` (``optim.overlap.BucketPlan``): the engineered-overlap
    path — the moment/master/param updates run per layer-group bucket with
    one combined parameter all-gather per bucket (and, under
    ``prefetch_ag``, an ``optimization_barrier`` chain staggering the
    buckets so gather k overlaps update k+1).  Everything before (norms,
    clipping) and after (EMA, skip select, metrics) is the shared
    whole-tree code, and the per-bucket lambdas are the SAME ones the
    monolithic path maps — numerics are bitwise identical; only the
    collective structure changes.

    ``tensorstats_cfg`` (``telemetry.tensorstats.TensorStatsConfig``,
    enabled): the tensor numerics observatory — per layer-group absmax /
    rms / zero / subnormal fraction / log2-exponent histogram of the grads
    (pre- and post-clip, and of the packed ZeRO-1 bucket payloads under its
    ``buckets`` phase), accumulated into ``opt_state["tensorstats"]``
    (which ``init_opt_state(..., tensorstats=cfg)`` must have created) and
    reported under ``metrics["tensorstats"]``.  The pre-clip rms reuses the
    grouped squared sums that already derive the clipping norm.  A pure
    observer: the update itself is bitwise-unchanged."""
    policy = policy or DtypePolicy()
    tstats = (tensorstats_cfg
              if tensorstats_cfg is not None
              and getattr(tensorstats_cfg, "enabled", False) else None)
    if tstats is not None and grad_group_fn is None:
        from neuronx_distributed_training_tpu.telemetry.health import (
            grad_group_of,
        )

        grad_group_fn = grad_group_of
    step = opt_state["step"] + 1
    grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads)
    if trainable_mask is not None:
        grads = jax.tree_util.tree_map(lambda g, m: g * m, grads, trainable_mask)
    group_sq = None
    if grad_group_fn is not None:
        group_sq = grouped_sq_norms(grads, grad_group_fn)
        total = None
        for s in group_sq.values():
            total = s if total is None else total + s
        gnorm = jnp.sqrt(total if total is not None else jnp.zeros((), jnp.float32))
    else:
        gnorm = global_norm(grads)
    track_finite = skip_nonfinite or grad_group_fn is not None \
        or extra_finite is not None
    updates_finite = None
    if track_finite:
        # any non-finite grad leaf poisons the squared-sum chain, so one
        # isfinite on the global norm covers the whole grad tree
        updates_finite = jnp.isfinite(gnorm)
        if extra_finite is not None:
            updates_finite = jnp.logical_and(
                updates_finite, jnp.asarray(extra_finite, bool))
    grads_preclip = grads  # tensorstats pre-clip view (a reference, no copy)
    if cfg.grad_clip_norm is not None and cfg.grad_clip_norm > 0:
        clip = jnp.minimum(1.0, cfg.grad_clip_norm / (gnorm + 1e-6))
        grads = jax.tree_util.tree_map(lambda g: g * clip, grads)

    b1, b2 = cfg.beta1, cfg.beta2
    c1 = 1.0 - b1 ** step.astype(jnp.float32)
    c2 = 1.0 - b2 ** step.astype(jnp.float32)
    masks = decay_mask(params, cfg)
    master = opt_state.get("master", params)
    lr = jnp.asarray(lr, jnp.float32)

    if trainable_mask is not None:
        # frozen params get no weight decay either
        masks = jax.tree_util.tree_map(lambda w, t: w * t, masks, trainable_mask)

    def mu_fn(mu, g):
        return b1 * mu.astype(jnp.float32) + (1 - b1) * g

    def nu_fn(nu, g):
        return b2 * nu.astype(jnp.float32) + (1 - b2) * jnp.square(g)

    def upd(m, mu, nu, wd_mask):
        mf = m.astype(jnp.float32)
        update = (mu / c1) / (jnp.sqrt(nu / c2) + cfg.eps)
        update = update + cfg.weight_decay * wd_mask * mf
        return mf - lr * update

    packed_payloads = (
        {} if (tstats is not None and tstats.buckets
               and bucket_plan is not None and bucket_plan.buckets) else None)
    if bucket_plan is not None and bucket_plan.buckets:
        from neuronx_distributed_training_tpu.optim.overlap import (
            bucketed_update,
        )

        new_mu, new_nu, new_master, new_params = bucketed_update(
            bucket_plan, params, grads, opt_state["mu"], opt_state["nu"],
            master, masks, mu_fn=mu_fn, nu_fn=nu_fn, upd_fn=upd,
            prefetch=prefetch_ag, collect_packed=packed_payloads,
        )
    else:
        new_mu = jax.tree_util.tree_map(mu_fn, opt_state["mu"], grads)
        new_nu = jax.tree_util.tree_map(nu_fn, opt_state["nu"], grads)
        new_master = jax.tree_util.tree_map(upd, master, new_mu, new_nu, masks)
        new_params = jax.tree_util.tree_map(
            lambda x, p: x.astype(p.dtype), new_master, params
        )

    odt = policy.optimizer_dtype
    new_state = {
        "step": step,
        "mu": jax.tree_util.tree_map(lambda x: x.astype(odt), new_mu),
        "nu": jax.tree_util.tree_map(lambda x: x.astype(odt), new_nu),
    }
    if "master" in opt_state:
        new_state["master"] = jax.tree_util.tree_map(lambda x: x.astype(odt), new_master)
    if "ema" in opt_state:
        e = ema_cfg or EMAConfig()
        apply = jnp.logical_and(
            step >= e.start_step,
            jnp.remainder(step, e.apply_every_n_steps) == 0,
        )
        d = jnp.where(apply, e.decay, 1.0)
        new_state["ema"] = jax.tree_util.tree_map(
            lambda old, p: (d * old.astype(jnp.float32)
                            + (1.0 - d) * p.astype(jnp.float32)).astype(odt),
            opt_state["ema"], new_master,
        )
    ts_metrics = None
    if tstats is not None:
        from neuronx_distributed_training_tpu.telemetry.tensorstats import (
            tensorstats_update,
        )

        new_state["tensorstats"], ts_metrics = tensorstats_update(
            opt_state["tensorstats"], tstats, group_fn=grad_group_fn,
            grads_pre=grads_preclip, grads_post=grads, group_sq=group_sq,
            packed=packed_payloads,
        )
    if skip_nonfinite:
        # in-graph skip: a select per leaf keeps params/moments/master/EMA AND
        # the step counter (bias correction must not advance on a skipped
        # step) bitwise-identical to the incoming state when non-finite
        keep = lambda new, old: jnp.where(updates_finite, new, old)
        new_params = jax.tree_util.tree_map(keep, new_params, params)
        new_state = {
            k: jax.tree_util.tree_map(keep, v, opt_state[k])
            for k, v in new_state.items()
        }
    metrics = {"grad_norm": gnorm}
    if updates_finite is not None:
        metrics["updates_finite"] = updates_finite
    if group_sq is not None:
        metrics["group_norms"] = {k: jnp.sqrt(v) for k, v in group_sq.items()}
    if ts_metrics is not None:
        metrics["tensorstats"] = ts_metrics
    return new_params, new_state, metrics


# ---------------------------------------------------------------------------
# ZeRO-1 sharding specs
# ---------------------------------------------------------------------------


def zero1_leaf_spec(spec: P, shape, mesh: Mesh, dp_axes=("data", "expert")) -> P:
    """Extend a param spec with DP sharding on the first unsharded, divisible dim.

    This is ZeRO-1: optimizer moments/master weights sharded over the DP group.
    Axes the param spec already uses (e.g. ``expert`` on MoE weights) are
    skipped — a mesh axis may appear at most once per spec.  Falls back to the
    param spec (replicated over DP) when nothing divides.
    """
    used = {
        a
        for e in spec
        if e is not None
        for a in (e if isinstance(e, tuple) else (e,))
    }
    avail = tuple(
        a for a in dp_axes if int(mesh.shape.get(a, 1)) > 1 and a not in used
    )
    dp_total = 1
    for a in avail:
        dp_total *= int(mesh.shape.get(a, 1))
    if dp_total == 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, (e, dim) in enumerate(zip(entries, shape)):
        if e is None and dim % dp_total == 0:
            entries[i] = avail if len(avail) > 1 else avail[0]
            return P(*entries)
    return spec


def opt_state_specs(params, param_specs, mesh: Mesh, *, zero1: bool = True,
                    policy: DtypePolicy | None = None,
                    zero1_exclude: tuple = (), ema: bool = False,
                    health: bool = False, tensorstats=None,
                    tensorstats_bucket_groups: tuple = ()):
    """Spec pytree matching ``init_opt_state`` output.

    ``zero1_exclude`` names path substrings whose moments keep the plain param
    spec (no DP sharding) — a generic escape hatch; nothing in the stock
    models needs it (the former embedding-under-PP exclusion was removed by
    switching the pipeline embed hooks to the one-hot matmul form, see
    ``ops.linear.apply_embedding``)."""
    policy = policy or DtypePolicy()

    if zero1:
        shapes = jax.tree_util.tree_map(lambda x: x.shape, params)

        def leaf_spec(path, s, sh):
            p = _path_str(path)
            if any(x in p for x in zero1_exclude):
                return s
            return zero1_leaf_spec(s, sh, mesh)

        moment_specs = jax.tree_util.tree_map_with_path(
            leaf_spec,
            param_specs,
            shapes,
            is_leaf=lambda x: isinstance(x, P),
        )
    else:
        moment_specs = param_specs
    out = {"step": P(), "mu": moment_specs, "nu": moment_specs}
    if jnp.dtype(policy.param_dtype) != jnp.dtype(policy.optimizer_dtype):
        out["master"] = moment_specs
    if ema:
        out["ema"] = moment_specs
    if health:
        out["health"] = {k: P() for k in HEALTH_STATE_KEYS}
    if tensorstats is not None and getattr(tensorstats, "enabled", False):
        from neuronx_distributed_training_tpu.telemetry.tensorstats import (
            tensorstats_state_specs,
        )

        out["tensorstats"] = tensorstats_state_specs(
            tensorstats, params,
            bucket_groups=tuple(tensorstats_bucket_groups))
    return out
