"""LR schedules.

``LinearAnnealingWithWarmUp`` reproduces the reference's registered scheduler
(``optim/lr_schedulers.py:11-23``): HF-style linear warmup to ``lr`` over
``warmup_steps`` then linear decay to ``min_lr`` (default 0) at ``max_steps``.
Schedules are pure ``step -> lr`` functions usable inside jit.
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp

Schedule = Callable[[Any], Any]


def linear_annealing_with_warmup(
    lr: float, warmup_steps: int, max_steps: int, min_lr: float = 0.0
) -> Schedule:
    def f(step):
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.maximum(1.0, float(warmup_steps))
        warm_lr = lr * step / warm
        decay_total = jnp.maximum(1.0, float(max_steps - warmup_steps))
        frac = jnp.clip((step - warmup_steps) / decay_total, 0.0, 1.0)
        decay_lr = lr + frac * (min_lr - lr)
        return jnp.where(step < warmup_steps, warm_lr, decay_lr)

    return f


def cosine_annealing(
    lr: float, warmup_steps: int, max_steps: int, min_lr: float = 0.0
) -> Schedule:
    def f(step):
        step = jnp.asarray(step, jnp.float32)
        warm = jnp.maximum(1.0, float(warmup_steps))
        warm_lr = lr * step / warm
        decay_total = jnp.maximum(1.0, float(max_steps - warmup_steps))
        frac = jnp.clip((step - warmup_steps) / decay_total, 0.0, 1.0)
        decay_lr = min_lr + 0.5 * (lr - min_lr) * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(step < warmup_steps, warm_lr, decay_lr)

    return f


def constant_lr(lr: float, *_, **__) -> Schedule:
    return lambda step: jnp.asarray(lr, jnp.float32)


_SCHEDULES = {
    "linearannealingwithwarmup": linear_annealing_with_warmup,
    "cosineannealing": cosine_annealing,
    "constant": constant_lr,
}


def build_lr_schedule(optim_cfg: dict[str, Any], max_steps_default: int = 10000) -> Schedule:
    """Build from the reference's ``model.optim`` block
    (``hf_llama3_8B_config.yaml:92-107``)."""
    lr = float(optim_cfg.get("lr", 3e-4))
    sched = dict(optim_cfg.get("sched", {}) or {})
    name = str(sched.get("name", "LinearAnnealingWithWarmUp")).lower()
    if name not in _SCHEDULES:
        raise ValueError(f"unknown LR schedule {sched.get('name')!r}")
    return _SCHEDULES[name](
        lr,
        int(sched.get("warmup_steps", 0)),
        int(sched.get("max_steps", max_steps_default)),
        float(sched.get("min_lr", 0.0)),
    )
