"""Engineered compute/comms overlap for the ZeRO-1 update.

The measurement plane (per-class achieved overlap from device traces, the
PC201/PC202 exposed-seconds ratchets) says the step is bandwidth-bound at
scale; this module is the *engineering* side: it turns the monolithic
step-boundary ZeRO-1 collectives into scheduled, bucketed pieces the XLA
latency-hiding scheduler can actually hide (cf. DeepCompile's
compiler-driven decomposition of ZeRO collectives, and the weight-update
sharding analysis in arXiv:2004.13336).

Three levers, all opt-in via ``distributed_strategy.overlap``:

- **Bucketed ZeRO-1 collectives** (``zero1_bucket_mb``): the AdamW update is
  decomposed into per-layer-group buckets (riding the health plane's
  ``grad_group_of`` naming).  Per bucket, every DP-sharded master/moment
  leaf's updated parameter is packed into ONE ``[dp, cols]`` buffer and
  resharded replicated in a single combined all-gather (``zero1_bucket_ag``
  named scope — the graph-contract ``zero1-bucket`` provenance class),
  instead of GSPMD's one all-gather per leaf at the step boundary.  Buckets
  are processed in reverse tree order — approximately gradient-completion
  order — so the first bucket's collective is in flight while later buckets
  are still computing.  The gradient reductions themselves are placed by
  GSPMD at their production sites (the backward); what bucketing controls
  is the *consumption* chain: each bucket's update can issue as soon as its
  group's grads are final instead of waiting for the whole tree.
- **Prefetched all-gathers** (``prefetch_ag``): an ``optimization_barrier``
  chain ties bucket k+1's gradient inputs to bucket k's pre-all-gather
  output.  Bucket k's all-gather and bucket k+1's update then depend on the
  same value but not on each other — the staggered structure the
  latency-hiding scheduler needs to overlap the gather with compute, and
  the prefetch that lands bucket k's replicated params ahead of their first
  forward consumer instead of serializing at the boundary.
- **Latency-hiding-scheduler knobs** (``xla_lhs``): the XLA flag set that
  makes the above actionable on TPU (async collectives + the LHS pass),
  merged into ``XLA_FLAGS`` with conflict detection instead of blind
  appending.

``pp_double_buffer`` is consumed by ``parallel.pipeline``: the stage-hop
collective-permutes move out of their compute ``cond``s to the tick
boundaries the work-compacted table's write->first-read intervals allow,
so a hop overlaps the neighbouring tick's compute.
"""

from __future__ import annotations

import dataclasses
import difflib
from typing import Any, Callable, Iterable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from neuronx_distributed_training_tpu.parallel import sharding as shd

#: the one named scope the combined all-gather lives under — graph contracts
#: corroborate the ``zero1-bucket`` provenance class against this substring
BUCKET_AG_SCOPE = "zero1_bucket_ag"

_OVERLAP_KEYS = ("zero1_bucket_mb", "prefetch_ag", "pp_double_buffer",
                 "xla_lhs")


@dataclasses.dataclass(frozen=True)
class OverlapConfig:
    """Validated ``distributed_strategy.overlap`` block (all levers off by
    default — the engineered paths are opt-in and graph-changing)."""

    zero1_bucket_mb: float = 0.0  # 0 = monolithic; >0 = coalesce grad groups
                                  # until a bucket holds >= this many MiB of
                                  # fp32 master weights
    prefetch_ag: bool = True      # barrier-chain buckets (no-op when
                                  # zero1_bucket_mb == 0)
    pp_double_buffer: bool = False  # hoist pipeline stage-hop permutes out of
                                    # their compute conds
    xla_lhs: bool = False         # export the TPU latency-hiding flag set

    @classmethod
    def from_config(cls, block: Optional[dict]) -> "OverlapConfig":
        if block is None:
            return cls()
        if not isinstance(block, dict):
            raise ValueError(
                "distributed_strategy.overlap must be a mapping, got "
                f"{type(block).__name__}"
            )
        for k in block:
            if k not in _OVERLAP_KEYS:
                near = difflib.get_close_matches(str(k), _OVERLAP_KEYS, n=1)
                hint = f" — did you mean '{near[0]}'?" if near else ""
                raise ValueError(
                    f"unknown distributed_strategy.overlap key '{k}'{hint} "
                    f"(valid: {', '.join(_OVERLAP_KEYS)})"
                )
        mb = block.get("zero1_bucket_mb", 0.0)
        if isinstance(mb, bool) or not isinstance(mb, (int, float)):
            raise ValueError(
                "distributed_strategy.overlap.zero1_bucket_mb must be a "
                f"number (MiB), got {type(mb).__name__}"
            )
        if mb < 0:
            raise ValueError(
                "distributed_strategy.overlap.zero1_bucket_mb must be >= 0, "
                f"got {mb}"
            )
        out = {"zero1_bucket_mb": float(mb)}
        for k in ("prefetch_ag", "pp_double_buffer", "xla_lhs"):
            if k in block:
                v = block[k]
                if not isinstance(v, bool):
                    raise ValueError(
                        f"distributed_strategy.overlap.{k} must be a bool, "
                        f"got {type(v).__name__}"
                    )
                out[k] = v
        return cls(**out)


# ---------------------------------------------------------------------------
# Bucket planning (static — built from abstract shapes + specs at assembly)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AGLeaf:
    """One leaf eligible for the combined all-gather: its moments/master are
    DP-sharded on exactly ``dim`` and the param spec is fully replicated, so
    the updated parameter can be packed shard-contiguously into the bucket's
    ``[dp, cols]`` buffer."""

    pos: int            # index into the flattened params tree
    dim: int            # the DP-sharded dim of the moment spec
    cols: int           # leaf.size // dp_total
    moved_shape: tuple  # shape after moveaxis(dim -> 0)


@dataclasses.dataclass(frozen=True)
class Bucket:
    name: str                  # "+".join of member grad groups
    idxs: tuple                # flattened leaf indices (all members)
    ag: tuple                  # AGLeaf entries (combined-gather members)
    bytes: int                 # fp32 master bytes in this bucket


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    buckets: tuple             # processing order: reverse tree-group order
    dp_entry: Any              # spec entry for the sharded pack dim
    dp_total: int
    num_leaves: int

    def describe(self) -> str:
        parts = [
            f"{b.name}[{len(b.idxs)} leaves, {len(b.ag)} packed, "
            f"{b.bytes / 2**20:.1f}MiB]"
            for b in self.buckets
        ]
        return f"zero1 buckets (dp={self.dp_total}): " + ", ".join(parts)


def _dp_avail(spec: P, mesh: Mesh, dp_axes) -> tuple:
    used = {
        a
        for e in spec
        if e is not None
        for a in (e if isinstance(e, tuple) else (e,))
    }
    return tuple(
        a for a in dp_axes if int(mesh.shape.get(a, 1)) > 1 and a not in used
    )


def _nontrivial_axes(entry: Any, mesh: Mesh) -> tuple:
    """The axes of one spec entry that actually shard on this mesh.  Specs
    routinely carry size-1 axis names ("model" on a dp-only mesh, "expert"
    on a dense run) — those partition nothing, and eligibility must judge
    the PHYSICAL layout, not the spelling."""
    if entry is None:
        return ()
    axes = entry if isinstance(entry, tuple) else (entry,)
    return tuple(a for a in axes if int(mesh.shape.get(a, 1)) > 1)


def build_bucket_plan(
    abstract_params,
    param_specs,
    moment_specs,
    mesh: Mesh,
    *,
    bucket_mb: float,
    group_fn: Callable,
    dp_axes=("data", "expert"),
) -> Optional[BucketPlan]:
    """Group the param tree's leaves into collective buckets.

    Leaves are grouped by ``group_fn(path)`` (the health plane's
    ``grad_group_of``), groups keep tree order, and consecutive groups are
    coalesced until a bucket holds ``bucket_mb`` MiB of fp32 master weights
    — so a tiny ``bucket_mb`` gives one bucket per group and a huge one
    gives a single bucket.  The returned processing order is REVERSED
    (approximately the backward's gradient-completion order).

    A leaf joins its bucket's combined all-gather only when the packing is
    provably a local reshape: the moment spec shards exactly one dim over
    the full available DP extent and the param spec is physically
    replicated (judged on mesh extents — size-1 axis names like "model" on
    a dp-only mesh don't disqualify; genuinely tp/ep-sharded params fall
    back to GSPMD's per-leaf gather, which keeps bucketing legal on any
    mesh).  Returns None when no DP extent is available (dp_total == 1) —
    bucketing is a no-op there.
    """
    leaves = jax.tree_util.tree_flatten_with_path(abstract_params)[0]
    treedef = jax.tree_util.tree_structure(abstract_params)
    pspecs = treedef.flatten_up_to(param_specs)
    mspecs = treedef.flatten_up_to(moment_specs)

    dp_total = 1
    for a in dp_axes:
        dp_total *= int(mesh.shape.get(a, 1))
    if dp_total == 1:
        return None

    # group leaves in tree order
    order: list = []
    members: dict = {}
    for pos, (path, leaf) in enumerate(leaves):
        g = group_fn(path)
        if g not in members:
            members[g] = []
            order.append(g)
        members[g].append(pos)

    dp_entry = None

    def ag_leaf(pos) -> Optional[AGLeaf]:
        nonlocal dp_entry
        leaf = leaves[pos][1]
        pspec, mspec = pspecs[pos], mspecs[pos]
        if tuple(mspec) == tuple(pspec):
            return None  # not ZeRO-1 sharded (excluded / nothing divides)
        if any(_nontrivial_axes(e, mesh) for e in pspec):
            return None  # param itself model-sharded: per-leaf fallback
        avail = _dp_avail(pspec, mesh, dp_axes)
        entry = avail if len(avail) > 1 else (avail[0] if avail else None)
        if entry is None:
            return None
        sharded = [
            (i, _nontrivial_axes(e, mesh))
            for i, e in enumerate(mspec)
            if _nontrivial_axes(e, mesh)
        ]
        if len(sharded) != 1 or sharded[0][1] != tuple(
                entry if isinstance(entry, tuple) else (entry,)):
            return None
        dim = sharded[0][0]
        shape = tuple(leaf.shape)
        if dim >= len(shape) or shape[dim] % dp_total != 0:
            return None
        size = 1
        for d in shape:
            size *= d
        if size == 0:
            return None
        if dp_entry is None:
            dp_entry = entry
        elif dp_entry != entry:
            return None  # mixed extents: keep the pack uniform
        moved = (shape[dim],) + shape[:dim] + shape[dim + 1:]
        return AGLeaf(pos=pos, dim=dim, cols=size // dp_total,
                      moved_shape=moved)

    threshold = float(bucket_mb) * 2**20
    buckets: list = []
    cur_names: list = []
    cur_idxs: list = []
    cur_ag: list = []
    cur_bytes = 0

    def close():
        nonlocal cur_names, cur_idxs, cur_ag, cur_bytes
        if cur_idxs:
            buckets.append(Bucket(
                name="+".join(cur_names), idxs=tuple(cur_idxs),
                ag=tuple(cur_ag), bytes=cur_bytes,
            ))
        cur_names, cur_idxs, cur_ag, cur_bytes = [], [], [], 0

    for g in reversed(order):
        cur_names.append(g)
        for pos in members[g]:
            cur_idxs.append(pos)
            a = ag_leaf(pos)
            if a is not None:
                cur_ag.append(a)
            leaf = leaves[pos][1]
            size = 1
            for d in leaf.shape:
                size *= d
            cur_bytes += size * 4  # fp32 master
        if cur_bytes >= threshold:
            close()
    close()

    return BucketPlan(
        buckets=tuple(buckets),
        dp_entry=dp_entry,
        dp_total=dp_total,
        num_leaves=len(leaves),
    )


# ---------------------------------------------------------------------------
# The bucketed update (traced — called from optim.adamw.adamw_update)
# ---------------------------------------------------------------------------


def bucketed_update(
    plan: BucketPlan,
    params,
    grads,
    mu,
    nu,
    master,
    masks,
    *,
    mu_fn: Callable,
    nu_fn: Callable,
    upd_fn: Callable,
    prefetch: bool = True,
    collect_packed=None,
):
    """Per-bucket AdamW inner update with combined parameter all-gathers.

    Applies the SAME per-leaf lambdas the monolithic path uses (``mu_fn``,
    ``nu_fn``, ``upd_fn``) bucket by bucket, so the numerics are bitwise
    identical — only the collective structure changes.  For each bucket the
    eligible updated params are cast to param dtype, packed shard-contiguous
    into one ``[dp, cols]`` buffer, and resharded replicated under the
    ``zero1_bucket_ag`` scope: one all-gather per bucket instead of one per
    leaf.  With ``prefetch`` an ``optimization_barrier`` ties bucket k+1's
    grads to bucket k's pre-gather output, staggering the chain so gather k
    overlaps update k+1.

    ``collect_packed`` (a dict, or None): when given, each bucket's packed
    pre-gather ``[dp, cols]`` buffer is recorded under its bucket name — the
    tensor numerics observatory (``telemetry.tensorstats``) reads the exact
    payload the combined all-gather moves.  Purely observational: the traced
    update itself is unchanged.

    Returns ``(new_mu, new_nu, new_master, new_params)`` as trees.
    """
    treedef = jax.tree_util.tree_structure(params)
    p_l = treedef.flatten_up_to(params)
    g_l = treedef.flatten_up_to(grads)
    mu_l = treedef.flatten_up_to(mu)
    nu_l = treedef.flatten_up_to(nu)
    m_l = treedef.flatten_up_to(master)
    w_l = treedef.flatten_up_to(masks)

    n = plan.num_leaves
    out_mu = [None] * n
    out_nu = [None] * n
    out_master = [None] * n
    out_params = [None] * n
    token = None

    for bucket in plan.buckets:
        gb = [g_l[i] for i in bucket.idxs]
        if prefetch and token is not None:
            # stagger: this bucket's inputs wait on the previous bucket's
            # (pre-gather) output, so the previous gather is free to overlap
            # this bucket's compute
            chained = jax.lax.optimization_barrier(tuple(gb) + (token,))
            gb = list(chained[:-1])
        for j, i in enumerate(bucket.idxs):
            g = gb[j]
            nmu = mu_fn(mu_l[i], g)
            nnu = nu_fn(nu_l[i], g)
            nm = upd_fn(m_l[i], nmu, nnu, w_l[i])
            out_mu[i] = nmu
            out_nu[i] = nnu
            out_master[i] = nm
            out_params[i] = nm.astype(p_l[i].dtype)

        if bucket.ag:
            pieces = [
                jnp.moveaxis(out_params[a.pos], a.dim, 0).reshape(
                    plan.dp_total, a.cols)
                for a in bucket.ag
            ]
            packed = (jnp.concatenate(pieces, axis=1) if len(pieces) > 1
                      else pieces[0])
            packed = shd.constrain(packed, P(plan.dp_entry))
            if collect_packed is not None:
                collect_packed[bucket.name] = packed
            with jax.named_scope(BUCKET_AG_SCOPE):
                gathered = shd.constrain(packed, P())
                # the barrier pins the combined gather: without it XLA's
                # slice-through-all-gather rewrite commutes the unpack slices
                # into the gather and splits it back into per-leaf collectives
                gathered = jax.lax.optimization_barrier(gathered)
            off = 0
            for a in bucket.ag:
                piece = jax.lax.slice_in_dim(gathered, off, off + a.cols,
                                             axis=1)
                off += a.cols
                v = piece.reshape(a.moved_shape)
                out_params[a.pos] = jnp.moveaxis(v, 0, a.dim)
            token = packed
        else:
            token = out_mu[bucket.idxs[-1]]

    unflat = jax.tree_util.tree_unflatten
    return (unflat(treedef, out_mu), unflat(treedef, out_nu),
            unflat(treedef, out_master), unflat(treedef, out_params))


# ---------------------------------------------------------------------------
# XLA latency-hiding-scheduler knobs + XLA_FLAGS merging
# ---------------------------------------------------------------------------

#: the TPU flag set ``xla_lhs: true`` exports — async collectives plus the
#: latency-hiding scheduler pass that consumes the bucketed structure.
#: TPU-only spellings: unknown flags are FATAL to the CPU jaxlib's flag
#: parser, so callers must gate on the backend (see ``xla_lhs_flags``).
TPU_LHS_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
)


def xla_lhs_flags(platform: str) -> tuple:
    """The flag set for ``xla_lhs: true`` on ``platform`` ("tpu"/"cpu"/...).

    Only TPU has the latency-hiding scheduler surface; every other backend
    returns empty (the knob is then an explicit no-op the caller should log,
    NOT an error — the same config must run on the CPU smoke)."""
    if str(platform).lower() == "tpu":
        return TPU_LHS_FLAGS
    return ()


def _flag_name(tok: str) -> str:
    return tok.split("=", 1)[0]


def merge_xla_flags(base: str, extra: Iterable[str]) -> tuple:
    """Merge ``extra`` flag tokens into an existing ``XLA_FLAGS`` string.

    User-provided flags WIN: an ``extra`` token whose flag name already
    appears in ``base`` with a different value is dropped and reported in
    ``conflicts`` (the caller warns).  Identical duplicates are dropped
    silently.  Returns ``(merged, conflicts)`` where ``conflicts`` is a list
    of ``(flag_name, base_token, extra_token)`` tuples.  This replaces the
    blind append whose duplicate-flag last-wins behavior was silent.
    """
    base_toks = [t for t in str(base or "").split() if t]
    by_name = {_flag_name(t): t for t in base_toks}
    merged = list(base_toks)
    conflicts = []
    for tok in extra:
        name = _flag_name(tok)
        cur = by_name.get(name)
        if cur is None:
            merged.append(tok)
            by_name[name] = tok
        elif cur != tok:
            conflicts.append((name, cur, tok))
    return " ".join(merged), conflicts
