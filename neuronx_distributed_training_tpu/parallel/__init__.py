"""Parallelism substrate: device mesh, sharding rules, ZeRO-1, pipeline, context parallel."""

from neuronx_distributed_training_tpu.parallel.mesh import (  # noqa: F401
    AXES,
    MeshConfig,
    build_mesh,
)
