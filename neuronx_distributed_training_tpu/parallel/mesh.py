"""Device-mesh construction — the TPU-native replacement for NxD ``parallel_state``.

The reference framework builds explicit process groups for TP/PP/DP/CP/EP
(``neuronx_distributed.parallel_state``, consumed at e.g. reference
``nlp_overrides.py:1274-1285`` and ``base.py:54-57``).  On TPU there is exactly one
piece of global state instead: a ``jax.sharding.Mesh`` whose named axes *are* the
parallel groups.  Collectives over a group become XLA collectives over a mesh axis,
and "which group am I in" questions become PartitionSpecs.

Axis layout (innermost = fastest ICI neighbours):

    (pipe, data, expert, context, model)

- ``model``   — tensor parallelism (and Megatron-style sequence parallelism, which
                shards activations over the same group; reference
                ``config_overview.rst:395-401`` ties SP degree == TP degree).
- ``context`` — context parallelism (ring attention over the sequence axis;
                reference ``base.py:199``, ``modeling_llama.py:484``).
- ``expert``  — expert parallelism for MoE.  Carved out of data parallelism the
                same way NxD carves EP groups from DP ranks: the *true* DP degree
                is ``data * expert`` for dense parameters and the batch.
- ``data``    — the remaining data parallelism (ZeRO-1 shards optimizer state over
                ``data`` × ``expert``).
- ``pipe``    — pipeline parallelism.

The reference derives ``dp = world / (tp * pp * cp)`` (``base.py:54-57``); we do the
same and additionally require ``ep | dp``.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

logger = logging.getLogger(__name__)

# Canonical mesh axis names, outermost-first.
AXES = ("pipe", "data", "expert", "context", "model")

# The compound axis the global batch is sharded over (true data parallelism).
DATA_AXES = ("data", "expert")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Parallel-degree configuration, mirroring the reference's
    ``distributed_strategy`` YAML block (``config_overview.rst:10-41``)."""

    tensor_model_parallel_size: int = 1
    pipeline_model_parallel_size: int = 1
    virtual_pipeline_model_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_model_parallel_size: int = 1
    sequence_parallel: bool = False

    @classmethod
    def from_config(cls, cfg: dict[str, Any]) -> "MeshConfig":
        """Build from a ``distributed_strategy`` config mapping (unknown keys ignored)."""
        ds = dict(cfg or {})
        vp = ds.get("virtual_pipeline_model_parallel_size")
        return cls(
            tensor_model_parallel_size=int(ds.get("tensor_model_parallel_size", 1)),
            pipeline_model_parallel_size=int(ds.get("pipeline_model_parallel_size", 1)),
            virtual_pipeline_model_parallel_size=int(vp) if vp else 1,
            context_parallel_size=int(ds.get("context_parallel_size", 1)),
            expert_model_parallel_size=int(ds.get("expert_model_parallel_size", 1)),
            sequence_parallel=bool(ds.get("sequence_parallel", False)),
        )

    @property
    def tp(self) -> int:
        return self.tensor_model_parallel_size

    @property
    def pp(self) -> int:
        return self.pipeline_model_parallel_size

    @property
    def cp(self) -> int:
        return self.context_parallel_size

    @property
    def ep(self) -> int:
        return self.expert_model_parallel_size

    def validate(self, n_devices: int) -> None:
        for name, v in (
            ("tensor_model_parallel_size", self.tp),
            ("pipeline_model_parallel_size", self.pp),
            ("context_parallel_size", self.cp),
            ("expert_model_parallel_size", self.ep),
            ("virtual_pipeline_model_parallel_size", self.virtual_pipeline_model_parallel_size),
        ):
            if v < 1:
                raise ValueError(f"{name} must be >= 1, got {v}")
        denom = self.tp * self.pp * self.cp
        if n_devices % denom != 0:
            raise ValueError(
                f"world size {n_devices} not divisible by tp*pp*cp = "
                f"{self.tp}*{self.pp}*{self.cp} = {denom}"
            )
        dp = n_devices // denom
        if dp % self.ep != 0:
            raise ValueError(
                f"data-parallel degree {dp} not divisible by "
                f"expert_model_parallel_size {self.ep}"
            )
        if self.sequence_parallel and self.tp == 1:
            raise ValueError(
                "sequence_parallel requires tensor_model_parallel_size > 1 "
                "(reference megatron_base_model.py:76-80)"
            )

    def dp_size(self, n_devices: int) -> int:
        """True data-parallel degree: world / (tp*pp*cp) — reference base.py:54-57."""
        return n_devices // (self.tp * self.pp * self.cp)

    def shape(self, n_devices: int) -> dict[str, int]:
        dp = self.dp_size(n_devices)
        return {
            "pipe": self.pp,
            "data": dp // self.ep,
            "expert": self.ep,
            "context": self.cp,
            "model": self.tp,
        }


def dcn_split(dims: tuple[int, ...], num_slices: int) -> tuple[tuple[int, ...], tuple[int, ...]] | None:
    """Split mesh dims into (DCN shape, per-slice ICI shape) for multi-slice.

    Slow DCN links should carry the least-frequent collectives: gradient
    reduction (``data``) first, else pipeline stage hops (``pipe``) — the
    standard multi-slice recipe ("How to Scale Your Model": DP over DCN, the
    model axes over ICI).  Returns None when neither axis divides
    ``num_slices`` — ``build_mesh`` treats that as a config error (a mesh
    whose TP/CP collectives straddle DCN would be quietly catastrophic for
    step time, so there is deliberately no fallback).
    Pure function of shapes — unit-testable without TPU slices.
    """
    dcn = [1] * len(dims)
    for axis_idx in (AXES.index("data"), AXES.index("pipe")):
        if dims[axis_idx] % num_slices == 0:
            dcn[axis_idx] = num_slices
            ici = list(dims)
            ici[axis_idx] = dims[axis_idx] // num_slices
            return tuple(dcn), tuple(ici)
    return None


def build_mesh(
    config: MeshConfig | None = None,
    devices: Sequence[jax.Device] | None = None,
    **kwargs: Any,
) -> Mesh:
    """Create the global device mesh for a parallel configuration.

    ``devices`` defaults to ``jax.devices()``.  Uses ``mesh_utils`` for
    ICI-topology-aware placement on real TPU slices, falling back to a plain
    reshape (CPU test meshes, odd device counts).

    Multi-slice (devices spanning DCN-connected slices): the ``data`` axis —
    else ``pipe`` — is laid over DCN via ``create_hybrid_device_mesh``, so
    TP/SP/CP/EP collectives ride ICI and only gradient reductions (or pipe
    stage hops) cross the slower inter-slice fabric.
    """
    if config is None:
        config = MeshConfig(**kwargs)
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    config.validate(n)
    shape = config.shape(n)
    dims = tuple(shape[a] for a in AXES)
    assert math.prod(dims) == n

    dev_array = None
    if devices[0].platform == "tpu":
        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        split = None
        if len(slice_ids) > 1:
            split = dcn_split(dims, len(slice_ids))
            if split is None:
                # config error, raised OUTSIDE the try: a mesh whose TP/CP
                # collectives straddle DCN must not silently "fall back"
                raise ValueError(
                    f"multi-slice mesh: neither data={shape['data']} nor "
                    f"pipe={shape['pipe']} divides num_slices="
                    f"{len(slice_ids)}; choose degrees so one does"
                )
        try:
            from jax.experimental import mesh_utils

            if split is not None:
                dcn_shape, ici_shape = split
                dev_array = mesh_utils.create_hybrid_device_mesh(
                    ici_shape, dcn_shape, devices=list(devices)
                )
            else:
                dev_array = mesh_utils.create_device_mesh(
                    dims, devices=list(devices)
                )
        except Exception as e:  # noqa: BLE001 — single-slice only: fall back,
            # but loudly (a topology-oblivious mesh degrades collective
            # bandwidth; mesh_utils raises ValueError for unmappable
            # topologies too, so no exception class is excluded here)
            if split is not None:
                # multi-slice: a plain reshape would interleave slices along
                # the inner axes — TP/CP collectives straddling DCN, the
                # outcome the indivisible-config raise above exists to prevent
                raise
            logger.warning(
                "mesh_utils device-mesh construction (%s) failed (%s); falling "
                "back to plain reshape — ICI-topology-aware placement lost",
                dims, e
            )
            dev_array = None
    if dev_array is None:
        dev_array = np.asarray(devices).reshape(dims)
    return Mesh(dev_array, AXES)


def batch_partition_spec(mesh: Mesh, *, context_sharded_seq: bool = False) -> PartitionSpec:
    """PartitionSpec for a ``[batch, seq, ...]`` global batch.

    Batch dim shards over the compound DP axis ``(data, expert)``; when context
    parallelism is active the sequence dim shards over ``context`` (the TPU-native
    form of the reference's ``get_batch_on_this_context_parallel_rank`` seq-split,
    ``base.py:199``).
    """
    if context_sharded_seq and mesh.shape.get("context", 1) > 1:
        return PartitionSpec(DATA_AXES, "context")
    return PartitionSpec(DATA_AXES)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return int(mesh.shape.get(axis, 1))


def dp_degree(mesh: Mesh) -> int:
    """True data-parallel degree (``data`` × ``expert`` axes)."""
    return mesh_axis_size(mesh, "data") * mesh_axis_size(mesh, "expert")
