"""Pipeline parallelism over the ``pipe`` mesh axis.

TPU-native replacement for NxD's pipeline engine (``NxDPPModel.run_train`` —
reference ``base.py:374-383`` — with its FX tracer/auto-partitioner and 1F1B
P2P schedule, configured by ``pipeline_config`` at ``base.py:136-157``).
Re-designed rather than translated:

- **no tracer**: models here are stacked layer pytrees; "partitioning" is just
  sharding the leading ``[num_layers, ...]`` dim over ``pipe``
  (``auto_partition`` with equal cuts falls out; manual ``pipeline_cuts`` are
  unnecessary when stages are equal-sized by construction);
- **schedule**: microbatches stream through stages inside one jitted
  ``lax.scan``; stage outputs move over ICI with ``lax.ppermute``.  Forward is
  the classic GPipe wavefront (num_micro + pp - 1 ticks); **backward is
  derived by autodiff** — ``scan``/``ppermute`` transpose to the reverse
  wavefront, giving a full fwd-then-bwd schedule.  Per-stage activations are
  rematerialized (``jax.checkpoint``) so only stage *inputs* are saved, the
  same memory class as the reference's 1F1B-with-recompute;
- **loss on last stage** (reference ``base.py:378-381``): the lm-head/loss
  hook runs on every rank (SPMD — the non-last ranks compute on garbage and
  their result is masked), but only the scalar loss crosses ranks (psum), not
  activations;
- embedding/head weights live OUTSIDE the pipelined stack and are replicated
  over ``pipe`` (they are still TP-sharded over ``model`` by GSPMD's auto
  axes) — a deliberate departure from the reference's stage-0/stage-N
  placement + embedding-tying all-reduce protocol (``module.py:28-157``).

``shard_map`` is manual over ``pipe`` only (``axis_names={"pipe"}``): data/
tensor/sequence sharding inside the body remains GSPMD-driven, so the same
model code runs under any tp x dp combination.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from neuronx_distributed_training_tpu.parallel import sharding as shd

PIPE_AXIS = "pipe"

# EmbedFn:    (params, microbatch_dict) -> activations [mb, s, h]
# StageFn:    (local_layer_params, activations, microbatch_dict) -> activations
# LossFn:     (params, activations, microbatch_dict) -> (scalar loss, scalar denom)
EmbedFn = Callable[[Any, dict], jax.Array]
StageFn = Callable[[Any, jax.Array, dict], jax.Array]
LossFn = Callable[[Any, jax.Array, dict], tuple]


def stage_layer_slice(num_layers: int, pp: int) -> int:
    if num_layers % pp != 0:
        raise ValueError(f"num_layers {num_layers} not divisible by pp {pp}")
    return num_layers // pp


def pipeline_loss(
    params: Any,
    layer_params: Any,  # stacked [num_layers, ...]; dim 0 sharded over "pipe"
    microbatches: dict[str, jax.Array],  # leaves [num_micro, mb, ...]
    *,
    embed_fn: EmbedFn,
    stage_fn: StageFn,
    loss_fn: LossFn,
    mesh=None,
    num_microbatches: Optional[int] = None,
) -> jax.Array:
    """Scalar pipeline-parallel loss (mean over microbatches).

    Falls back to a plain sequential microbatch loop when pp == 1, so the same
    entry point drives both pipelined and unpipelined configs.
    """
    mesh = mesh or shd.active_mesh()
    pp = int(mesh.shape.get(PIPE_AXIS, 1)) if mesh is not None else 1
    nm = num_microbatches or jax.tree_util.tree_leaves(microbatches)[0].shape[0]

    if pp == 1:
        def body(acc, mb):
            x = embed_fn(params, mb)
            x = stage_fn(layer_params, x, mb)
            loss, denom = loss_fn(params, x, mb)
            return (acc[0] + loss, acc[1] + denom), None

        (loss_sum, denom_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), microbatches
        )
        return loss_sum / jnp.maximum(denom_sum, 1.0)

    body = functools.partial(
        _pipeline_body,
        embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn, pp=pp, nm=nm,
    )
    from jax.sharding import PartitionSpec as P

    fn = jax.shard_map(
        body,
        mesh=mesh,
        # manual over pipe only: layer stack sharded on dim 0; params and
        # microbatches replicated across pipe (GSPMD still shards them over
        # data/model inside)
        in_specs=(P(), P(PIPE_AXIS), P()),
        out_specs=P(),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )
    return fn(params, layer_params, microbatches)


def _pipeline_body(params, local_layers, microbatches, *, embed_fn, stage_fn,
                   loss_fn, pp, nm):
    """Per-pipe-rank wavefront loop (inside shard_map, manual over "pipe")."""
    rank = jax.lax.axis_index(PIPE_AXIS)
    is_first = rank == 0
    is_last = rank == pp - 1

    mb0 = jax.tree_util.tree_map(lambda x: x[0], microbatches)
    x0 = embed_fn(params, mb0)  # shape/dtype template for the stream buffer

    # rematerialize stage activations in backward: only stage inputs are saved
    compute = jax.checkpoint(stage_fn)

    send_perm = [(i, i + 1) for i in range(pp - 1)]  # rank 0 receives zeros

    def tick(carry, t):
        recv, loss_acc, denom_acc = carry
        # stage-0 input: microbatch t (clamped; ticks past nm-1 are drain-only)
        t_in = jnp.clip(t, 0, nm - 1)
        mb_in = jax.tree_util.tree_map(lambda x: x[t_in], microbatches)
        fresh = embed_fn(params, mb_in)
        x = jnp.where(is_first, fresh, recv)
        y = compute(local_layers, x, mb_in)

        # last stage: microbatch t - (pp-1) exits the pipe at this tick
        t_out = t - (pp - 1)
        t_out_c = jnp.clip(t_out, 0, nm - 1)
        mb_out = jax.tree_util.tree_map(lambda x: x[t_out_c], microbatches)
        loss, denom = loss_fn(params, y, mb_out)
        valid = jnp.logical_and(is_last, jnp.logical_and(t_out >= 0, t_out < nm))
        loss_acc = loss_acc + jnp.where(valid, loss, 0.0)
        denom_acc = denom_acc + jnp.where(valid, denom, 0.0)

        recv = jax.lax.ppermute(y, PIPE_AXIS, send_perm)
        return (recv, loss_acc, denom_acc), None

    zeros = jnp.zeros_like(x0)
    (_, loss_acc, denom_acc), _ = jax.lax.scan(
        tick,
        (zeros, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        jnp.arange(nm + pp - 1),
    )
    # only the last rank's accumulators are real; psum broadcasts the scalars
    loss_total = jax.lax.psum(loss_acc, PIPE_AXIS)
    denom_total = jax.lax.psum(denom_acc, PIPE_AXIS)
    return loss_total / jnp.maximum(denom_total, 1.0)
