"""Pipeline parallelism over the ``pipe`` mesh axis.

TPU-native replacement for NxD's pipeline engine (``NxDPPModel.run_train`` —
reference ``base.py:374-383`` — with its FX tracer/auto-partitioner and 1F1B
P2P schedule, configured by ``pipeline_config`` at ``base.py:136-157``).
Re-designed rather than translated:

- **no tracer**: models here are stacked layer pytrees; "partitioning" is just
  sharding the leading ``[num_layers, ...]`` dim over ``pipe``
  (``auto_partition`` with equal cuts falls out; manual ``pipeline_cuts`` are
  unnecessary when stages are equal-sized by construction);
- **schedule**: microbatches stream through stages inside one jitted
  ``lax.scan``; stage outputs move over ICI with ``lax.ppermute``.  Forward is
  the classic GPipe wavefront (num_micro + pp - 1 ticks); **backward is
  derived by autodiff** — ``scan``/``ppermute`` transpose to the reverse
  wavefront, giving a full fwd-then-bwd schedule.  Per-stage activations are
  rematerialized (``jax.checkpoint``) so only stage *inputs* are saved, the
  same memory class as the reference's 1F1B-with-recompute;
- **loss on last stage** (reference ``base.py:378-381``): the lm-head/loss
  hook runs on every rank (SPMD — the non-last ranks compute on garbage and
  their result is masked), but only the scalar loss crosses ranks (psum), not
  activations;
- embedding/head weights live OUTSIDE the pipelined stack and are replicated
  over ``pipe`` (they are still TP-sharded over ``model`` by GSPMD's auto
  axes) — a deliberate departure from the reference's stage-0/stage-N
  placement + embedding-tying all-reduce protocol (``module.py:28-157``).

``shard_map`` is manual over ``pipe`` only (``axis_names={"pipe"}``): data/
tensor/sequence sharding inside the body remains GSPMD-driven, so the same
model code runs under any tp x dp combination.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from neuronx_distributed_training_tpu.parallel import sharding as shd

PIPE_AXIS = "pipe"

# EmbedFn:    (params, microbatch_dict) -> activations [mb, s, h]
# StageFn:    (local_layer_params, activations, microbatch_dict) -> activations,
#             or (activations, aux_scalar) when ``stage_aux=True`` (the MoE
#             router-loss carry: each stage contributes its local layers' aux)
# LossFn:     (params, activations, microbatch_dict) -> (scalar loss, scalar denom)
# The microbatch dict passed to StageFn additionally carries ``_chunk`` (the
# virtual-pipeline chunk index, 0 when vp == 1) so stages can derive
# stage-unique PRNG keys for dropout.
EmbedFn = Callable[[Any, dict], jax.Array]
StageFn = Callable[[Any, jax.Array, dict], jax.Array]
LossFn = Callable[[Any, jax.Array, dict], tuple]


def stage_layer_slice(num_layers: int, pp: int, vp: int = 1) -> int:
    if num_layers % (pp * vp) != 0:
        raise ValueError(
            f"num_layers {num_layers} not divisible by pp*vp = {pp}*{vp}"
        )
    return num_layers // (pp * vp)


def to_interleaved(layer_stack: Any, pp: int, vp: int) -> Any:
    """[L, ...] stacked layers -> [vp, pp, Lc, ...] stage-major layout.

    Stage ``s = c*pp + r`` (chunk c on rank r) covers layers
    ``[s*Lc, (s+1)*Lc)`` — the interleaved assignment of the reference's
    ``virtual_pipeline_model_parallel_size`` (``base.py:85,155``).  Pure
    reshape: layer index ``l = (c*pp + r)*Lc + k`` has dims ordered (c, r, k),
    so the ``pp`` dim can be sharded over ``pipe`` without any transpose.
    """

    def one(x):
        L = x.shape[0]
        lc = stage_layer_slice(L, pp, vp)
        return x.reshape((vp, pp, lc) + x.shape[1:])

    return jax.tree_util.tree_map(one, layer_stack)


def from_interleaved(layer_stack: Any) -> Any:
    """Inverse of ``to_interleaved``: [vp, pp, Lc, ...] -> [L, ...]."""

    def one(x):
        vp, pp, lc = x.shape[:3]
        return x.reshape((vp * pp * lc,) + x.shape[3:])

    return jax.tree_util.tree_map(one, layer_stack)


def pipeline_loss(
    params: Any,
    layer_params: Any,  # vp==1: [num_layers, ...] dim0 over "pipe";
                        # vp>1: interleaved [vp, pp, Lc, ...] dim1 over "pipe"
    microbatches: dict[str, jax.Array],  # leaves [num_micro, mb, ...]
    *,
    embed_fn: EmbedFn,
    stage_fn: StageFn,
    loss_fn: LossFn,
    mesh=None,
    num_microbatches: Optional[int] = None,
    virtual_pipeline_size: int = 1,
    stage_aux: bool = False,
    aux_scale: float = 0.0,
) -> jax.Array:
    """Scalar pipeline-parallel loss (mean over microbatches).

    ``virtual_pipeline_size > 1`` runs the interleaved/circular schedule
    (reference VPP, ``base.py:85,155``): each rank holds ``vp`` non-adjacent
    layer chunks (pass ``to_interleaved(layers, pp, vp)``), microbatches cycle
    through the ranks ``vp`` times, and per-rank utilization improves from
    ``nm/(nm+pp-1)`` to ``nm*vp/(nm*vp+pp-1)``.

    Falls back to a plain sequential microbatch loop when pp == 1, so the same
    entry point drives both pipelined and unpipelined configs.
    """
    mesh = mesh or shd.active_mesh()
    pp = int(mesh.shape.get(PIPE_AXIS, 1)) if mesh is not None else 1
    nm = num_microbatches or jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    vp = virtual_pipeline_size
    if vp > 1 and 1 < pp and nm < pp:
        # chunk c+1 reads the circular store at tick c*nm + m, but the last
        # rank's chunk-c output is only parked at tick c*nm + m + pp — with
        # nm < pp the read precedes the write and the loss is silently wrong
        raise ValueError(
            f"interleaved pipeline needs num_microbatches >= pp "
            f"(got nm={nm}, pp={pp}, vp={vp})"
        )

    if pp == 1:
        if vp > 1:
            layer_params = from_interleaved(layer_params)

        def body(acc, mb):
            x = embed_fn(params, mb)
            out = stage_fn(layer_params, x, {**mb, "_chunk": jnp.zeros((), jnp.int32)})
            x, s_aux = out if stage_aux else (out, jnp.zeros((), jnp.float32))
            loss, denom = loss_fn(params, x, mb)
            return (acc[0] + loss, acc[1] + denom, acc[2] + s_aux), None

        (loss_sum, denom_sum, aux_sum), _ = jax.lax.scan(
            body,
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
             jnp.zeros((), jnp.float32)),
            microbatches,
        )
        return loss_sum / jnp.maximum(denom_sum, 1.0) + aux_scale * aux_sum

    body = functools.partial(
        _pipeline_body,
        embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn, pp=pp, nm=nm, vp=vp,
        stage_aux=stage_aux, aux_scale=aux_scale,
    )
    from jax.sharding import PartitionSpec as P

    layer_spec = P(None, PIPE_AXIS) if vp > 1 else P(PIPE_AXIS)
    fn = jax.shard_map(
        body,
        mesh=mesh,
        # manual over pipe only: params and microbatches replicated across pipe
        # (GSPMD still shards them over data/model inside)
        in_specs=(P(), layer_spec, P()),
        out_specs=P(),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )
    return fn(params, layer_params, microbatches)


def _pipeline_body(params, local_layers, microbatches, *, embed_fn, stage_fn,
                   loss_fn, pp, nm, vp, stage_aux=False, aux_scale=0.0):
    """Per-pipe-rank circular wavefront loop (inside shard_map, manual "pipe").

    Schedule: rank ``r`` at tick ``t`` works on work-index ``w = t - r`` —
    microbatch ``m = w mod nm`` of chunk ``c = w // nm``.  Chunk hand-off
    between chunks rides a per-microbatch circular store on rank 0 (outputs of
    the last rank come back around the cyclic ring one tick later and wait in
    ``circ_storage`` until chunk ``c+1``'s slot).  Total ticks
    ``nm*vp + pp - 1``.  With vp == 1 this is the plain GPipe wavefront.
    """
    rank = jax.lax.axis_index(PIPE_AXIS)
    is_first = rank == 0
    is_last = rank == pp - 1

    # normalize local layer layout to [vp, Lc, ...]
    if vp > 1:
        local_layers = jax.tree_util.tree_map(
            lambda x: jnp.squeeze(x, axis=1), local_layers
        )
    else:
        local_layers = jax.tree_util.tree_map(lambda x: x[None], local_layers)

    mb0 = jax.tree_util.tree_map(lambda x: x[0], microbatches)
    x0 = embed_fn(params, mb0)  # shape/dtype template for the stream buffers

    # rematerialize stage activations in backward: only stage inputs are saved
    compute = jax.checkpoint(stage_fn)
    # the embed and loss hooks run EVERY tick; un-rematerialized, their
    # residuals are retained for all nm+pp-1 ticks — the loss hook's
    # [mbs, s, vocab] logits dominate the high-water (measured 4.5x the
    # unpipelined step at pp=4/nm=16, tools/pp_memory_probe.py).
    # remat brings the schedule back to the stage-input O(nm * mbs*s*h)
    # class, the same trade the reference's 1F1B-with-recompute makes.
    embed = jax.checkpoint(embed_fn)
    compute_loss = jax.checkpoint(loss_fn)

    cyclic = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        recv, circ, loss_acc, denom_acc, aux_acc = carry

        if vp > 1:
            # rank 0: recv holds last-rank output from tick t-1 (work index
            # w_back); park it in the circular store for its next chunk
            w_back = t - 1 - (pp - 1)
            m_back = jnp.clip(jnp.remainder(w_back, nm), 0, nm - 1)
            back_valid = jnp.logical_and(w_back >= 0, w_back < nm * (vp - 1))
            slot = jax.lax.dynamic_index_in_dim(circ, m_back, 0, keepdims=False)
            circ = jax.lax.dynamic_update_index_in_dim(
                circ, jnp.where(back_valid, recv, slot), m_back, 0
            )

        w = t - rank
        w_c = jnp.clip(w, 0, nm * vp - 1)
        m = jnp.remainder(w_c, nm)
        c = w_c // nm
        mb = jax.tree_util.tree_map(
            lambda x: jax.lax.dynamic_index_in_dim(x, m, 0, keepdims=False),
            microbatches,
        )
        fresh = embed(params, mb)
        if vp > 1:
            parked = jax.lax.dynamic_index_in_dim(circ, m, 0, keepdims=False)
            first_in = jnp.where(c == 0, fresh, parked)
        else:
            first_in = fresh
        x = jnp.where(is_first, first_in, recv)

        lp_c = jax.tree_util.tree_map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            local_layers,
        )
        out = compute(lp_c, x, {**mb, "_chunk": c})
        y, s_aux = out if stage_aux else (out, jnp.zeros((), jnp.float32))
        # every rank+chunk contributes its local layers' aux once per valid
        # work index (the MoE router-loss carry: psum over pipe at the end
        # sums over ALL layers, exactly like the unpipelined scan carry)
        work_valid = jnp.logical_and(w >= 0, w < nm * vp)
        aux_acc = aux_acc + jnp.where(work_valid, s_aux, 0.0)

        loss, denom = compute_loss(params, y, mb)
        valid = jnp.logical_and(
            jnp.logical_and(is_last, c == vp - 1), jnp.logical_and(w >= 0, w < nm * vp)
        )
        loss_acc = loss_acc + jnp.where(valid, loss, 0.0)
        denom_acc = denom_acc + jnp.where(valid, denom, 0.0)

        recv = jax.lax.ppermute(y, PIPE_AXIS, cyclic)
        return (recv, circ, loss_acc, denom_acc, aux_acc), None

    zeros = jnp.zeros_like(x0)
    circ0 = (
        jnp.zeros((nm,) + x0.shape, x0.dtype) if vp > 1 else jnp.zeros((1, 1), x0.dtype)
    )
    (_, _, loss_acc, denom_acc, aux_acc), _ = jax.lax.scan(
        tick,
        (zeros, circ0, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
         jnp.zeros((), jnp.float32)),
        jnp.arange(nm * vp + pp - 1),
    )
    # only the last rank's accumulators are real; psum broadcasts the scalars
    loss_total = jax.lax.psum(loss_acc, PIPE_AXIS)
    denom_total = jax.lax.psum(denom_acc, PIPE_AXIS)
    aux_total = jax.lax.psum(aux_acc, PIPE_AXIS)
    return loss_total / jnp.maximum(denom_total, 1.0) + aux_scale * aux_total
