"""Pipeline parallelism over the ``pipe`` mesh axis.

TPU-native replacement for NxD's pipeline engine (``NxDPPModel.run_train`` —
reference ``base.py:374-383`` — with its FX tracer/auto-partitioner and 1F1B
P2P schedule, configured by ``pipeline_config`` at ``base.py:136-157``).
Re-designed rather than translated:

- **no tracer**: models here are stacked layer pytrees; "partitioning" is just
  sharding the leading ``[num_layers, ...]`` dim over ``pipe``
  (``auto_partition`` with equal cuts falls out; manual ``pipeline_cuts`` are
  unnecessary when stages are equal-sized by construction);
- **schedule**: microbatches stream through stages inside one jitted
  ``lax.scan``; stage outputs move over ICI with ``lax.ppermute``.  Forward is
  the classic GPipe wavefront (num_micro + pp - 1 ticks); **backward is
  derived by autodiff** — ``scan``/``ppermute`` transpose to the reverse
  wavefront, giving a full fwd-then-bwd schedule.  Per-stage activations are
  rematerialized (``jax.checkpoint``) so only stage *inputs* are saved, the
  same memory class as the reference's 1F1B-with-recompute;
- **loss OUTSIDE the wavefront, balanced over ranks** (vs the reference's
  last-stage-only loss, ``base.py:378-381``): each completed microbatch's
  last-stage output is routed in one tick-uniform ppermute hop to rank
  ``m % pp`` and parked there; the lm-head + CE then run ONCE, outside the
  manual region, with the microbatch dim sharded over ``pipe``.  Total head FLOPs equal the unpipelined step (no per-rank
  redundancy, no warmup/cooldown ticks), and the head's wall-clock is
  ``nm/pp`` per rank instead of the reference's ``nm``-serial on the last
  stage.  (A per-rank ``lax.cond`` gate is NOT an option: GSPMD inserts
  collective-permutes inside the hooks whose rendezvous needs every device,
  so a pipe-divergent branch deadlocks — verified on the 8-device mesh.)
- **embedding also outside the wavefront**: all microbatch embeddings are
  computed once under plain GSPMD (pipe-sharded round-robin, gather path —
  the partitioner's gather-transpose crash only bites inside the manual
  submesh) and routed to rank 0 tick-by-tick with a tick-uniform
  switch+ppermute.  Net effect (tools/pp_flops_probe.py): pp=4 compiled
  FLOPs within 2.1% of the unpipelined step at equal tokens — the residual
  is bubble-tick stage compute, which costs no wall-clock;
- embedding/head weights live OUTSIDE the pipelined stack and are replicated
  over ``pipe`` (they are still TP-sharded over ``model`` by GSPMD's auto
  axes) — a deliberate departure from the reference's stage-0/stage-N
  placement + embedding-tying all-reduce protocol (``module.py:28-157``).

``shard_map`` is manual over ``pipe`` only (``axis_names={"pipe"}``): data/
tensor/sequence sharding inside the body remains GSPMD-driven, so the same
model code runs under any tp x dp combination.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_training_tpu.parallel import sharding as shd

PIPE_AXIS = "pipe"

# EmbedFn:    (params, microbatch_dict) -> activations [mb, s, h]
# StageFn:    (local_layer_params, activations, microbatch_dict) -> activations,
#             or (activations, aux_scalar) when ``stage_aux=True`` (the MoE
#             router-loss carry: each stage contributes its local layers' aux)
# LossFn:     (params, activations, microbatch_dict) -> (scalar loss, scalar denom)
# The microbatch dict passed to StageFn additionally carries ``_chunk`` (the
# virtual-pipeline chunk index, 0 when vp == 1) so stages can derive
# stage-unique PRNG keys for dropout.
EmbedFn = Callable[[Any, dict], jax.Array]
StageFn = Callable[[Any, jax.Array, dict], jax.Array]
LossFn = Callable[[Any, jax.Array, dict], tuple]


def stage_layer_slice(num_layers: int, pp: int, vp: int = 1) -> int:
    if num_layers % (pp * vp) != 0:
        raise ValueError(
            f"num_layers {num_layers} not divisible by pp*vp = {pp}*{vp}"
        )
    return num_layers // (pp * vp)


def to_interleaved(layer_stack: Any, pp: int, vp: int) -> Any:
    """[L, ...] stacked layers -> [vp, pp, Lc, ...] stage-major layout.

    Stage ``s = c*pp + r`` (chunk c on rank r) covers layers
    ``[s*Lc, (s+1)*Lc)`` — the interleaved assignment of the reference's
    ``virtual_pipeline_model_parallel_size`` (``base.py:85,155``).  Pure
    reshape: layer index ``l = (c*pp + r)*Lc + k`` has dims ordered (c, r, k),
    so the ``pp`` dim can be sharded over ``pipe`` without any transpose.
    """

    def one(x):
        L = x.shape[0]
        lc = stage_layer_slice(L, pp, vp)
        return x.reshape((vp, pp, lc) + x.shape[1:])

    return jax.tree_util.tree_map(one, layer_stack)


def from_interleaved(layer_stack: Any) -> Any:
    """Inverse of ``to_interleaved``: [vp, pp, Lc, ...] -> [L, ...]."""

    def one(x):
        vp, pp, lc = x.shape[:3]
        return x.reshape((vp * pp * lc,) + x.shape[3:])

    return jax.tree_util.tree_map(one, layer_stack)


def pipeline_loss(
    params: Any,
    layer_params: Any,  # vp==1: [num_layers, ...] dim0 over "pipe";
                        # vp>1: interleaved [vp, pp, Lc, ...] dim1 over "pipe"
    microbatches: dict[str, jax.Array],  # leaves [num_micro, mb, ...]
    *,
    embed_fn: EmbedFn,
    stage_fn: StageFn,
    loss_fn: LossFn,
    mesh=None,
    num_microbatches: Optional[int] = None,
    virtual_pipeline_size: int = 1,
    stage_aux: bool = False,
    aux_scale: float = 0.0,
) -> jax.Array:
    """Scalar pipeline-parallel loss (mean over microbatches).

    ``virtual_pipeline_size > 1`` runs the interleaved/circular schedule
    (reference VPP, ``base.py:85,155``): each rank holds ``vp`` non-adjacent
    layer chunks (pass ``to_interleaved(layers, pp, vp)``), microbatches cycle
    through the ranks ``vp`` times, and per-rank utilization improves from
    ``nm/(nm+pp-1)`` to ``nm*vp/(nm*vp+pp-1)``.

    Falls back to a plain sequential microbatch loop when pp == 1, so the same
    entry point drives both pipelined and unpipelined configs.
    """
    mesh = mesh or shd.active_mesh()
    pp = int(mesh.shape.get(PIPE_AXIS, 1)) if mesh is not None else 1
    nm = num_microbatches or jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    vp = virtual_pipeline_size
    if vp > 1 and 1 < pp and nm < pp:
        # chunk c+1 reads the circular store at tick c*nm + m, but the last
        # rank's chunk-c output is only parked at tick c*nm + m + pp — with
        # nm < pp the read precedes the write and the loss is silently wrong
        raise ValueError(
            f"interleaved pipeline needs num_microbatches >= pp "
            f"(got nm={nm}, pp={pp}, vp={vp})"
        )

    if pp == 1:
        if vp > 1:
            layer_params = from_interleaved(layer_params)
        # same remat class as the pp>1 wavefront: per microbatch only the
        # stage input is saved (without this, the scan retains every layer's
        # activations for all nm microbatches)
        stage_ck = jax.checkpoint(stage_fn)

        def body(acc, mb):
            x = embed_fn(params, mb)
            out = stage_ck(layer_params, x, {**mb, "_chunk": jnp.zeros((), jnp.int32)})
            x, s_aux = out if stage_aux else (out, jnp.zeros((), jnp.float32))
            loss, denom = loss_fn(params, x, mb)
            return (acc[0] + loss, acc[1] + denom, acc[2] + s_aux), None

        (loss_sum, denom_sum, aux_sum), _ = jax.lax.scan(
            body,
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
             jnp.zeros((), jnp.float32)),
            microbatches,
        )
        return loss_sum / jnp.maximum(denom_sum, 1.0) + aux_scale * aux_sum

    from jax.sharding import PartitionSpec as P

    # round-robin layout shared by the embed feed and the loss parking:
    # row g = r*slots + l <-> microbatch m = l*pp + r, dim 0 sharded over pipe
    slots = -(-nm // pp)
    g = np.arange(pp * slots)
    m_of_g = (g % slots) * pp + g // slots
    real = m_of_g < nm
    m_idx = np.where(real, m_of_g, 0)
    mb_perm = jax.tree_util.tree_map(lambda x: x[m_idx], microbatches)

    # ---- embedding, once, outside the manual region --------------------
    # Per-device FLOPs = (nm/pp) embeds (vs every-rank-every-tick inside the
    # wavefront), and the hook may use the plain gather path — the SPMD
    # partitioner's gather-transpose CHECK-crash only bites inside the manual
    # pipe submesh.  Rank m % pp holds microbatch m's embedding; the body
    # routes it to rank 0 at tick m with a tick-uniform switch + ppermute.
    emb = jax.vmap(lambda m: embed_fn(params, m))(mb_perm)
    # constrain ONLY the leading (pipe) dim: the trailing dims keep the
    # hook's own sharding (batch over data, seq over model under SP) — a bare
    # P("pipe") would pin them replicated and all-gather the whole global
    # batch's embeddings across data
    unc = P.UNCONSTRAINED
    emb = shd.constrain(emb, P(PIPE_AXIS, *([unc] * (emb.ndim - 1))))

    body = functools.partial(
        _pipeline_body,
        stage_fn=stage_fn, pp=pp, nm=nm, vp=vp, slots=slots,
        stage_aux=stage_aux,
    )
    layer_spec = P(None, PIPE_AXIS) if vp > 1 else P(PIPE_AXIS)
    fn = shd.shard_map(
        body,
        mesh=mesh,
        # manual over pipe only: layers sharded on their pipe dim,
        # microbatches replicated across pipe (GSPMD still shards them over
        # data/model inside); the embed feed and the parked outputs are
        # pipe-sharded on dim 0.  (params themselves are not an operand —
        # the embed and loss hooks, the only consumers, run outside.)
        in_specs=(layer_spec, P(), P(PIPE_AXIS)),
        # aux comes back as a [pp] pipe-tiled vector summed OUTSIDE the manual
        # region (not an in-body psum + replicated-scalar out): the replicated
        # scalar's transpose trips legacy shard_map's spec check when a
        # nonzero aux cotangent flows (MoE router loss under jax.grad), while
        # the tiled sum transposes cleanly on every jax version
        out_specs=(P(PIPE_AXIS), P(PIPE_AXIS)),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )
    parked, aux_ranks = fn(layer_params, microbatches, emb)
    aux_total = jnp.sum(aux_ranks)

    # ---- head + CE, once, outside the manual region --------------------
    # parked row g holds microbatch m_of_g's last-stage output (same layout
    # as the embed feed), sharded over pipe — the loss below is pipe-parallel.

    def resh(x):  # [pp*slots, ...] -> [slots, pp, ...]; pp dim stays sharded
        return jnp.swapaxes(x.reshape((pp, slots) + x.shape[1:]), 0, 1)

    y_r = resh(parked)
    mb_r = jax.tree_util.tree_map(resh, mb_perm)
    mask_r = jnp.swapaxes(
        jnp.asarray(real, jnp.float32).reshape(pp, slots), 0, 1
    )
    # remat: per scan step only (y_i, mb_i) are saved; head/CE intermediates
    # (the [*, s, vocab]-class buffers) are recomputed in backward
    vloss = jax.checkpoint(
        jax.vmap(lambda y, mb: loss_fn(params, y, mb), in_axes=(0, 0))
    )

    def lbody(acc, xs):
        y_i, mb_i, mk = xs
        l_v, d_v = vloss(y_i, mb_i)
        return (acc[0] + jnp.sum(l_v * mk), acc[1] + jnp.sum(d_v * mk)), None

    (loss_sum, denom_sum), _ = jax.lax.scan(
        lbody,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (y_r, mb_r, mask_r),
    )
    return loss_sum / jnp.maximum(denom_sum, 1.0) + aux_scale * aux_total


def _pipeline_body(local_layers, microbatches, emb, *, stage_fn,
                   pp, nm, vp, slots, stage_aux=False):
    """Per-pipe-rank circular wavefront loop (inside shard_map, manual "pipe").

    Schedule: rank ``r`` at tick ``t`` works on work-index ``w = t - r`` —
    microbatch ``m = w mod nm`` of chunk ``c = w // nm``.  Chunk hand-off
    between chunks rides a per-microbatch circular store on rank 0 (outputs of
    the last rank come back around the cyclic ring one tick later and wait in
    ``circ_storage`` until chunk ``c+1``'s slot).  Total ticks
    ``nm*vp + pp - 1``.  With vp == 1 this is the plain GPipe wavefront.

    ``emb [slots, mb, s, h]`` is this rank's round-robin share of the
    pre-computed microbatch embeddings (microbatch ``m`` lives on rank
    ``m % pp`` at slot ``m // pp``); the body routes slot ``t // pp`` from
    rank ``t % pp`` to rank 0 at tick ``t`` — both the branch index and the
    ``t < nm`` gate depend only on the tick, so every device takes the same
    path and the collective-permute inside is safe (a RANK-dependent gate
    would deadlock: GSPMD collectives need every device at the rendezvous).

    Returns ``(parked, aux)``: ``parked [slots, mb, s, h]`` holds the
    final-chunk outputs of the microbatches this rank parks (same layout as
    ``emb``) — the caller computes the loss over them outside the manual
    region — and ``aux [1]`` is this rank's MoE router-aux contribution (the
    caller sums the pipe-tiled vector; summing outside instead of an in-body
    psum keeps the backward legal on legacy shard_map).
    """
    rank = jax.lax.axis_index(PIPE_AXIS)
    is_first = rank == 0
    is_last = rank == pp - 1

    # normalize local layer layout to [vp, Lc, ...]
    if vp > 1:
        local_layers = jax.tree_util.tree_map(
            lambda x: jnp.squeeze(x, axis=1), local_layers
        )
    else:
        local_layers = jax.tree_util.tree_map(lambda x: x[None], local_layers)

    x0 = emb[0]  # shape/dtype template for the stream buffers

    # rematerialize stage activations in backward: only stage inputs are
    # saved — the stage-input O(nm * mbs*s*h) class, the same trade the
    # reference's 1F1B-with-recompute makes.  (The embed and loss hooks left
    # the tick loop entirely — see pipeline_loss.)  The per-chunk layer
    # slicing happens INSIDE the checkpointed region: sliced with a traced
    # chunk index OUTSIDE it, the slice becomes a per-tick residual the scan
    # stacks — a params-sized save every tick (measured 0.5 GiB x L x nm at
    # 70B shape, tools/pp_memory_flagship.py) instead of one loop-invariant
    # reference to the param buffer.
    def _stage_sliced(ll, c, x, mb):
        lp_c = jax.tree_util.tree_map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            ll,
        )
        return stage_fn(lp_c, x, mb)

    compute = jax.checkpoint(_stage_sliced)

    cyclic = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        recv, circ, park, aux_acc = carry

        if vp > 1:
            # rank 0: recv holds last-rank output from tick t-1 (work index
            # w_back); park it in the circular store for its next chunk
            w_back = t - 1 - (pp - 1)
            m_back = jnp.clip(jnp.remainder(w_back, nm), 0, nm - 1)
            back_valid = jnp.logical_and(w_back >= 0, w_back < nm * (vp - 1))
            slot = jax.lax.dynamic_index_in_dim(circ, m_back, 0, keepdims=False)
            circ = jax.lax.dynamic_update_index_in_dim(
                circ, jnp.where(back_valid, recv, slot), m_back, 0
            )

        w = t - rank
        w_c = jnp.clip(w, 0, nm * vp - 1)
        m = jnp.remainder(w_c, nm)
        c = w_c // nm
        mb = jax.tree_util.tree_map(
            lambda x: jax.lax.dynamic_index_in_dim(x, m, 0, keepdims=False),
            microbatches,
        )
        # rank 0 consumes microbatch t's embedding at tick t (< nm): fetch it
        # from its round-robin owner.  Branch index and gate are tick-only —
        # uniform across every device (see docstring).
        e_t = jax.lax.dynamic_index_in_dim(
            emb, jnp.clip(t // pp, 0, slots - 1), 0, keepdims=False
        )
        fresh = jax.lax.cond(
            t < nm,
            lambda: jax.lax.switch(
                jnp.remainder(t, pp),
                [functools.partial(
                    jax.lax.ppermute, e_t, PIPE_AXIS, [(o, 0)]
                ) for o in range(pp)],
            ),
            lambda: jnp.zeros(x0.shape, x0.dtype),
        )
        if vp > 1:
            parked_in = jax.lax.dynamic_index_in_dim(circ, m, 0, keepdims=False)
            first_in = jnp.where(c == 0, fresh, parked_in)
        else:
            first_in = fresh
        x = jnp.where(is_first, first_in, recv)

        out = compute(local_layers, c, x, {**mb, "_chunk": c})
        y, s_aux = out if stage_aux else (out, jnp.zeros((), jnp.float32))
        # every rank+chunk contributes its local layers' aux once per valid
        # work index (the MoE router-loss carry: psum over pipe at the end
        # sums over ALL layers, exactly like the unpipelined scan carry)
        work_valid = jnp.logical_and(w >= 0, w < nm * vp)
        aux_acc = aux_acc + jnp.where(work_valid, s_aux, 0.0)

        # microbatch m_done finishes its LAST chunk on the last rank this
        # tick; route it to its parking rank m_done % pp in ONE hop (the
        # same tick-uniform switch + ppermute as the embed feed above — the
        # destination depends only on the tick, so every device takes the
        # same branch).  The loss is computed over the parked outputs
        # outside the manual region.
        w_done = t - (pp - 1)
        done_valid = jnp.logical_and(
            w_done >= nm * (vp - 1), w_done < nm * vp
        )
        m_done = jnp.clip(jnp.remainder(w_done, nm), 0, nm - 1)
        y_b = jax.lax.cond(
            done_valid,
            lambda: jax.lax.switch(
                jnp.remainder(m_done, pp),
                [functools.partial(
                    jax.lax.ppermute, y, PIPE_AXIS, [(pp - 1, o)]
                ) for o in range(pp)],
            ),
            lambda: jnp.zeros(x0.shape, x0.dtype),
        )
        mine = jnp.logical_and(done_valid, jnp.remainder(m_done, pp) == rank)
        p_slot = m_done // pp
        cur = jax.lax.dynamic_index_in_dim(park, p_slot, 0, keepdims=False)
        park = jax.lax.dynamic_update_index_in_dim(
            park, jnp.where(mine, y_b, cur), p_slot, 0
        )

        recv = jax.lax.ppermute(y, PIPE_AXIS, cyclic)
        return (recv, circ, park, aux_acc), None

    zeros = jnp.zeros_like(x0)
    circ0 = (
        jnp.zeros((nm,) + x0.shape, x0.dtype) if vp > 1 else jnp.zeros((1, 1), x0.dtype)
    )
    park0 = jnp.zeros((slots,) + x0.shape, x0.dtype)
    (_, _, park, aux_acc), _ = jax.lax.scan(
        tick,
        (zeros, circ0, park0, jnp.zeros((), jnp.float32)),
        jnp.arange(nm * vp + pp - 1),
    )
    return park, aux_acc[None]


# ---------------------------------------------------------------------------
# 1F1B: single-pass schedule with in-loop pipe-sharded head and manual grads
# ---------------------------------------------------------------------------
#
# The GPipe-wavefront-with-autodiff above is transparent to ``jax.grad`` but
# pays for it in memory: autodiff of the tick scan retains one stage input per
# tick — O(nm + pp) activation-sized residuals per rank (measured 0.45 GiB/tick
# at flagship shape, bench_results/pp_memory_flagship.md).  The reference's
# engine instead runs 1F1B (``base.py:374-383``): backward for microbatch m
# starts as soon as its forward leaves the last stage, bounding in-flight
# activations to O(pp).
#
# ``pipeline_loss_and_grad`` is the TPU-native 1F1B: ONE ``lax.scan`` over a
# WORK-COMPACTED schedule table (``work_table`` below — schedule as data): at
# each compacted tick, rank ``r`` executes the table's (kind, microbatch,
# chunk) entry for that tick, with the forward / head / backward / wgrad
# blocks gated on tick-uniform ``lax.cond`` flags so a tick no rank forwards
# (backwards) on costs nothing.  Because JAX autodiff cannot interleave a
# scan's backward into its forward, the backward is MANUAL: each B tick calls
# ``jax.vjp`` on the stage (recompute-and-backprop within the tick — the same
# FLOPs as the wavefront's rematerialized backward), activation cotangents ride
# the reverse ring, and parameter gradients accumulate in the scan carry.
# Saved state is an interval-allocated ring of stage inputs — the O(pp) class.
#
# The lm-head + CE cannot stay hoisted (its cotangent would be needed before
# the forward scan ends), so it moves INSIDE the tick loop, sharded over
# ``pipe`` on the VOCAB dim: when microbatch m finishes at tick m + pp - 1 its
# output is broadcast over the pipe ring (one psum) and every rank computes
# logits for its V/pp vocab slice — total head FLOPs stay at parity with the
# unpipelined step (the property tests/test_pp_flops_parity.py pins), and the
# closed-form CE backward (softmax - onehot) yields dy in the same tick.
# This works because both backward seeds are known before the loss value:
# d(loss)/d(loss_sum) = 1/denom_total (denom is a function of labels only) and
# d(loss)/d(stage aux) = aux_scale.
#
# Scope: plain matmul head (tied embed or lm_head.w), token-level CE
# (pretrain/SFT).  Three manual-vjp variants share the tick loop:
# ``1f1b`` (vp == 1), ``1f1b-interleaved`` (vp > 1: the circular interleave
# above, backward threaded through the same chunk ring), and ``1f1b-zb``
# (vp == 1, ZB-H1-style: the backward tick splits into a dgrad pass whose
# activation cotangent feeds the upstream stage immediately and a wgrad pass
# deferred ``rank`` ticks into this rank's cooldown bubble).  Preference
# alignment and exotic heads keep the autodiff wavefront —
# ``supports_1f1b`` is the gate.


PIPELINE_SCHEDULES = ("auto", "1f1b", "1f1b-interleaved", "1f1b-zb",
                      "wavefront")
#: the manual-vjp family (everything but the autodiff wavefront)
MANUAL_VJP_SCHEDULES = ("1f1b", "1f1b-interleaved", "1f1b-zb")


def blocked_1f1b_reason(parallel_cfg: dict,
                        schedule: str = "1f1b") -> Optional[str]:
    """Config-SHAPE constraints on a manual-vjp schedule (no model object
    needed).

    The single source of truth shared by ``supports_1f1b`` (trainer build)
    and ``config.loader.validate_config`` (load time) — one wording, one
    catalog, whichever layer fires first.  Returns the blocking reason, or
    None when the shape qualifies (the model-family checks in
    ``supports_1f1b`` still apply).
    """
    pp = int(parallel_cfg.get("pipeline_model_parallel_size", 1) or 1)
    vp = int(parallel_cfg.get("virtual_pipeline_model_parallel_size", 1) or 1)
    cp = int(parallel_cfg.get("context_parallel_size", 1) or 1)
    alignment = parallel_cfg.get("alignment")
    if schedule not in MANUAL_VJP_SCHEDULES:
        raise ValueError(
            f"blocked_1f1b_reason: not a manual-vjp schedule: {schedule!r}"
        )
    if pp <= 1:
        return f"{schedule} requires pipeline_model_parallel_size > 1"
    if vp > 1 and schedule != "1f1b-interleaved":
        return (
            f"the virtual pipeline (virtual_pipeline_model_parallel_size > 1) "
            f"runs under the circular interleaved manual-vjp schedule — set "
            f"pipeline.schedule: 1f1b-interleaved (or auto) — not {schedule}"
        )
    if vp <= 1 and schedule == "1f1b-interleaved":
        return (
            "1f1b-interleaved needs virtual_pipeline_model_parallel_size > 1 "
            "(with vp == 1 there is nothing to interleave; use 1f1b)"
        )
    if cp > 1:
        return (
            f"context parallelism under pp is proven for the autodiff "
            f"wavefront only (blockwise attention vjp inside the manual "
            f"{schedule} tick loop is unvalidated); use schedule: wavefront "
            f"for pp x cp"
        )
    if alignment in ("dpo", "orpo", "kto"):
        return (
            f"preference alignment ({alignment}) pipelines via the "
            f"concatenated-forward wavefront; the manual-vjp schedules "
            f"implement token-level CE only"
        )
    if parallel_cfg.get("lora"):
        return (
            f"LoRA adapters are not wired for the manual-vjp {schedule} head "
            f"(adapter grads on lm_head would be silently dropped)"
        )
    return None


def supports_1f1b(model_cfg: Any, parallel_cfg: dict,
                  schedule: str = "1f1b") -> tuple[bool, str]:
    """Can the manual-vjp ``schedule`` run this model/parallelism combo?

    Returns ``(ok, reason)``; ``reason`` explains the first blocking
    constraint when ``ok`` is False (and is the message ``resolve_schedule``
    raises when the config FORCES a manual-vjp schedule).

    ``parallel_cfg`` mirrors the ``distributed_strategy`` block plus trainer
    context: ``pipeline_model_parallel_size``,
    ``virtual_pipeline_model_parallel_size``, ``context_parallel_size``,
    ``alignment`` (None/"sft" or a preference strategy), ``lora`` (bool).
    ``schedule`` picks the variant: ``1f1b`` (vp == 1), ``1f1b-interleaved``
    (the circular interleave, vp > 1), or ``1f1b-zb`` (the zero-bubble
    dgrad/wgrad split, vp == 1).  The model side requires the
    plain-matmul-head token-CE structure the in-loop vocab-sharded head
    implements: llama/mistral qualifies today.  Mixtral's head/aux wiring
    exists but its dropless-MoE stage vjp is gated out (backend-dependent
    numerics — see the branch below), and megatron-GPT (learned positions,
    dropout threading, post_ln/normformer/gpt_j head variants) keeps the
    autodiff wavefront until its head is wired.
    """
    blocked = blocked_1f1b_reason(parallel_cfg, schedule)
    if blocked is not None:
        return False, blocked
    if getattr(model_cfg, "attention_impl", "") == "zigzag_ring":
        return False, "zigzag_ring attention is not supported under pp at all"
    from neuronx_distributed_training_tpu.models import llama as _llama

    if isinstance(model_cfg, _llama.LlamaConfig):
        return True, f"llama/mistral: plain matmul head + token CE ({schedule})"
    from neuronx_distributed_training_tpu.models import mixtral as _mixtral

    if isinstance(model_cfg, _mixtral.MixtralConfig):
        # The head/aux wiring exists (mixtral.onef1b_head_hooks), but the
        # sort-based dropless-MoE stage vjp is numerically corrupted when
        # linearized at a scan-carry-derived activation inside the legacy
        # fully-manual shard_map fallback (loss exact, stage grads off by a
        # few percent; bisected tick-by-tick — dense llama stages are exact
        # under the identical schedule).  Until the toolchain's shard_map
        # supports partial-auto natively, mixtral keeps the wavefront.
        return False, (
            "mixtral: dropless-MoE stage vjp has backend-dependent numerics "
            "under the 1f1b tick loop (dense families only for now)"
        )
    return False, (
        f"{type(model_cfg).__name__}: head not wired for the manual-vjp "
        f"{schedule} schedule (supported families: llama/mistral)"
    )


def resolve_schedule(schedule: str, model_cfg: Any, parallel_cfg: dict) -> str:
    """``pipeline.schedule`` knob -> concrete schedule name.

    ``auto`` picks the memory-bounded manual-vjp family whenever
    ``supports_1f1b`` allows: ``1f1b-interleaved`` when the config carries a
    virtual pipeline (vp > 1 — O(nm*vp) chunk inputs instead of the
    wavefront's ~2x autodiff residuals, and the (pp-1)/(nm*vp) bubble), else
    plain ``1f1b`` (O(pp) in-flight activations).  ``1f1b-zb`` is never
    auto-selected: its deferred-wgrad pass re-linearizes the stage (one
    extra forward per microbatch under remat), a trade the autotune cost
    model prices per plan — force it via the knob or ``tools/plan.py
    --apply`` when the bubble dominates (small nm/pp ratios).  Forcing any
    manual-vjp schedule on an unsupported combo raises with the gate's
    reason instead of failing deep inside shard_map.
    """
    schedule = str(schedule or "auto").lower()
    if schedule not in PIPELINE_SCHEDULES:
        raise ValueError(
            f"pipeline.schedule must be one of {'/'.join(PIPELINE_SCHEDULES)}, "
            f"got {schedule!r}"
        )
    if schedule == "wavefront":
        return "wavefront"
    vp = int(parallel_cfg.get(
        "virtual_pipeline_model_parallel_size", 1) or 1)
    if schedule == "auto":
        preferred = "1f1b-interleaved" if vp > 1 else "1f1b"
        ok, _ = supports_1f1b(model_cfg, parallel_cfg, preferred)
        return preferred if ok else "wavefront"
    ok, reason = supports_1f1b(model_cfg, parallel_cfg, schedule)
    if not ok:
        raise ValueError(
            f"pipeline.schedule: {schedule} is unsupported here: {reason}")
    return schedule


# ---------------------------------------------------------------------------
# Work-compacted schedule tables (schedule as data)
# ---------------------------------------------------------------------------
#
# The manual-vjp executor used to be LOCKSTEP: one scan tick per global tick
# of the classic algebra, every rank executing the full F + head + B (+W)
# body every tick with `jnp.where` masks — a masked tick burned full compute,
# so the priced bubble asymptotics never showed up in wall-clock (the
# documented ~1.25x interleaved-vs-plain gap at pp=2/nm=16/vp=2).  The
# executor below instead iterates over a PRECOMPUTED work table built host
# side per schedule: a static ``[T, pp]`` array of (work_kind, microbatch,
# chunk) entries.  Each scan tick gates its F / head / B / wgrad blocks on
# tick-uniform table flags (``lax.cond`` whose predicate depends only on the
# tick, so every device reaches every collective rendezvous together), which
# compacts a kind's masked ticks out of the executed trip count: a tick no
# rank forwards on costs no forward, a tick no rank backwards on costs no
# backward.
#
# Orderings encoded in the table:
# - plain ``1f1b``: microbatch order; B(m) may share the tick with the head
#   that seeded it (the old dy_next carry cost one tick of latency).
# - ``1f1b-interleaved``: depth-first **m-major pp-group** order (the
#   Megatron interleave): microbatches advance in groups of ``pp`` through
#   all ``vp`` chunks before the next group starts, and the backward walks
#   the same groups with chunks descending.  F and B overlap like plain
#   1F1B instead of serializing chunk-major, and a work item's stage input
#   is consumed O(vp*pp) ticks after its save — the chunk-input store
#   shrinks from O(vp*nm) to a ring bounded by the schedule's true
#   in-flight window (``ring_slot_counts``; priced by
#   ``autotune.cost_model``'s ``pipeline_rings`` term).
# - ``1f1b-zb``: the dgrad tick parks dy and the wgrad for microbatch ``m``
#   runs on EVERY rank at rank 0's dgrad tick (the table's rank-uniform
#   fill) — wgrad ticks are fully dense, the park-ring re-linearization is
#   table data rather than a fixed ``m + 2pp - 1`` slot.
#
# Every ring (stage-input store, forward/backward chunk hand-off, head-dy
# park, zb deferred-dy park) is sized by interval allocation over the
# table's actual write->last-read lifetimes — collision-free by
# construction, asserted at build time.


def _fwd_order(pp: int, nm: int, vp: int) -> list[tuple[int, int]]:
    """Forward work order (chunk, microbatch), shared by every rank."""
    if vp == 1:
        return [(0, m) for m in range(nm)]
    order = []
    for g0 in range(0, nm, pp):
        group = range(g0, min(g0 + pp, nm))
        for c in range(vp):
            order.extend((c, m) for m in group)
    return order


def _bwd_order(pp: int, nm: int, vp: int) -> list[tuple[int, int]]:
    """Backward work order: same pp-groups, chunks descending."""
    if vp == 1:
        return [(0, m) for m in range(nm)]
    order = []
    for g0 in range(0, nm, pp):
        group = range(g0, min(g0 + pp, nm))
        for c in reversed(range(vp)):
            order.extend((c, m) for m in group)
    return order


def _interval_alloc(items: list[tuple[int, int, Any]]
                    ) -> tuple[dict, int]:
    """Greedy register allocation over (write_tick, last_read_tick, key)
    lifetimes -> ({key: slot}, n_slots).

    A slot is reusable only for a write STRICTLY after its previous
    occupant's last read: within one tick the executor's block order does
    run writes before their same-tick reads, but the conservative rule
    keeps every cross-value hazard impossible by construction."""
    out: dict = {}
    busy_until: list[int] = []  # slot -> last read tick of current occupant
    for write, last_read, key in sorted(items, key=lambda it: (it[0], it[1])):
        if last_read < write:
            raise AssertionError(
                f"work table bug: value {key} read at {last_read} before "
                f"its write at {write}")
        for s, until in enumerate(busy_until):
            if until < write:
                out[key] = s
                busy_until[s] = last_read
                break
        else:
            out[key] = len(busy_until)
            busy_until.append(last_read)
    return out, max(1, len(busy_until))


#: per-tick work weights for the table-level bubble accounting: a forward
#: costs ~1 unit, a full-vjp backward ~3 (recompute + dgrad + wgrad), a
#: zb dgrad-only backward ~2, a deferred wgrad ~2 (re-linearize + dW) —
#: the fwd+2xbwd convention split per pullback
_WORK_UNITS = {"f": 1.0, "b_full": 3.0, "b_dgrad": 2.0, "w": 2.0}


@dataclasses.dataclass(frozen=True)
class WorkTable:
    """Host-side compacted schedule for one manual-vjp variant.

    ``rank_cols`` are ``[span, pp]`` arrays (one column per pipe rank, fed
    to the executor pipe-sharded on dim 1); ``glob_cols`` are ``[span]``
    tick-uniform arrays (collective gates and ring bookkeeping — identical
    on every rank by construction, which is what makes the in-scan
    ``lax.cond`` gates rendezvous-safe).  ``ring_sizes`` are the
    interval-allocated slot counts per ring."""

    schedule: str
    pp: int
    nm: int
    vp: int
    span: int
    rank_cols: dict[str, np.ndarray]
    glob_cols: dict[str, np.ndarray]
    ring_sizes: dict[str, int]

    @property
    def lockstep_span(self) -> int:
        """The old one-scan-tick-per-global-tick trip count, for reference."""
        return (2 * self.vp - 1) * self.nm + 2 * self.pp - 1

    def tick_counts(self) -> dict[str, int]:
        g = self.glob_cols
        return {
            "span": self.span,
            "f_ticks": int(g["has_f"].sum()),
            "b_ticks": int(g["has_b"].sum()),
            "w_ticks": int(g["has_w"].sum()),
            "head_ticks": int(g["has_h"].sum()),
            "lockstep_span": self.lockstep_span,
        }

    def bubble_fraction(self) -> float:
        """Predicted idle fraction of the COMPACTED execution: the fraction
        of executed work units that are masked fill/drain slots.  Weighted
        by ``_WORK_UNITS`` — for ``1f1b`` and ``1f1b-interleaved`` the F and
        B windows are equal-length and the weights cancel, reproducing the
        closed-form ``b/(1+b)`` exactly (a tested invariant); for
        ``1f1b-zb`` this is the HONEST SPMD number (the dense wgrad fill
        cannot erase the dgrad chain's fill/drain the way the MPMD ZB-H1
        asymptotic assumes)."""
        wb = _WORK_UNITS["b_dgrad"] if self.schedule == "1f1b-zb" \
            else _WORK_UNITS["b_full"]
        g, r = self.glob_cols, self.rank_cols
        per_tick = (_WORK_UNITS["f"] * g["has_f"]
                    + wb * g["has_b"] + _WORK_UNITS["w"] * g["has_w"])
        executed = self.pp * float(per_tick.sum())
        useful = (_WORK_UNITS["f"] * float(r["f_valid"].sum())
                  + wb * float(r["b_valid"].sum())
                  + _WORK_UNITS["w"] * float(r["w_valid"].sum()))
        return 1.0 - useful / executed if executed > 0 else 0.0


@functools.lru_cache(maxsize=None)
def work_table(schedule: str, pp: int, nm: int, vp: int = 1) -> WorkTable:
    """Build the compacted work table for one manual-vjp schedule.

    Per-rank F/B streams are exact one-tick shifts of rank 0's forward and
    rank ``pp-1``'s backward streams (the ring-hop carries require the
    producing rank's output to be consumed exactly one tick later); the
    variable-latency hand-offs (chunk ring on rank 0, reverse chunk ring on
    rank ``pp-1``, head-dy park, zb deferred-dy park) all ride
    interval-allocated rings, so the streams themselves may compact freely."""
    if schedule not in MANUAL_VJP_SCHEDULES:
        raise ValueError(f"work_table: not a manual-vjp schedule: {schedule!r}")
    if pp <= 1 or nm <= 0:
        raise ValueError(f"work_table needs pp > 1 and nm > 0 (pp={pp}, nm={nm})")
    vp = max(int(vp or 1), 1)
    if (vp > 1) != (schedule == "1f1b-interleaved"):
        raise ValueError(
            f"work_table: schedule {schedule} is inconsistent with vp={vp}")
    zb = schedule == "1f1b-zb"

    # -- rank-0 forward stream (greedy ASAP, one F per tick) ---------------
    t0F: dict[tuple[int, int], int] = {}
    prev = -1
    for c, m in _fwd_order(pp, nm, vp):
        dep = t0F[(c - 1, m)] + pp if c > 0 else 0
        prev = max(prev + 1, dep)
        t0F[(c, m)] = prev
    # head(m) shares the tick of the last rank's final-chunk forward
    tH = {m: t0F[(vp - 1, m)] + pp - 1 for m in range(nm)}

    # -- last-rank backward stream (greedy ASAP, one B per tick) -----------
    tLB: dict[tuple[int, int], int] = {}
    prev = -1
    for c, m in _bwd_order(pp, nm, vp):
        dep = tH[m] if c == vp - 1 else tLB[(c + 1, m)] + pp
        prev = max(prev + 1, dep)
        tLB[(c, m)] = prev
    # zb deferred wgrad: rank-uniform at rank 0's dgrad tick — every rank
    # has parked its dy by then, so wgrad ticks are fully dense (no rank
    # burns a masked wgrad)
    tW = {m: tLB[(0, m)] + pp - 1 for m in range(nm)} if zb else {}

    span = 1 + max(
        max(t for t in t0F.values()) + pp - 1,
        max(t for t in tLB.values()) + pp - 1,
        max(tW.values()) if tW else 0,
    )

    def ri(dtype=np.int32):
        return np.zeros((span, pp), dtype)

    def gi(dtype=np.int32):
        return np.zeros((span,), dtype)

    rank_cols = {
        "f_m": ri(), "f_c": ri(), "f_valid": ri(bool), "f_slot": ri(),
        "b_m": ri(), "b_c": ri(), "b_valid": ri(bool), "b_slot": ri(),
        "w_m": ri(), "w_valid": ri(bool), "w_x_slot": ri(),
        "bdy_slot": ri(), "w_dy_slot": ri(),
    }
    glob_cols = {
        "has_f": gi(bool), "has_b": gi(bool), "has_w": gi(bool),
        "has_h": gi(bool), "h_m": gi(),
        "dyw_slot": gi(), "dyr_slot": gi(),
        "feed_valid": gi(bool), "feed_src": gi(), "feed_slot": gi(),
        "cpark_valid": gi(bool), "cpark_slot": gi(), "cread_slot": gi(),
        "bpark_valid": gi(bool), "bpark_slot": gi(), "bread_slot": gi(),
        "d0_valid": gi(bool), "d0_dst": gi(), "d0_slot": gi(),
    }

    for (c, m), t0 in t0F.items():
        for r in range(pp):
            t = t0 + r
            rank_cols["f_m"][t, r] = m
            rank_cols["f_c"][t, r] = c
            rank_cols["f_valid"][t, r] = True
        if c == 0:
            glob_cols["feed_valid"][t0] = True
            glob_cols["feed_src"][t0] = m % pp
            glob_cols["feed_slot"][t0] = m // pp
    for (c, m), tl in tLB.items():
        for r in range(pp):
            t = tl + (pp - 1 - r)
            rank_cols["b_m"][t, r] = m
            rank_cols["b_c"][t, r] = c
            rank_cols["b_valid"][t, r] = True
        if c == 0:
            t0b = tl + pp - 1  # rank 0's dgrad tick
            glob_cols["d0_valid"][t0b] = True
            glob_cols["d0_dst"][t0b] = m % pp
            glob_cols["d0_slot"][t0b] = m // pp
    for m, t in tH.items():
        glob_cols["has_h"][t] = True
        glob_cols["h_m"][t] = m
    for m, t in tW.items():
        for r in range(pp):
            rank_cols["w_m"][t, r] = m
            rank_cols["w_valid"][t, r] = True
    glob_cols["has_f"] = rank_cols["f_valid"].any(axis=1)
    glob_cols["has_b"] = rank_cols["b_valid"].any(axis=1)
    glob_cols["has_w"] = rank_cols["w_valid"].any(axis=1)

    ring_sizes: dict[str, int] = {}

    # stage-input store: write at the rank's F tick, last read at its B
    # tick (and the rank-uniform wgrad tick under zb)
    n_inflight = 1
    for r in range(pp):
        items = []
        for (c, m), t0 in t0F.items():
            write = t0 + r
            last = tLB[(c, m)] + (pp - 1 - r)
            if zb:
                last = max(last, tW[m])
            items.append((write, last, (c, m)))
        alloc, n = _interval_alloc(items)
        n_inflight = max(n_inflight, n)
        for (c, m), s in alloc.items():
            rank_cols["f_slot"][t0F[(c, m)] + r, r] = s
            rank_cols["b_slot"][tLB[(c, m)] + (pp - 1 - r), r] = s
            if zb:
                rank_cols["w_x_slot"][tW[m], r] = s
    ring_sizes["inflight"] = n_inflight

    # forward chunk hand-off (rank 0): last rank's chunk-c output parks one
    # tick after its F, read by rank 0's F of chunk c+1
    if vp > 1:
        items = [(t0F[(c, m)] + pp, t0F[(c + 1, m)], (c, m))
                 for (c, m) in t0F if c < vp - 1]
        alloc, n = _interval_alloc(items)
        ring_sizes["circ"] = n
        for (c, m), s in alloc.items():
            glob_cols["cpark_valid"][t0F[(c, m)] + pp] = True
            glob_cols["cpark_slot"][t0F[(c, m)] + pp] = s
            glob_cols["cread_slot"][t0F[(c + 1, m)]] = s
        # backward chunk hand-off (rank pp-1): rank 0's chunk-c dgrad parks
        # one tick after its B, read by the last rank's B of chunk c-1
        items = [(tLB[(c, m)] + pp, tLB[(c - 1, m)], (c, m))
                 for (c, m) in tLB if c >= 1]
        alloc, n = _interval_alloc(items)
        ring_sizes["bcirc"] = n
        for (c, m), s in alloc.items():
            glob_cols["bpark_valid"][tLB[(c, m)] + pp] = True
            glob_cols["bpark_slot"][tLB[(c, m)] + pp] = s
            glob_cols["bread_slot"][tLB[(c - 1, m)]] = s
    else:
        ring_sizes["circ"] = ring_sizes["bcirc"] = 0

    # head-dy park: written at the head tick, read by the last rank's
    # final-chunk B (same tick legal: the head block precedes the backward
    # block)
    items = [(tH[m], tLB[(vp - 1, m)], m) for m in range(nm)]
    alloc, n = _interval_alloc(items)
    ring_sizes["dy"] = n
    for m, s in alloc.items():
        glob_cols["dyw_slot"][tH[m]] = s
        glob_cols["dyr_slot"][tLB[(vp - 1, m)]] = s

    # zb deferred-dy park: each rank parks dy at its dgrad tick, reads it
    # at the rank-uniform wgrad tick
    if zb:
        n_wdy = 1
        for r in range(pp):
            items = [(tLB[(0, m)] + (pp - 1 - r), tW[m], m)
                     for m in range(nm)]
            alloc, n = _interval_alloc(items)
            n_wdy = max(n_wdy, n)
            for m, s in alloc.items():
                rank_cols["bdy_slot"][tLB[(0, m)] + (pp - 1 - r), r] = s
                rank_cols["w_dy_slot"][tW[m], r] = s
        ring_sizes["wdy"] = n_wdy
    else:
        ring_sizes["wdy"] = 0

    return WorkTable(schedule=schedule, pp=pp, nm=nm, vp=vp, span=span,
                     rank_cols=rank_cols, glob_cols=glob_cols,
                     ring_sizes=ring_sizes)


def ring_slot_counts(schedule: str, pp: int, nm: int, vp: int = 1
                     ) -> dict[str, int]:
    """Stage-input-sized ring slots the compacted executor allocates for a
    schedule — what ``autotune.cost_model`` prices as ``pipeline_rings``
    (the delta over plain 1f1b, whose buffering the calibrated stage floor
    already absorbs).  Includes a ``total``."""
    sizes = dict(work_table(schedule, pp, nm, vp).ring_sizes)
    sizes["total"] = sum(sizes.values())
    return sizes


def bubble_multiplier(schedule: Optional[str], pp: int, nm: int,
                      vp: int = 1) -> float:
    """Pipeline-bubble work multiplier: fill/drain time as a fraction of the
    schedule's useful in-pipeline work (what ``autotune.cost_model`` charges
    as ``bubble_seconds = multiplier * inner``).

    - ``wavefront`` / ``1f1b``: the classic ``(pp-1)/nm`` — with a virtual
      pipeline the circular interleave cycles microbatches through the ranks
      ``vp`` times, per-rank utilization ``nm*vp/(nm*vp + pp - 1)``
      (``pipeline_loss`` docstring), so the multiplier divides by ``nm*vp``.
    - ``1f1b-interleaved``: same ``(pp-1)/(nm*vp)`` — the interleave is the
      bubble win; the manual vjp changes memory, not fill/drain.
    - ``1f1b-zb``: ``(pp-1)/(3*nm)`` — ZB-H1 asymptotics: with the backward
      split F:dgrad:wgrad ≈ 1:1:1, only the F+dgrad chain needs the
      fill/drain serialization and the deferred wgrad tail fills the
      cooldown, leaving the one-third warmup residual it cannot cover.
    """
    if pp <= 1 or nm <= 0:
        return 0.0
    vp = max(int(vp or 1), 1)
    if schedule == "1f1b-zb":
        return (pp - 1) / (3.0 * nm)
    if schedule == "1f1b":
        return (pp - 1) / float(nm)
    # wavefront + 1f1b-interleaved share the circular-interleave utilization
    return (pp - 1) / float(nm * vp)


def predicted_bubble_fraction(schedule: Optional[str], pp: int, nm: int,
                              vp: int = 1) -> float:
    """Predicted idle fraction of TOTAL pipelined step time — the telemetry
    number (``run_summary.json`` / bench JSON ``bubble_fraction_predicted``);
    0.0 when pp == 1.

    For the manual-vjp schedules this is derived from the COMPACTED work
    table the executor actually runs (``WorkTable.bubble_fraction``): for
    ``1f1b`` and ``1f1b-interleaved`` it equals the closed-form
    ``b / (1 + b)`` exactly (the compacted table realizes the priced
    asymptotics — a tested invariant), while ``1f1b-zb`` reports the honest
    SPMD number (the dense wgrad fill cannot erase the dgrad chain's
    fill/drain the way the MPMD ZB-H1 asymptotic assumes).  The autodiff
    wavefront keeps the closed form."""
    if pp <= 1 or nm <= 0:
        return 0.0
    if schedule in MANUAL_VJP_SCHEDULES:
        # telemetry must not raise on an off-gate combo: normalize vp the
        # way the executor's own dispatch does (interleaved is the only
        # vp>1 schedule; a vp==1 "interleave" degenerates to plain 1f1b)
        vp = max(int(vp or 1), 1) if schedule == "1f1b-interleaved" else 1
        if schedule == "1f1b-interleaved" and vp == 1:
            schedule = "1f1b"
        return work_table(schedule, pp, nm, vp).bubble_fraction()
    b = bubble_multiplier(schedule, pp, nm, vp)
    return b / (1.0 + b)


def _tree_index(tree, i):
    return jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_index_in_dim(x, i, 0, keepdims=False), tree
    )


def ce_denominator(microbatches: dict, *, shift_labels: bool,
                   ignore_index: int = -100) -> jax.Array:
    """Total valid-token count over all microbatches — a function of labels
    only, which is what lets 1F1B seed the CE backward before the forward
    finishes.  Matches the masking in ``ops.cross_entropy``."""
    labels = microbatches["labels"]
    loss_mask = microbatches.get("loss_mask")
    if shift_labels:
        labels = labels[..., 1:]
        loss_mask = None if loss_mask is None else loss_mask[..., 1:]
    valid = (labels != ignore_index).astype(jnp.float32)
    if loss_mask is not None:
        valid = valid * loss_mask.astype(jnp.float32)
    return jnp.sum(valid)


def pipeline_loss_and_grad(
    params: Any,
    layer_params: Any,  # vp==1: [num_layers, ...] dim0 over "pipe";
                        # vp>1: interleaved [vp, pp, Lc, ...] dim1 over "pipe"
    microbatches: dict[str, jax.Array],  # leaves [num_micro, mb, ...]
    *,
    embed_fn: EmbedFn,
    stage_fn: StageFn,
    head_hidden_fn: Callable,  # (head_params, y) -> h   (final norm / identity)
    head_params: Any,          # pytree whose grads flow through head_hidden_fn
    head_weight: jax.Array,    # [V, H] — logits = h @ W.T; pipe-sharded on V
    mesh=None,
    num_microbatches: Optional[int] = None,
    virtual_pipeline_size: int = 1,
    zero_bubble: bool = False,
    stage_aux: bool = False,
    aux_scale: float = 0.0,
    shift_labels: bool = True,
    grad_dtype=jnp.float32,
    ignore_index: int = -100,
    double_buffer: bool = False,
):
    """Manual-vjp pipeline step: returns ``(loss, grads)`` where ``grads``
    has exactly the keys ``{"layers", "params_from_embed", "head_params",
    "head_weight"}`` (a tested invariant — tests/test_pipeline_1f1b.py).

    ``virtual_pipeline_size > 1`` runs the circular interleaved 1F1B
    (``1f1b-interleaved``): layers arrive in the ``to_interleaved``
    ``[vp, pp, Lc, ...]`` layout, microbatches cycle through the ranks
    ``vp`` times in the forward (the wavefront's circular schedule) and the
    backward threads the chunk ring in reverse; like the wavefront it needs
    ``num_microbatches >= pp`` (circular-store write-before-read).
    ``zero_bubble`` runs the ZB-H1-style split (``1f1b-zb``, vp == 1 only):
    the backward tick computes only the activation cotangent (dgrad) so the
    upstream stage unblocks immediately, and the weight-gradient pass for
    microbatch ``m`` is deferred ``rank`` ticks — exactly this rank's
    cooldown-bubble budget — re-linearizing the stage against the saved
    input (the remat trade: one extra stage forward per microbatch).

    - ``layers``: tree shaped/sharded like ``layer_params``;
    - ``params_from_embed``: a PARAMS-shaped tree — the parked cotangent of
      the permuted embed feed has already been pulled through ``jax.vjp`` of
      the embed computation internally, so its ``embed`` entries hold the
      embedding-table grads and every leaf the embed hook does not touch is
      zero.  Add the other grad entries onto it to assemble the full grad
      pytree;
    - ``head_params``: grads of ``head_hidden_fn``'s params (final norm);
    - ``head_weight``: [V, H] grad of the head matmul (transpose into
      ``lm_head.w`` for an untied [H, V] head; add to the embed-table grad
      when tied).

    Loss matches ``pipeline_loss`` (same masking and normalization); the
    caller divides nothing — normalization by the global valid-token count is
    already inside.

    ``double_buffer`` (``distributed_strategy.overlap.pp_double_buffer``)
    moves both stage-hop collective-permutes out of their compute ``cond``s:
    the forward hop issues after the F cond (overlapping the same tick's
    head/backward compute) and the reverse hop defers to the next tick's
    top, ahead of its first read (overlapping that tick's forward compute).
    Gating/data paths are unchanged, so loss and grads are value-identical;
    only the scheduler's freedom changes.
    """
    mesh = mesh or shd.active_mesh()
    pp = int(mesh.shape.get(PIPE_AXIS, 1)) if mesh is not None else 1
    nm = num_microbatches or jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    vp = int(virtual_pipeline_size or 1)
    if pp <= 1:
        raise ValueError("pipeline_loss_and_grad requires pp > 1")
    if zero_bubble and vp > 1:
        raise ValueError(
            "zero_bubble (1f1b-zb) is vp == 1 only; the interleaved chunk "
            "ring has no per-rank cooldown window to defer wgrads into"
        )
    if vp > 1 and nm < pp:
        # chunk c+1 reads the circular store at the tick chunk c's last-rank
        # output is parked only when nm >= pp (same hazard as pipeline_loss)
        raise ValueError(
            f"interleaved pipeline needs num_microbatches >= pp "
            f"(got nm={nm}, pp={pp}, vp={vp})"
        )

    from jax.sharding import PartitionSpec as P

    denom = jnp.maximum(ce_denominator(
        microbatches, shift_labels=shift_labels, ignore_index=ignore_index
    ), 1.0)

    # round-robin embed feed, identical to pipeline_loss: row g = r*slots + l
    # <-> microbatch m = l*pp + r, dim 0 sharded over pipe
    slots = -(-nm // pp)
    g = np.arange(pp * slots)
    m_of_g = (g % slots) * pp + g // slots
    m_idx = np.where(m_of_g < nm, m_of_g, 0)
    mb_perm = jax.tree_util.tree_map(lambda x: x[m_idx], microbatches)

    def emb_of(p):
        e = jax.vmap(lambda m: embed_fn(p, m))(mb_perm)
        unc = P.UNCONSTRAINED
        return shd.constrain(e, P(PIPE_AXIS, *([unc] * (e.ndim - 1))))

    emb, emb_vjp = jax.vjp(emb_of, params)

    # the compacted schedule as data: per-rank work entries ride into the
    # manual region pipe-sharded on their rank dim, tick-uniform gate/ring
    # columns replicated (see work_table)
    schedule_name = ("1f1b-zb" if zero_bubble
                     else ("1f1b-interleaved" if vp > 1 else "1f1b"))
    table = work_table(schedule_name, pp, nm, vp)
    wt_rank = {k: jnp.asarray(v) for k, v in table.rank_cols.items()}
    wt_glob = {k: jnp.asarray(v) for k, v in table.glob_cols.items()}

    body = functools.partial(
        _onef1b_body,
        stage_fn=stage_fn, head_hidden_fn=head_hidden_fn, pp=pp, nm=nm,
        vp=vp, zero_bubble=zero_bubble, rings=table.ring_sizes,
        slots=slots, stage_aux=stage_aux, aux_scale=float(aux_scale),
        shift_labels=shift_labels, grad_dtype=grad_dtype,
        ignore_index=ignore_index, double_buffer=bool(double_buffer),
    )
    layer_spec = P(None, PIPE_AXIS) if vp > 1 else P(PIPE_AXIS)
    vocab_spec = P(PIPE_AXIS, *([None] * (head_weight.ndim - 1)))
    fn = shd.shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_spec, P(), P(), vocab_spec, P(PIPE_AXIS), P(),
                  P(None, PIPE_AXIS), P()),
        out_specs=(P(), layer_spec, P(PIPE_AXIS), vocab_spec, P(), P()),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )
    loss_sum, d_layers, d_emb, d_w, d_head_params, aux_total = fn(
        layer_params, head_params, microbatches, head_weight, emb, denom,
        wt_rank, wt_glob,
    )
    loss = loss_sum / denom + aux_scale * aux_total
    (d_params_embed,) = emb_vjp(d_emb.astype(emb.dtype))
    grads = {
        "layers": d_layers,
        "params_from_embed": d_params_embed,
        "head_params": d_head_params,
        "head_weight": d_w,
    }
    return loss, grads


def _onef1b_body(local_layers, head_params, microbatches, w_r, emb, denom,
                 wt_rank, wt_glob, *,
                 stage_fn, head_hidden_fn, pp, nm, vp, zero_bubble, rings,
                 slots, stage_aux, aux_scale, shift_labels, grad_dtype,
                 ignore_index, double_buffer=False):
    """Per-pipe-rank WORK-COMPACTED manual-vjp tick loop (inside shard_map,
    manual "pipe").

    The schedule is DATA, not control flow: one ``lax.scan`` over the
    compacted work table (``work_table`` — ``wt_rank`` carries this rank's
    per-tick (kind, microbatch, chunk, ring-slot) entries pipe-sharded on
    their rank dim, ``wt_glob`` the tick-uniform gates and ring
    bookkeeping).  Each tick gates its forward / head / backward / wgrad
    blocks on the table's ``has_*`` flags with ``lax.cond``: the predicates
    are tick-only (identical on every device), so every collective inside a
    taken branch — ring hops, head psums, embed feed and embed-cotangent
    routing switches — still reaches its rendezvous on every device, while
    a tick no rank forwards (backwards) on executes no stage compute at
    all.  That is what cashes the priced bubble in wall-clock: the old
    lockstep loop burned the full body on all
    ``(2*vp - 1)*nm + 2*pp - 1`` ticks, the compacted loop runs F on
    ``nm*vp + pp - 1`` ticks and B on ``nm*vp + pp - 1`` ticks (dense for
    ``nm % pp == 0`` — the m-major pp-group interleave order overlaps the
    F/B windows like plain 1F1B instead of serializing chunk-major).

    Stream alignment: rank ``r``'s F(c, m) runs exactly one tick after rank
    ``r-1``'s (the forward ring-hop carry), rank ``r``'s B(c, m) exactly
    one tick after rank ``r+1``'s (the reverse hop) — per-rank streams are
    shifts of the table's rank-0 forward / last-rank backward streams.
    Variable-latency hand-offs ride interval-allocated rings instead of
    carry slots: the stage-input store (``inflight``), the forward chunk
    ring on rank 0 (``circ``), the backward chunk ring on rank ``pp-1``
    (``bcirc``), the head-dy park (``dy_ring`` — the head may seed its B
    the SAME tick now), and zb's deferred-dy park (``wdy_ring``).  Under
    ``zero_bubble`` the B tick computes dgrad only and the wgrad for
    microbatch ``m`` runs at the table's rank-uniform fill tick — same dy,
    same saved input, grads bitwise the plain-1F1B split into two
    pullbacks."""
    rank = jax.lax.axis_index(PIPE_AXIS)
    is_first = rank == 0
    is_last = rank == pp - 1
    vr = w_r.shape[0]  # local vocab slice size

    x0 = emb[0]
    cyclic = [(i, (i + 1) % pp) for i in range(pp)]
    reverse = [((i + 1) % pp, i) for i in range(pp)]

    # normalize local layer layout: vp>1 arrives [vp, 1, Lc, ...] (dim1 is
    # the pipe shard) -> [vp, Lc, ...]; vp==1 stays flat [Lc, ...]
    if vp > 1:
        local_layers = jax.tree_util.tree_map(
            lambda x: jnp.squeeze(x, axis=1), local_layers
        )

    def chunk_layers(c):
        if vp == 1:
            return local_layers
        return jax.tree_util.tree_map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            local_layers,
        )

    def stage_flat(lp, x, mb, c):
        out = stage_fn(lp, x, {**mb, "_chunk": jnp.asarray(c, jnp.int32)})
        if stage_aux:
            return out
        return out, jnp.zeros((), jnp.float32)

    def acc_layers(dl, d_lp, c, bv):
        """Accumulate a chunk's weight grads (into chunk row c when vp>1)."""
        if vp == 1:
            return jax.tree_util.tree_map(
                lambda a, gkk: a + bv * gkk.astype(grad_dtype), dl, d_lp
            )

        def one(a, gkk):
            cur = jax.lax.dynamic_index_in_dim(a, c, 0, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(
                a, cur + bv * gkk.astype(grad_dtype), c, 0
            )

        return jax.tree_util.tree_map(one, dl, d_lp)

    def ring_at(ring, slot):
        return jax.lax.dynamic_index_in_dim(ring, slot, 0, keepdims=False)

    def ring_put(ring, slot, value, valid):
        cur = ring_at(ring, slot)
        return jax.lax.dynamic_update_index_in_dim(
            ring, jnp.where(valid, value, cur), slot, 0
        )

    def tick(carry, xt):
        (recv, cot_recv, inflight, circ, bcirc, dy_ring, wdy_ring,
         d_layers, d_emb, d_w, d_hp_acc, loss_acc, aux_acc) = carry

        if double_buffer:
            # double-buffered reverse hop: ``cot_recv`` carries the UNHOPPED
            # dgrad parked by the previous tick's b_block; it hops here at
            # the tick top — gated on the table's shifted has_b column, the
            # write->first-read interval the compacted schedule guarantees —
            # so the collective-permute overlaps this tick's forward compute
            # instead of serializing inside last tick's backward cond.  Its
            # consumer (this tick's b_block / bcirc park) reads the hopped
            # value exactly as the in-cond form did: value-identical.
            cot_recv = jax.lax.cond(
                xt["hop_b"],
                lambda: jax.lax.ppermute(cot_recv, PIPE_AXIS, reverse),
                lambda: cot_recv,
            )

        # ---- chunk hand-off parks (values hopped at the previous tick) -
        # recv holds the predecessor's y from tick t-1: on rank 0 that is
        # the last rank's output, parked for its next chunk; cot_recv holds
        # the successor's dgrad: on rank pp-1 that is rank 0's, parked for
        # the previous chunk's B tick.  The parked value is only meaningful
        # on the owning rank (other ranks park garbage in their local ring,
        # never read — the same SPMD trade the wavefront makes).
        if vp > 1:
            circ = ring_put(circ, xt["cpark_slot"], recv, xt["cpark_valid"])
            bcirc = ring_put(bcirc, xt["bpark_slot"], cot_recv,
                             xt["bpark_valid"])

        # ---- forward work ----------------------------------------------
        m_F, c_F, f_valid = xt["f_m"], xt["f_c"], xt["f_valid"]

        def f_block(inflight):
            mbF = _tree_index(microbatches, m_F)
            # rank 0 consumes microbatch m_F's embedding at its chunk-0 F
            # tick: fetch it from its round-robin owner.  Branch index and
            # gate are table columns — tick-uniform on every device.
            e_t = jax.lax.dynamic_index_in_dim(
                emb, xt["feed_slot"], 0, keepdims=False
            )
            fresh = jax.lax.cond(
                xt["feed_valid"],
                lambda: jax.lax.switch(
                    xt["feed_src"],
                    [functools.partial(
                        jax.lax.ppermute, e_t, PIPE_AXIS, [(o, 0)]
                    ) for o in range(pp)],
                ),
                lambda: jnp.zeros(x0.shape, x0.dtype),
            )
            if vp > 1:
                parked_in = ring_at(circ, xt["cread_slot"])
                first_in = jnp.where(c_F == 0, fresh, parked_in)
            else:
                first_in = fresh
            x_in = jnp.where(is_first, first_in, recv)
            y, s_aux = stage_flat(chunk_layers(c_F), x_in, mbF, c_F)
            # save the stage input for this rank's B (and zb wgrad) tick
            inflight = ring_put(inflight, xt["f_slot"], x_in, f_valid)
            if double_buffer:
                # hop hoisted out of this cond (issued below, after the
                # cond) so it can overlap the head/backward compute
                return y, s_aux, inflight, recv
            # forward ring hop: consumed by the successor's F next tick
            hop = jax.lax.ppermute(y, PIPE_AXIS, cyclic)
            return y, s_aux, inflight, hop

        y, s_aux, inflight, recv = jax.lax.cond(
            xt["has_f"], f_block,
            lambda inflight: (jnp.zeros(x0.shape, x0.dtype),
                              jnp.zeros((), jnp.float32), inflight, recv),
            inflight,
        )
        if double_buffer:
            # hoisted forward hop: a cond branch is an atomic unit to XLA,
            # so the in-cond permute serialized between this tick's stage
            # compute and its head/backward blocks; standing alone it only
            # depends on ``y`` and overlaps both
            recv = jax.lax.cond(
                xt["has_f"],
                lambda: jax.lax.ppermute(y, PIPE_AXIS, cyclic),
                lambda: recv,
            )
        aux_acc = aux_acc + jnp.where(f_valid, s_aux, 0.0)

        # ---- head + CE (vocab sliced over pipe) ------------------------
        def h_block(dy_ring, d_w, d_hp_acc, loss_acc):
            # the head tick IS the last rank's final-chunk F tick: broadcast
            # its fresh output over the pipe ring, then every rank computes
            # logits for its V/pp vocab slice
            m_H = xt["h_m"]
            y_bcast = jax.lax.psum(
                jnp.where(
                    jnp.logical_and(is_last,
                                    jnp.logical_and(f_valid, c_F == vp - 1)),
                    y, 0.0,
                ),
                PIPE_AXIS,
            )
            mbH = _tree_index(microbatches, m_H)
            # hidden fn under vjp over BOTH (hp, y) so the norm-weight grad
            # and dy fall out of one pass; the CE backward is closed-form
            (h_out, head_vjp) = jax.vjp(head_hidden_fn, head_params, y_bcast)
            if shift_labels:
                h2 = h_out[:, :-1]
                labels2 = mbH["labels"][:, 1:]
                lmH = mbH.get("loss_mask")
                lm2 = None if lmH is None else lmH[:, 1:]
            else:
                h2 = h_out
                labels2 = mbH["labels"]
                lmH = mbH.get("loss_mask")
                lm2 = lmH
            valid = labels2 != ignore_index
            safe = jnp.where(valid, labels2, 0)
            mask = valid.astype(jnp.float32)
            if lm2 is not None:
                mask = mask * lm2.astype(jnp.float32)
            logits = jnp.einsum(
                "bsh,vh->bsv", h2, w_r, preferred_element_type=jnp.float32
            )
            gmax = jax.lax.pmax(
                jax.lax.stop_gradient(jnp.max(logits, axis=-1)), PIPE_AXIS
            )
            shifted = logits - gmax[..., None]
            sumexp = jax.lax.psum(jnp.sum(jnp.exp(shifted), axis=-1),
                                  PIPE_AXIS)
            lse = jnp.log(sumexp) + gmax
            off = rank * vr
            onehot = (
                jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                         logits.ndim - 1)
                + off == safe[..., None]
            )
            ll = jax.lax.psum(
                jnp.sum(jnp.where(onehot, logits, 0.0), axis=-1), PIPE_AXIS
            )
            loss_m = jnp.sum((lse - ll) * mask)
            p_r = jnp.exp(shifted) / sumexp[..., None]
            dlogits = (p_r - onehot.astype(jnp.float32)) \
                * (mask / denom)[..., None]
            dlogits = dlogits.astype(h2.dtype)
            d_wr_t = jnp.einsum(
                "bsv,bsh->vh", dlogits, h2, preferred_element_type=jnp.float32
            )
            dh2 = jax.lax.psum(
                jnp.einsum("bsv,vh->bsh", dlogits, w_r,
                           preferred_element_type=jnp.float32),
                PIPE_AXIS,
            ).astype(h_out.dtype)
            if shift_labels:
                dh = jnp.pad(
                    dh2, ((0, 0), (0, 1)) + ((0, 0),) * (dh2.ndim - 2)
                )
            else:
                dh = dh2
            d_hp_t, dy_t = head_vjp(dh)
            loss_acc = loss_acc + loss_m
            d_w = d_w + d_wr_t.astype(grad_dtype)
            d_hp_acc = jax.tree_util.tree_map(
                lambda a, gkk: a + gkk.astype(grad_dtype), d_hp_acc, d_hp_t
            )
            # park dy for the last rank's final-chunk B (same tick legal:
            # this block precedes the backward block)
            dy_ring = ring_put(dy_ring, xt["dyw_slot"],
                               dy_t.astype(x0.dtype), True)
            return dy_ring, d_w, d_hp_acc, loss_acc

        dy_ring, d_w, d_hp_acc, loss_acc = jax.lax.cond(
            xt["has_h"], h_block, lambda *a: a,
            dy_ring, d_w, d_hp_acc, loss_acc,
        )

        # ---- backward (full vjp, or dgrad-only under zero_bubble) ------
        m_B, c_B, b_valid = xt["b_m"], xt["b_c"], xt["b_valid"]

        def b_block(wdy_ring, d_layers, d_emb):
            mbB = _tree_index(microbatches, m_B)
            x_saved = ring_at(inflight, xt["b_slot"])
            dy_parked = ring_at(dy_ring, xt["dyr_slot"])
            if vp > 1:
                last_dy = jnp.where(
                    c_B == vp - 1, dy_parked,
                    ring_at(bcirc, xt["bread_slot"]),
                )
            else:
                last_dy = dy_parked
            dy_in = jnp.where(is_last, last_dy, cot_recv)
            seed = (dy_in.astype(x0.dtype),
                    jnp.asarray(aux_scale, jnp.float32))
            bv = b_valid.astype(jnp.float32)
            lp_B = chunk_layers(c_B)

            if zero_bubble:
                # dgrad only: the activation cotangent unblocks the
                # upstream stage this tick; dy parks for the table's
                # deferred wgrad fill tick
                _, x_vjp = jax.vjp(lambda x: stage_flat(lp_B, x, mbB, c_B),
                                   x_saved)
                (d_x_t,) = x_vjp(seed)
                wdy_ring = ring_put(wdy_ring, xt["bdy_slot"], dy_in, b_valid)
            else:
                def stage_for_vjp(lp, x):
                    return stage_flat(lp, x, mbB, c_B)

                _, stage_vjp = jax.vjp(stage_for_vjp, lp_B, x_saved)
                d_lp_t, d_x_t = stage_vjp(seed)
                d_layers = acc_layers(d_layers, d_lp_t, c_B, bv)
            d_x_masked = jnp.where(b_valid, d_x_t, jnp.zeros_like(d_x_t))

            # embed cotangent: rank 0's chunk-0 d_x routes back to its
            # round-robin owner (the reverse of the embed feed) — gate and
            # destination are table columns, tick-uniform
            d_x0 = jnp.where(is_first, d_x_masked, jnp.zeros_like(d_x_masked))
            routed = jax.lax.cond(
                xt["d0_valid"],
                lambda: jax.lax.switch(
                    xt["d0_dst"],
                    [functools.partial(
                        jax.lax.ppermute, d_x0, PIPE_AXIS, [(0, o)]
                    ) for o in range(pp)],
                ),
                lambda: jnp.zeros_like(d_x0),
            )
            mine = jnp.logical_and(xt["d0_valid"], xt["d0_dst"] == rank)
            d_emb = ring_put(d_emb, xt["d0_slot"],
                             routed.astype(grad_dtype), mine)
            if double_buffer:
                # park the dgrad unhopped; the deferred hop at the NEXT
                # tick's top delivers it before its first read (the final
                # tick's pending value has no consumer — the table would
                # otherwise have scheduled another B — so never hopping it
                # is safe)
                return wdy_ring, d_layers, d_emb, d_x_masked
            # reverse ring hop: consumed by the predecessor's B next tick
            cot_hop = jax.lax.ppermute(d_x_masked, PIPE_AXIS, reverse)
            return wdy_ring, d_layers, d_emb, cot_hop

        wdy_ring, d_layers, d_emb, cot_recv = jax.lax.cond(
            xt["has_b"], b_block,
            lambda wdy_ring, d_layers, d_emb: (wdy_ring, d_layers, d_emb,
                                               cot_recv),
            wdy_ring, d_layers, d_emb,
        )

        # ---- deferred wgrad (zb fill ticks — rank-uniform, fully dense) -
        if zero_bubble:
            def w_block(d_layers):
                m_W = xt["w_m"]
                mbW = _tree_index(microbatches, m_W)
                x_w = ring_at(inflight, xt["w_x_slot"])
                dy_w = ring_at(wdy_ring, xt["w_dy_slot"])
                _, lp_vjp = jax.vjp(
                    lambda lp: stage_flat(lp, x_w, mbW,
                                          jnp.zeros((), jnp.int32)),
                    local_layers,
                )
                (d_lp_w,) = lp_vjp(
                    (dy_w.astype(x0.dtype),
                     jnp.asarray(aux_scale, jnp.float32))
                )
                return acc_layers(d_layers, d_lp_w, 0,
                                  xt["w_valid"].astype(jnp.float32))

            d_layers = jax.lax.cond(
                xt["has_w"], w_block, lambda d_layers: d_layers, d_layers
            )

        return (recv, cot_recv, inflight, circ, bcirc, dy_ring, wdy_ring,
                d_layers, d_emb, d_w, d_hp_acc, loss_acc, aux_acc), None

    zeros = jnp.zeros_like(x0)
    inflight0 = jnp.zeros((rings["inflight"],) + x0.shape, x0.dtype)
    circ0 = (jnp.zeros((rings["circ"],) + x0.shape, x0.dtype) if vp > 1
             else jnp.zeros((1, 1), x0.dtype))
    bcirc0 = (jnp.zeros((rings["bcirc"],) + x0.shape, x0.dtype) if vp > 1
              else jnp.zeros((1, 1), x0.dtype))
    dy_ring0 = jnp.zeros((rings["dy"],) + x0.shape, x0.dtype)
    wdy_ring0 = (jnp.zeros((rings["wdy"],) + x0.shape, x0.dtype)
                 if zero_bubble else jnp.zeros((1, 1), x0.dtype))
    d_layers0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, grad_dtype), local_layers
    )
    d_emb0 = jnp.zeros((slots,) + x0.shape, grad_dtype)
    d_w0 = jnp.zeros(w_r.shape, grad_dtype)
    d_hp0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, grad_dtype), head_params
    )
    carry0 = (zeros, jnp.zeros_like(x0), inflight0,
              circ0, bcirc0, dy_ring0, wdy_ring0, d_layers0, d_emb0, d_w0,
              d_hp0, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
    # per-rank columns arrive [T, 1] (pipe-sharded on dim 1) -> [T]; the
    # scan consumes one row of the table per compacted tick
    xs = {**{k: v[:, 0] for k, v in wt_rank.items()}, **wt_glob}
    if double_buffer:
        # tick-uniform gate for the deferred reverse hop: "did the PREVIOUS
        # tick run a backward" — has_b shifted one tick right (the pending
        # dgrad parked at t-1 hops at the top of t)
        hb = xs["has_b"]
        xs["hop_b"] = jnp.concatenate([jnp.zeros((1,), hb.dtype), hb[:-1]])
    carry, _ = jax.lax.scan(tick, carry0, xs)
    (_, _, _, _, _, _, _, d_layers, d_emb, d_w, d_hp_acc, loss_acc,
     aux_acc) = carry
    if vp > 1:
        # restore the interleaved [vp, 1, Lc, ...] local layout (dim1 is
        # this rank's pipe shard) so the out spec reassembles [vp, pp, Lc]
        d_layers = jax.tree_util.tree_map(lambda x: x[:, None], d_layers)
    aux_total = jax.lax.psum(aux_acc, PIPE_AXIS)
    # loss and head grads are computed identically on every rank (the CE is
    # psum-closed over pipe); d_w is this rank's vocab slice
    return loss_acc, d_layers, d_emb, d_w, d_hp_acc, aux_total
