"""Ring attention — context-parallel attention over the ``context`` mesh axis.

The TPU-native replacement for the reference's NKI ring-attention kernel
(``neuronx_distributed.kernels.ring_attention_kernel``, called at reference
``modeling_llama.py:71,484`` with explicit CP src/tgt ring pairs).  Design:

- the sequence is sharded over the ``context`` axis; each rank holds local
  Q/K/V chunks ``[b, s/cp, h, d]``;
- a ``lax.scan`` performs ``cp`` ring steps: attend local Q to the currently
  held KV chunk, then rotate K/V to the next rank with ``lax.ppermute`` over
  ICI (the reference's ``get_context_model_parallel_src_tgt_pairs`` ring);
- partial results merge with the online-softmax (m, l, acc) recurrence in fp32
  — mathematically identical to flash attention's block accumulation, so the
  result matches full-sequence attention to numerical precision;
- the whole thing is plain differentiable JAX (``ppermute`` transposes to the
  reverse ring, ``scan`` reverses): no hand-written backward.  The per-chunk
  score/prob tensors are rematerialized in backward (``jax.checkpoint``), so
  memory stays O(s/cp * s/cp) per step like the reference kernel — this is
  what makes CP long-context viable.

The public ``ring_attention`` wraps the per-rank body in ``shard_map`` over the
active mesh: batch over ``(data, expert)``, heads over ``model``, sequence over
``context``.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from neuronx_distributed_training_tpu.parallel.mesh import DATA_AXES
from neuronx_distributed_training_tpu.parallel import sharding as shd

NEG_INF = -1e30

logger = logging.getLogger(__name__)
_warned_bkv: set = set()


def _block_update(qh, ks, vs, o_acc, m_acc, l_acc, q_off, kv_off, *, scale,
                  causal, window, kv_mask=None):
    """One online-softmax accumulation against a KV BLOCK (ks, vs).

    qh [b, h, sq, d]; ks/vs [b, h, bkv, d] (GQA heads already repeated);
    o_acc [b, h, sq, d]; m_acc/l_acc [b, h, sq, 1].  Offsets are traced
    scalars (global positions of query row 0 / kv row 0).  ``kv_mask``
    [b, bkv] (1 = real key) masks padded keys.
    """
    s = jax.lax.dot_general(
        qh, ks, (((3,), (3,)), ((0, 1), (0, 1))), preferred_element_type=jnp.float32
    ) * scale  # [b, h, sq, bkv]
    sq, bkv = s.shape[-2], s.shape[-1]
    q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, bkv), 0)
    kv_pos = kv_off + jax.lax.broadcasted_iota(jnp.int32, (sq, bkv), 1)
    if causal:
        s = jnp.where(kv_pos <= q_pos, s, NEG_INF)
    if window is not None:
        # Mixtral-style sliding window on GLOBAL positions (reference
        # modeling_mixtral.py:145-148); composes with the ring offsets
        s = jnp.where(kv_pos > q_pos - window, s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, NEG_INF)
    m_c = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_acc, m_c)
    alpha = jnp.exp(m_acc - m_new)  # rescale of previous partials
    p = jnp.exp(s - m_new)
    l_new = alpha * l_acc + jnp.sum(p, axis=-1, keepdims=True)
    o_new = alpha * o_acc + jax.lax.dot_general(
        p.astype(vs.dtype), vs, (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    )
    return o_new, m_new, l_new


def _chunk_update(q, kc, vc, o_acc, m_acc, l_acc, q_off, kv_off, *, scale,
                  causal, window, block_kv, kv_mask=None):
    """Accumulate one ring chunk BLOCKWISE over its KV length.

    The fp32 score tensor is [b, h, sq, block_kv] per inner step instead of
    [b, h, sq, s/cp] — this is what keeps 32k-sequence CP inside single-chip
    memory (flash attention's tiling, expressed in XLA; the Pallas kernel is
    the single-chip fast path, this is the ring body).
    q [b, h, sq, d]; kc/vc [b, kvh, skv, d] (un-repeated GQA heads — repeated
    here, inside the remat boundary, so the ring rotates and the scan carries
    only kvh heads).  ``kv_mask`` [b, skv] (1 = real key) masks padded keys.
    """
    h, kvh = q.shape[1], kc.shape[1]
    if kvh != h:
        kc = jnp.repeat(kc, h // kvh, axis=1)
        vc = jnp.repeat(vc, h // kvh, axis=1)
    skv = kc.shape[2]
    bkv = min(block_kv, skv)
    if skv % bkv:
        bkv = skv  # non-divisible chunk: single block (tiny cases only)
    n_blocks = skv // bkv

    if n_blocks == 1:
        return _block_update(q, kc, vc, o_acc, m_acc, l_acc, q_off, kv_off,
                             scale=scale, causal=causal, window=window,
                             kv_mask=kv_mask)

    def blk(carry, i):
        o, m, l = carry
        ks = jax.lax.dynamic_slice_in_dim(kc, i * bkv, bkv, axis=2)
        vs = jax.lax.dynamic_slice_in_dim(vc, i * bkv, bkv, axis=2)
        ms = (None if kv_mask is None
              else jax.lax.dynamic_slice_in_dim(kv_mask, i * bkv, bkv, axis=1))
        o, m, l = _block_update(q, ks, vs, o, m, l, q_off, kv_off + i * bkv,
                                scale=scale, causal=causal, window=window,
                                kv_mask=ms)
        return (o, m, l), None

    (o_acc, m_acc, l_acc), _ = jax.lax.scan(
        blk, (o_acc, m_acc, l_acc), jnp.arange(n_blocks)
    )
    return o_acc, m_acc, l_acc


def _merge_partial(o_acc, lse_acc, o_c, lse_c):
    """Online merge of a normalized partial attention result.

    ``(o_acc [b,h,sq,d] fp32, lse_acc [b,h,sq])`` += chunk ``(o_c, lse_c)``:
    ``o = sum_i o_i * exp(lse_i - lse)``, ``lse = logaddexp_i lse_i`` — exact
    softmax recombination; fully-masked chunks carry ``lse_c = NEG_INF`` and
    drop out via the where-guarded weights (``exp(NEG_INF - NEG_INF)`` must
    not become 1).
    """
    lse_new = jnp.maximum(lse_acc, lse_c) + jnp.log1p(
        jnp.exp(-jnp.abs(lse_acc - lse_c))
    )
    lse_new = jnp.where(
        jnp.maximum(lse_acc, lse_c) > NEG_INF / 2, lse_new, NEG_INF
    )
    w_prev = jnp.where(lse_acc > NEG_INF / 2, jnp.exp(lse_acc - lse_new), 0.0)
    w_c = jnp.where(lse_c > NEG_INF / 2, jnp.exp(lse_c - lse_new), 0.0)
    o_new = o_acc * w_prev[..., None] + o_c.astype(jnp.float32) * w_c[..., None]
    return o_new, lse_new


def _ring_local_flash(q, k, v, kvm=None, *, axis_name, cp, causal, window,
                      interpret):
    """Per-rank ring body fused with the Pallas flash kernel.

    q [b, sq, h, d]; k/v [b, skv, kvh, d]; kvm None or [b, skv] (local key
    padding mask chunk, rotated with K/V) -> o [b, sq, h, d].

    The ring is unrolled over the (static) step index ``t`` so the kernel's
    block-masking offsets stay trace-time constants: at ``t == 0`` the held
    chunk is the rank's own (diagonal — causal mask, offset 0); at ``t > 0``
    the chunk ``src = my - t (mod cp)`` is either entirely in the past
    (``my >= t`` — no mask, relative offset ``t*sq``) or entirely in the
    future (contribution dropped by zeroing its merge weight).  The wasted
    future-chunk compute is the standard causal-ring imbalance (zig-zag
    sharding would fix it; the reference's ring kernel has the same property).
    """
    b, sq, h, d = q.shape
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    from neuronx_distributed_training_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    o_acc = jnp.zeros((b, h, sq, d), jnp.float32)
    lse_acc = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    kc, vc, mc = k, v, kvm
    for t in range(cp):
        if not causal:
            o_c, lse_c = flash_attention_with_lse(
                q, kc, vc, causal=False, attention_mask=mc, interpret=interpret
            )
        elif t == 0:
            o_c, lse_c = flash_attention_with_lse(
                q, kc, vc, causal=True, sliding_window=window, q_offset=0,
                attention_mask=mc, interpret=interpret,
            )
        else:
            # past chunk: fully causally visible; only the sliding window (if
            # any) masks, with static relative offset t*sq
            o_c, lse_c = flash_attention_with_lse(
                q, kc, vc, causal=False, sliding_window=window,
                q_offset=t * sq, attention_mask=mc, interpret=interpret,
            ) if window is not None else flash_attention_with_lse(
                q, kc, vc, causal=False, attention_mask=mc, interpret=interpret
            )
            lse_c = jnp.where(my >= t, lse_c, NEG_INF)
        o_acc, lse_acc = _merge_partial(
            o_acc, lse_acc, jnp.swapaxes(o_c, 1, 2), lse_c
        )
        if t < cp - 1:
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)
            if mc is not None:
                mc = jax.lax.ppermute(mc, axis_name, perm)
    o = jnp.where(lse_acc[..., None] > NEG_INF / 2, o_acc, 0.0)
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)


def _ring_local(q, k, v, kvm=None, *, axis_name, cp, causal, window, block_kv):
    """Per-rank ring attention body (runs inside shard_map).

    q [b, sq, h, d]; k/v [b, skv, kvh, d] (local chunks); kvm None or
    [b, skv] (local key padding mask, rotated with K/V) -> o [b, sq, h, d].
    """
    b, sq, h, d = q.shape
    skv = k.shape[1]
    my = jax.lax.axis_index(axis_name)
    q_off = my * sq
    scale = 1.0 / (d ** 0.5)

    # head-major layout for the inner matmuls
    qh = jnp.swapaxes(q, 1, 2)  # [b, h, sq, d]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)

    perm = [(i, (i + 1) % cp) for i in range(cp)]
    compute = jax.checkpoint(
        functools.partial(_chunk_update, scale=scale, causal=causal,
                          window=window, block_kv=block_kv)
    )

    def step(carry, t):
        o_acc, m_acc, l_acc, kc, vc, mc = carry
        src = jax.lax.rem(my - t + cp, cp)  # rank whose chunk we currently hold
        o_acc, m_acc, l_acc = compute(
            qh, kc, vc, o_acc, m_acc, l_acc, q_off, src * skv, kv_mask=mc
        )
        # rotate KV around the ring (skipped result unused on last step, but
        # keeping it unconditional keeps the collective schedule uniform)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        if mc is not None:
            mc = jax.lax.ppermute(mc, axis_name, perm)
        return (o_acc, m_acc, l_acc, kc, vc, mc), None

    (o_acc, m_acc, l_acc, _, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, kh, vh, kvm), jnp.arange(cp)
    )
    # causal: every row sees at least itself at t=0, so l > 0; guard anyway
    l_safe = jnp.where(l_acc == 0.0, 1.0, l_acc)
    o = jnp.where(m_acc > NEG_INF / 2, o_acc / l_safe, 0.0)
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)  # [b, sq, h, d]


def in_manual_region() -> bool:
    """True inside a ``shard_map`` Manual region (e.g. the pipeline body).

    A nested inner ``shard_map`` mishandles data that VARIES over the outer
    manual axis under ``check_vma=False``: the forward is right but the
    backward sums cotangents across the outer axis (verified: pipe-varying
    inputs through a nested ring produce corrupted dq/dk/dv while loss stays
    exact).  CP attention therefore must NOT open an inner shard_map there —
    callers switch to the pure-GSPMD blockwise body instead.
    """
    if shd.manual_fallback_active():
        # legacy-jax fully-manual fallback (shd.shard_map): no abstract-mesh
        # query exists there, the thread-local flag IS the signal
        return True
    get_abstract_mesh = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract_mesh is None:
        return False  # legacy jax outside the fallback: no manual context
    cur = get_abstract_mesh()
    return bool(getattr(cur, "axis_names", None)
                and any("Manual" in str(t) for t in cur.axis_types))


def pick_bkv(s: int, block_kv: int) -> tuple[int, bool]:
    """Largest divisor of ``s`` no bigger than ``block_kv``, and whether the
    choice is degraded (>8x smaller than asked — an s/bkv-step scan).  Shared
    by ``blockwise_gspmd_attention`` and the config-validation catalog so the
    load-time rejection can never drift from the trace-time selection."""
    bkv = max(1, min(block_kv, s))
    while s % bkv:
        bkv -= 1
    return bkv, bkv * 8 < min(block_kv, s)


def blockwise_gspmd_attention(q, k, v, *, causal=True, sliding_window=None,
                              block_kv: int = 512, attention_mask=None):
    """Memory-bounded global attention with NO explicit collectives.

    The online-softmax block scan of ``_chunk_update`` applied to the FULL
    (GSPMD-global) sequence: XLA partitions the seq-sharded operands and
    inserts the context-axis collectives itself, so this is correct under any
    enclosing manual region (the nested-shard_map backward hazard above).
    It is the CP-attention body used under pipeline parallelism — the
    explicit ppermute ring (faster comm schedule) is the pp == 1 fast path.
    Score memory stays O(sq x block_kv) like the ring body.
    ``attention_mask`` [b, s] (1 = real key) masks padded keys in-scan.
    """
    b, s, h, d = q.shape
    # largest divisor of s <= block_kv: _chunk_update's non-divisible
    # fallback collapses to ONE block, which at the full global sequence
    # would be an O(s^2) score tensor — exactly what this body must bound
    bkv, degraded = pick_bkv(s, block_kv)
    if degraded and (s, block_kv) not in _warned_bkv:
        # a non-smooth sequence length (e.g. prime s) degrades to a tiny bkv
        # and an s/bkv-step scan with pathological compile/step time — make
        # the cliff loud instead of silent (ADVICE r2), once per shape
        _warned_bkv.add((s, block_kv))
        logger.warning(
            "blockwise_gspmd_attention: seq %d has no divisor near block_kv "
            "%d (chose %d) — the %d-step scan will be slow; pad the sequence "
            "to a smoother length", s, block_kv, bkv, s // bkv,
        )
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s, 1), jnp.float32)
    compute = jax.checkpoint(functools.partial(
        _chunk_update, scale=1.0 / (d ** 0.5), causal=causal,
        window=sliding_window, block_kv=bkv,
    ))
    kvm = None if attention_mask is None else attention_mask.astype(jnp.int32)
    o, m, l = compute(qh, kh, vh, o0, m0, l0, 0, 0, kv_mask=kvm)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.where(m > NEG_INF / 2, o / l_safe, 0.0)
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)


def _cp_prep(q, k, v, *, axis_name, mesh, tag):
    """Shared CP-attention scaffolding: resolve mesh/cp/tp, validate head
    divisibility, apply the GQA KV replication for ``tp > kv_heads`` (the
    reference's ``kv_shared_group_size`` trick, ``modeling_llama.py:310-320``
    — consecutive ``jnp.repeat`` so TP rank ``r`` holds exactly the KV head
    its Q heads attend to; gradient accumulation over the sharing ranks is
    XLA's job), and build the shard_map spec.

    Returns ``None`` when cp == 1 (caller falls back to core attention), else
    ``(mesh, cp, tp, k, v, q_spec, h_l, kvh_l)``.  When cp > 1 inside a
    Manual region (``in_manual_region()``) callers must NOT open the inner
    shard_map — ring routes to ``blockwise_gspmd_attention``, zigzag raises.
    """
    mesh = mesh or shd.active_mesh()
    cp = int(mesh.shape.get(axis_name, 1)) if mesh is not None else 1
    if cp == 1:
        return None
    h, kvh = q.shape[2], k.shape[2]
    tp = int(mesh.shape.get("model", 1))
    if tp > 1:
        if h % tp != 0:
            raise ValueError(
                f"{tag}: num_heads {h} must be divisible by tp {tp}"
            )
        if kvh % tp != 0:
            if tp % kvh != 0:
                raise ValueError(
                    f"{tag}: kv_heads {kvh} and tp {tp} must divide "
                    f"one another (got kvh%tp and tp%kvh both nonzero)"
                )
            mult = tp // kvh
            k = jnp.repeat(k, mult, axis=2)
            v = jnp.repeat(v, mult, axis=2)
    q_spec = P(DATA_AXES, "context", "model" if tp > 1 else None, None)
    h_l = h // tp if tp > 1 else h
    kvh_eff = k.shape[2]  # after any tp>kvh replication above
    kvh_l = kvh_eff // tp if tp > 1 else kvh_eff
    return mesh, cp, tp, k, v, q_spec, h_l, kvh_l


def ring_attention(
    q: jax.Array,  # [b, s, h, d]  (seq sharded over "context" under GSPMD)
    k: jax.Array,  # [b, s, kvh, d]
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    axis_name: str = "context",
    mesh=None,
    block_kv: int = 512,
    attention_mask: Optional[jax.Array] = None,  # [b, s] 1 = real key
) -> jax.Array:
    """Context-parallel ring attention over the active mesh.

    Falls back to ``core_attention`` when no mesh is active or cp == 1 (so the
    same model code runs in unit tests and CP-off configs), matching the
    dispatch contract of ``ops.attention``.

    GQA with ``tp > kv_heads``: KV heads are replicated ``tp / kv_heads``
    times (consecutively, so TP rank ``r`` holds exactly the KV head its Q
    heads attend to) — the reference's ``kv_shared_group_size`` /
    ``GQAQKVColumnParallelLinear(kv_size_multiplier=...)`` trick
    (``modeling_llama.py:310-320``, ``config_overview.rst:403-409``).  The
    replication is a GSPMD-level ``jnp.repeat`` so gradient accumulation over
    the sharing TP ranks is XLA's job.
    """
    if not causal:
        # the window is a causal-attention concept everywhere in this stack
        # (core_attention applies it inside the causal mask; flash_attention
        # drops it when causal=False) — match that contract here
        sliding_window = None
    mesh_ = mesh or shd.active_mesh()
    cp_ = int(mesh_.shape.get(axis_name, 1)) if mesh_ is not None else 1
    if cp_ > 1 and in_manual_region():
        # pipeline body (Manual over pipe): the GSPMD blockwise body — the
        # reference's TP x PP x CP flagship layout
        # (hf_llama3_70B_CP_config.yaml) runs through here
        return blockwise_gspmd_attention(
            q, k, v, causal=causal, sliding_window=sliding_window,
            block_kv=block_kv, attention_mask=attention_mask,
        )
    prep = _cp_prep(q, k, v, axis_name=axis_name, mesh=mesh, tag="ring attention")
    if prep is None:
        from neuronx_distributed_training_tpu.ops.attention import (
            core_attention,
            padding_mask_bias,
        )

        return core_attention(
            q, k, v, causal=causal, sliding_window=sliding_window,
            bias=(None if attention_mask is None
                  else padding_mask_bias(attention_mask)),
        )
    mesh, cp, tp, k, v, q_spec, h_l, kvh_l = prep

    # fuse the Pallas flash kernel into the ring body when the local shapes
    # tile (VERDICT r1: the ring step should be the flash kernel, not XLA
    # blockwise); tiny/odd shapes keep the XLA blockwise body
    from neuronx_distributed_training_tpu.ops.flash_attention import flash_tileable

    s, d = q.shape[1], q.shape[3]
    sq_l = s // cp
    if flash_tileable(sq_l, sq_l, d, max(h_l, 1), max(kvh_l, 1)):
        body = functools.partial(
            _ring_local_flash, axis_name=axis_name, cp=cp, causal=causal,
            window=sliding_window, interpret=None,
        )
    else:
        body = functools.partial(
            _ring_local, axis_name=axis_name, cp=cp, causal=causal,
            window=sliding_window, block_kv=block_kv,
        )
    extra_specs, extra_args = (), ()
    if attention_mask is not None:
        extra_specs = (P(DATA_AXES, "context"),)
        extra_args = (attention_mask.astype(jnp.int32),)
    fn = shd.shard_map(
        body,
        mesh=mesh,
        in_specs=(q_spec, q_spec, q_spec) + extra_specs,
        out_specs=q_spec,
        check_vma=False,
    )
    return fn(q, k, v, *extra_args)


# ---------------------------------------------------------------------------
# zig-zag layout — balanced causal ring (not in the reference)
# ---------------------------------------------------------------------------


def zigzag_positions(s: int, cp: int) -> jnp.ndarray:
    """Original position of each token slot in the zig-zag layout ``[s]``.

    The sequence splits into ``2*cp`` chunks; CP rank ``r`` holds chunks
    ``(r, 2cp-1-r)``.  Contiguous causal rings are imbalanced — rank 0's chunk
    is visible to nothing it holds while rank ``cp-1`` attends everything
    (the "causal-ring imbalance" noted on ``_ring_local_flash``); pairing the
    ``r``-th-lowest with the ``r``-th-highest chunk gives every rank the same
    causal work per ring step.  The reference has no equivalent (its NKI ring
    kernel is contiguous).

    Returns ``pos`` with ``pos[p]`` = original position of the token stored at
    layout slot ``p`` (slots are contiguous per rank under the usual
    ``P(..., "context", ...)`` sharding).  ``cp == 1`` is the identity.
    """
    if s % (2 * cp) != 0:
        raise ValueError(f"zigzag: seq {s} must divide by 2*cp = {2 * cp}")
    hc = s // (2 * cp)
    idx = []
    for r in range(cp):
        idx.append(jnp.arange(r * hc, (r + 1) * hc))
        idx.append(jnp.arange((2 * cp - 1 - r) * hc, (2 * cp - r) * hc))
    return jnp.concatenate(idx)


def zigzag_transform_batch(batch: dict, cp: int) -> dict:
    """Permute a causal-LM batch into the zig-zag layout.

    Labels are shifted to next-token targets in the ORIGINAL order first (the
    in-model shift is order-dependent and must be disabled —
    ``shift_labels=False``), then every per-token array is gathered through
    the permutation.  Gathering a seq-sharded batch is a cross-rank permute of
    ids/labels only (a few bytes per token, once per step).
    """
    ids = batch["input_ids"]
    s = ids.shape[1]
    pos = zigzag_positions(s, cp)
    labels = batch.get("labels", ids)
    loss_mask = batch.get("loss_mask")
    # next-token shift in original order (ce_ops.shift_for_next_token
    # semantics: target[i] = labels[i+1], final slot masked out)
    pad = jnp.full(labels.shape[:1] + (1,), -100, labels.dtype)
    tgt = jnp.concatenate([labels[:, 1:], pad], axis=1)
    if loss_mask is not None:
        mpad = jnp.zeros(loss_mask.shape[:1] + (1,), loss_mask.dtype)
        loss_mask = jnp.concatenate([loss_mask[:, 1:], mpad], axis=1)
    out = dict(batch)
    out["input_ids"] = jnp.take(ids, pos, axis=1)
    out["labels"] = jnp.take(tgt, pos, axis=1)
    if loss_mask is not None:
        out["loss_mask"] = jnp.take(loss_mask, pos, axis=1)
    return out


def _pair_attn(qh, kh, vh, *, diag, use_flash, interpret=None):
    """One (q half-chunk, kv half-chunk) attention -> normalized (o, lse).

    ``diag=True``: same chunk, plain causal.  ``diag=False``: kv chunk is
    entirely in the q chunk's past — no mask.  q/k/v are [b, hc, heads, d];
    returns (o [b, h, hc, d] fp32, lse [b, h, hc]).
    """
    if use_flash:
        from neuronx_distributed_training_tpu.ops.flash_attention import (
            flash_attention_with_lse,
        )

        o, lse = flash_attention_with_lse(
            qh, kh, vh, causal=diag, interpret=interpret
        )
        return jnp.swapaxes(o, 1, 2).astype(jnp.float32), lse
    b, hc, h, d = qh.shape
    q_t = jnp.swapaxes(qh, 1, 2)
    o0 = jnp.zeros((b, h, hc, d), jnp.float32)
    m0 = jnp.full((b, h, hc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, hc, 1), jnp.float32)
    # remat the O(hc^2) scores in backward — same memory class as _ring_local
    compute = jax.checkpoint(functools.partial(
        _chunk_update, scale=1.0 / (d ** 0.5), causal=diag, window=None,
        block_kv=hc,
    ))
    o, m, l = compute(
        q_t, jnp.swapaxes(kh, 1, 2), jnp.swapaxes(vh, 1, 2), o0, m0, l0, 0, 0,
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = jnp.where(m > NEG_INF / 2, m + jnp.log(l_safe), NEG_INF)[..., 0]
    return o / l_safe, lse


def _zigzag_local(q, k, v, *, axis_name, cp, use_flash):
    """Per-rank zig-zag ring body (inside shard_map).

    q [b, 2*hc, h, d]: the rank's chunks (a=my, b=2cp-1-my) back to back.
    Ring over KV like the contiguous body; every (q half, kv half) pair is one
    of three STATIC mask cases — kv chunk < q chunk: no mask; ==: plain
    causal; >: skipped — selected per pair with ``lax.switch`` on the traced
    chunk ids, so each rank executes exactly ``2*cp + 1`` visible pairs
    regardless of rank index (the balance property).
    """
    b, s2, h, d = q.shape
    hc = s2 // 2
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def pair(qh, kh, vh, qc, kc):
        def full(_):
            return _pair_attn(qh, kh, vh, diag=False, use_flash=use_flash)

        def diag(_):
            return _pair_attn(qh, kh, vh, diag=True, use_flash=use_flash)

        def skip(_):
            return (jnp.zeros((b, h, hc, d), jnp.float32),
                    jnp.full((b, h, hc), NEG_INF, jnp.float32))

        sel = jnp.where(kc < qc, 0, jnp.where(kc == qc, 1, 2))
        return jax.lax.switch(sel, [full, diag, skip], None)

    o_acc = jnp.zeros((b, 2, h, hc, d), jnp.float32)  # per q half
    lse_acc = jnp.full((b, 2, h, hc), NEG_INF, jnp.float32)
    kc_, vc_ = k, v
    q_halves = (q[:, :hc], q[:, hc:])
    for t in range(cp):
        src = jax.lax.rem(my - t + cp, cp)
        held_chunks = (src, 2 * cp - 1 - src)
        my_chunks = (my, 2 * cp - 1 - my)
        for qi in range(2):
            for ki in range(2):
                o_c, lse_c = pair(
                    q_halves[qi], kc_[:, ki * hc:(ki + 1) * hc],
                    vc_[:, ki * hc:(ki + 1) * hc],
                    my_chunks[qi], held_chunks[ki],
                )
                o_new, lse_new = _merge_partial(
                    o_acc[:, qi], lse_acc[:, qi], o_c, lse_c
                )
                o_acc = o_acc.at[:, qi].set(o_new)
                lse_acc = lse_acc.at[:, qi].set(lse_new)
        if t < cp - 1:
            kc_ = jax.lax.ppermute(kc_, axis_name, perm)
            vc_ = jax.lax.ppermute(vc_, axis_name, perm)
    o = jnp.where(lse_acc[..., None] > NEG_INF / 2, o_acc, 0.0)
    # [b, 2, h, hc, d] -> [b, 2*hc, h, d]
    o = jnp.swapaxes(o, 2, 3).reshape(b, s2, h, d)
    return o.astype(q.dtype)


def zigzag_ring_attention(
    q: jax.Array,  # [b, s, h, d] in the ZIG-ZAG layout, seq over "context"
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    axis_name: str = "context",
    mesh=None,
) -> jax.Array:
    """Balanced causal ring attention over the zig-zag layout.

    Inputs must already be in the layout ``zigzag_positions`` describes (the
    trainer permutes the batch via ``zigzag_transform_batch`` and feeds the
    model matching RoPE positions).  cp == 1 is the identity layout, so the
    fallback is plain core attention — same dispatch contract as the ring.
    Causal only: non-causal rings have no imbalance to fix.
    """
    if not causal:
        raise ValueError("zigzag ring is causal-only; use ring_attention")
    prep = _cp_prep(q, k, v, axis_name=axis_name, mesh=mesh, tag="zigzag ring")
    if prep is None:
        from neuronx_distributed_training_tpu.ops.attention import core_attention

        return core_attention(q, k, v, causal=True)
    if in_manual_region():
        # the zig-zag layout's mask cases assume the explicit ring; inside a
        # manual region the trainer's pp guard should have fired already
        raise ValueError(
            "zigzag ring cannot run inside a manual (pipeline) region; use "
            "fusions.ring_attention for pp + cp configs"
        )
    mesh, cp, tp, k, v, q_spec, h_l, kvh_l = prep

    s, d = q.shape[1], q.shape[3]
    if s % (2 * cp) != 0:
        raise ValueError(f"zigzag ring: seq {s} must divide by 2*cp = {2 * cp}")
    from neuronx_distributed_training_tpu.ops.flash_attention import flash_tileable

    hc = s // (2 * cp)
    use_flash = flash_tileable(hc, hc, d, max(h_l, 1), max(kvh_l, 1))

    fn = shd.shard_map(
        functools.partial(_zigzag_local, axis_name=axis_name, cp=cp,
                          use_flash=use_flash),
        mesh=mesh,
        in_specs=(q_spec, q_spec, q_spec),
        out_specs=q_spec,
        check_vma=False,
    )
    return fn(q, k, v)
