"""Sharding rules and constraint helpers.

Where the reference wires explicit NxD parallel layers and hand-written
scatter/gather calls (``ColumnParallelLinear``/``RowParallelLinear``/
``scatter_to_sequence_parallel_region`` — reference ``modeling_llama.py:74-78``,
``modeling_mixtral.py:677-679``), the TPU-native design expresses *all* of
TP/SP/CP/DP as PartitionSpecs:

- tensor parallelism   = weight specs over the ``model`` axis
- sequence parallelism = activation seq-dim constrained to ``model`` between blocks
- context parallelism  = activation seq-dim constrained to ``context``
- data parallelism     = batch dim over the compound ``(data, expert)`` axis

XLA/GSPMD then inserts exactly the all-gathers/reduce-scatters the reference's
layers perform by hand.  ``constrain`` is a mesh-aware
``with_sharding_constraint`` that no-ops when no mesh is active, so every model
function also runs unsharded (unit tests, single host).
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.parallel.mesh import DATA_AXES

_STATE = threading.local()


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None, check_vma=True):
    """Version-portable ``shard_map``.

    New JAX (``jax.shard_map``): passes through unchanged, including partial
    manualness via ``axis_names`` (e.g. the pipeline body is Manual over
    ``pipe`` only; GSPMD keeps sharding data/model inside).

    Old JAX (``jax.experimental.shard_map``, no ``axis_names``/``check_vma``):
    partial-auto shard_map is unusable there (``axis_index`` lowers to a bare
    PartitionId the SPMD partitioner rejects, and operand transfers CHECK-fail
    on manual-subgroup mismatches), so the fallback runs the body manual over
    ALL mesh axes.  ``in_specs`` keep their meaning — axes not named in a spec
    are replicated — so the body computes the same values, merely without
    GSPMD re-sharding its internals over the auto axes (each data/model rank
    redundantly holds the full replicated slice).  Collectives over the named
    axes are identical.  ``constrain`` calls inside the body become no-ops via
    a thread-local flag set for the duration of the body trace (their specs
    name axes that are Manual in the fallback, which old wsc cannot express).
    """
    if hasattr(jax, "shard_map"):
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, **kw)
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @functools.wraps(f)
    def body(*args, **kwargs):
        prev = getattr(_STATE, "manual_all", False)
        _STATE.manual_all = True
        try:
            return f(*args, **kwargs)
        finally:
            _STATE.manual_all = prev

    return _legacy_shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma),
    )


def active_mesh() -> Optional[Mesh]:
    return getattr(_STATE, "mesh", None)


def manual_fallback_active() -> bool:
    """True while tracing inside the legacy fully-manual ``shard_map``
    fallback (see ``shard_map`` below) — the signal ``constrain`` and
    nested-manual-region checks use on jax versions without an abstract-mesh
    query."""
    return bool(getattr(_STATE, "manual_all", False))


@contextlib.contextmanager
def use_mesh(mesh: Optional[Mesh]):
    """Activate a mesh for ``constrain``/``named_sharding`` inside the block."""
    prev = active_mesh()
    _STATE.mesh = mesh
    try:
        yield mesh
    finally:
        _STATE.mesh = prev


def named_sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    m = mesh or active_mesh()
    if m is None:
        raise RuntimeError("no active mesh; wrap in parallel.sharding.use_mesh(mesh)")
    return NamedSharding(m, spec)


def constrain(x, spec: Optional[P], mesh: Optional[Mesh] = None):
    """``with_sharding_constraint`` if a mesh is active, else identity.

    Prefers the bare-PartitionSpec form, which resolves against the *context*
    mesh — required inside ``shard_map`` regions (e.g. the pipeline body, which
    is Manual over ``pipe``), where a NamedSharding built from the outer
    all-Auto mesh would conflict.  Falls back to an explicit NamedSharding when
    no context mesh is set.
    """
    if spec is None:
        return x
    if manual_fallback_active():
        # inside the legacy fully-manual shard_map fallback (see shard_map
        # above): every mesh axis is Manual there, so sharding constraints are
        # inexpressible — and unnecessary, the values are already per-device
        return x
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except RuntimeError as e:
        # ONLY the no-context-mesh case falls through (plain jit under the
        # legacy `with mesh:` manager); a genuine spec error (bad axis, rank
        # mismatch — ValueError) must propagate, not silently return
        # unconstrained activations.  The no-mesh message has drifted across
        # jax versions ("non-empty mesh in context" vs "requires a non-empty
        # mesh if you are passing"), so match the stable stem.
        if "non-empty mesh" not in str(e):
            raise
        m = mesh or active_mesh()
        if m is None:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(m, spec))


def spec_errors(specs, mesh: Mesh) -> list[str]:
    """Static PartitionSpec lint over a spec pytree: every named axis must
    exist in ``mesh`` and no axis may be used twice within one spec (XLA
    rejects the latter late, with a partitioner error that names neither the
    leaf nor the axis).  Returns curated ``path: problem`` strings; empty
    means clean.  The pre-flight graph auditor runs this before lowering so
    a bad spec dies with a leaf path instead of a GSPMD traceback."""
    known = set(mesh.axis_names)
    errors: list[str] = []

    def visit(path, spec):
        if spec is None or not isinstance(spec, P):
            return spec
        where = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                         for p in path) or "<root>"
        seen: set[str] = set()
        for dim in spec:
            for ax in (dim if isinstance(dim, tuple) else (dim,)):
                if ax is None:
                    continue
                if ax not in known:
                    errors.append(
                        f"{where}: spec {spec} names axis {ax!r} absent from "
                        f"mesh axes {sorted(known)}"
                    )
                elif ax in seen:
                    errors.append(
                        f"{where}: spec {spec} uses axis {ax!r} twice — one "
                        f"mesh axis cannot shard two tensor dims"
                    )
                seen.add(ax)
        return spec

    jax.tree_util.tree_map_with_path(
        visit, specs, is_leaf=lambda x: isinstance(x, P) or x is None,
    )
    return errors


def validate_specs(specs, mesh: Mesh) -> None:
    """Raise ``ValueError`` listing every defect ``spec_errors`` finds."""
    errors = spec_errors(specs, mesh)
    if errors:
        raise ValueError(
            "invalid PartitionSpecs:\n  " + "\n  ".join(errors[:20])
            + (f"\n  ... and {len(errors) - 20} more" if len(errors) > 20
               else "")
        )


def seq_axes(sequence_parallel: bool, context_parallel: bool):
    """Mesh axes the activation sequence dim is sharded over between blocks.

    CP splits the sequence first (outer), Megatron-SP shards the remainder over
    the TP group (reference composes them the same way: CP batch-level split at
    ``base.py:199``, then per-layer SP inside NxD layers)."""
    axes = []
    if context_parallel:
        axes.append("context")
    if sequence_parallel:
        axes.append("model")
    if not axes:
        return None
    return tuple(axes) if len(axes) > 1 else axes[0]


def act_spec(sequence_parallel: bool = False, context_parallel: bool = False) -> P:
    """Spec for block-boundary activations ``[batch, seq, hidden]``."""
    return P(DATA_AXES, seq_axes(sequence_parallel, context_parallel), None)


def heads_spec(context_parallel: bool = False) -> P:
    """Spec for attention-internal activations ``[batch, seq, heads, head_dim]``:
    heads over ``model`` (TP), seq over ``context`` only (attention needs the
    full TP-group sequence — the all-gather GSPMD inserts here is the reference's
    pre-QKV all-gather under SP)."""
    return P(DATA_AXES, "context" if context_parallel else None, "model", None)


def logits_spec(context_parallel: bool = False) -> P:
    """Spec for lm-head logits ``[batch, seq, vocab]``: vocab over ``model``
    (the reference's no-gather ColumnParallel lm_head + parallel_cross_entropy,
    ``modeling_llama.py:808-833``)."""
    return P(DATA_AXES, "context" if context_parallel else None, "model")
