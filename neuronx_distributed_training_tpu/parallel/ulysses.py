"""Ulysses / all-to-all sequence parallelism over the ``context`` mesh axis.

A capability the reference does NOT have (SURVEY.md §2.11: ``grep -ri ulysses``
over the reference -> 0 hits; its long-context story is Megatron-SP + ring
attention only).  DeepSpeed-Ulysses (arXiv:2309.14509) redistributes the
sequence-sharded activations to HEAD-sharded just for attention:

- outside attention the sequence stays sharded over ``context`` (same layout
  the ring path uses, so the CP batch split / RoPE offsets / loss machinery
  in the trainer is shared);
- ``all_to_all`` #1 (heads -> seq): each rank trades its local sequence chunk
  of all heads for the FULL sequence of ``h/cp`` heads;
- attention runs locally per rank with ordinary causal masking (the Pallas
  flash kernel when shapes tile — no ring step, no online merge);
- ``all_to_all`` #2 (seq -> heads) restores the sequence-sharded layout.

vs ring attention: 2 all-to-alls instead of ``cp`` ppermutes, no causal-ring
compute imbalance (every rank does the same triangular work), at the cost of
requiring ``heads/tp`` divisible by ``cp``.  On ICI the all-to-alls are cheap;
Ulysses tends to win when ``cp`` is small relative to head count, ring when
sequence length dominates or cp exceeds the head budget.

GQA KV heads replicate (consecutively) until they divide ``tp*cp``, the same
``kv_shared_group_size`` trick as the ring path (reference
``modeling_llama.py:310-320``) — gradients flow through ``jnp.repeat``'s
transpose (a sum over replicas), so training under replication stays exact.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from neuronx_distributed_training_tpu.parallel.mesh import DATA_AXES
from neuronx_distributed_training_tpu.parallel import sharding as shd


def _ulysses_local(q, k, v, kvm=None, *, axis_name, causal, window, use_flash,
                   interpret=None):
    """Per-rank body (inside shard_map, manual over the whole mesh).

    q [b, sq, h_l, d]; k/v [b, sq, kvh_l, d] with sq = s/cp the local
    sequence chunk and h_l the rank-local head count (h_l % cp == 0,
    kvh_l % cp == 0 — arranged by the wrapper).  ``kvm`` is the local
    [b, sq] key padding mask chunk; attention runs over the FULL sequence
    per rank, so the mask is all-gathered (bytes per token, once per layer).
    """
    # all-to-all #1: trade head shards for the full sequence
    qf = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kf = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vf = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    mf = (None if kvm is None
          else jax.lax.all_gather(kvm, axis_name, axis=1, tiled=True))
    # full-sequence attention on h_l/cp local heads — plain causal, offset 0
    if use_flash:
        from neuronx_distributed_training_tpu.ops.flash_attention import (
            flash_attention,
        )

        o = flash_attention(qf, kf, vf, causal=causal, sliding_window=window,
                            attention_mask=mf, interpret=interpret)
    else:
        from neuronx_distributed_training_tpu.ops.attention import (
            core_attention,
            padding_mask_bias,
        )

        o = core_attention(qf, kf, vf, causal=causal, sliding_window=window,
                           bias=(None if mf is None else padding_mask_bias(mf)))
    # all-to-all #2: back to sequence-sharded, all heads local
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,  # [b, s, h, d]  (seq sharded over "context" under GSPMD)
    k: jax.Array,  # [b, s, kvh, d]
    v: jax.Array,
    *,
    causal: bool = True,
    sliding_window: Optional[int] = None,
    axis_name: str = "context",
    mesh=None,
    block_kv: int = 512,
    attention_mask: Optional[jax.Array] = None,  # [b, s] 1 = real key
) -> jax.Array:
    """All-to-all context-parallel attention over the active mesh.

    Same dispatch contract as ``ring_attention``: falls back to
    ``core_attention`` when no mesh is active or cp == 1, so the same model
    code runs in unit tests and CP-off configs.
    """
    if not causal:
        sliding_window = None  # window is a causal concept in this stack
    mesh = mesh or shd.active_mesh()
    cp = int(mesh.shape.get(axis_name, 1)) if mesh is not None else 1
    if cp == 1:
        from neuronx_distributed_training_tpu.ops.attention import (
            core_attention,
            padding_mask_bias,
        )

        return core_attention(
            q, k, v, causal=causal, sliding_window=sliding_window,
            bias=(None if attention_mask is None
                  else padding_mask_bias(attention_mask)),
        )
    from neuronx_distributed_training_tpu.parallel.ring_attention import (
        blockwise_gspmd_attention,
        in_manual_region,
    )

    if in_manual_region():
        # a nested shard_map corrupts backward for pipe-varying inputs (see
        # ring_attention.in_manual_region) — under pipeline parallelism CP
        # attention runs the GSPMD blockwise body instead
        return blockwise_gspmd_attention(
            q, k, v, causal=causal, sliding_window=sliding_window,
            block_kv=block_kv, attention_mask=attention_mask,
        )

    h, kvh = q.shape[2], k.shape[2]
    tp = int(mesh.shape.get("model", 1))
    if h % (tp * cp) != 0:
        raise ValueError(
            f"ulysses attention: num_heads {h} must be divisible by tp*cp = "
            f"{tp}*{cp} (use ring attention when cp exceeds the head budget)"
        )
    # KV replication until kv heads divide tp*cp while q/kv head groups stay
    # aligned (consecutive repeat; see module docstring)
    if kvh % (tp * cp) != 0:
        if (tp * cp) % kvh != 0:
            raise ValueError(
                f"ulysses attention: kv_heads {kvh} and tp*cp {tp * cp} must "
                f"divide one another"
            )
        mult = (tp * cp) // kvh
        # kvh*mult == tp*cp divides h (checked above), so groups stay aligned
        k = jnp.repeat(k, mult, axis=2)
        v = jnp.repeat(v, mult, axis=2)

    q_spec = P(DATA_AXES, "context", "model" if tp > 1 else None, None)
    kv_spec = P(DATA_AXES, "context", "model" if tp > 1 else None, None)

    from neuronx_distributed_training_tpu.ops.flash_attention import flash_tileable

    s, d = q.shape[1], q.shape[3]
    h_l = h // tp
    kvh_l = k.shape[2] // tp
    # per-rank attention shapes after all-to-all: full seq, h_l/cp heads
    use_flash = flash_tileable(s, s, d, max(h_l // cp, 1), max(kvh_l // cp, 1))
    body = functools.partial(
        _ulysses_local, axis_name=axis_name, causal=causal,
        window=sliding_window, use_flash=use_flash,
    )
    extra_specs, extra_args = (), ()
    if attention_mask is not None:
        extra_specs = (P(DATA_AXES, "context"),)
        extra_args = (attention_mask.astype(jnp.int32),)
    fn = shd.shard_map(
        body,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec) + extra_specs,
        out_specs=q_spec,
        check_vma=False,
    )
    return fn(q, k, v, *extra_args)
