"""PEFT — parameter-efficient fine-tuning (LoRA)."""

from neuronx_distributed_training_tpu.peft.lora import (  # noqa: F401
    LoraConfig,
    add_lora,
    lora_param_specs,
    merge_lora,
    trainable_mask,
)
