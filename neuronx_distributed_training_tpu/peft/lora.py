"""LoRA — low-rank adapters as a pytree transform.

The reference wraps target nn.Modules with NxD's LoRA machinery
(``nxd.modules.lora.LoraConfig`` built at reference ``llama_model.py:51-65``,
with ``lora_rank/lora_alpha/lora_dropout/target_modules`` and save/merge
options).  TPU-native: LoRA is a *pytree transform* —

- ``add_lora`` injects ``lora_a``/``lora_b``/``lora_scale`` leaves into every
  linear param-dict whose tree path matches a target-module name;
  ``ops.linear.apply_linear`` picks them up automatically, so NO model code
  changes;
- ``trainable_mask`` marks adapter leaves trainable and base weights frozen —
  the optimizer multiplies grads by this mask (the freeze);
- ``merge_lora`` folds ``w + A @ B * scale`` back into the base weight for
  export (the reference's ``save_lora_config_adapter``/merge options);
- sharding: A ``[in, r]`` follows the input dim of the base spec, B ``[r, out]``
  the output dim, so TP layouts (column/row) keep working unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# default targets mirror the reference's config surface
# (config_overview.rst: target_modules: [qkv_proj] etc.)
DEFAULT_TARGETS = ("qkv", "q", "k", "v", "o", "gate_up", "down")


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """Mirrors the reference's ``model.lora`` YAML block (``llama_model.py:51-65``)."""

    rank: int = 16
    alpha: float = 32.0
    dropout: float = 0.0  # dropout on the adapter input (applied by caller RNG)
    target_modules: tuple = DEFAULT_TARGETS

    @classmethod
    def from_config(cls, lora_cfg: dict[str, Any]) -> "LoraConfig":
        c = dict(lora_cfg or {})
        targets = c.get("target_modules")
        return cls(
            rank=int(c.get("lora_rank", c.get("rank", 16))),
            alpha=float(c.get("lora_alpha", c.get("alpha", 32.0))),
            dropout=float(c.get("lora_dropout", c.get("dropout", 0.0))),
            target_modules=tuple(
                t.replace("_proj", "") for t in targets
            ) if targets else DEFAULT_TARGETS,
        )

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _is_target_linear(path, leaf_dict) -> bool:
    return isinstance(leaf_dict, dict) and "w" in leaf_dict and hasattr(
        leaf_dict["w"], "ndim"
    ) and leaf_dict["w"].ndim >= 2


def _path_names(path) -> list[str]:
    return [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]


def add_lora(params: Any, cfg: LoraConfig, key: jax.Array) -> Any:
    """Return params with adapters injected into matching linear dicts.

    Matching: the linear's dict key (e.g. ``qkv``, ``o``, ``gate_up``) is in
    ``cfg.target_modules``.  A is gaussian-init, B zero-init (adapter starts as
    identity), per standard LoRA.  Works on stacked layer dicts (leading
    ``[num_layers]`` dim) transparently.
    """
    counter = [0]

    def visit(path, node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if (
                isinstance(v, dict)
                and k in cfg.target_modules
                and "w" in v
                and getattr(v["w"], "ndim", 0) >= 2
            ):
                w = v["w"]
                *lead, in_dim, out_dim = w.shape
                counter[0] += 1
                ka = jax.random.fold_in(key, counter[0])
                a = (0.02 * jax.random.truncated_normal(
                    ka, -2.0, 2.0, (*lead, in_dim, cfg.rank), jnp.float32
                )).astype(w.dtype)
                b = jnp.zeros((*lead, cfg.rank, out_dim), w.dtype)
                out[k] = {
                    **v,
                    "lora_a": a,
                    "lora_b": b,
                    # scale carries the stacked-layer lead dims so lax.scan can
                    # slice it per layer alongside a/b
                    "lora_scale": jnp.full(tuple(lead), cfg.scale, jnp.float32),
                }
            else:
                out[k] = visit(path + [k], v)
        return out

    return visit([], params)


def lora_param_specs(param_specs: Any, cfg: LoraConfig) -> Any:
    """Extend a spec pytree with adapter specs.

    For a base weight spec ``(..., in_ax, out_ax)``: A gets ``(..., in_ax,
    None)``, B gets ``(..., None, out_ax)`` — preserving column/row TP layouts.
    """

    def visit(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            if isinstance(v, dict) and "w" in v and isinstance(v["w"], P) and (
                k in cfg.target_modules
            ):
                wspec = tuple(v["w"])
                lead = wspec[:-2] if len(wspec) >= 2 else ()
                in_ax = wspec[-2] if len(wspec) >= 2 else None
                out_ax = wspec[-1] if len(wspec) >= 1 else None
                out[k] = {
                    **v,
                    "lora_a": P(*lead, in_ax, None),
                    "lora_b": P(*lead, None, out_ax),
                    "lora_scale": P(*(None for _ in lead)),
                }
            else:
                out[k] = visit(v)
        return out

    return visit(param_specs)


def trainable_mask(params: Any) -> Any:
    """1.0 for adapter A/B leaves, 0.0 elsewhere (the LoRA freeze).

    ``lora_scale`` stays frozen: it encodes the configured alpha/r, not a
    learnable parameter."""

    def leaf(path, x):
        names = _path_names(path)
        return 1.0 if any(n in ("lora_a", "lora_b") for n in names) else 0.0

    return jax.tree_util.tree_map_with_path(leaf, params)


def merge_lora(params: Any) -> Any:
    """Fold adapters into base weights (export / the reference's merge option)."""

    def visit(node):
        if not isinstance(node, dict):
            return node
        if "lora_a" in node and "w" in node:
            w = node["w"]
            delta = jnp.einsum(
                "...ir,...ro->...io",
                node["lora_a"].astype(jnp.float32),
                node["lora_b"].astype(jnp.float32),
            ) * node["lora_scale"][..., None, None]
            merged = {k: v for k, v in node.items() if not k.startswith("lora_")}
            merged["w"] = (w.astype(jnp.float32) + delta).astype(w.dtype)
            return merged
        return {k: visit(v) for k, v in node.items()}

    return visit(params)
