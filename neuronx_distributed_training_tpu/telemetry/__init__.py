"""Unified step telemetry: span timing, MFU, compile census, goodput.

The observable surface the reference ships piecemeal (NeMo ``TimingCallback``,
``llama_perf_estimate.py``, profiler hooks) as ONE subsystem the trainer
threads through every sink: per-step span decomposition (``spans``), a
first-compile memory/collective/FLOPs census persisted to ``run_summary.json``
(``census``), retrace detection (``recompile``), the numerics flight recorder
(in-graph health probes in ``health``, ring buffer / anomaly bundles / hang
watchdog in ``flight_recorder``), and the ``exp_manager: telemetry:`` knob
block that gates it all (``config``).  Everything here is host-side
bookkeeping — no device syncs between logging boundaries (the anomaly dump
path, which only runs once a step has already gone non-finite, is the one
deliberate exception).
"""

from neuronx_distributed_training_tpu.telemetry.alerts import (
    ALERT_ACTIONS,
    AlertEngine,
    AlertRule,
    parse_alerts,
)
from neuronx_distributed_training_tpu.telemetry.census import (
    compile_census,
    memory_analysis_bytes,
)
from neuronx_distributed_training_tpu.telemetry.fleet import (
    FleetAggregator,
    FleetBeacon,
    FleetConfig,
    FleetPlane,
    aggregate_fleet,
)
from neuronx_distributed_training_tpu.telemetry.config import (
    TELEMETRY_KNOBS,
    TelemetryConfig,
)
from neuronx_distributed_training_tpu.telemetry.flight_recorder import (
    HangWatchdog,
    HealthMonitor,
)
from neuronx_distributed_training_tpu.telemetry.health import (
    HEALTH_POLICIES,
    HealthConfig,
    grad_group_of,
)
from neuronx_distributed_training_tpu.telemetry.memory import (
    MEMORY_SUMMARY_NAME,
    SUBSYSTEMS,
    MemoryConfig,
    MemoryPlane,
    attribute_profile,
    device_memory_samples,
    is_oom_error,
    load_memory_summary,
    memory_metrics,
    parse_memory_profile,
    tree_bytes_by_subsystem,
)
from neuronx_distributed_training_tpu.telemetry.recompile import RecompileDetector
from neuronx_distributed_training_tpu.telemetry.tensorstats import (
    HIST_PREFIX as TENSORSTATS_HIST_PREFIX,
    SCALAR_PREFIX as TENSORSTATS_SCALAR_PREFIX,
    TensorStatsConfig,
    decode_cum,
    init_tensorstats_state,
    tensorstats_state_specs,
    tensorstats_update,
)
from neuronx_distributed_training_tpu.telemetry.spans import (
    NON_PRODUCTIVE_SPANS,
    SpanTimer,
)
from neuronx_distributed_training_tpu.telemetry.step_timeline import (
    analyze_pipeline,
    pipeline_facts,
)
from neuronx_distributed_training_tpu.telemetry.trace import (
    TraceCapture,
    TraceConfig,
    trace_steps,
)
from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
    analyze_trace_dir,
    load_trace_summary,
)

__all__ = [
    "ALERT_ACTIONS",
    "AlertEngine",
    "AlertRule",
    "FleetAggregator",
    "FleetBeacon",
    "FleetConfig",
    "FleetPlane",
    "HEALTH_POLICIES",
    "HangWatchdog",
    "HealthConfig",
    "HealthMonitor",
    "MEMORY_SUMMARY_NAME",
    "MemoryConfig",
    "MemoryPlane",
    "NON_PRODUCTIVE_SPANS",
    "SUBSYSTEMS",
    "RecompileDetector",
    "SpanTimer",
    "TELEMETRY_KNOBS",
    "TENSORSTATS_HIST_PREFIX",
    "TENSORSTATS_SCALAR_PREFIX",
    "TelemetryConfig",
    "TensorStatsConfig",
    "TraceCapture",
    "TraceConfig",
    "aggregate_fleet",
    "analyze_pipeline",
    "analyze_trace_dir",
    "attribute_profile",
    "compile_census",
    "decode_cum",
    "device_memory_samples",
    "grad_group_of",
    "init_tensorstats_state",
    "is_oom_error",
    "load_memory_summary",
    "load_trace_summary",
    "memory_analysis_bytes",
    "memory_metrics",
    "parse_alerts",
    "parse_memory_profile",
    "pipeline_facts",
    "tensorstats_state_specs",
    "tensorstats_update",
    "trace_steps",
    "tree_bytes_by_subsystem",
]
